#!/usr/bin/env python
"""Headline benchmark: population fitness-evaluation throughput
(trees-rows evaluated per second per chip) on the Feynman-I.6.2a north-star
config (BASELINE.json: npopulations=64, npop=1000, L2DistLoss).

This is the analog of the reference's `score_func` hot path
(src/LossFunctions.jl:86-115 over eval_tree_array): here one jitted XLA call
scores a whole chunk of the 64k-tree population against the HBM-resident
dataset.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the same workload on the multithreaded XLA CPU
backend of this machine (the stand-in for the reference's CPU-multithreaded
throughput; the reference publishes no absolute numbers — BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Fallback CPU anchor (trees-rows/sec) measured on this image's XLA CPU
# backend when no in-process CPU backend is available; refreshed whenever
# bench.py is run on a CPU-only session.
_CPU_FALLBACK = 3.85e6  # measured on this image's XLA CPU (2026-07-29)

N_POPULATIONS = 64
NPOP = 1000
N_ROWS = 1000
MAXSIZE = 20
CHUNK = 8192
REPS = 3


def _build_workload(jax, jnp, options, n_trees, n_feat):
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )

    key = jax.random.PRNGKey(0)
    sizes = jax.random.randint(
        jax.random.PRNGKey(1), (n_trees,), 3, MAXSIZE
    )
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, n_feat, options.operators, options.max_len
        )
    )(jax.random.split(key, n_trees), sizes)
    return trees


def _time_backend(jax, jnp, options, device, n_trees, label, verbose):
    """Score n_trees random trees against the Feynman-I.6.2a dataset on
    `device`; return trees-rows/sec."""
    from symbolicregression_jl_tpu.models.fitness import score_trees

    n_feat = 1
    rng = np.random.default_rng(0)
    theta = rng.uniform(1.0, 3.0, N_ROWS).astype(np.float32)
    X_h = theta[None, :]
    y_h = (np.exp(-(theta**2) / 2.0) / np.sqrt(2 * np.pi)).astype(np.float32)

    with jax.default_device(device):
        trees = _build_workload(jax, jnp, options, n_trees, n_feat)
        X = jnp.asarray(X_h)
        y = jnp.asarray(y_h)
        baseline = jnp.float32(float(np.var(y_h)))

        # The jitted step returns one scalar so each rep ends with a real
        # device->host transfer: block_until_ready alone can return early on
        # async transport backends, yielding bogus sub-ms timings.
        def step(t, X, y, b):
            scores, losses = score_trees(t, X, y, None, b, options)
            finite = jnp.isfinite(scores)
            return jnp.sum(jnp.where(finite, scores, 0.0)), jnp.sum(finite)

        fn = jax.jit(step)
        n_chunks = max(1, n_trees // CHUNK)
        chunks = [
            jax.tree_util.tree_map(
                lambda x: x[i * CHUNK:(i + 1) * CHUNK], trees
            )
            for i in range(n_chunks)
        ]
        # warmup / compile
        float(fn(chunks[0], X, y, baseline)[0])

        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            outs = [fn(c, X, y, baseline) for c in chunks]
            total = sum(float(s) for s, _ in outs)  # forces full sync
            times.append(time.perf_counter() - t0)
        best = float(np.median(times))
        assert np.isfinite(total)

    done_trees = n_chunks * min(CHUNK, n_trees)
    rate = done_trees * N_ROWS / best
    if verbose:
        print(
            f"# {label}: {done_trees} trees x {N_ROWS} rows in {best*1e3:.1f} ms "
            f"-> {rate:.3e} trees-rows/s",
            file=sys.stderr,
        )
    return rate


def main(verbose=True):
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=MAXSIZE,
        loss="L2DistLoss",
    )

    devices = jax.devices()
    main_dev = devices[0]
    platform = main_dev.platform
    n_trees = N_POPULATIONS * NPOP

    value = _time_backend(
        jax, jnp, options, main_dev, n_trees, f"main ({platform})", verbose
    )

    # CPU anchor (dispatch_eval auto-routes to the jnp path under
    # jax.default_device(cpu) — pallas_available honors the context)
    cpu_rate = None
    if platform != "cpu":
        try:
            cpu_dev = jax.devices("cpu")[0]
            cpu_rate = _time_backend(
                jax, jnp, options, cpu_dev, min(n_trees, 8192),
                "cpu anchor", verbose,
            )
        except Exception as e:  # pragma: no cover
            if verbose:
                print(f"# cpu anchor unavailable: {e}", file=sys.stderr)
            cpu_rate = _CPU_FALLBACK
    else:
        cpu_rate = value

    print(
        json.dumps(
            {
                "metric": (
                    "population fitness-eval throughput, Feynman-I.6.2a "
                    f"(64x1000 trees, {N_ROWS} rows, maxsize {MAXSIZE}, "
                    f"platform {platform})"
                ),
                "value": round(value, 1),
                "unit": "trees-rows/sec/chip",
                "vs_baseline": round(value / cpu_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main(verbose="--quiet" not in sys.argv)
