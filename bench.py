#!/usr/bin/env python
"""Headline benchmark: population fitness-evaluation throughput
(trees-rows evaluated per second per chip) on the Feynman-I.6.2a north-star
config (BASELINE.json: npopulations=64, npop=1000, L2DistLoss).

This is the analog of the reference's `score_func` hot path
(src/LossFunctions.jl:86-115 over eval_tree_array): here one jitted XLA call
scores a whole chunk of the 64k-tree population against the HBM-resident
dataset.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the same workload on the multithreaded XLA CPU
backend of this machine (the stand-in for the reference's CPU-multithreaded
throughput; the reference publishes no absolute numbers — BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Fallback CPU anchor (trees-rows/sec) measured on this image's XLA CPU
# backend when no in-process CPU backend is available; refreshed whenever
# bench.py is run on a CPU-only session.
_CPU_FALLBACK = 3.85e6  # measured on this image's XLA CPU (2026-07-29)

N_POPULATIONS = 64
NPOP = 1000
# 2048 rows since round 5: the 2026-08-02 on-chip rows sweep measured the
# default kernel at 1.393e9 t-r/s with 2048 rows vs 1.054e9 at 1024 —
# past full sublane occupancy (>=1024 rows) extra row tiles amortize the
# kernel's fixed per-step cost (the 42% overhead term in the opset
# decomposition), so the larger dataset is the better operating point
# users should pick when they have the rows. The CPU anchors are
# co-measured at the SAME shape; their per-(tree,row) cost is linear in
# rows, so their trees-rows/s rates (including the last-resort
# _CPU_FALLBACK constant above) are ~shape-independent and vs_baseline
# stays an apples-to-apples ratio.
N_ROWS = 2048
MAXSIZE = 20
CHUNK = 8192
REPS = 3

# Max relative per-tree loss deviation accepted as "parity", shared by the
# verdict in main() and the conditioning filter in _mse_parity (the filter
# admits a tree only when f32 arithmetic can intrinsically deliver this
# tolerance, so the two must move together).
PARITY_TOL = 1e-3


def _build_workload(jax, jnp, options, n_trees, n_feat):
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )

    key = jax.random.PRNGKey(0)
    sizes = jax.random.randint(
        jax.random.PRNGKey(1), (n_trees,), 3, MAXSIZE
    )
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, n_feat, options.operators, options.max_len
        )
    )(jax.random.split(key, n_trees), sizes)
    return trees


def _feynman_data():
    """Feynman-I.6.2a: y = exp(-theta^2/2)/sqrt(2*pi), theta ~ U(1, 3).

    Single source of the benchmark workload — the main timing and the CPU
    anchor MUST score the identical dataset."""
    rng = np.random.default_rng(0)
    theta = rng.uniform(1.0, 3.0, N_ROWS).astype(np.float32)
    X = theta[None, :]
    y = (np.exp(-(theta**2) / 2.0) / np.sqrt(2 * np.pi)).astype(np.float32)
    return X, y


def _dispatch_overhead_s(jax, jnp, device):
    """Fixed cost of one dispatch+fetch round trip on `device`. On tunneled
    TPU transports this is tens of milliseconds and would otherwise dominate
    any single-dispatch timing."""
    with jax.default_device(device):
        f = jax.jit(lambda x: jnp.sum(x * 2.0))
        x = jnp.ones((8, 128), jnp.float32)
        float(f(x))
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(f(x))
            ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _time_backend(jax, jnp, options, device, n_trees, n_inner, label,
                  verbose, spans=None):
    """Score n_trees random trees against the Feynman-I.6.2a dataset on
    `device`; return (trees-rows/sec, compile seconds, tree lengths).

    The scoring step runs `n_inner` times INSIDE one jit (constants
    perturbed per iteration so no computation can be reused) and the fixed
    dispatch overhead — measured separately — is subtracted; a single
    dispatch through a tunneled TPU transport costs ~70 ms, which would
    swamp the kernel.

    spans: a telemetry.spans.SpanRecorder — the timed rep loop is
    recorded as an `eval`-stage span whose attrs carry the workload
    shape, the measured overhead, and the derived overhead-subtracted
    trees_rows_per_s (the number roofline_fraction is computed from)."""
    from symbolicregression_jl_tpu.models.fitness import score_trees

    if spans is None:
        from symbolicregression_jl_tpu.telemetry.spans import NULL as spans

    n_feat = 1
    X_h, y_h = _feynman_data()

    overhead = _dispatch_overhead_s(jax, jnp, device)
    with jax.default_device(device):
        trees = _build_workload(jax, jnp, options, n_trees, n_feat)
        X = jnp.asarray(X_h)
        y = jnp.asarray(y_h)
        baseline = jnp.float32(float(np.var(y_h)))

        def body(i, acc):
            t = trees._replace(cval=trees.cval + acc * 1e-12)
            scores, _ = score_trees(t, X, y, None, baseline, options)
            # bounded accumulator: keeps each iteration data-dependent on
            # the last without ever overflowing f32
            good = jnp.where(jnp.isfinite(scores), scores, 0.0)
            return acc + jnp.clip(jnp.mean(good), 0.0, 1.0)

        fn = jax.jit(
            lambda: jax.lax.fori_loop(0, n_inner, body, jnp.float32(0.0))
        )
        t_c = time.perf_counter()
        total = float(fn())  # warmup / compile
        compile_s = time.perf_counter() - t_c
        assert np.isfinite(total)

        with spans.span(
            "eval", trees=n_trees, rows=N_ROWS, inner_iters=n_inner,
            reps=REPS, label=label,
        ) as sp:
            times = []
            for _ in range(REPS):
                t0 = time.perf_counter()
                float(fn())  # scalar fetch forces a full sync
                times.append(time.perf_counter() - t0)
            per_iter = max(
                (float(np.median(times)) - overhead) / n_inner, 1e-9
            )
            sp.attrs["dispatch_overhead_s"] = overhead
            sp.attrs["trees_rows_per_s"] = n_trees * N_ROWS / per_iter

    lengths = np.asarray(jax.device_get(trees.length), dtype=np.float64)
    rate = n_trees * N_ROWS / per_iter
    if verbose:
        print(
            f"# {label}: {n_trees} trees x {N_ROWS} rows x {n_inner} iters, "
            f"{per_iter*1e3:.1f} ms/iter (dispatch overhead "
            f"{overhead*1e3:.0f} ms subtracted; first call incl. compile "
            f"{compile_s:.1f}s) -> {rate:.3e} trees-rows/s",
            file=sys.stderr,
        )
    return rate, compile_s, lengths


def time_pallas_variant(jax, jnp, trees, X, operators, overhead,
                        n_inner, **kw):
    """Shared timing harness for kernel A/B scripts (kernel_tune,
    opset_sweep): n_inner kernel calls inside ONE jit with the
    constant-perturbation trick, 3-rep median, dispatch overhead
    subtracted. Keeping this here keeps every sweep's methodology in
    lockstep with the headline benchmark by construction.

    Returns (trees_rows_per_s, seconds_per_iteration, compile_seconds)."""
    from symbolicregression_jl_tpu.ops.pallas_eval import eval_trees_pallas

    def body(i, acc):
        t = trees._replace(cval=trees.cval + acc * 1e-12)
        y, ok = eval_trees_pallas(t, X, operators, **kw)
        s = jnp.where(ok, jnp.mean(y, axis=-1), 0.0)
        return acc + jnp.clip(jnp.mean(s), 0.0, 1.0)

    fn = jax.jit(
        lambda: jax.lax.fori_loop(0, n_inner, body, jnp.float32(0.0))
    )
    t_c0 = time.perf_counter()
    total = float(fn())
    compile_s = time.perf_counter() - t_c0
    assert np.isfinite(total), kw
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn())
        ts.append(time.perf_counter() - t0)
    per_iter = max((float(np.median(ts)) - overhead) / n_inner, 1e-9)
    n_trees = int(np.prod(trees.length.shape))
    # row count from the actual workload (kernel_tune's --rows-sweep
    # passes datasets of varying width)
    return n_trees * X.shape[1] / per_iter, per_iter, compile_s


ANCHOR_REPS = 5  # the anchor swung 1.8x between rounds when timed once;
# >=5 runs with the spread recorded makes vs_baseline attributable


def _cpu_core_count():
    """Cores actually available to this process (affinity-aware) — the
    honest multiplier behind any 'multithreaded' anchor claim."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover
        return os.cpu_count()


def _native_cpu_anchor(jax, options, n_trees, verbose):
    """Native-C++ score throughput (eval + MSE reduction) on the same
    workload — the honest stand-in for the reference's compiled-Julia CPU
    `score_func` path. Threaded across however many cores the process
    actually has (the printed line says how many: a 1-core container is
    NOT a multithreaded anchor, and labeling it as one overstated the
    anchor in BENCH_r05). Returns (median trees-rows/sec, per-run rates)
    or (None, [])."""
    from symbolicregression_jl_tpu import native

    if not native.native_available():
        return None, []
    X, y = _feynman_data()
    with jax.default_device(jax.devices("cpu")[0]):
        trees = _build_workload(jax, None, options, n_trees, 1)
        arrs = tuple(np.asarray(x) for x in trees)
    out = native.eval_batch(*arrs, X, options.operators, y_target=y)
    if out is None:
        return None, []
    rates = []
    for _ in range(ANCHOR_REPS):
        t0 = time.perf_counter()
        native.eval_batch(*arrs, X, options.operators, y_target=y)
        rates.append(n_trees * N_ROWS / (time.perf_counter() - t0))
    rate = float(np.median(rates))
    if verbose:
        n_cores = _cpu_core_count()
        print(
            f"# native CPU anchor (C++ score, {n_cores} core"
            f"{'s' if n_cores != 1 else ''}): {n_trees} "
            f"trees x {N_ROWS} rows, {len(rates)} runs -> median "
            f"{rate:.3e} trees-rows/s "
            f"(spread {min(rates):.3e}..{max(rates):.3e})",
            file=sys.stderr,
        )
    return rate, rates


def _mse_parity(jax, jnp, options, device, n_check, verbose):
    """North-star requires MSE *parity*, not just throughput: the TPU
    kernel's per-tree losses must match the CPU reference interpreter's.

    Parity is only meaningful on trees whose evaluation is numerically
    *stable* in float32. Random workloads contain ill-conditioned trees —
    e.g. `const / cos(exp(exp(exp(c))))`, where a few-ULP difference in a
    transcendental upstream rotates the cosine argument by radians, so
    every correct implementation (numpy f32, numpy f64, XLA-CPU, TPU)
    returns a different answer; milder cases like `cos(260.3*...)`
    amplify exp's last-ULP variation ~1000x into percent-level loss
    shifts. Those are excluded by an implementation-independent condition
    test: the f64 numpy-oracle loss is re-evaluated with f32-ULP-scale
    (3e-7) random relative perturbations of the constants and inputs, and
    a tree counts as stable only when 10x its observed loss spread stays
    under the parity tolerance — i.e. parity is demanded exactly where
    f32 arithmetic itself can deliver it. Returns max relative
    |loss_dev - loss_cpu| over stable finite-on-both trees."""
    from symbolicregression_jl_tpu.models.fitness import score_trees
    from symbolicregression_jl_tpu.ops.eval_numpy import eval_tree_numpy

    X_h, y_h = _feynman_data()
    baseline = float(np.var(y_h))

    # one workload, built once on CPU, shipped verbatim to both backends
    with jax.default_device(jax.devices("cpu")[0]):
        trees = _build_workload(jax, jnp, options, n_check, 1)
    trees_h = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), trees
    )

    def losses_on(dev):
        # 'auto' dispatch routes to the Pallas kernel on TPU and the jnp
        # lockstep interpreter under a CPU default_device
        with jax.default_device(dev):
            tt = jax.tree_util.tree_map(jnp.asarray, trees_h)
            _, losses = score_trees(
                tt, jnp.asarray(X_h), jnp.asarray(y_h), None,
                jnp.float32(baseline), options,
            )
            return np.asarray(jax.device_get(losses))

    l_dev = losses_on(device)
    l_cpu = losses_on(jax.devices("cpu")[0])

    # f32-conditioning filter via the jax-free f64 numpy oracle:
    # tol_i = max loss spread under K perturbations of relative size EPS
    X64 = X_h.astype(np.float64)
    y64 = y_h.astype(np.float64)
    rng = np.random.default_rng(42)
    EPS, K, SAFETY, TOL = 3e-7, 3, 10.0, PARITY_TOL

    def oracle_loss(t_i, Xp, yd):
        with np.errstate(all="ignore"):
            y_pred, complete = eval_tree_numpy(t_i, Xp, options.operators)
            return (
                float(np.mean((y_pred - yd) ** 2))
                if complete else np.inf
            )

    def perturb(a):
        if np.issubdtype(a.dtype, np.floating):
            return a * (1.0 + EPS * rng.standard_normal(a.shape))
        return a

    # three per-tree classes: `stable` (value parity demanded), `poisoned`
    # (f32 oracle hits a NaN/Inf domain — finiteness parity demanded: a
    # backend that silently un-poisons a tree must not escape the check),
    # and the ill-conditioned remainder (excluded, counted separately)
    stable = np.zeros(n_check, bool)
    poisoned = np.zeros(n_check, bool)
    for i in range(n_check):
        t_i = jax.tree_util.tree_map(lambda x: x[i], trees_h)
        poisoned[i] = not np.isfinite(oracle_loss(t_i, X_h, y_h))
        base = oracle_loss(t_i, X64, y64)
        if not np.isfinite(base):
            continue
        spread = 0.0
        for _ in range(K):
            lk = oracle_loss(
                jax.tree_util.tree_map(perturb, t_i), perturb(X64), y64
            )
            if not np.isfinite(lk):
                spread = np.inf
                break
            spread = max(
                spread, abs(lk - base) / max(abs(base), 1e-6)
            )
        # an f32-poisoned tree can't be value-compared even if its f64
        # evaluation is stable (borderline overflow): classes stay disjoint
        stable[i] = (SAFETY * spread < TOL) and not poisoned[i]

    both = np.isfinite(l_dev) & np.isfinite(l_cpu) & stable
    # finiteness must match the ORACLE wherever it gives a decisive
    # answer — finite on stable trees, non-finite on poisoned trees —
    # so a shared backend defect that un-poisons a tree can't slip
    # through by agreeing with itself; only the ill-conditioned middle
    # ground — e.g. overflow within ULPs of the f32 cutoff — is exempt
    decisive = stable | poisoned
    expect_finite = stable[decisive]
    agree_finite = float(
        np.mean(
            (np.isfinite(l_dev[decisive]) == expect_finite)
            & (np.isfinite(l_cpu[decisive]) == expect_finite)
        )
    ) if decisive.any() else float("nan")
    rel = np.abs(l_dev[both] - l_cpu[both]) / np.maximum(
        np.abs(l_cpu[both]), 1e-6
    )
    # a parity verdict over too few mutually-finite trees is vacuous —
    # report that as its own state, not as a numerical mismatch
    enough = rel.size >= 100
    max_rel = float(rel.max()) if enough else float("nan")
    if verbose:
        n_illcond = int(n_check - stable.sum() - poisoned.sum())
        print(
            f"# MSE parity vs CPU interpreter: {int(both.sum())} stable "
            f"trees compared ({int(poisoned.sum())} oracle-poisoned held "
            f"to finiteness parity only, {n_illcond} f32-ill-conditioned "
            "excluded by oracle perturbation test), "
            f"max rel dev {max_rel:.2e}, finite-mask agreement "
            f"{agree_finite:.4f}",
            file=sys.stderr,
        )
    return (max_rel if enough else None), agree_finite


# Acquisition diagnostics for the output JSON, filled by
# _devices_or_cpu_fallback: list of {"sleep_s", "probe_s", "result"} per
# attempt, plus the final tunnel verdict ("up" / "down").
ACQUISITION = {"attempts": [], "tunnel_state": "unknown"}

# Sleep before each TPU probe attempt (seconds). Spread over ~10 minutes:
# the axon tunnel has been observed to recover on that timescale, and a
# benchmark that permanently pins to CPU after one failed probe throws the
# round's headline number away.
def _parse_schedule(raw):
    try:
        vals = tuple(
            max(0, int(x)) for x in raw.split(",") if x.strip()
        )
    except ValueError:
        return (0, 20, 40, 80, 160, 300)
    return vals or (0,)


_PROBE_BACKOFFS = _parse_schedule(
    os.environ.get("SRTPU_BENCH_PROBE_SCHEDULE", "0,20,40,80,160,300")
)
# Per-phase bounds (VERDICT r3 #6 — the r3 artifact recorded a 240 s
# direct-init-hung stall on a half-open tunnel): each probe subprocess
# is killed at _PROBE_TIMEOUT and each in-process init abandoned at
# _INIT_TIMEOUT, so an attempt's worst case is their sum (~115 s, when
# the tunnel passes the probe then hangs the init) and the common hang
# mode costs one probe timeout. A healthy tunnel probes in ~3-25 s and,
# once probed, inits in seconds.
# The shipped default, exposed as its own constant so tests can assert
# the bound directly instead of regex-scanning source text; the effective
# _PROBE_TIMEOUT still honors SRTPU_BENCH_PROBE_TIMEOUT at import time.
_PROBE_TIMEOUT_DEFAULT = 55.0
try:
    _PROBE_TIMEOUT = float(
        os.environ.get(
            "SRTPU_BENCH_PROBE_TIMEOUT", str(_PROBE_TIMEOUT_DEFAULT)
        )
    )
except ValueError:
    _PROBE_TIMEOUT = _PROBE_TIMEOUT_DEFAULT
_INIT_TIMEOUT = 60.0  # in-process backend init watchdog


def _probe_tpu_subprocess(timeout):
    """Try `jax.devices()` in a throwaway subprocess (killed on timeout, so
    a hung tunnel can't poison this process's backend state). Returns the
    platform string, or None on hang/error."""
    import subprocess

    import signal

    code = "import jax; print('PLAT=' + jax.devices()[0].platform)"
    # start_new_session + killpg: the axon plugin may spawn tunnel helper
    # processes that inherit the pipes; killing only the direct child would
    # leave communicate() blocked on pipe EOF forever
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except Exception:
            p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:  # pragma: no cover
            pass
        return None, "hang"
    if p.returncode != 0:
        tail = (err or "").strip().splitlines()
        return None, "error: " + (
            tail[-1][:120] if tail else f"rc={p.returncode}"
        )
    for line in out.splitlines():
        if line.startswith("PLAT="):
            return line[len("PLAT="):].strip(), "ok"
    return None, "no-platform-line"


def _init_backend_with_watchdog(timeout):
    """In-process jax.devices() guarded by a watchdog thread (the tunnel
    can pass a subprocess probe and still hang a moment later). Returns
    (devices, None) on success, (None, reason) on error or hang."""
    import threading

    import jax

    box = {}

    def probe():
        try:
            box["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            box["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if "devices" in box:
        return box["devices"], None
    if "error" in box:
        return None, f"init-error: {str(box['error'])[:120]}"
    return None, "init-hung"


_MEMO_PATH = "/tmp/srtpu_tunnel_memo.json"
_MEMO_TTL = 900.0  # seconds a recorded tunnel verdict stays trustworthy


def _write_memo(state):
    try:
        with open(_MEMO_PATH, "w") as f:
            json.dump({"state": state, "t": time.time()}, f)
    except OSError:  # pragma: no cover
        pass


def _read_memo():
    try:
        with open(_MEMO_PATH) as f:
            memo = json.load(f)
        if time.time() - float(memo["t"]) < _MEMO_TTL:
            return memo["state"]
    except Exception:
        pass
    return None


def _clear_memo():
    """Drop a memo that live evidence just contradicted (a memo-trusted
    init hung or landed on CPU): the tunnel's real state is unknown, so
    the next entry point must re-probe rather than inherit a stale 'up'
    and burn its own full init timeout on it."""
    try:
        os.remove(_MEMO_PATH)
    except OSError:
        pass


def _fallback_to_cpu(verbose):
    """Re-exec this script pinned to CPU, carrying the diagnostics."""
    if verbose:
        print(
            f"# TPU backend unavailable after "
            f"{len(ACQUISITION['attempts'])} acquisition attempts; "
            "re-running on CPU",
            file=sys.stderr,
        )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["_SRTPU_BENCH_CPU_FALLBACK"] = "1"
    env["_SRTPU_BENCH_ACQ"] = json.dumps(ACQUISITION)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _init_and_classify():
    """Watchdogged in-process init, classified: ('tpu', devices, dt) on a
    real accelerator; ('cpu-fallback', devices, dt) when init completed
    but landed on CPU (sitecustomize's 'axon,cpu' ordering falls back
    silently when the tunnel drops between probe and init — those CPU
    devices must NEVER be recorded as tunnel_state='up'); ('init-hung'/
    'init-error: ...', None, dt) otherwise. After 'cpu-fallback' this
    process's one-shot backend is poisoned — callers must re-exec."""
    t0 = time.perf_counter()
    devices, why = _init_backend_with_watchdog(_INIT_TIMEOUT)
    dt = round(time.perf_counter() - t0, 1)
    if devices is not None:
        if devices[0].platform != "cpu":
            return "tpu", devices, dt
        return "cpu-fallback", devices, dt
    return why, None, dt


def _pin_cpu_absent():
    """No accelerator registered at all — nothing to wait for. Pin CPU so
    the in-process init can't race a tunnel that comes back in its hang
    state, and record the verdict."""
    ACQUISITION["tunnel_state"] = "absent"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax.devices()


def _reexec(resume_at):
    env = dict(os.environ)
    env["_SRTPU_BENCH_ACQ"] = json.dumps(ACQUISITION)
    env["_SRTPU_BENCH_RESUME_AT"] = str(resume_at)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _devices_or_cpu_fallback(verbose, use_memo=False):
    """Acquire the accelerator with bounded retry/backoff; fall back to CPU
    only after the full probe schedule fails.

    The axon TPU tunnel, when unhealthy, HANGS backend init indefinitely
    (observed for 8+ hours on 2026-07-30) rather than erroring. Strategy
    (probe-first, VERDICT r3 #6): every attempt — including the first —
    runs `jax.devices()` in a killable subprocess probe, and only a
    successful probe earns an in-process init (itself under a 60 s
    watchdog: a tunnel can pass the probe and hang a moment later). Each
    phase is bounded (probe <= _PROBE_TIMEOUT, init <= _INIT_TIMEOUT), so
    a half-open relay costs tens of seconds per attempt, not the r3
    artifact's 240 s stall.
    On total failure, re-exec pinned to CPU so the benchmark still
    records a result. Per-attempt diagnostics land in ACQUISITION.

    `use_memo=True` (the auxiliary entry points — suite.py, feynman.py,
    kernel_tune.py) trusts a recent verdict from another process instead
    of re-running the whole schedule against a dead tunnel. bench.py
    itself never trusts the memo: the round's official number must fight
    the full schedule.
    """
    # restore diagnostics from a prior exec of this acquisition loop
    try:
        ACQUISITION.update(
            json.loads(os.environ.get("_SRTPU_BENCH_ACQ", "{}"))
        )
    except Exception:
        pass

    if os.environ.get("_SRTPU_BENCH_CPU_FALLBACK") == "1":
        # distinguish the relay's half-open mode (probe or init HANGS —
        # something answers the connection but never completes) from a
        # plainly dead tunnel (fast errors): the two have different
        # recovery timescales and the artifact should say which we saw.
        # Exact-match the recorder's own constants — free-form error text
        # (e.g. "probe-ok-init-error: <stderr tail>" whose truncated tail
        # could end in "init-hung") must not key the diagnosis.
        _HUNG_RESULTS = {
            "probe-hang", "memo-up-init-hung", "probe-ok-init-hung",
        }
        hung = any(
            a.get("result") in _HUNG_RESULTS
            for a in ACQUISITION["attempts"]
        )
        ACQUISITION["tunnel_state"] = "half-open" if hung else "down"
        import jax

        # NOT redundant with the env var set before re-exec: this image's
        # sitecustomize rewrites JAX_PLATFORMS=cpu back to "axon,cpu"; the
        # in-process config update is the pin that actually sticks (popping
        # the axon pool IP also disables the tunnel, belt and braces).
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()

    resumed = "_SRTPU_BENCH_RESUME_AT" in os.environ
    start = int(os.environ.get("_SRTPU_BENCH_RESUME_AT", "0"))

    if use_memo and not resumed:
        memo = _read_memo()
        if memo == "down":
            ACQUISITION["attempts"].append(
                {"sleep_s": 0, "probe_s": 0.0, "result": "memo-down"}
            )
            _fallback_to_cpu(verbose)
        if memo == "up":
            # a sibling process verified the tunnel moments ago: skip the
            # ~15-25 s throwaway probe subprocess — on a ~31-minute chip
            # window the watcher's 7 steps would otherwise burn minutes
            # re-proving the same verdict. The init watchdog still bounds
            # the cost if the tunnel dropped since.
            kind, devices, dt = _init_and_classify()
            rec = {"sleep_s": 0, "probe_s": 0.0, "init_s": dt,
                   "result": f"memo-up-{kind}"}
            ACQUISITION["attempts"].append(rec)
            if kind == "tpu":
                ACQUISITION["tunnel_state"] = "up"
                _write_memo("up")
                return devices
            # hung or silently-CPU: this process's backend is poisoned —
            # continue the full schedule in a fresh process (init errors
            # could retry in-process, but re-exec keeps one code path).
            # The memo that promised 'up' is contradicted by what just
            # happened: clear it so the re-exec'd schedule (and any
            # sibling entry point) re-probes instead of trusting it —
            # and so a killed re-exec can't leave the stale 'up' behind
            # to cost every later entry point a full hung init.
            _clear_memo()
            _reexec(0)

    if not resumed:
        # Probe-first (VERDICT r3 #6): a killable subprocess probe screens
        # the tunnel BEFORE any in-process init — on a half-open relay the
        # in-process path used to block for the full 240 s watchdog and,
        # worse, poison this process's one-shot backend init. A healthy
        # tunnel pays ~15-25 s of throwaway probe; a hung one costs
        # exactly _PROBE_TIMEOUT and leaves this process clean to retry.
        t0 = time.perf_counter()
        plat, why = _probe_tpu_subprocess(_PROBE_TIMEOUT)
        rec = {
            "sleep_s": 0,
            "probe_s": round(time.perf_counter() - t0, 1),
            "result": plat or f"probe-{why}",
        }
        ACQUISITION["attempts"].append(rec)
        if plat is not None and plat != "cpu":
            # the tunnel just answered the probe: if the init completes
            # with a retryable error, retry the init directly instead of
            # paying another ~20 s throwaway probe for a verdict we have
            for _ in range(2):
                kind, devices, dt = _init_and_classify()
                rec["init_s"] = dt
                # always record the LATEST outcome: a retried init that
                # succeeds must not leave the first error as the
                # attempt's published result
                rec["result"] = "tpu" if kind == "tpu" else (
                    f"probe-ok-{kind}"
                )
                if kind == "tpu":
                    ACQUISITION["tunnel_state"] = "up"
                    _write_memo("up")
                    return devices
                if kind in ("init-hung", "cpu-fallback"):
                    # init-hung: the watchdog thread is stuck inside
                    # xla_bridge's one-shot init holding its lock;
                    # cpu-fallback: the backend initialized, but as CPU.
                    # Either way nothing in this process can init the
                    # TPU backend again — continue in a fresh one. As in
                    # the memo-up branch above: live evidence just showed
                    # the tunnel poisoned, so drop any memo before the
                    # re-exec — a sibling suite child trusting a stale
                    # 'up' would burn a full init timeout on this same
                    # known-poisoned tunnel.
                    _clear_memo()
                    _reexec(0)
            # two init errors in a row → fall through to the schedule
            # loop from slot 0 (its zero sleep is still right: the
            # tunnel is answering, something else is wrong)
        elif plat == "cpu":
            return _pin_cpu_absent()
        else:
            # the fast-path PROBE failed (hang/error): skip the
            # schedule's zero-sleep first slot — an immediate identical
            # re-probe learns nothing. (Unless the schedule has only
            # that one slot: a single-slot schedule must still get its
            # one retry rather than fall straight to the CPU fallback.)
            start = min(1, n - 1) if (n := len(_PROBE_BACKOFFS)) else 0

    n = len(_PROBE_BACKOFFS)
    i = start
    streak_jumped = False
    while i < n:
        backoff = _PROBE_BACKOFFS[i]
        if backoff:
            time.sleep(backoff)
        t0 = time.perf_counter()
        plat, why = _probe_tpu_subprocess(_PROBE_TIMEOUT)
        rec = {
            "sleep_s": backoff,
            "probe_s": round(time.perf_counter() - t0, 1),
            # same spelling as the fast path ("probe-hang"/"probe-error:
            # ..."): the half-open classifier and the streak check key on
            # these constants — one recorder format, three readers
            "result": plat or f"probe-{why}",
        }
        ACQUISITION["attempts"].append(rec)
        if plat is not None and plat != "cpu":
            kind, devices, dt = _init_and_classify()
            rec["init_s"] = dt
            if kind == "tpu":
                ACQUISITION["tunnel_state"] = "up"
                _write_memo("up")
                return devices
            rec["result"] = f"probe-ok-{kind}"
            # as in the fast path: a hang (or a silent CPU init) poisons
            # this process's backend forever; an init error is retryable
            # in-process. Clear the memo either way (even when the
            # schedule is exhausted and no re-exec follows): the tunnel
            # just proved poisoned, and sibling entry points must
            # re-probe rather than inherit a stale 'up'.
            if kind in ("init-hung", "cpu-fallback"):
                _clear_memo()
                if i + 1 < n:
                    _reexec(i + 1)
        elif plat == "cpu":
            return _pin_cpu_absent()
        # A hang may heal with time. Three identical fast errors in a row
        # usually won't — but the error text can't distinguish "plugin
        # broken" from "single tunnel slot busy", so instead of giving up,
        # jump straight to the final (longest-wait) attempt: one late shot
        # at recovery without burning the middle of the schedule.
        tail = [a["result"] for a in ACQUISITION["attempts"][-3:]]
        if (
            not streak_jumped
            and i + 1 < n - 1
            and len(tail) == 3
            and len(set(tail)) == 1
            and tail[0].startswith("probe-error")
        ):
            streak_jumped = True
            if verbose:
                print(
                    f"# TPU probe attempt {i + 1}/{n}: {rec['result']} "
                    f"(3rd identical error); skipping to final attempt "
                    f"in {_PROBE_BACKOFFS[n - 1]}s",
                    file=sys.stderr,
                )
            i = n - 1
            continue
        if verbose and i + 1 < n:
            print(
                f"# TPU probe attempt {i + 1}/{n}: {rec['result']}; "
                f"retrying in {_PROBE_BACKOFFS[i + 1]}s",
                file=sys.stderr,
            )
        i += 1

    _write_memo("down")
    _fallback_to_cpu(verbose)


def _last_tpu_block():
    """The most recent on-chip evidence captured by scripts/tpu_watcher.py
    (BENCH_TPU_LATEST.json), with log tails stripped — embedded in the
    output whenever this run is forced into its CPU fallback, so the
    official artifact carries a dated hardware record even when the
    tunnel is down at capture time."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_LATEST.json"
    )
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:
        return None
    steps = {}
    for name, rec in (data.get("steps") or {}).items():
        rec = {k: v for k, v in rec.items() if not k.endswith("_tail")}
        # a recorded CPU-fallback bench line may itself carry a last_tpu
        # block — drop it so the embedding can't nest recursively
        rec["json"] = [
            {k: v for k, v in j.items() if k != "last_tpu"}
            for j in rec.get("json", []) or []
        ]
        steps[name] = rec
    out = {
        "captured_at": data.get("captured_at"),
        "complete": data.get("complete"),
        "steps": steps,
    }
    for j in steps.get("bench", {}).get("json", []):
        # only a line the bench itself attributes to the chip counts as
        # the on-chip headline (a flapping tunnel can leave a recorded
        # CPU-fallback bench step)
        if "vs_baseline" in j and j.get("platform") == "tpu":
            out["value"] = j.get("value")
            out["vs_baseline"] = j.get("vs_baseline")
    return out


def _roofline_skip_reason(platform, pallas_routed, error=None):
    """Why roofline_measured is null, as a machine-checkable string
    (distinct reasons, never a silent null): 'cpu-only' — a CPU run has
    no VPU-issue roofline bound; 'interpreter-path' — the device run's
    scoring stayed on the jnp interpreter (work-volume gate or
    eval_backend), so the kernel roofline does not describe it;
    'import-failure' — the roofline model itself could not be imported;
    'error: <Type>' — the model imported but the computation failed.
    Returns None exactly when the measured fraction should have a
    value. The MODELED fraction (roofline_modeled, srprof) has no skip
    reason: it exists on every platform — CPU-only rounds carry it
    instead of a silent null."""
    if platform == "cpu":
        return "cpu-only"
    if not pallas_routed:
        return "interpreter-path"
    if error is not None:
        if isinstance(error, ImportError):
            return "import-failure"
        return f"error: {type(error).__name__}"
    return None


def main(verbose=True):
    t_main_start = time.time()
    devices = _devices_or_cpu_fallback(verbose)

    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=MAXSIZE,
        loss="L2DistLoss",
    )

    main_dev = devices[0]
    platform = main_dev.platform
    n_trees = N_POPULATIONS * NPOP

    # Per-run telemetry event log (telemetry/ subsystem): the
    # machine-readable record of this bench run — the tunnel-acquisition
    # verdict and the eval-stage span roofline_fraction is computed
    # from. Observability must never sink the benchmark: any failure
    # here degrades to sink=None.
    sink, spans = None, None
    try:
        import tempfile

        from symbolicregression_jl_tpu.telemetry.events import (
            open_event_log,
        )
        from symbolicregression_jl_tpu.telemetry.spans import SpanRecorder

        tdir = os.environ.get(
            "SRTPU_BENCH_TELEMETRY_DIR"
        ) or tempfile.mkdtemp(prefix="srtpu_bench_telemetry_")
        sink = open_event_log(tdir)
        # fleet provenance (additive run_start fields + registration):
        # a watcher-launched bench stamps the step's retry counter and
        # registers into the fleet root srfleet watches
        try:
            _attempt = max(1, int(os.environ.get("SRTPU_RUN_ATTEMPT", "1")))
        except ValueError:
            _attempt = 1
        sink.emit(
            "run_start",
            run_id=sink.run_id,
            attempt=_attempt,
            config_fingerprint=(
                f"bench-{N_POPULATIONS}x{NPOP}-rows{N_ROWS}"
                f"-maxsize{MAXSIZE}"
            ),
            backend=platform,
            devices=[str(d) for d in devices],
            nout=1,
            x_shape=[1, N_ROWS],
        )
        _fleet_root = os.environ.get("SRTPU_FLEET_ROOT")
        if _fleet_root:
            from symbolicregression_jl_tpu.telemetry.fleet import (
                register_run,
            )

            register_run(
                _fleet_root, source="bench", run_id=sink.run_id,
                telemetry_dir=tdir, attempt=_attempt,
            )
        sink.emit(
            "tunnel_state",
            state=ACQUISITION["tunnel_state"],
            attempts=ACQUISITION["attempts"],
        )
        spans = SpanRecorder(sink)
    except Exception as e:  # pragma: no cover - defensive
        sink, spans = None, None
        if verbose:
            print(f"# telemetry unavailable: {e}", file=sys.stderr)

    if platform != "cpu":
        # persistent compilation cache: TPU executables serialize safely
        # (the known segfault is CPU-only), so a repeat bench run loads its
        # kernel from cache instead of paying the 20-40s compile
        try:
            from symbolicregression_jl_tpu.utils.precompile import (
                enable_compilation_cache,
            )

            enable_compilation_cache()
        except Exception as e:  # pragma: no cover
            if verbose:
                print(f"# compilation cache unavailable: {e}",
                      file=sys.stderr)

    value, compile_s, workload_lengths = _time_backend(
        jax, jnp, options, main_dev, min(n_trees, CHUNK), 20,
        f"main ({platform})", verbose, spans=spans,
    )

    parity = ""
    if platform != "cpu":
        try:
            max_rel, agree = _mse_parity(
                jax, jnp, options, main_dev, 2048, verbose
            )
            if max_rel is None:
                verdict = "INSUFFICIENT-SAMPLE"
            elif max_rel < PARITY_TOL and agree > 0.999:
                verdict = "OK"
            else:
                verdict = "MISMATCH"
            parity = f"; MSE parity vs CPU: {verdict}"
        except Exception as e:  # pragma: no cover
            if verbose:
                print(f"# parity check failed: {e}", file=sys.stderr)

    # Preferred anchor: native multithreaded C++ score path (the analog of
    # the reference's compiled-Julia CPU throughput). Fallback: XLA-CPU
    # lockstep interpreter.
    cpu_rate, anchor_rates = None, []
    try:
        cpu_rate, anchor_rates = _native_cpu_anchor(
            jax, options, min(n_trees, 8192), verbose
        )
    except Exception as e:  # pragma: no cover
        if verbose:
            print(f"# native anchor failed: {e}", file=sys.stderr)
    anchor = "native-C++-MT-CPU"
    # secondary anchor: the XLA-CPU lockstep interpreter on the same
    # workload, so swings in vs_baseline are attributable to the native
    # anchor vs the machine (VERDICT r2 weak-5). Skipped when this run
    # IS the CPU fallback (then `value` is that number already).
    xla_cpu_rate = None
    if platform != "cpu":
        try:
            cpu_dev = jax.devices("cpu")[0]
            xla_cpu_rate, _, _ = _time_backend(
                jax, jnp, options, cpu_dev, min(n_trees, 8192), 1,
                "xla-cpu anchor", verbose,
            )
        except Exception as e:  # pragma: no cover
            if verbose:
                print(f"# xla-cpu anchor unavailable: {e}",
                      file=sys.stderr)
    if cpu_rate is None:
        anchor = "xla-cpu"
        if xla_cpu_rate is not None:
            cpu_rate = xla_cpu_rate
        elif platform != "cpu":
            cpu_rate = _CPU_FALLBACK
        else:
            cpu_rate = value

    n_cores = _cpu_core_count()
    # the anchor label carries the measured core count: a 1-core
    # container's native anchor is single-threaded, and calling it
    # "multithreaded" overstated the baseline (BENCH_r05)
    if anchor == "native-C++-MT-CPU":
        anchor = f"native-C++-CPU-{n_cores}core"

    # bucketed-vs-flat jnp interpreter throughput (ISSUE 5): the
    # length-bucketed eval dispatch (Options.eval_bucket_ladder) against
    # the flat interpreter on the SAME workload and device — measured on
    # the CPU backend, the interpreter's production home (on TPU the
    # large-batch scoring path runs the Pallas kernel, whose own bucket
    # dispatch is A/B'd separately below as pallas_bucketed_vs_flat).
    # The flat reference reuses the rate already measured above
    # (main run on CPU platform, the xla-cpu anchor otherwise).
    bucketed_rate, bucketed_ratio = None, None
    interp_flat_rate = value if platform == "cpu" else xla_cpu_rate
    if interp_flat_rate is not None:
        try:
            b_options = make_options(
                binary_operators=["+", "-", "*", "/"],
                unary_operators=["cos", "exp"],
                maxsize=MAXSIZE,
                loss="L2DistLoss",
                eval_backend="jnp",
                eval_bucket_ladder=(0.25, 0.5, 0.75, 1.0),
            )
            b_dev = main_dev if platform == "cpu" else jax.devices("cpu")[0]
            b_inner = 20 if platform == "cpu" else 1
            bucketed_rate, _, _ = _time_backend(
                jax, jnp, b_options, b_dev, min(n_trees, 8192), b_inner,
                "bucketed interp (cpu)", verbose,
            )
            bucketed_ratio = bucketed_rate / interp_flat_rate
        except Exception as e:  # pragma: no cover
            if verbose:
                print(f"# bucketed interp measurement failed: {e}",
                      file=sys.stderr)

    # MEASURED roofline: achieved fraction of the kernel's VPU-issue
    # roofline (see benchmark/roofline.py for the model; CPU runs have
    # no such bound). Computed from the telemetry eval-stage span's
    # measured throughput; when the fraction is null,
    # roofline_skip_reason says WHY (distinct reasons — a null with no
    # reason is a bug, not a benign skip).
    roofline_measured = None
    pallas_routed = False
    if platform != "cpu":
        try:
            from symbolicregression_jl_tpu.models.fitness import (
                resolve_eval_backend_pallas,
            )

            # THE kernel routing decision the timed run's score_trees
            # calls actually made (single source of truth in fitness.py:
            # backend knob x kernel availability x dtype x work volume)
            pallas_routed = resolve_eval_backend_pallas(
                options.eval_backend, options.dtype,
                min(n_trees, CHUNK), N_ROWS,
            )
        except Exception:  # pragma: no cover
            pallas_routed = False
    roofline_error = None
    if platform != "cpu" and pallas_routed:
        try:
            sys.path.insert(
                0,
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), "benchmark"
                ),
            )
            import inspect

            from roofline import kernel_roofline

            from symbolicregression_jl_tpu.ops.pallas_eval import (
                _SLOT_UNROLL,
                eval_trees_pallas,
            )

            # the timed run's own workload, returned by _time_backend.
            # Interleaved tree groups (tree_unroll consecutive trees
            # after the wrapper's length sort) advance in lockstep to
            # the GROUP's max length, so executed slots come from
            # per-group maxima, not per-tree lengths.
            tu = inspect.signature(eval_trees_pallas).parameters[
                "tree_unroll"
            ].default
            lens = np.sort(workload_lengths)
            pad = (-len(lens)) % tu
            if pad:
                lens = np.concatenate([lens, np.repeat(lens[-1], pad)])
            gmax = lens.reshape(-1, tu).max(axis=1)
            executed = np.ceil(gmax / _SLOT_UNROLL) * _SLOT_UNROLL
            avg = float(
                np.repeat(executed, tu)[: len(workload_lengths)].mean()
            )
            rl = kernel_roofline(options.operators, avg)
            # the telemetry eval-stage span carries the measured
            # throughput (identical to `value`: _time_backend records
            # the overhead-subtracted rate as a span attribute)
            span_rate = value
            if spans is not None:
                ev_span = next(
                    (s for s in spans.spans if s.name == "eval"), None
                )
                if ev_span is not None:
                    span_rate = ev_span.attrs.get(
                        "trees_rows_per_s", value
                    )
            roofline_measured = round(span_rate / rl["bound"], 4)
        except Exception as e:  # pragma: no cover
            roofline_error = e
            if verbose:
                print(f"# roofline unavailable: {e}", file=sys.stderr)
    roofline_skip_reason = (
        None if roofline_measured is not None
        else _roofline_skip_reason(platform, pallas_routed, roofline_error)
    )

    # MODELED roofline (srprof; docs/observability.md "Profiling"):
    # analysis/cost.py models the element-ops/bytes of the exact
    # scoring program this run timed, telemetry.profile joins that with
    # the measured rate against the device-kind peak table (CPU peaks
    # calibrated by a one-shot microbench) — so CPU-only rounds carry a
    # non-null roofline column instead of just a skip reason, and on
    # chip the modeled and measured fractions cross-check each other.
    roofline_modeled = None
    try:
        import jax as _jax

        from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost
        from symbolicregression_jl_tpu.models.fitness import score_trees
        from symbolicregression_jl_tpu.telemetry.profile import (
            device_peaks,
            roofline_join,
        )

        nt = min(n_trees, CHUNK)
        trees_aval = _jax.eval_shape(
            lambda: _build_workload(jax, jnp, options, nt, 1)
        )
        _cost = jaxpr_cost(_jax.make_jaxpr(
            lambda t, X, y, bl: score_trees(t, X, y, None, bl, options)
        )(
            trees_aval,
            _jax.ShapeDtypeStruct((1, N_ROWS), jnp.float32),
            _jax.ShapeDtypeStruct((N_ROWS,), jnp.float32),
            _jax.ShapeDtypeStruct((), jnp.float32),
        ))
        # seconds one scoring dispatch took at the measured
        # (overhead-subtracted) rate
        _measured_s = nt * N_ROWS / value
        _join = roofline_join(
            _cost["flops"], _cost["bytes"], _measured_s,
            device_peaks(main_dev), io_bytes=_cost.get("io_bytes"),
        )
        if _join["fraction"] is not None:
            roofline_modeled = round(_join["fraction"], 4)
    except Exception as e:  # pragma: no cover - defensive
        if verbose:
            print(f"# modeled roofline unavailable: {e}", file=sys.stderr)

    # the event log carries the roofline verdict too (fractions OR the
    # machine-checkable skip reason — never a silent null): the run
    # doctor (telemetry.analyze) and TRAJECTORY.json read it from here
    # whenever the eval-stage span exists, so a probe re-exec or a
    # downstream consumer that only has the log still sees WHY the
    # measured fraction is absent
    if sink is not None:
        sink.emit(
            "roofline",
            fraction=roofline_measured,
            modeled_fraction=roofline_modeled,
            skip_reason=roofline_skip_reason,
            trees_rows_per_s=value,
        )

    # ---- Pallas bucketed-vs-flat kernel ratio (ISSUE 17): the
    # bucket-laddered kernel dispatch (per-bucket t_block re-clamp over
    # the shared length-major sort) against the flat kernel on the bench
    # workload. On-chip only — interpret mode on CPU times the Pallas
    # interpreter, not the Mosaic schedule, so CPU rounds carry a skip
    # reason instead of a silent null (mirrors roofline_skip_reason;
    # the CPU-portable bit-identity half lives in
    # benchmark/suite.py::bench_pallas_bucketed). ----
    pallas_bucketed_rate = None
    pallas_bucketed_ratio = None
    pallas_bucketed_skip = None
    if platform == "cpu":
        pallas_bucketed_skip = "cpu-only round"
    elif not pallas_routed:
        pallas_bucketed_skip = "pallas-not-routed"
    else:
        try:
            _pb_trees = _build_workload(
                jax, jnp, options, min(n_trees, CHUNK), 1
            )
            _pb_X = jnp.asarray(_feynman_data()[0])
            _pb_over = _dispatch_overhead_s(jax, jnp, main_dev)
            _pb_flat, _, _ = time_pallas_variant(
                jax, jnp, _pb_trees, _pb_X, options.operators, _pb_over,
                20,
            )
            pallas_bucketed_rate, _, _ = time_pallas_variant(
                jax, jnp, _pb_trees, _pb_X, options.operators, _pb_over,
                20, bucket_ladder=(0.25, 0.5, 0.75, 1.0),
            )
            pallas_bucketed_ratio = pallas_bucketed_rate / _pb_flat
        except Exception as e:  # pragma: no cover - device-fault path
            pallas_bucketed_skip = f"error: {type(e).__name__}"
            if verbose:
                print(f"# pallas bucketed A/B failed: {e}",
                      file=sys.stderr)

    # ---- kernel tune-cache provenance (ISSUE 17): whether THIS round's
    # `auto` routing had a persistent tuned config to consult
    # (symbolicregression_jl_tpu/tune), and what it resolved to — a
    # miss with present=False is the byte-identical static-default
    # regime, not an error. ----
    kernel_tune_cache = None
    try:
        from symbolicregression_jl_tpu.tune import (
            current_device_kind,
            default_cache_path,
            load_tune_cache,
            lookup_kernel_config,
            tuned_min_work,
        )

        _tc_path = (
            os.environ.get("SRTPU_TUNE_CACHE") or default_cache_path()
        )
        _tc = load_tune_cache()
        _tc_kind = current_device_kind()
        _tc_cfg = lookup_kernel_config(
            options.operators, options.max_len, "float32"
        )
        kernel_tune_cache = {
            "present": _tc is not None,
            "path": _tc_path,
            "device_kind": _tc_kind,
            "hit": _tc_cfg is not None,
            "config": _tc_cfg,
            "min_work": tuned_min_work(),
        }
    except Exception as e:  # pragma: no cover - defensive
        if verbose:
            print(f"# tune-cache provenance unavailable: {e}",
                  file=sys.stderr)

    # ---- multi-chip real-search capture (benchmark/multichip.py):
    # the production equation_search sharded over an island mesh vs the
    # single-device run, at the north-star 64-island config. Replaces
    # the dryrun-only MULTICHIP evidence. multichip_skip_reason mirrors
    # roofline_skip_reason: None exactly when the capture ran on THIS
    # run's (non-CPU) platform; otherwise it names why the on-chip
    # capture is absent ('single-device' — the tunnel exposes one chip;
    # 'tunnel-down' — this run is the CPU fallback; 'shape-indivisible'
    # — the mesh cannot tile the devices), and on the CPU fallback the
    # rows still carry the 8-virtual-device harness capture (subprocess:
    # the device-count force must precede backend init). ----
    multichip_rows = None
    multichip_skip_reason = None
    if os.environ.get("SRTPU_BENCH_MULTICHIP", "1") == "0":
        multichip_skip_reason = "disabled"
        _on_chip = False
    else:
        _here = os.path.dirname(os.path.abspath(__file__))
        _bench_dir = os.path.join(_here, "benchmark")
        if _bench_dir not in sys.path:
            sys.path.insert(0, _bench_dir)
        _mc_latest = os.path.join(_here, "MULTICHIP_LATEST.json")
        _on_chip = platform != "cpu" and len(devices) > 1
    if multichip_skip_reason == "disabled":
        pass
    elif _on_chip:
        try:
            from multichip import NORTHSTAR, run_capture, write_latest

            multichip_rows = run_capture(dict(NORTHSTAR))
            summary = next(
                (r for r in multichip_rows
                 if r.get("case") == "summary"), None
            )
            if summary is None:
                # the capture names its own skip reason (e.g.
                # 'shape-indivisible' when the mesh degraded to one
                # device, 'single-device' when only one exists)
                multichip_skip_reason = next(
                    (r["skipped"] for r in multichip_rows
                     if "skipped" in r), "no-summary"
                )
            else:
                # the ON-CHIP capture is the strongest evidence the repo
                # has — LATEST must carry it, not only the CPU-fallback
                # harness numbers
                write_latest(_mc_latest, multichip_rows, platform)
        except Exception as e:  # pragma: no cover - device-fault path
            multichip_skip_reason = f"error: {type(e).__name__}"
            if verbose:
                print(f"# multichip capture failed: {e}", file=sys.stderr)
    else:
        multichip_skip_reason = (
            "single-device" if platform != "cpu" else "tunnel-down"
        )
        try:
            from multichip import run_subprocess

            # never clobber an on-chip LATEST record with the weaker
            # CPU-harness capture: --out only when the existing file is
            # absent or itself a CPU capture
            _keep = False
            try:
                with open(_mc_latest) as f:
                    _keep = json.load(f).get("platform") not in (
                        None, "cpu",
                    )
            except (OSError, ValueError):
                _keep = False
            multichip_rows, mc_error = run_subprocess(
                extra_args=("--northstar",) if _keep else (
                    "--northstar", "--out", _mc_latest,
                ),
                timeout=900,
            )
            multichip_rows = multichip_rows or None
            if mc_error is not None and verbose:
                print(f"# host multichip capture failed: {mc_error}",
                      file=sys.stderr)
        except Exception as e:  # pragma: no cover - defensive
            if verbose:
                print(f"# host multichip capture failed: {e}",
                      file=sys.stderr)
    # ---- numeric-containment census (ISSUE 15,
    # docs/robustness_numeric.md): score the benchmark workload once on
    # the CPU interpreter and count the trees whose loss the
    # containment layer clamped to the inf sentinel
    # (ops/losses.py::contain_nonfinite) — random GP trees over the
    # Feynman data legitimately overflow/leave domains, and this
    # fraction is the bench-side twin of the search telemetry's
    # population_nonfinite_fraction gauge: a jump between rounds means
    # an operator or containment regression, not a slower kernel. ----
    containment = None
    try:
        from symbolicregression_jl_tpu.models.fitness import (
            eval_loss_trees,
        )

        _nt = min(n_trees, 2048)
        _trees_c = _build_workload(jax, jnp, options, _nt, 1)
        _Xc, _yc = _feynman_data()
        with jax.default_device(jax.devices("cpu")[0]):
            _loss_c = eval_loss_trees(
                _trees_c, jnp.asarray(_Xc), jnp.asarray(_yc), None,
                options.operators, options.elementwise_loss,
                backend="jnp",
            )
            _nonfin = int(jnp.sum(~jnp.isfinite(_loss_c)))
        containment = {
            "trees": int(_nt),
            "nonfinite_trees": _nonfin,
            "nonfinite_frac": round(_nonfin / _nt, 4),
        }
    except Exception as e:  # pragma: no cover - defensive
        if verbose:
            print(f"# containment census unavailable: {e}",
                  file=sys.stderr)

    # ---- serving throughput (ISSUE 16, docs/serving.md): four tiny
    # same-shape jobs through the srserve JobServer at max_tenants=2 —
    # two dispatches of one warm-compiled bucket. jobs/s is the
    # number multi-tenant batching is supposed to move (N jobs on one
    # compile instead of N compiles); warm_hit_rate > 0 is the
    # warm-path evidence. A report, never a gate. ----
    serving_throughput = None
    try:
        from symbolicregression_jl_tpu.serving import JobServer

        _srv = JobServer(
            niterations=1, max_tenants=2, flush_timeout_s=600.0,
            binary_operators=["+", "-", "*"], unary_operators=["cos"],
            npop=24, npopulations=2, ncycles_per_iteration=20,
            maxsize=10, seed=0, verbosity=0, progress=False,
        )
        _rng = np.random.default_rng(0)
        for _i in range(4):
            _Xs = _rng.standard_normal((2, 100)).astype(np.float32)
            _ys = _Xs[0] * _Xs[0] + np.cos(_Xs[1])
            _srv.submit(_Xs, _ys, job_id=f"bench-{_i}", seed=_i)
        _t0 = time.perf_counter()
        _done = _srv.drain()
        _wall = time.perf_counter() - _t0
        _stats = _srv.stats()
        serving_throughput = {
            "jobs": len(_done),
            "jobs_per_s": round(len(_done) / _wall, 3) if _wall else None,
            "max_tenants": 2,
            "dispatches": _stats["dispatches"],
            "warm_hit_rate": round(_stats["warm_hit_rate"], 3),
            "all_complete": all(
                bool(j.result.frontier()) for j in _done
            ),
            "wall_s": round(_wall, 2),
        }
    except Exception as e:  # pragma: no cover - defensive
        if verbose:
            print(f"# serving throughput unavailable: {e}",
                  file=sys.stderr)

    # ---- round-over-round trajectory (scripts/bench_trajectory.py):
    # the checked-in BENCH_r*/MULTICHIP_* series + regression flags ride
    # along in the artifact, so a drop is visible the moment this JSON
    # lands (a report, never a gate — and never allowed to sink the
    # bench). ----
    trajectory = None
    try:
        _scripts = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"
        )
        if _scripts not in sys.path:
            sys.path.insert(0, _scripts)
        from bench_trajectory import bench_summary, build_trajectory

        trajectory = bench_summary(
            build_trajectory(os.path.dirname(os.path.abspath(__file__)))
        )
    except Exception as e:  # pragma: no cover - defensive
        if verbose:
            print(f"# trajectory unavailable: {e}", file=sys.stderr)

    out = {
        "metric": (
            "population fitness-eval throughput, Feynman-I.6.2a "
            f"({min(n_trees, CHUNK)} trees/batch x {N_ROWS} rows, "
            f"maxsize {MAXSIZE}, platform {platform}; baseline = "
            f"{anchor} score throughput{parity})"
        ),
        "value": round(value, 1),
        "unit": "trees-rows/sec/chip",
        "vs_baseline": round(value / cpu_rate, 3),
        "platform": platform,
        "tunnel_state": ACQUISITION["tunnel_state"],
        "attempts": ACQUISITION["attempts"],
        "anchor_cpu_cores": n_cores,
        "anchor_runs": len(anchor_rates),
        "anchor_spread": (
            [round(min(anchor_rates), 1), round(max(anchor_rates), 1)]
            if anchor_rates else None
        ),
        "anchor_xla_cpu": (
            round(xla_cpu_rate, 1) if xla_cpu_rate is not None else None
        ),
        # jnp interpreter with Options.eval_bucket_ladder vs flat, same
        # workload, CPU backend (docs/eval_pipeline.md)
        "interp_bucketed": (
            round(bucketed_rate, 1) if bucketed_rate is not None else None
        ),
        "interp_bucketed_vs_flat": (
            round(bucketed_ratio, 3) if bucketed_ratio is not None else None
        ),
        "first_call_s": round(compile_s, 1),
        # the old roofline_fraction split in two (ISSUE 12): measured =
        # achieved vs the kernel VPU-issue bound (on-chip Pallas runs
        # only; skip_reason says why it is null), modeled = srprof's
        # cost-model fraction vs the device peak table (every platform,
        # never a silent null on this CPU image)
        "roofline_measured": roofline_measured,
        "roofline_modeled": roofline_modeled,
        "roofline_skip_reason": roofline_skip_reason,
        # Pallas kernel with the bucket ladder vs flat, same workload,
        # on-chip only (ISSUE 17; skip reason on CPU rounds)
        "pallas_bucketed": (
            round(pallas_bucketed_rate, 1)
            if pallas_bucketed_rate is not None else None
        ),
        "pallas_bucketed_vs_flat": (
            round(pallas_bucketed_ratio, 3)
            if pallas_bucketed_ratio is not None else None
        ),
        "pallas_bucketed_skip_reason": pallas_bucketed_skip,
        # persistent autotuner provenance (ISSUE 17): cache present/hit
        # and the config the `auto` router resolved for this round
        "kernel_tune_cache": kernel_tune_cache,
        # real-search island-sharding capture (benchmark/multichip.py);
        # the skip reason names why no ON-PLATFORM capture exists
        "multichip": multichip_rows,
        "multichip_skip_reason": multichip_skip_reason,
        # round-over-round series + regression flags (bench_trajectory)
        "trajectory": trajectory,
        # non-finite/clamp census of the scored workload (ISSUE 15):
        # the inf-sentinel fraction the containment layer produced
        "containment": containment,
        # multi-tenant job-server throughput (ISSUE 16): jobs/s through
        # the warm-compiled srserve bucket path
        "serving_throughput": serving_throughput,
        "telemetry_event_log": sink.path if sink is not None else None,
    }
    if platform == "cpu":
        out["last_tpu"] = _last_tpu_block()
    if sink is not None:
        # close the trail properly: consumers (telemetry.analyze, the
        # watcher's --telemetry-dir classifier) treat a log without
        # run_end as still-in-flight/killed — a finished bench must
        # read as completed
        sink.emit(
            "run_end",
            num_evals=float(min(n_trees, CHUNK)),
            search_time_s=time.time() - t_main_start,
        )
        sink.close()
    print(json.dumps(out))


if __name__ == "__main__":
    main(verbose="--quiet" not in sys.argv)
