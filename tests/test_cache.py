"""Evaluation memo bank (cache/ subsystem, ISSUE 1): device/host hash
twins, intra-batch dedup correctness under hash collisions, LRU
eviction/invalidation, the device-memo bypass, and the headline
guarantee — a seeded search with cache_fitness=True produces a
bit-identical hall of fame to the uncached run while reporting a
nonzero cache hit rate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu.cache.dedup as dedup_mod
from symbolicregression_jl_tpu.cache.dedup import (
    DeviceMemo,
    dedup_eval_losses,
    empty_device_memo,
)
from symbolicregression_jl_tpu.cache.hashing import (
    split_key,
    tree_hash_device,
    tree_hash_host,
)
from symbolicregression_jl_tpu.cache.memo import (
    FitnessMemoBank,
    clear_memo_banks,
    dataset_fingerprint,
    get_memo_bank,
)
from symbolicregression_jl_tpu.models.trees import (
    encode_tree,
    parse_expression,
    set_constants,
    stack_trees,
)
from symbolicregression_jl_tpu.ops.interpreter import eval_trees, filler_trees
from symbolicregression_jl_tpu.ops.operators import make_operator_set

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])


def _t(s, max_len=16):
    return encode_tree(parse_expression(s, OPS), max_len)


def _combined(h1, h2):
    return (np.asarray(h1).astype(np.uint64) << np.uint64(32)) | np.asarray(
        h2
    ).astype(np.uint64)


# ---------------------------------------------------------------------------
# hashing: device/host twins + canonicalization
# ---------------------------------------------------------------------------


def test_device_host_hash_twins_agree():
    batch = stack_trees(
        [_t("(x0 + 1.5) * cos(x1)"), _t("x0 - 1.5"), _t("exp(x1) / x0")]
    )
    h1, h2 = jax.jit(tree_hash_device)(batch)
    assert np.array_equal(_combined(h1, h2), tree_hash_host(batch))


def test_hash_ignores_padding_and_dead_fields():
    a = _t("x0 + 1.0", max_len=8)
    b = _t("x0 + 1.0", max_len=8)
    b = b._replace(
        kind=b.kind.at[5:].set(4),
        op=b.op.at[0].set(3),  # x0 is VAR: op slot is dead
        cval=b.cval.at[5:].set(99.0),
    )
    assert tree_hash_host(a) == tree_hash_host(b)
    ha = tree_hash_device(a)
    hb = tree_hash_device(b)
    assert _combined(*ha) == _combined(*hb)


def test_hash_distinguishes_constants():
    # constant bits feed the key: constant mutation/re-optimization makes
    # a NEW key (the memo bank's natural invalidation rule)
    assert tree_hash_host(_t("x0 + 1.5")) != tree_hash_host(_t("x0 + 1.6"))


def test_split_key_roundtrip():
    keys = tree_hash_host(stack_trees([_t("x0 + 1.5"), _t("cos(x1)")]))
    h1, h2 = split_key(keys)
    assert np.array_equal(_combined(h1, h2), keys)


# ---------------------------------------------------------------------------
# intra-batch dedup
# ---------------------------------------------------------------------------


def _batch_with_dups():
    return stack_trees(
        [
            _t("x0 + 1.5"),
            _t("cos(x1)"),
            _t("x0 + 1.5"),
            _t("x0 * x1"),
            _t("cos(x1)"),
            _t("x0 + 1.5"),
        ]
    )


def _eval_fn(X):
    def f(tb):
        y, ok = eval_trees(tb, X, OPS)
        loss = jnp.mean(y**2, axis=-1)
        return jnp.where(ok & jnp.isfinite(loss), loss, jnp.inf)

    return f


def test_dedup_bit_identical_and_counts(rng):
    X = jnp.asarray(rng.standard_normal((2, 40)).astype(np.float32))
    batch = _batch_with_dups()
    direct = _eval_fn(X)(batch)
    loss, stats = jax.jit(
        lambda b: dedup_eval_losses(b, _eval_fn(X))
    )(batch)
    assert np.array_equal(np.asarray(direct), np.asarray(loss))
    assert (int(stats.total), int(stats.unique), int(stats.memo_hits)) == (
        6, 3, 0,
    )


def test_dedup_correct_under_total_hash_collision(rng, monkeypatch):
    """The hash is only the sort key: a degenerate constant hash makes
    EVERY pair collide, so distinct programs sort adjacent and duplicate
    programs scatter apart. Exact content comparison must then (a) never
    merge the adjacent distinct programs and (b) at worst miss dedup on
    the scattered duplicates — a collision costs missed savings, never a
    wrong loss."""
    X = jnp.asarray(rng.standard_normal((2, 40)).astype(np.float32))
    batch = _batch_with_dups()
    direct = _eval_fn(X)(batch)

    def degenerate(trees):
        n = trees.length.shape
        return jnp.zeros(n, jnp.uint32), jnp.zeros(n, jnp.uint32)

    monkeypatch.setattr(dedup_mod, "tree_hash_device", degenerate)
    loss, stats = dedup_eval_losses(batch, _eval_fn(X))
    assert np.array_equal(np.asarray(direct), np.asarray(loss))
    # the sort is length-major with the hash as tie-break (_lex_order),
    # so even a fully colliding hash still groups by program length and
    # the stable tie-break keeps original order within a length. Here:
    # the two length-2 cos(x1) copies become adjacent and merge; in the
    # length-3 run (add@0, add@2, mul@3, add@5 in original order) the
    # mul splits off the last add -> segments {add,add},{mul},{add}.
    # 4 segments: some dedup missed (degraded), every loss exact, and
    # distinct programs never merged — the collision-safety contract.
    assert int(stats.unique) == 4
    assert int(stats.total) == 6
    assert int(stats.memo_hits) == 0
    # duplicates that happen to sit adjacent still merge under the
    # colliding hash (the stable sort preserves their adjacency)
    adj = stack_trees([_t("x0 + 1.5"), _t("x0 + 1.5"), _t("cos(x1)")])
    loss2, stats2 = dedup_eval_losses(adj, _eval_fn(X))
    assert np.array_equal(
        np.asarray(_eval_fn(X)(adj)), np.asarray(loss2)
    )
    assert int(stats2.unique) == 2


def test_dedup_memo_hits_bypass_evaluation(rng):
    """A memo entry is SERVED, not recomputed: plant a poisoned loss for
    one program and see it propagate to every duplicate."""
    X = jnp.asarray(rng.standard_normal((2, 40)).astype(np.float32))
    batch = _batch_with_dups()
    direct = np.asarray(_eval_fn(X)(batch))
    keys = tree_hash_host(batch)
    bank = FitnessMemoBank(capacity=8)
    bank.absorb(keys[0], 123.0)
    memo = bank.device_snapshot(4, np.float32)
    loss, stats = jax.jit(
        lambda b, m: dedup_eval_losses(b, _eval_fn(X), m)
    )(batch, memo)
    loss = np.asarray(loss)
    assert (loss[[0, 2, 5]] == 123.0).all()  # all dups of the planted tree
    assert np.array_equal(loss[[1, 3, 4]], direct[[1, 3, 4]])
    assert int(stats.memo_hits) == 1  # counted once per unique program


def test_dedup_empty_memo_table_is_inert(rng):
    X = jnp.asarray(rng.standard_normal((2, 40)).astype(np.float32))
    batch = _batch_with_dups()
    direct = _eval_fn(X)(batch)
    loss, stats = dedup_eval_losses(
        batch, _eval_fn(X), empty_device_memo(0, jnp.float32)
    )
    assert np.array_equal(np.asarray(direct), np.asarray(loss))
    assert int(stats.memo_hits) == 0


def test_filler_trees_are_valid_cheap_programs(rng):
    X = jnp.asarray(rng.standard_normal((2, 8)).astype(np.float32))
    f = filler_trees((3,), 16, jnp.float32)
    y, ok = eval_trees(f, X, OPS)
    assert bool(np.asarray(ok).all())
    assert np.array_equal(np.asarray(y), np.zeros((3, 8), np.float32))


# ---------------------------------------------------------------------------
# host LRU memo bank
# ---------------------------------------------------------------------------


def test_lru_eviction_order():
    bank = FitnessMemoBank(capacity=3)
    bank.absorb([1, 2, 3], [0.1, 0.2, 0.3])
    _, hit = bank.lookup([1])  # refreshes key 1 to most-recent
    assert hit.all()
    bank.absorb([4], [0.4])  # evicts key 2 (oldest), not the refreshed 1
    vals, hits = bank.lookup([1, 2, 3, 4])
    assert hits.tolist() == [True, False, True, True]
    assert bank.stats["evicted"] == 1
    assert len(bank) == 3


def test_absorb_refreshes_and_skips_nan():
    bank = FitnessMemoBank(capacity=2)
    bank.absorb([1], [0.5])
    bank.absorb([1], [0.75])  # refresh, not insert
    assert len(bank) == 1 and bank.stats["inserted"] == 1
    vals, hits = bank.lookup([1])
    assert hits[0] and vals[0] == 0.75
    bank.absorb([2], [np.nan])  # NaN never equals a replayed eval: skip
    assert not bank.lookup([2])[1][0]
    bank.absorb([3], [np.inf])  # inf IS a valid value (known-bad tree)
    vals, hits = bank.lookup([3])
    assert hits[0] and np.isinf(vals[0])


def test_invalidation_on_constant_reoptimization():
    """Keys include constant bits, so rewriting constants in place (the
    BFGS optimize pass's effect) makes a NEW key — the bank can never
    serve a stale pre-optimization loss for the re-optimized tree. The
    explicit invalidate() covers callers that rewrote cval under a key
    they still hold."""
    tree = _t("(x0 * 2.0) + 0.5")
    bank = FitnessMemoBank(capacity=8)
    bank.absorb_trees(tree, np.asarray(0.25))
    # re-optimize the constants in place
    new_cval = jnp.where(tree.kind == 1, tree.cval * 1.5, tree.cval)
    reopt = set_constants(tree, new_cval)
    assert tree_hash_host(reopt) != tree_hash_host(tree)
    assert not bank.lookup(tree_hash_host(reopt))[1][0]  # no stale serve
    # and the old entry can be dropped explicitly
    assert bank.invalidate_trees(tree) == 1
    assert not bank.lookup(tree_hash_host(tree))[1][0]
    assert bank.stats["invalidated"] == 1


def test_device_snapshot_takes_most_recent():
    bank = FitnessMemoBank(capacity=8)
    bank.absorb([10, 11, 12, 13], [1.0, 2.0, 3.0, 4.0])
    snap = bank.device_snapshot(2, np.float32)
    assert int(snap.count) == 2
    keys = _combined(snap.h1[:2], snap.h2[:2])
    assert set(keys.tolist()) == {12, 13}  # the two newest
    assert set(np.asarray(snap.loss[:2]).tolist()) == {3.0, 4.0}


def test_bank_registry_shares_by_fingerprint(rng):
    from symbolicregression_jl_tpu.models.options import make_options

    clear_memo_banks()
    opts = make_options(verbosity=0, progress=False)
    X = rng.standard_normal((2, 10)).astype(np.float32)
    y = X[0] * 2
    fp = dataset_fingerprint(X, y, None, opts)
    assert get_memo_bank(fp) is get_memo_bank(fp)
    fp2 = dataset_fingerprint(X, y + 1, None, opts)
    assert fp2 != fp
    # op codes are indices into the operator set: a different set is a
    # different evaluation context even with identical data bytes
    ob = make_options(binary_operators=["+", "*"], verbosity=0,
                      progress=False)
    assert dataset_fingerprint(X, y, None, ob) != fp
    # two distinct callables must NOT share a context ('<lambda>' is a
    # name, not an identity) — distinct lambdas, distinct fingerprints
    la = make_options(loss=lambda p, t: (p - t) ** 2, verbosity=0,
                      progress=False)
    lb = make_options(loss=lambda p, t: abs(p - t), verbosity=0,
                      progress=False)
    assert dataset_fingerprint(X, y, None, la) != dataset_fingerprint(
        X, y, None, lb
    )
    # eval-path shape is part of the context (ULP-distinct kernels):
    # 'auto' is resolved the way the rescore resolves it — on this CPU
    # test env that is 'jnp', so auto and jnp SHARE a context while a
    # pinned 'pallas' names a different kernel and must not
    oj = make_options(eval_backend="jnp", verbosity=0, progress=False)
    assert dataset_fingerprint(X, y, None, oj) == fp
    op = make_options(eval_backend="pallas", verbosity=0, progress=False)
    assert dataset_fingerprint(X, y, None, op) != fp
    # a raised capacity knob grows an existing bank; a lowered one is
    # ignored (grow-only — never evict a warmer sibling's entries)
    assert get_memo_bank(fp2, capacity=32).capacity == 32
    assert get_memo_bank(fp2, capacity=128).capacity == 128
    assert get_memo_bank(fp2, capacity=64).capacity == 128
    clear_memo_banks()


# ---------------------------------------------------------------------------
# end-to-end: the acceptance guarantee
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_seeded_search_cached_vs_uncached_identical(rng):
    """cache_fitness=True on a seeded search: bit-identical hall of fame,
    nonzero reported cache hit rate, per-iteration unique-ratio rows."""
    from symbolicregression_jl_tpu import equation_search

    X = rng.standard_normal((3, 48)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2
    # ncycles*B (= 10*2 replacements) < npop guarantees members survive
    # verbatim between iterations, so the rescore-serving memo tier gets
    # hits within the 3-iteration budget (the bank serves only the
    # population rescore — see docs/memo_bank.md)
    kw = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npopulations=2,
        npop=33,
        ncycles_per_iteration=10,
        maxsize=10,
        seed=11,
        verbosity=0,
        progress=False,
        niterations=3,
    )
    r0 = equation_search(X, y, **kw)
    clear_memo_banks()
    r1 = equation_search(X, y, cache_fitness=True, **kw)

    def frontier(r):
        return [
            (c.complexity, float(c.loss), float(c.score), c.equation)
            for c in r.frontier()
        ]

    assert frontier(r0) == frontier(r1)
    assert r0.cache_stats is None
    totals = r1.cache_stats["totals"]
    assert totals["scored"] > 0
    assert totals["hit_rate"] > 0.0  # dedup finds duplicates even early
    assert totals["memo_hits"] > 0  # population rescore hits the bank
    rows = r1.cache_stats["per_iteration"]
    assert len(rows) == 3
    for row in rows:
        assert 0 < row["unique"] <= row["scored"]
        assert row["eval_batch_fill"] <= row["unique_ratio"]
    # the bank absorbed this search's populations
    assert r1.cache_stats["banks"][0]["size"] > 0
    clear_memo_banks()


def test_progress_line_and_recorder_surface_cache_counters():
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.utils.progress import SearchProgress
    from symbolicregression_jl_tpu.utils.recorder import Recorder

    opts = make_options(verbosity=0, progress=False, cache_fitness=True)
    progress = SearchProgress(4, opts)
    line = progress.status_line(
        0, 0.5, 100.0, cache_counts=(200, 120, 30)
    )
    # saved = 200 - (120 - 30) = 110 -> 55%; dedup 40%; memo 15%
    assert "Cache: 55% hits" in line
    assert "dedup 40%" in line and "memo 15%" in line
    # zero scored: no cache segment rather than a division error
    assert "Cache" not in progress.status_line(
        0, 0.5, 100.0, cache_counts=(0, 0, 0)
    )

    rec = Recorder(opts)
    rec.record_cache(
        0, 0, {"output": 0, "iteration": 0, "scored": 10, "unique": 8,
               "memo_hits": 2, "evaluated": 6, "unique_ratio": 0.8,
               "memo_hit_rate": 0.2, "eval_batch_fill": 0.6},
    )
    entry = rec.record["out1_cache"]["iteration1"]
    assert entry["scored"] == 10 and "output" not in entry
