"""Global stop semantics + zero-recompile scalar sweeps + float64 story
(round-3 additions, split from test_api.py: the XLA:CPU jaxlib on this
image segfaults once one process accumulates too many compiled programs,
and conftest clears compile caches at MODULE boundaries — keeping this
compile-heavy group in its own module keeps both modules under the
threshold)."""

import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options

from test_api import TINY, make_data



@pytest.mark.slow
def test_global_stop_across_outputs(rng):
    """Global stop semantics (reference src/SymbolicRegression.jl:899-909):
    max_evals/'q'/timeout end the WHOLE multi-output search the moment
    they trip; the loss threshold stops only when EVERY output satisfies
    it (src/SearchUtils.jl:109-141)."""
    X, y0 = make_data(rng)
    y = np.stack([y0, X[1] * 2.0])

    # max_evals trips during output 0's first iteration -> output 1 never
    # runs one; its hall of fame is empty exactly like the reference's
    # (exists-flags only fill when an iteration merges members)
    seen = []
    res = sr.equation_search(
        X, y, niterations=4, max_evals=1,
        on_iteration=lambda j, it, cands: seen.append((j, it)),
        seed=0, **TINY,
    )
    assert seen == [(0, 0)]
    assert len(res.candidates) == 2 and res.frontier(1) == []

    # trivially-satisfied loss threshold: every output must get its
    # iteration before the all-outputs check stops the joint loop
    seen2 = []
    sr.equation_search(
        X, y, niterations=4, early_stop_condition=1e3,
        on_iteration=lambda j, it, cands: seen2.append((j, it)),
        seed=0, **TINY,
    )
    assert seen2 == [(0, 0), (1, 0)]



@pytest.mark.slow
def test_loss_threshold_needs_all_outputs(rng):
    """One satisfied output must NOT stop the search while another output
    is unsatisfied (reference src/SearchUtils.jl:117-128 returns false on
    the first unsatisfied output)."""
    X, _ = make_data(rng)
    # output 0 = x0 exactly (solved to 0.0 loss immediately);
    # output 1 = pure noise (can never reach the threshold)
    y = np.stack([X[0], rng.standard_normal(X.shape[1]).astype(np.float32)])
    seen = []
    sr.equation_search(
        X, y, niterations=2, early_stop_condition=1e-6,
        on_iteration=lambda j, it, cands: seen.append((j, it)),
        seed=0, **TINY,
    )
    # both outputs ran the full budget: the satisfied output 0 keeps
    # iterating until output 1 satisfies or the budget ends
    assert seen == [(0, 0), (1, 0), (0, 1), (1, 1)]



@pytest.mark.slow
def test_scalar_knob_sweep_reuses_compilation(rng):
    """TRACED_SCALAR_FIELDS knobs (parsimony/alpha/migration fractions...)
    enter the jitted iteration as traced arguments: Options differing only
    in them share one compiled graph (the reference pays compilation per
    method, not per config — src/precompile.jl:34-79) while the values
    still flow in per call."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.api import (
        _make_init_fn,
        _make_iteration_fn,
    )

    base = dict(
        binary_operators=("+", "-", "*"), unary_operators=("cos",),
        npop=16, npopulations=2, ncycles_per_iteration=10, maxsize=10,
        should_optimize_constants=False,
    )
    o1 = make_options(parsimony=0.0, **base)
    o2 = make_options(
        parsimony=5.0, alpha=3.0, fraction_replaced=0.5, **base
    )
    assert o1 == o2 and hash(o1) == hash(o2)
    f = _make_iteration_fn(o1, False)
    assert _make_iteration_fn(o2, False) is f  # lru dedup by graph key

    X = jnp.asarray((rng.standard_normal((3, 64)) * 2).astype(np.float32))
    y = 2.0 * jnp.cos(X[2]) + X[0] ** 2
    bl = jnp.float32(float(jnp.var(y)))
    init = _make_init_fn(o1, 3, False)
    s0 = init(
        jax.random.split(jax.random.PRNGKey(0), 2), X, y, bl,
        o1.traced_scalars(),
    )
    cm = jnp.int32(o1.maxsize)
    sA, _ = f(s0, jax.random.PRNGKey(1), cm, X, y, bl, o1.traced_scalars())
    n_traces = f._cache_size()
    sB, _ = f(s0, jax.random.PRNGKey(1), cm, X, y, bl, o2.traced_scalars())
    assert f._cache_size() == n_traces, "scalar-only change retraced"
    # the swept values actually reach the computation
    a = np.asarray(sA.pop.scores)
    b = np.asarray(sB.pop.scores)
    m = np.isfinite(a) & np.isfinite(b)
    assert not np.allclose(a[m], b[m])



def test_float64_interpreter_warning():
    """precision='float64' warns up front about the interpreter routing
    (the Pallas kernel is f32/bf16-only; VERDICT r2 missing-1)."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        make_options(precision="float64")
    assert any("float64" in str(x.message) for x in w)
    # the explicit kernel request fails at construction, not mid-search
    import pytest

    with pytest.raises(ValueError, match="float32/bfloat16"):
        make_options(precision="float64", eval_backend="pallas")
    # explicit jnp backend means the user already chose the interpreter
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        make_options(precision="float64", eval_backend="jnp")
    assert not any("float64" in str(x.message) for x in w2)
