"""Tests for the C++ host runtime (native/srtpu_native.cpp via native.py).

Each native entry point is checked against its pure-Python/JAX counterpart:
printer vs models.trees.tree_to_string, parser vs parse_expression,
simplifier vs eval-equivalence (and vs the device simplifier's shrinkage),
evaluator vs ops.interpreter.eval_trees, CSV loader vs numpy parsing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import native
from symbolicregression_jl_tpu.models.mutate_device import (
    gen_random_tree_fixed_size,
)
from symbolicregression_jl_tpu.models.trees import (
    TreeBatch,
    encode_tree,
    parse_expression,
    tree_to_string,
)
from symbolicregression_jl_tpu.ops.interpreter import eval_trees
from symbolicregression_jl_tpu.ops.operators import make_operator_set

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native library not built"
)

OPS = make_operator_set(
    binary_operators=["+", "-", "*", "/", "^"],
    unary_operators=["cos", "exp", "log", "sqrt", "neg"],
)
MAX_LEN = 32


def random_trees(n, nfeat=3, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    sizes = jax.random.randint(jax.random.PRNGKey(seed + 1), (n,), 1, 16)
    return jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, nfeat, OPS, MAX_LEN)
    )(keys, sizes)


def to_np(trees):
    return tuple(np.asarray(x) for x in trees)


class TestPrinter:
    def test_matches_python_printer(self):
        trees = random_trees(100)
        kind, op, feat, cval, length = to_np(trees)
        got = native.trees_to_strings(kind, op, feat, cval, length, OPS)
        assert got is not None
        for t in range(100):
            want = tree_to_string(trees[t], OPS)
            assert got[t] == want

    def test_variable_names(self):
        trees = random_trees(10, nfeat=2, seed=3)
        kind, op, feat, cval, length = to_np(trees)
        names = ("alpha", "beta")
        got = native.trees_to_strings(
            kind, op, feat, cval, length, OPS, names
        )
        for t in range(10):
            assert got[t] == tree_to_string(trees[t], OPS, names)

    def test_large_batch_buffer_growth(self):
        trees = random_trees(2000, seed=7)
        kind, op, feat, cval, length = to_np(trees)
        got = native.trees_to_strings(kind, op, feat, cval, length, OPS)
        assert len(got) == 2000
        assert all(isinstance(s, str) and s for s in got)


class TestParser:
    @pytest.mark.parametrize(
        "s",
        [
            "x0 + x1",
            "(x0 + 1.5) * cos(x2)",
            "x0 - x1 - x2",
            "x0 / x1 / x2",
            "2 ^ x0 ^ 2",
            "-x0 + exp(-2.5)",
            "sqrt(log(x1 + 3))",
            "1e-3 * x0",
            "neg(x2) * (x0 - 0.5)",
        ],
    )
    def test_roundtrip_matches_python_parser(self, s):
        ref = encode_tree(parse_expression(s, OPS), MAX_LEN)
        got = native.parse_to_arrays(s, OPS, MAX_LEN)
        assert got is not None
        kind, op, feat, cval, length = got
        assert int(length) == int(ref.length)
        np.testing.assert_array_equal(kind, np.asarray(ref.kind))
        np.testing.assert_array_equal(op, np.asarray(ref.op))
        np.testing.assert_array_equal(feat, np.asarray(ref.feat))
        np.testing.assert_allclose(cval, np.asarray(ref.cval), rtol=1e-6)

    def test_parse_error(self):
        with pytest.raises(ValueError):
            native.parse_to_arrays("x0 + unknown_fn(x1)", OPS, MAX_LEN)
        with pytest.raises(ValueError):
            native.parse_to_arrays("x0 + ", OPS, MAX_LEN)

    def test_print_parse_roundtrip(self):
        trees = random_trees(50, seed=11)
        kind, op, feat, cval, length = to_np(trees)
        strings = native.trees_to_strings(kind, op, feat, cval, length, OPS)
        X = np.random.default_rng(0).normal(size=(3, 16)).astype(np.float32)
        y0, ok0 = eval_trees(trees, jnp.asarray(X), OPS)
        for t in range(50):
            k2, o2, f2, c2, n2 = native.parse_to_arrays(
                strings[t], OPS, MAX_LEN
            )
            tb = TreeBatch(
                kind=jnp.asarray(k2), op=jnp.asarray(o2),
                feat=jnp.asarray(f2), cval=jnp.asarray(c2),
                length=jnp.asarray(n2),
            )
            y1, _ = eval_trees(tb, jnp.asarray(X), OPS)
            if bool(ok0[t]):
                np.testing.assert_allclose(
                    np.asarray(y1), np.asarray(y0[t]), rtol=1e-3, atol=1e-4
                )


class TestEval:
    def test_matches_interpreter(self):
        trees = random_trees(200, seed=5)
        X = np.random.default_rng(1).normal(size=(3, 100)).astype(np.float32)
        y_ref, ok_ref = eval_trees(trees, jnp.asarray(X), OPS)
        kind, op, feat, cval, length = to_np(trees)
        out = native.eval_batch(kind, op, feat, cval, length, X, OPS)
        assert out is not None
        y, ok = out
        y_ref = np.asarray(y_ref)
        ok_ref = np.asarray(ok_ref)
        np.testing.assert_array_equal(ok, ok_ref)
        # native evaluates in double then casts to f32; the interpreter is
        # f32 throughout, so deep trees accumulate ~1e-4 relative drift
        mask = ok_ref
        np.testing.assert_allclose(
            y[mask], y_ref[mask], rtol=1e-3, atol=1e-4
        )

    def test_nan_propagation(self):
        # log of a negative constant poisons the tree -> ok=False
        expr = parse_expression("log(0 - 2) + x0", OPS)
        t = encode_tree(expr, MAX_LEN)
        kind, op, feat, cval, length = to_np(t)
        X = np.ones((1, 8), np.float32)
        y, ok = native.eval_batch(
            kind[None], op[None], feat[None], cval[None],
            np.asarray([length]), X, OPS,
        )
        assert not ok[0]
        assert np.isnan(y[0]).all()

    def test_multithreaded_matches_single(self):
        trees = random_trees(64, seed=9)
        X = np.random.default_rng(2).normal(size=(3, 64)).astype(np.float32)
        kind, op, feat, cval, length = to_np(trees)
        y1, ok1 = native.eval_batch(
            kind, op, feat, cval, length, X, OPS, n_threads=1
        )
        y8, ok8 = native.eval_batch(
            kind, op, feat, cval, length, X, OPS, n_threads=8
        )
        np.testing.assert_array_equal(ok1, ok8)
        np.testing.assert_array_equal(y1, y8)


class TestSimplify:
    def _simplify_one(self, s, fold=True, combine=True):
        t = encode_tree(parse_expression(s, OPS), MAX_LEN)
        kind, op, feat, cval, length = to_np(t)
        out = native.simplify_arrays(
            kind[None], op[None], feat[None], cval[None],
            np.asarray([length]), OPS, fold=fold, combine=combine,
        )
        assert out is not None
        k, o, f, c, n, changed = out
        tb = TreeBatch(
            kind=jnp.asarray(k[0]), op=jnp.asarray(o[0]),
            feat=jnp.asarray(f[0]), cval=jnp.asarray(c[0]),
            length=jnp.asarray(n[0]),
        )
        return tb, changed

    def test_constant_folding(self):
        tb, changed = self._simplify_one("(1 + 2) * x0")
        assert changed == 1
        assert int(tb.length) == 3  # [3, x0, *]
        assert tree_to_string(tb, OPS) == "(3 * x0)" or tree_to_string(
            tb, OPS
        ) == "(x0 * 3)"

    def test_combine_chain(self):
        # (x0 + 1) + 2 -> x0 + 3
        tb, changed = self._simplify_one("(x0 + 1) + 2")
        assert changed == 1
        assert int(tb.length) == 3
        assert "3" in tree_to_string(tb, OPS)

    def test_eval_equivalence_random(self):
        trees = random_trees(150, seed=21)
        X = np.random.default_rng(3).uniform(0.5, 2.0, (3, 50)).astype(
            np.float32
        )
        y_ref, ok_ref = eval_trees(trees, jnp.asarray(X), OPS)
        kind, op, feat, cval, length = to_np(trees)
        out = native.simplify_arrays(
            kind, op, feat, cval, length, OPS
        )
        k, o, f, c, n, _ = out
        tb = TreeBatch(
            kind=jnp.asarray(k), op=jnp.asarray(o), feat=jnp.asarray(f),
            cval=jnp.asarray(c), length=jnp.asarray(n),
        )
        y2, ok2 = eval_trees(tb, jnp.asarray(X), OPS)
        # simplified trees never grow
        assert np.all(np.asarray(n) <= np.asarray(length))
        both = np.asarray(ok_ref) & np.asarray(ok2)
        np.testing.assert_allclose(
            np.asarray(y2)[both], np.asarray(y_ref)[both],
            rtol=1e-3, atol=1e-4,
        )

    def test_nan_not_folded(self):
        # log(-2) must NOT be folded into a NaN constant
        tb, changed = self._simplify_one("log(0 - 2) + x0")
        s = tree_to_string(tb, OPS)
        assert "log" in s


class TestCSV:
    def test_roundtrip(self, tmp_path):
        rng = np.random.default_rng(0)
        data = rng.normal(size=(50, 4))
        path = tmp_path / "d.csv"
        header = "a,b,c,target"
        np.savetxt(path, data, delimiter=",", header=header, comments="")
        out = native.load_csv(str(path))
        assert out is not None
        got, names = out
        assert names == ["a", "b", "c", "target"]
        np.testing.assert_allclose(got, data, rtol=1e-6)

    def test_no_header_tab(self, tmp_path):
        data = np.arange(12.0).reshape(4, 3)
        path = tmp_path / "d.tsv"
        np.savetxt(path, data, delimiter="\t")
        got, names = native.load_csv(str(path))
        assert names is None
        np.testing.assert_allclose(got, data)

    def test_missing_file(self):
        with pytest.raises(OSError):
            native.load_csv("/nonexistent/file.csv")


class TestOpMaps:
    def test_known_ops_mapped(self):
        maps = native.op_maps(OPS)
        assert maps is not None
        una, bina = maps
        assert (una >= 0).all() and (bina >= 0).all()

    def test_custom_op_rejected(self):
        from symbolicregression_jl_tpu.ops.operators import (
            OperatorSet,
            register_unary,
        )

        register_unary("my_custom_native_test", lambda x: x + 1)
        ops = OperatorSet(
            unary_names=("my_custom_native_test",), binary_names=("+",)
        )
        assert native.op_maps(ops) is None


class TestLoadCsvDataset:
    def test_load_with_target_name(self, tmp_path):
        import symbolicregression_jl_tpu as sr

        rng = np.random.default_rng(1)
        X = rng.normal(size=(30, 2))
        y = X[:, 0] * 2 + 1
        path = tmp_path / "ds.csv"
        np.savetxt(
            path, np.column_stack([X, y]), delimiter=",",
            header="a,b,target", comments="",
        )
        ds = sr.load_csv_dataset(str(path), target="target")
        assert ds.X.shape == (2, 30)
        assert ds.variable_names == ("a", "b")
        np.testing.assert_allclose(np.asarray(ds.y), y, rtol=1e-5)

    def test_default_last_column_and_weights(self, tmp_path):
        import symbolicregression_jl_tpu as sr

        data = np.arange(24.0).reshape(6, 4)
        path = tmp_path / "ds2.csv"
        np.savetxt(path, data, delimiter=",")
        ds = sr.load_csv_dataset(str(path), weights_column=2)
        assert ds.X.shape == (2, 6)
        np.testing.assert_allclose(np.asarray(ds.weights), data[:, 2])
        np.testing.assert_allclose(np.asarray(ds.y), data[:, 3])


def test_parse_rejects_malformed_number():
    with pytest.raises(ValueError, match="number"):
        native.parse_to_arrays("1.2.3 * x0", OPS, MAX_LEN)
