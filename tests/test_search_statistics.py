"""Adaptive-parsimony window algebra
(analog of reference test/test_search_statistics.jl:10-41)."""

import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.parsimony import (
    init_search_statistics,
    move_window,
    normalize_frequencies,
    update_frequencies,
)


def test_init_all_ones():
    stats = init_search_statistics(10)
    np.testing.assert_allclose(np.asarray(stats.frequencies), np.ones(10))


def test_update_scatter_adds():
    stats = init_search_statistics(5)
    stats = update_frequencies(stats, jnp.asarray([1, 1, 3, 5]))
    np.testing.assert_allclose(
        np.asarray(stats.frequencies), [3.0, 1.0, 2.0, 1.0, 2.0]
    )


def test_update_drops_out_of_range():
    stats = init_search_statistics(3)
    stats = update_frequencies(stats, jnp.asarray([0, 4, -2, 2]))
    np.testing.assert_allclose(np.asarray(stats.frequencies), [1.0, 2.0, 1.0])


def test_move_window_preserves_total_at_cap():
    stats = init_search_statistics(4)
    stats = stats._replace(window_size=8.0)
    for _ in range(5):
        stats = update_frequencies(stats, jnp.asarray([2, 2, 2, 2]))
    stats = move_window(stats)
    assert float(jnp.sum(stats.frequencies)) == np.float32(8.0)
    # bin 2 must remain the most frequent after the shave
    f = np.asarray(stats.frequencies)
    assert f[1] == f.max()


def test_move_window_noop_below_cap():
    stats = init_search_statistics(4)  # total 4 << window
    before = np.asarray(stats.frequencies).copy()
    after = np.asarray(move_window(stats).frequencies)
    np.testing.assert_allclose(before, after)


def test_normalized_sums_to_one():
    stats = init_search_statistics(6)
    stats = update_frequencies(stats, jnp.asarray([1, 2, 3]))
    assert float(jnp.sum(normalize_frequencies(stats))) == np.float32(1.0)
