"""Static-analysis subsystem (ISSUEs 3+4): srlint rule detection on
known-bad fixtures, pragma suppression, reporter schema, compile-surface
contracts, the srmem HBM-footprint gate, and both baseline drift gates.

The srlint fixtures under tests/data/srlint_fixtures/ are parsed, never
imported; each file documents inline which lines must (and must NOT) be
flagged. Everything here is CPU-only AST/tracing work — no TPU, and the
only jax executions are eval_shape/make_jaxpr traces."""

import json
import os
import subprocess
import sys

import pytest

from symbolicregression_jl_tpu.analysis import (
    RULES,
    AnalysisReport,
    lint_package,
    lint_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "srlint_fixtures")


def _lint_fixture(name):
    return lint_paths(
        FIXTURES, files=[os.path.join(FIXTURES, name)], repo_root=REPO
    )


def _active(violations, rule=None):
    return [
        v for v in violations
        if not v.suppressed and (rule is None or v.rule_id == rule)
    ]


# ---------------------------------------------------------------------------
# srlint: one fixture per rule
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_sr001_host_sync_detected():
    vs = _lint_fixture("fixture_sr001.py")
    hits = _active(vs, "SR001")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    # reachable through the call graph, not just the jitted def itself
    assert any(v.function == "_inner" for v in hits)
    # host-only helper with identical calls stays clean
    assert not any(v.function == "host_only" for v in hits)


@pytest.mark.fast
def test_sr002_tracer_control_flow_detected():
    vs = _lint_fixture("fixture_sr002.py")
    hits = _active(vs, "SR002")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    assert all(v.function == "branchy" for v in hits)
    # static bool / identity / shape-math branches in fine() not flagged
    assert not _active(vs, "SR001")


@pytest.mark.fast
def test_sr003_unsorted_dict_iteration_detected():
    vs = _lint_fixture("fixture_sr003.py")
    hits = _active(vs, "SR003")
    assert len(hits) == 2, [v.to_dict() for v in vs]
    assert all(v.function == "build" for v in hits)


@pytest.mark.fast
def test_sr004_implicit_dtype_detected():
    vs = _lint_fixture("fixture_sr004.py")
    hits = _active(vs, "SR004")
    # zeros/ones/full/arange without dtype; positional+kwarg dtype and
    # zeros_like stay clean
    assert len(hits) == 4, [v.to_dict() for v in vs]


@pytest.mark.fast
def test_sr005_stale_static_argnames_detected():
    vs = _lint_fixture("fixture_sr005.py")
    hits = _active(vs, "SR005")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    messages = " ".join(v.message for v in hits)
    for stale in ("block_sz", "tile", "modes"):
        assert stale in messages
    # the valid wrapper, the decorator form and **kwargs are not flagged
    assert not any("block_size'" in v.message for v in hits)


@pytest.mark.fast
def test_sr006_missing_donation_detected():
    vs = _lint_fixture("fixture_sr006.py")
    hits = _active(vs, "SR006")
    # plain wrap, bare decorator, aliased return
    assert len(hits) == 3, [v.to_dict() for v in vs]
    functions = {v.function for v in hits}
    assert functions == {"step", "dec_step", "aliased"}
    # donating wrappers, the pure function, and the static param stay clean
    messages = " ".join(v.message for v in hits)
    assert "dec_donated" not in messages
    assert "'block'" not in messages


@pytest.mark.fast
def test_sr007_broadcast_materialization_detected():
    vs = _lint_fixture("fixture_sr007.py")
    hits = _active(vs, "SR007")
    # broadcast_to, outer, tile with literal factor >= 8
    assert len(hits) == 3, [v.to_dict() for v in vs]
    assert all(v.function == "hot" for v in hits)
    # identical call outside the jit call graph stays clean
    assert not any(v.function == "host_only" for v in hits)


@pytest.mark.fast
def test_sr008_host_roundtrip_detected():
    vs = _lint_fixture("fixture_sr008.py")
    hits = _active(vs, "SR008")
    # tainted-name feed-back + inline round-trip, both in drive()
    assert len(hits) == 2, [v.to_dict() for v in vs]
    assert all(v.function == "drive" for v in hits)
    assert not any(v.function == "fine" for v in vs)
    # reassignment from a non-sync value kills the taint
    assert not any(v.function == "retainted" for v in vs)


@pytest.mark.fast
def test_sr009_where_after_nan_producing_op_detected():
    vs = _lint_fixture("fixture_sr009.py")
    hits = _active(vs, "SR009")
    # log branch, sqrt branch, unclamped division, fractional power
    assert len(hits) == 4, [v.to_dict() for v in vs]
    functions = {v.function for v in hits}
    assert functions == {
        "bad_log_branch", "bad_sqrt_branch", "bad_division_branch",
        "bad_fractional_power",
    }
    # clamped inputs (the safe_* pattern), integer powers, plain selects
    # and host-only code stay clean; the pragma suppresses
    assert not any(
        v.function and v.function.startswith(("good_", "host_only"))
        for v in hits
    )
    sup = [v for v in vs if v.suppressed and v.rule_id == "SR009"]
    assert len(sup) == 1 and sup[0].function == "pragma_suppressed"


@pytest.mark.fast
def test_clean_fixture_produces_zero_findings():
    vs = _lint_fixture("fixture_clean.py")
    assert vs == [], [v.to_dict() for v in vs]


# ---------------------------------------------------------------------------
# pragmas + reporters
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_pragma_suppression():
    vs = _lint_fixture("fixture_pragmas.py")
    active = _active(vs)
    suppressed = [v for v in vs if v.suppressed]
    # the mismatched-rule pragma does NOT suppress
    assert len(active) == 1 and active[0].rule_id == "SR001"
    # single-rule, multi-rule, and justified pragmas all suppress
    assert len(suppressed) == 3
    assert {v.rule_id for v in suppressed} == {"SR001", "SR004"}


@pytest.mark.fast
def test_json_report_schema():
    vs = _lint_fixture("fixture_pragmas.py")
    report = AnalysisReport(violations=vs)
    payload = json.loads(report.to_json())
    assert payload["schema_version"] == 1
    assert payload["tool"] == "srlint"
    assert payload["ok"] is False
    assert payload["counts"] == {"SR001": 1}
    assert payload["suppressed"] == 3
    assert payload["surface"] is None
    assert payload["memory"] is None
    for v in payload["violations"]:
        assert set(v) == {
            "rule", "name", "path", "line", "col", "function", "message",
            "suppressed",
        }
        assert v["rule"] in RULES
    # text renderer shows only active findings plus the summary line
    text = report.to_text()
    assert text.count("SR001") >= 1
    assert "suppressed by pragma" in text


@pytest.mark.fast
def test_rule_catalog_documented():
    for rule in RULES.values():
        assert rule.summary and rule.rationale
    # docs cross-check: every rule id appears in the rule catalog doc
    doc = open(os.path.join(REPO, "docs", "static_analysis.md")).read()
    for rid in RULES:
        assert rid in doc, f"{rid} missing from docs/static_analysis.md"


# ---------------------------------------------------------------------------
# the repo itself must be clean (the lint lands green — ISSUE 3 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_package_tree_is_srlint_clean():
    vs = lint_package(repo_root=REPO)
    active = _active(vs)
    assert active == [], "\n".join(
        f"{v.path}:{v.line} {v.rule_id} {v.message}" for v in active
    )


# ---------------------------------------------------------------------------
# compile surface
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_compile_surface_single_config(tmp_path):
    """One small config end-to-end under JAX_PLATFORMS=cpu (conftest):
    aval stability, IslandState contract, no callbacks/f64, census
    written and re-read as a baseline. Slow: ~6s of tracing (tier-1
    timing hygiene, ISSUE 4)."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        check_surface,
    )

    path = str(tmp_path / "baseline.json")
    r = check_surface(
        update_baseline=True, baseline_path=path,
        configs=(("base", {}),), include_chunked=False,
    )
    assert r["problems"] == []
    entry = r["configs"]["base"]
    assert entry["stable_avals"]
    assert entry["total_primitives"] > 100
    assert not any("callback" in p for p in entry["primitives"])
    # second run diffs clean against the just-written baseline
    r2 = check_surface(
        baseline_path=path, configs=(("base", {}),), include_chunked=False,
    )
    assert r2["ok"], r2["problems"]
    assert r2["baseline_checked"] and r2["baseline_match"]


@pytest.mark.fast
def test_baseline_diff_catches_injected_primitive(tmp_path):
    """Acceptance: an extra primitive in the census fails the diff."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        diff_baseline,
    )

    baseline = {
        "configs": {
            "base": {"primitives": {"add": 10, "mul": 5}},
        }
    }
    clean = {"base": {"primitives": {"add": 10, "mul": 5}}}
    assert diff_baseline(clean, baseline) == []
    injected = {"base": {"primitives": {"add": 10, "mul": 5,
                                        "pure_callback": 1}}}
    probs = diff_baseline(injected, baseline)
    assert len(probs) == 1 and "pure_callback" in probs[0]
    grown = {"base": {"primitives": {"add": 11, "mul": 5}}}
    probs = diff_baseline(grown, baseline)
    assert len(probs) == 1 and "baseline 10 -> now 11" in probs[0]
    missing = {"other": {"primitives": {}}}
    probs = diff_baseline(missing, baseline)
    assert len(probs) == 2  # unknown config + config no longer produced


@pytest.mark.fast
def test_baseline_diff_collective_census_and_skip():
    """The sharded config's collective census diffs like the primitive
    counts, and a skipped config (single-device host) is exempt in both
    directions instead of reading as missing/unknown."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        diff_baseline,
    )

    baseline = {
        "configs": {
            "sharded": {
                "primitives": {"add": 3},
                "collectives": {"all-gather": 16, "all-reduce": 14},
            },
        }
    }
    clean = {"sharded": {"primitives": {"add": 3},
                         "collectives": {"all-gather": 16,
                                         "all-reduce": 14}}}
    assert diff_baseline(clean, baseline) == []
    drifted = {"sharded": {"primitives": {"add": 3},
                           "collectives": {"all-gather": 17,
                                           "all-reduce": 14}}}
    probs = diff_baseline(drifted, baseline)
    assert len(probs) == 1 and "all-gather" in probs[0]
    vanished = {"sharded": {"primitives": {"add": 3}, "collectives": {}}}
    probs = diff_baseline(vanished, baseline)
    assert len(probs) == 2  # both collective counts dropped to 0
    skipped = {"sharded": {"skipped": "1 device(s)"}}
    assert diff_baseline(skipped, baseline) == []


@pytest.mark.fast
def test_collective_census_counts_hlo_ops():
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        collective_census,
    )

    hlo = (
        "%ag = f32[8,4]{1,0} all-gather(f32[1,4]{1,0} %p), dims={0}\n"
        "%ar = f32[] all-reduce(f32[] %x), to_apply=%sum\n"
        "%ag2.s = f32[8]{0} all-gather-start(f32[1]{0} %q)\n"
        "%ag2.d = f32[8]{0} all-gather-done(f32[8]{0} %ag2.s)\n"
    )
    assert collective_census(hlo) == {"all-gather": 2, "all-reduce": 1}


@pytest.mark.fast
def test_checked_in_baseline_exists_and_well_formed():
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        BASELINE_PATH,
    )

    with open(BASELINE_PATH) as f:
        payload = json.load(f)
    assert payload["schema_version"] == 1
    assert set(payload["configs"]) == {
        "base", "cache", "islands4", "pop32", "bucketed", "rowsharded",
        "chunked", "sharded", "tenants2",
    }
    for entry in payload["configs"].values():
        assert entry["total_primitives"] == sum(
            entry["primitives"].values()
        )
        assert not any("callback" in p for p in entry["primitives"])
    # the sharded config additionally pins the collective census — the
    # cross-device traffic shape of the partitioned iteration
    sharded = payload["configs"]["sharded"]
    assert sharded["n_devices"] >= 2
    assert sharded["collectives"] and all(
        n > 0 for n in sharded["collectives"].values()
    )


# ---------------------------------------------------------------------------
# srmem (memory engine)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_live_buffer_peak_models_liveness_and_blowups():
    """The estimator sees a materialized broadcast as both peak bytes and
    an SR007-signature blowup; a pointwise chain of the same shapes does
    not blow up."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.memory import live_buffer_peak

    def blowy(x):  # (1024,) f32 -> (512, 1024) f32: 2MB from 4KB
        big = jnp.broadcast_to(x, (512, 1024)) * 2.0
        return big.sum()

    est = live_buffer_peak(
        jax.make_jaxpr(blowy)(jnp.zeros((1024,), jnp.float32))
    )
    assert est["peak_bytes"] >= 512 * 1024 * 4
    assert est["args_bytes"] == 1024 * 4
    assert est["blowups"], est
    assert est["blowups"][0]["factor"] >= 8

    def pointwise(x):
        return ((x * 2.0) + 1.0).sum()

    est2 = live_buffer_peak(
        jax.make_jaxpr(pointwise)(jnp.zeros((1024,), jnp.float32))
    )
    assert est2["blowups"] == []
    assert est2["peak_bytes"] < est["peak_bytes"]


@pytest.mark.slow
def test_memory_single_config_baseline_roundtrip(tmp_path):
    """One config end-to-end: stages attributed, baseline written, and a
    second run diffs clean against it (the srmem analog of the
    compile-surface round-trip above). Slow: two full single-config
    analyses, ~14s of tracing."""
    from symbolicregression_jl_tpu.analysis.memory import check_memory

    path = str(tmp_path / "memory_baseline.json")
    r = check_memory(
        update_baseline=True, baseline_path=path, configs=(("base", {}),),
    )
    entry = r["configs"]["base"]
    assert entry["peak_modeled_bytes"] > 0
    assert set(entry["stages"]) == {
        "init", "cycle", "mutate", "eval", "simplify", "optimize",
        "merge_migrate",
    }
    assert entry["footprint_bytes"] == (
        entry["args_bytes"] + entry["peak_modeled_bytes"]
    )
    r2 = check_memory(baseline_path=path, configs=(("base", {}),))
    assert r2["ok"], r2["problems"]
    assert r2["baseline_checked"] and r2["baseline_match"]


@pytest.mark.fast
def test_memory_diff_catches_injected_regression():
    """Acceptance: a >10% modeled-peak growth fails, a shrink only notes,
    and config-set drift fails in both directions."""
    from symbolicregression_jl_tpu.analysis.memory import (
        diff_memory_baseline,
    )

    baseline = {
        "configs": {
            "base": {
                "peak_modeled_bytes": 1000,
                "stages": {"optimize": {"peak_modeled_bytes": 800}},
            },
        }
    }

    def configs(peak, stage_peak):
        return {
            "base": {
                "peak_modeled_bytes": peak,
                "stages": {"optimize": {"peak_modeled_bytes": stage_peak}},
            }
        }

    probs, notes = diff_memory_baseline(configs(1050, 820), baseline)
    assert probs == [] and notes == []
    probs, notes = diff_memory_baseline(configs(1200, 800), baseline)
    assert len(probs) == 1 and "+20%" in probs[0]
    # per-stage attribution regresses independently of the headline peak
    probs, notes = diff_memory_baseline(configs(1000, 1600), baseline)
    assert len(probs) == 1 and "base.optimize" in probs[0]
    # improvements never fail; they suggest a refresh
    probs, notes = diff_memory_baseline(configs(500, 400), baseline)
    assert probs == [] and len(notes) == 2
    probs, _ = diff_memory_baseline(
        {"other": {"peak_modeled_bytes": 1, "stages": {}}}, baseline
    )
    assert len(probs) == 2  # unknown config + config no longer produced
    # stage-set drift fails in both directions too: a baseline stage
    # that is no longer produced must not silently stop being gated
    probs, _ = diff_memory_baseline(
        {"base": {"peak_modeled_bytes": 1000, "stages": {}}}, baseline
    )
    assert len(probs) == 1 and "base.optimize no longer produced" in probs[0]


@pytest.mark.slow
def test_memory_budget_gate_fails_oversize_config(tmp_path):
    """Acceptance: a config whose modeled footprint exceeds the HBM
    budget fails even when it matches the baseline perfectly. Slow:
    two full single-config analyses (tier-1 timing hygiene)."""
    from symbolicregression_jl_tpu.analysis.memory import check_memory

    path = str(tmp_path / "memory_baseline.json")
    check_memory(
        update_baseline=True, baseline_path=path, configs=(("base", {}),),
    )
    r = check_memory(
        baseline_path=path, configs=(("base", {}),),
        hbm_budget_gb=1e-6,
    )
    assert not r["ok"]
    assert any("exceeds the 1e-06GB budget" in p for p in r["problems"])


@pytest.mark.fast
def test_checked_in_memory_baseline_exists_and_well_formed():
    from symbolicregression_jl_tpu.analysis.memory import BASELINE_PATH

    with open(BASELINE_PATH) as f:
        payload = json.load(f)
    assert payload["schema_version"] == 1
    assert set(payload["configs"]) == {
        "base", "cache", "islands4", "pop32", "bucketed", "rowsharded",
        "sharded", "tenants2",
    }
    for entry in payload["configs"].values():
        assert entry["peak_modeled_bytes"] > 0
        assert entry["stages"]


@pytest.mark.fast
def test_baseline_writer_stable_format(tmp_path):
    """Both checked-in baselines go through one writer: sorted keys,
    2-space indent, trailing newline — so refreshes diff minimally."""
    from symbolicregression_jl_tpu.analysis.report import (
        write_baseline_json,
    )

    path = str(tmp_path / "b.json")
    write_baseline_json(path, {"b": {"z": 1, "a": 2}, "a": 0})
    text = open(path).read()
    assert text.endswith("\n")
    assert text.index('"a"') < text.index('"b"') < text.index('"z"')
    write_baseline_json(path, {"a": 0, "b": {"a": 2, "z": 1}})
    assert open(path).read() == text


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_cli_lint_only_json():
    """`python -m symbolicregression_jl_tpu.analysis --only lint` exits 0
    on the repo at HEAD and prints the JSON schema."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--only", "lint", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["counts"] == {}


@pytest.mark.fast
def test_cli_in_process_exit_codes(tmp_path, monkeypatch):
    """main() returns nonzero when lint finds active violations."""
    import symbolicregression_jl_tpu.analysis as ana
    from symbolicregression_jl_tpu.analysis.__main__ import main

    # clean repo: exit 0 (lint engine only; surface covered above)
    assert main(["--only", "lint", "--format", "json"]) == 0

    def bad_lint():
        return lint_paths(
            FIXTURES,
            files=[os.path.join(FIXTURES, "fixture_sr001.py")],
            repo_root=REPO,
        )

    monkeypatch.setattr(ana, "lint_package", bad_lint)
    assert main(["--only", "lint", "--format", "text"]) == 1


@pytest.mark.slow
def test_cli_full_run_green_at_head():
    """The full gate — srlint + compile surface + srmem vs the checked-in
    baselines — exits 0 on the repo at HEAD (the ISSUE 3/4 acceptance
    criterion). Slow: traces the whole Options matrix twice (~2 min)."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["surface"]["baseline_match"] is True
    assert payload["memory"]["baseline_match"] is True


@pytest.mark.slow
def test_cli_memory_only_nonzero_on_tiny_budget():
    """Acceptance: `--only memory` exits nonzero when a config exceeds
    the HBM budget. Slow: traces the full Options matrix."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--only", "memory", "--format", "json",
         "--hbm-budget-gb", "1e-6"],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["memory"]["ok"] is False
    assert any(
        "budget" in p for p in payload["memory"]["problems"]
    )


@pytest.mark.slow
def test_scripts_lint_entry_point():
    """scripts/lint.py (the suite-case entry) runs the same gate plus the
    docs drift check and exits 0 at HEAD."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--only", "lint", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["docs"]["api_reference_current"] is True
