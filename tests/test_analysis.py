"""Static-analysis subsystem (ISSUEs 3+4): srlint rule detection on
known-bad fixtures, pragma suppression, reporter schema, compile-surface
contracts, the srmem HBM-footprint gate, and both baseline drift gates.

The srlint fixtures under tests/data/srlint_fixtures/ are parsed, never
imported; each file documents inline which lines must (and must NOT) be
flagged. Everything here is CPU-only AST/tracing work — no TPU, and the
only jax executions are eval_shape/make_jaxpr traces."""

import json
import os
import subprocess
import sys

import pytest

from symbolicregression_jl_tpu.analysis import (
    RULES,
    AnalysisReport,
    lint_package,
    lint_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "srlint_fixtures")


def _lint_fixture(name):
    return lint_paths(
        FIXTURES, files=[os.path.join(FIXTURES, name)], repo_root=REPO
    )


def _active(violations, rule=None):
    return [
        v for v in violations
        if not v.suppressed and (rule is None or v.rule_id == rule)
    ]


# ---------------------------------------------------------------------------
# srlint: one fixture per rule
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_sr001_host_sync_detected():
    vs = _lint_fixture("fixture_sr001.py")
    hits = _active(vs, "SR001")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    # reachable through the call graph, not just the jitted def itself
    assert any(v.function == "_inner" for v in hits)
    # host-only helper with identical calls stays clean
    assert not any(v.function == "host_only" for v in hits)


@pytest.mark.fast
def test_sr002_tracer_control_flow_detected():
    vs = _lint_fixture("fixture_sr002.py")
    hits = _active(vs, "SR002")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    assert all(v.function == "branchy" for v in hits)
    # static bool / identity / shape-math branches in fine() not flagged
    assert not _active(vs, "SR001")


@pytest.mark.fast
def test_sr003_unsorted_dict_iteration_detected():
    vs = _lint_fixture("fixture_sr003.py")
    hits = _active(vs, "SR003")
    assert len(hits) == 2, [v.to_dict() for v in vs]
    assert all(v.function == "build" for v in hits)


@pytest.mark.fast
def test_sr004_implicit_dtype_detected():
    vs = _lint_fixture("fixture_sr004.py")
    hits = _active(vs, "SR004")
    # zeros/ones/full/arange without dtype; positional+kwarg dtype and
    # zeros_like stay clean
    assert len(hits) == 4, [v.to_dict() for v in vs]


@pytest.mark.fast
def test_sr005_stale_static_argnames_detected():
    vs = _lint_fixture("fixture_sr005.py")
    hits = _active(vs, "SR005")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    messages = " ".join(v.message for v in hits)
    for stale in ("block_sz", "tile", "modes"):
        assert stale in messages
    # the valid wrapper, the decorator form and **kwargs are not flagged
    assert not any("block_size'" in v.message for v in hits)


@pytest.mark.fast
def test_sr006_missing_donation_detected():
    vs = _lint_fixture("fixture_sr006.py")
    hits = _active(vs, "SR006")
    # plain wrap, bare decorator, aliased return
    assert len(hits) == 3, [v.to_dict() for v in vs]
    functions = {v.function for v in hits}
    assert functions == {"step", "dec_step", "aliased"}
    # donating wrappers, the pure function, and the static param stay clean
    messages = " ".join(v.message for v in hits)
    assert "dec_donated" not in messages
    assert "'block'" not in messages


@pytest.mark.fast
def test_sr007_broadcast_materialization_detected():
    vs = _lint_fixture("fixture_sr007.py")
    hits = _active(vs, "SR007")
    # broadcast_to, outer, tile with literal factor >= 8
    assert len(hits) == 3, [v.to_dict() for v in vs]
    assert all(v.function == "hot" for v in hits)
    # identical call outside the jit call graph stays clean
    assert not any(v.function == "host_only" for v in hits)


@pytest.mark.fast
def test_sr008_host_roundtrip_detected():
    vs = _lint_fixture("fixture_sr008.py")
    hits = _active(vs, "SR008")
    # tainted-name feed-back + inline round-trip, both in drive()
    assert len(hits) == 2, [v.to_dict() for v in vs]
    assert all(v.function == "drive" for v in hits)
    assert not any(v.function == "fine" for v in vs)
    # reassignment from a non-sync value kills the taint
    assert not any(v.function == "retainted" for v in vs)


@pytest.mark.fast
def test_sr009_where_after_nan_producing_op_detected():
    vs = _lint_fixture("fixture_sr009.py")
    hits = _active(vs, "SR009")
    # log branch, sqrt branch, unclamped division, fractional power
    assert len(hits) == 4, [v.to_dict() for v in vs]
    functions = {v.function for v in hits}
    assert functions == {
        "bad_log_branch", "bad_sqrt_branch", "bad_division_branch",
        "bad_fractional_power",
    }
    # clamped inputs (the safe_* pattern), integer powers, plain selects
    # and host-only code stay clean; the pragma suppresses
    assert not any(
        v.function and v.function.startswith(("good_", "host_only"))
        for v in hits
    )
    sup = [v for v in vs if v.suppressed and v.rule_id == "SR009"]
    assert len(sup) == 1 and sup[0].function == "pragma_suppressed"


@pytest.mark.fast
def test_clean_fixture_produces_zero_findings():
    vs = _lint_fixture("fixture_clean.py")
    assert vs == [], [v.to_dict() for v in vs]


# ---------------------------------------------------------------------------
# pragmas + reporters
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_pragma_suppression():
    vs = _lint_fixture("fixture_pragmas.py")
    active = _active(vs)
    suppressed = [v for v in vs if v.suppressed]
    # the mismatched-rule pragma does NOT suppress
    assert len(active) == 1 and active[0].rule_id == "SR001"
    # single-rule, multi-rule, and justified pragmas all suppress
    assert len(suppressed) == 3
    assert {v.rule_id for v in suppressed} == {"SR001", "SR004"}


@pytest.mark.fast
def test_json_report_schema():
    vs = _lint_fixture("fixture_pragmas.py")
    report = AnalysisReport(violations=vs)
    payload = json.loads(report.to_json())
    assert payload["schema_version"] == 1
    assert payload["tool"] == "srlint"
    assert payload["ok"] is False
    assert payload["counts"] == {"SR001": 1}
    assert payload["suppressed"] == 3
    assert payload["surface"] is None
    assert payload["memory"] is None
    assert payload["shard"] is None
    for v in payload["violations"]:
        assert set(v) == {
            "rule", "name", "path", "line", "col", "function", "message",
            "suppressed",
        }
        assert v["rule"] in RULES
    # text renderer shows only active findings plus the summary line
    text = report.to_text()
    assert text.count("SR001") >= 1
    assert "suppressed by pragma" in text


@pytest.mark.fast
def test_rule_catalog_documented():
    for rule in RULES.values():
        assert rule.summary and rule.rationale
    # docs cross-check: every rule id appears in the rule catalog doc
    doc = open(os.path.join(REPO, "docs", "static_analysis.md")).read()
    for rid in RULES:
        assert rid in doc, f"{rid} missing from docs/static_analysis.md"


# ---------------------------------------------------------------------------
# the repo itself must be clean (the lint lands green — ISSUE 3 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_package_tree_is_srlint_clean():
    vs = lint_package(repo_root=REPO)
    active = _active(vs)
    assert active == [], "\n".join(
        f"{v.path}:{v.line} {v.rule_id} {v.message}" for v in active
    )


# ---------------------------------------------------------------------------
# compile surface
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_compile_surface_single_config(tmp_path):
    """One small config end-to-end under JAX_PLATFORMS=cpu (conftest):
    aval stability, IslandState contract, no callbacks/f64, census
    written and re-read as a baseline. Slow: ~6s of tracing (tier-1
    timing hygiene, ISSUE 4)."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        check_surface,
    )

    path = str(tmp_path / "baseline.json")
    r = check_surface(
        update_baseline=True, baseline_path=path,
        configs=(("base", {}),), include_chunked=False,
    )
    assert r["problems"] == []
    entry = r["configs"]["base"]
    assert entry["stable_avals"]
    assert entry["total_primitives"] > 100
    assert not any("callback" in p for p in entry["primitives"])
    # second run diffs clean against the just-written baseline
    r2 = check_surface(
        baseline_path=path, configs=(("base", {}),), include_chunked=False,
    )
    assert r2["ok"], r2["problems"]
    assert r2["baseline_checked"] and r2["baseline_match"]


@pytest.mark.fast
def test_baseline_diff_catches_injected_primitive(tmp_path):
    """Acceptance: an extra primitive in the census fails the diff."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        diff_baseline,
    )

    baseline = {
        "configs": {
            "base": {"primitives": {"add": 10, "mul": 5}},
        }
    }
    clean = {"base": {"primitives": {"add": 10, "mul": 5}}}
    assert diff_baseline(clean, baseline) == []
    injected = {"base": {"primitives": {"add": 10, "mul": 5,
                                        "pure_callback": 1}}}
    probs = diff_baseline(injected, baseline)
    assert len(probs) == 1 and "pure_callback" in probs[0]
    grown = {"base": {"primitives": {"add": 11, "mul": 5}}}
    probs = diff_baseline(grown, baseline)
    assert len(probs) == 1 and "baseline 10 -> now 11" in probs[0]
    missing = {"other": {"primitives": {}}}
    probs = diff_baseline(missing, baseline)
    assert len(probs) == 2  # unknown config + config no longer produced


@pytest.mark.fast
def test_baseline_diff_collective_census_and_skip():
    """The sharded config's collective census diffs like the primitive
    counts, and a skipped config (single-device host) is exempt in both
    directions instead of reading as missing/unknown."""
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        diff_baseline,
    )

    baseline = {
        "configs": {
            "sharded": {
                "primitives": {"add": 3},
                "collectives": {"all-gather": 16, "all-reduce": 14},
            },
        }
    }
    clean = {"sharded": {"primitives": {"add": 3},
                         "collectives": {"all-gather": 16,
                                         "all-reduce": 14}}}
    assert diff_baseline(clean, baseline) == []
    drifted = {"sharded": {"primitives": {"add": 3},
                           "collectives": {"all-gather": 17,
                                           "all-reduce": 14}}}
    probs = diff_baseline(drifted, baseline)
    assert len(probs) == 1 and "all-gather" in probs[0]
    vanished = {"sharded": {"primitives": {"add": 3}, "collectives": {}}}
    probs = diff_baseline(vanished, baseline)
    assert len(probs) == 2  # both collective counts dropped to 0
    skipped = {"sharded": {"skipped": "1 device(s)"}}
    assert diff_baseline(skipped, baseline) == []


@pytest.mark.fast
def test_collective_census_counts_hlo_ops():
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        collective_census,
    )

    hlo = (
        "%ag = f32[8,4]{1,0} all-gather(f32[1,4]{1,0} %p), dims={0}\n"
        "%ar = f32[] all-reduce(f32[] %x), to_apply=%sum\n"
        "%ag2.s = f32[8]{0} all-gather-start(f32[1]{0} %q)\n"
        "%ag2.d = f32[8]{0} all-gather-done(f32[8]{0} %ag2.s)\n"
    )
    assert collective_census(hlo) == {"all-gather": 2, "all-reduce": 1}


@pytest.mark.fast
def test_checked_in_baseline_exists_and_well_formed():
    from symbolicregression_jl_tpu.analysis.compile_surface import (
        BASELINE_PATH,
    )

    with open(BASELINE_PATH) as f:
        payload = json.load(f)
    assert payload["schema_version"] == 1
    assert set(payload["configs"]) == {
        "base", "cache", "islands4", "pop32", "bucketed", "rowsharded",
        "chunked", "sharded", "tenants2",
    }
    for entry in payload["configs"].values():
        assert entry["total_primitives"] == sum(
            entry["primitives"].values()
        )
        assert not any("callback" in p for p in entry["primitives"])
    # the sharded config additionally pins the collective census — the
    # cross-device traffic shape of the partitioned iteration
    sharded = payload["configs"]["sharded"]
    assert sharded["n_devices"] >= 2
    assert sharded["collectives"] and all(
        n > 0 for n in sharded["collectives"].values()
    )


# ---------------------------------------------------------------------------
# srmem (memory engine)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_live_buffer_peak_models_liveness_and_blowups():
    """The estimator sees a materialized broadcast as both peak bytes and
    an SR007-signature blowup; a pointwise chain of the same shapes does
    not blow up."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.memory import live_buffer_peak

    def blowy(x):  # (1024,) f32 -> (512, 1024) f32: 2MB from 4KB
        big = jnp.broadcast_to(x, (512, 1024)) * 2.0
        return big.sum()

    est = live_buffer_peak(
        jax.make_jaxpr(blowy)(jnp.zeros((1024,), jnp.float32))
    )
    assert est["peak_bytes"] >= 512 * 1024 * 4
    assert est["args_bytes"] == 1024 * 4
    assert est["blowups"], est
    assert est["blowups"][0]["factor"] >= 8

    def pointwise(x):
        return ((x * 2.0) + 1.0).sum()

    est2 = live_buffer_peak(
        jax.make_jaxpr(pointwise)(jnp.zeros((1024,), jnp.float32))
    )
    assert est2["blowups"] == []
    assert est2["peak_bytes"] < est["peak_bytes"]


@pytest.mark.slow
def test_memory_single_config_baseline_roundtrip(tmp_path):
    """One config end-to-end: stages attributed, baseline written, and a
    second run diffs clean against it (the srmem analog of the
    compile-surface round-trip above). Slow: two full single-config
    analyses, ~14s of tracing."""
    from symbolicregression_jl_tpu.analysis.memory import check_memory

    path = str(tmp_path / "memory_baseline.json")
    r = check_memory(
        update_baseline=True, baseline_path=path, configs=(("base", {}),),
    )
    entry = r["configs"]["base"]
    assert entry["peak_modeled_bytes"] > 0
    assert set(entry["stages"]) == {
        "init", "cycle", "mutate", "eval", "simplify", "optimize",
        "merge_migrate",
    }
    assert entry["footprint_bytes"] == (
        entry["args_bytes"] + entry["peak_modeled_bytes"]
    )
    r2 = check_memory(baseline_path=path, configs=(("base", {}),))
    assert r2["ok"], r2["problems"]
    assert r2["baseline_checked"] and r2["baseline_match"]


@pytest.mark.fast
def test_memory_diff_catches_injected_regression():
    """Acceptance: a >10% modeled-peak growth fails, a shrink only notes,
    and config-set drift fails in both directions."""
    from symbolicregression_jl_tpu.analysis.memory import (
        diff_memory_baseline,
    )

    baseline = {
        "configs": {
            "base": {
                "peak_modeled_bytes": 1000,
                "stages": {"optimize": {"peak_modeled_bytes": 800}},
            },
        }
    }

    def configs(peak, stage_peak):
        return {
            "base": {
                "peak_modeled_bytes": peak,
                "stages": {"optimize": {"peak_modeled_bytes": stage_peak}},
            }
        }

    probs, notes = diff_memory_baseline(configs(1050, 820), baseline)
    assert probs == [] and notes == []
    probs, notes = diff_memory_baseline(configs(1200, 800), baseline)
    assert len(probs) == 1 and "+20%" in probs[0]
    # per-stage attribution regresses independently of the headline peak
    probs, notes = diff_memory_baseline(configs(1000, 1600), baseline)
    assert len(probs) == 1 and "base.optimize" in probs[0]
    # improvements never fail; they suggest a refresh
    probs, notes = diff_memory_baseline(configs(500, 400), baseline)
    assert probs == [] and len(notes) == 2
    probs, _ = diff_memory_baseline(
        {"other": {"peak_modeled_bytes": 1, "stages": {}}}, baseline
    )
    assert len(probs) == 2  # unknown config + config no longer produced
    # stage-set drift fails in both directions too: a baseline stage
    # that is no longer produced must not silently stop being gated
    probs, _ = diff_memory_baseline(
        {"base": {"peak_modeled_bytes": 1000, "stages": {}}}, baseline
    )
    assert len(probs) == 1 and "base.optimize no longer produced" in probs[0]


@pytest.mark.slow
def test_memory_budget_gate_fails_oversize_config(tmp_path):
    """Acceptance: a config whose modeled footprint exceeds the HBM
    budget fails even when it matches the baseline perfectly. Slow:
    two full single-config analyses (tier-1 timing hygiene)."""
    from symbolicregression_jl_tpu.analysis.memory import check_memory

    path = str(tmp_path / "memory_baseline.json")
    check_memory(
        update_baseline=True, baseline_path=path, configs=(("base", {}),),
    )
    r = check_memory(
        baseline_path=path, configs=(("base", {}),),
        hbm_budget_gb=1e-6,
    )
    assert not r["ok"]
    assert any("exceeds the 1e-06GB budget" in p for p in r["problems"])


@pytest.mark.fast
def test_checked_in_memory_baseline_exists_and_well_formed():
    from symbolicregression_jl_tpu.analysis.memory import BASELINE_PATH

    with open(BASELINE_PATH) as f:
        payload = json.load(f)
    assert payload["schema_version"] == 1
    assert set(payload["configs"]) == {
        "base", "cache", "islands4", "pop32", "bucketed", "rowsharded",
        "sharded", "tenants2",
    }
    for entry in payload["configs"].values():
        assert entry["peak_modeled_bytes"] > 0
        assert entry["stages"]


@pytest.mark.fast
def test_baseline_writer_stable_format(tmp_path):
    """Both checked-in baselines go through one writer: sorted keys,
    2-space indent, trailing newline — so refreshes diff minimally."""
    from symbolicregression_jl_tpu.analysis.report import (
        write_baseline_json,
    )

    path = str(tmp_path / "b.json")
    write_baseline_json(path, {"b": {"z": 1, "a": 2}, "a": 0})
    text = open(path).read()
    assert text.endswith("\n")
    assert text.index('"a"') < text.index('"b"') < text.index('"z"')
    write_baseline_json(path, {"a": 0, "b": {"a": 2, "z": 1}})
    assert open(path).read() == text


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_cli_lint_only_json():
    """`python -m symbolicregression_jl_tpu.analysis --only lint` exits 0
    on the repo at HEAD and prints the JSON schema."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--only", "lint", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["counts"] == {}


@pytest.mark.fast
def test_cli_in_process_exit_codes(tmp_path, monkeypatch):
    """main() returns nonzero when lint finds active violations."""
    import symbolicregression_jl_tpu.analysis as ana
    from symbolicregression_jl_tpu.analysis.__main__ import main

    # clean repo: exit 0 (lint engine only; surface covered above)
    assert main(["--only", "lint", "--format", "json"]) == 0

    def bad_lint():
        return lint_paths(
            FIXTURES,
            files=[os.path.join(FIXTURES, "fixture_sr001.py")],
            repo_root=REPO,
        )

    monkeypatch.setattr(ana, "lint_package", bad_lint)
    assert main(["--only", "lint", "--format", "text"]) == 1


@pytest.mark.slow
def test_cli_full_run_green_at_head():
    """The full gate — all six engines vs the checked-in baselines —
    exits 0 on the repo at HEAD (the ISSUE 3/4 acceptance criterion).
    Slow: traces the whole Options matrix twice AND AOT-compiles the
    srshard mesh matrix (~20 min cold; a warm persistent JAX compile
    cache, inherited via JAX_COMPILATION_CACHE_DIR, cuts it to ~3)."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=2700,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["surface"]["baseline_match"] is True
    assert payload["memory"]["baseline_match"] is True
    assert payload["shard"]["baseline_match"] is True
    assert payload["shard"]["cross_tenant_collectives"] == 0


@pytest.mark.slow
def test_cli_memory_only_nonzero_on_tiny_budget():
    """Acceptance: `--only memory` exits nonzero when a config exceeds
    the HBM budget. Slow: traces the full Options matrix."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--only", "memory", "--format", "json",
         "--hbm-budget-gb", "1e-6"],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["memory"]["ok"] is False
    assert any(
        "budget" in p for p in payload["memory"]["problems"]
    )


# ---------------------------------------------------------------------------
# srshard: sharding contract + communication cost model (ISSUE 19)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_shard_replica_group_decoding():
    """HLO replica-group forms decode to real participant lists: the
    iota form (with transpose), the brace form, source_target_pairs,
    and the empty/absent forms meaning all participants."""
    from symbolicregression_jl_tpu.analysis.shard import (
        _decode_iota_groups,
        _participant_groups,
    )

    # [4,2]<=[2,4]T(1,0): iota over (2,4), transposed, reshaped (4,2)
    assert _decode_iota_groups(4, 2, [2, 4], [1, 0]) == [
        [0, 4], [1, 5], [2, 6], [3, 7],
    ]
    assert _decode_iota_groups(2, 4, [2, 4], None) == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]
    assert _participant_groups(
        "replica_groups=[4,2]<=[2,4]T(1,0)", 8
    ) == [[0, 4], [1, 5], [2, 6], [3, 7]]
    assert _participant_groups(
        "replica_groups={{0,1},{2,3}}", 8
    ) == [[0, 1], [2, 3]]
    assert _participant_groups(
        "source_target_pairs={{0,1},{1,0}}", 8
    ) == [[0, 1], [1, 0]]
    # empty groups / absent attribute = one group of everyone
    assert _participant_groups("replica_groups={}", 4) == [[0, 1, 2, 3]]
    assert _participant_groups("channel_id=1", 4) == [[0, 1, 2, 3]]


@pytest.mark.fast
def test_shard_collective_parse_and_pricing():
    """parse_collectives reads op, payload bytes, and groups off HLO
    text (counting async pairs once); price_comms applies the ring
    factors over the tabled bandwidth."""
    from symbolicregression_jl_tpu.analysis import shard

    hlo = "\n".join([
        "  %ar = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %x), "
        "replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add",
        "  %ag.s = (f32[4]{0}, f32[16]{0}) all-gather-start(f32[4]{0} "
        "%y), replica_groups=[2,4]<=[8], dimensions={0}",
        "  %ag.d = f32[16]{0} all-gather-done((f32[4]{0}, f32[16]{0}) "
        "%ag.s)",
        "  %cp = f32[256]{0} collective-permute(f32[256]{0} %z), "
        "source_target_pairs={{0,1},{1,0}}",
        "  %noise = f32[2]{0} add(f32[2]{0} %a, f32[2]{0} %b)",
    ])
    colls = shard.parse_collectives(hlo, 8)
    assert shard.census_of(colls) == {
        "all-gather": 1, "all-reduce": 1, "collective-permute": 1,
    }
    by_op = {c["op"]: c for c in colls}
    assert by_op["all-reduce"]["bytes"] == 8 * 16 * 4
    assert by_op["all-reduce"]["groups"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # async start: the largest tuple element (the gathered output)
    assert by_op["all-gather"]["bytes"] == 16 * 4
    assert by_op["all-gather"]["groups"] == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]
    assert by_op["collective-permute"]["groups"] == [[0, 1], [1, 0]]

    priced = shard.price_comms(colls, "v5e")
    assert priced["comm_bytes"] == 512 + 64 + 1024
    bw = shard.ICI_BANDWIDTH["v5e"]
    want_s = (
        512 * 2 * 3 / 4 / bw  # all-reduce, g=4: 2(g-1)/g
        + 64 * 3 / 4 / bw     # all-gather, g=4: (g-1)/g
        + 1024 * 1.0 / bw     # collective-permute
    )
    assert abs(priced["modeled_s"] - want_s) < 1e-18

    # bandwidth table: substring match, unknown kind -> host fallback
    assert shard.interconnect_bandwidth("TPU v5 lite") == bw
    assert (
        shard.interconnect_bandwidth("cpu")
        == shard.HOST_INTERCONNECT_BYTES_PER_S
    )
    # comms fraction against the fixed model device kind
    assert shard.comms_fraction(0.0, 1e9) == 0.0
    frac = shard.comms_fraction(1e-3, 3.9e9)  # compute_s = 1e-3
    assert abs(frac - 0.5) < 1e-9


@pytest.mark.fast
def test_shard_cross_tenant_detection_and_bisection():
    """ISSUE 19 acceptance (injected defect b): a deliberate
    cross-tenant reduction on the (tenants, islands) mesh is detected
    from the compiled HLO's replica groups, and the group-halving
    bisection names the culprit output leaf."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbolicregression_jl_tpu.analysis import shard
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8 forced-host devices")
    opts = make_options(binary_operators=["+"], npopulations=4, tenants=2)
    mesh = make_mesh(opts, 4, devices=jax.devices()[:8], tenants=2)
    assert mesh is not None and mesh.devices.shape == (2, 4)
    sh = NamedSharding(mesh, P(opts.tenant_axis, opts.island_axis))
    x = jax.ShapeDtypeStruct((2, 4, 512), jnp.float32)

    # leaf 0 is elementwise (tenant-local); leaf 1 reduces over EVERY
    # axis including tenants — the injected isolation leak
    def leaky(a):
        return (a * 2.0, jnp.sum(a))

    compiled = jax.jit(leaky, in_shardings=sh).lower(x).compile()
    colls = shard.parse_collectives(compiled.as_text(), 8)
    bad = shard.cross_tenant_collectives(colls, n_island_shards=4)
    assert bad, "cross-tenant reduction not detected"
    assert any(c["op"] == "all-reduce" for c in bad)

    # per-tenant reduction stays clean: sum over islands+rows only
    def clean(a):
        return (a * 2.0, jnp.sum(a, axis=(1, 2)))

    c2 = jax.jit(clean, in_shardings=sh).lower(x).compile()
    colls2 = shard.parse_collectives(c2.as_text(), 8)
    assert shard.cross_tenant_collectives(colls2, 4) == []

    # bisection: compiling output-leaf subsets pins the leak to leaf 1
    def compile_hlo(idxs):
        f = lambda a: tuple(leaky(a)[i] for i in idxs)  # noqa: E731
        return (
            jax.jit(f, in_shardings=sh).lower(x).compile().as_text()
        )

    culprits = shard._bisect_tenant_culprits(
        compile_hlo, n_leaves=2, n_island_shards=4, n_devices=8
    )
    assert culprits == [1]


@pytest.mark.fast
def test_shard_cross_tenant_exemptions():
    """The two structurally value-preserving GSPMD artifacts the real
    tenant-batched iteration emits are exempt from the cross-tenant
    gate; everything else crossing the tenant axis stays a violation
    (cross_tenant_collectives docstring)."""
    from symbolicregression_jl_tpu.analysis.shard import (
        cross_tenant_collectives,
    )

    cross = [[0, 4], [1, 5], [2, 6], [3, 7]]  # pairs across 2 tenants
    within = [[0, 1, 2, 3], [4, 5, 6, 7]]
    # replication data movement: exempt even across tenants
    ag = {"op": "all-gather", "bytes": 768, "groups": cross}
    # SPMD while-predicate convergence: pred[] scalar, exempt
    pred_ar = {"op": "all-reduce", "bytes": 1, "groups": cross}
    # a real data psum across tenants (f32[] = 4 bytes): violation
    data_ar = {"op": "all-reduce", "bytes": 4, "groups": cross}
    # data movement ops that can mis-route tenant data: violations
    cp = {"op": "collective-permute", "bytes": 64,
          "groups": [[0, 4], [4, 0]]}
    rs = {"op": "reduce-scatter", "bytes": 128, "groups": cross}
    # within-tenant traffic never flags regardless of op
    ok_ar = {"op": "all-reduce", "bytes": 4096, "groups": within}

    bad = cross_tenant_collectives(
        [ag, pred_ar, data_ar, cp, rs, ok_ar], n_island_shards=4
    )
    assert bad == [data_ar, cp, rs]


@pytest.mark.fast
def test_shard_replication_blowup_names_leaf():
    """ISSUE 19 acceptance (injected defect a): dropping the island
    out_sharding on one carry leaf makes GSPMD replicate it; the
    replication gate flags exactly that leaf BY NAME against the
    contract's expected sharding."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbolicregression_jl_tpu.analysis.shard import (
        _replication_stats,
    )
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8 forced-host devices")
    opts = make_options(binary_operators=["+"], npopulations=8)
    mesh = make_mesh(opts, 8, devices=jax.devices()[:8])
    isl = NamedSharding(mesh, P(opts.island_axis))
    rep = NamedSharding(mesh, P())

    avals = {
        "trees": jax.ShapeDtypeStruct((8, 64, 8), jnp.float32),
        "losses": jax.ShapeDtypeStruct((8, 64), jnp.float32),
    }

    def f(t):
        return {k: v * 2.0 for k, v in t.items()}

    # the injected defect: trees' island out_sharding dropped -> P()
    compiled = (
        jax.jit(
            f,
            in_shardings=({"trees": isl, "losses": isl},),
            out_shardings={"trees": rep, "losses": isl},
        )
        .lower(avals).compile()
    )
    expected = {"trees": isl, "losses": isl}
    problems, max_factor = _replication_stats(
        "fused", jax.eval_shape(f, avals), compiled.output_shardings,
        expected, n_devices=8,
    )
    assert len(problems) == 1, problems
    assert "replication blowup" in problems[0]
    assert "'trees'" in problems[0] and "'losses'" not in problems[0]
    assert max_factor == pytest.approx(8.0)

    # contract-conforming shardings pass with factor 1
    ok_compiled = (
        jax.jit(
            f,
            in_shardings=({"trees": isl, "losses": isl},),
            out_shardings={"trees": isl, "losses": isl},
        )
        .lower(avals).compile()
    )
    problems, max_factor = _replication_stats(
        "fused", jax.eval_shape(f, avals),
        ok_compiled.output_shardings, expected, n_devices=8,
    )
    assert problems == []
    assert max_factor == pytest.approx(1.0)


@pytest.mark.fast
def test_shard_baseline_diff_gates():
    """diff_shard_baseline: census drift fails exactly; comm-byte
    growth beyond tolerance fails while shrinks only note; skipped
    configs are exempt in both directions; structural drift (stage set,
    mesh shape, missing sections) fails."""
    from symbolicregression_jl_tpu.analysis.shard import (
        diff_shard_baseline,
    )

    def entry(comm=1000, census=None, fused=None):
        e = {
            "mesh_shape": {"islands": 4, "rows": 2},
            "n_devices": 8,
            "stage_set": ["eval"],
            "stages": {
                "eval": {
                    "collectives": dict(census or {"all-reduce": 2}),
                    "comm_bytes": comm,
                    "comms_fraction": 0.1,
                },
            },
        }
        if fused is not None:
            e["fused"] = fused
        return e

    base = {"configs": {"mesh4x2": entry()}}

    probs, notes = diff_shard_baseline({"mesh4x2": entry()}, base)
    assert probs == [] and notes == []

    # census drift fails exactly
    probs, _ = diff_shard_baseline(
        {"mesh4x2": entry(census={"all-reduce": 3})}, base
    )
    assert any("census drift" in p for p in probs)

    # +11% comm bytes fails at the 10% tolerance; -20% only notes
    probs, _ = diff_shard_baseline({"mesh4x2": entry(comm=1111)}, base)
    assert any("grew" in p for p in probs)
    probs, notes = diff_shard_baseline({"mesh4x2": entry(comm=800)}, base)
    assert probs == []
    assert any("shrank" in n for n in notes)

    # skipped exempts the config in both directions
    probs, notes = diff_shard_baseline(
        {"mesh4x2": {"skipped": "1 device(s)"}}, base
    )
    assert probs == [] and notes == []

    # structural drift: stage set, mesh shape, missing config/section
    changed = entry()
    changed["stage_set"] = ["eval", "init"]
    probs, _ = diff_shard_baseline({"mesh4x2": changed}, base)
    assert any("stage set changed" in p for p in probs)

    changed = entry()
    changed["mesh_shape"] = {"islands": 8, "rows": 1}
    probs, _ = diff_shard_baseline({"mesh4x2": changed}, base)
    assert any("mesh shape changed" in p for p in probs)

    probs, _ = diff_shard_baseline({"mesh1x8": entry()}, base)
    assert any("no config" in p for p in probs)
    assert any("no longer produced" in p for p in probs)

    # a fused section appearing without a baseline fails toward refresh
    probs, _ = diff_shard_baseline(
        {"mesh4x2": entry(fused={
            "collectives": {}, "comm_bytes": 0, "comms_fraction": 0.0,
        })},
        base,
    )
    assert any("fused" in p for p in probs)


@pytest.mark.fast
def test_shard_baseline_stage_comms_join(tmp_path):
    """baseline_stage_comms never raises: {} without a baseline; the
    canonical config's stage fractions otherwise (the srprof report
    join)."""
    from symbolicregression_jl_tpu.analysis.shard import (
        baseline_stage_comms,
    )

    missing = str(tmp_path / "nope.json")
    assert baseline_stage_comms(baseline_path=missing) == {}

    bp = tmp_path / "shard_baseline.json"
    bp.write_text(json.dumps({
        "configs": {
            "mesh4x2": {
                "stages": {
                    "eval": {"comm_bytes": 10, "comms_fraction": 0.25},
                    "cycle": {"comm_bytes": 10, "comms_fraction": 0.5},
                    "broken": {"comm_bytes": 10},
                },
            },
        },
    }))
    assert baseline_stage_comms(baseline_path=str(bp)) == {
        "eval": 0.25, "cycle": 0.5,
    }
    bp.write_text("not json")
    assert baseline_stage_comms(baseline_path=str(bp)) == {}


@pytest.mark.fast
def test_shard_skips_below_eight_devices(monkeypatch, tmp_path):
    """<8 devices: every config is SKIPPED (not missing) — no compile,
    no baseline failure in update mode, and skipped entries are never
    written into the baseline."""
    import jax

    from symbolicregression_jl_tpu.analysis import shard

    one = list(jax.devices())[:1]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: one)
    bp = str(tmp_path / "shard_baseline.json")

    res = shard.check_shard(baseline_path=bp)
    assert all("skipped" in e for e in res["configs"].values())
    assert res["comms_fraction"] is None
    # no baseline at all is still a problem (the gate must be armed)
    assert any("no shard baseline" in p for p in res["problems"])

    res = shard.check_shard(update_baseline=True, baseline_path=bp)
    assert res["ok"], res["problems"]
    written = json.load(open(bp))
    assert written["configs"] == {}, (
        "skipped configs must never be written into the baseline"
    )


@pytest.mark.fast
def test_render_shard_text_lines():
    from symbolicregression_jl_tpu.analysis.report import (
        render_shard_text,
    )

    shard = {
        "ok": False,
        "problems": ["mesh4x2: CROSS-TENANT all-reduce"],
        "notes": ["mesh1x8: fused iteration not compiled on this mesh"],
        "configs": {
            "mesh4x2": {
                "mesh_shape": {"islands": 4, "rows": 2},
                "stage_set": ["eval"],
                "stages": {
                    "eval": {
                        "collectives": {"all-reduce": 2},
                        "comm_bytes": 2048,
                        "comms_fraction": 0.1,
                    },
                },
                "fused": {
                    "collectives": {"all-gather": 3},
                    "comm_bytes": 4096,
                    "comms_fraction": 0.25,
                    "max_replication_factor": 1.0,
                },
            },
            "skipme": {"skipped": "1 device(s)"},
        },
        "baseline_checked": True,
        "baseline_match": False,
        "cross_tenant_collectives": 1,
        "max_replication_factor": 1.0,
    }
    text = render_shard_text(shard)
    assert "srshard: mesh4x2: CROSS-TENANT all-reduce" in text
    assert "note: mesh1x8" in text
    assert "mesh 4x2" in text and "comms share 25.0%" in text
    assert "skipme: skipped" in text
    assert "FAIL" in text and "1 CROSS-TENANT collective(s)" in text
    assert "baseline MISMATCH" in text


@pytest.mark.slow
def test_shard_small_matrix_gate_end_to_end(tmp_path):
    """check_shard on a one-stage matrix round-trips its baseline, and
    an injected >10% comm-byte growth (a tampered baseline) fails the
    gate — the ISSUE 19 regression-gate acceptance without the full
    ~5-minute matrix."""
    from symbolicregression_jl_tpu.analysis import shard

    matrix = (("mesh4x2", dict(row_shards=2), ("eval",), False),)
    bp = str(tmp_path / "shard_baseline.json")

    res = shard.check_shard(
        update_baseline=True, baseline_path=bp, matrix=matrix
    )
    assert res["ok"], res["problems"]
    entry = res["configs"]["mesh4x2"]
    assert entry["mesh_shape"] == {"islands": 4, "rows": 2}
    assert entry["specs"]["island"] == ["islands"]
    assert entry["stages"]["eval"]["comm_bytes"] > 0, (
        "the row-sharded eval must reduce across the rows axis"
    )

    res2 = shard.check_shard(baseline_path=bp, matrix=matrix)
    assert res2["ok"], res2["problems"]
    assert res2["baseline_checked"] and res2["baseline_match"]

    # injected regression: pretend the baseline was 20% leaner
    data = json.load(open(bp))
    sec = data["configs"]["mesh4x2"]["stages"]["eval"]
    sec["comm_bytes"] = int(sec["comm_bytes"] / 1.2)
    with open(bp, "w") as f:
        json.dump(data, f)
    res3 = shard.check_shard(baseline_path=bp, matrix=matrix)
    assert not res3["ok"]
    assert any(
        "comm bytes grew" in p and "mesh4x2.eval" in p
        for p in res3["problems"]
    )


@pytest.mark.slow
def test_checked_in_shard_baseline_exists_and_well_formed():
    """The shard baseline rides the repo like the other four: present,
    schema-stamped, and covering the full mesh matrix with the
    canonical config carrying a fused section."""
    from symbolicregression_jl_tpu.analysis.shard import (
        BASELINE_PATH,
        CANONICAL_CONFIG,
        _MESH_MATRIX,
    )

    assert os.path.exists(BASELINE_PATH), (
        "analysis/shard_baseline.json must be committed"
    )
    with open(BASELINE_PATH) as f:
        data = json.load(f)
    assert data["schema_version"] == 1
    assert data["model_device_kind"] == "v5e"
    names = {name for name, *_ in _MESH_MATRIX}
    assert set(data["configs"]) == names
    canon = data["configs"][CANONICAL_CONFIG]
    assert "fused" in canon
    assert set(canon["stages"]) == {
        "init", "cycle", "mutate", "eval", "simplify", "optimize",
        "merge_migrate",
    }
    for cfg in data["configs"].values():
        for sec in list(cfg["stages"].values()) + (
            [cfg["fused"]] if "fused" in cfg else []
        ):
            assert set(sec) == {
                "collectives", "comm_bytes", "comms_fraction",
            }
            assert sec["comm_bytes"] >= 0


@pytest.mark.slow
def test_scripts_lint_entry_point():
    """scripts/lint.py (the suite-case entry) runs the same gate plus the
    docs drift check and exits 0 at HEAD."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--only", "lint", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["docs"]["api_reference_current"] is True
