"""Constraint checks (reference test/test_constraints.jl,
test/test_nested_constraints.jl, test/test_complexity.jl)."""

import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.complexity import compute_complexity
from symbolicregression_jl_tpu.models.constraints import check_constraints
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.trees import Expr, encode_tree
from symbolicregression_jl_tpu.ops.operators import make_operator_set


def build(expr, maxlen=24):
    return encode_tree(expr, maxlen)


def test_size_cap():
    opt = make_options(binary_operators=["+", "*"], unary_operators=["cos"])
    ops = opt.operators
    e = Expr.binary(0, Expr.var(0), Expr.var(1))  # size 3
    t = build(e)
    assert bool(check_constraints(t, opt, jnp.int32(3)))
    assert not bool(check_constraints(t, opt, jnp.int32(2)))


def test_depth_cap():
    opt = make_options(
        binary_operators=["+"], unary_operators=["cos"], maxdepth=2, maxsize=20
    )
    cos = 0
    shallow = Expr.unary(cos, Expr.var(0))  # depth 2
    deep = Expr.unary(cos, Expr.unary(cos, Expr.var(0)))  # depth 3
    assert bool(check_constraints(build(shallow), opt, jnp.int32(20)))
    assert not bool(check_constraints(build(deep), opt, jnp.int32(20)))


def test_unary_op_subtree_cap():
    # exp's argument limited to 2 nodes (reference constraints=Dict("exp"=>2))
    opt = make_options(
        binary_operators=["+", "*"],
        unary_operators=["exp"],
        constraints={"exp": 2},
    )
    ops = opt.operators
    exp_i = ops.unary_index("exp")
    plus = ops.binary_index("+")
    ok_tree = Expr.unary(exp_i, Expr.var(0))  # child size 1
    bad_tree = Expr.unary(
        exp_i, Expr.binary(plus, Expr.var(0), Expr.var(1))
    )  # child size 3
    assert bool(check_constraints(build(ok_tree), opt, jnp.int32(20)))
    assert not bool(check_constraints(build(bad_tree), opt, jnp.int32(20)))


def test_binary_op_asymmetric_caps():
    # ^ with (-1, 2): unlimited base, exponent at most 2 nodes
    opt = make_options(
        binary_operators=["+", "^"],
        unary_operators=["cos"],
        constraints={"^": (-1, 2)},
    )
    ops = opt.operators
    pow_i = ops.binary_index("^")
    plus = ops.binary_index("+")
    big = Expr.binary(plus, Expr.var(0), Expr.binary(plus, Expr.var(1), Expr.var(2)))
    ok_tree = Expr.binary(pow_i, big, Expr.const(2.0))
    bad_tree = Expr.binary(pow_i, Expr.var(0), big)
    assert bool(check_constraints(build(ok_tree), opt, jnp.int32(20)))
    assert not bool(check_constraints(build(bad_tree), opt, jnp.int32(20)))


def test_nested_constraints():
    # cos may not contain cos (reference nested_constraints syntax
    # Dict("cos" => Dict("cos" => 0)))
    opt = make_options(
        binary_operators=["+"],
        unary_operators=["cos"],
        nested_constraints={"cos": {"cos": 0}},
    )
    cos, plus = 0, 0
    ok_tree = Expr.binary(
        plus, Expr.unary(cos, Expr.var(0)), Expr.unary(cos, Expr.var(1))
    )  # sibling cos: fine
    bad_tree = Expr.unary(cos, Expr.binary(plus, Expr.unary(cos, Expr.var(0)), Expr.var(1)))
    assert bool(check_constraints(build(ok_tree), opt, jnp.int32(20)))
    assert not bool(check_constraints(build(bad_tree), opt, jnp.int32(20)))


def test_nested_count_threshold():
    # + may contain at most 2 nested + strictly inside
    opt = make_options(
        binary_operators=["+"],
        nested_constraints={"+": {"+": 2}},
    )
    plus = 0
    t2 = Expr.binary(
        plus, Expr.binary(plus, Expr.var(0), Expr.var(1)),
        Expr.binary(plus, Expr.var(2), Expr.var(3)),
    )  # root + contains 2 inner +
    assert bool(check_constraints(build(t2), opt, jnp.int32(20)))
    t3 = Expr.binary(plus, t2, Expr.binary(plus, Expr.var(0), Expr.var(1)))
    # new root contains 4 inner +
    assert not bool(check_constraints(build(t3), opt, jnp.int32(20)))


def test_custom_complexity():
    opt = make_options(
        binary_operators=["+", "*"],
        unary_operators=["exp"],
        complexity_of_operators={"exp": 3, "*": 2},
        complexity_of_constants=2,
        complexity_of_variables=1,
    )
    ops = opt.operators
    e = Expr.binary(
        ops.binary_index("*"),
        Expr.unary(ops.unary_index("exp"), Expr.var(0)),
        Expr.const(1.0),
    )
    # exp(x0) * 1.0: * (2) + exp (3) + var (1) + const (2) = 8
    assert int(compute_complexity(build(e), opt)) == 8


def test_batched_constraints(rng):
    from symbolicregression_jl_tpu.models.trees import stack_trees
    from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size

    opt = make_options(binary_operators=["+", "*"], unary_operators=["cos"])
    trees = stack_trees(
        [
            build(random_expr_fixed_size(rng, opt.operators, 3, s))
            for s in [3, 5, 7, 9, 11]
        ]
    )
    ok = check_constraints(trees, opt, jnp.int32(7))
    lens = np.asarray(trees.length)
    np.testing.assert_array_equal(np.asarray(ok), lens <= 7)
