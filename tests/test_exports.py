"""Public API surface parity with the reference's export list
(reference src/SymbolicRegression.jl:4-59). Everything a user of the
reference reaches for must resolve at the package top level."""

import symbolicregression_jl_tpu as sr


def test_all_exports_resolve():
    missing = [n for n in sr.__all__ if not hasattr(sr, n)]
    assert not missing, missing


def test_reference_export_analogs_present():
    # reference name -> this package's analog (same name unless the flat
    # encoding forces a different one; value-semantics names like
    # set_node!/copy_node have no analog and are documented in PARITY.md)
    analogs = {
        "Population": "Population",
        "PopMember": "Population",  # struct-of-arrays: members live in it
        "HallOfFame": "HallOfFame",
        "Options": "Options",
        "Dataset": "Dataset",
        "MutationWeights": "MutationWeights",
        "Node": "TreeBatch",
        "EquationSearch": "EquationSearch",
        "s_r_cycle": "s_r_cycle",
        "calculate_pareto_frontier": "calculate_pareto_frontier",
        "compute_complexity": "compute_complexity",
        "string_tree": "tree_to_string",
        "eval_tree_array": "eval_tree",
        "eval_diff_tree_array": "eval_diff_tree",
        "eval_grad_tree_array": "eval_grad_constants",
        "node_to_symbolic": "to_sympy",
        "symbolic_to_node": "from_sympy",
        "simplify_tree": "simplify_tree",
        "combine_operators": "combine_operators",
        "gen_random_tree_fixed_size": "gen_random_tree_fixed_size",
    }
    for ref_name, ours in analogs.items():
        assert hasattr(sr, ours), (ref_name, ours)


def test_operator_library_importable():
    # reference exports the scalar operator fns (plus, safe_log, ...);
    # ours live one module down with the same names
    from symbolicregression_jl_tpu.ops import operators as O

    for name in (
        "safe_pow", "safe_log", "safe_log2", "safe_log10", "safe_log1p",
        "safe_acosh", "safe_sqrt", "atanh_clip", "gamma_op", "erf_op",
        "erfc_op",
    ):
        assert callable(getattr(O, name)), name


def test_api_reference_current():
    """The generated API page covers __all__ exactly and is committed in
    sync with the docstrings (the reference's generated-docs guarantee,
    /root/reference/docs/make.jl:8-35)."""
    import importlib
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scripts_dir = os.path.join(repo, "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        gen = importlib.import_module("gen_api_reference")
    finally:
        # remove the exact entry: the module's own body inserts REPO at
        # index 0, so a positional pop would strip that instead and leak
        # scripts/ onto sys.path for the rest of the session
        sys.path.remove(scripts_dir)
    text = gen.generate()
    for name in sr.__all__:
        assert f"### `{name}`" in text, f"{name} missing from generated page"
    with open(os.path.join(repo, "docs", "api_reference.md")) as f:
        committed = f.read()
    assert committed == text, (
        "docs/api_reference.md out of date — run "
        "python scripts/gen_api_reference.py"
    )


def test_simplify_combine_roundtrip():
    import jax

    ops = sr.make_operator_set(["+", "*"], ["cos"])
    t = sr.encode_tree(sr.parse_expression("(x0 + 1.0) + 2.0", ops), 24)
    t2, ch = sr.combine_operators(t, ops)
    s = sr.tree_to_string(jax.device_get(t2), ops)
    assert bool(ch) and "3" in s, s
