"""Loss library vs closed forms (analog of reference test/test_losses.jl:
elementwise + weighted custom losses checked against closed-form values)."""

import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.ops.losses import (
    LOSS_REGISTRY,
    aggregate_loss,
    resolve_loss,
)


def test_l2_closed_form():
    f = LOSS_REGISTRY["L2DistLoss"]
    pred = jnp.asarray([1.0, 2.0, 3.0])
    targ = jnp.asarray([0.0, 2.0, 5.0])
    np.testing.assert_allclose(np.asarray(f(pred, targ)), [1.0, 0.0, 4.0])


def test_l1_closed_form():
    f = LOSS_REGISTRY["L1DistLoss"]
    pred = jnp.asarray([1.0, -2.0])
    targ = jnp.asarray([0.0, 2.0])
    np.testing.assert_allclose(np.asarray(f(pred, targ)), [1.0, 4.0])


def test_huber_quadratic_then_linear():
    f = LOSS_REGISTRY["HuberLoss"]  # delta=1
    # |r|<=1: r^2/2 ; else delta*(|r| - delta/2)
    r_small = np.asarray(f(jnp.asarray([0.5]), jnp.asarray([0.0])))
    r_big = np.asarray(f(jnp.asarray([3.0]), jnp.asarray([0.0])))
    np.testing.assert_allclose(r_small, [0.125])
    np.testing.assert_allclose(r_big, [2.5])


def test_quantile_pinball():
    f = LOSS_REGISTRY["QuantileLoss"]  # tau = 0.5
    over = np.asarray(f(jnp.asarray([2.0]), jnp.asarray([0.0])))
    under = np.asarray(f(jnp.asarray([-2.0]), jnp.asarray([0.0])))
    np.testing.assert_allclose(over, under)  # symmetric at tau=0.5


def test_margin_losses_signs():
    # margin losses consume agreement = pred*target
    hinge = LOSS_REGISTRY["L1HingeLoss"]
    assert float(hinge(jnp.asarray([2.0]), jnp.asarray([1.0]))[0]) == 0.0
    assert float(hinge(jnp.asarray([-1.0]), jnp.asarray([1.0]))[0]) == 2.0


def test_all_registered_losses_finite_on_generic_input():
    pred = jnp.asarray([0.3, -1.2, 2.0, 0.0])
    targ = jnp.asarray([1.0, -1.0, 1.0, -1.0])
    for name, fn in LOSS_REGISTRY.items():
        out = np.asarray(fn(pred, targ))
        assert out.shape == (4,), name
        assert np.all(np.isfinite(out)), name


def test_weighted_aggregation():
    elem = jnp.asarray([1.0, 3.0])
    w = jnp.asarray([1.0, 3.0])
    assert float(aggregate_loss(elem, None)) == pytest.approx(2.0)
    assert float(aggregate_loss(elem, w)) == pytest.approx(2.5)


def test_resolve_loss_accepts_callable_and_name():
    fn = resolve_loss("L2DistLoss")
    assert callable(fn)
    custom = lambda p, t: (p - t) ** 4
    assert resolve_loss(custom) is custom
    with pytest.raises((KeyError, ValueError)):
        resolve_loss("NoSuchLoss")


def test_log_cosh_loss_matches_naive():
    f = LOSS_REGISTRY["LogCoshLoss"]
    d = np.array([-30.0, -2.0, -0.1, 0.0, 0.1, 2.0, 30.0], np.float32)
    got = np.asarray(f(jnp.asarray(d), jnp.zeros_like(jnp.asarray(d))))
    # naive log(cosh) overflows beyond |d| ~ 88; compare where it doesn't
    want = np.log(np.cosh(d.astype(np.float64)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lp_dist_loss_default_is_squared():
    f = LOSS_REGISTRY["LPDistLoss"]
    p = jnp.asarray([1.0, -3.0])
    t = jnp.asarray([0.5, 1.0])
    np.testing.assert_allclose(
        np.asarray(f(p, t)), [0.25, 16.0], rtol=1e-6
    )
