"""srkey — the Options compile-identity contract checker (ISSUE 18).

Covers: classification-registry completeness (and failure on injected
holes), _graph_key AST coverage, per-field key/scalar semantics,
memo-fingerprint coverage, the callable-token fix for the id()-reuse
aliasing hazard (SR011), the SR010/SR011 lint rules on their fixtures,
and the CLI wiring (`--only keys`, comma-separated engine subsets).

The differential-tracing runs (every production program traced three
times per config) are slow-marked; everything else is registry/AST/
constructor work on CPU."""

import gc
import json
import os
import subprocess
import sys

import pytest

from symbolicregression_jl_tpu.analysis import lint_paths
from symbolicregression_jl_tpu.analysis.keys import (
    ALT_SPECS,
    _graph_key_reads,
    check_keys,
)
from symbolicregression_jl_tpu.models.options import (
    GRAPH_FIELDS,
    ORCHESTRATION_FIELDS,
    TRACED_SCALAR_FIELDS,
    Options,
    callable_token,
    make_options,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "srlint_fixtures")


def _lint_fixture(name):
    return lint_paths(
        FIXTURES, files=[os.path.join(FIXTURES, name)], repo_root=REPO
    )


def _active(violations, rule=None):
    return [
        v for v in violations
        if not v.suppressed and (rule is None or v.rule_id == rule)
    ]


# ---------------------------------------------------------------------------
# classification registry
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_registry_complete_and_disjoint():
    import dataclasses

    actual = {f.name for f in dataclasses.fields(Options)}
    declared = (
        set(GRAPH_FIELDS) | set(TRACED_SCALAR_FIELDS)
        | set(ORCHESTRATION_FIELDS)
    )
    assert declared == actual
    assert not set(GRAPH_FIELDS) & set(TRACED_SCALAR_FIELDS)
    assert not set(GRAPH_FIELDS) & set(ORCHESTRATION_FIELDS)
    assert not set(TRACED_SCALAR_FIELDS) & set(ORCHESTRATION_FIELDS)
    # traced_scalars()' tuple IS the scalar registry, in order
    assert len(TRACED_SCALAR_FIELDS) == len(
        make_options(verbosity=0).traced_scalars()
    )


@pytest.mark.fast
def test_injected_unclassified_field_fails_fast():
    r = check_keys(
        trace=False,
        _override=(
            tuple(f for f in GRAPH_FIELDS if f != "maxsize"),
            TRACED_SCALAR_FIELDS,
            ORCHESTRATION_FIELDS,
        ),
    )
    assert not r["ok"]
    assert any("UNCLASSIFIED" in p and "maxsize" in p for p in r["problems"])
    # fail-fast: a broken registry skips the downstream checks
    assert "semantics" not in r and r["traced"] is False


@pytest.mark.fast
def test_injected_double_classification_fails():
    r = check_keys(
        trace=False,
        _override=(
            GRAPH_FIELDS,
            TRACED_SCALAR_FIELDS,
            ORCHESTRATION_FIELDS + ("maxsize",),
        ),
    )
    assert not r["ok"]
    assert any("doubly classified" in p for p in r["problems"])


@pytest.mark.fast
def test_injected_unknown_field_fails():
    r = check_keys(
        trace=False,
        _override=(
            GRAPH_FIELDS + ("no_such_knob",),
            TRACED_SCALAR_FIELDS,
            ORCHESTRATION_FIELDS,
        ),
    )
    assert not r["ok"]
    assert any("no such field" in p for p in r["problems"])


# ---------------------------------------------------------------------------
# _graph_key coverage + per-field semantics (no tracing)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_graph_key_covers_exactly_the_graph_fields():
    reads = set(_graph_key_reads())
    assert set(GRAPH_FIELDS) <= reads
    assert not reads & set(ORCHESTRATION_FIELDS)
    assert not reads & set(TRACED_SCALAR_FIELDS)


def test_check_keys_semantics_green_without_tracing():
    r = check_keys(trace=False)
    assert r["ok"], r["problems"]
    assert r["semantics"]["missing_specs"] == []
    # every classified field was perturbed and behaved per its class
    assert r["semantics"]["checked"] == len(GRAPH_FIELDS) + len(
        TRACED_SCALAR_FIELDS
    ) + len(ORCHESTRATION_FIELDS)
    # memo-fingerprint coverage ran too
    assert "eval_backend" in r["fingerprint"]["covered"]
    assert any("tracing skipped" in n for n in r["notes"])


@pytest.mark.fast
def test_every_field_has_a_perturbation_spec():
    for field in (
        GRAPH_FIELDS + TRACED_SCALAR_FIELDS + ORCHESTRATION_FIELDS
    ):
        assert field in ALT_SPECS, field


@pytest.mark.fast
def test_misclassified_orchestration_field_is_flagged():
    # 'annealing' pretends to be orchestration: it is read in _graph_key
    # (coverage) and its perturbation changes the key (semantics)
    r = check_keys(
        trace=False,
        _override=(
            tuple(f for f in GRAPH_FIELDS if f != "annealing"),
            TRACED_SCALAR_FIELDS,
            ORCHESTRATION_FIELDS + ("annealing",),
        ),
    )
    assert not r["ok"]
    assert any(
        "annealing" in p and "_graph_key" in p for p in r["problems"]
    )


@pytest.mark.fast
def test_misclassified_graph_field_is_flagged():
    # 'seed' pretends to be graph: absent from the key AND its
    # perturbation does not change the key
    r = check_keys(
        trace=False,
        _override=(
            GRAPH_FIELDS + ("seed",),
            TRACED_SCALAR_FIELDS,
            tuple(f for f in ORCHESTRATION_FIELDS if f != "seed"),
        ),
    )
    assert not r["ok"]
    assert any("seed" in p and "ABSENT" in p for p in r["problems"])
    assert any(
        "seed" in p and "does NOT change" in p for p in r["problems"]
    )


# ---------------------------------------------------------------------------
# callable_token: the SR011 fix
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_callable_token_stable_and_distinct():
    f = lambda x: x  # noqa: E731
    g = lambda x: -x  # noqa: E731
    assert callable_token(f) == callable_token(f)
    assert callable_token(f) != callable_token(g)


@pytest.mark.fast
def test_callable_token_never_aliases_after_gc():
    # the id()-reuse hazard: delete the first callable, allocate more —
    # CPython may hand a new lambda the dead one's id(); the token
    # registry pins a strong reference, so tokens never collide
    tok1 = callable_token(lambda x: x + 1)
    gc.collect()
    tokens = {tok1}
    for i in range(64):
        t = callable_token(lambda x, i=i: x * i)
        assert t not in tokens
        tokens.add(t)
        gc.collect()


@pytest.mark.fast
def test_graph_key_distinguishes_distinct_custom_losses():
    f = lambda tree, X, y, w, o: 0.0  # noqa: E731
    a = make_options(loss_function=f, verbosity=0)
    del f
    gc.collect()
    g = lambda tree, X, y, w, o: 1.0  # noqa: E731
    b = make_options(loss_function=g, verbosity=0)
    assert a._graph_key() != b._graph_key()
    # non-callable configs: same kwargs -> byte-identical keys
    assert (
        make_options(loss="L1DistLoss", verbosity=0)._graph_key()
        == make_options(loss="L1DistLoss", verbosity=0)._graph_key()
    )


@pytest.mark.fast
def test_memo_fingerprint_distinguishes_distinct_losses():
    import numpy as np

    from symbolicregression_jl_tpu.cache.memo import dataset_fingerprint

    X = np.ones((2, 16), dtype=np.float32)
    y = np.ones(16, dtype=np.float32)
    f = lambda tree, X, y, w, o: 0.0  # noqa: E731
    a = make_options(loss_function=f, verbosity=0)
    fp_a = dataset_fingerprint(X, y, None, a)
    assert fp_a == dataset_fingerprint(X, y, None, a)  # stable
    del f
    gc.collect()
    g = lambda tree, X, y, w, o: 1.0  # noqa: E731
    b = make_options(loss_function=g, verbosity=0)
    assert fp_a != dataset_fingerprint(X, y, None, b)


# ---------------------------------------------------------------------------
# SR010 / SR011 lint rules
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_sr010_orchestration_read_in_jit_detected():
    vs = _lint_fixture("fixture_sr010.py")
    hits = _active(vs, "SR010")
    assert len(hits) == 3, [v.to_dict() for v in vs]
    assert {v.line for v in hits} == {20, 26, 38}
    # reachable through the call graph, attribute receivers covered
    assert any(v.function == "_inner" for v in hits)
    assert not any(
        v.function in ("good_graph_read", "good_other_receiver",
                       "host_only")
        for v in hits
    )
    sup = [v for v in vs if v.suppressed and v.rule_id == "SR010"]
    assert len(sup) == 1 and sup[0].line == 55


@pytest.mark.fast
def test_sr011_callable_id_in_key_detected():
    vs = _lint_fixture("fixture_sr011.py")
    hits = _active(vs, "SR011")
    assert len(hits) == 4, [v.to_dict() for v in vs]
    assert {v.line for v in hits} == {10, 15, 21, 26}
    # host code is NOT exempt, but non-keyish names and shadowed id are
    assert not any(
        v.function in ("ordinary_helper", "shadowed_key",
                       "good_token_key")
        for v in hits
    )
    sup = [v for v in vs if v.suppressed and v.rule_id == "SR011"]
    assert len(sup) == 1 and sup[0].line == 49


@pytest.mark.fast
def test_sr012_sharding_constraint_in_batched_body_detected():
    vs = _lint_fixture("fixture_sr012.py")
    hits = _active(vs, "SR012")
    assert len(hits) == 4, [v.to_dict() for v in vs]
    assert {v.function for v in hits} == {
        "batched_body", "batched_named", "scan_body", "_inner_helper"
    }
    # mesh-as-parameter, local mesh, and never-batched hosts are exempt
    assert not any(
        v.function in ("good_param_mesh", "good_local_mesh",
                       "host_constrain", "driver")
        for v in hits
    )
    # every active hit names the offending outer mesh object
    assert all("MESH" in v.message for v in hits)
    sup = [v for v in vs if v.suppressed and v.rule_id == "SR012"]
    assert len(sup) == 1 and sup[0].function == "pragma_body"


@pytest.mark.fast
def test_package_clean_under_sr010_sr011_sr012():
    from symbolicregression_jl_tpu.analysis import lint_package

    vs = lint_package()
    assert not _active(vs, "SR010"), [v.to_dict() for v in vs]
    assert not _active(vs, "SR011"), [v.to_dict() for v in vs]
    # the production tenant-vmapped iteration takes its mesh as a
    # parameter (inner_mesh) — SR012's exemption — so the package scans
    # clean; a constraint naming an outer mesh inside a batched body
    # would fail here before srshard's compile-time census sees it
    assert not _active(vs, "SR012"), [v.to_dict() for v in vs]


# ---------------------------------------------------------------------------
# report + CLI wiring
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_parse_only_accepts_comma_subsets():
    import argparse

    from symbolicregression_jl_tpu.analysis import _parse_only

    assert _parse_only("keys") == frozenset({"keys"})
    assert _parse_only("lint,keys") == frozenset({"lint", "keys"})
    with pytest.raises(argparse.ArgumentTypeError):
        _parse_only("bogus")
    with pytest.raises(argparse.ArgumentTypeError):
        _parse_only(",")


@pytest.mark.fast
def test_report_gates_on_keys_section():
    from symbolicregression_jl_tpu.analysis import AnalysisReport

    bad = AnalysisReport(keys={"ok": False, "problems": ["x"]})
    assert bad.ok is False
    good = AnalysisReport(keys={"ok": True, "problems": []})
    assert good.ok is True
    payload = json.loads(good.to_json())
    assert payload["keys"] == {"ok": True, "problems": []}
    text = AnalysisReport(keys={
        "ok": True, "problems": [], "notes": [],
        "fields": {"graph": 46, "traced_scalar": 8, "orchestration": 28},
        "traced": True,
        "configs": {"base": {
            "orchestration_invariant": True, "scalar_invariant": True,
            "culprits": [],
        }},
    }).to_text()
    assert "srkey: ok" in text and "orchestration invariant" in text


@pytest.mark.fast
def test_cli_engine_subset_selection(monkeypatch, capsys):
    import symbolicregression_jl_tpu.analysis as A
    from symbolicregression_jl_tpu.analysis.__main__ import main

    calls = {}

    def fake_run(**kw):
        calls.update(kw)
        return A.AnalysisReport()

    monkeypatch.setattr(A, "run_analysis", fake_run)
    assert main(["--only", "lint,keys", "--format", "json"]) == 0
    capsys.readouterr()
    assert calls["lint"] and calls["keys"]
    assert not (calls["surface"] or calls["memory"] or calls["cost"])


# ---------------------------------------------------------------------------
# differential tracing (slow: traces every production program 3x/config)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_check_keys_green_with_differential_tracing():
    r = check_keys()
    assert r["ok"], r["problems"]
    assert r["traced"]
    for name in ("base", "tenants2"):
        entry = r["configs"][name]
        assert entry["orchestration_invariant"], name
        assert entry["scalar_invariant"], name
        assert entry["culprits"] == []
        # the fused iteration traces alongside every phased stage
        assert "iteration" in entry["stages"]


@pytest.mark.slow
def test_differential_tracing_catches_injected_leak():
    # misclassify 'annealing' as orchestration: the combined-orch trace
    # must mismatch and the bisection must name exactly that field
    r = check_keys(
        configs=(("base", {}),),
        _override=(
            tuple(f for f in GRAPH_FIELDS if f != "annealing"),
            TRACED_SCALAR_FIELDS,
            ORCHESTRATION_FIELDS + ("annealing",),
        ),
    )
    assert not r["ok"]
    entry = r["configs"]["base"]
    assert entry["orchestration_invariant"] is False
    assert entry["culprits"] == ["annealing"]
    assert any(
        "changed traced program" in p and "annealing" in p
        for p in r["problems"]
    )


@pytest.mark.slow
def test_cli_only_keys_green():
    """Acceptance: `python -m symbolicregression_jl_tpu.analysis --only
    keys` exits 0 on the repo and reports the srkey JSON section."""
    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.analysis",
         "--only", "keys", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=870,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["keys"]["ok"] is True
    assert payload["keys"]["traced"] is True
    assert payload["surface"] is None and payload["memory"] is None
