"""Resume logic of the standing TPU evidence watcher
(scripts/tpu_watcher.py): a restarted watcher must never burn a tunnel
window re-running finished work, must never silently trust stale or
mismatched records, and must persist its attempt caps. Pure host-side
logic — no jax import, no tunnel."""

import datetime
import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def watcher(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "tpu_watcher_under_test",
        os.path.join(REPO, "scripts", "tpu_watcher.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.RESULT_PATH = str(tmp_path / "BENCH_TPU_LATEST.json")
    return mod


def write_capture(watcher, steps, complete=False, captured_at=None):
    payload = {
        "captured_at": captured_at
        or datetime.datetime.now().isoformat(timespec="seconds"),
        "complete": complete,
        "steps": steps,
    }
    with open(watcher.RESULT_PATH, "w") as f:
        json.dump(payload, f)


def clean_rec(watcher, name):
    argv = {s[0]: [str(a) for a in s[1]] for s in watcher.STEPS}[name]
    return {
        "on_chip": True,
        "rc": 0,
        "partial": False,
        "timed_out": False,
        "attempts": 1,
        "argv": argv,
    }


def resume_state(watcher):
    """(done, attempts, started) via the watcher's OWN derivation."""
    results, started = watcher.load_previous_results()
    _, done, attempts, _ = watcher.compute_resume_state(results)
    return done, attempts, started


def test_clean_records_resume(watcher):
    write_capture(
        watcher,
        {
            "bench": clean_rec(watcher, "bench"),
            "tpu_tests": clean_rec(watcher, "tpu_tests"),
        },
    )
    done, attempts, started = resume_state(watcher)
    assert done == {"bench", "tpu_tests"}
    assert started is not None


def test_complete_capture_never_resumes(watcher):
    write_capture(
        watcher, {"bench": clean_rec(watcher, "bench")}, complete=True
    )
    assert watcher.load_previous_results() == ({}, None)


def test_stale_capture_never_resumes(watcher):
    old = (
        datetime.datetime.now() - datetime.timedelta(hours=30)
    ).isoformat(timespec="seconds")
    write_capture(
        watcher, {"bench": clean_rec(watcher, "bench")}, captured_at=old
    )
    assert watcher.load_previous_results() == ({}, None)


def test_malformed_files_fall_back_fresh(watcher):
    for payload in (
        {"captured_at": "2026-08-01T00:00:00", "steps": ["not", "a", "dict"]},
        {"steps": {"bench": clean_rec(watcher, "bench")}},  # no timestamp
    ):
        with open(watcher.RESULT_PATH, "w") as f:
            json.dump(payload, f)
        assert watcher.load_previous_results() == ({}, None)
    with open(watcher.RESULT_PATH, "w") as f:
        f.write("{corrupt json")
    assert watcher.load_previous_results() == ({}, None)


def test_non_dict_record_skipped(watcher):
    write_capture(
        watcher,
        {"bench": clean_rec(watcher, "bench"), "suite": "garbage"},
    )
    steps, _ = watcher.load_previous_results()
    assert set(steps) == {"bench"}


def test_argv_mismatch_is_stale(watcher):
    rec = clean_rec(watcher, "rows_sweep")
    rec["argv"] = rec["argv"][:-2]  # pre-r5 sweep without --rows-max
    write_capture(watcher, {"rows_sweep": rec})
    done, _, _ = resume_state(watcher)
    assert done == set()


def test_missing_argv_is_stale(watcher):
    rec = clean_rec(watcher, "bench")
    del rec["argv"]
    write_capture(watcher, {"bench": rec})
    done, _, _ = resume_state(watcher)
    assert done == set()


def test_orphan_step_name_is_stale(watcher):
    rec = clean_rec(watcher, "bench")
    write_capture(watcher, {"renamed_step": rec})
    done, _, _ = resume_state(watcher)
    assert done == set()


def test_exhausted_partial_not_rerun_and_attempts_restored(watcher):
    bad = clean_rec(watcher, "scale_bisect")
    bad.update(partial=True, rc=1, on_chip=False,
               attempts=watcher.MAX_ATTEMPTS)
    retry = clean_rec(watcher, "suite")
    retry.update(partial=True, rc=1, attempts=1)
    write_capture(watcher, {"scale_bisect": bad, "suite": retry})
    done, attempts, _ = resume_state(watcher)
    assert done == {"scale_bisect"}  # cap hit: recorded, never re-run
    assert attempts["suite"] == 1  # cap continues, not reset


def test_step_order_round5_policy(watcher):
    """One short canary (bench), then the scale-fault bisect FIRST —
    localizing the two-round 64x1000 fault is the round's defining job
    (VERDICT r4 #1) — then the isolated suite whose northstar rows the
    bisect unblocks; feynman_scale last because its per-case --resume
    makes it the only step whose partial progress survives a tunnel
    drop."""
    names = [s[0] for s in watcher.STEPS]
    assert names.index("bench") < names.index("scale_bisect")
    assert names.index("scale_bisect") < names.index("suite")
    assert names.index("suite") < names.index("tpu_tests")
    assert names[-1] == "feynman_scale"


def test_all_records_stale_resets_epoch(watcher, monkeypatch):
    """A capture whose every record is dropped as stale must NOT inherit
    the old file's first_captured_at — a 23h-old inherited epoch would
    spuriously trip the 24h guard on the next restart."""
    old = (
        datetime.datetime.now() - datetime.timedelta(hours=23)
    ).isoformat(timespec="seconds")
    rec = clean_rec(watcher, "bench")
    del rec["argv"]  # pre-upgrade format: dropped as stale
    write_capture(watcher, {"bench": rec}, captured_at=old)

    saved = []
    monkeypatch.setattr(
        watcher,
        "save_and_commit",
        lambda results, done, first_captured_at=None: saved.append(
            first_captured_at
        ),
    )
    monkeypatch.setattr(
        watcher, "probe_platform", lambda timeout=90: None
    )
    monkeypatch.setattr(sys, "argv", ["tpu_watcher.py"])
    # with the tunnel probed down, main() loops forever — grab the epoch
    # it pinned by interrupting the first sleep
    def stop(_):
        raise KeyboardInterrupt

    monkeypatch.setattr(watcher.time, "sleep", stop)
    with pytest.raises(KeyboardInterrupt):
        watcher.main()
    # epoch was re-pinned to now, not inherited: a subsequent
    # load_previous_results on a file stamped now must not be stale
    results, _, _, _ = watcher.compute_resume_state({})
    assert results == {}  # sanity on the helper contract


def test_jsonless_retry_preserves_prior_on_chip_json(watcher):
    """The retry merge: a json-less failure must carry forward the
    earlier attempt's on-chip JSON (hours of finished feynman cases)
    instead of overwriting it in the payload."""
    prev = {
        "json": [{"case": "I.8.14", "platform": "tpu", "solved": True}],
        "on_chip": True,
        "partial": True,
        "rc": 1,
        "attempts": 1,
    }
    rec = {"json": [], "on_chip": False, "partial": True, "rc": 1,
           "attempts": 2}
    watcher.merge_retry_record(prev, rec)
    assert rec["json"] == prev["json"]
    assert rec["on_chip"] is True
    assert rec["json_from_earlier_attempt"]

    # a retry that produced its own json keeps it
    rec2 = {"json": [{"case": "x"}], "on_chip": True, "partial": True}
    watcher.merge_retry_record(prev, rec2)
    assert rec2["json"] == [{"case": "x"}]
    assert "json_from_earlier_attempt" not in rec2

    # no prior record: no-op
    rec3 = {"json": [], "on_chip": False}
    watcher.merge_retry_record(None, rec3)
    assert rec3["json"] == []


def test_jsonless_retry_preserves_prior_telemetry(watcher):
    """The supervised-resume progress memory must survive a
    telemetry-less crash between attempts: without the carry, the next
    no-progress resumable fault would look like a FIRST snapshot and
    re-zero the attempt cap forever."""
    prev = {"telemetry": {"classification": "resumable",
                          "last_saved_iteration": 5},
            "json": [], "partial": True, "attempts": 1}
    rec = {"json": [], "partial": True, "attempts": 2}
    watcher.merge_retry_record(prev, rec)
    assert rec["telemetry"]["last_saved_iteration"] == 5
    assert rec["telemetry_from_earlier_attempt"]
    # a retry with its own telemetry keeps it
    rec2 = {"telemetry": {"classification": "dead"}}
    watcher.merge_retry_record(prev, rec2)
    assert rec2["telemetry"]["classification"] == "dead"
    assert "telemetry_from_earlier_attempt" not in rec2
    # the accounting consequence: after the carry, a resumable fault
    # stuck at the same iteration does NOT reset the counter
    assert watcher.adjust_attempts_for_resume(
        rec, _tele_rec("resumable", 5), 2
    ) == 2


def _tele_rec(classification, last_saved_iteration=None):
    return {
        "telemetry": {
            "classification": classification,
            "last_saved_iteration": last_saved_iteration,
        }
    }


def test_supervised_resume_attempt_accounting(watcher):
    """ISSUE 11 satellite: a supervised resume must not burn an attempt
    from MAX_ATTEMPTS the way a dead restart does — resume WITH progress
    (snapshot advanced) resets the counter; resume WITHOUT progress
    keeps the decrement (crash loops still terminate)."""
    adjust = watcher.adjust_attempts_for_resume
    # first snapshot ever = progress: reset
    assert adjust(None, _tele_rec("resumable", 3), 2) == 0
    # snapshot advanced past the previous attempt's: reset
    assert adjust(
        _tele_rec("resumable", 3), _tele_rec("resumable", 7), 2
    ) == 0
    # resumable but the snapshot never moved: keep the decrement
    assert adjust(
        _tele_rec("resumable", 7), _tele_rec("resumable", 7), 2
    ) == 2
    assert adjust(
        _tele_rec("resumable", 7), _tele_rec("resumable", 5), 2
    ) == 2
    # non-resumable classifications are untouched
    assert adjust(None, _tele_rec("dead"), 2) == 2
    assert adjust(None, _tele_rec("in-flight", 4), 2) == 2
    # resumable with no iteration evidence: no reset (no proof of
    # progress), and records without telemetry are untouched
    assert adjust(None, _tele_rec("resumable"), 2) == 2
    assert adjust(None, {}, 1) == 1
    assert adjust(None, None, 1) == 1


def _write_events(dirpath, name, events):
    import json as _json

    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with open(path, "w") as f:
        for e in events:
            f.write(_json.dumps(e) + "\n")
    return path


def test_telemetry_verdict_kill_with_snapshot_is_resumable(
    watcher, tmp_path
):
    """A SIGKILLed run writes no dispatch_fault — the log just stops.
    With saved_state events in the trail the step is RESUMABLE (the
    supervised-resume path), and last_saved_iteration carries the
    progress signal the attempt accounting compares."""
    d = str(tmp_path / "tele")
    ev = [
        {"v": 1, "t": 1.0, "run": "r", "type": "run_start",
         "backend": "cpu"},
        {"v": 1, "t": 2.0, "run": "r", "type": "saved_state",
         "outputs": 1, "iteration": 2, "path": "s.ckpt"},
        {"v": 1, "t": 3.0, "run": "r", "type": "saved_state",
         "outputs": 1, "iteration": 4, "path": "s.ckpt"},
    ]
    _write_events(d, "events-a.jsonl", ev)
    tv = watcher.read_telemetry_verdict(d, since_ts=0.0)
    assert tv["classification"] == "resumable"
    assert tv["last_saved_iteration"] == 4
    assert tv["saved_states"] == 2

    # a run_end flips it to completed; a fault with no snapshot is dead
    _write_events(
        d, "events-a.jsonl",
        ev + [{"v": 1, "t": 4.0, "run": "r", "type": "run_end",
               "num_evals": 1, "search_time_s": 1.0}],
    )
    assert watcher.read_telemetry_verdict(d, 0.0)[
        "classification"] == "completed"
    _write_events(
        d, "events-a.jsonl",
        [ev[0], {"v": 1, "t": 2.0, "run": "r", "type": "dispatch_fault",
                 "where": "iteration", "error_type": "XlaRuntimeError"}],
    )
    tv = watcher.read_telemetry_verdict(d, 0.0)
    assert tv["classification"] == "dead"
    assert tv["last_saved_iteration"] is None

    # killed with NOTHING recoverable stays in-flight (dead restart)
    _write_events(d, "events-a.jsonl", [ev[0]])
    assert watcher.read_telemetry_verdict(d, 0.0)[
        "classification"] == "in-flight"

    fault = {"v": 1, "t": 2.5, "run": "r", "type": "dispatch_fault",
             "where": "iteration", "error_type": "FaultInjected"}
    done = {"v": 1, "t": 4.0, "run": "r2", "type": "run_end",
            "num_evals": 1, "search_time_s": 1.0}
    # the supervised success trail — faulted attempt's log + resumed
    # attempt's run_end AFTER it in the same window — reads COMPLETED
    _write_events(d, "events-a.jsonl", ev + [fault, done])
    assert watcher.read_telemetry_verdict(d, 0.0)[
        "classification"] == "completed"
    # ...but a fault NEWER than the last run_end (a later sub-run
    # dying) still reads resumable
    _write_events(
        d, "events-a.jsonl",
        [ev[0], dict(done, t=1.5)] + ev[1:] + [fault],
    )
    assert watcher.read_telemetry_verdict(d, 0.0)[
        "classification"] == "resumable"
    # ...and so does a KILL after an earlier sub-run completed: the
    # snapshots postdate the last run_end (no fault event, the killed
    # run's log simply stops) — an early completed case in the window
    # must not mask the preempted-but-progressing one
    _write_events(
        d, "events-a.jsonl", [ev[0], dict(done, t=1.5)] + ev[1:],
    )
    assert watcher.read_telemetry_verdict(d, 0.0)[
        "classification"] == "resumable"


def test_finalize_when_fully_covered(watcher, monkeypatch):
    write_capture(
        watcher, {s[0]: clean_rec(watcher, s[0]) for s in watcher.STEPS}
    )
    calls = []
    monkeypatch.setattr(
        watcher,
        "save_and_commit",
        lambda results, done, first_captured_at=None: calls.append(
            (done, set(results), first_captured_at)
        ),
    )
    monkeypatch.setattr(sys, "argv", ["tpu_watcher.py"])
    watcher.main()
    assert len(calls) == 1
    done, names, started = calls[0]
    assert done is True
    assert names == {s[0] for s in watcher.STEPS}
    assert started is not None
