"""On-device tree surgery property tests.

Parity targets: reference test/test_crossover.jl (conservation of symbols),
mutation semantics of src/MutationFunctions.jl, simplify equivalence
(test/test_simplification.jl)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.mutate_device import (
    append_random_op,
    crossover_trees,
    delete_random_op,
    gen_random_tree_fixed_size,
    insert_random_op,
    mutate_constant,
    mutate_operator,
    prepend_random_op,
    simplify_tree,
)
from symbolicregression_jl_tpu.models.trees import (
    CONST,
    VAR,
    Expr,
    decode_tree,
    encode_tree,
    expr_to_string,
    is_valid_postfix,
    stack_trees,
)
from symbolicregression_jl_tpu.ops.eval_numpy import eval_expr_numpy
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])
L = 24
NFEAT = 5


def random_tree(rng, size=None):
    size = size or int(rng.integers(1, 14))
    return encode_tree(random_expr_fixed_size(rng, OPS, NFEAT, size), L)


def keys(n, seed=0):
    return jax.random.split(jax.random.PRNGKey(seed), n)


def test_gen_random_tree_validity():
    gen = jax.jit(
        lambda k, s: gen_random_tree_fixed_size(k, s, NFEAT, OPS, L)
    )
    for i, k in enumerate(keys(40)):
        target = 1 + i % 15
        t = gen(k, target)
        assert is_valid_postfix(t), f"invalid tree at {i}"
        # size lands within overshoot-by-1 of target
        assert 1 <= int(t.length) <= target + 1


def test_mutate_constant_changes_one_constant(rng):
    f = jax.jit(
        lambda k, t: mutate_constant(k, t, jnp.float32(1.0), 0.076, 0.01)
    )
    hits = 0
    for k in keys(30):
        t = random_tree(rng)
        t2, ok = f(k, t)
        n_const = int(np.sum((np.asarray(t.kind) == CONST)))
        if n_const == 0:
            assert not bool(ok)
            continue
        hits += 1
        assert bool(ok)
        assert is_valid_postfix(t2)
        diff = np.sum(np.asarray(t.cval) != np.asarray(t2.cval))
        assert diff == 1
        # structure untouched
        np.testing.assert_array_equal(np.asarray(t.kind), np.asarray(t2.kind))
    assert hits > 5


def test_mutate_operator_same_arity(rng):
    f = jax.jit(lambda k, t: mutate_operator(k, t, OPS))
    for k in keys(30):
        t = random_tree(rng, size=9)
        t2, ok = f(k, t)
        assert bool(ok)
        assert is_valid_postfix(t2)
        np.testing.assert_array_equal(np.asarray(t.kind), np.asarray(t2.kind))
        changed = np.asarray(t.op) != np.asarray(t2.op)
        assert changed.sum() <= 1


def test_append_random_op(rng):
    f = jax.jit(lambda k, t: append_random_op(k, t, NFEAT, OPS))
    for k in keys(30):
        t = random_tree(rng)
        t2, ok = f(k, t)
        if bool(ok):
            assert is_valid_postfix(t2)
            delta = int(t2.length) - int(t.length)
            assert delta in (1, 2)  # unary leaf->op(leaf): +1; binary: +2


def test_insert_and_prepend(rng):
    fi = jax.jit(lambda k, t: insert_random_op(k, t, NFEAT, OPS))
    fp = jax.jit(lambda k, t: prepend_random_op(k, t, NFEAT, OPS))
    for k in keys(30):
        t = random_tree(rng)
        for f in (fi, fp):
            t2, ok = f(k, t)
            if bool(ok):
                assert is_valid_postfix(t2)
                delta = int(t2.length) - int(t.length)
                assert delta in (1, 2)


def test_prepend_puts_old_root_under_new_root(rng):
    fp = jax.jit(lambda k, t: prepend_random_op(k, t, NFEAT, OPS))
    t = random_tree(rng, size=7)
    old = expr_to_string(decode_tree(t), OPS)
    for k in keys(10, seed=3):
        t2, ok = fp(k, t)
        if bool(ok):
            s = expr_to_string(decode_tree(t2), OPS)
            assert old in s  # old tree is a contiguous child of the new root


def test_delete_random_op(rng):
    f = jax.jit(lambda k, t: delete_random_op(k, t, NFEAT, OPS))
    for k in keys(40):
        t = random_tree(rng)
        t2, ok = f(k, t)
        assert bool(ok)
        assert is_valid_postfix(t2)
        if int(t.length) > 1:
            assert int(t2.length) < int(t.length)


def test_crossover_validity_and_conservation(rng):
    """Conservation of symbols (reference test/test_crossover.jl:18-45):
    the multiset of nodes in (a', b') equals the multiset in (a, b)."""
    f = jax.jit(lambda k, a, b: crossover_trees(k, a, b))
    n_ok = 0
    for k in keys(100):
        a, b = random_tree(rng), random_tree(rng)
        a2, b2, ok = f(k, a, b)
        if not bool(ok):
            continue
        n_ok += 1
        assert is_valid_postfix(a2) and is_valid_postfix(b2)

        def sig(t):
            n = int(t.length)
            return sorted(
                zip(
                    np.asarray(t.kind)[:n].tolist(),
                    np.asarray(t.op)[:n].tolist(),
                    np.asarray(t.feat)[:n].tolist(),
                    np.round(np.asarray(t.cval)[:n], 5).tolist(),
                )
            )

        assert sorted(sig(a) + sig(b)) == sorted(sig(a2) + sig(b2))
    assert n_ok > 50


def test_simplify_constant_folding():
    # (1 + 2) * x0 -> 3 * x0
    plus, mult = OPS.binary_index("+"), OPS.binary_index("*")
    e = Expr.binary(
        mult, Expr.binary(plus, Expr.const(1.0), Expr.const(2.0)), Expr.var(0)
    )
    t = encode_tree(e, L)
    t2, changed = jax.jit(lambda t: simplify_tree(t, OPS))(t)
    assert bool(changed)
    assert int(t2.length) == 3
    s = expr_to_string(decode_tree(t2), OPS)
    assert s == "(3 * x0)"


def test_simplify_preserves_value(rng):
    f = jax.jit(lambda t: simplify_tree(t, OPS))
    X = rng.standard_normal((NFEAT, 20)).astype(np.float32)
    for _ in range(40):
        t = random_tree(rng)
        t2, changed = f(t)
        assert is_valid_postfix(t2)
        assert int(t2.length) <= int(t.length)
        y1, c1 = eval_expr_numpy(decode_tree(t), X, OPS)
        y2, c2 = eval_expr_numpy(decode_tree(t2), X, OPS)
        if c1 and c2:
            np.testing.assert_allclose(y1, y2, rtol=1e-4, atol=1e-4)


def test_simplify_whole_constant_tree():
    plus = OPS.binary_index("+")
    cos = OPS.unary_index("cos")
    e = Expr.binary(plus, Expr.unary(cos, Expr.const(0.0)), Expr.const(1.0))
    t = encode_tree(e, L)
    t2, changed = simplify_tree(t, OPS)
    assert bool(changed) and int(t2.length) == 1
    assert abs(float(t2.cval[0]) - 2.0) < 1e-6


def test_mutations_under_vmap(rng):
    """All mutations batch cleanly under vmap (the evolution-step usage)."""
    trees = stack_trees([random_tree(rng, size=7) for _ in range(16)])
    ks = jax.random.split(jax.random.PRNGKey(7), 16)
    t2, ok = jax.vmap(lambda k, t: append_random_op(k, t, NFEAT, OPS))(ks, trees)
    assert ok.shape == (16,)
    for i in range(16):
        if bool(ok[i]):
            assert is_valid_postfix(t2[i])


# --------------------------- combine_operators ------------------------------
# (reference combine_operators applied at src/SingleIteration.jl:73-74)

from symbolicregression_jl_tpu.models.mutate_device import combine_operators
from symbolicregression_jl_tpu.ops.interpreter import eval_tree as _eval_tree


def _enc(s, ops, L=24):
    import jax.numpy as _jnp
    from symbolicregression_jl_tpu.models.trees import encode_tree, parse_expression
    return jax.tree_util.tree_map(
        _jnp.asarray, encode_tree(parse_expression(s, ops), L)
    )


def test_combine_constant_add_chain():
    ops = make_operator_set(["+", "-", "*", "/"], [])
    t = _enc("(x0 + 1.0) + 2.0", ops)
    t2, changed = combine_operators(t, ops)
    assert bool(changed)
    assert int(t2.length) == 3  # x0, 3.0, +
    d = decode_tree(jax.tree_util.tree_map(np.asarray, t2))
    s = expr_to_string(d, ops)
    assert "3" in s and "x0" in s


def test_combine_handles_left_constants_commutative():
    ops = make_operator_set(["+", "-", "*", "/"], [])
    t = _enc("2.0 * (3.0 * x0)", ops)  # needs rotation then fold
    t2, _ = combine_operators(t, ops)
    assert int(t2.length) == 3  # x0 * 6 in some order
    X = jnp.asarray(np.linspace(-2, 2, 7, dtype=np.float32)[None])
    y1, _ = _eval_tree(t, X, ops)
    y2, _ = _eval_tree(t2, X, ops)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_combine_sub_div_identities():
    ops = make_operator_set(["+", "-", "*", "/"], [])
    for expr in ["(x0 - 1.5) - 2.5", "(x0 + 1.0) - 3.0", "(x0 / 2.0) / 4.0",
                 "(x0 * 2.0) / 8.0", "(x0 - 1.0) + 5.0", "(x0 / 3.0) * 6.0"]:
        t = _enc(expr, ops)
        t2, changed = combine_operators(t, ops)
        assert bool(changed), expr
        assert int(t2.length) == 3, expr
        X = jnp.asarray(np.linspace(-2, 2, 9, dtype=np.float32)[None])
        y1, _ = _eval_tree(t, X, ops)
        y2, _ = _eval_tree(t2, X, ops)
        np.testing.assert_allclose(
            np.asarray(y1), np.asarray(y2), rtol=1e-5, atol=1e-6,
            err_msg=expr,
        )


def test_combine_preserves_random_tree_values(rng):
    ops = make_operator_set(["+", "-", "*", "/"], ["cos"])
    X = jnp.asarray((rng.standard_normal((3, 40)) * 2).astype(np.float32))
    from symbolicregression_jl_tpu.models.trees import encode_tree, stack_trees
    from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size
    for _ in range(20):
        e = random_expr_fixed_size(rng, ops, 3, int(rng.integers(3, 18)))
        t = jax.tree_util.tree_map(jnp.asarray, encode_tree(e, 24))
        t2, _ = combine_operators(t, ops)
        y1, ok1 = _eval_tree(t, X, ops)
        y2, ok2 = _eval_tree(t2, X, ops)
        if bool(ok1):
            np.testing.assert_allclose(
                np.asarray(y1), np.asarray(y2), rtol=2e-4, atol=1e-4
            )
