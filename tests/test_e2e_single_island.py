"""End-to-end: a single island recovers the reference's precompile workload
target 2*cos(x4) + x1^2 - 2 with loss < 1e-2
(parity: reference test/test_mixed.jl:129-141 quality bar, BASELINE.md).

The iteration here is the full single-island analog of the reference's
worker step — s_r_cycle THEN simplify THEN constant optimization
(src/SingleIteration.jl:17-127): the target's constants (2, -2) are found
by BFGS, not by constant-perturbation mutations alone. A single island is
diversity-limited, so recovery is seed-dependent either way (the robust
multi-island path is covered by test_api/test_mixed); with the optimizer
the test seed converges in ~3 iterations instead of skirting the
threshold, which is what keeps this deterministic engine-level test
stable under PRNG-stream changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.evolve import (
    init_island_state,
    optimize_island_constants,
    s_r_cycle,
    simplify_population,
)
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.trees import is_valid_postfix, tree_to_string


@pytest.mark.slow
def test_recovers_synthetic_target(rng):
    X = (rng.standard_normal((5, 100)) * 2).astype(np.float32)
    y = 2 * np.cos(X[4]) + X[1] ** 2 - 2
    opt = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=66,
        maxsize=18,
        ncycles_per_iteration=300,
    )
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    baseline = float(np.mean((y - y.mean()) ** 2))
    state = init_island_state(
        jax.random.PRNGKey(1), opt, 5, Xj, yj, None, baseline
    )
    cm = jnp.int32(opt.maxsize)

    def one_iteration(st, k):
        st = s_r_cycle(st, cm, Xj, yj, None, baseline, opt)
        st = simplify_population(st, cm, Xj, yj, None, baseline, opt)
        # same helper the production iteration uses (api.py)
        return optimize_island_constants(k, st, Xj, yj, None, baseline, opt)

    step = jax.jit(one_iteration)
    master = jax.random.PRNGKey(7)
    best = np.inf
    for it in range(12):
        master, k_opt = jax.random.split(master)
        state = step(state, k_opt)
        hl, he = np.asarray(state.hof.losses), np.asarray(state.hof.exists)
        best = hl[he].min()
        if best < 1e-2:
            break
    assert best < 1e-2, f"failed to recover target, best loss {best}"

    # all hall-of-fame trees decode as valid postfix programs
    for i in np.where(he)[0]:
        t = jax.tree_util.tree_map(lambda x: x[i], state.hof.trees)
        assert is_valid_postfix(t)
        tree_to_string(t, opt.operators)  # printable

    # population invariants
    assert int(state.pop.npop) == 66
    assert bool(np.isfinite(np.asarray(state.pop.scores)).any())
    assert float(state.num_evals) > 0
