"""equation_search API integration tests.

Parity targets: reference test/full.jl tier — recovery (test_mixed.jl),
multi-output, weighted, resume (test_fast_cycle.jl:29-38), early stop
(test_early_stop.jl), determinism (test_deterministic.jl:27-29), checkpoint
CSV (output_file double-write)."""

import os

import jax
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.utils.output import load_hof_csv

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=2,
    ncycles_per_iteration=30,
    maxsize=12,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
)


def make_data(rng, n=60):
    X = (rng.standard_normal((3, n)) * 2).astype(np.float32)
    y = X[0] * X[0] + 2.0 * np.cos(X[2])
    return X, y


@pytest.mark.slow
def test_recovery_and_predict(rng):
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y,
        niterations=14,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npop=48, npopulations=4, ncycles_per_iteration=150, maxsize=14,
        verbosity=0, progress=False, early_stop_condition=1e-5, seed=2,
    )
    best = res.best()
    assert best.loss < 1e-2
    pred = res.predict(X)
    np.testing.assert_allclose(pred, y, atol=0.3)
    # frontier is sorted by complexity with strictly improving loss
    front = res.frontier()
    assert all(
        a.complexity < b.complexity and a.loss > b.loss
        for a, b in zip(front, front[1:])
    )


def test_multi_output(rng):
    X, y0 = make_data(rng)
    y = np.stack([y0, X[1] * 2.0])
    res = sr.equation_search(X, y, niterations=2, seed=0, **TINY)
    assert len(res.candidates) == 2
    assert res.multi_output
    for out in (0, 1):
        assert len(res.frontier(out)) > 0
        res.predict(X, output=out)


def test_resume_state(rng):
    X, y = make_data(rng)
    res1 = sr.equation_search(
        X, y, niterations=2, return_state=True, seed=1, **TINY
    )
    assert res1.state is not None
    best1 = res1.best().loss
    res2 = sr.equation_search(
        X, y, niterations=2, saved_state=res1.state, seed=1, **TINY
    )
    assert res2.best().loss <= best1 + 1e-9
    assert res2.state is None  # only returned when asked


def test_early_stop_and_callback(rng):
    X, y = make_data(rng)
    seen = []
    res = sr.equation_search(
        X, y, niterations=10, early_stop_condition=1e3,  # trivially satisfied
        on_iteration=lambda j, it, cands: seen.append(it),
        seed=0, **TINY,
    )
    assert len(seen) == 1  # stopped after the first iteration


def test_weighted_search(rng):
    X, y = make_data(rng)
    w = np.ones_like(y)
    res = sr.equation_search(X, y, weights=w, niterations=1, seed=0, **TINY)
    assert len(res.frontier()) > 0


def test_checkpoint_csv(rng, tmp_path):
    X, y = make_data(rng)
    path = str(tmp_path / "hof.csv")
    opts = dict(TINY)
    opts["output_file"] = path
    res = sr.equation_search(X, y, niterations=1, seed=0, **opts)
    assert os.path.exists(path) and os.path.exists(path + ".bkup")
    reloaded = load_hof_csv(path, make_options(**{k: v for k, v in TINY.items()
                                                  if k in ("binary_operators", "unary_operators", "maxsize")}))
    assert [c.complexity for c in reloaded] == [
        c.complexity for c in res.frontier()
    ]


def test_deterministic_same_seed(rng):
    X, y = make_data(rng)
    r1 = sr.equation_search(X, y, niterations=2, seed=5, **TINY)
    r2 = sr.equation_search(X, y, niterations=2, seed=5, **TINY)
    assert [c.equation for c in r1.frontier()] == [
        c.equation for c in r2.frontier()
    ]
    r3 = sr.equation_search(X, y, niterations=2, seed=6, **TINY)
    # different seed should explore differently (not a hard guarantee, but
    # overwhelmingly likely with these budgets)
    assert [c.equation for c in r3.frontier()] != [
        c.equation for c in r1.frontier()
    ] or r3.best().loss != r1.best().loss


def test_timeout_stops_early(rng):
    """timeout_in_seconds ends the search after the current iteration
    (analog of reference test/test_stop_on_clock.jl:9-14)."""
    X, y = make_data(rng, n=40)
    its = []
    res = sr.equation_search(
        X, y, niterations=50, runtests=False, seed=5,
        timeout_in_seconds=1e-3, on_iteration=lambda j, it, c: its.append(it),
        **TINY
    )
    # the loop checks the clock after each iteration: only the first ran
    assert len(its) == 1
    assert res.search_time_s < 60.0


def test_turbo_and_fast_cycle_knobs():
    """Reference compatibility knobs: turbo maps to the eval-backend
    switch (the Pallas kernel is this framework's SIMD analog,
    src/Options.jl:250-252); fast_cycle is accepted as a no-op (the
    engine is always fully batched)."""
    o1 = make_options(binary_operators=["+"], turbo=True, fast_cycle=True)
    assert o1.eval_backend == "auto"
    o2 = make_options(binary_operators=["+"], turbo=False)
    assert o2.eval_backend == "jnp"


def test_option_validation(rng):
    X, y = make_data(rng)
    with pytest.raises(ValueError):
        sr.equation_search(X, y, options=make_options(), niterations=1,
                           npop=10)  # both options= and kwargs
    with pytest.raises(ValueError):
        sr.equation_search(X[:, :10], y, niterations=1, **TINY)  # shape clash


def test_preflight_rejects_nonfinite(rng):
    X, y = make_data(rng)
    Xbad = X.copy()
    Xbad[0, 0] = np.nan
    with pytest.raises(ValueError):
        sr.equation_search(Xbad, y, niterations=1, **TINY)
