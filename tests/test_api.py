"""equation_search API integration tests.

Parity targets: reference test/full.jl tier — recovery (test_mixed.jl),
multi-output, weighted, resume (test_fast_cycle.jl:29-38), early stop
(test_early_stop.jl), determinism (test_deterministic.jl:27-29), checkpoint
CSV (output_file double-write)."""

import os

import jax
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.utils.output import load_hof_csv

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=2,
    ncycles_per_iteration=30,
    maxsize=12,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
)


def make_data(rng, n=60):
    X = (rng.standard_normal((3, n)) * 2).astype(np.float32)
    y = X[0] * X[0] + 2.0 * np.cos(X[2])
    return X, y


@pytest.mark.slow
def test_recovery_and_predict(rng):
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y,
        niterations=14,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npop=48, npopulations=4, ncycles_per_iteration=150, maxsize=14,
        verbosity=0, progress=False, early_stop_condition=1e-5, seed=2,
    )
    best = res.best_loss()
    assert best.loss < 1e-2
    pred = res.predict(X)
    np.testing.assert_allclose(pred, y, atol=0.3)
    # frontier is sorted by complexity with strictly improving loss
    front = res.frontier()
    assert all(
        a.complexity < b.complexity and a.loss > b.loss
        for a, b in zip(front, front[1:])
    )


@pytest.mark.slow
def test_multi_output(rng):
    X, y0 = make_data(rng)
    y = np.stack([y0, X[1] * 2.0])
    res = sr.equation_search(X, y, niterations=2, seed=0, **TINY)
    assert len(res.candidates) == 2
    assert res.multi_output
    for out in (0, 1):
        assert len(res.frontier(out)) > 0
        res.predict(X, output=out)


@pytest.mark.slow
def test_resume_state(rng):
    X, y = make_data(rng)
    res1 = sr.equation_search(
        X, y, niterations=2, return_state=True, seed=1, **TINY
    )
    assert res1.state is not None
    best1 = res1.best_loss().loss
    res2 = sr.equation_search(
        X, y, niterations=2, saved_state=res1.state, seed=1, **TINY
    )
    assert res2.best_loss().loss <= best1 + 1e-9
    assert res2.state is None  # only returned when asked


@pytest.mark.slow
def test_early_stop_and_callback(rng):
    X, y = make_data(rng)
    seen = []
    res = sr.equation_search(
        X, y, niterations=10, early_stop_condition=1e3,  # trivially satisfied
        on_iteration=lambda j, it, cands: seen.append(it),
        seed=0, **TINY,
    )
    assert len(seen) == 1  # stopped after the first iteration


@pytest.mark.slow
def test_weighted_search(rng):
    X, y = make_data(rng)
    w = np.ones_like(y)
    res = sr.equation_search(X, y, weights=w, niterations=1, seed=0, **TINY)
    assert len(res.frontier()) > 0


@pytest.mark.slow
def test_checkpoint_csv(rng, tmp_path):
    X, y = make_data(rng)
    path = str(tmp_path / "hof.csv")
    opts = dict(TINY)
    opts["output_file"] = path
    res = sr.equation_search(X, y, niterations=1, seed=0, **opts)
    assert os.path.exists(path) and os.path.exists(path + ".bkup")
    reloaded = load_hof_csv(path, make_options(**{k: v for k, v in TINY.items()
                                                  if k in ("binary_operators", "unary_operators", "maxsize")}))
    assert [c.complexity for c in reloaded] == [
        c.complexity for c in res.frontier()
    ]


@pytest.mark.slow
def test_deterministic_same_seed(rng):
    X, y = make_data(rng)
    r1 = sr.equation_search(X, y, niterations=2, seed=5, **TINY)
    r2 = sr.equation_search(X, y, niterations=2, seed=5, **TINY)
    assert [c.equation for c in r1.frontier()] == [
        c.equation for c in r2.frontier()
    ]
    r3 = sr.equation_search(X, y, niterations=2, seed=6, **TINY)
    # different seed should explore differently (not a hard guarantee, but
    # overwhelmingly likely with these budgets)
    assert [c.equation for c in r3.frontier()] != [
        c.equation for c in r1.frontier()
    ] or r3.best().loss != r1.best().loss


@pytest.mark.slow
def test_timeout_stops_early(rng):
    """timeout_in_seconds ends the search after the current iteration
    (analog of reference test/test_stop_on_clock.jl:9-14)."""
    X, y = make_data(rng, n=40)
    its = []
    res = sr.equation_search(
        X, y, niterations=50, runtests=False, seed=5,
        timeout_in_seconds=1e-3, on_iteration=lambda j, it, c: its.append(it),
        **TINY
    )
    # the loop checks the clock after each iteration: only the first ran
    assert len(its) == 1
    assert res.search_time_s < 60.0


def test_turbo_and_fast_cycle_knobs():
    """Reference compatibility knobs: turbo maps to the eval-backend
    switch (the Pallas kernel is this framework's SIMD analog,
    src/Options.jl:250-252); fast_cycle is accepted as a no-op (the
    engine is always fully batched)."""
    o1 = make_options(binary_operators=["+"], turbo=True, fast_cycle=True)
    assert o1.eval_backend == "auto"
    o2 = make_options(binary_operators=["+"], turbo=False)
    assert o2.eval_backend == "jnp"


def test_option_validation(rng):
    X, y = make_data(rng)
    with pytest.raises(ValueError):
        sr.equation_search(X, y, options=make_options(), niterations=1,
                           npop=10)  # both options= and kwargs
    with pytest.raises(ValueError):
        sr.equation_search(X[:, :10], y, niterations=1, **TINY)  # shape clash


def test_preflight_rejects_nonfinite(rng):
    X, y = make_data(rng)
    Xbad = X.copy()
    Xbad[0, 0] = np.nan
    with pytest.raises(ValueError):
        sr.equation_search(Xbad, y, niterations=1, **TINY)


@pytest.mark.slow
def test_resume_mismatched_options_recreates(rng):
    """A saved_state whose npop no longer matches Options is recreated with
    a warning, keeping the saved hall of fame (analog of reference
    src/SymbolicRegression.jl:532-573)."""
    import warnings

    X, y = make_data(rng)
    res1 = sr.equation_search(
        X, y, niterations=1, return_state=True, seed=1, **TINY
    )
    hof_best = min(c.loss for c in res1.frontier())
    smaller = dict(TINY)
    smaller["npop"] = 16
    with pytest.warns(UserWarning, match="recreating"):
        res2 = sr.equation_search(
            X, y, niterations=1, saved_state=res1.state, seed=1, **smaller
        )
    assert len(res2.frontier()) > 0
    # the saved hall of fame survived the population recreation
    assert min(c.loss for c in res2.frontier()) <= hof_best + 1e-6


@pytest.mark.slow
def test_warm_start_from_csv(rng, tmp_path):
    """warm_start_file seeds the search from a hall-of-fame CSV (analog of
    load_saved_hall_of_fame, reference src/SearchUtils.jl:275-301)."""
    X, y = make_data(rng)
    path = str(tmp_path / "hof.csv")
    opts = dict(TINY)
    opts["output_file"] = path
    res1 = sr.equation_search(X, y, niterations=2, seed=1, **opts)
    best1 = min(c.loss for c in res1.frontier())
    res2 = sr.equation_search(
        X, y, niterations=1, warm_start_file=path, seed=99, **TINY
    )
    # the reloaded + rescored equations keep the search at least as good
    assert min(c.loss for c in res2.frontier()) <= best1 + 1e-5


def test_best_picks_score_column():
    """best() selects by the -dlog(loss)/dcomplexity score column like the
    reference's printed table (src/HallOfFame.jl:136-139); best_loss()
    keeps the min-loss pick."""
    from symbolicregression_jl_tpu.api import EquationSearchResult
    from symbolicregression_jl_tpu.utils.output import Candidate

    cands = [
        Candidate(complexity=1, loss=1.0, score=0.0, equation="a", tree=None),
        Candidate(complexity=3, loss=0.01, score=2.30, equation="b", tree=None),
        Candidate(complexity=9, loss=0.008, score=0.037, equation="c", tree=None),
    ]
    res = EquationSearchResult(
        candidates=[cands], options=None, variable_names=None
    )
    assert res.best().equation == "b"  # biggest log-loss drop per size
    assert res.best_loss().equation == "c"  # global min loss


def test_predict_warns_on_domain_violation(rng):
    """predict surfaces the eval ok=false flag (NaN/Inf domain) as a
    warning instead of silently returning non-finite values."""
    import warnings

    from symbolicregression_jl_tpu.api import EquationSearchResult
    from symbolicregression_jl_tpu.models.trees import encode_tree, parse_expression
    from symbolicregression_jl_tpu.utils.output import Candidate

    opts = make_options(
        binary_operators=["+"], unary_operators=["log"], maxsize=8
    )
    tree = encode_tree(parse_expression("log(x0)", opts.operators), opts.max_len)
    cand = Candidate(
        complexity=2, loss=0.0, score=1.0, equation="log(x0)", tree=tree
    )
    res = EquationSearchResult(
        candidates=[[cand]], options=opts, variable_names=None
    )
    X = np.array([[-1.0, 2.0]], dtype=np.float32)
    with pytest.warns(RuntimeWarning, match="NaN/Inf"):
        y = res.predict(X)
    assert not np.isfinite(y).all()
    # clean inputs: no warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        y2 = res.predict(np.array([[1.0, 2.0]], dtype=np.float32))
    assert np.isfinite(y2).all()


def test_reference_parallelism_kwargs(rng):
    """Reference EquationSearch scheduling kwargs are accepted for drop-in
    migration: parallelism validates, numprocs/procs warn (SPMD replaces
    worker spawning)."""
    X, y = make_data(rng, n=40)
    res = sr.equation_search(
        X, y, niterations=1, parallelism="multithreading", seed=0,
        runtests=False, **TINY,
    )
    assert len(res.frontier()) > 0
    with pytest.raises(ValueError, match="parallelism"):
        sr.equation_search(
            X, y, niterations=1, parallelism="gpu", runtests=False, **TINY
        )
    with pytest.warns(UserWarning, match="no effect"):
        sr.equation_search(
            X, y, niterations=1, numprocs=4, seed=0, runtests=False, **TINY
        )


@pytest.mark.slow
def test_independent_island_batches(rng):
    """Reference-exact per-island minibatch draws
    (src/LossFunctions.jl:95-115) as an Options knob."""
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y, niterations=2, batching=True, batch_size=20,
        independent_island_batches=True, seed=0, runtests=False, **TINY,
    )
    assert len(res.frontier()) > 0
    assert np.isfinite(res.best_loss().loss)


@pytest.mark.slow
def test_integer_input_data_is_cast(rng):
    """Integer-typed X/y are accepted and cast to the working float dtype
    (deviation from reference test_integer_evaluation.jl, which preserves
    integer node types — a float-first TPU engine casts at the boundary)."""
    X = rng.integers(-5, 5, (2, 40)).astype(np.int64)
    y = (X[0] * X[1]).astype(np.int64)
    res = sr.equation_search(
        X, y, niterations=2, seed=0, runtests=False, **TINY
    )
    assert len(res.frontier()) > 0
    pred = res.predict(X)
    assert pred.dtype == np.float32


@pytest.mark.slow
def test_checkpoint_bkup_fallback(rng, tmp_path):
    """A torn or missing main checkpoint falls back to the .bkup
    double-write (the reference's survive-mid-write-kill mechanism,
    src/SymbolicRegression.jl:749-767)."""
    X, y = make_data(rng)
    path = str(tmp_path / "hof.csv")
    opts = dict(TINY)
    opts["output_file"] = path
    res = sr.equation_search(X, y, niterations=1, seed=0, **opts)
    expect = [c.complexity for c in res.frontier()]

    # missing main file (killed before the rewrite started)
    body = open(path).read()
    os.remove(path)
    reloaded = load_hof_csv(path, res.options)
    assert [c.complexity for c in reloaded] == expect

    # torn main file (killed mid-write): intact .bkup must win
    with open(path, "w") as f:
        f.write(body[: len(body) // 2].rsplit("\n", 1)[0] + "\n(((")
    reloaded = load_hof_csv(path, res.options)
    assert [c.complexity for c in reloaded] == expect


def test_deprecated_kwargs_remap():
    """camelCase kwargs remap to their snake_case fields with the same
    table the reference keeps (analog of test/test_deprecation.jl;
    src/Options.jl:122-143)."""
    o = make_options(
        binary_operators=["+"],
        batchSize=17,
        crossoverProbability=0.25,
        useFrequency=False,
        ns=4,
        probPickFirst=0.9,
        fractionReplaced=0.1,
        npop=16,
    )
    assert o.batch_size == 17
    assert o.crossover_probability == 0.25
    assert o.use_frequency is False
    assert o.tournament_selection_n == 4
    assert o.tournament_selection_p == 0.9
    assert o.fraction_replaced == 0.1
    with pytest.raises(ValueError, match="Duplicate"):
        make_options(binary_operators=["+"], batchSize=1, batch_size=2)


@pytest.mark.slow
def test_readme_quickstart_executes(monkeypatch, capsys):
    """The README quickstart code blocks execute as written (analog of the
    reference running its README example, test/full.jl:19-21). The search
    budget is shrunk through a wrapper so the API surface — not the wall
    clock — is what's under test."""
    import re

    import symbolicregression_jl_tpu.sklearn as sk_mod

    orig = sr.equation_search

    def small_budget(*a, **k):
        k["niterations"] = 1
        k.setdefault("npop", 16)
        k.setdefault("npopulations", 2)
        k.setdefault("ncycles_per_iteration", 15)
        k.setdefault("maxsize", 10)
        k.setdefault("tournament_selection_n", 6)
        k.setdefault("verbosity", 0)
        k.setdefault("progress", False)
        k.setdefault("runtests", False)
        return orig(*a, **k)

    monkeypatch.setattr(sr, "equation_search", small_budget)
    monkeypatch.setattr(sk_mod, "equation_search", small_budget)

    path = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(path, encoding="utf-8") as f:
        readme = f.read()
    all_blocks = re.findall(r"```python\n(.*?)```", readme, re.DOTALL)
    # anchor on content, not position: the functional quickstart and the
    # estimator-facade block
    blocks = [
        b for b in all_blocks
        if "equation_search(" in b or "SymbolicRegressor(" in b
    ]
    assert len(blocks) >= 2
    ns = {}
    for block in blocks[:2]:
        exec(compile(block, "<README>", "exec"), ns)  # noqa: S102
    out = capsys.readouterr().out
    assert "Hall of Fame" in out  # print(result) rendered the table


@pytest.mark.slow
def test_search_state_disk_roundtrip(rng, tmp_path):
    """Full search state survives a disk round-trip and resumes exactly
    (beyond the reference, whose exact-resume state lives only in the
    session): resume-from-disk equals resume-from-memory."""
    X, y = make_data(rng)
    res1 = sr.equation_search(
        X, y, niterations=1, return_state=True, seed=4, **TINY
    )
    path = str(tmp_path / "run.ckpt")
    sr.save_search_state(path, res1.state)

    loaded = sr.load_search_state(path)
    res_mem = sr.equation_search(
        X, y, niterations=1, saved_state=res1.state, seed=4, **TINY
    )
    res_disk = sr.equation_search(
        X, y, niterations=1, saved_state=loaded, seed=4, **TINY
    )
    assert [(c.complexity, c.equation) for c in res_disk.frontier()] == [
        (c.complexity, c.equation) for c in res_mem.frontier()
    ]

    # torn main file falls back to .bkup
    with open(path, "r+b") as f:
        f.truncate(100)
    loaded2 = sr.load_search_state(path)
    assert loaded2[0].iteration == loaded[0].iteration

    with pytest.raises(FileNotFoundError):
        sr.load_search_state(str(tmp_path / "missing.ckpt"))
    # both copies corrupt -> ValueError, never silently a fresh start
    with open(path, "wb") as f:
        f.write(b"garbage")
    with open(path + ".bkup", "wb") as f:
        f.write(b"garbage")
    with pytest.raises(ValueError, match="unreadable"):
        sr.load_search_state(path)


def test_reference_option_kwargs_parity():
    """The remaining reference Options kwargs accepted for drop-in
    migration: elementwise_loss (the reference's rename of loss,
    src/Options.jl:142,319), una_constraints/bin_constraints dicts merged
    into the unified constraints mapping (src/Options.jl:33-84), plus the
    save_to_file / terminal_width / define_helper_functions knobs."""
    o = make_options(
        binary_operators=["+", "*", "^"],
        unary_operators=["cos", "exp"],
        elementwise_loss="L1DistLoss",
        una_constraints={"exp": 5},
        bin_constraints={"^": (3, 1)},
        save_to_file=False,
        terminal_width=72,
        define_helper_functions=False,
    )
    assert o.loss == "L1DistLoss"
    cons = dict(o.constraints)
    assert cons["exp"] == 5 and tuple(cons["^"]) == (3, 1)
    assert o.save_to_file is False and o.terminal_width == 72

    with pytest.raises(ValueError, match="not both"):
        make_options(binary_operators=["+"], loss="L1DistLoss",
                     elementwise_loss="L2DistLoss")
    with pytest.raises(ValueError, match="constrained in both"):
        make_options(binary_operators=["+"], unary_operators=["exp"],
                     constraints={"exp": 4}, una_constraints={"exp": 5})
    with pytest.raises(ValueError, match="dict"):
        make_options(binary_operators=["+"], bin_constraints=[(3, 1)])


@pytest.mark.slow
def test_save_to_file_false_suppresses_csv(tmp_path):
    """save_to_file=False keeps output_file configured but writes nothing
    (reference src/Options.jl:285)."""
    X = np.random.default_rng(0).standard_normal((2, 30)).astype(np.float32)
    y = X[0] + X[1]
    path = str(tmp_path / "hof.csv")
    res = sr.equation_search(
        X, y, niterations=1, seed=0, output_file=path, save_to_file=False,
        **TINY,
    )
    assert res.best() is not None
    assert not os.path.exists(path) and not os.path.exists(path + ".bkup")


def test_recorder_env_default(monkeypatch):
    """Unset recorder kwarg defaults from PYSR_RECORDER=1 like the
    reference (src/Options.jl:597-599); an explicit kwarg wins."""
    monkeypatch.setenv("PYSR_RECORDER", "1")
    assert make_options(binary_operators=["+"]).recorder is True
    assert make_options(binary_operators=["+"], recorder=False).recorder is False
    monkeypatch.delenv("PYSR_RECORDER")
    assert make_options(binary_operators=["+"]).recorder is False


@pytest.mark.slow
def test_donated_carry_search_bit_identical_3_seeds(rng, monkeypatch):
    """Buffer donation (SRTPU_DONATE, default on) changes HBM reuse only,
    never values: over 3 seeds the donated search's HallOfFame — losses,
    complexities, and rendered equations — is bit-identical to the
    non-donated one (the ISSUE 4 acceptance criterion; srmem/SR006
    motivate WHY the production path donates)."""
    X, y = make_data(rng)

    def frontier_bits(res):
        return [
            (c.complexity, float(c.loss), c.equation)
            for c in res.frontier()
        ]

    for seed in (0, 1, 2):
        monkeypatch.setenv("SRTPU_DONATE", "0")
        r_off = sr.equation_search(X, y, niterations=2, seed=seed, **TINY)
        monkeypatch.setenv("SRTPU_DONATE", "1")
        r_on = sr.equation_search(X, y, niterations=2, seed=seed, **TINY)
        assert frontier_bits(r_on) == frontier_bits(r_off), seed

    # the chunked-dispatch driver donates through its phase jits too —
    # with and without the fitness cache (cache+chunked is the combo
    # where the absorb snapshot aliases the donated carry and must be
    # copied before the optimize/merge dispatches delete it)
    for extra in ({}, {"cache_fitness": True}):
        chunked = dict(TINY, max_cycles_per_dispatch=15, **extra)
        monkeypatch.setenv("SRTPU_DONATE", "0")
        c_off = sr.equation_search(X, y, niterations=2, seed=0, **chunked)
        monkeypatch.setenv("SRTPU_DONATE", "1")
        c_on = sr.equation_search(X, y, niterations=2, seed=0, **chunked)
        assert frontier_bits(c_on) == frontier_bits(c_off), extra
