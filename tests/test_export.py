"""Symbolic export round-trips.

Parity target: reference test/test_simplification.jl:66-83 — tree -> symbolic
-> tree round-trip must be eval-equivalent on random data within tolerance.
"""

import numpy as np
import pytest

sympy = pytest.importorskip("sympy")

from symbolicregression_jl_tpu.models.trees import (
    CONST,
    Expr,
    encode_tree,
    parse_expression,
    tree_to_string,
)
from symbolicregression_jl_tpu.ops.eval_numpy import eval_tree_numpy
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.utils.export import (
    from_sympy,
    sympy_simplify_tree,
    to_callable,
    to_latex,
    to_sympy,
)

OPS = make_operator_set(["+", "-", "*", "/", "^"], ["cos", "exp", "sqrt", "log"])
MAX_LEN = 32


def _expr(s):
    return parse_expression(s, OPS)


def _assert_eval_equivalent(rng, tree_a, tree_b, atol=1e-4, ops=OPS):
    X = rng.uniform(0.5, 3.0, size=(3, 64)).astype(np.float32)
    ya, oka = eval_tree_numpy(tree_a, X, ops)
    yb, okb = eval_tree_numpy(tree_b, X, ops)
    assert bool(oka) and bool(okb)
    np.testing.assert_allclose(ya, yb, rtol=1e-3, atol=atol)


@pytest.mark.parametrize(
    "expr_str",
    [
        "((x0 + x1) * cos(x2))",
        "(exp(x0) / (x1 + 1.5))",
        "sqrt((x0 * x0))",
        "(2.5 * (x0 + (x1 * x2)))",
        "log((x0 + 2.0))",
        "((x0 ^ 2.0) - (x1 / 3.0))",
    ],
)
def test_sympy_roundtrip_eval_equivalent(rng, expr_str):
    tree = encode_tree(_expr(expr_str), MAX_LEN)
    s = to_sympy(tree, OPS)
    back = encode_tree(from_sympy(s, OPS), MAX_LEN)
    _assert_eval_equivalent(rng, tree, back)


def test_sympy_form_is_correct():
    tree = encode_tree(_expr("((x0 + x0) * cos(x1))"), MAX_LEN)
    s = sympy.simplify(to_sympy(tree, OPS))
    x0, x1 = sympy.symbols("x0 x1", real=True)
    assert sympy.simplify(s - 2 * x0 * sympy.cos(x1)) == 0


def test_simplify_tree_shrinks_redundancy(rng):
    # x0 + x0 + x0 - x0 simplifies to 2*x0
    tree = encode_tree(_expr("(((x0 + x0) + x0) - x0)"), MAX_LEN)
    simp = sympy_simplify_tree(tree, OPS, max_len=MAX_LEN)
    _assert_eval_equivalent(rng, tree, simp)
    assert int(simp.length) <= int(tree.length)


def test_simplify_falls_back_when_inexpressible(rng):
    # sin not in the operator set: sympy may produce forms needing it; the
    # helper must return an eval-equivalent tree regardless.
    ops = make_operator_set(["+", "*"], ["cos"])
    tree = encode_tree(parse_expression("(cos(x0) * cos(x0))", ops), MAX_LEN)
    simp = sympy_simplify_tree(tree, ops, max_len=MAX_LEN)
    _assert_eval_equivalent(rng, tree, simp, ops=ops)


def test_variable_names():
    tree = encode_tree(
        parse_expression("(alpha + beta)", OPS, ["alpha", "beta"]), MAX_LEN
    )
    s = to_sympy(tree, OPS, ["alpha", "beta"])
    assert {str(v) for v in s.free_symbols} == {"alpha", "beta"}
    back = from_sympy(s, OPS, ["alpha", "beta"])
    assert tree_to_string(encode_tree(back, MAX_LEN), OPS, ["alpha", "beta"]) in (
        "(alpha + beta)",
        "(beta + alpha)",
    )


def test_latex():
    tree = encode_tree(_expr("(x0 / (x1 + 1.0))"), MAX_LEN)
    tex = to_latex(tree, OPS)
    assert "frac" in tex


def test_to_callable(rng):
    tree = encode_tree(_expr("((x0 * x0) + cos(x1))"), MAX_LEN)
    f = to_callable(tree, OPS)
    X = rng.normal(size=(2, 32)).astype(np.float32)
    y = np.asarray(f(X))
    np.testing.assert_allclose(
        y, X[0] ** 2 + np.cos(X[1]), rtol=1e-5, atol=1e-5
    )


def test_from_sympy_subtraction_without_mult(rng):
    # sympy stores x0 - x1 as Add(x0, Mul(-1, x1)); conversion must use "-"
    # rather than demanding "*" in the operator set.
    ops = make_operator_set(["+", "-"], [])
    x0, x1 = sympy.symbols("x0 x1", real=True)
    e = from_sympy(x0 - x1, ops)
    tree = encode_tree(e, MAX_LEN)
    _assert_eval_equivalent(
        rng, tree, encode_tree(parse_expression("(x0 - x1)", ops), MAX_LEN),
        ops=ops,
    )
    # pure negation: -x0 with no "*" either
    e2 = from_sympy(-x0, ops)
    X = rng.normal(size=(1, 16)).astype(np.float32)
    y, ok = eval_tree_numpy(encode_tree(e2, MAX_LEN), X, ops)
    np.testing.assert_allclose(y, -X[0], rtol=1e-6)


def test_from_sympy_inv_and_neg_preference():
    ops = make_operator_set(["+", "*"], ["inv", "neg"])
    x0 = sympy.Symbol("x0", real=True)
    e = from_sympy(1 / x0, ops)
    assert e.kind != CONST  # uses inv(x0)
    assert ops.unary_names[e.op] == "inv"
    e2 = from_sympy(-x0, ops)
    assert ops.unary_names[e2.op] == "neg" or ops.binary_names[e2.op] == "*"


def test_from_sympy_rejects_missing_operator():
    ops = make_operator_set(["+", "*"], [])
    x0 = sympy.Symbol("x0", real=True)
    with pytest.raises(ValueError):
        from_sympy(sympy.sin(x0), ops)
