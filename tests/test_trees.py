"""Tree encoding round-trip, printing, parsing, structural queries.

Parity targets: reference test/test_print.jl (string forms) and
DynamicExpressions tree manipulation semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.trees import (
    BIN,
    CONST,
    PAD,
    UNA,
    VAR,
    Expr,
    decode_tree,
    encode_tree,
    expr_to_string,
    is_valid_postfix,
    node_depths,
    parse_expression,
    subtree_sizes,
    tree_depth,
    tree_to_string,
)
from symbolicregression_jl_tpu.ops.operators import make_operator_set

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])


def example_expr():
    # 2*cos(x3) + x0^2 - 2  (the reference's precompile workload family,
    # reference src/precompile.jl:39-41) using * for square
    cos = OPS.unary_index("cos")
    plus = OPS.binary_index("+")
    sub = OPS.binary_index("-")
    mult = OPS.binary_index("*")
    x0 = Expr.var(0)
    return Expr.binary(
        sub,
        Expr.binary(
            plus,
            Expr.binary(mult, Expr.const(2.0), Expr.unary(cos, Expr.var(3))),
            Expr.binary(mult, x0, x0),
        ),
        Expr.const(2.0),
    )


def test_encode_decode_roundtrip():
    e = example_expr()
    t = encode_tree(e, max_len=24)
    assert int(t.length) == e.size() == 10
    e2 = decode_tree(t)
    assert expr_to_string(e, OPS) == expr_to_string(e2, OPS)


def test_postfix_layout():
    # cos(x1) encodes as [x1, cos]
    e = Expr.unary(OPS.unary_index("cos"), Expr.var(1))
    t = encode_tree(e, max_len=8)
    kind = np.asarray(t.kind)
    assert kind[0] == VAR and kind[1] == UNA and kind[2] == PAD
    assert int(t.length) == 2


def test_string_form():
    s = tree_to_string(encode_tree(example_expr(), 24), OPS)
    assert s == "(((2 * cos(x3)) + (x0 * x0)) - 2)"


def test_variable_names():
    e = Expr.binary(OPS.binary_index("+"), Expr.var(0), Expr.var(1))
    s = expr_to_string(e, OPS, variable_names=["alpha", "beta"])
    assert s == "(alpha + beta)"


def test_parse_roundtrip():
    e = example_expr()
    s = expr_to_string(e, OPS)
    e2 = parse_expression(s, OPS)
    assert expr_to_string(e2, OPS) == s


def test_parse_unary_minus_and_pow():
    ops = make_operator_set(["+", "-", "*", "/", "^"], ["neg", "sqrt"])
    e = parse_expression("-sqrt(x0) + x1 ^ 2.5", ops)
    s = expr_to_string(e, ops)
    assert "sqrt" in s and "^" in s


def test_subtree_sizes():
    e = example_expr()
    t = encode_tree(e, 24)
    sizes = np.asarray(subtree_sizes(t.kind, t.length))
    # root at slot length-1 covers the whole tree
    assert sizes[int(t.length) - 1] == 10
    # leaves have size 1
    kind = np.asarray(t.kind)
    for i in range(int(t.length)):
        if kind[i] in (CONST, VAR):
            assert sizes[i] == 1
    assert np.all(sizes[int(t.length):] == 0)


def test_depths():
    e = example_expr()
    t = encode_tree(e, 24)
    assert int(tree_depth(t.kind, t.length)) == e.depth() == 5


def test_decode_rejects_invalid():
    t = encode_tree(example_expr(), 24)
    bad = t._replace(kind=t.kind.at[0].set(BIN))
    assert not is_valid_postfix(bad)
    assert is_valid_postfix(t)


def test_oversized_raises():
    e = example_expr()
    with pytest.raises(ValueError):
        encode_tree(e, max_len=4)
