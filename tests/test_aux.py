"""Aux subsystems: recorder (analog of reference test/test_recorder.jl:24-46),
progress/resource telemetry, custom full-tree loss_function
(test/test_custom_objectives.jl:5-39), eval_diff_tree
(test/test_derivatives.jl)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.trees import encode_tree, parse_expression
from symbolicregression_jl_tpu.ops.interpreter import eval_diff_tree, eval_tree
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.utils.progress import (
    ResourceMonitor,
    SearchProgress,
)
from symbolicregression_jl_tpu.utils.recorder import (
    Recorder,
    find_iteration_from_record,
    recursive_merge,
)

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "sin"])


# --------------------------- recorder --------------------------------------


def test_recorder_json_schema(tmp_path):
    options = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"],
        npop=8, npopulations=2, ncycles_per_iteration=8,
        tournament_selection_n=4,
        recorder=True, recorder_file=str(tmp_path / "rec.json"),
        verbosity=0, progress=False,
    )
    rng = np.random.default_rng(0)
    X = rng.standard_normal((2, 40)).astype(np.float32)
    y = 2.0 * X[0]
    sr.equation_search(X, y, options=options, niterations=2)
    with open(options.recorder_file) as f:
        rec = json.load(f)
    assert "options" in rec
    assert "out1_pop1" in rec and "iteration1" in rec["out1_pop1"]
    members = rec["out1_pop1"]["iteration1"]["population"]
    assert len(members) == options.npop
    for m in members[:3]:
        assert {"ref", "tree", "score", "loss", "birth", "parent"} <= set(m)
    assert find_iteration_from_record("out1_pop1", rec) == 2
    assert "out1_hall_of_fame" in rec
    assert rec["num_evals"] > 0
    # aggregate mutation telemetry: cumulative, accepted <= proposed
    from symbolicregression_jl_tpu.models.evolve import MUTATION_NAMES

    mc1 = rec["out1_pop1"]["iteration1"]["mutation_counts"]
    mc2 = rec["out1_pop1"]["iteration2"]["mutation_counts"]
    assert set(mc1) == set(MUTATION_NAMES)
    assert sum(v["proposed"] for v in mc1.values()) > 0
    for name in MUTATION_NAMES:
        assert 0 <= mc1[name]["accepted"] <= mc1[name]["proposed"]
        assert mc2[name]["proposed"] >= mc1[name]["proposed"]
    # full per-event mutation lineage (reference schema asserted by
    # test/test_recorder.jl:24-46: mutations keyed by ref with
    # events/parent/tree/score/loss)
    muts = rec["mutations"]
    assert len(muts) > 20
    n_events = sum(
        1 for m in muts.values() for e in m["events"]
        if e["type"] != "death"
    )
    # every proposal is logged: niterations x ncycles x islands x B slots
    assert n_events == 2 * 8 * 2 * 2
    for m in list(muts.values())[:5]:
        assert {"tree", "score", "loss", "parent", "events"} <= set(m)
        for e in m["events"]:
            if e["type"] == "death":
                continue
            assert e["mutation"] in MUTATION_NAMES
            assert e["reason"] in (
                "accept", "reject", "constraint_failed", "noop"
            )
            assert isinstance(e["accepted"], bool)
    # replaced members of recorded lineage get death events
    # (reference src/RegularizedEvolution.jl death records)
    n_deaths = sum(
        1 for m in muts.values() for e in m["events"]
        if e["type"] == "death"
    )
    assert n_deaths > 0


def test_recursive_merge():
    a = {"x": {"p": 1}, "y": 2}
    b = {"x": {"q": 3}, "z": 4}
    m = recursive_merge(a, b)
    assert m == {"x": {"p": 1, "q": 3}, "y": 2, "z": 4}


# --------------------------- progress --------------------------------------


def test_search_progress_cycles_per_second(monkeypatch):
    options = make_options(binary_operators=["+"], npop=10,
                           tournament_selection_n=5,
                           ncycles_per_iteration=100)
    prog = SearchProgress(10, options)
    t = [1000.0]
    monkeypatch.setattr("time.time", lambda: t[0])
    prog.note_iteration()
    t[0] += 2.0
    prog.note_iteration()
    # 100*10/10 = 100 equations per iteration; 100 per 2s = 50/s
    assert prog.cycles_per_second == pytest.approx(50.0)
    line = prog.status_line(1, 0.5, 123.0)
    assert "Cycles/second" in line and "2/10" in line


def test_resource_monitor_warns(capsys):
    mon = ResourceMonitor(warn_fraction=0.2)
    for _ in range(6):
        mon.note(device_s=1.0, host_s=1.0)  # 50% host occupation
    os.environ.pop("SYMBOLIC_REGRESSION_TEST", None)
    try:
        mon.maybe_warn()
    finally:
        os.environ["SYMBOLIC_REGRESSION_TEST"] = "true"
    assert mon.host_occupation == pytest.approx(0.5)
    assert "orchestration" in capsys.readouterr().err


# --------------------------- custom loss_function ---------------------------


def test_custom_loss_function_steers_search():
    """Search with an objective rewarding f = 0.5*(x0 + x1)
    (analog of reference test/test_custom_objectives.jl:5-39)."""

    def loss_fn(tree, X, y, weights, options):
        pred, ok = eval_tree(tree, X, options.operators)
        target = 0.5 * (X[0] + X[1])
        mse = jnp.mean((pred - target) ** 2)
        return jnp.where(ok, mse, jnp.inf)

    options = make_options(
        binary_operators=["+", "*", "/"],
        loss_function=loss_fn,
        npop=24, npopulations=4, ncycles_per_iteration=60,
        maxsize=12, verbosity=0, progress=False, seed=3,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (2, 64)).astype(np.float32)
    y = np.zeros(64, np.float32)  # ignored by the custom objective
    res = sr.equation_search(X, y, options=options, niterations=6)
    assert res.best_loss().loss < 1e-2


# --------------------------- eval_diff -------------------------------------


def test_eval_diff_matches_analytic():
    expr = parse_expression("x0 * x0 + cos(x1)", OPS)
    tree = jax.tree_util.tree_map(jnp.asarray, encode_tree(expr, 16))
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.standard_normal((2, 30)).astype(np.float32))
    y, d0, ok = eval_diff_tree(tree, X, OPS, 0)
    assert bool(ok)
    np.testing.assert_allclose(np.asarray(d0), 2 * np.asarray(X[0]),
                               rtol=1e-5)
    _, d1, _ = eval_diff_tree(tree, X, OPS, 1)
    np.testing.assert_allclose(np.asarray(d1), -np.sin(np.asarray(X[1])),
                               rtol=1e-4, atol=1e-6)


# --------------------------- preflight -------------------------------------


def test_preflight_rejects_overlapping_operators():
    import dataclasses

    import pytest

    from symbolicregression_jl_tpu.ops.operators import OperatorSet
    from symbolicregression_jl_tpu.utils.preflight import (
        PreflightError, preflight_checks)

    options = make_options(binary_operators=["+"], unary_operators=["abs"])
    X = np.ones((2, 10), np.float32)
    preflight_checks(options, X, X[:1], None)  # no overlap: fine

    # make_operator_set rejects overlap at construction, so smuggle an
    # overlapping set past it to exercise preflight's own check
    # (reference src/Configure.jl:44-50)
    overlapping = dataclasses.replace(
        options.operators,
        unary_names=("abs", "max"),
        binary_names=("+", "max"),
    )

    class Opts:
        operators = overlapping
        batching = options.batching

    with pytest.raises(PreflightError, match="both binary and unary"):
        preflight_checks(Opts(), X, X[:1], None)


def test_pipeline_probe_runs():
    from symbolicregression_jl_tpu.utils.preflight import test_entire_pipeline

    options = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"],
        npop=16, npopulations=2, tournament_selection_n=4,
    )
    rng = np.random.default_rng(0)
    X = rng.standard_normal((2, 30)).astype(np.float32)
    y = (X[0] * 2)[None, :]
    test_entire_pipeline(options, X, y)  # must not raise


def test_quit_watcher_disabled_in_tests():
    from symbolicregression_jl_tpu.utils.progress import QuitWatcher

    w = QuitWatcher(enabled=True)
    assert not w.enabled  # SYMBOLIC_REGRESSION_TEST=true
    assert w.should_quit() is False


# --------------------------- precompile ------------------------------------


def test_do_precompilation_compile_mode(tmp_path):
    import jax

    import symbolicregression_jl_tpu as sr

    # jax_compilation_cache_dir is process-global; leaving it on after this
    # test would make LATER tests write persistent-cache entries, and on
    # this image executable.serialize() segfaults on some CPU executables
    # (see conftest.py). Restore whatever was configured before.
    prev = jax.config.jax_compilation_cache_dir
    try:
        sr.do_precompilation(mode="compile", cache_dir=str(tmp_path))
        # cache dir was created and the jit programs compiled without error
        import os

        assert os.path.isdir(str(tmp_path))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        # restoring the config is NOT enough: the cache is a process-global
        # singleton that stays initialized (and keeps writing entries) once
        # the first compile used it — reset it so later tests' compiles
        # don't reach the crashing serializer
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()


def test_do_precompilation_bad_mode():
    import pytest

    import symbolicregression_jl_tpu as sr

    with pytest.raises(ValueError):
        sr.do_precompilation(mode="everything")


# --------------------------- compilation cache ------------------------------


@pytest.mark.slow
def test_compilation_cache_probe(tmp_path):
    """The persistent-cache serializer probe runs the known-crashy workload
    in a subprocess and never takes down the caller; when it reports safe,
    its own compiles have pre-warmed the cache directory."""
    from symbolicregression_jl_tpu.utils.precompile import (
        probe_compilation_cache,
    )

    cache_dir = str(tmp_path / "xla_cache")
    ok = probe_compilation_cache(cache_dir)
    assert isinstance(ok, bool)
    if ok:
        assert os.path.isdir(cache_dir) and len(os.listdir(cache_dir)) > 0


# --------------------------- profiling -------------------------------------


def test_profiler_trace_and_memory_stats(tmp_path):
    """XLA profiler wrapper captures a trace of device work and the memory
    snapshot reports per device (the profiling analog of the reference's
    benchmark/analyze.py tooling)."""
    from symbolicregression_jl_tpu.utils import profiling

    d = str(tmp_path / "trace")
    with profiling.trace(d):
        with profiling.annotate("tiny-op"):
            jnp.ones((8, 8)).sum().block_until_ready()
    # a capture directory with at least one event file appeared
    files = [p for p in os.walk(d)]
    assert any(fs for _, _, fs in files), "no trace files written"
    stats = profiling.device_memory_stats()
    assert len(stats) == len(jax.devices())
