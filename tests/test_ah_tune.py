"""Persistent kernel-tune cache (ISSUE 17): schema round-trip, robust
load (corrupt/truncated/wrong-schema files degrade to the static
defaults, never crash), per-device-kind isolation, the `auto` router's
consultation of the tuned crossover, and the srcost candidate ranking
the autotuner's measured sweep order rides on. All CPU, no kernels."""

import json

import jax.numpy as jnp
import pytest

from symbolicregression_jl_tpu.analysis.cost import (
    pallas_config_cost,
    pallas_kernel_cost_entries,
    rank_kernel_configs,
)
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.tune import (
    SCHEMA_VERSION,
    current_device_kind,
    entry_key,
    load_tune_cache,
    lookup_kernel_config,
    opset_fingerprint,
    reset_tune_cache_memo,
    save_tune_cache,
    tuned_min_work,
    update_tune_cache,
    validate_tune_cache,
)

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])

CONFIG = {
    "t_block": 256,
    "r_block": 1024,
    "dispatch": "mux",
    "tree_unroll": 8,
    "ladder": [0.25, 0.5, 0.75, 1.0],
}


@pytest.fixture(autouse=True)
def _fresh_memo():
    reset_tune_cache_memo()
    yield
    reset_tune_cache_memo()


def _cache_with(device_kind, interpret=False, min_work=None,
                config=CONFIG, maxsize=24):
    return update_tune_cache(
        None, device_kind, interpret,
        entry_key(opset_fingerprint(OPS), maxsize, "float32"),
        config, trees_rows_per_s=1.0e9, min_work=min_work,
    )


# ---------------------------------------------------------------------------
# cache: round-trip, robust load, isolation
# ---------------------------------------------------------------------------


def test_round_trip(tmp_path, monkeypatch):
    path = str(tmp_path / "tune_cache.json")
    monkeypatch.setenv("SRTPU_TUNE_CACHE", path)
    cache = _cache_with("TPU v5e", min_work=1 << 20)
    assert save_tune_cache(cache) == path
    assert load_tune_cache() == cache
    cfg = lookup_kernel_config(OPS, 24, "float32", device_kind="TPU v5e")
    assert cfg == CONFIG
    assert tuned_min_work(device_kind="TPU v5e") == 1 << 20
    # sorted-key writer: refreshes must diff like every other baseline
    with open(path) as f:
        text = f.read()
    assert text == json.dumps(cache, indent=2, sort_keys=True) + "\n"


def test_missing_file_is_none(tmp_path, monkeypatch):
    monkeypatch.setenv("SRTPU_TUNE_CACHE", str(tmp_path / "absent.json"))
    assert load_tune_cache() is None
    assert lookup_kernel_config(OPS, 24, "float32") is None
    assert tuned_min_work() is None


@pytest.mark.parametrize("payload", [
    "{not json at all",
    json.dumps({"schema_version": SCHEMA_VERSION})[: 20],  # truncated
    "[1, 2, 3]",  # parses, but not an object
])
def test_corrupt_cache_warns_and_defaults(tmp_path, monkeypatch, payload):
    path = tmp_path / "tune_cache.json"
    path.write_text(payload)
    monkeypatch.setenv("SRTPU_TUNE_CACHE", str(path))
    with pytest.warns(UserWarning, match="static kernel defaults"):
        assert load_tune_cache() is None
    # memoized verdict: lookups keep returning the defaults, no crash
    assert lookup_kernel_config(OPS, 24, "float32") is None
    assert tuned_min_work() is None


def test_schema_version_mismatch_ignored_with_warning(tmp_path,
                                                      monkeypatch):
    cache = _cache_with("cpu", interpret=True, min_work=4096)
    cache["schema_version"] = SCHEMA_VERSION + 1
    path = tmp_path / "tune_cache.json"
    path.write_text(json.dumps(cache))
    monkeypatch.setenv("SRTPU_TUNE_CACHE", str(path))
    with pytest.warns(UserWarning, match="schema_version"):
        assert load_tune_cache() is None
    assert tuned_min_work(device_kind="cpu") is None


def test_device_kind_isolation(tmp_path, monkeypatch):
    path = str(tmp_path / "tune_cache.json")
    monkeypatch.setenv("SRTPU_TUNE_CACHE", path)
    save_tune_cache(_cache_with("TPU v5e", min_work=2048))
    # a TPU-tuned cache must change NOTHING for another device kind
    assert lookup_kernel_config(OPS, 24, "float32",
                                device_kind="cpu") is None
    assert tuned_min_work(device_kind="cpu") is None
    assert lookup_kernel_config(OPS, 24, "float32",
                                device_kind="TPU v5e") == CONFIG
    # ... and entry keys isolate on (opset, maxsize, dtype) too
    other_ops = make_operator_set(["+", "-"], ["cos"])
    assert lookup_kernel_config(other_ops, 24, "float32",
                                device_kind="TPU v5e") is None
    assert lookup_kernel_config(OPS, 32, "float32",
                                device_kind="TPU v5e") is None


def test_interpret_quarantine():
    # the CPU fallback sweep must never masquerade as on-chip data
    with pytest.raises(ValueError, match="interpret"):
        _cache_with("TPU v5e", interpret=True)
    # and a hand-merged cache that violates it fails validation
    bad = _cache_with("TPU v5e")
    bad["device_kinds"]["TPU v5e"]["interpret"] = True
    assert any("interpret" in p for p in validate_tune_cache(bad))
    # mixing measurement modes under one device kind is refused as well
    cache = _cache_with("cpu", interpret=True)
    with pytest.raises(ValueError, match="mix"):
        update_tune_cache(
            cache, "cpu", False,
            entry_key(opset_fingerprint(OPS), 32, "float32"), CONFIG,
        )


def test_validate_rejects_malformed_configs():
    def bad_config(**kw):
        cache = _cache_with("cpu", interpret=True, config={**CONFIG, **kw})
        return validate_tune_cache(cache)

    assert bad_config(dispatch="vliw")
    assert bad_config(tree_unroll=3)
    assert bad_config(t_block=260)  # not a multiple of tree_unroll 8
    assert bad_config(r_block=200)  # not a multiple of 128
    assert bad_config(ladder=[0.5, 0.25, 1.0])  # not ascending
    assert bad_config(ladder=[0.25, 0.5])  # does not end at 1.0
    assert validate_tune_cache(_cache_with("cpu", interpret=True)) == []
    # the writer refuses an invalid payload outright
    with pytest.raises(ValueError, match="invalid"):
        save_tune_cache(_cache_with("cpu", interpret=True,
                                    config={**CONFIG, "dispatch": "x"}),
                        path="/dev/null")


# ---------------------------------------------------------------------------
# router consultation
# ---------------------------------------------------------------------------


def test_auto_router_consults_tuned_crossover(tmp_path, monkeypatch):
    import symbolicregression_jl_tpu.ops.pallas_eval as pe
    from symbolicregression_jl_tpu.models.fitness import (
        _PALLAS_MIN_WORK,
        resolve_eval_backend_pallas,
    )

    monkeypatch.setattr(pe, "pallas_available", lambda: True)
    monkeypatch.setenv("SRTPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    # no cache on disk: the static crossover, byte-identical to the
    # pre-autotuner rule
    below = int(_PALLAS_MIN_WORK ** 0.5) // 2
    assert not resolve_eval_backend_pallas(
        "auto", jnp.float32, below, below
    )
    assert resolve_eval_backend_pallas("auto", jnp.float32, 1024, 1024)
    # a tuned crossover for THIS device kind replaces the static rule
    kind = current_device_kind()
    save_tune_cache(_cache_with(kind, interpret="tpu" not in kind.lower(),
                                min_work=5000))
    assert resolve_eval_backend_pallas("auto", jnp.float32, 100, 100)
    assert not resolve_eval_backend_pallas("auto", jnp.float32, 50, 50)
    # a foreign device kind's crossover changes nothing
    save_tune_cache(_cache_with("TPU imaginary-v9", min_work=5000))
    assert not resolve_eval_backend_pallas(
        "auto", jnp.float32, 100, 100
    )


def test_tuned_kernel_kwargs(tmp_path, monkeypatch):
    from symbolicregression_jl_tpu.models.fitness import (
        _tuned_kernel_kwargs,
    )

    monkeypatch.setenv("SRTPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    # no cache: {} — untuned dispatch keeps the static defaults exactly
    assert _tuned_kernel_kwargs(OPS, 24, "float32") == {}
    kind = current_device_kind()
    save_tune_cache(_cache_with(kind,
                                interpret="tpu" not in kind.lower()))
    kw = _tuned_kernel_kwargs(OPS, 24, "float32")
    assert kw == {
        "t_block": 256, "r_block": 1024, "dispatch": "mux",
        "tree_unroll": 8, "bucket_ladder": (0.25, 0.5, 0.75, 1.0),
    }
    # a different maxsize misses -> static defaults again
    assert _tuned_kernel_kwargs(OPS, 40, "float32") == {}


# ---------------------------------------------------------------------------
# srcost candidate ranking
# ---------------------------------------------------------------------------

BASE = {"t_block": 256, "r_block": 1024, "dispatch": "mux",
        "tree_unroll": 8, "ladder": []}


def test_ranking_prefers_mux_over_chain():
    # chain's serial select surcharge (n_ops * 1.25 vs ceil(log2 n_ops))
    # makes it strictly more modeled flops at identical geometry
    lengths = [5] * 40 + [19] * 8
    chain = {**BASE, "dispatch": "chain"}
    c_mux = pallas_config_cost(lengths, BASE, 256, 3, OPS)
    c_chain = pallas_config_cost(lengths, chain, 256, 3, OPS)
    assert c_chain["flops"] > c_mux["flops"]
    ranked = rank_kernel_configs([chain, BASE], lengths, 256, 3, OPS)
    assert ranked[0][0] == BASE


def test_ranking_prefers_less_row_padding():
    # nrows=1500: r_block 512 pads to 1536 rows, 1024 pads to 2048 —
    # strictly more dead lanes at identical slot work
    lengths = [9] * 256
    small = {**BASE, "r_block": 512}
    ranked = rank_kernel_configs([BASE, small], lengths, 1500, 3, OPS)
    assert ranked[0][0] == small


def test_ranking_prefers_less_tree_padding():
    # T=300 with t_block 256 pads the tree axis to 512; t_block 128
    # pads to 384 — same executed slots (padded trees are length 0),
    # smaller tables and waste
    lengths = [9] * 300
    small = {**BASE, "t_block": 128}
    c_big = pallas_config_cost(lengths, BASE, 256, 3, OPS)
    c_small = pallas_config_cost(lengths, small, 256, 3, OPS)
    assert c_small["bytes"] < c_big["bytes"]
    assert c_small["flops"] == c_big["flops"]  # padded trees run 0 steps
    ranked = rank_kernel_configs([BASE, small], lengths, 256, 3, OPS)
    assert ranked[0][0] == small


def test_ranking_penalizes_mixed_length_groups():
    # hand-computed: lengths [3]*60 + [19]*4, _SLOT_UNROLL=4.
    # unroll 4: 15 all-short groups (1 step each) + 1 long group
    #   (5 steps) -> executed = 15*1*4*4 + 5*4*4 = 320 slot-visits.
    # unroll 16: groups 0-2 all short (3*1*4*16=192), group 3 mixes 12
    #   short with the 4 long trees -> gmax 19 -> 5*4*16 = 320;
    #   total 512. The narrower interleave must rank first.
    lengths = [3] * 60 + [19] * 4
    narrow = {**BASE, "tree_unroll": 4}
    wide = {**BASE, "tree_unroll": 16}
    c_narrow = pallas_config_cost(lengths, narrow, 256, 3, OPS)
    c_wide = pallas_config_cost(lengths, wide, 256, 3, OPS)
    assert c_narrow["executed_slots"] == 320
    assert c_wide["executed_slots"] == 512
    assert c_wide["flops"] / c_narrow["flops"] == pytest.approx(512 / 320)
    ranked = rank_kernel_configs([wide, narrow], lengths, 256, 3, OPS)
    assert ranked[0][0] == narrow


def test_kernel_cost_baseline_entries_are_honest():
    entries = pallas_kernel_cost_entries()
    assert set(entries) == {
        "pallas_postfix_flat", "pallas_postfix_bucketed",
        "pallas_postfix_fused",
    }
    # the model must NOT invent a bucketed slot-work win: on the clean
    # skewed histogram the ladder only re-tiles, it cannot truncate
    flat, buck = (entries["pallas_postfix_flat"],
                  entries["pallas_postfix_bucketed"])
    assert buck["flops"] == flat["flops"]
    # the fused epilogue's whole point: the (T, nrows) value write-back
    # never reaches HBM, so modeled bytes collapse
    assert entries["pallas_postfix_fused"]["bytes"] < 0.25 * buck["bytes"]


def test_model_ranked_sweep_measures_top_k_and_survives_errors():
    from symbolicregression_jl_tpu.tune import (
        model_ranked_sweep,
        sweep_to_cache,
    )

    lengths = [5] * 40 + [19] * 8
    calls = []

    def measure(config):
        calls.append(config)
        if config["dispatch"] == "chain":
            raise RuntimeError("lowering exploded")
        return 100.0 + config["t_block"]

    candidates = [
        {**BASE, "t_block": tb, "dispatch": d}
        for tb in (128, 256) for d in ("mux", "chain")
    ]
    sweep = model_ranked_sweep(OPS, lengths, 256, 3, measure,
                               candidates=candidates, top_k=3)
    assert len(calls) == 3
    assert len(sweep["measured"]) == 3
    errors = [m for m in sweep["measured"] if "error" in m]
    assert all(m["config"]["dispatch"] == "chain" for m in errors)
    best = sweep["best"]
    assert best["config"]["dispatch"] == "mux"
    assert best["trees_rows_per_s"] == max(
        m["trees_rows_per_s"] for m in sweep["measured"]
        if "trees_rows_per_s" in m
    )
    cache = sweep_to_cache(sweep, OPS, 24, interpret=True,
                           device_kind="cpu", min_work=4096)
    assert validate_tune_cache(cache) == []
    assert cache["device_kinds"]["cpu"]["min_work"] == 4096
