"""Two-process multi-host search test.

Reference analog: the addprocs(2)-shaped distributed tests
(test/test_custom_operators_multiprocessing.jl:18-34) — here two real OS
processes join through jax.distributed with a local coordinator, each
exposing 4 virtual CPU devices, and run a sharded equation_search over the
global 8-device mesh (islands x rows = 4 x 2).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.skip(
    reason="jaxlib CPU backend cannot run multi-process computations "
    "('Multiprocess computations aren't implemented on the CPU "
    "backend') — the collective launch fails identically on every CI "
    "host since this test landed. Un-skip on a real multi-host TPU/GPU "
    "slice; tracked as ROADMAP #4 (cross-host pod-slice meshes)."
)
def test_two_process_sharded_search():
    port = _free_port()
    env = dict(os.environ)
    # the workers set their own XLA_FLAGS/platform; drop the suite's 8-dev
    # flag so each worker really has 4 local devices
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers timed out; partial output: {outs}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert "MULTIHOST_OK" in out, f"worker {i} output:\n{out[-3000:]}"
    # both hosts computed the same global search: identical best loss
    best = [o.split("MULTIHOST_OK")[1].strip() for o in outs]
    assert best[0] == best[1], best
