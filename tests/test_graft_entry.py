"""Driver contract: entry() compiles single-chip; dryrun_multichip runs the
full sharded training step on an 8-device virtual mesh (the analog of the
reference's in-process addprocs distributed tests, SURVEY.md §4.3)."""

import sys
from pathlib import Path

import jax
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    scores, losses = out
    assert scores.shape == (1024,)


def test_dryrun_multichip_8():
    assert len(jax.devices()) >= 8, "conftest should provide 8 CPU devices"
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)
