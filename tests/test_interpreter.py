"""Interpreter exactness vs the NumPy oracle, NaN semantics, gradients.

Parity targets: reference test/test_evaluation.jl (every fusion branch ×
dtypes), test/test_nan_detection.jl (NaN/Inf -> complete=false),
test/test_derivatives.jl (gradient correctness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.trees import (
    Expr,
    TreeBatch,
    encode_tree,
    stack_trees,
)
from symbolicregression_jl_tpu.ops.eval_numpy import eval_expr_numpy
from symbolicregression_jl_tpu.ops.interpreter import (
    eval_grad_constants,
    eval_grad_variables,
    eval_tree,
    eval_trees,
)
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size

MAX_LEN = 24


def rand_X(rng, nfeat=5, n=37, scale=2.0):
    return (rng.standard_normal((nfeat, n)) * scale).astype(np.float32)


@pytest.mark.parametrize(
    "binary,unary",
    [
        (["+", "-", "*", "/"], ["cos", "exp"]),
        (["+", "*", "^"], ["log", "sqrt", "abs", "neg"]),
        (["+", "-", "*", "/", "greater", "logical_or"], ["sin", "tanh", "relu", "square", "cube"]),
        (["max", "min", "mod"], ["sigmoid", "gauss", "erf", "atan"]),
    ],
)
def test_random_trees_match_oracle(rng, binary, unary):
    ops = make_operator_set(binary, unary)
    X = rand_X(rng)
    exprs = [
        random_expr_fixed_size(rng, ops, X.shape[0], int(rng.integers(1, 16)))
        for _ in range(50)
    ]
    trees = stack_trees([encode_tree(e, MAX_LEN) for e in exprs])
    y, ok = jax.jit(lambda t: eval_trees(t, jnp.asarray(X), ops))(trees)
    y, ok = np.asarray(y), np.asarray(ok)
    for i, e in enumerate(exprs):
        y_ref, complete_ref = eval_expr_numpy(e, X, ops)
        assert bool(ok[i]) == complete_ref, f"tree {i} ok flag mismatch"
        if complete_ref:
            # Mask rows where float32 itself is ill-conditioned (e.g. trig of
            # huge arguments): float32 vs float64 oracle disagreement.
            y_ref64, _ = eval_expr_numpy(e, X.astype(np.float64), ops)
            stable = np.abs(y_ref - y_ref64) <= 1e-4 * (1.0 + np.abs(y_ref64))
            np.testing.assert_allclose(
                y[i][stable],
                y_ref[stable],
                rtol=2e-4,
                atol=2e-4,
                err_msg=f"tree {i}",
            )


def test_fusion_shapes(rng):
    """Each arity/structure case the reference kernels specialize
    (test/test_evaluation.jl:12-23): deg2 with const/var children, deg1 over
    deg2, etc."""
    ops = make_operator_set(["+", "*"], ["cos"])
    plus, mult, cos = 0, 1, 0
    X = rand_X(rng, nfeat=3, n=11)
    cases = [
        Expr.binary(plus, Expr.const(1.5), Expr.const(2.5)),  # deg2_l0_r0
        Expr.binary(plus, Expr.const(1.5), Expr.var(1)),  # deg2_l0
        Expr.binary(mult, Expr.var(0), Expr.const(2.5)),  # deg2_r0
        Expr.unary(cos, Expr.binary(plus, Expr.const(1.0), Expr.var(2))),  # deg1_l2
        Expr.unary(cos, Expr.unary(cos, Expr.const(0.5))),  # deg1_l1_ll0
        Expr.var(2),
        Expr.const(3.25),
    ]
    for e in cases:
        t = encode_tree(e, MAX_LEN)
        y, ok = eval_tree(t, jnp.asarray(X), ops)
        y_ref, complete = eval_expr_numpy(e, X, ops)
        assert bool(ok) == complete
        np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_nan_detection(rng, dtype):
    """Division by zero, sqrt(-1), log(0), Inf constants -> ok=False
    (reference test/test_nan_detection.jl:5-47)."""
    if dtype == np.float64:
        jax.config.update("jax_enable_x64", True)
    try:
        ops = make_operator_set(["+", "/", "^"], ["sqrt", "log"])
        div, plus = ops.binary_index("/"), ops.binary_index("+")
        sqrt, log = ops.unary_index("sqrt"), ops.unary_index("log")
        X = np.zeros((2, 5), dtype)
        X[0] = [1.0, 2.0, 3.0, 4.0, 5.0]  # positive feature
        X[1] = [-1.0, -2.0, 0.0, 1.0, 2.0]  # mixed feature
        cases = [
            (Expr.binary(div, Expr.const(1.0), Expr.var(1)), False),  # 1/0
            (Expr.unary(sqrt, Expr.var(1)), False),  # sqrt(-1)
            (Expr.unary(sqrt, Expr.var(0)), True),
            (Expr.unary(log, Expr.var(1)), False),
            (Expr.unary(log, Expr.var(0)), True),
            (Expr.binary(plus, Expr.var(0), Expr.const(np.inf)), False),
            (Expr.binary(plus, Expr.var(0), Expr.const(np.nan)), False),
            # intermediate NaN must flag even if later ops could mask it:
            (
                Expr.binary(
                    plus, Expr.unary(sqrt, Expr.var(1)), Expr.const(0.0)
                ),
                False,
            ),
        ]
        for e, expect_ok in cases:
            t = encode_tree(e, MAX_LEN, dtype=dtype)
            _, ok = eval_tree(t, jnp.asarray(X), ops)
            assert bool(ok) == expect_ok, f"{e}"
    finally:
        jax.config.update("jax_enable_x64", False)


def test_empty_and_padded_batch(rng):
    ops = make_operator_set(["+"], [])
    X = rand_X(rng, nfeat=2, n=7)
    t = encode_tree(Expr.var(0), MAX_LEN)
    empty = TreeBatch(
        kind=jnp.zeros(MAX_LEN, jnp.int32),
        op=jnp.zeros(MAX_LEN, jnp.int32),
        feat=jnp.zeros(MAX_LEN, jnp.int32),
        cval=jnp.zeros(MAX_LEN, jnp.float32),
        length=jnp.int32(0),
    )
    batch = stack_trees([t, empty])
    y, ok = eval_trees(batch, jnp.asarray(X), ops)
    assert bool(ok[0]) and not bool(ok[1])
    np.testing.assert_allclose(np.asarray(y[0]), X[0], rtol=1e-6)


def test_grad_constants(rng):
    """d/dc of c*cos(x0) + c2 matches analytic."""
    ops = make_operator_set(["+", "*"], ["cos"])
    plus, mult, cos = 0, 1, 0
    e = Expr.binary(
        plus,
        Expr.binary(mult, Expr.const(1.7), Expr.unary(cos, Expr.var(0))),
        Expr.const(0.3),
    )
    t = encode_tree(e, MAX_LEN)
    X = rand_X(rng, nfeat=1, n=9)
    batch = stack_trees([t])
    y, ok, dy = eval_grad_constants(batch, jnp.asarray(X), ops)
    dy = np.asarray(dy)[0]  # (L, n)
    # constant slots: slot0 = 1.7 (postfix: [1.7, x0, cos, *, 0.3, +])
    np.testing.assert_allclose(dy[0], np.cos(X[0]), rtol=1e-5)
    np.testing.assert_allclose(dy[4], np.ones(9), rtol=1e-5)
    # non-const slots have zero gradient
    np.testing.assert_allclose(dy[1], 0.0)


def test_grad_variables(rng):
    ops = make_operator_set(["*"], ["sin"])
    e = Expr.unary(0, Expr.binary(0, Expr.const(2.0), Expr.var(0)))  # sin(2x)
    t = encode_tree(e, MAX_LEN)
    X = rand_X(rng, nfeat=1, n=13)
    y, dX = eval_grad_variables(t, jnp.asarray(X), ops)
    np.testing.assert_allclose(
        np.asarray(dX)[0], 2.0 * np.cos(2.0 * X[0]), rtol=1e-5, atol=1e-6
    )


def test_batch_shapes(rng):
    """eval_trees supports arbitrary leading batch dims (islands, npop)."""
    ops = make_operator_set(["+", "*"], ["cos"])
    X = rand_X(rng, nfeat=2, n=5)
    exprs = [
        random_expr_fixed_size(rng, ops, 2, 5) for _ in range(6)
    ]
    flat = stack_trees([encode_tree(e, MAX_LEN) for e in exprs])
    nested = jax.tree_util.tree_map(
        lambda x: x.reshape((2, 3) + x.shape[1:]), flat
    )
    y, ok = eval_trees(nested, jnp.asarray(X), ops)
    assert y.shape == (2, 3, 5) and ok.shape == (2, 3)
