"""User-registered operators through eval and a full search (analog of
reference test/test_custom_operators.jl and test/user_defined_operator.jl;
the worker-shipping half of those tests has no analog — SPMD programs are
identical on every host)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.ops.operators import (
    BINARY_REGISTRY,
    UNARY_REGISTRY,
    register_binary,
    register_unary,
)


@pytest.fixture
def custom_ops():
    register_binary("op2c", lambda x, y: x * x + 1.0 / (y * y + 0.1))
    register_unary("op3c", lambda x: jnp.sin(x) + jnp.cos(x))
    yield
    BINARY_REGISTRY.pop("op2c", None)
    UNARY_REGISTRY.pop("op3c", None)


def test_custom_operator_eval_matches_closure(custom_ops, rng):
    """Parse/print/eval round-trip with registered operators, checked
    against the direct closure (reference test_custom_operators.jl:5-24)."""
    ops = sr.make_operator_set(["+", "op2c"], ["op3c"])
    expr = sr.parse_expression("op2c(x0, op3c(x1))", ops)
    tree = jax.tree_util.tree_map(
        jnp.asarray, sr.encode_tree(expr, 16)
    )
    X = jnp.asarray(rng.standard_normal((2, 20)).astype(np.float32))
    y, ok = sr.eval_tree(tree, X, ops)
    assert bool(ok)
    x0, x1 = np.asarray(X[0]), np.asarray(X[1])
    want = x0**2 + 1.0 / ((np.sin(x1) + np.cos(x1)) ** 2 + 0.1)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5)


def test_search_with_custom_operator(custom_ops, rng):
    """A search whose operator set includes a registered custom unary
    recovers a target built from it (reference user_defined_operator.jl)."""
    X = rng.standard_normal((2, 60)).astype(np.float32)
    y = (np.sin(X[0]) + np.cos(X[0])) * 2.0
    res = sr.equation_search(
        X, y, niterations=4,
        binary_operators=["+", "*"], unary_operators=["op3c"],
        npop=24, npopulations=2, ncycles_per_iteration=40, maxsize=10,
        tournament_selection_n=6, verbosity=0, progress=False,
        seed=0, early_stop_condition=1e-6,
    )
    assert res.best_loss().loss < 1e-2
