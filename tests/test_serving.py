"""srserve tests (ISSUE 16): the tenant-batched engine's bit-identity
contract, the job server's bucketing/warm-compile/timeout mechanics,
the tenant-isolation Options guards, and the serving observability
surface (srtpu_serve_* exposition + the queue_stalled alert rule).

The bit-identity tests are the serving contract: tenant t of a batched
search must equal the SOLO equation_search of the same Options
(tenants=1) with seed=seeds[t] — bit for bit, losses and scores
included, fused and chunked drivers alike. conftest forces 8 virtual
CPU devices, so the 4-tenant runs exercise the real (tenants, islands)
mesh: 4 tenants x 2 islands tiles all 8.
"""

import dataclasses

import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import (
    TenantIsolationError,
    make_options,
)
from symbolicregression_jl_tpu.serving import (
    DEFAULT_FEATURE_LADDER,
    DEFAULT_ROW_LADDER,
    JobServer,
    batched_equation_search,
    pad_to_ladder,
)
from symbolicregression_jl_tpu.telemetry.alerts import evaluate_alerts
from symbolicregression_jl_tpu.telemetry.export import (
    render_openmetrics,
    validate_exposition,
)
from symbolicregression_jl_tpu.telemetry.metrics import MetricsRegistry

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=2,
    ncycles_per_iteration=40,
    maxsize=12,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
)


def make_jobs(T=4, n=48, nfeat=2, weighted=True, seed=0):
    rng = np.random.default_rng(seed)
    jobs = []
    for t in range(T):
        X = (rng.standard_normal((nfeat, n)) * 2).astype(np.float32)
        y = X[0] * X[0] + (t + 1) * np.cos(X[-1])
        w = (
            rng.uniform(0.5, 1.5, n).astype(np.float32)
            if weighted else None
        )
        jobs.append((X, y, w))
    return jobs


def frontier(res):
    return [
        (c.complexity, c.equation, float(c.loss), float(c.score))
        for c in res.frontier()
    ]


class _FakeResult:
    """Engine stand-in for host-logic job-server tests."""

    def frontier(self):
        return []


def _solo_frontiers(jobs, opts, seeds, niterations):
    out = []
    for (X, y, w), s in zip(jobs, seeds):
        solo = dataclasses.replace(opts, tenants=1, seed=int(s))
        out.append(frontier(sr.equation_search(
            X, y, weights=w, options=solo, niterations=niterations,
        )))
    return out


@pytest.mark.slow
def test_batched_bit_identity_fused():
    """ISSUE 16 acceptance (fused): each tenant of the 4-tenant batched
    search equals its solo run bit for bit — same Options, per-tenant
    seeds, weighted datasets, (4 tenants x 2 islands) mesh."""
    jobs = make_jobs(T=4)
    opts = make_options(seed=0, **TINY)
    seeds = [10, 11, 12, 13]
    batched = batched_equation_search(
        jobs, options=opts, seeds=seeds, niterations=3,
    )
    solos = _solo_frontiers(jobs, opts, seeds, 3)
    for t in range(4):
        assert frontier(batched[t]) == solos[t], f"tenant {t}"


@pytest.mark.slow
def test_batched_bit_identity_chunked():
    """ISSUE 16 acceptance (chunked): the phased driver carries the
    same contract — chunked batched equals chunked solo bit for bit
    (and, through the existing chunked==fused contract, the fused solo
    too)."""
    jobs = make_jobs(T=4)
    opts = make_options(seed=0, max_cycles_per_dispatch=15, **TINY)
    seeds = [20, 21, 22, 23]
    batched = batched_equation_search(
        jobs, options=opts, seeds=seeds, niterations=2,
    )
    solos = _solo_frontiers(jobs, opts, seeds, 2)
    for t in range(4):
        assert frontier(batched[t]) == solos[t], f"tenant {t}"


@pytest.mark.slow
def test_batched_two_tenants_bit_identity_quick():
    """The small form of the contract: 2 unweighted tenants, 2
    iterations, against solo runs — exercising the vmapped factories,
    the tenant mesh, and the per-tenant PRNG chains end to end. Slow:
    compiles both the batched and the solo program (~3 min on one
    core); tier-1 covers the real dispatch path through
    test_job_server_bucketing_warm_hits_and_exposition instead."""
    jobs = make_jobs(T=2, n=32, weighted=False)
    opts = make_options(seed=0, **{
        **TINY, "ncycles_per_iteration": 20, "npop": 16,
    })
    seeds = [5, 6]
    batched = batched_equation_search(
        jobs, options=opts, seeds=seeds, niterations=2,
    )
    solos = _solo_frontiers(jobs, opts, seeds, 2)
    assert frontier(batched[0]) == solos[0]
    assert frontier(batched[1]) == solos[1]
    # tenants with different data/seed genuinely diverge (the batch is
    # not broadcasting tenant 0 everywhere)
    assert frontier(batched[0]) != frontier(batched[1])


def test_batched_single_tenant_routes_solo(monkeypatch):
    """T=1 delegates to the solo front door (so a 1-job batch carries
    every solo feature and its warm jit cache): the effective Options
    has tenants=1 and the per-tenant seed, weights pass through. The
    solo entry point is stubbed — the solo search itself is covered
    everywhere else; this pins the routing."""
    calls = {}

    def fake_solo(X, y, *, weights=None, options=None, **kw):
        calls.update(X=X, weights=weights, options=options, **kw)
        return "solo-result"

    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.equation_search", fake_solo
    )
    (X, y, w), = make_jobs(T=1, n=32)
    res = batched_equation_search(
        [(X, y, w)], niterations=1, seed=4, **TINY
    )
    assert res == ["solo-result"]
    assert calls["options"].tenants == 1
    assert calls["options"].seed == 4
    assert calls["weights"] is w
    assert calls["niterations"] == 1


def test_batched_input_contracts():
    """Admission rejections fire before any compile: shape mismatch,
    mixed weights, seed-count mismatch, empty batch."""
    jobs = make_jobs(T=2, n=32, weighted=False)
    opts = make_options(**TINY)
    bad_shape = [jobs[0], (jobs[1][0][:, :16], jobs[1][1][:16], None)]
    with pytest.raises(ValueError, match="pad ladder"):
        batched_equation_search(bad_shape, options=opts)
    mixed = [
        jobs[0],
        (jobs[1][0], jobs[1][1], np.ones(32, np.float32)),
    ]
    with pytest.raises(ValueError, match="all-or-none"):
        batched_equation_search(mixed, options=opts)
    with pytest.raises(ValueError, match="seeds"):
        batched_equation_search(jobs, options=opts, seeds=[1, 2, 3])
    with pytest.raises(ValueError, match=">= 1 dataset"):
        batched_equation_search([], options=opts)


def test_tenant_isolation_guards():
    """Options combinations that cannot keep tenants isolated are
    rejected up front (ISSUE 16 satellite): stateful recorder hooks and
    shared output paths raise the structured TenantIsolationError,
    row_shards conflicts with the (tenants, islands) mesh, and the solo
    front door refuses tenants > 1 outright."""
    with pytest.raises(TenantIsolationError) as ei:
        make_options(
            binary_operators=["+"], tenants=2,
            snapshot_path="/tmp/one_file.pkl",
        )
    assert "snapshot_path" in ei.value.fields
    with pytest.raises(ValueError, match="row_shards"):
        make_options(binary_operators=["+"], tenants=2, row_shards=2)
    # a per-tenant template is fine
    make_options(
        binary_operators=["+"], tenants=2,
        snapshot_path="/tmp/snap_{tenant}.pkl",
    )
    X = np.ones((2, 16), np.float32)
    y = np.ones(16, np.float32)
    with pytest.raises(ValueError, match="batched_equation_search"):
        sr.equation_search(
            X, y, niterations=1, tenants=2, runtests=False, **TINY
        )


def test_pad_to_ladder():
    assert pad_to_ladder(1, DEFAULT_ROW_LADDER) == 32
    assert pad_to_ladder(32, DEFAULT_ROW_LADDER) == 32
    assert pad_to_ladder(33, DEFAULT_ROW_LADDER) == 64
    assert pad_to_ladder(8192, DEFAULT_ROW_LADDER) == 8192
    # beyond the ladder: next power of two, never a crash
    assert pad_to_ladder(9000, DEFAULT_ROW_LADDER) == 16384
    assert pad_to_ladder(3, DEFAULT_FEATURE_LADDER) == 4
    assert pad_to_ladder(32, DEFAULT_FEATURE_LADDER) == 32


def test_job_server_bucketing_warm_hits_and_exposition(tmp_path):
    """The bucketing/warm-compile path end to end: 4 same-shape jobs at
    max_tenants=2 make 2 dispatches of the SAME (bucket, T) — the
    second is a warm hit; every job completes with a finite-loss
    frontier; run ids land in the fleet registry; the serve gauges
    render as a valid OpenMetrics exposition."""
    registry = MetricsRegistry()
    fleet_root = str(tmp_path / "fleet")
    server = JobServer(
        niterations=1, max_tenants=2, flush_timeout_s=60.0,
        fleet_root=fleet_root, registry=registry,
        seed=0, **{**TINY, "npop": 16, "ncycles_per_iteration": 20},
    )
    # different ROW COUNTS, one padded bucket: 30 and 27 both quantize
    # to the 32 rung
    rng = np.random.default_rng(0)
    for i, n in enumerate([30, 27, 30, 27]):
        X = rng.standard_normal((2, n)).astype(np.float32)
        y = X[0] * X[0]
        server.submit(X, y, job_id=f"j{i}", seed=i)
    assert server.pending() == 4
    assert server.stats()["buckets"] == 1

    done = server.drain()
    assert sorted(j.job_id for j in done) == ["j0", "j1", "j2", "j3"]
    assert server.pending() == 0
    stats = server.stats()
    assert stats["dispatches"] == 2
    assert stats["warm_hits"] == 1
    assert server.warm_hit_rate == pytest.approx(0.5)
    warm_flags = {j.job_id: j.warm for j in done}
    assert not warm_flags["j0"] and warm_flags["j2"]
    for j in done:
        assert j.tenants == 2
        assert j.result.frontier()
        assert np.isfinite(min(c.loss for c in j.result.frontier()))
        assert j.latency_s >= j.queue_wait_s >= 0.0

    from symbolicregression_jl_tpu.telemetry.fleet import load_registry

    recs = load_registry(fleet_root)
    assert sorted(r["run_id"] for r in recs) == ["j0", "j1", "j2", "j3"]
    assert all(r["source"] == "srserve" for r in recs)

    text = render_openmetrics(registry=registry)
    assert validate_exposition(text) == []
    for name in (
        "srtpu_serve_queue_depth",
        "srtpu_serve_bucket_fill",
        "srtpu_serve_warm_hit_rate",
        "srtpu_serve_job_latency_seconds",
        "srtpu_serve_tenants",
    ):
        assert name in text, name


def test_job_server_timeout_flush_with_fake_clock(monkeypatch):
    """Partial buckets sit until the flush timeout, then dispatch (the
    injectable clock makes the timing deterministic); distinct shapes
    land in distinct buckets. The engine is stubbed — flush/bucket
    mechanics are host-side; the real dispatch path is covered by
    test_job_server_bucketing_warm_hits_and_exposition."""
    dispatched = []

    def fake_engine(datasets, *, seeds=None, **kw):
        dispatched.append((len(datasets), list(seeds)))
        return [_FakeResult() for _ in datasets]

    monkeypatch.setattr(
        "symbolicregression_jl_tpu.serving.jobs.batched_equation_search",
        fake_engine,
    )
    now = [0.0]
    server = JobServer(
        niterations=1, max_tenants=4, flush_timeout_s=2.0,
        clock=lambda: now[0],
        seed=0, **{**TINY, "npop": 16, "ncycles_per_iteration": 20},
    )
    rng = np.random.default_rng(1)
    X = rng.standard_normal((2, 20)).astype(np.float32)
    server.submit(X, X[0] * X[0], job_id="small")
    X2 = rng.standard_normal((3, 100)).astype(np.float32)
    server.submit(X2, X2[0] + X2[1], job_id="big")
    assert server.stats()["buckets"] == 2  # (32, 2) vs (128, 4) pads

    assert server.flush() == []            # under the timeout: holds
    assert server.pending() == 2
    now[0] = 2.5
    assert server.oldest_wait_s() == pytest.approx(2.5)
    done = server.flush()                  # past the timeout: partial
    assert sorted(j.job_id for j in done) == ["big", "small"]
    assert all(j.tenants == 1 for j in done)
    assert server.pending() == 0
    # two single-job dispatches, never a cross-bucket batch
    assert dispatched == [(1, [0]), (1, [0])]


def test_queue_stalled_alert_rule():
    """The queue_stalled rule fires on a JobServer.alert_row-shaped row
    whose oldest wait exceeds the deadline — default 4x the server's
    own flush timeout, overridable via ctx['queue_deadline_s'] — and
    stays silent on fresh queues and non-queue rows."""
    row = {
        "run_id": "srserve-queue",
        "serve_queue_depth": 3,
        "serve_queue_oldest_wait_s": 9.0,
        "serve_flush_timeout_s": 2.0,
    }
    fired = evaluate_alerts([row], {})
    assert [a["rule"] for a in fired] == ["queue_stalled"]
    assert fired[0]["severity"] == "warning"
    assert fired[0]["value"] == 9.0 and fired[0]["threshold"] == 8.0

    fresh = dict(row, serve_queue_oldest_wait_s=1.0)
    assert evaluate_alerts([fresh], {}) == []
    # explicit deadline wins over the flush-timeout default
    assert evaluate_alerts([fresh], {"queue_deadline_s": 0.5}) != []
    # rows without the queue fields never trip it
    assert evaluate_alerts(
        [{"run_id": "r0", "verdict": "completed"}], {}
    ) == []

    # the live server produces a row the rule can read
    server = JobServer(
        flush_timeout_s=2.0, clock=lambda: 0.0,
        binary_operators=["+"], verbosity=0, progress=False,
    )
    r = server.alert_row()
    assert r["serve_queue_oldest_wait_s"] is None
    assert evaluate_alerts([r], {}) == []


def test_batched_telemetry_and_registry(tmp_path):
    """Per-tenant telemetry fan-out: the batched run writes run_start /
    serve_metrics / run_end events carrying per-tenant arrays, and the
    registry gains tenant-indexed best-loss gauges from ONE fused
    reduction per observed iteration."""
    import glob
    import json

    # weighted jobs + the bucketing test's Options: same graph key and
    # shapes as its dispatches, so this rides that test's warm compile
    # (telemetry_every is host cadence, not part of the graph key)
    jobs = make_jobs(T=2, n=32)
    registry = MetricsRegistry()
    tdir = str(tmp_path / "events")
    opts = make_options(
        seed=0, telemetry_every=1,
        **{**TINY, "npop": 16, "ncycles_per_iteration": 20},
    )
    batched_equation_search(
        jobs, options=opts, seeds=[1, 2], niterations=1,
        registry=registry, telemetry_dir=tdir,
    )
    gauges = registry.snapshot()["gauges"]
    assert "serve_tenant_best_loss_0" in gauges
    assert "serve_tenant_best_loss_1" in gauges
    assert gauges["serve_tenants"] == 2

    logs = glob.glob(tdir + "/events-*.jsonl")
    assert logs
    events = [
        json.loads(line)
        for line in open(logs[0])
        if line.strip()
    ]
    kinds = [e.get("type") for e in events]
    assert "run_start" in kinds and "run_end" in kinds
    start = events[kinds.index("run_start")]
    assert start["tenants"] == 2 and start["seeds"] == [1, 2]
    sm = [e for e in kinds if e == "serve_metrics"]
    assert sm, "no serve_metrics events"
    end = events[kinds.index("run_end")]
    assert len(end["best_loss"]) == 2 and len(end["num_evals"]) == 2
