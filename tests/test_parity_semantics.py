"""Micro-tests pinning reference-exact search semantics (round 3):
mutation-weight conditioning (src/Mutate.jl:54-62), tournament frequency
range gating (src/Population.jl:96-101), and the acceptance gate's
normalized-frequency ratio with its out-of-range 1e-6 constant
(src/Mutate.jl:231-245). These are distribution-level semantics the e2e
recovery tests can't distinguish from near-misses — pin them directly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.evolve import (
    _accept_mutation,
    _adjusted_mutation_logits,
)
from symbolicregression_jl_tpu.models.options import (
    ADD_NODE,
    INSERT_NODE,
    MUTATE_CONSTANT,
    make_options,
)
from symbolicregression_jl_tpu.models.trees import (
    encode_tree,
    parse_expression,
)
from symbolicregression_jl_tpu.ops.operators import make_operator_set

OPS = make_operator_set(["+", "*"], ["cos"])
OPT = make_options(
    binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10
)


def tree_of(s, max_len=16):
    return jax.tree_util.tree_map(
        jnp.asarray, encode_tree(parse_expression(s, OPS), max_len)
    )


def logits_of(s, curmaxsize=10):
    return np.asarray(
        _adjusted_mutation_logits(tree_of(s), jnp.int32(curmaxsize), OPT)
    )


def test_mutate_constant_weight_scales_with_constant_count():
    """weights.mutate_constant *= min(8, #constants)/8 (src/Mutate.jl:54)."""
    base = OPT.mutation_weights.mutate_constant
    w1 = np.exp(logits_of("x0 + 1.5")[MUTATE_CONSTANT])
    w2 = np.exp(logits_of("(x0 + 1.5) * (2.5 + 0.5)")[MUTATE_CONSTANT])
    assert w1 == pytest.approx(base * 1 / 8, rel=1e-6)
    assert w2 == pytest.approx(base * 3 / 8, rel=1e-6)
    # zero constants -> impossible
    assert logits_of("x0 + x1")[MUTATE_CONSTANT] == -np.inf


def test_add_insert_zeroed_at_size_and_depth_caps():
    """n >= curmaxsize OR depth >= maxdepth zeroes add/insert
    (src/Mutate.jl:58-61)."""
    # size cap: complexity 5 vs curmaxsize 5
    lg = logits_of("(x0 + x1) * 1.5", curmaxsize=5)
    assert lg[ADD_NODE] == -np.inf and lg[INSERT_NODE] == -np.inf
    # depth cap: maxdepth defaults to maxsize=10; build depth-10 chain
    deep = "cos(" * 9 + "x0" + ")" * 9
    lg2 = logits_of(deep, curmaxsize=32)
    assert lg2[ADD_NODE] == -np.inf and lg2[INSERT_NODE] == -np.inf
    # under both caps: present
    lg3 = logits_of("x0 + x1", curmaxsize=10)
    assert np.isfinite(lg3[ADD_NODE]) and np.isfinite(lg3[INSERT_NODE])


def _accept_prob(old_s, new_s, freqs, old_tree, new_tree, n=4096, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    acc = jax.vmap(
        lambda k: _accept_mutation(
            k, old_tree, new_tree, jnp.float32(old_s), jnp.float32(new_s),
            jnp.float32(0.5), freqs, OPT,
        )
    )(keys)
    return float(np.mean(np.asarray(acc)))


def test_acceptance_frequency_ratio_normalized_with_oob_constant():
    """prob *= f_old/f_new with NORMALIZED in-range frequencies and the
    exact constant 1e-6 out of range (src/Mutate.jl:231-245)."""
    S = OPT.actual_maxsize
    # complexity = node count here: 3 and 5
    t3, t5 = tree_of("x0 + x1"), tree_of("x0 + (x1 * x0)")
    freqs = jnp.ones(S, jnp.float32).at[2].set(8.0)  # size 3 bin = 8x
    # equal scores -> annealing factor 1; ratio = f(3)/f(5)
    tot = S - 1 + 8.0
    expect = (8.0 / tot) / (1.0 / tot)  # = 8
    p = _accept_prob(1.0, 1.0, freqs, t3, t5)
    assert p == pytest.approx(min(1.0, expect), abs=0.05)  # ratio > 1 -> ~1
    p_rev = _accept_prob(1.0, 1.0, freqs, t5, t3)
    assert p_rev == pytest.approx(1.0 / 8.0, abs=0.03)
    # out-of-range member (complexity 13 > maxsize 10, also beyond the
    # maxsize+2 histogram): its frequency is the constant 1e-6 in
    # NORMALIZED units -> old tiny, new in-range normal -> ratio
    # ~ 1e-6/(1/tot) << 1 -> essentially never accepted
    t13 = tree_of("((x0+x1)*(x0+x1))*((x0+x1)*1.5)")  # complexity 13 > maxsize
    from symbolicregression_jl_tpu.models.complexity import (
        compute_complexity,
    )

    assert int(compute_complexity(t13, OPT)) == 13
    p_oob = _accept_prob(1.0, 1.0, jnp.ones(S, jnp.float32), t13, t3)
    assert p_oob < 0.01
