"""Unified search telemetry (ISSUE 7): spans, metrics registry, JSONL
event log, and the satellites that ride along (bench roofline skip
reasons, quiet-mode ResourceMonitor, recorder/cache_stats schema).

File name sorts EARLY (test_ab_*) and everything outside the `slow`
marker is CPU-only host-side unit work (<10s total): the tier-1 budget
(memory: tier1-timing-budget) pays for dots, not searches. The
full-search round trips — bit-identical HoF with telemetry on/off, the
seven-span event log from a real run — live under `slow`.
"""

import json
import math
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu.telemetry import (
    STAGES,
    EventLog,
    MetricsRegistry,
    SpanRecorder,
    validate_event,
    validate_events_file,
)
from symbolicregression_jl_tpu.telemetry.spans import NULL as NULL_SPANS

GOLDEN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data", "telemetry",
    "golden_events.jsonl",
)


class FakeSink:
    def __init__(self):
        self.events = []

    def emit(self, type, **fields):
        self.events.append({"type": type, **fields})
        return self.events[-1]


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_recorder_records_and_emits():
    sink = FakeSink()
    rec = SpanRecorder(sink)
    rec.set_context(output=0, iteration=3)
    with rec.span("cycle", chunks=2) as sp:
        sp.fence = np.ones(4)  # block_until_ready passthrough
        sp.attrs["extra"] = 1
    assert len(rec.spans) == 1
    sp = rec.spans[0]
    assert sp.name == "cycle" and sp.duration_s >= 0.0
    assert sp.attrs == {"output": 0, "iteration": 3, "chunks": 2,
                        "extra": 1}
    (ev,) = sink.events
    assert ev["type"] == "span" and ev["name"] == "cycle"
    assert ev["attrs"]["iteration"] == 3
    # context update replaces; None removes
    rec.set_context(iteration=4, output=None)
    with rec.span("simplify"):
        pass
    assert rec.spans[-1].attrs == {"iteration": 4}
    assert rec.total_s("cycle") == sp.duration_s


def test_span_retention_capped_and_run_ids_unique():
    rec = SpanRecorder(max_retained=3)
    for i in range(5):
        with rec.span(f"s{i}"):
            pass
    assert [s.name for s in rec.spans] == ["s2", "s3", "s4"]
    from symbolicregression_jl_tpu.telemetry.events import _default_run_id

    # sub-second back-to-back runs must not collide on the log path
    assert _default_run_id() != _default_run_id()


def test_span_exception_recorded_and_reraised():
    rec = SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("optimize"):
            raise RuntimeError("boom")
    assert rec.spans[-1].attrs["error"] == "RuntimeError"


def test_null_span_recorder_is_inert():
    with NULL_SPANS.span("cycle") as sp:
        sp.fence = np.ones(2)
    assert NULL_SPANS.spans == []


def test_stage_vocabulary_is_the_srmem_one():
    # the names build_stage_programs decomposes the iteration into
    # (asserted against STAGES inside analysis.memory at build time)
    assert STAGES == (
        "init", "cycle", "mutate", "eval", "simplify", "optimize",
        "merge_migrate",
    )


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_semantics():
    reg = MetricsRegistry()
    c = reg.counter("iters", "help")
    c.inc()
    c.inc(2)
    assert reg.counter("iters").value == 3  # same instrument back
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(TypeError):
        reg.gauge("iters")  # kind mismatch
    g = reg.gauge("best_loss")
    g.set(np.float32(0.5))
    h = reg.histogram("length", [4, 8, 12])
    h.observe(3)
    h.observe(9)
    h.observe(99)  # overflow bucket
    h.add_counts([1, 0, 0])
    assert h.counts == [2, 0, 1, 1] and h.total == 4
    with pytest.raises(ValueError):
        reg.histogram("bad", [8, 4])
    snap = reg.snapshot()
    assert snap["counters"]["iters"] == 3.0
    assert snap["gauges"]["best_loss"] == 0.5
    assert snap["histograms"]["length"]["counts"] == [2, 0, 1, 1]
    # non-finite gauge values become None (strict-JSON event log)
    g.set(float("inf"))
    assert reg.snapshot()["gauges"]["best_loss"] is None


def test_hypervolume_2d_bounds():
    from symbolicregression_jl_tpu.telemetry.metrics import hypervolume_2d

    # the HoF frontier of 4 slots: members at complexity 2 (loss 0.5)
    # and 3 (loss 0.1), reference (S+1, baseline) — the staircase
    # covers slots 2..4: [0, 0.5, 0.9, 0.9] / 4 in normalized units
    hv = hypervolume_2d([2, 3], [0.5, 0.1], ref_complexity=5,
                        ref_loss=1.0)
    assert math.isclose(hv, (0.0 + 0.5 + 0.9 + 0.9) / 4)
    assert hypervolume_2d([2, 3], [0.5, 0.1], 5, 0.0) == 0.0
    assert hypervolume_2d([], [], 5, 1.0) == 0.0


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------


def test_event_log_line_buffered_strict_json(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")
    log.emit(
        "run_start", config_fingerprint="abc", backend="cpu",
        devices=["TFRT_CPU_0"], nout=1,
    )
    log.emit(
        "span", name="eval", t_start=1.0, duration_s=0.5,
        attrs={"bad": float("nan"), "arr": np.arange(3),
               "f": np.float32(2.0)},
    )
    # crash-safety: both lines are on disk BEFORE close (line-buffered)
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 2
    ev = json.loads(lines[1])
    assert ev["v"] == 1 and ev["run"] == "r1"
    assert ev["attrs"]["bad"] is None  # NaN sanitized, strict JSON
    assert ev["attrs"]["arr"] == [0, 1, 2]
    assert ev["attrs"]["f"] == 2.0
    log.close()
    report = validate_events_file(path)
    assert report["ok"], report["problems"]
    assert report["events"] == 2


def test_event_log_never_fatal_on_hostile_fields(tmp_path):
    # arbitrary objects (np.asarray would wrap them as 0-d object
    # arrays) stringify instead of recursing; emit survives anything
    import pathlib

    from symbolicregression_jl_tpu.telemetry.events import _sanitize

    class Weird:
        def __repr__(self):
            return "<weird>"

    assert _sanitize(Weird()) == "<weird>"
    assert _sanitize(pathlib.Path("/tmp/x")) in ("/tmp/x", "\\tmp\\x")
    assert _sanitize(np.array([Weird()], dtype=object)) == ["<weird>"]
    log = EventLog(str(tmp_path / "e.jsonl"), run_id="r")
    ev = log.emit("probe_error", error="x", ctx=Weird())
    assert ev is not None and ev["ctx"] == "<weird>"
    log.close()
    assert validate_events_file(str(tmp_path / "e.jsonl"))["events"] == 1


def test_validate_catches_schema_violations(tmp_path):
    # per-type requirements: a span without its name/duration fails
    bad = {"v": 1, "t": 0.0, "run": "r", "type": "span"}
    problems = validate_event(bad)
    assert any("name" in p for p in problems)
    assert any("duration_s" in p for p in problems)
    # wrong envelope version
    assert validate_event({"v": 2, "t": 0.0, "run": "r",
                           "type": "run_end"})
    # unknown type
    assert validate_event({"v": 1, "t": 0.0, "run": "r", "type": "nope"})
    # file-level: first event must be run_start; bare Infinity rejected
    p = tmp_path / "bad.jsonl"
    p.write_text(
        '{"v": 1, "t": 0.0, "run": "r", "type": "run_end", '
        '"num_evals": Infinity, "search_time_s": 1.0}\n'
    )
    report = validate_events_file(str(p))
    assert not report["ok"]
    assert any("strict JSON" in x for x in report["problems"])


def test_golden_fixture_validates_with_all_stage_spans():
    # the same invariant scripts/lint.py's telemetry-schema gate enforces
    report = validate_events_file(GOLDEN)
    assert report["ok"], report["problems"]
    names = set()
    with open(GOLDEN) as f:
        for line in f:
            e = json.loads(line)
            if e["type"] == "span":
                names.add(e["name"])
    assert set(STAGES) <= names


def test_lint_telemetry_schema_gate():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "srtpu_lint", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "lint.py",
        )
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.check_telemetry_schema()
    assert out["ok"], out["detail"]
    assert out["events"] > 0


# ---------------------------------------------------------------------------
# satellites: bench roofline skip reason
# ---------------------------------------------------------------------------


def test_roofline_skip_reason_selection():
    import importlib

    bench = importlib.import_module("bench")
    fn = bench._roofline_skip_reason
    assert fn("cpu", False) == "cpu-only"
    # CPU wins even if routing would have picked the kernel elsewhere
    assert fn("cpu", True) == "cpu-only"
    assert fn("tpu", False) == "interpreter-path"
    assert fn("tpu", True, ImportError("no roofline")) == "import-failure"
    # ModuleNotFoundError is an ImportError: same reason
    assert fn("tpu", True, ModuleNotFoundError("x")) == "import-failure"
    assert fn("tpu", True, ZeroDivisionError()) == "error: ZeroDivisionError"
    assert fn("tpu", True, None) is None  # fraction should exist


# ---------------------------------------------------------------------------
# satellites: ResourceMonitor quiet mode + sink
# ---------------------------------------------------------------------------


def _tripped_monitor(**kw):
    from symbolicregression_jl_tpu.utils.progress import ResourceMonitor

    m = ResourceMonitor(warn_fraction=0.2, **kw)
    for _ in range(5):
        m.note(device_s=0.1, host_s=0.9)
    return m


def test_resource_monitor_emits_event_and_respects_quiet(
    monkeypatch, capsys
):
    # quiet console (verbosity=0): the event still lands on the sink,
    # nothing is printed
    monkeypatch.setenv("SYMBOLIC_REGRESSION_TEST", "")
    sink = FakeSink()
    m = _tripped_monitor(sink=sink, verbosity=0)
    m.maybe_warn()
    (ev,) = sink.events
    assert ev["type"] == "resource_warning"
    assert ev["host_occupation"] == pytest.approx(0.9)
    assert capsys.readouterr().err == ""
    # verbose console: printed once, never twice
    m2 = _tripped_monitor(sink=None, verbosity=1)
    m2.maybe_warn()
    m2.maybe_warn()
    assert capsys.readouterr().err.count("Warning") == 1
    # SYMBOLIC_REGRESSION_TEST=true silences the console but not the sink
    monkeypatch.setenv("SYMBOLIC_REGRESSION_TEST", "true")
    sink3 = FakeSink()
    m3 = _tripped_monitor(sink=sink3, verbosity=1)
    m3.maybe_warn()
    assert len(sink3.events) == 1
    assert capsys.readouterr().err == ""


def test_progress_report_emits_event_without_console(capsys):
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.utils.progress import SearchProgress

    sink = FakeSink()
    progress = SearchProgress(4, make_options(verbosity=0), sink=sink)
    progress.report(
        0, float("inf"), 100.0, cache_counts=(10, 8, 2),
        console=False, output=0, search_iteration=0,
    )
    (ev,) = sink.events
    assert ev["type"] == "progress"
    assert ev["best_loss"] is None  # inf -> null (strict JSON)
    assert ev["num_evals"] == 100.0
    assert ev["cache"] == {"scored": 10, "unique": 8, "memo_hits": 2}
    assert capsys.readouterr().out == ""


# ---------------------------------------------------------------------------
# satellites: recorder out{j}_cache payload + sink; checkpoint event
# ---------------------------------------------------------------------------


def test_recorder_cache_payload_schema_and_save_event(tmp_path):
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.utils.recorder import Recorder

    sink = FakeSink()
    opts = make_options(cache_fitness=True, verbosity=0)
    rec = Recorder(opts, sink=sink)
    fields = ("scored", "unique", "memo_hits", "evaluated",
              "unique_ratio", "memo_hit_rate", "eval_batch_fill")
    for it in range(3):
        rec.record_cache(1, it, {
            "output": 1, "iteration": it, "scored": 100 * (it + 1),
            "unique": 60, "memo_hits": 10 * it, "evaluated": 60 - 10 * it,
            "unique_ratio": 0.6, "memo_hit_rate": 0.1 * it,
            "eval_batch_fill": 0.5,
        })
    cache = rec.record["out2_cache"]
    assert sorted(cache) == ["iteration1", "iteration2", "iteration3"]
    for entry in cache.values():
        assert all(k in entry for k in fields)
        assert "output" not in entry and "iteration" not in entry
    path = rec.save(str(tmp_path / "rec.json"))
    (ev,) = sink.events
    assert ev["type"] == "recorder_saved" and ev["path"] == path


def test_save_search_state_emits_saved_state_event(tmp_path):
    from symbolicregression_jl_tpu.api import SearchState
    from symbolicregression_jl_tpu.utils.checkpoint import (
        load_search_state,
        save_search_state,
    )

    sink = FakeSink()
    state = SearchState(
        island_states={"a": np.ones(3, np.float32)},
        global_hof={"b": np.zeros(2, np.float32)},
        iteration=4,
    )
    path = str(tmp_path / "run.ckpt")
    save_search_state(path, [state], sink=sink)
    (ev,) = sink.events
    assert ev["type"] == "saved_state"
    assert ev["path"] == path and ev["outputs"] == 1
    assert ev["iteration"] == 4
    assert load_search_state(path)[0].iteration == 4


# ---------------------------------------------------------------------------
# options knobs
# ---------------------------------------------------------------------------


def test_telemetry_options_are_orchestration_only():
    from symbolicregression_jl_tpu.models.options import make_options

    base = make_options()
    tele = make_options(
        telemetry=True, telemetry_dir="/tmp/x", telemetry_every=3
    )
    # same compiled graph: hash/eq ignore the telemetry knobs, so the
    # jit factories' lru_caches hit across them
    assert base == tele and hash(base) == hash(tele)
    with pytest.raises(ValueError):
        make_options(telemetry_every=0)


# ---------------------------------------------------------------------------
# full-search round trips (slow: real compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_search_telemetry_round_trip(tmp_path):
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(0)
    X = rng.standard_normal((2, 64)).astype(np.float32)
    y = 2.0 * np.cos(X[1]) + X[0] ** 2
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        niterations=2, npopulations=3, npop=16, ncycles_per_iteration=8,
        maxsize=10, seed=5, verbosity=0, progress=False,
    )
    r_off = sr.equation_search(X, y, **kw)
    r_on = sr.equation_search(
        X, y, telemetry=True, telemetry_dir=str(tmp_path),
        telemetry_every=1, **kw,
    )

    def frontier(r):
        return [
            (c.complexity, float(c.loss), float(c.score), c.equation)
            for c in r.frontier()
        ]

    # ISSUE 7 acceptance: telemetry must not change the search
    assert frontier(r_off) == frontier(r_on)

    (path,) = [
        os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
        if f.endswith(".jsonl")
    ]
    report = validate_events_file(path)
    assert report["ok"], report["problems"]
    events = [json.loads(line) for line in open(path)]
    assert events[0]["type"] == "run_start"
    assert events[-1]["type"] == "run_end"
    span_names = [e["name"] for e in events if e["type"] == "span"]
    assert set(STAGES) <= set(span_names)  # all seven stages
    # per-iteration phases appear once per iteration; probes once per run
    assert span_names.count("simplify") == 2
    assert span_names.count("mutate") == 1
    metrics = [e for e in events if e["type"] == "metrics"]
    assert [m["iteration"] for m in metrics] == [0, 1]
    for m in metrics:
        snap = m["snapshot"]
        assert snap["gauges"]["best_loss"] is not None
        assert snap["gauges"]["hof_size"] >= 1
        # search-dynamics fields (ISSUE 10): exact hypervolume,
        # per-island diversity, Pareto snapshot, per-mutation counters
        assert 0.0 <= snap["gauges"]["hof_hypervolume"] <= 1.0
        assert 0.0 < snap["gauges"]["population_diversity"] <= 1.0
        assert sum(
            snap["histograms"]["population_length"]["counts"]
        ) == 3 * 16  # islands x npop
        assert len(m["per_island"]["best_loss"]) == 3
        assert len(m["per_island"]["diversity"]) == 3
        assert all(0.0 < d <= 1.0 for d in m["per_island"]["diversity"])
        pareto = m["pareto"]
        assert len(pareto["complexity"]) == len(pareto["loss"]) >= 1
        assert pareto["complexity"] == sorted(pareto["complexity"])
        muts = m["mutations"]
        from symbolicregression_jl_tpu.models.evolve import (
            MUTATION_NAMES,
        )

        assert set(muts) == set(MUTATION_NAMES)
        for row in muts.values():
            assert 0 <= row["accepted"] <= row["proposed"]
    # acceptance counters are cumulative: monotone across snapshots
    first, last = metrics[0]["mutations"], metrics[-1]["mutations"]
    assert all(
        last[k]["proposed"] >= first[k]["proposed"] for k in first
    )
    assert [e for e in events if e["type"] == "progress"]
    # the run doctor reads this same log as healthy
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    report = analyze_run(path)
    assert report["verdict"] == "healthy", report["reasons"]
    assert report["spans_complete"]


@pytest.mark.slow
def test_chunked_driver_telemetry_bit_identical(tmp_path):
    """ISSUE 10 acceptance: telemetry on/off HoF bit-identity holds on
    the CHUNKED dispatch driver too (max_cycles_per_dispatch set), not
    only the fused one — the dynamics reduction reads state, never
    perturbs the phase programs."""
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(3)
    X = rng.standard_normal((2, 64)).astype(np.float32)
    y = X[0] * X[1] + np.cos(X[1])
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        niterations=2, npopulations=3, npop=16, ncycles_per_iteration=8,
        maxsize=10, seed=11, verbosity=0, progress=False,
        max_cycles_per_dispatch=3,
    )
    r_off = sr.equation_search(X, y, **kw)
    r_on = sr.equation_search(
        X, y, telemetry=True, telemetry_dir=str(tmp_path), **kw
    )

    def frontier(r):
        return [
            (c.complexity, float(c.loss), float(c.score), c.equation)
            for c in r.frontier()
        ]

    assert frontier(r_off) == frontier(r_on)
    (path,) = [
        os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
        if f.endswith(".jsonl")
    ]
    report = validate_events_file(path)
    assert report["ok"], report["problems"]
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    assert analyze_run(path)["verdict"] == "healthy"


@pytest.mark.slow
def test_cache_stats_schema_and_recorder_cache_round_trip(tmp_path):
    """ISSUE 7 satellite: result.cache_stats schema + monotone counters
    and the Recorder's out{j}_cache payloads from a REAL search (the
    cache suite case was the only thing asserting these)."""
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(1)
    X = rng.standard_normal((2, 64)).astype(np.float32)
    y = X[0] * X[1] - 0.5
    sr.clear_memo_banks()
    r = sr.equation_search(
        X, y,
        binary_operators=["+", "-", "*"],
        niterations=3, npopulations=2, npop=16, ncycles_per_iteration=8,
        maxsize=10, seed=2, verbosity=0, progress=False,
        cache_fitness=True, recorder=True,
        recorder_file=str(tmp_path / "rec.json"),
    )
    stats = r.cache_stats
    assert set(stats) == {"totals", "per_iteration", "banks"}
    totals = stats["totals"]
    for k in ("scored", "unique", "memo_hits", "evaluated", "hit_rate",
              "unique_ratio"):
        assert k in totals
    rows = stats["per_iteration"]
    assert len(rows) == 3
    cum = np.zeros(3, np.int64)
    for i, row in enumerate(rows):
        assert row["iteration"] == i and row["output"] == 0
        delta = np.array(
            [row["scored"], row["unique"], row["memo_hits"]], np.int64
        )
        # per-iteration deltas of cumulative device counters: never
        # negative, so the cumulative series is monotone non-decreasing
        assert (delta >= 0).all()
        assert row["evaluated"] == row["unique"] - row["memo_hits"]
        cum += delta
    assert totals["scored"] == int(cum[0])
    assert totals["unique"] == int(cum[1])
    assert totals["memo_hits"] == int(cum[2])
    # recorder carries the same rows under out1_cache
    rec = json.load(open(tmp_path / "rec.json"))
    cache = rec["out1_cache"]
    assert sorted(cache) == ["iteration1", "iteration2", "iteration3"]
    for i, row in enumerate(rows):
        entry = cache[f"iteration{i + 1}"]
        assert entry["scored"] == row["scored"]
        assert entry["memo_hits"] == row["memo_hits"]
    sr.clear_memo_banks()
