"""Scalar operator-semantics table across dtypes — the analog of the
reference's generic operator tests (test/test_operators.jl:26-66):
NaN-safe domain guards, pow edge cases, comparison/logical semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.ops.operators import (
    BINARY_REGISTRY,
    UNARY_REGISTRY,
)


def u(name, x, dtype):
    return float(UNARY_REGISTRY[name](jnp.asarray(x, dtype)))


def b(name, x, y, dtype):
    return float(
        BINARY_REGISTRY[name](jnp.asarray(x, dtype), jnp.asarray(y, dtype))
    )


DTYPES = [jnp.float32, jnp.float16, jnp.bfloat16]


@pytest.mark.parametrize("dtype", DTYPES)
def test_safe_unary_domains(dtype):
    val, val2 = 0.5, 3.2
    tol = 2e-2 if dtype != jnp.float32 else 1e-6
    assert abs(u("log", val, dtype) - np.log(val)) < tol
    assert np.isnan(u("log", -val, dtype))
    assert np.isnan(u("log", 0.0, dtype))
    assert abs(u("log2", val, dtype) - np.log2(val)) < tol
    assert np.isnan(u("log2", -val, dtype))
    assert np.isnan(u("log2", 0.0, dtype))
    assert abs(u("log10", val, dtype) - np.log10(val)) < tol
    assert np.isnan(u("log10", -val, dtype))
    assert abs(u("acosh", val2, dtype) - np.arccosh(val2)) < tol * 2
    assert np.isnan(u("acosh", -val2, dtype))
    assert abs(u("sqrt", val, dtype) - np.sqrt(val)) < tol
    assert np.isnan(u("sqrt", -val, dtype))
    assert u("neg", -val, dtype) == pytest.approx(val, abs=tol)
    assert u("square", val, dtype) == pytest.approx(val * val, abs=tol)
    assert u("cube", val, dtype) == pytest.approx(val**3, abs=tol)
    assert u("relu", -val, dtype) == 0.0
    assert u("relu", val, dtype) == pytest.approx(val, abs=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_safe_pow_edge_cases(dtype):
    """safe_pow NaN table (reference src/Operators.jl:38-46)."""
    val, val2 = 0.5, 3.2
    tol = 5e-2 if dtype != jnp.float32 else 1e-5
    assert np.isnan(b("pow", 0.0, -1.0, dtype))
    assert np.isnan(b("pow", -val, val2, dtype))
    assert np.isnan(b("pow", -val, -val2, dtype))
    assert np.isnan(b("pow", 0.0, -val2, dtype))
    assert abs(b("pow", val, val2, dtype) - val**val2) < tol
    assert abs(b("pow", val, -val2, dtype) - val ** (-val2)) < tol
    # integer exponents of negative bases are fine / NaN per parity
    assert not np.isnan(b("pow", -1.0, 2.0, dtype))
    assert np.isnan(b("pow", -1.0, 2.1, dtype))


@pytest.mark.parametrize("dtype", DTYPES)
def test_comparison_and_logical(dtype):
    val, val2 = 0.5, 3.2
    assert b("greater", val, val2, dtype) == 0.0
    assert b("greater", val2, val, dtype) == 1.0
    assert b("logical_or", val, val2, dtype) == 1.0
    assert b("logical_or", 0.0, val2, dtype) == 1.0
    assert b("logical_and", 0.0, val2, dtype) == 0.0
    assert b("logical_and", val, val2, dtype) == 1.0
    assert b("/", val, val2, dtype) == pytest.approx(val / val2, rel=2e-2)


def test_gamma_pole_is_nan():
    """gamma at non-positive integers -> NaN (reference
    src/Operators.jl:8-12 maps the Inf pole to NaN)."""
    assert np.isnan(u("gamma", 0.0, jnp.float32))
    assert np.isnan(u("gamma", -1.0, jnp.float32))
    assert u("gamma", 4.0, jnp.float32) == pytest.approx(6.0, rel=1e-5)
