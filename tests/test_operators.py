"""Scalar operator-semantics table across dtypes — the analog of the
reference's generic operator tests (test/test_operators.jl:26-66):
NaN-safe domain guards, pow edge cases, comparison/logical semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.ops.operators import (
    BINARY_REGISTRY,
    UNARY_REGISTRY,
)


def u(name, x, dtype):
    return float(UNARY_REGISTRY[name](jnp.asarray(x, dtype)))


def b(name, x, y, dtype):
    return float(
        BINARY_REGISTRY[name](jnp.asarray(x, dtype), jnp.asarray(y, dtype))
    )


DTYPES = [jnp.float32, jnp.float16, jnp.bfloat16]


@pytest.mark.parametrize("dtype", DTYPES)
def test_safe_unary_domains(dtype):
    val, val2 = 0.5, 3.2
    tol = 2e-2 if dtype != jnp.float32 else 1e-6
    assert abs(u("log", val, dtype) - np.log(val)) < tol
    assert np.isnan(u("log", -val, dtype))
    assert np.isnan(u("log", 0.0, dtype))
    assert abs(u("log2", val, dtype) - np.log2(val)) < tol
    assert np.isnan(u("log2", -val, dtype))
    assert np.isnan(u("log2", 0.0, dtype))
    assert abs(u("log10", val, dtype) - np.log10(val)) < tol
    assert np.isnan(u("log10", -val, dtype))
    assert abs(u("acosh", val2, dtype) - np.arccosh(val2)) < tol * 2
    assert np.isnan(u("acosh", -val2, dtype))
    assert abs(u("sqrt", val, dtype) - np.sqrt(val)) < tol
    assert np.isnan(u("sqrt", -val, dtype))
    assert u("neg", -val, dtype) == pytest.approx(val, abs=tol)
    assert u("square", val, dtype) == pytest.approx(val * val, abs=tol)
    assert u("cube", val, dtype) == pytest.approx(val**3, abs=tol)
    assert u("relu", -val, dtype) == 0.0
    assert u("relu", val, dtype) == pytest.approx(val, abs=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_safe_pow_edge_cases(dtype):
    """safe_pow NaN table (reference src/Operators.jl:38-46)."""
    val, val2 = 0.5, 3.2
    tol = 5e-2 if dtype != jnp.float32 else 1e-5
    assert np.isnan(b("pow", 0.0, -1.0, dtype))
    assert np.isnan(b("pow", -val, val2, dtype))
    assert np.isnan(b("pow", -val, -val2, dtype))
    assert np.isnan(b("pow", 0.0, -val2, dtype))
    assert abs(b("pow", val, val2, dtype) - val**val2) < tol
    assert abs(b("pow", val, -val2, dtype) - val ** (-val2)) < tol
    # integer exponents of negative bases are fine / NaN per parity
    assert not np.isnan(b("pow", -1.0, 2.0, dtype))
    assert np.isnan(b("pow", -1.0, 2.1, dtype))


@pytest.mark.parametrize("dtype", DTYPES)
def test_comparison_and_logical(dtype):
    val, val2 = 0.5, 3.2
    assert b("greater", val, val2, dtype) == 0.0
    assert b("greater", val2, val, dtype) == 1.0
    assert b("logical_or", val, val2, dtype) == 1.0
    assert b("logical_or", 0.0, val2, dtype) == 1.0
    assert b("logical_and", 0.0, val2, dtype) == 0.0
    assert b("logical_and", val, val2, dtype) == 1.0
    assert b("/", val, val2, dtype) == pytest.approx(val / val2, rel=2e-2)


def test_gamma_pole_is_nan():
    """gamma at non-positive integers -> NaN (reference
    src/Operators.jl:8-12 maps the Inf pole to NaN)."""
    assert np.isnan(u("gamma", 0.0, jnp.float32))
    assert np.isnan(u("gamma", -1.0, jnp.float32))
    assert u("gamma", 4.0, jnp.float32) == pytest.approx(6.0, rel=1e-5)


# ---------------------------------------------------------------------------
# Mosaic-safe kernel substitutes (KERNEL_SUBSTITUTES_UNARY / _BINARY)
# ---------------------------------------------------------------------------
# Each substitute must match its lax-backed registry twin — same NaN-domain
# guards bit-for-bit, values within an op-specific f32 tolerance. Relative
# tolerance is the primary bar; the abs floor covers regions where the
# reference value itself is ~0 (erf near 0, mod near multiples) and the
# substitute's absolute error (<~1.5e-7 for erf) dominates the ratio.

_SUBSTITUTE_CASES = [
    # (name, rel_tol, abs_floor)
    ("cosh", 2e-5, 0.0),
    ("sinh", 2e-4, 1e-6),
    ("atan", 2e-6, 1e-7),
    ("asin", 2e-6, 1e-7),
    ("acos", 2e-6, 1e-7),
    ("asinh", 2e-6, 1e-7),
    ("acosh", 1e-5, 1e-6),
    ("atanh", 1e-3, 1e-5),  # wrap boundaries sit next to the poles
    ("erf", 1e-5, 2e-7),
    ("erfc", 1e-5, 2e-7),
]


def _unary_grid():
    return np.concatenate([
        np.linspace(-30.0, 30.0, 1501),
        np.linspace(-1.5, 1.5, 751),
        # the cosh/sinh near-overflow window: exp(|x|) overflows f32 from
        # ~88.72 but cosh/sinh stay finite to ~89.42 — the composition
        # must match the interpreter's validity flag there
        np.linspace(85.0, 95.0, 101),
        np.linspace(-95.0, -85.0, 101),
        [0.0, -0.0, 1e-8, -1e-8, 1e8, -1e8, np.inf, -np.inf, np.nan],
    ]).astype(np.float32)


def _agree(a, b, rel_tol, abs_floor):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    # NaN-domain semantics must agree exactly
    np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
    same_inf = np.isinf(a) & np.isinf(b) & (np.sign(a) == np.sign(b))
    fin = ~(np.isnan(a) | same_inf)
    dev = np.abs(a - b) / np.maximum(np.abs(b), abs_floor / rel_tol)
    assert np.all(dev[fin] <= rel_tol), (
        f"max rel dev {np.max(dev[fin]):.3e}"
    )


@pytest.mark.parametrize("name,rel_tol,abs_floor", _SUBSTITUTE_CASES)
def test_kernel_substitute_unary_parity(name, rel_tol, abs_floor):
    from symbolicregression_jl_tpu.ops.operators import (
        KERNEL_SUBSTITUTES_UNARY,
    )

    x = jnp.asarray(_unary_grid())
    _agree(KERNEL_SUBSTITUTES_UNARY[name](x), UNARY_REGISTRY[name](x),
           rel_tol, abs_floor)


def test_kernel_substitute_gamma_parity():
    """gamma: both f32 routes carry ~1e-3 noise (exp(lgamma) amplifies
    lgamma's error; Lanczos pays cancellation), so compare each against
    the f64 truth instead of against each other, and require identical
    NaN semantics (poles and overflow -> NaN)."""
    import math

    from symbolicregression_jl_tpu.ops.operators import (
        KERNEL_SUBSTITUTES_UNARY,
    )

    xs = np.concatenate([
        np.linspace(-34.0, 34.0, 1701),
        [0.5, 1.0, 4.0, 33.0, -2.5, 0.0, -1.0, np.inf, -np.inf, np.nan],
    ]).astype(np.float32)

    def truth(v):
        try:
            r = math.gamma(float(v))
        except (ValueError, OverflowError):
            return np.nan
        return r if abs(r) < 3.4e38 else np.nan  # f32 overflow -> NaN

    t = np.array([truth(v) for v in xs.astype(np.float64)])
    a = np.asarray(KERNEL_SUBSTITUTES_UNARY["gamma"](jnp.asarray(xs)), np.float64)
    b = np.asarray(UNARY_REGISTRY["gamma"](jnp.asarray(xs)), np.float64)
    np.testing.assert_array_equal(np.isnan(a), np.isnan(t))
    np.testing.assert_array_equal(np.isnan(b), np.isnan(t))
    fin = ~np.isnan(t)
    dev = np.abs(a - t)[fin] / np.maximum(np.abs(t[fin]), 1e-30)
    assert np.max(dev) < 5e-3


def test_kernel_substitute_binary_parity():
    from symbolicregression_jl_tpu.ops.operators import (
        KERNEL_SUBSTITUTES_BINARY,
    )

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.uniform(-40, 40, 4096).astype(np.float32))
    y = jnp.asarray(rng.uniform(-40, 40, 4096).astype(np.float32))
    # mod: floor-mod identity; error grows with |x/y|, bounded on this grid
    _agree(KERNEL_SUBSTITUTES_BINARY["mod"](x, y), BINARY_REGISTRY["mod"](x, y),
           1e-3, 1e-4)
    # atan2: finite non-axis inputs
    _agree(KERNEL_SUBSTITUTES_BINARY["atan2"](x, y), jnp.arctan2(x, y),
           1e-5, 1e-7)
    # atan2 axis/quadrant table (finite edges the composition must get right)
    pts = [(0.0, 1.0), (0.0, -1.0), (1.0, 0.0), (-1.0, 0.0),
           (1.0, 1.0), (1.0, -1.0), (-1.0, -1.0), (-1.0, 1.0), (0.0, 0.0)]
    for yy, xx in pts:
        got = float(KERNEL_SUBSTITUTES_BINARY["atan2"](
            jnp.float32(yy), jnp.float32(xx)))
        want = float(np.arctan2(np.float32(yy), np.float32(xx)))
        assert got == pytest.approx(want, abs=1e-6), (yy, xx)


def test_kernel_substitutes_only_use_mosaic_primitives():
    """Every substitute must trace to lax primitives Mosaic can lower —
    the entire point of the table. Guards against someone 'simplifying' a
    composition back to jnp.cosh and silently breaking the compiled path."""
    from symbolicregression_jl_tpu.ops.operators import (
        KERNEL_SUBSTITUTES_BINARY,
        KERNEL_SUBSTITUTES_UNARY,
    )

    # the elementwise subset of jax/_src/pallas/mosaic/lowering.py's rule
    # table (checked 2026-08-01) plus structural prims jaxprs always carry
    allowed = {
        "abs", "add", "and", "ceil", "clamp", "cos", "div", "eq", "exp",
        "exp2", "floor", "ge", "gt", "integer_pow", "is_finite", "le",
        "log", "log1p", "logistic", "lt", "max", "min", "mul", "ne",
        "neg", "not", "or", "pow", "round", "rsqrt", "select_n", "sign",
        "sin", "sqrt", "square", "sub", "tan", "tanh", "xor",
        "broadcast_in_dim", "convert_element_type", "reduce_sum",
        "reduce_max", "reduce_min", "stop_gradient", "iota", "pjit",
        # cotangent accumulation in transposed jaxprs; Mosaic registers a
        # rule for ad_util.add_any_p (lowering.py:2576)
        "add_any",
    }

    def prims_of(jaxpr, acc):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in ("pjit", "jit"):
                prims_of(eqn.params["jaxpr"].jaxpr, acc)
            elif name in ("custom_jvp_call", "custom_vjp_call",
                          "custom_jvp_call_jaxpr"):
                prims_of(eqn.params["call_jaxpr"].jaxpr, acc)
            else:
                acc.add(name)
        return acc

    x = jnp.ones((8,), jnp.float32)
    both = [(n, f, 1) for n, f in KERNEL_SUBSTITUTES_UNARY.items()] + [
        (n, f, 2) for n, f in KERNEL_SUBSTITUTES_BINARY.items()
    ]
    for name, fn, arity in both:
        args = (x,) * arity
        used = prims_of(jax.make_jaxpr(fn)(*args).jaxpr, set())
        illegal = used - allowed
        assert not illegal, f"{name} uses non-Mosaic primitives {illegal}"
        # the grad kernel lowers jax.vjp of every substitute INSIDE the
        # Pallas kernel (pallas_grad bwd_body), so the backward jaxpr must
        # be Mosaic-clean too — incl. the custom_jvp exact-derivative rules
        def vjp_apply(*a):
            out, pull = jax.vjp(fn, *a)
            return pull(jnp.ones_like(out))
        used_b = prims_of(jax.make_jaxpr(vjp_apply)(*args).jaxpr, set())
        illegal_b = used_b - allowed
        assert not illegal_b, (
            f"{name} vjp uses non-Mosaic primitives {illegal_b}"
        )


def test_kernel_substitute_gradients_match_lax():
    """d/dx of each differentiable substitute vs its lax twin — ON a grid
    INCLUDING x = 0, where the |x|-based compositions' plain autodiff
    would give a spurious zero subgradient (the custom_jvp exact rules
    exist precisely for this)."""
    from symbolicregression_jl_tpu.ops.operators import (
        KERNEL_SUBSTITUTES_BINARY,
        KERNEL_SUBSTITUTES_UNARY,
    )

    xs = jnp.asarray(
        np.array([0.0, -0.0, 0.3, -0.7, 1.5, -2.5, 5.0], np.float32)
    )
    twins = {
        "atan": jnp.arctan, "asin": jnp.arcsin, "acos": jnp.arccos,
        "sinh": jnp.sinh, "cosh": jnp.cosh, "asinh": jnp.arcsinh,
        "erf": jax.lax.erf, "erfc": jax.lax.erfc,
    }
    for name, lax_fn in twins.items():
        sub = KERNEL_SUBSTITUTES_UNARY[name]
        g_sub = jax.vmap(jax.grad(lambda v, f=sub: f(v).sum()))(xs)
        g_lax = jax.vmap(jax.grad(lambda v, f=lax_fn: f(v).sum()))(xs)
        dom = np.isfinite(np.asarray(g_lax))  # asin/acos NaN outside [-1,1]
        np.testing.assert_allclose(
            np.asarray(g_sub)[dom], np.asarray(g_lax)[dom],
            rtol=1e-5, atol=1e-6, err_msg=name,
        )
    # atan2: both partials at generic points AND on the y-axis (x=0)
    pts = [(1.0, 2.0), (-1.5, 0.5), (1.0, 0.0), (-2.0, 0.0), (0.5, -1.0)]
    f_sub = KERNEL_SUBSTITUTES_BINARY["atan2"]
    for yy, xx in pts:
        gs = jax.grad(lambda a, b: f_sub(a, b), argnums=(0, 1))(
            jnp.float32(yy), jnp.float32(xx))
        gl = jax.grad(jnp.arctan2, argnums=(0, 1))(
            jnp.float32(yy), jnp.float32(xx))
        np.testing.assert_allclose(
            np.asarray(gs), np.asarray(gl), rtol=1e-5, atol=1e-6,
            err_msg=f"atan2 at {(yy, xx)}",
        )


def test_register_does_not_clobber_other_arity_substitute():
    """register_binary('atan', ...) must not delete the unary atan's
    Mosaic substitute (the registries are separate namespaces)."""
    from symbolicregression_jl_tpu.ops.operators import (
        BINARY_REGISTRY,
        KERNEL_SUBSTITUTES_BINARY,
        KERNEL_SUBSTITUTES_UNARY,
        register_binary,
    )

    assert "atan" in KERNEL_SUBSTITUTES_UNARY
    try:
        register_binary("atan", lambda x, y: x + y)
        assert "atan" in KERNEL_SUBSTITUTES_UNARY
        assert "atan" not in KERNEL_SUBSTITUTES_BINARY
    finally:
        BINARY_REGISTRY.pop("atan", None)
        KERNEL_SUBSTITUTES_BINARY.pop("atan", None)
