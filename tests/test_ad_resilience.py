"""Preemption-tolerant search (ISSUE 11, docs/resilience.md): periodic
snapshots resume BIT-IDENTICALLY to the uninterrupted run (same hall of
fame, same host key chain) on fused and chunked drivers with donation on
and off; checkpoint writes are crash-atomic under injected torn writes;
corrupt checkpoints fail loud (never a silent fresh start); and the
auto-resume supervisor turns an injected mid-search fault into the
uninterrupted run's exact result. Fast, CPU-only; the one real-SIGKILL
subprocess round trip is marked slow."""

import dataclasses
import os
import pickle
import random
import subprocess
import sys

import numpy as np
import pytest

import jax

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.resilience import (
    FaultInjected,
    FaultPlan,
    backoff_s,
    clear_fault_plan,
    faults,
    set_fault_plan,
    supervised_search,
)
from symbolicregression_jl_tpu.utils.checkpoint import (
    CheckpointIncompatible,
    load_search_state,
    options_fingerprint,
    save_search_state,
)

# DELIBERATELY the exact Options shape of test_dispatch_chunking's fast
# e2e test (same _graph_key -> the iteration/init factories' lru_caches
# share one compile per driver/donation variant across both files —
# tier-1 dot-budget hygiene)
KW = dict(
    binary_operators=["+", "*"],
    npop=10,
    npopulations=2,
    ncycles_per_iteration=5,
    tournament_selection_n=4,
    maxsize=8,
    progress=False,
    verbosity=0,
    save_to_file=False,
    seed=0,
    deterministic=True,
)

# search-level kwargs for every equation_search in this file: preflight
# already ran in earlier test files; skipping it here keeps each tiny
# search compile-bound only (bit-identity is unaffected — preflight is
# validation, not state)
SKW = dict(runtests=False)


def _data():
    rng = np.random.default_rng(1)
    X = rng.standard_normal((2, 48)).astype(np.float32)
    y = (X[0] * X[0] + 0.5).astype(np.float32)
    return X, y


def _frontier(r):
    return [
        (c.complexity, float(c.loss), float(c.score), c.equation)
        for c in r.frontier()
    ]


def _assert_hof_bit_identical(sa, sb):
    for a, b in zip(
        jax.tree_util.tree_leaves(sa.global_hof),
        jax.tree_util.tree_leaves(sb.global_hof),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    clear_fault_plan()
    yield
    clear_fault_plan()


@pytest.fixture(scope="module")
def tiny_run():
    """One 1-iteration search whose state feeds the checkpoint unit
    tests (module-scoped: the compile is paid once)."""
    X, y = _data()
    return sr.equation_search(
        X, y, niterations=1, return_state=True, **KW, **SKW
    )


# ---------------------------------------------------------------------------
# tentpole: fault -> snapshot -> supervisor resume, bit-identical
# ---------------------------------------------------------------------------


@pytest.mark.fast
@pytest.mark.parametrize(
    "driver_kw",
    [
        pytest.param({}, id="fused"),
        # the chunked driver compiles five phase programs nothing else
        # in the fast tier pays for (~2 min on the 1-core CI box):
        # slow tier, same budget policy as PR 7's sharded searches —
        # the fast tier keeps the fused combo, whose graphs the rest
        # of this file reuses
        pytest.param(
            {"max_cycles_per_dispatch": 2}, id="chunked",
            marks=pytest.mark.slow,
        ),
    ],
)
@pytest.mark.parametrize(
    "donate",
    [
        pytest.param("1", id="donate"),
        # donation-off compiles a whole graph set nothing else in the
        # fast tier uses (donate is part of the jit factories' cache
        # key): slow tier, like the other SRTPU_DONATE A/B searches
        pytest.param("0", id="nodonate", marks=pytest.mark.slow),
    ],
)
def test_supervised_resume_bit_identical(
    tmp_path, monkeypatch, driver_kw, donate
):
    """The acceptance contract: a fault-injected kill of dispatch 1,
    snapshotting every dispatch, supervisor-resumed — the final hall of
    fame AND the host key chain must be bit-identical to the
    uninterrupted run, on both drivers, donation on and off."""
    monkeypatch.setenv("SRTPU_DONATE", donate)
    X, y = _data()
    kw = {**KW, **driver_kw}
    base = sr.equation_search(
        X, y, niterations=2, return_state=True, **kw, **SKW
    )

    snap = str(tmp_path / "run.ckpt")
    set_fault_plan(FaultPlan(kind="raise", at=1))
    sup = supervised_search(
        X, y, niterations=2,
        snapshot_path=snap, snapshot_every_dispatches=1,
        max_attempts=3, backoff_base_s=0.0, backoff_jitter=0.0,
        sleep_fn=lambda s: None, return_state=True, **kw, **SKW,
    )
    assert sup.attempts == 2
    assert sup.resumes == 1
    assert sup.history[0]["error_type"] == "FaultInjected"
    assert sup.history[0]["resumed_from_iteration"] is None  # fresh start

    assert _frontier(base) == _frontier(sup.result)
    sa, sb = base.state[0], sup.result.state[0]
    _assert_hof_bit_identical(sa, sb)
    # same key chain: the resumed run continued the interrupted one's
    # host PRNG stream exactly
    np.testing.assert_array_equal(
        np.asarray(sa.rng_key), np.asarray(sb.rng_key)
    )


@pytest.mark.fast
def test_resume_twice_from_one_snapshot_bit_identical(tmp_path):
    """One snapshot, two resumes: both must equal each other AND the
    uninterrupted 3-iteration run (the snapshot is a pure serialization
    point, not a consumable)."""
    X, y = _data()
    full = sr.equation_search(
        X, y, niterations=3, return_state=True, **KW, **SKW
    )
    snap = str(tmp_path / "snap.ckpt")
    sr.equation_search(
        X, y, niterations=2, snapshot_path=snap,
        snapshot_every_dispatches=2, **KW, **SKW,
    )
    s1 = load_search_state(snap)
    s2 = load_search_state(snap)
    assert s1[0].iteration == 2
    assert s1[0].rng_key is not None
    r1 = sr.equation_search(
        X, y, niterations=1, saved_state=s1, return_state=True, **KW,
        **SKW,
    )
    r2 = sr.equation_search(
        X, y, niterations=1, saved_state=s2, return_state=True, **KW,
        **SKW,
    )
    assert _frontier(r1) == _frontier(r2) == _frontier(full)
    _assert_hof_bit_identical(r1.state[0], full.state[0])


@pytest.mark.fast
def test_resume_bit_identical_under_warmup_curriculum(tmp_path):
    """warmup_maxsize_by > 0: the curriculum denominator is the
    ABSOLUTE planned total (resume start + remaining), so the resumed
    run's size-cap ramp — and therefore its hall of fame — matches the
    uninterrupted run exactly even though it passes only the remaining
    iteration count. (warmup/curmaxsize are host-side + traced: this
    reuses the already-compiled graphs.)"""
    X, y = _data()
    kw = {**KW, "warmup_maxsize_by": 0.67}
    full = sr.equation_search(
        X, y, niterations=3, return_state=True, **kw, **SKW
    )
    snap = str(tmp_path / "w.ckpt")
    sr.equation_search(
        X, y, niterations=1, snapshot_path=snap, **kw, **SKW
    )
    resumed = sr.equation_search(
        X, y, niterations=2, saved_state=load_search_state(snap),
        return_state=True, **kw, **SKW,
    )
    assert _frontier(resumed) == _frontier(full)
    _assert_hof_bit_identical(resumed.state[0], full.state[0])


@pytest.mark.fast
def test_supervisor_exhausts_attempts_and_reraises(tmp_path):
    """max_attempts=1 with a fault at dispatch 0: nothing to resume
    from, the cap trips immediately, and the original exception
    propagates (a deterministically failing config must not loop)."""
    X, y = _data()
    set_fault_plan(FaultPlan(kind="raise", at=0))
    with pytest.raises(FaultInjected):
        supervised_search(
            X, y, niterations=1,
            snapshot_path=str(tmp_path / "never.ckpt"),
            max_attempts=1, sleep_fn=lambda s: None, **KW, **SKW,
        )


@pytest.mark.fast
def test_supervisor_restarts_clean_on_stale_snapshot(tmp_path, tiny_run):
    """A snapshot from a DIFFERENT config at snapshot_path (fingerprint
    mismatch) must cause a clean fresh start, not a crash and not a
    garbage resume. The stale file is forged by doctoring a real
    snapshot's stamp (same search shape everywhere: no extra compile)."""
    X, y = _data()
    snap = str(tmp_path / "stale.ckpt")
    save_search_state(snap, tiny_run.state, options=tiny_run.options)
    for p in (snap, snap + ".bkup"):
        with open(p, "rb") as f:
            data = pickle.load(f)
        data["options_fingerprint"]["npop"] = 999
        with open(p, "wb") as f:
            pickle.dump(data, f)
    sup = supervised_search(
        X, y, niterations=1, snapshot_path=snap,
        max_attempts=2, sleep_fn=lambda s: None, **KW, **SKW,
    )
    assert sup.attempts == 1
    assert sup.resumes == 0
    assert sup.result.frontier()
    # the restart decision is on the record even though the fresh
    # attempt succeeded
    assert "snapshot_error" in sup.history[0]
    assert "npop" in sup.history[0]["snapshot_error"]


@pytest.mark.fast
def test_supervisor_propagates_corrupt_checkpoint(tmp_path):
    """Both twins unreadable is NOT a fresh start: the load contract's
    refusal propagates through the supervisor — banked progress must
    never silently become a rerun."""
    X, y = _data()
    snap = str(tmp_path / "corrupt.ckpt")
    for p in (snap, snap + ".bkup"):
        with open(p, "wb") as f:
            f.write(b"not a pickle")
    with pytest.raises(ValueError, match="refusing"):
        supervised_search(
            X, y, niterations=1, snapshot_path=snap,
            max_attempts=2, sleep_fn=lambda s: None, **KW, **SKW,
        )


@pytest.mark.fast
def test_snapshot_cadence_round_aligned_not_stretched():
    """Multi-output cadence: a snapshot fires at the first round end
    after every k-dispatch boundary — never stretched to
    lcm(k, nout) by requiring the boundary to LAND on a round end."""
    from symbolicregression_jl_tpu.api import _snapshot_due

    # nout=1: exactly the every-k schedule
    fires = [g for g in range(1, 13) if _snapshot_due(g, 1, 3)]
    assert fires == [3, 6, 9, 12]
    # nout=2, every=5: round ends at 2,4,6,...; boundaries 5,10 are
    # picked up at the NEXT round end (6, 10) — cadence ~5, not 10
    fires = [g for g in range(2, 21, 2) if _snapshot_due(g, 2, 5)]
    assert fires == [6, 10, 16, 20]
    # nout=5, every=7: cadence ~7 (10, 15, 25, ...), not lcm=35
    fires = [g for g in range(5, 41, 5) if _snapshot_due(g, 5, 7)]
    assert fires == [10, 15, 25, 30, 35]


@pytest.mark.fast
def test_recreate_fallback_ignores_checkpoint_rng_key(tiny_run):
    """An INCOMPATIBLE saved state (populations recreated with a
    warning) must not leak the dead run's key chain into the fresh
    init: the recreate fallback stays reproducible from Options.seed
    (SearchState's documented contract). The saved state is made
    incompatible by truncating its population arrays — same Options
    everywhere, no extra compile."""
    X, y = _data()
    s0 = tiny_run.state[0]
    pop = s0.island_states.pop
    bad = [dataclasses.replace(
        s0,
        island_states=s0.island_states._replace(
            pop=pop._replace(scores=pop.scores[:, :-1])
        ),
        # a key chain the fallback must NOT adopt
        rng_key=np.asarray(jax.random.PRNGKey(12345)),
    )]
    with pytest.warns(UserWarning, match="recreating"):
        recreated = sr.equation_search(
            X, y, niterations=1, saved_state=bad,
            return_state=True, **KW, **SKW,
        )
    # same seed-derived chain as the never-resumed run of these Options
    np.testing.assert_array_equal(
        np.asarray(tiny_run.state[0].rng_key),
        np.asarray(recreated.state[0].rng_key),
    )


@pytest.mark.fast
def test_snapshot_write_fault_propagates_without_torn_files(tmp_path):
    """An injected tear during the in-loop periodic snapshot must
    propagate out of equation_search (the supervisor's classify-and-
    resume path) while the crash-atomic discipline keeps the torn
    bytes quarantined in the .tmp sibling."""
    X, y = _data()
    snap = str(tmp_path / "t.ckpt")
    set_fault_plan(FaultPlan(kind="tear_checkpoint", at=0))
    with pytest.raises(FaultInjected):
        sr.equation_search(
            X, y, niterations=1, snapshot_path=snap,
            snapshot_every_dispatches=1, **KW, **SKW,
        )
    assert not os.path.exists(snap)
    assert os.path.exists(snap + ".tmp")


@pytest.mark.fast
def test_snapshot_path_alone_defaults_to_every_dispatch():
    """A configured snapshot_path must never be a silent no-op: the
    default cadence 0 normalizes to 1 (every dispatch)."""
    o = sr.make_options(snapshot_path="x.ckpt")
    assert o.snapshot_every_dispatches == 1
    o2 = sr.make_options(snapshot_path="x.ckpt",
                         snapshot_every_dispatches=4)
    assert o2.snapshot_every_dispatches == 4
    with pytest.raises(ValueError, match="requires snapshot_path"):
        sr.make_options(snapshot_every_dispatches=2)


@pytest.mark.slow
def test_real_sigkill_then_cross_process_supervised_resume(tmp_path):
    """The honest preemption: a child process SIGKILLs ITSELF mid-search
    (fault plan from the environment, fuse file persisting the spent
    mark), then a fresh supervisor in THIS process picks up the dead
    child's snapshot and finishes — bit-identical to uninterrupted."""
    X, y = _data()
    base = sr.equation_search(X, y, niterations=2, **KW, **SKW)
    snap = str(tmp_path / "killed.ckpt")
    fuse = str(tmp_path / "fuse")
    code = (
        "import numpy as np\n"
        "import symbolicregression_jl_tpu as sr\n"
        "rng = np.random.default_rng(1)\n"
        "X = rng.standard_normal((2, 48)).astype(np.float32)\n"
        "y = (X[0] * X[0] + 0.5).astype(np.float32)\n"
        f"sr.equation_search(X, y, niterations=2, snapshot_path={snap!r},"
        f" snapshot_every_dispatches=1, runtests=False, **{KW!r})\n"
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SRTPU_FAULT_PLAN": "kill@1",
        "SRTPU_FAULT_FUSE": fuse,
    }
    p = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=600,
    )
    assert p.returncode != 0  # SIGKILLed mid-run
    assert os.path.exists(fuse)  # the plan spent itself before dying
    assert os.path.exists(snap)  # dispatch 0's snapshot survived

    sup = supervised_search(
        X, y, niterations=2, snapshot_path=snap,
        snapshot_every_dispatches=1, max_attempts=2,
        sleep_fn=lambda s: None, **KW, **SKW,
    )
    assert sup.attempts == 1
    assert sup.resumes == 1  # attempt 1 started from the dead run's file
    assert _frontier(base) == _frontier(sup.result)


@pytest.mark.fast
def test_saved_state_event_carries_cadence_and_schema_accepts(tiny_run, tmp_path):
    """Cheap schema-agreement checks (no search, no phased compile): a
    periodic save_search_state emits the cadence provenance
    (dispatch/cause), and the additive run_start `snapshot`/`resume_from`
    fields validate against the checked-in schema exactly as api.py
    emits them — the full telemetry round trip is the slow test below
    and the suite `resilience` case."""
    from symbolicregression_jl_tpu.telemetry.events import (
        EventLog,
        load_schema,
        validate_event,
    )

    log_path = str(tmp_path / "events-x.jsonl")
    sink = EventLog(log_path, run_id="r")
    snap = str(tmp_path / "s.ckpt")
    save_search_state(
        snap, tiny_run.state, sink=sink, options=tiny_run.options,
        dispatch=3, cause="periodic",
    )
    start = sink.emit(
        "run_start",
        config_fingerprint="x", backend="cpu", devices=["cpu:0"],
        snapshot={"path": snap, "every_dispatches": 3},
        resume_from={"path": snap, "iteration": 1, "outputs": 1,
                     "populations_compatible": True},
    )
    sink.close()
    schema = load_schema()
    assert validate_event(start, schema) == []
    import json

    with open(log_path) as f:
        events = [json.loads(line) for line in f]
    saved_ev = events[0]
    assert saved_ev["type"] == "saved_state"
    assert saved_ev["dispatch"] == 3
    assert saved_ev["cause"] == "periodic"
    assert saved_ev["iteration"] == tiny_run.state[0].iteration
    assert validate_event(saved_ev, schema) == []


@pytest.mark.slow
def test_snapshot_and_resume_telemetry_events_validate(tmp_path):
    """The schema-additive trail end to end: a snapshotting run's log
    carries `saved_state` events with cadence provenance (dispatch/
    cause) and a `run_start.snapshot` block; the resumed run's
    `run_start` carries `resume_from`; both logs validate against the
    checked-in schema and the doctor reads the resumed run as healthy.
    Slow tier: telemetry forces the phased driver, a compile set
    nothing in the fast tier otherwise pays for."""
    import json

    from symbolicregression_jl_tpu.telemetry import validate_events_file
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    X, y = _data()
    tele = str(tmp_path / "tele")
    snap = str(tmp_path / "s.ckpt")
    sr.equation_search(
        X, y, niterations=1, snapshot_path=snap,
        snapshot_every_dispatches=1, telemetry=True, telemetry_dir=tele,
        **KW, **SKW,
    )
    saved = load_search_state(snap)
    sr.equation_search(
        X, y, niterations=1, saved_state=saved, telemetry=True,
        telemetry_dir=tele, **KW, **SKW,
    )
    logs = sorted(
        (os.path.join(tele, f) for f in os.listdir(tele)),
        key=os.path.getmtime,
    )
    assert len(logs) == 2
    for log in logs:
        assert validate_events_file(log)["ok"], log

    def events(path):
        with open(path) as f:
            return [json.loads(line) for line in f]

    first, second = events(logs[0]), events(logs[1])
    start1 = first[0]
    assert start1["type"] == "run_start"
    assert start1["snapshot"] == {"path": snap, "every_dispatches": 1}
    assert start1["resume_from"] is None
    saved_evs = [e for e in first if e["type"] == "saved_state"
                 and not e.get("in_memory")]
    assert saved_evs and saved_evs[0]["cause"] == "periodic"
    assert saved_evs[0]["dispatch"] == 1
    assert saved_evs[0]["path"] == snap

    start2 = second[0]
    assert start2["resume_from"]["path"] == snap
    assert start2["resume_from"]["iteration"] == 1
    report = analyze_run(logs[1])
    assert report["verdict"] == "healthy"
    assert report["run"]["resume_from"]["path"] == snap


# ---------------------------------------------------------------------------
# satellite: crash-atomic checkpoint writes + loud corrupt-load failures
# ---------------------------------------------------------------------------


def _bump(state, by=5):
    return [dataclasses.replace(s, iteration=s.iteration + by)
            for s in state]


@pytest.mark.fast
def test_torn_first_write_leaves_both_files_intact(tmp_path, tiny_run):
    """Kill mid-byte during the MAIN file's write: with the tmp+fsync+
    os.replace discipline neither the main file nor .bkup moves — the
    torn bytes live only in the .tmp sibling the loader never reads
    (the exact hole the old sequential open(.., 'wb') pair had)."""
    snap = str(tmp_path / "a.ckpt")
    save_search_state(snap, tiny_run.state, options=tiny_run.options)
    v1_main = open(snap, "rb").read()
    v1_bkup = open(snap + ".bkup", "rb").read()

    set_fault_plan(FaultPlan(kind="tear_checkpoint", at=0))
    with pytest.raises(FaultInjected):
        save_search_state(
            snap, _bump(tiny_run.state), options=tiny_run.options
        )
    assert open(snap, "rb").read() == v1_main
    assert open(snap + ".bkup", "rb").read() == v1_bkup
    assert os.path.exists(snap + ".tmp")  # the torn write, quarantined
    loaded = load_search_state(snap, options=tiny_run.options)
    assert loaded[0].iteration == tiny_run.state[0].iteration


@pytest.mark.fast
def test_torn_backup_write_leaves_loadable_bkup(tmp_path, tiny_run):
    """Kill between the two writes (tear at file-write index 1): the
    main file already holds the NEW snapshot, .bkup still holds the old
    one — and when the main file is later destroyed, load falls back to
    that loadable .bkup instead of silently fresh-starting."""
    snap = str(tmp_path / "b.ckpt")
    save_search_state(snap, tiny_run.state, options=tiny_run.options)
    old_iter = tiny_run.state[0].iteration

    set_fault_plan(FaultPlan(kind="tear_checkpoint", at=1))
    with pytest.raises(FaultInjected):
        save_search_state(
            snap, _bump(tiny_run.state), options=tiny_run.options
        )
    # main advanced, backup one snapshot behind — both loadable
    assert load_search_state(snap)[0].iteration == old_iter + 5
    payload = open(snap, "rb").read()
    with open(snap, "wb") as f:
        f.write(payload[: len(payload) // 2])
    assert load_search_state(snap)[0].iteration == old_iter


@pytest.mark.fast
def test_truncated_checkpoint_raises_never_fresh_start(tmp_path, tiny_run):
    snap = str(tmp_path / "c.ckpt")
    save_search_state(snap, tiny_run.state)
    payload = open(snap, "rb").read()
    for p in (snap, snap + ".bkup"):
        with open(p, "wb") as f:
            f.write(payload[: len(payload) // 2])
    with pytest.raises(ValueError, match="refusing"):
        load_search_state(snap)
    with pytest.raises(FileNotFoundError):
        load_search_state(str(tmp_path / "missing.ckpt"))


@pytest.mark.fast
def test_wrong_magic_raises(tmp_path):
    snap = str(tmp_path / "d.ckpt")
    with open(snap, "wb") as f:
        pickle.dump({"magic": "not-a-checkpoint", "outputs": []}, f)
    with pytest.raises(ValueError, match="refusing"):
        load_search_state(snap)


@pytest.mark.fast
def test_fingerprint_mismatch_fails_at_load_with_named_fields(
    tmp_path, tiny_run
):
    """Satellite: an incompatible resume fails AT load_search_state,
    naming the mismatched Options fields — not deep inside
    equation_search's shape validation."""
    snap = str(tmp_path / "e.ckpt")
    save_search_state(snap, tiny_run.state, options=tiny_run.options)
    other = sr.make_options(**{**KW, "npop": 12})
    with pytest.raises(CheckpointIncompatible, match="npop"):
        load_search_state(snap, options=other)
    # the compatible config still loads; unstamped (options=None) too
    assert load_search_state(snap, options=tiny_run.options)
    assert load_search_state(snap)
    # stamp matches the documented fingerprint fields
    fp = options_fingerprint(tiny_run.options)
    assert fp["npop"] == KW["npop"]
    assert "precision" in fp


@pytest.mark.fast
def test_unstamped_v1_checkpoint_still_loads(tmp_path, tiny_run):
    """Back-compat: a payload without fingerprint/rng_key (the v1
    schema) loads with fingerprint checking skipped."""
    snap = str(tmp_path / "f.ckpt")
    save_search_state(snap, tiny_run.state)
    with open(snap, "rb") as f:
        data = pickle.load(f)
    data["magic"] = "srtpu-search-state-v1"
    data.pop("options_fingerprint", None)
    for d in data["outputs"]:
        d.pop("rng_key", None)
    with open(snap, "wb") as f:
        pickle.dump(data, f)
    os.remove(snap + ".bkup")
    loaded = load_search_state(snap, options=tiny_run.options)
    assert loaded[0].rng_key is None
    assert loaded[0].iteration == tiny_run.state[0].iteration


# ---------------------------------------------------------------------------
# fault-plan + backoff units (no search, no jax dispatch)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_fault_plan_parse_and_validation():
    p = FaultPlan.parse("raise@3")
    assert p == FaultPlan(kind="raise", at=3)
    assert p.spec() == "raise@3"
    with pytest.raises(ValueError, match="kind"):
        FaultPlan.parse("explode@1")
    with pytest.raises(ValueError, match="form"):
        FaultPlan.parse("raise")
    with pytest.raises(ValueError, match="integer"):
        FaultPlan.parse("raise@soon")
    with pytest.raises(ValueError, match=">= 0"):
        FaultPlan(kind="raise", at=-1)


@pytest.mark.fast
def test_fault_plan_is_one_shot_and_index_exact():
    set_fault_plan(FaultPlan(kind="raise", at=2))
    faults.on_dispatch(0)  # below the index: no-op
    faults.on_dispatch(1)
    with pytest.raises(FaultInjected):
        faults.on_dispatch(2)
    faults.on_dispatch(2)  # spent: the resumed attempt runs clean


@pytest.mark.fast
def test_tunnel_down_fault_spells_unavailable():
    set_fault_plan(FaultPlan(kind="tunnel_down", at=0))
    with pytest.raises(FaultInjected, match="UNAVAILABLE"):
        faults.on_dispatch(0)


@pytest.mark.fast
def test_env_plan_and_fuse_survive_process_restart(tmp_path, monkeypatch):
    fuse = str(tmp_path / "fuse")
    monkeypatch.setenv(faults.ENV_PLAN, "raise@0")
    monkeypatch.setenv(faults.ENV_FUSE, fuse)
    clear_fault_plan()  # no explicit plan: the env drives
    assert faults.get_fault_plan() == FaultPlan(kind="raise", at=0)
    with pytest.raises(FaultInjected):
        faults.on_dispatch(0)
    assert os.path.exists(fuse)
    # "restart": in-process spent marks cleared, env unchanged — the
    # blown fuse alone keeps the plan inert
    clear_fault_plan()
    faults.on_dispatch(0)
    # the fuse stores WHICH plan blew it: a stale fuse from the
    # previous scenario must not disarm a different plan
    monkeypatch.setenv(faults.ENV_PLAN, "raise@5")
    clear_fault_plan()
    with pytest.raises(FaultInjected):
        faults.on_dispatch(5)


@pytest.mark.fast
def test_backoff_exponential_capped_jittered():
    rng = random.Random(0)
    assert backoff_s(1, 1.0, 60.0, 0.0, rng) == 1.0
    assert backoff_s(3, 1.0, 60.0, 0.0, rng) == 4.0
    assert backoff_s(30, 1.0, 60.0, 0.0, rng) == 60.0
    d = backoff_s(1, 1.0, 60.0, 0.5, random.Random(7))
    assert 1.0 <= d <= 1.5


@pytest.mark.fast
def test_supervisor_rejects_saved_state_kwarg():
    X, y = _data()
    with pytest.raises(ValueError, match="saved_state"):
        supervised_search(
            X, y, snapshot_path="x.ckpt", saved_state=[], **KW
        )
