"""SymbolicRegressor estimator facade: fit/predict/score round trip with
sklearn (n_samples, n_features) data layout."""

import numpy as np
import pytest

from symbolicregression_jl_tpu.sklearn import SymbolicRegressor

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=2,
    ncycles_per_iteration=40,
    maxsize=10,
    verbosity=0,
    progress=False,
    runtests=False,
)


@pytest.mark.slow
def test_fit_predict_score(rng):
    n = 80
    Xs = (rng.standard_normal((n, 2)) * 2).astype(np.float32)  # sklearn layout
    y = Xs[:, 0] * Xs[:, 1]
    est = SymbolicRegressor(niterations=6, seed=0, **TINY)
    assert est.get_params()["npop"] == 24
    est.fit(Xs, y)
    assert est.n_features_in_ == 2
    assert len(est.equations_) == 1 and est.best_equation_
    y_pred = est.predict(Xs)
    assert y_pred.shape == (n,)
    r2 = est.score(Xs, y)
    assert r2 > 0.95, f"R^2 {r2} too low; best {est.best_equation_}"


def test_unfitted_and_bad_shapes(rng):
    est = SymbolicRegressor(niterations=1, **TINY)
    with pytest.raises(RuntimeError):
        est.predict(np.zeros((3, 2)))
    with pytest.raises(ValueError):
        est.fit(np.zeros(5), np.zeros(5))
    est.set_params(niterations=3, npop=16)
    assert est.get_params()["niterations"] == 3
    assert est.get_params()["npop"] == 16


def test_set_params_rejects_unknown():
    """sklearn contract: set_params raises on invalid names so typos in
    tuned grids fail fast (GridSearchCV/clone rely on this)."""
    est = SymbolicRegressor(niterations=1, **TINY)
    with pytest.raises(ValueError, match="Invalid parameter"):
        est.set_params(npoop=10)
    # valid names (including deprecated aliases) still work
    est.set_params(npop=16, npopulations=3)
    assert est.get_params()["npop"] == 16


def test_score_constant_target(rng):
    """R^2 for a constant target follows sklearn's r2_score convention:
    0.0 for imperfect predictions instead of a clamped-denominator
    nonsense value."""
    n = 40
    Xs = (rng.standard_normal((n, 2))).astype(np.float32)
    y = Xs[:, 0] + Xs[:, 1]
    est = SymbolicRegressor(niterations=1, seed=0, **TINY)
    est.fit(Xs, y)
    y_const = np.full(n, 3.0, dtype=np.float32)
    s = est.score(Xs, y_const)
    assert s == 0.0
