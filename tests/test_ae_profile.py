"""srprof (ISSUE 12): the analytic cost model (analysis/cost.py), the
modeled-vs-measured profiler (telemetry/profile.py), the cost-baseline
gate, the doctor's compile-event folding, srtop's utilization column and
CI exit code, and the bench-trajectory modeled-roofline series.

File name sorts between test_ad_* and test_analysis; everything outside
the `slow` marker is CPU-only host-side work on hand-computable jaxprs
and synthetic event lists (the CPU peak calibration microbench is the
one timed piece, ~1s). The real-search modeled-vs-measured join and the
profiling-on/off hall-of-fame bit-identity live under `slow`.
"""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# cost model: hand-computable jaxprs
# ---------------------------------------------------------------------------


def test_cost_matmul_flops_and_bytes():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    a = jnp.ones((8, 16), jnp.float32)
    b = jnp.ones((16, 32), jnp.float32)
    c = jaxpr_cost(jax.make_jaxpr(lambda a, b: a @ b)(a, b))
    # 2*M*N*K multiply-accumulates
    assert c["flops"] == 2 * 8 * 32 * 16
    # bytes: both inputs + the output, f32
    assert c["bytes"] == 4 * (8 * 16 + 16 * 32 + 8 * 32)
    assert c["io_bytes"] == 4 * (8 * 16 + 16 * 32 + 8 * 32)
    assert c["padded_waste_fraction"] == 0.0


def test_cost_reduce_prices_input_and_transcendental_weight():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import (
        FLOP_WEIGHTS,
        jaxpr_cost,
    )

    x = jnp.ones((1000,), jnp.float32)
    c = jaxpr_cost(jax.make_jaxpr(jnp.sum)(x))
    assert c["flops"] == 1000  # reductions price by INPUT elements

    c = jaxpr_cost(jax.make_jaxpr(jnp.exp)(x))
    assert c["flops"] == FLOP_WEIGHTS["exp"] * 1000


def test_cost_scan_multiplies_body_by_trip_count():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    def f(x):
        def body(c, _):
            return c * 2.0, None

        out, _ = jax.lax.scan(body, x, None, length=10)
        return out

    c = jaxpr_cost(jax.make_jaxpr(f)(jnp.ones((64,), jnp.float32)))
    assert c["flops"] == 64 * 10  # one mul per element per trip
    assert c["by_primitive"]["mul"] == 640.0


def test_cost_while_counts_once_and_tallies():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    def f(x):
        return jax.lax.while_loop(
            lambda v: jnp.sum(v) < 1e6, lambda v: v * 2.0, x
        )

    c = jaxpr_cost(jax.make_jaxpr(f)(jnp.ones((64,), jnp.float32)))
    assert c["while_loops"] == 1
    # body (64 muls) + cond (64-elem reduce + compare) counted ONCE
    assert c["flops"] >= 64 + 64
    assert c["flops"] < 64 * 10  # no phantom trip multiplier


def test_cost_padded_waste_fraction_hand_computed():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    # gt (mask, 100) + mul (compute, 100) + select_n (mask, 100):
    # waste = 200/300
    c = jaxpr_cost(jax.make_jaxpr(
        lambda x: jnp.where(x > 0, x * 2.0, x)
    )(jnp.ones((100,), jnp.float32)))
    assert math.isclose(c["padded_waste_fraction"], 2 / 3, abs_tol=1e-5)
    assert c["mask_flops"] == 200.0


def test_cost_data_movement_is_bytes_only():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    c = jaxpr_cost(jax.make_jaxpr(
        lambda x: jnp.transpose(x).reshape(-1)
    )(jnp.ones((8, 16), jnp.float32)))
    assert c["flops"] == 0.0
    assert c["bytes"] > 0


def test_cost_cond_data_movement_branches_keep_bytes():
    """A cond whose branches are all flop-free still takes its heaviest
    branch's BYTES (bytes are the tie-break when element-ops tie) —
    dropping them would let data movement added inside a cond slip
    under the baseline gate."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from symbolicregression_jl_tpu.analysis.cost import jaxpr_cost

    x = jnp.ones((1024,), jnp.float32)
    heavy = jaxpr_cost(jax.make_jaxpr(
        lambda p, v: lax.cond(
            p, lambda a: lax.rev(a, (0,)), lambda a: a, v
        )
    )(True, x))
    light = jaxpr_cost(jax.make_jaxpr(
        lambda p, v: lax.cond(p, lambda a: a, lambda a: a, v)
    )(True, x))
    assert heavy["flops"] == light["flops"] == 0.0
    assert heavy["bytes"] > light["bytes"]


# ---------------------------------------------------------------------------
# roofline join + device peaks
# ---------------------------------------------------------------------------


def test_roofline_join_compute_and_memory_bounds():
    from symbolicregression_jl_tpu.telemetry.profile import roofline_join

    peaks = {"flops_per_s": 1e12, "bytes_per_s": 1e11}
    # high intensity (io): compute ceiling binds
    j = roofline_join(1e9, 1e9, 0.01, peaks, io_bytes=1e6)
    assert j["bound"] == "compute"
    assert math.isclose(j["fraction_raw"], (1e9 / 0.01) / 1e12)
    assert 0 < j["fraction"] <= 1.0
    # low intensity even fused: memory ceiling binds
    j = roofline_join(1e6, 1e9, 0.01, peaks, io_bytes=1e9)
    assert j["bound"] == "memory"
    attainable = (1e6 / 1e9) * 1e11
    assert math.isclose(j["attainable_flops_per_s"], attainable)
    # degenerate inputs -> all-null row, never a crash
    j = roofline_join(0.0, 1e6, 0.0, peaks)
    assert j["fraction"] is None


def test_roofline_join_clamps_and_keeps_raw():
    from symbolicregression_jl_tpu.telemetry.profile import roofline_join

    peaks = {"flops_per_s": 1e6, "bytes_per_s": 1e12}
    j = roofline_join(1e9, 1e3, 0.01, peaks, io_bytes=1e3)
    assert j["fraction"] == 1.0  # clamped
    assert j["fraction_raw"] > 1.0  # overshoot preserved


def test_device_peaks_cpu_calibrated_and_tpu_tabled():
    from symbolicregression_jl_tpu.telemetry import profile as prof

    p = prof.device_peaks()  # CPU under the test harness
    assert p["source"] == "calibrated:cpu"
    assert p["flops_per_s"] > 0 and p["bytes_per_s"] > 0
    # cached: second call returns the identical measurement
    assert prof.device_peaks()["flops_per_s"] == p["flops_per_s"]

    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    t = prof.device_peaks(FakeDev())
    assert t["source"] == "table:v5 lite"
    assert t["flops_per_s"] == prof.TPU_PEAKS["v5 lite"]["flops_per_s"]

    class OddDev:
        platform = "tpu"
        device_kind = "TPU v99"

    assert prof.device_peaks(OddDev())["source"] == "table:default"


# ---------------------------------------------------------------------------
# cost baseline gate
# ---------------------------------------------------------------------------


def _fake_cost_entry(flops, bytes_, stages):
    return {
        "flops": flops, "bytes": bytes_, "padded_waste_fraction": 0.3,
        "stages": {
            s: {"flops": f, "bytes": b, "padded_waste_fraction": 0.3}
            for s, (f, b) in stages.items()
        },
    }


def test_cost_baseline_diff_catches_injected_regression():
    from symbolicregression_jl_tpu.analysis.cost import diff_cost_baseline

    baseline = {"configs": {
        "base": _fake_cost_entry(1000.0, 5000.0, {"cycle": (800.0, 4000.0)}),
    }}
    # +50% flops on the config and the stage: both fail
    grown = {
        "base": _fake_cost_entry(1500.0, 5000.0, {"cycle": (1200.0, 4000.0)})
    }
    problems, notes = diff_cost_baseline(grown, baseline)
    assert any("base: modeled flops grew" in p for p in problems)
    assert any("base.cycle: modeled flops grew" in p for p in problems)
    # -50%: a note, never a failure
    shrunk = {
        "base": _fake_cost_entry(500.0, 5000.0, {"cycle": (400.0, 4000.0)})
    }
    problems, notes = diff_cost_baseline(shrunk, baseline)
    assert not problems and any("shrank" in n for n in notes)
    # within tolerance: silent
    ok = {
        "base": _fake_cost_entry(1050.0, 5100.0, {"cycle": (820.0, 4100.0)})
    }
    problems, notes = diff_cost_baseline(ok, baseline)
    assert not problems and not notes
    # a stage/config that vanishes must fail, not silently stop gating
    gone = {"base": _fake_cost_entry(1000.0, 5000.0, {})}
    problems, _ = diff_cost_baseline(gone, baseline)
    assert any("no longer produced" in p for p in problems)
    problems, _ = diff_cost_baseline({}, baseline)
    assert any("base" in p and "no longer produced" in p
               for p in problems)


def test_checked_in_cost_baseline_well_formed():
    from symbolicregression_jl_tpu.analysis.cost import BASELINE_PATH
    from symbolicregression_jl_tpu.telemetry.spans import STAGES

    with open(BASELINE_PATH) as f:
        baseline = json.load(f)
    assert baseline["schema_version"] == 1
    configs = baseline["configs"]
    # the compile_surface matrix, stage-attributed on the shared
    # seven-stage vocabulary, every figure positive — plus the
    # whole-kernel Pallas entries (pallas_kernel_cost_entries), which
    # price one launch and carry no stage attribution
    assert set(configs) == {"base", "cache", "islands4", "pop32",
                            "bucketed", "rowsharded", "tenants2",
                            "pallas_postfix_flat",
                            "pallas_postfix_bucketed",
                            "pallas_postfix_fused"}
    for name, entry in configs.items():
        assert entry["flops"] > 0 and entry["bytes"] > 0
        assert 0.0 < entry["padded_waste_fraction"] < 1.0
        if name.startswith("pallas_"):
            assert entry["stages"] == {}
        else:
            assert set(entry["stages"]) == set(STAGES)
            for s in entry["stages"].values():
                assert s["flops"] > 0 and s["bytes"] > 0


# ---------------------------------------------------------------------------
# schema evolution: profile / compile events
# ---------------------------------------------------------------------------


def _env(type, **fields):
    return {"v": 1, "t": 1.0, "run": "r", "type": type, **fields}


def test_schema_accepts_profile_and_compile_events():
    from symbolicregression_jl_tpu.telemetry import validate_event

    assert validate_event(_env(
        "profile", stage="cycle", flops=1e6, bytes=1e7,
        padded_waste_fraction=0.4, measured_s=0.1,
        roofline_fraction=0.2, bound="compute",
        device_kind="cpu", peak_source="calibrated:cpu",
    )) == []
    assert validate_event(_env(
        "compile", name="cycle", phase=None, duration_s=12.5,
    )) == []
    # nulls where the stage recorded no span are legal
    assert validate_event(_env(
        "profile", stage="eval", flops=1.0, bytes=1.0,
        measured_s=None, roofline_fraction=None,
    )) == []


def test_schema_rejects_malformed_profile_and_compile():
    from symbolicregression_jl_tpu.telemetry import validate_event

    # missing required fields
    assert validate_event(_env("profile", stage="cycle"))
    assert validate_event(_env("compile", name="cycle"))
    # retyped required field
    assert validate_event(_env(
        "profile", stage=3, flops=1.0, bytes=1.0,
    ))
    assert validate_event(_env(
        "compile", name="cycle", duration_s="slow",
    ))


def test_roofline_event_accepts_modeled_fraction():
    from symbolicregression_jl_tpu.telemetry import validate_event

    assert validate_event(_env(
        "roofline", fraction=None, modeled_fraction=0.31,
        skip_reason="cpu-only", trees_rows_per_s=1e6,
    )) == []


# ---------------------------------------------------------------------------
# profiler report from synthetic events
# ---------------------------------------------------------------------------

_STAGES = ("init", "cycle", "mutate", "eval", "simplify", "optimize",
           "merge_migrate")


def _profile_events(stages=_STAGES, frac=0.2):
    events = [_env("run_start", config_fingerprint="x", backend="cpu",
                   devices=["c"], nout=1)]
    for i, s in enumerate(stages):
        events.append(_env(
            "profile", stage=s, flops=1e6 * (i + 1), bytes=1e7,
            padded_waste_fraction=0.4, measured_s=0.01 * (i + 1),
            measured_total_s=0.02 * (i + 1), count=2,
            roofline_fraction=frac, roofline_fraction_raw=frac,
            bound="compute", device_kind="cpu",
            peak_source="calibrated:cpu",
        ))
    events.append(_env("compile", name="cycle", duration_s=30.0))
    events.append(_env("run_end", num_evals=10.0, search_time_s=1.0))
    return events


def test_profile_report_complete_and_rendered(tmp_path, capsys):
    from symbolicregression_jl_tpu.telemetry.profile import (
        main,
        profile_report,
        render_text,
    )

    report = profile_report(_profile_events())
    assert report["complete"] and not report["missing_stages"]
    assert list(report["stages"]) == list(_STAGES)  # STAGES order
    cyc = report["stages"]["cycle"]
    assert cyc["modeled_share"] is not None
    assert cyc["wall_share"] is not None and cyc["skew"] is not None
    assert report["compile"]["cycle"]["total_s"] == 30.0
    text = render_text(report)
    for s in _STAGES:
        assert s in text
    assert "compile: 30.00s" in text

    # CLI: complete log -> 0, missing stage -> 1
    p = tmp_path / "events-full.jsonl"
    p.write_text("".join(
        json.dumps(e) + "\n" for e in _profile_events()
    ))
    assert main([str(p)]) == 0
    q = tmp_path / "events-part.jsonl"
    q.write_text("".join(
        json.dumps(e) + "\n" for e in _profile_events(_STAGES[:3])
    ))
    assert main([str(q)]) == 1
    capsys.readouterr()


def test_profile_report_skew_weights_modeled_share_by_count():
    """modeled_share weights per-dispatch flops by dispatch count (the
    wall side is count-multiplied): a stage dispatched 10x with the
    same per-dispatch cost and per-dispatch wall as a one-shot probe
    stage must show the same skew ~1, not a 10x-inflated one."""
    from symbolicregression_jl_tpu.telemetry.profile import (
        profile_report,
    )

    events = [
        _env("run_start", config_fingerprint="x", backend="cpu",
             devices=["c"], nout=1),
        _env("profile", stage="cycle", flops=1e6, bytes=1e7,
             measured_total_s=1.0, count=10),
        _env("profile", stage="eval", flops=1e6, bytes=1e7,
             measured_total_s=0.1, count=1),
        _env("run_end", num_evals=1.0, search_time_s=1.0),
    ]
    rep = profile_report(events)
    cyc, ev = rep["stages"]["cycle"], rep["stages"]["eval"]
    assert math.isclose(cyc["skew"], 1.0)
    assert math.isclose(ev["skew"], 1.0)
    assert math.isclose(cyc["modeled_share"], 10 / 11)


def test_emit_profile_events_joins_and_subtracts_compile():
    """The join math, without tracing: stub stage_costs so the test is
    pure host arithmetic."""
    from symbolicregression_jl_tpu.telemetry import profile as prof

    class FakeSink:
        def __init__(self):
            self.events = []

        def emit(self, type, **f):
            self.events.append({"type": type, **f})

    orig = None
    import symbolicregression_jl_tpu.analysis.cost as cost_mod

    orig = cost_mod.stage_costs

    def fake_stage_costs(options, nfeatures, nrows):
        return {
            "cycle": {"flops": 1e6, "bytes": 1e7, "io_bytes": 1e5,
                      "padded_waste_fraction": 0.4, "while_loops": 0},
            "eval": {"flops": 2e5, "bytes": 1e6, "io_bytes": 1e4,
                     "padded_waste_fraction": 0.4, "while_loops": 0},
        }

    cost_mod.stage_costs = fake_stage_costs
    try:
        sink = FakeSink()
        rows = prof.emit_profile_events(
            sink,
            # cycle's 10.2s span total includes 10s of compile
            {"cycle": (10.2, 2), "eval": (0.01, 1)},
            options=None, nfeatures=2, nrows=32,
            compile_totals={"cycle": 10.0},
        )
    finally:
        cost_mod.stage_costs = orig
    by = {r["stage"]: r for r in rows}
    assert math.isclose(by["cycle"]["measured_total_s"], 0.2)
    assert math.isclose(by["cycle"]["measured_s"], 0.1)
    assert by["cycle"]["compile_s"] == 10.0
    assert by["eval"]["compile_s"] is None
    for r in rows:
        assert 0.0 < r["roofline_fraction"] <= 1.0
    assert len(sink.events) == 2
    assert all(e["type"] == "profile" for e in sink.events)


# ---------------------------------------------------------------------------
# run doctor: compile folding + compile-bound flag
# ---------------------------------------------------------------------------


def _doctor_events(compile_s, cycle_span_s, extra_span_s=1.0):
    events = [_env("run_start", config_fingerprint="x", backend="cpu",
                   devices=["c"], nout=1)]
    for s in _STAGES:
        events.append(_env(
            "span", name=s, t_start=1.0,
            duration_s=cycle_span_s if s == "cycle" else extra_span_s,
        ))
    if compile_s:
        events.append(_env("compile", name="cycle",
                           duration_s=compile_s))
    events.append(_env(
        "metrics", output=0, iteration=0,
        snapshot={"counters": {}, "gauges": {"best_loss": 1.0},
                  "histograms": {}},
    ))
    events.append(_env("run_end", num_evals=10.0, search_time_s=1.0))
    return events


def test_doctor_folds_compile_out_of_stage_breakdown():
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    report = analyze_run(_doctor_events(compile_s=30.0, cycle_span_s=32.0))
    # the cycle row shows steady-state time, not compile+steady
    assert math.isclose(report["stages"]["cycle"]["total_s"], 2.0)
    assert report["compile"]["total_s"] == 30.0
    assert report["compile"]["by_stage"] == {"cycle": 30.0}
    # 30 / (30 + 2 + 6x1) -> ~79% compile share: flagged
    assert report["compile_bound"] is True
    assert any("compile-bound" in r for r in report["reasons"])
    assert report["verdict"] == "healthy"  # a flag, not a verdict

    from symbolicregression_jl_tpu.telemetry.analyze import render_text

    text = render_text(report)
    assert "COMPILE-BOUND" in text and "compile excluded" in text


def test_doctor_compile_under_half_not_flagged():
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    report = analyze_run(_doctor_events(compile_s=3.0, cycle_span_s=10.0))
    assert report["compile_bound"] is False
    assert not any("compile-bound" in r for r in report["reasons"])
    # no compile events at all: no compile section, share 0
    report = analyze_run(_doctor_events(compile_s=0.0, cycle_span_s=10.0))
    assert "compile" not in report
    assert report["compile_share"] == 0.0


# ---------------------------------------------------------------------------
# srtop: utilization column + --once CI gate
# ---------------------------------------------------------------------------


def test_srtop_utilization_column_and_flag(tmp_path, capsys):
    srtop = _load_script("srtop")
    events = _doctor_events(compile_s=0.0, cycle_span_s=10.0)
    # modeled shares: merge_migrate tiny model share but large wall
    # share -> flagged; cycle's wall share matches its model share
    for i, s in enumerate(_STAGES):
        events.append(_env(
            "profile", stage=s,
            flops=(1e8 if s == "cycle" else 1e3), bytes=1e7,
            measured_s=0.1, roofline_fraction=0.5,
        ))
    p = tmp_path / "events-u.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in events))
    rc = srtop.main([str(p), "--once"])
    out = capsys.readouterr().out
    assert rc == 0  # healthy log
    assert "|mod " in out
    # every non-cycle stage shares 1s of 16s wall (6%) with ~0% model
    # share; none crosses the 10% wall floor except... cycle dominates
    # wall AND model: no spurious flag on it
    assert "cycle 10.0s (62%|mod 100%)" in out


def test_srtop_once_exits_nonzero_on_unhealthy(tmp_path, capsys):
    srtop = _load_script("srtop")
    # incomplete log (no run_end): verdict incomplete -> rc 1
    events = _doctor_events(compile_s=0.0, cycle_span_s=1.0)[:-1]
    p = tmp_path / "events-bad.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in events))
    rc = srtop.main([str(p), "--once"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "doctor verdict: incomplete" in out
    # faulted log -> rc 1 as well
    events.append(_env("dispatch_fault", where="iteration",
                       error_type="XlaRuntimeError"))
    p.write_text("".join(json.dumps(e) + "\n" for e in events))
    assert srtop.main([str(p), "--once"]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# bench trajectory: the modeled roofline series
# ---------------------------------------------------------------------------


def test_bench_trajectory_picks_up_split_roofline(tmp_path):
    bt = _load_script("bench_trajectory")
    # old-era round: single roofline_fraction key
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"value": 1e6, "vs_baseline": 0.2, "platform": "cpu",
                   "roofline_fraction": None,
                   "roofline_skip_reason": "cpu-only"},
    }))
    # new-era round: split keys, modeled non-null on CPU
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "parsed": {"value": 1.1e6, "vs_baseline": 0.21, "platform": "cpu",
                   "roofline_measured": None,
                   "roofline_modeled": 0.31,
                   "roofline_skip_reason": "cpu-only"},
    }))
    traj = bt.build_trajectory(str(tmp_path))
    assert "roofline_modeled" in traj["series"]
    vals = [p["value"] for p in traj["series"]["roofline_modeled"]]
    assert vals == [None, 0.31]
    md = bt.render_markdown(traj)
    assert "roofline (modeled)" in md
    assert "0.31" in md
    # the bench-embedded summary block carries the modeled series too
    assert bt.bench_summary(traj)["roofline_modeled"] == [None, 0.31]


def test_checked_in_trajectory_carries_modeled_column():
    with open(os.path.join(REPO, "TRAJECTORY.json")) as f:
        traj = json.load(f)
    assert "roofline_modeled" in traj["series"]
    with open(os.path.join(REPO, "TRAJECTORY.md")) as f:
        assert "roofline (modeled)" in f.read()


# ---------------------------------------------------------------------------
# real-search round trips (slow: real compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_real_search_modeled_vs_measured_join(tmp_path):
    """ISSUE 12 acceptance: a real 2-iteration CPU search's log reports
    per-stage modeled element-ops/bytes, measured wall time, and a
    non-null modeled roofline fraction for ALL seven stages."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.telemetry import validate_events_file
    from symbolicregression_jl_tpu.telemetry.analyze import (
        analyze_run,
        resolve_log,
    )
    from symbolicregression_jl_tpu.telemetry.profile import (
        main as profile_main,
        profile_report,
    )

    rng = np.random.default_rng(0)
    X = rng.standard_normal((2, 64)).astype(np.float32)
    y = 2.0 * np.cos(X[1]) + X[0] ** 2
    sr.equation_search(
        X, y,
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        niterations=2, npopulations=3, npop=16,
        ncycles_per_iteration=8, maxsize=10, seed=5, verbosity=0,
        progress=False, telemetry=True, telemetry_dir=str(tmp_path),
    )
    log = resolve_log(str(tmp_path))
    val = validate_events_file(log)
    assert val["ok"], val["problems"]
    report = profile_report(log)
    assert report["complete"], report["missing_stages"]
    for stage, row in report["stages"].items():
        assert row["flops"] > 0 and row["bytes"] > 0, stage
        assert row["measured_total_s"] is not None, stage
        f = row["roofline_fraction"]
        assert isinstance(f, float) and 0.0 < f <= 1.0, (stage, f)
        assert 0.0 < row["padded_waste_fraction"] < 1.0, stage
    # the report CLI renders it and exits 0
    assert profile_main([log]) == 0
    # compile events landed for init + every phased-driver program, and
    # the doctor folds them out rather than smearing the first spans
    doctor = analyze_run(log)
    assert set(doctor["compile"]["by_stage"]) == {
        "init", "cycle", "simplify", "optimize", "merge_migrate",
    }
    assert doctor["verdict"] == "healthy", doctor["reasons"]


@pytest.mark.slow
def test_profile_trace_dir_bit_identical_and_captures(tmp_path):
    """Options.profile_trace_dir captures an XLA trace without touching
    the search: hall of fame bit-identical with tracing on vs off."""
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(0)
    X = rng.standard_normal((2, 64)).astype(np.float32)
    y = 2.0 * np.cos(X[1]) + X[0] ** 2
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        niterations=2, npopulations=3, npop=16,
        ncycles_per_iteration=8, maxsize=10, seed=5, verbosity=0,
        progress=False,
    )
    r_off = sr.equation_search(X, y, **kw)
    trace_dir = tmp_path / "trace"
    r_on = sr.equation_search(
        X, y, profile_trace_dir=str(trace_dir), **kw
    )

    def frontier(r):
        return [
            (c.complexity, float(c.loss), float(c.score), c.equation)
            for c in r.frontier()
        ]

    assert frontier(r_off) == frontier(r_on)
    # the capture actually wrote a trace
    captured = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir) for f in files
    ]
    assert captured, "profile_trace_dir produced no trace files"
