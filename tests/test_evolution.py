"""Evolution engine unit tests: tournament statistics, hall-of-fame merge,
Pareto frontier (parity: reference test/test_prob_pick_first.jl:24-43,
src/HallOfFame.jl semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.population import (
    HallOfFame,
    Population,
    best_sub_pop,
    calculate_pareto_frontier,
    init_hall_of_fame,
    merge_halls_of_fame,
    tournament_winner,
    update_hall_of_fame,
)
from symbolicregression_jl_tpu.models.trees import Expr, encode_tree, stack_trees
from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size

OPT = make_options(
    binary_operators=["+", "-", "*", "/"],
    unary_operators=["cos"],
    npop=20,
    tournament_selection_n=5,
    tournament_selection_p=0.8,
    use_frequency_in_tournament=False,
)


def make_pop(rng, npop=20, scores=None):
    trees = stack_trees(
        [
            encode_tree(
                random_expr_fixed_size(rng, OPT.operators, 3, 5), OPT.max_len
            )
            for _ in range(npop)
        ]
    )
    scores = jnp.asarray(
        scores if scores is not None else rng.random(npop).astype(np.float32)
    )
    return Population(
        trees=trees,
        scores=scores,
        losses=scores,
        birth=jnp.arange(npop, dtype=jnp.int32),
    )


def test_tournament_prefers_best(rng):
    """With p=0.8 the best member of the sampled tournament should win ~80%
    of the time (reference test/test_prob_pick_first.jl)."""
    scores = np.arange(20, dtype=np.float32)  # member 0 is best
    pop = make_pop(rng, scores=scores)
    freqs = jnp.ones(OPT.actual_maxsize)
    f = jax.jit(lambda k: tournament_winner(k, pop, freqs, OPT))
    keys = jax.random.split(jax.random.PRNGKey(0), 400)
    wins = np.array([int(f(k)) for k in keys])
    # the winner's score should be the min of its tournament most of the time;
    # global best (index 0) should win much more often than uniform (5/20)
    frac0 = np.mean(wins == 0)
    assert frac0 > 0.1  # uniform would be 0.05 in expectation per-slot
    # rank correlation: lower indices (better scores) win more
    assert np.mean(wins < 10) > 0.8


def test_best_sub_pop(rng):
    scores = rng.random(20).astype(np.float32)
    pop = make_pop(rng, scores=scores)
    trees, s, l = best_sub_pop(pop, 5)
    np.testing.assert_allclose(np.asarray(s), np.sort(scores)[:5])


def test_hall_of_fame_update_and_pareto(rng):
    hof = init_hall_of_fame(OPT)
    # candidates at complexities 1, 3, 5 with chosen losses
    cand = [
        (Expr.const(1.0), 5.0),
        (
            Expr.binary(0, Expr.var(0), Expr.const(1.0)),
            3.0,
        ),  # complexity 3
        (
            Expr.binary(
                0, Expr.var(0), Expr.binary(1, Expr.var(1), Expr.const(2.0))
            ),
            4.0,  # complexity 5 but WORSE than complexity-3: not on frontier
        ),
    ]
    trees = stack_trees([encode_tree(e, OPT.max_len) for e, _ in cand])
    losses = jnp.asarray([l for _, l in cand], jnp.float32)
    hof = update_hall_of_fame(hof, trees, losses, losses, OPT)
    exists = np.asarray(hof.exists)
    assert exists[0] and exists[2] and exists[4]
    front = np.asarray(calculate_pareto_frontier(hof))
    assert front[0] and front[2] and not front[4]

    # a better complexity-5 candidate takes the slot
    better = stack_trees(
        [encode_tree(cand[2][0], OPT.max_len)]
    )
    hof2 = update_hall_of_fame(
        hof, better, jnp.asarray([1.0]), jnp.asarray([1.0]), OPT
    )
    assert float(hof2.losses[4]) == 1.0
    front2 = np.asarray(calculate_pareto_frontier(hof2))
    assert front2[4]


def test_hof_merge():
    a = init_hall_of_fame(OPT)
    b = init_hall_of_fame(OPT)
    t = stack_trees([encode_tree(Expr.const(2.0), OPT.max_len)])
    a = update_hall_of_fame(a, t, jnp.asarray([2.0]), jnp.asarray([2.0]), OPT)
    b = update_hall_of_fame(b, t, jnp.asarray([1.0]), jnp.asarray([1.0]), OPT)
    m = merge_halls_of_fame(a, b)
    assert float(m.losses[0]) == 1.0
    m2 = merge_halls_of_fame(b, a)
    assert float(m2.losses[0]) == 1.0


def test_update_hof_ignores_out_of_range_and_nan(rng):
    hof = init_hall_of_fame(OPT)
    t = stack_trees([encode_tree(Expr.const(1.0), OPT.max_len)])
    hof2 = update_hall_of_fame(
        hof, t, jnp.asarray([jnp.inf]), jnp.asarray([jnp.inf]), OPT
    )
    assert not bool(hof2.exists.any())


def test_optimize_mutation_weight_improves_constants(rng):
    """mutation_weights.optimize > 0 actually optimizes constants (the
    reference runs constant optimization inside the mutation switch,
    src/Mutate.jl:142-168; here it is an equivalently-sized iteration-level
    pass) and records improvements in the OPTIMIZE telemetry row."""
    from symbolicregression_jl_tpu.api import _make_iteration_fn
    from symbolicregression_jl_tpu.models.evolve import (
        MUTATION_NAMES,
        expected_optimize_count,
        init_island_state,
    )

    opts = make_options(
        binary_operators=["+", "*"],
        unary_operators=[],
        npop=24,
        npopulations=2,
        ncycles_per_iteration=10,
        maxsize=10,
        should_optimize_constants=False,  # regular pass OFF: only the
        # optimize mutation may fit constants
        mutation_weights=dict(
            mutate_constant=0.0, mutate_operator=0.0, add_node=0.0,
            insert_node=0.0, delete_node=0.0, simplify=0.0,
            randomize=0.0, do_nothing=1.0, optimize=1.0,
        ),
        verbosity=0,
        progress=False,
    )
    assert expected_optimize_count(opts) > 0

    X = jnp.asarray(rng.standard_normal((2, 50)).astype(np.float32))
    y = 2.5 * X[0] + 0.7
    baseline = jnp.float32(jnp.var(y))

    keys = jax.random.split(jax.random.PRNGKey(0), opts.npopulations)
    states = jax.vmap(
        lambda k: init_island_state(
            k, opts, 2, X, y, None, baseline
        )
    )(keys)
    loss0 = float(jnp.sum(jnp.where(jnp.isfinite(states.pop.losses),
                                    states.pop.losses, 0.0)))

    fn = _make_iteration_fn(opts, False)
    states2, _ = fn(states, jax.random.PRNGKey(1), jnp.int32(opts.maxsize),
                    X, y, baseline, opts.traced_scalars())
    loss1 = float(jnp.sum(jnp.where(jnp.isfinite(states2.pop.losses),
                                    states2.pop.losses, 0.0)))
    opt_row = MUTATION_NAMES.index("optimize")
    accepted = int(jnp.sum(states2.mut_counts[:, opt_row, 1]))
    proposed = int(jnp.sum(states2.mut_counts[:, opt_row, 0]))
    assert proposed > 0  # the switch sampled optimize slots
    assert accepted > 0  # the pass improved at least one member
    assert loss1 < loss0  # population got strictly better
