"""Chunked-dispatch iteration driver (options.max_cycles_per_dispatch):
phased dispatches must reproduce the fused single-jit iteration exactly.

The knob exists for the at-scale TPU fault story (BASELINE.md): a 64x1000
iteration as ONE device call is the only program shape that has ever
faulted the chip, so the production driver can split it into bounded
calls — but only if the split is a pure dispatch decision with zero
numerical effect. These tests pin that equivalence (annealing ON so the
iteration-wide LinRange(1,0) schedule slicing is exercised, ncycles not
divisible by the chunk so the remainder path runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.api import (
    _make_init_fn,
    _make_iteration_driver,
    _make_iteration_fn,
)
from symbolicregression_jl_tpu.models.options import make_options


def _opts(**kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npop=12,
        npopulations=3,
        ncycles_per_iteration=7,
        tournament_selection_n=4,
        maxsize=10,
        annealing=True,
        seed=0,
    )
    base.update(kw)
    return make_options(**base)


def _setup(options):
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((2, 64)).astype(np.float32))
    y = jnp.asarray(np.asarray(2.0 * jnp.cos(X[1]) + X[0]))
    baseline = jnp.float32(float(jnp.var(y)))
    init = _make_init_fn(options, 2, False)
    scalars = options.traced_scalars()
    states = init(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    return states, X, y, baseline, scalars


@pytest.mark.fast
def test_chunked_matches_fused():
    fused_o = _opts()
    chunk_o = _opts(max_cycles_per_dispatch=3)  # 7 cycles -> 3+3+1
    states, X, y, baseline, scalars = _setup(fused_o)
    cm = jnp.int32(fused_o.maxsize)
    key = jax.random.PRNGKey(7)

    s1, g1 = _make_iteration_fn(fused_o, False)(
        states, key, cm, X, y, baseline, scalars
    )
    s2, g2 = _make_iteration_driver(chunk_o, False)(
        states, key, cm, X, y, baseline, scalars
    )

    np.testing.assert_array_equal(np.asarray(g1.losses), np.asarray(g2.losses))
    for a, b in zip(jax.tree_util.tree_leaves(g1.trees),
                    jax.tree_util.tree_leaves(g2.trees)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # every leaf of the island state — populations, HoFs, adaptive-
    # parsimony stats windows, PRNG keys, telemetry — must be bit-equal
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.fast
def test_chunked_driver_is_fused_when_unset():
    o = _opts()
    assert _make_iteration_driver(o, False) is _make_iteration_fn(o, False)


@pytest.mark.fast
def test_chunked_recorder_events_concatenate():
    chunk_o = _opts(max_cycles_per_dispatch=4, recorder=True)
    fused_o = _opts(recorder=True)
    states, X, y, baseline, scalars = _setup(fused_o)
    cm = jnp.int32(fused_o.maxsize)
    key = jax.random.PRNGKey(3)

    s1, g1, ev1 = _make_iteration_fn(fused_o, False)(
        states, key, cm, X, y, baseline, scalars
    )
    s2, g2, ev2 = _make_iteration_driver(chunk_o, False)(
        states, key, cm, X, y, baseline, scalars
    )
    for a, b in zip(jax.tree_util.tree_leaves(ev1),
                    jax.tree_util.tree_leaves(ev2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(g1.losses), np.asarray(g2.losses))


@pytest.mark.fast
def test_chunked_batching_runs_deterministically():
    """batching=True under chunking: NOT bit-equal to fused (each chunk
    re-derives its minibatch key chain — documented on the Options
    field), but it must run and be deterministic call-over-call."""
    o = _opts(max_cycles_per_dispatch=3, batching=True, batch_size=16)
    states, X, y, baseline, scalars = _setup(o)
    cm = jnp.int32(o.maxsize)
    key = jax.random.PRNGKey(11)
    drv = _make_iteration_driver(o, False)
    _, g1 = drv(states, key, cm, X, y, baseline, scalars)
    _, g2 = drv(states, key, cm, X, y, baseline, scalars)
    np.testing.assert_array_equal(np.asarray(g1.losses), np.asarray(g2.losses))
    assert np.isfinite(np.asarray(g1.losses)).any()


@pytest.mark.fast
def test_chunked_equation_search_end_to_end():
    """The knob through the public API: same tiny search, fused vs
    chunked, identical hall of fame."""
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(1)
    X = rng.standard_normal((2, 48)).astype(np.float32)
    y = (X[0] * X[0] + 0.5).astype(np.float32)
    common = dict(
        binary_operators=["+", "*"],
        npop=10,
        npopulations=2,
        ncycles_per_iteration=5,
        tournament_selection_n=4,
        maxsize=8,
        progress=False,
        verbosity=0,
        save_to_file=False,
        seed=0,
        deterministic=True,
    )
    h1 = sr.equation_search(X, y, niterations=2, **common)
    h2 = sr.equation_search(
        X, y, niterations=2, max_cycles_per_dispatch=2, **common
    )
    b1, b2 = h1.best(), h2.best()
    assert b1.loss == b2.loss
    assert b1.equation == b2.equation
