"""Pallas kernel correctness vs the jnp interpreter (interpret mode on CPU;
the same kernel runs compiled on TPU — exercised by bench.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.trees import encode_tree, stack_trees
from symbolicregression_jl_tpu.ops.interpreter import eval_trees
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.ops.pallas_eval import (
    eval_trees_pallas,
    fuse_opcodes,
)
from symbolicregression_jl_tpu.utils.random_exprs import random_expr_fixed_size

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp", "sqrt", "log"])
L = 24
NFEAT = 4


def batch(rng, n, max_size=14, ops=OPS):
    return stack_trees(
        [
            encode_tree(
                random_expr_fixed_size(
                    rng, ops, NFEAT, int(rng.integers(1, max_size))
                ),
                L,
            )
            for _ in range(n)
        ]
    )


def test_fuse_opcodes(rng):
    trees = batch(rng, 8)
    pcode = np.asarray(fuse_opcodes(trees, OPS))
    kind = np.asarray(trees.kind)
    op = np.asarray(trees.op)
    U = OPS.n_unary
    assert np.all(pcode[kind == 0] == 0)
    assert np.all(pcode[kind == 1] == 1)
    assert np.all(pcode[kind == 2] == 2)
    assert np.all(pcode[kind == 3] == 3 + op[kind == 3])
    assert np.all(pcode[kind == 4] == 3 + U + op[kind == 4])


@pytest.mark.parametrize("n_trees,n_rows", [(10, 37), (3, 130), (17, 256)])
def test_pallas_matches_jnp(rng, n_trees, n_rows):
    trees = batch(rng, n_trees)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, n_rows)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    ok_np = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[ok_np],
        np.asarray(y_ref)[ok_np],
        rtol=1e-5,
        atol=1e-5,
    )


def test_pallas_row_padding_no_poison(rng):
    """Padded rows must not mark a tree incomplete: sqrt(x0) with all-valid
    rows positive stays ok even when padded region would be negative."""
    ops = make_operator_set(["+"], ["sqrt"])
    from symbolicregression_jl_tpu.models.trees import Expr

    e = Expr.unary(0, Expr.var(0))
    trees = stack_trees([encode_tree(e, L)])
    X = jnp.asarray(np.full((1, 100), 4.0, np.float32))
    y, ok = eval_trees_pallas(
        trees, X, ops, t_block=8, r_block=128, interpret=True
    )
    assert bool(ok[0])
    np.testing.assert_allclose(np.asarray(y)[0], 2.0, rtol=1e-6)


def test_interpret_max_len_not_multiple_of_unroll():
    """max_len % 4 != 0 must not index past the slot tables (regression:
    the 4-slot loop groups round the per-tree bound up to a multiple of 4).
    """
    import numpy as np

    from symbolicregression_jl_tpu.models.trees import encode_tree, parse_expression
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees

    s = "((x0 + 1.5) * x0) + ((x0 - 0.5) * (x0 + 2))"  # size 13
    expr = parse_expression(s, OPS)
    L = 14  # not a multiple of 4, barely fits the tree
    tree = encode_tree(expr, L)
    trees = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], tree)
    X = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, 50)).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(trees, X, OPS, interpret=True)
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-6
    )


def test_interpret_unrolled_slot_loop_variant():
    """The A/B 'unrolled' slot-loop variant must agree with 'dynamic'."""
    import numpy as np

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )

    n = 32
    sizes = jax.random.randint(jax.random.PRNGKey(3), (n,), 1, 14)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 2, OPS, 16)
    )(jax.random.split(jax.random.PRNGKey(4), n), sizes)
    X = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 40)).astype(np.float32)
    )
    y_d, ok_d = eval_trees_pallas(trees, X, OPS, interpret=True)
    y_u, ok_u = eval_trees_pallas(
        trees, X, OPS, interpret=True, slot_loop="unrolled"
    )
    np.testing.assert_array_equal(np.asarray(ok_d), np.asarray(ok_u))
    np.testing.assert_allclose(
        np.asarray(y_d), np.asarray(y_u), rtol=1e-6, atol=1e-7
    )


@pytest.mark.parametrize("dispatch", ["mux", "chain"])
@pytest.mark.parametrize("tree_unroll", [1, 2, 4, 8])
@pytest.mark.parametrize("sort_trees", [True, False])
def test_kernel_variants_agree(rng, dispatch, tree_unroll, sort_trees):
    """Every (dispatch, tree_unroll, sort) kernel variant must produce the
    jnp interpreter's results bit-for-bit in ok and numerically in y."""
    trees = batch(rng, 13)  # odd count: exercises group padding
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 50)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        dispatch=dispatch, tree_unroll=tree_unroll, sort_trees=sort_trees,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("leaf_skip", [True, "class"])
@pytest.mark.parametrize("tree_unroll", [1, 4])
@pytest.mark.parametrize("compute_dtype", ["float32", "bfloat16"])
def test_leaf_skip_variant_agrees(rng, tree_unroll, compute_dtype,
                                  leaf_skip):
    """The leaf-skip kernels (scalar-predicated 2-way leaf|op branch, and
    the 3-way leaf|unary|binary 'class' split) must match the always-mux
    kernel exactly: same stores, same poison semantics — including PAD
    slots taking the leaf branch harmlessly and non-finite CONST leaves
    still poisoning."""
    trees = batch(rng, 13)
    # plant a non-finite constant leaf in one tree: the leaf branch must
    # still record the poison
    from symbolicregression_jl_tpu.models.trees import CONST

    kind0 = np.asarray(trees.kind)
    cval0 = np.array(trees.cval, np.float32)  # copy: jax buffers are RO
    t_i, s_i = np.argwhere(kind0 == CONST)[0]
    cval0[t_i, s_i] = np.inf
    trees = trees._replace(cval=jnp.asarray(cval0))
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 50)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        tree_unroll=tree_unroll, compute_dtype=compute_dtype,
    )
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        tree_unroll=tree_unroll, compute_dtype=compute_dtype,
        leaf_skip=leaf_skip,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_array_equal(np.asarray(y)[m], np.asarray(y_ref)[m])
    assert not np.asarray(ok)[t_i]  # the inf const poisoned its tree


@pytest.mark.parametrize("leaf_skip", [False, True, "class"])
@pytest.mark.parametrize(
    "bins,unas",
    [
        (["+"], []),  # single binary, no unary: degenerate mux + fallback
        (["+", "*"], ["cos"]),  # single unary arm
        (["+", "-", "*", "/"],
         ["square", "sqrt", "abs", "cos", "exp", "log"]),  # wide set
    ],
)
def test_skip_variants_across_opsets(rng, bins, unas, leaf_skip):
    """Branch/mux boundaries across operator-set shapes: every skip shape
    must reproduce the jnp interpreter on sets where an arm is empty,
    singleton, or wide (the 'class' fallback for U=0 included)."""
    ops2 = make_operator_set(bins, unas)
    trees = batch(rng, 9, max_size=12, ops=ops2)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 40)) * 1.5).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, ops2)
    y, ok = eval_trees_pallas(
        trees, X, ops2, t_block=8, r_block=128, interpret=True,
        tree_unroll=2, leaf_skip=leaf_skip,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )


def test_leaf_skip_rejects_instr_program(rng):
    trees = batch(rng, 4)
    X = jnp.asarray(rng.standard_normal((NFEAT, 16)).astype(np.float32))
    with pytest.raises(ValueError, match="postfix"):
        eval_trees_pallas(
            trees, X, OPS, interpret=True, program="instr", leaf_skip=True
        )


def test_options_kernel_leaf_skip_validation():
    """The Options knob mirrors the kernel's argument contract at
    construction time, so a bad combination fails at make_options rather
    than deep inside a jitted search step."""
    from symbolicregression_jl_tpu.models.options import make_options

    make_options(kernel_leaf_skip="class")  # postfix-auto: fine
    make_options(kernel_leaf_skip=True, kernel_program="postfix")
    # 'auto' resolves to the measured default, never conflicts
    make_options(kernel_leaf_skip="auto", kernel_program="instr")
    with pytest.raises(ValueError, match="kernel_leaf_skip"):
        make_options(kernel_leaf_skip="always")
    with pytest.raises(ValueError, match="leaf slots"):
        make_options(kernel_leaf_skip=True, kernel_program="instr")


def test_dispatch_routes_leaf_skip(rng, monkeypatch):
    """options.kernel_leaf_skip reaches the kernel call: 'auto' resolves
    to fitness._DEFAULT_LEAF_SKIP, explicit values pass through, and the
    instr programs force False (they have no leaf slots)."""
    from symbolicregression_jl_tpu.models import fitness
    from symbolicregression_jl_tpu.ops import pallas_eval as pe

    seen = {}

    def fake_eval(trees, X, operators, **kw):
        seen.update(kw)
        return jnp.zeros((4, 16), jnp.float32), jnp.ones(4, bool)

    monkeypatch.setattr(pe, "eval_trees_pallas", fake_eval)
    trees = batch(rng, 4)
    X = jnp.asarray(rng.standard_normal((NFEAT, 16)).astype(np.float32))

    fitness.dispatch_eval(trees, X, OPS, backend="pallas",
                          leaf_skip="class")
    assert seen["leaf_skip"] == "class"
    fitness.dispatch_eval(trees, X, OPS, backend="pallas")
    assert seen["leaf_skip"] == fitness._DEFAULT_LEAF_SKIP
    fitness.dispatch_eval(trees, X, OPS, backend="pallas",
                          program="instr", leaf_skip=True)
    assert seen["leaf_skip"] is False


def test_pallas_bf16_compute_tolerance(rng):
    """bf16-compute / f32-accumulate kernel variant stays within bf16
    tolerance of the f32 oracle (the TPU-native analog of the reference's
    type-generic eval sweeps, test/test_tree_construction.jl:96-145)."""
    trees = batch(rng, 12, max_size=10)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 64)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        compute_dtype="bfloat16",
    )
    assert y.dtype == jnp.float32  # f32 accumulate/output
    ok_np = np.asarray(ok_ref)
    # the finite-mask can legitimately differ near overflow (bf16 inf where
    # f32 survives); require agreement on trees that are finite in BOTH
    both = ok_np & np.asarray(ok)
    assert both.sum() >= 1
    ref = np.asarray(y_ref)[both]
    got = np.asarray(y)[both]
    # bf16 has ~8 mantissa bits; deep trees compound error
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.1)


def test_pallas_bf16_auto_routing():
    """'auto' dispatch routes bf16 inputs to the kernel's bf16 variant
    (only when a TPU backend is active — here we just pin the plumbing:
    dispatch on CPU stays on the jnp path and preserves dtype)."""
    from symbolicregression_jl_tpu.models.fitness import dispatch_eval

    rng = np.random.default_rng(0)
    trees = batch(rng, 4)
    X = jnp.asarray(rng.standard_normal((NFEAT, 16))).astype(jnp.bfloat16)
    y, ok = dispatch_eval(trees, X, OPS, backend="auto")
    assert y.shape == (4, 16)


@pytest.mark.parametrize("program", ["instr", "instr_packed"])
@pytest.mark.parametrize("tree_unroll", [1, 4])
@pytest.mark.parametrize("sort_trees", [True, False])
def test_instr_program_matches_jnp(rng, program, tree_unroll, sort_trees):
    """The compressed operator-only instruction programs (program='instr'
    and its packed-word variant) must reproduce the jnp interpreter
    bit-for-bit in ok and numerically in y — including the
    operand-finiteness poison semantics (leaves are operands there, not
    executed slots)."""
    trees = batch(rng, 13)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 50)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        program=program, tree_unroll=tree_unroll, sort_trees=sort_trees,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("program", ["instr", "instr_packed"])
def test_instr_program_bare_leaves_and_unary_chains(rng, program):
    """Edge shapes of the compressed program: bare-leaf trees run one
    synthetic IDENT instruction; pure unary chains compress to length-1
    programs... of nearly the tree's own length (no leaves to drop)."""
    from symbolicregression_jl_tpu.models.trees import Expr

    chain = Expr.var(0)
    for _ in range(9):
        chain = Expr.unary(0, chain)  # cos^9(x0)
    trees = stack_trees([
        encode_tree(Expr.const(2.5), L),
        encode_tree(Expr.var(1), L),
        encode_tree(chain, L),
    ])
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 40))).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        program=program,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("program", ["instr", "instr_packed"])
def test_instr_program_infinite_operand_poison(rng, program):
    """relu(-inf) = 0 is finite, but the tree must still be flagged not-ok
    (the jnp interpreter poisons the leaf slot; the instr kernel must
    poison via the operand check)."""
    ops = make_operator_set(["+"], ["relu"])
    from symbolicregression_jl_tpu.models.trees import Expr

    e = Expr.unary(0, Expr.const(float("-inf")))
    trees = stack_trees([encode_tree(e, L)])
    X = jnp.asarray(np.ones((1, 30), np.float32))
    y_ref, ok_ref = eval_trees(trees, X, ops)
    y, ok = eval_trees_pallas(
        trees, X, ops, t_block=8, r_block=128, interpret=True,
        program=program,
    )
    assert not bool(ok[0])
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))


def test_instr_packed_rejects_oversized_layout(rng):
    """An explicit instr_packed request that does not fit the packed
    word's bitfields must fail loudly, not silently fall back (a silent
    fallback would mislabel benchmark and roofline results)."""
    trees = batch(rng, 4)
    # 3000 features blows the 11-bit unified-index budget
    X = jnp.zeros((3000, 8), jnp.float32)
    with pytest.raises(ValueError, match="instr_packed"):
        eval_trees_pallas(
            trees, X, OPS, interpret=True, program="instr_packed"
        )


def test_instruction_schedule_compression(rng):
    """Instruction count equals the number of operator nodes (>=1 for any
    nonempty tree), always <= postfix length."""
    from symbolicregression_jl_tpu.ops.pallas_eval import (
        instruction_schedule,
    )

    trees = batch(rng, 16)
    tables, n_instr = instruction_schedule(trees, OPS)
    kind = np.asarray(trees.kind)
    n_ops = ((kind == 3) | (kind == 4)).sum(axis=-1)
    expect = np.maximum(n_ops, 1)
    np.testing.assert_array_equal(np.asarray(n_instr), expect)
    assert tables["icode"].shape == trees.kind.shape


def test_mosaic_substituted_opset_matches_jnp(rng):
    """Op sets whose lax impls Mosaic cannot lower (cosh/sinh/atan/erf/
    gamma/mod...) must still run through the kernel via the
    KERNEL_SUBSTITUTES compositions, matching the jnp interpreter (which
    keeps the exact lax fns) within the compositions' accuracy."""
    ops = make_operator_set(
        ["+", "-", "*", "mod"],
        ["cosh", "sinh", "atan", "erf", "atanh", "gamma"],
    )
    trees = batch(rng, 12, max_size=10, ops=ops)
    X = jnp.asarray(rng.uniform(-3, 3, (NFEAT, 64)).astype(np.float32))
    y_ref, ok_ref = eval_trees(trees, X, ops)
    y, ok = eval_trees_pallas(
        trees, X, ops, t_block=8, r_block=128, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=2e-4, atol=1e-5
    )


@pytest.mark.parametrize("program", ["postfix", "instr"])
def test_rows_beyond_one_block_accumulate(rng, program):
    """nrows > r_block splits the row grid (grid_j > 1); the poison row
    must accumulate across row tiles — a NaN in the LAST tile must still
    poison the tree, and valid trees must match the interpreter."""
    trees = batch(rng, 9, max_size=12)
    n_rows = 300  # 3 row tiles at r_block=128
    X_h = (rng.standard_normal((NFEAT, n_rows)) * 2).astype(np.float32)
    y_ref, ok_ref = eval_trees(trees, jnp.asarray(X_h), OPS)
    y, ok = eval_trees_pallas(
        trees, jnp.asarray(X_h), OPS, t_block=8, r_block=128,
        interpret=True, program=program,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )
    # force a poison visible ONLY in the final row tile: log of a
    # negative feature value placed past row 256. A deterministic
    # log(x0) tree guarantees the scenario actually fires (random trees
    # might not apply log to a feature at all).
    from symbolicregression_jl_tpu.models.trees import Expr

    ops = make_operator_set(["+", "-", "*", "/"], ["log"])
    log_tree = encode_tree(Expr.unary(ops.unary_index("log"),
                                      Expr.var(0)), L)
    t2 = stack_trees(
        [log_tree]
        + [encode_tree(
            random_expr_fixed_size(rng, ops, NFEAT, 6), L
        ) for _ in range(5)]
    )
    X2 = np.abs(X_h) + 0.5
    X2[:, -1] = -1.0  # row 299 -> tile 2
    y2_ref, ok2_ref = eval_trees(t2, jnp.asarray(X2), ops)
    assert not bool(np.asarray(ok2_ref)[0]), (
        "log(x0) over a negative final-tile row must poison tree 0"
    )
    y2, ok2 = eval_trees_pallas(
        t2, jnp.asarray(X2), ops, t_block=8, r_block=128,
        interpret=True, program=program,
    )
    np.testing.assert_array_equal(np.asarray(ok2), np.asarray(ok2_ref))


@pytest.mark.parametrize("leaf_skip", [False, True, "class"])
def test_scalar_pack_matches_jnp(rng, leaf_skip):
    """The packed-scalar postfix variant (one SMEM word per slot instead
    of four table reads) must be numerically identical to the unpacked
    kernel — only the scalar fetch changes, never the dataflow. Covers
    composition with every leaf_skip mode and a multi-row-tile grid."""
    trees = batch(rng, 15)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 300)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        scalar_pack=True, leaf_skip=leaf_skip,
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )


def test_scalar_pack_width_validation(rng):
    """Fields beyond the packed word's widths must fail loudly, not
    silently fall back (benchmark attribution), and scalar_pack is a
    postfix-only knob."""
    trees = batch(rng, 4)
    X_wide = jnp.zeros((300, 8), jnp.float32)  # 300 features > 8-bit field
    with pytest.raises(ValueError, match="scalar_pack"):
        eval_trees_pallas(
            trees, X_wide, OPS, interpret=True, scalar_pack=True
        )
    X = jnp.zeros((NFEAT, 8), jnp.float32)
    with pytest.raises(ValueError, match="postfix"):
        eval_trees_pallas(
            trees, X, OPS, interpret=True, scalar_pack=True,
            program="instr",
        )


def test_operand_schedule_top_invariant(rng):
    """Encode-time invariant the top_carry kernel relies on: in postfix
    order every operator slot's right/unary operand (stack top) is the
    immediately preceding slot's result — ridx == si - 1."""
    from symbolicregression_jl_tpu.ops.pallas_eval import operand_schedule

    trees = batch(rng, 64, max_size=22)
    _, ridx = operand_schedule(trees.kind)
    kind = np.asarray(trees.kind)
    is_op = (kind == 3) | (kind == 4)
    si = np.broadcast_to(np.arange(kind.shape[1]), kind.shape)
    np.testing.assert_array_equal(np.asarray(ridx)[is_op], si[is_op] - 1)


@pytest.mark.parametrize("kw", [
    dict(top_carry=True),
    dict(top_carry=True, scalar_pack=True),
    dict(top_carry=True, leaf_skip="class"),
    dict(top_carry=True, slot_loop="unrolled"),
])
def test_top_carry_matches_jnp(rng, kw):
    """The register-carried top-of-stack variant must match the
    interpreter exactly across its composable knobs (the invariant test
    above is why the carry is sound)."""
    trees = batch(rng, 13)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 60)) * 2).astype(np.float32)
    )
    y_ref, ok_ref = eval_trees(trees, X, OPS)
    y, ok = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True, **kw
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-5, atol=1e-5
    )


# ---------------------------------------------------------------------------
# bucket-laddered kernel dispatch + fused loss epilogue (ISSUE 17)
# ---------------------------------------------------------------------------


def _skewed_batch(rng, n=32):
    """80/15/5 short/mid/long — the skew the ladder exists for."""
    sizes = np.where(
        rng.random(n) < 0.80, rng.integers(2, 7, n),
        np.where(rng.random(n) < 0.75, rng.integers(7, 13, n),
                 rng.integers(13, 22, n)),
    )
    return stack_trees([
        encode_tree(
            random_expr_fixed_size(rng, OPS, NFEAT, int(s)), L
        )
        for s in sizes
    ])


@pytest.mark.parametrize("ladder", [
    (0.25, 0.5, 1.0),
    (1.0,),  # one rung: still must be the identity
])
def test_bucketed_bit_identical_to_flat(rng, ladder):
    """The bucket ladder is a DISPATCH decomposition, not a numeric
    mode: values, ok mask, and inverse-permutation scatter must be
    bit-identical to the flat kernel on a skewed batch."""
    trees = _skewed_batch(rng)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 140)) * 2).astype(np.float32)
    )
    y_flat, ok_flat = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True
    )
    y_buck, ok_buck = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        bucket_ladder=ladder,
    )
    assert np.array_equal(
        np.asarray(y_flat), np.asarray(y_buck), equal_nan=True
    )
    np.testing.assert_array_equal(
        np.asarray(ok_flat), np.asarray(ok_buck)
    )


def test_bucketed_poison_bit_identical_to_flat(rng):
    """Poison semantics cross bucket boundaries unchanged: planted inf
    constants must poison the SAME trees under the ladder."""
    trees = _skewed_batch(rng)
    n = trees.length.shape[0]
    trees = trees._replace(cval=jnp.where(
        (jnp.arange(n) % 5 == 0)[:, None], jnp.inf, trees.cval
    ))
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 140)) * 2).astype(np.float32)
    )
    y_flat, ok_flat = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True
    )
    y_buck, ok_buck = eval_trees_pallas(
        trees, X, OPS, t_block=8, r_block=128, interpret=True,
        bucket_ladder=(0.25, 0.5, 1.0),
    )
    assert not bool(np.all(np.asarray(ok_flat)))  # poison took effect
    np.testing.assert_array_equal(
        np.asarray(ok_flat), np.asarray(ok_buck)
    )
    assert np.array_equal(
        np.asarray(y_flat), np.asarray(y_buck), equal_nan=True
    )


def test_bucketed_requires_postfix():
    trees = stack_trees([encode_tree(
        random_expr_fixed_size(np.random.default_rng(0), OPS, NFEAT, 5),
        L,
    )])
    X = jnp.zeros((NFEAT, 8), jnp.float32)
    with pytest.raises(ValueError, match="bucket_ladder"):
        eval_trees_pallas(
            trees, X, OPS, interpret=True, program="instr",
            bucket_ladder=(0.5, 1.0),
        )


@pytest.mark.parametrize("r_block,bucket_ladder", [
    (128, (0.25, 0.5, 1.0)),  # 2 row tiles: exercises accum_tile j>0
    (256, ()),  # single row tile, flat dispatch
])
def test_fused_epilogue_bit_identical_to_host_twin(rng, r_block,
                                                   bucket_ladder):
    """The kernel-fused loss epilogue vs the host composition it
    replaces — contain_nonfinite(aggregate_loss(elem,
    tile_rows=r_block), ok) — must agree BITWISE, with both sides
    jitted (the production regime; under jit XLA folds the constant
    row-count divisor to a reciprocal-multiply on both sides alike,
    where an eager host graph would divide — a 1-ULP seam this contract
    deliberately excludes by jitting both)."""
    from symbolicregression_jl_tpu.ops.losses import (
        aggregate_loss,
        contain_nonfinite,
        l2_dist_loss,
    )
    from symbolicregression_jl_tpu.ops.pallas_eval import (
        eval_loss_trees_pallas,
    )

    trees = _skewed_batch(rng)
    n_rows = 140
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, n_rows)) * 2).astype(np.float32)
    )
    y = (2.0 * jnp.cos(X[2]) + X[1] ** 2).astype(jnp.float32)

    @jax.jit
    def host_twin(t):
        yp, ok = eval_trees_pallas(
            t, X, OPS, t_block=8, r_block=r_block, interpret=True,
            bucket_ladder=bucket_ladder,
        )
        elem = l2_dist_loss(yp, y[None, :])
        return contain_nonfinite(
            aggregate_loss(elem, None, tile_rows=r_block), ok
        )

    fused = eval_loss_trees_pallas(
        trees, X, y, OPS, l2_dist_loss, t_block=8, r_block=r_block,
        interpret=True, bucket_ladder=bucket_ladder,
    )
    assert np.array_equal(
        np.asarray(fused), np.asarray(host_twin(trees)), equal_nan=True
    )
    # and with poison planted: the inf sentinel must land identically
    n = trees.length.shape[0]
    poisoned = trees._replace(cval=jnp.where(
        (jnp.arange(n) % 7 == 0)[:, None], jnp.inf, trees.cval
    ))
    fused_p = eval_loss_trees_pallas(
        poisoned, X, y, OPS, l2_dist_loss, t_block=8, r_block=r_block,
        interpret=True, bucket_ladder=bucket_ladder,
    )
    ref_p = np.asarray(host_twin(poisoned))
    assert np.isinf(ref_p).any()
    assert np.array_equal(np.asarray(fused_p), ref_p, equal_nan=True)


def test_fused_loss_builder_routes_to_kernel(rng, monkeypatch):
    """_make_eval_loss_fn's Pallas branch must take the KERNEL-FUSED
    epilogue for unweighted float32 postfix batches, honoring the
    Options ladder — asserted by substituting an interpret-mode
    recording wrapper for the compiled entry point."""
    import symbolicregression_jl_tpu.ops.pallas_eval as pe
    from symbolicregression_jl_tpu.models.fitness import eval_loss_trees
    from symbolicregression_jl_tpu.ops.losses import l2_dist_loss

    trees = _skewed_batch(rng, n=16)
    X = jnp.asarray(
        (rng.standard_normal((NFEAT, 130)) * 2).astype(np.float32)
    )
    y = (X[0] + 1.0).astype(jnp.float32)
    real_loss = pe.eval_loss_trees_pallas
    real_value = pe.eval_trees_pallas
    calls = []

    def recording(t, Xa, ya, operators, loss_fn, **kw):
        calls.append(kw)
        kw.update(interpret=True, t_block=8, r_block=128)
        return real_loss(t, Xa, ya, operators, loss_fn, **kw)

    def value_interpret(t, Xa, operators, **kw):
        kw.update(interpret=True, t_block=8, r_block=128)
        return real_value(t, Xa, operators, **kw)

    monkeypatch.setattr(pe, "pallas_available", lambda: True)
    monkeypatch.setattr(pe, "eval_loss_trees_pallas", recording)
    # the weighted fall-through exercises dispatch_eval's VALUE kernel,
    # which on CPU must also run under interpret
    monkeypatch.setattr(pe, "eval_trees_pallas", value_interpret)
    ladder = (0.5, 1.0)
    loss = eval_loss_trees(
        trees, X, y, None, OPS, l2_dist_loss, backend="pallas",
        bucket_ladder=ladder,
    )
    assert len(calls) == 1
    assert calls[0].get("bucket_ladder") == ladder
    # weighted batches must fall through to the unfused composition
    w = jnp.ones_like(y)
    eval_loss_trees(
        trees, X, y, w, OPS, l2_dist_loss, backend="pallas",
        bucket_ladder=ladder,
    )
    assert len(calls) == 1
    # correctness of the routed loss vs the jnp interpreter graph
    ref = eval_loss_trees(
        trees, X, y, None, OPS, l2_dist_loss, backend="jnp"
    )
    m = np.isfinite(np.asarray(ref))
    np.testing.assert_allclose(
        np.asarray(loss)[m], np.asarray(ref)[m], rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.isfinite(np.asarray(loss)), m
    )
