"""Length-bucketed + fused/row-tiled evaluation (ISSUE 5).

Exactness contract under test: the bucketed jnp dispatch
(models/fitness.py eval_loss_trees_bucketed) and the untiled fused
reduction (ops/interpreter.py eval_loss_trees_fused) are BIT-IDENTICAL
to the flat interpreter path; the row-tiled mode is close-but-not-exact
by design (tile-wise partial sums). docs/eval_pipeline.md documents the
guarantees per path.

File intentionally sorts LAST in tests/: the tier-1 runner is a
timeout-bounded dot count, so new fast tests must not displace the
early-alphabet files (ROADMAP tier-1 note); search-heavy cases here are
additionally under the `slow` marker.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.fitness import (
    _bucket_bounds,
    _pallas_work_gate,
    eval_loss_trees,
    eval_loss_trees_bucketed,
    score_trees,
    score_trees_cached,
)
from symbolicregression_jl_tpu.models.mutate_device import (
    gen_random_tree_fixed_size,
)
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.ops.interpreter import eval_loss_trees_fused

LADDER = (0.25, 0.5, 1.0)


def _options(**kw):
    kw.setdefault("binary_operators", ["+", "-", "*", "/"])
    kw.setdefault("unary_operators", ["cos", "exp"])
    kw.setdefault("maxsize", 12)
    return make_options(**kw)


def _workload(options, n_trees, n_rows, seed, sizes=None, nfeat=2):
    rng = np.random.default_rng(seed)
    if sizes is None:
        sizes = rng.integers(1, options.maxsize + 1, n_trees)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, nfeat, options.operators, options.max_len
        )
    )(
        jax.random.split(jax.random.PRNGKey(seed), n_trees),
        jnp.asarray(np.asarray(sizes, np.int32)),
    )
    X = jnp.asarray(rng.standard_normal((nfeat, n_rows)), jnp.float32)
    y = 2.0 * jnp.cos(X[-1]) + X[0] ** 2 - 0.5
    return trees, X, y


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bucketed_bit_identical_to_flat(seed):
    options = _options()
    trees, X, y = _workload(options, 256, 64, seed)
    ops, loss_fn = options.operators, options.elementwise_loss
    flat = eval_loss_trees(trees, X, y, None, ops, loss_fn, backend="jnp")
    buck = eval_loss_trees(
        trees, X, y, None, ops, loss_fn, backend="jnp",
        bucket_ladder=LADDER,
    )
    assert np.array_equal(np.asarray(flat), np.asarray(buck))


def test_bucketed_bit_identical_weighted_and_bf16():
    options = _options()
    trees, X, y = _workload(options, 128, 48, 3)
    ops, loss_fn = options.operators, options.elementwise_loss
    w = jnp.asarray(np.random.default_rng(3).random(48), jnp.float32)
    flat = eval_loss_trees(trees, X, y, w, ops, loss_fn, backend="jnp")
    buck = eval_loss_trees(
        trees, X, y, w, ops, loss_fn, backend="jnp", bucket_ladder=LADDER
    )
    assert np.array_equal(np.asarray(flat), np.asarray(buck))
    # bf16 storage: same exactness claim at the TPU-native half precision
    tb = trees._replace(cval=trees.cval.astype(jnp.bfloat16))
    Xb, yb = X.astype(jnp.bfloat16), y.astype(jnp.bfloat16)
    flat_b = eval_loss_trees(tb, Xb, yb, None, ops, loss_fn, backend="jnp")
    buck_b = eval_loss_trees(
        tb, Xb, yb, None, ops, loss_fn, backend="jnp", bucket_ladder=LADDER
    )
    assert np.array_equal(
        np.asarray(flat_b, np.float32), np.asarray(buck_b, np.float32)
    )


def test_bucket_boundary_lengths_exact():
    """Trees whose lengths tie exactly across a positional bucket edge:
    the sort may place equal-length trees on both sides of a boundary,
    and per-tree results must not depend on which side they land."""
    options = _options(maxsize=12)
    n = 64
    bounds = _bucket_bounds(n, LADDER)
    sizes = np.ones(n, np.int32) * 3
    # a run of identical mid-size trees straddling the first boundary,
    # and max-length trees at the very end
    lo = max(bounds[1] - 4, 0)
    sizes[lo:bounds[1] + 4] = 7
    sizes[-4:] = options.maxsize
    trees, X, y = _workload(options, n, 32, 5, sizes=sizes)
    ops, loss_fn = options.operators, options.elementwise_loss
    flat = eval_loss_trees(trees, X, y, None, ops, loss_fn, backend="jnp")
    buck = eval_loss_trees(
        trees, X, y, None, ops, loss_fn, backend="jnp",
        bucket_ladder=LADDER,
    )
    assert np.array_equal(np.asarray(flat), np.asarray(buck))
    # degenerate ladders: a single full-batch rung (adaptive max-length
    # truncation) and a ladder finer than the batch (empty buckets)
    for ladder in [(1.0,), tuple((i + 1) / 16 for i in range(16))]:
        buck2 = eval_loss_trees(
            trees, X, y, None, ops, loss_fn, backend="jnp",
            bucket_ladder=ladder,
        )
        assert np.array_equal(np.asarray(flat), np.asarray(buck2))


def test_bucket_bounds_static():
    assert _bucket_bounds(100, (0.25, 0.5, 1.0)) == (0, 25, 50, 100)
    assert _bucket_bounds(2, (0.25, 0.5, 1.0)) == (0, 0, 1, 2)
    assert _bucket_bounds(0, (1.0,)) == (0, 0)


def test_fused_matches_flat_composition():
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.losses import aggregate_loss

    options = _options()
    trees, X, y = _workload(options, 96, 40, 7)
    ops, loss_fn = options.operators, options.elementwise_loss
    for w in (None, jnp.asarray(
            np.random.default_rng(7).random(40), jnp.float32)):
        y_pred, ok = eval_trees(trees, X, ops)
        loss = aggregate_loss(loss_fn(y_pred, y), w)
        flat = jnp.where(ok & jnp.isfinite(loss), loss, jnp.inf)
        fused = eval_loss_trees_fused(trees, X, y, w, ops, loss_fn)
        assert np.array_equal(np.asarray(flat), np.asarray(fused))


def test_row_tiled_close_and_same_inf_pattern():
    options = _options()
    trees, X, y = _workload(options, 96, 50, 9)
    ops, loss_fn = options.operators, options.elementwise_loss
    flat = np.asarray(
        eval_loss_trees(trees, X, y, None, ops, loss_fn, backend="jnp")
    )
    for w in (None, jnp.asarray(
            np.random.default_rng(9).random(50) + 0.1, jnp.float32)):
        ref = np.asarray(
            eval_loss_trees(trees, X, y, w, ops, loss_fn, backend="jnp")
        )
        # 13 does not divide 50: exercises the masked pad tile
        tiled = np.asarray(
            eval_loss_trees_fused(
                trees, X, y, w, ops, loss_fn, rows_per_tile=13
            )
        )
        assert np.array_equal(np.isfinite(ref), np.isfinite(tiled))
        fin = np.isfinite(ref)
        np.testing.assert_allclose(ref[fin], tiled[fin], rtol=1e-5)
    # a whole-batch tile is the exact path: bit-identical
    whole = np.asarray(
        eval_loss_trees_fused(
            trees, X, y, None, ops, loss_fn, rows_per_tile=50
        )
    )
    assert np.array_equal(flat, whole)


def test_bucketed_composes_with_row_tiling():
    options = _options()
    trees, X, y = _workload(options, 64, 30, 11)
    ops, loss_fn = options.operators, options.elementwise_loss
    ref = np.asarray(
        eval_loss_trees(trees, X, y, None, ops, loss_fn, backend="jnp")
    )
    both = np.asarray(
        eval_loss_trees(
            trees, X, y, None, ops, loss_fn, backend="jnp",
            bucket_ladder=LADDER, rows_per_tile=8,
        )
    )
    fin = np.isfinite(ref)
    assert np.array_equal(fin, np.isfinite(both))
    np.testing.assert_allclose(ref[fin], both[fin], rtol=1e-5)


def test_bucketed_under_island_vmap():
    """The per-island vmapped scoring path (independent island batches)
    batches the bucketed graph's while_loops; results must still match
    the flat path lane for lane."""
    options = _options()
    I, B = 3, 32
    trees, X, y = _workload(options, I * B, 24, 13)
    itrees = jax.tree_util.tree_map(
        lambda a: a.reshape((I, B) + a.shape[1:]), trees
    )
    ops, loss_fn = options.operators, options.elementwise_loss
    flat = jax.vmap(
        lambda t: eval_loss_trees(t, X, y, None, ops, loss_fn,
                                  backend="jnp")
    )(itrees)
    buck = jax.vmap(
        lambda t: eval_loss_trees(t, X, y, None, ops, loss_fn,
                                  backend="jnp", bucket_ladder=LADDER)
    )(itrees)
    assert np.array_equal(np.asarray(flat), np.asarray(buck))


def test_cached_scoring_bit_identical_with_ladder():
    """Dedup + ladder share one length-major sort (cache/dedup.py): the
    cached scorer's losses must equal the uncached flat scorer's even
    with duplicates and memo-style fillers in the eval buffer."""
    options_flat = _options()
    options_b = _options(eval_bucket_ladder=LADDER)
    trees, X, y = _workload(options_flat, 128, 32, 17)
    dup = jax.tree_util.tree_map(lambda a: a.at[40:80].set(a[0:40]), trees)
    bl = jnp.float32(float(jnp.var(y)))
    s_f, l_f = score_trees(dup, X, y, None, bl, options_flat)
    s_c, l_c, stats = score_trees_cached(dup, X, y, None, bl, options_b)
    assert np.array_equal(np.asarray(l_f), np.asarray(l_c))
    assert np.array_equal(np.asarray(s_f), np.asarray(s_c))
    assert int(stats.unique) < int(stats.total)


def test_pallas_work_gate_volume():
    # calibration point: 512 trees at one full (8, 128) row tile
    assert _pallas_work_gate(512, 1024)
    assert _pallas_work_gate(64, 100_000)
    # large-batch/tiny-rows: kernel would mostly pad the row tile
    assert not _pallas_work_gate(8192, 8)
    assert not _pallas_work_gate(511, 1024)


def test_option_validation():
    with pytest.raises(ValueError, match="ascending"):
        _options(eval_bucket_ladder=(0.5, 0.25, 1.0))
    with pytest.raises(ValueError, match="ascending"):
        _options(eval_bucket_ladder=(0.5, 1.5))
    with pytest.raises(ValueError, match="end at 1.0"):
        _options(eval_bucket_ladder=(0.25, 0.5))
    with pytest.raises(ValueError, match="eval_rows_per_tile"):
        _options(eval_rows_per_tile=-1)
    # list form normalizes to a (hashable) tuple
    o = _options(eval_bucket_ladder=[0.5, 1.0])
    assert o.eval_bucket_ladder == (0.5, 1.0)
    hash(o)


def test_graph_key_includes_eval_knobs():
    a = _options()
    b = _options(eval_bucket_ladder=LADDER)
    c = _options(eval_rows_per_tile=64)
    assert a != b and a != c and hash(a) != hash(b)


def test_presorted_matches_sorted_path():
    """presorted=True must be a pure performance hint: identical values
    on any ordering (per-tree results are bucket-assignment-invariant)."""
    options = _options()
    trees, X, y = _workload(options, 96, 24, 19)
    ops, loss_fn = options.operators, options.elementwise_loss
    a = eval_loss_trees_bucketed(
        trees, X, y, None, ops, loss_fn, LADDER, presorted=False
    )
    b = eval_loss_trees_bucketed(
        trees, X, y, None, ops, loss_fn, LADDER, presorted=True
    )
    assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_search_hof_identical_bucketed_vs_flat():
    """Full equation_search trajectories: the bucketed ladder must leave
    the hall of fame bit-identical under the fused driver, the chunked
    driver, and the cached scorer (their bit-identity guarantees
    compose)."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 96)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=3, npop=24, ncycles_per_iteration=16, maxsize=10,
        seed=11, verbosity=0, progress=False, niterations=2,
    )
    front = lambda r: [
        (c.complexity, float(c.loss), float(c.score), c.equation)
        for c in r.frontier()
    ]
    ref = front(sr.equation_search(X, y, **kw))
    assert ref
    for extra in (
        dict(eval_bucket_ladder=(0.5, 1.0)),
        dict(eval_bucket_ladder=(0.5, 1.0), max_cycles_per_dispatch=5),
        dict(eval_bucket_ladder=(0.5, 1.0), cache_fitness=True),
    ):
        sr.clear_memo_banks()
        assert front(sr.equation_search(X, y, **kw, **extra)) == ref
