"""Test harness: force CPU with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY.md §4 implication (e): the analog of
the reference's in-process addprocs(2) trick, test/runtests.jl).

Note: this image's sitecustomize registers the experimental 'axon' TPU
tunnel backend and forces jax_platforms='axon,cpu'; initializing it from
tests would hang on the single tunnel slot, so we override to pure CPU
*before* any backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SYMBOLIC_REGRESSION_TEST", "true")

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the evolution step is a large scatter/gather
# graph whose XLA optimization dominates test wall-time; repeat runs hit the
# cache and skip it.
_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache",
)
os.makedirs(_CACHE_DIR, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
