"""Test harness: force CPU with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY.md §4 implication (e): the analog of
the reference's in-process addprocs(2) trick, test/runtests.jl).

Note: this image's sitecustomize registers the experimental 'axon' TPU
tunnel backend and forces jax_platforms='axon,cpu'; initializing it from
tests would hang on the single tunnel slot, so we override to pure CPU
*before* any backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SYMBOLIC_REGRESSION_TEST", "true")

import jax

# SRTPU_TPU_TESTS=1 leaves the platform alone so tests/test_tpu_hardware.py
# can run against the real chip; everything else always runs on CPU.
if os.environ.get("SRTPU_TPU_TESTS", "") != "1":
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: OFF by default since 2026-08-01. It was
# default-on 2026-07-30..31 (two full passes wrote ~100 CPU executables
# cleanly), but the round-3 search graphs deterministically crash this
# image's executable serializer (`put_executable_and_time` abort at the
# same test, 3/3 runs, fresh cache dir included) — the same jaxlib bug
# utils/precompile.py probe-guards on the production side. A reliable
# ~38-min suite beats a crashing ~15-min one. Opt back in with
# SRTPU_TEST_CACHE=<dir> (or "1" for the default location) if a future
# jaxlib fixes the serializer.
_cache_dir = os.environ.get("SRTPU_TEST_CACHE", "0")
if _cache_dir not in ("", "0"):
    if _cache_dir == "1":
        _cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "srtpu_test_xla"
        )
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# This image's jaxlib segfaults nondeterministically inside XLA:CPU
# compilation (and executable serialization) once a single process has
# accumulated many large compiled programs — observed as crashes in
# backend_compile_and_load / put_executable_and_time around the ~85th test
# of a cold full-suite run. Dropping every compiled executable between test
# modules keeps the native state small; recompiles across modules are cheap
# because tests within a module share Options (and therefore programs).
# Module-scoped so the guard is evaluated once per module per worker —
# correct under pytest-xdist (each worker has its own process and its own
# _last_module cell) and under randomized intra-module test order.
_last_module = [None]


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules(request):
    mod = request.module.__name__
    if _last_module[0] is not None and _last_module[0] != mod:
        jax.clear_caches()
    _last_module[0] = mod
    yield
