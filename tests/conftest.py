"""Test harness: force CPU with 8 virtual devices so multi-chip sharding
tests run without TPU hardware (SURVEY.md §4 implication (e): the analog of
the reference's in-process addprocs(2) trick, test/runtests.jl).

Note: this image's sitecustomize registers the experimental 'axon' TPU
tunnel backend and forces jax_platforms='axon,cpu'; initializing it from
tests would hang on the single tunnel slot, so we override to pure CPU
*before* any backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SYMBOLIC_REGRESSION_TEST", "true")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
