"""Search-dynamics observability (ISSUE 10): the run doctor
(telemetry/analyze.py), the exact hypervolume, srtop, the bench
trajectory aggregator, schema evolution, and the watcher's telemetry
classification.

File name sorts EARLY (test_ac_*) and everything here is fast CPU-only
host-side work — synthetic event lists and the checked-in artifacts, no
searches, no compiles (the full closed loop — real search -> event log
-> healthy verdict — lives in benchmark/suite.py's `run_doctor` case
and test_ab_telemetry's slow round trip)."""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu.telemetry.analyze import (
    VERDICTS,
    analyze_run,
    compare_runs,
    load_events,
    resolve_log,
    self_check,
)
from symbolicregression_jl_tpu.telemetry.analyze import main as analyze_main
from symbolicregression_jl_tpu.telemetry.metrics import hypervolume_2d

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(
    REPO, "tests", "data", "telemetry", "golden_events.jsonl"
)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# synthetic event-log builder
# ---------------------------------------------------------------------------


def make_run(
    best,
    diversity=None,
    finite_frac=None,
    fault=False,
    saved=False,
    complete=True,
    spans=("init", "cycle", "mutate", "eval", "simplify", "optimize",
           "merge_migrate"),
):
    """A synthetic event list shaped like a real run: run_start, one
    span per stage, one metrics event per entry of `best`, optional
    fault/saved_state, optional run_end."""
    t = [0.0]

    def ev(type, **f):
        t[0] += 1.0
        return {"v": 1, "t": t[0], "run": "r", "type": type, **f}

    events = [ev("run_start", config_fingerprint="x", backend="cpu",
                 devices=["TFRT_CPU_0"], nout=1)]
    for s in spans:
        events.append(ev("span", name=s, t_start=t[0], duration_s=0.5))
    for i, b in enumerate(best):
        gauges = {"best_loss": b}
        if diversity is not None:
            gauges["population_diversity"] = diversity[i]
        if finite_frac is not None:
            gauges["population_finite_frac"] = finite_frac[i]
        events.append(ev(
            "metrics", output=0, iteration=i,
            snapshot={"counters": {}, "gauges": gauges, "histograms": {}},
        ))
    if saved:
        events.append(ev("saved_state", outputs=1, path="/tmp/x.ckpt",
                         iteration=len(best)))
    if fault:
        events.append(ev(
            "dispatch_fault", where="iteration",
            error_type="XlaRuntimeError", error="UNAVAILABLE",
            iteration=len(best), fatal=True,
        ))
    if complete:
        events.append(ev("run_end", num_evals=100.0, search_time_s=9.0))
    return events


# ---------------------------------------------------------------------------
# exact hypervolume
# ---------------------------------------------------------------------------


def test_hypervolume_2d_staircase_exact():
    # two frontier points, reference (10, 2.0), floor 1: widths 3 and 5
    # at heights 1.0 and 1.8 -> (3*1.0 + 5*1.8) / (9 * 2.0)
    hv = hypervolume_2d([2, 5], [1.0, 0.2], ref_complexity=10,
                        ref_loss=2.0)
    assert math.isclose(hv, (3 * 1.0 + 5 * 1.8) / (9 * 2.0))


def test_hypervolume_2d_dominated_points_drop_out():
    # the complexity-4 point is dominated (higher loss than the
    # running minimum): adding it must not change the volume
    base = hypervolume_2d([2, 5], [1.0, 0.2], 10, 2.0)
    with_dominated = hypervolume_2d([2, 4, 5], [1.0, 1.5, 0.2], 10, 2.0)
    assert math.isclose(base, with_dominated)


def test_hypervolume_2d_matches_slot_scan_on_hof_data():
    # on integer slot data the exact staircase equals the old per-slot
    # scan (mean of clipped normalized improvements)
    rng = np.random.default_rng(0)
    S, baseline = 12, 2.0
    losses = rng.uniform(0.05, 3.0, S)
    exists = rng.random(S) < 0.7
    c = (np.where(exists)[0] + 1).tolist()
    l = losses[exists].tolist()
    best = np.where(exists, losses, np.inf)
    runmin = np.minimum.accumulate(best)
    slot_scan = float(np.mean(np.where(
        np.isfinite(runmin), np.clip(1 - runmin / baseline, 0, 1), 0.0
    )))
    assert math.isclose(
        hypervolume_2d(c, l, S + 1, baseline), slot_scan, rel_tol=1e-12
    )


def test_hypervolume_2d_edge_cases():
    assert hypervolume_2d([1], [0.5], 2, float("nan")) == 0.0
    assert hypervolume_2d([5], [0.5], 5, 1.0) == 0.0  # at reference
    assert hypervolume_2d([1], [float("inf")], 5, 1.0) == 0.0
    # negative losses clip at 0: cannot dominate beyond the box
    assert hypervolume_2d([1], [-5.0], 2, 1.0) == 1.0


def test_mutation_counts_table():
    from symbolicregression_jl_tpu.models.evolve import (
        MUTATION_NAMES,
        mutation_counts_table,
    )

    K = len(MUTATION_NAMES)
    counts = np.zeros((3, K, 2), np.int32)  # (islands, kinds, 2)
    counts[:, 0, 0] = 4  # mutate_constant proposed 12, accepted 6
    counts[:, 0, 1] = 2
    table = mutation_counts_table(counts)
    assert set(table) == set(MUTATION_NAMES)
    assert table["mutate_constant"] == {
        "proposed": 12, "accepted": 6, "accept_rate": 0.5,
    }
    assert table["crossover"]["accept_rate"] is None  # never proposed


# ---------------------------------------------------------------------------
# run doctor verdicts
# ---------------------------------------------------------------------------


def test_analyze_healthy_improving_run():
    ev = make_run(best=[2.0, 1.5, 1.0, 0.6, 0.4, 0.2],
                  diversity=[0.9] * 6)
    r = analyze_run(ev)
    assert r["verdict"] == "healthy"
    assert r["complete"] and r["spans_complete"]
    assert r["best_loss"]["improvement"] == pytest.approx(0.9)


def test_analyze_stalled_plateau_with_diversity_collapse():
    # flat best loss over the window AND diversity at the floor
    ev = make_run(best=[1.0] * 8, diversity=[0.9, 0.8, 0.5, 0.3, 0.15,
                                             0.12, 0.1, 0.1])
    r = analyze_run(ev)
    assert r["verdict"] == "stalled"
    assert any("plateau" in x for x in r["reasons"])


def test_analyze_plateau_with_healthy_diversity_stays_healthy():
    ev = make_run(best=[1.0] * 8, diversity=[0.9] * 8)
    r = analyze_run(ev)
    assert r["verdict"] == "healthy"
    assert any("plateau" in x for x in r["reasons"])


def test_analyze_converged_zero_loss_is_healthy_not_stalled():
    # a run that found the exact equation: loss pinned at 0 with the
    # population converged onto the solution — success, not a stall
    ev = make_run(best=[1.0, 0.1, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
                  diversity=[0.9, 0.5, 0.1, 0.05, 0.05, 0.05, 0.05,
                             0.05])
    r = analyze_run(ev)
    assert r["verdict"] == "healthy"
    assert any("converged" in x for x in r["reasons"])


def test_analyze_short_run_never_stalls():
    # 2 snapshots cannot span the stall window: a flat tiny run is
    # healthy (the suite's 2-iteration case must not read as stalled)
    ev = make_run(best=[1.0, 1.0], diversity=[0.1, 0.1])
    assert analyze_run(ev)["verdict"] == "healthy"


def test_analyze_diverging_on_nan_flood_and_finite_collapse():
    ev = make_run(best=[1.0, None, None], diversity=[0.9] * 3)
    assert analyze_run(ev)["verdict"] == "diverging"
    ev2 = make_run(best=[1.0, 0.9, 0.8],
                   finite_frac=[1.0, 0.5, 0.05])
    r2 = analyze_run(ev2)
    assert r2["verdict"] == "diverging"
    assert any("finite" in x for x in r2["reasons"])


def test_analyze_multi_output_series_not_interleaved():
    # nout=2, one metrics event per output per iteration: output 0
    # improves to ~0 while output 1 sits flat at 2.0 with healthy
    # diversity — the zigzag [2.0, 1e-6, 2.0, ...] must NOT read as a
    # plateau or divergence; per-output judgment keeps it healthy
    t = [0.0]

    def ev(type, **f):
        t[0] += 1.0
        return {"v": 1, "t": t[0], "run": "r", "type": type, **f}

    events = [ev("run_start", config_fingerprint="x", backend="cpu",
                 devices=["d"], nout=2)]
    for s in ("init", "cycle", "mutate", "eval", "simplify", "optimize",
              "merge_migrate"):
        events.append(ev("span", name=s, t_start=t[0], duration_s=0.1))
    b0 = [2.0, 1.0, 0.1, 1e-4, 1e-5, 1e-6, 1e-6, 1e-6]
    for i in range(len(b0)):
        for j, b in ((0, b0[i]), (1, 2.0)):
            events.append(ev(
                "metrics", output=j, iteration=i,
                snapshot={"counters": {}, "histograms": {}, "gauges": {
                    "best_loss": b, "population_diversity": 0.8,
                }},
            ))
    events.append(ev("run_end", num_evals=1.0, search_time_s=1.0))
    r = analyze_run(events)
    assert r["verdict"] == "healthy", r["reasons"]
    assert set(r["per_output"]) == {0, 1}
    assert r["per_output"][0]["best_loss"] == pytest.approx(1e-6)
    assert r["per_output"][1]["best_loss"] == 2.0
    # one output NaN-flooding tips the whole run to diverging
    events2 = [e for e in events if e["type"] != "run_end"]
    events2.append(ev(
        "metrics", output=1, iteration=len(b0),
        snapshot={"counters": {}, "histograms": {},
                  "gauges": {"best_loss": None}},
    ))
    assert analyze_run(events2)["verdict"] == "diverging"


def test_analyze_faulted_resumable_vs_dead():
    r = analyze_run(make_run(best=[1.0], fault=True, saved=True,
                             complete=False))
    assert r["verdict"] == "faulted" and r["resumable"]
    r2 = analyze_run(make_run(best=[1.0], fault=True, complete=False))
    assert r2["verdict"] == "faulted" and not r2["resumable"]
    assert all(v in VERDICTS for v in (r["verdict"], r2["verdict"]))


def test_analyze_incomplete_and_empty():
    r = analyze_run(make_run(best=[2.0, 1.0], complete=False))
    assert r["verdict"] == "incomplete"
    assert analyze_run([])["verdict"] == "empty"


def test_analyze_tolerates_truncated_file(tmp_path):
    p = tmp_path / "events.jsonl"
    lines = [json.dumps(e) for e in make_run(best=[2.0, 1.0])]
    # a mid-write kill: the last line is cut mid-object
    p.write_text("\n".join(lines[:-1]) + "\n" + lines[-1][: len(lines[-1]) // 2])
    events, skipped = load_events(str(p))
    assert skipped == 1 and len(events) == len(lines) - 1
    r = analyze_run(str(p))
    assert r["skipped_lines"] == 1
    assert r["verdict"] == "incomplete"  # run_end was the cut line


def test_analyze_golden_fixture_healthy():
    r = analyze_run(GOLDEN)
    assert r["verdict"] == "healthy", r["reasons"]
    assert r["spans_complete"]
    assert 0.0 < r["diversity"]["last"] <= 1.0
    assert 0.0 <= r["hypervolume"]["last"] <= 1.0
    assert r["mutations"]  # per-mutation acceptance table present
    assert r["pareto"]["complexity"]
    out = self_check(GOLDEN)
    assert out["ok"] and out["verdict"] == "healthy"


def test_compare_runs_ratios():
    a = make_run(best=[2.0, 1.0], diversity=[0.9, 0.8])
    b = make_run(best=[2.0, 0.5], diversity=[0.9, 0.6])
    cmp = compare_runs(a, b)
    assert cmp["verdicts"] == {"a": "healthy", "b": "healthy"}
    row = cmp["metrics"]["best_loss"]
    assert row["a"] == 1.0 and row["b"] == 0.5 and row["ratio"] == 0.5
    assert "cycle" in cmp["stages"]


def test_analyze_cli_exit_codes(tmp_path, capsys):
    # healthy golden -> 0; crafted plateau fixture -> 1, STALLED printed
    assert analyze_main([GOLDEN]) == 0
    capsys.readouterr()
    p = tmp_path / "stalled.jsonl"
    p.write_text("\n".join(
        json.dumps(e) for e in make_run(
            best=[1.0] * 8, diversity=[0.1] * 8
        )
    ) + "\n")
    assert analyze_main([str(p)]) == 1
    assert "STALLED" in capsys.readouterr().out
    # self-check mode + directory resolution (events-* naming)
    assert analyze_main([GOLDEN, "--self-check"]) == 0
    d = tmp_path / "runs"
    d.mkdir()
    (d / "events-x.jsonl").write_text(open(GOLDEN).read())
    assert resolve_log(str(d)).endswith("events-x.jsonl")
    empty = tmp_path / "nothing_here"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        resolve_log(str(empty))
    # comparison mode exits 0 and prints both verdicts
    assert analyze_main([GOLDEN, str(p)]) == 0
    out = capsys.readouterr().out
    assert "healthy" in out and "stalled" in out


# ---------------------------------------------------------------------------
# schema evolution (v1 is additive-open, required fields are load-bearing)
# ---------------------------------------------------------------------------


def test_schema_accepts_additive_fields():
    from symbolicregression_jl_tpu.telemetry import validate_event

    base = {
        "v": 1, "t": 0.0, "run": "r", "type": "metrics",
        "snapshot": {"counters": {}, "gauges": {}, "histograms": {}},
    }
    assert validate_event(base) == []
    # additive fields — the dynamics extensions and any future ones —
    # must validate on v1 without a schema bump
    extended = dict(
        base,
        pareto={"complexity": [1, 3], "loss": [2.0, 1.0]},
        mutations={"add_node": {"proposed": 3, "accepted": 1,
                                "accept_rate": 1 / 3}},
        per_island={"diversity": [0.5]},
        some_future_field={"anything": True},
    )
    assert validate_event(extended) == []


def test_schema_rejects_removed_and_retyped_required_fields():
    from symbolicregression_jl_tpu.telemetry import validate_event

    base = {
        "v": 1, "t": 0.0, "run": "r", "type": "metrics",
        "snapshot": {"counters": {}, "gauges": {}, "histograms": {}},
    }
    removed = {k: v for k, v in base.items() if k != "snapshot"}
    assert any("snapshot" in p for p in validate_event(removed))
    retyped = dict(base, snapshot="not-an-object")
    assert any("snapshot" in p for p in validate_event(retyped))
    # envelope: retyped run id / wrong version are rejected too
    assert validate_event(dict(base, run=7))
    assert validate_event(dict(base, v="1"))


def test_schema_file_carries_dynamics_and_roofline():
    from symbolicregression_jl_tpu.telemetry.events import load_schema

    schema = load_schema()
    assert "roofline" in schema["properties"]["type"]["enum"]
    assert "roofline" in schema["definitions"]
    metrics_props = schema["definitions"]["metrics"]["properties"]
    assert "pareto" in metrics_props and "mutations" in metrics_props
    # a roofline event (bench.py) validates: fraction OR skip_reason
    from symbolicregression_jl_tpu.telemetry import validate_event

    assert validate_event({
        "v": 1, "t": 0.0, "run": "r", "type": "roofline",
        "fraction": None, "skip_reason": "cpu-only",
        "trees_rows_per_s": 1e6,
    }) == []


def test_schema_run_start_fleet_provenance():
    """ISSUE 13: run_start's additive `run_id` (stable uuid, the fleet
    join key) and `attempt` (1-based supervisor attempt index) validate
    on v1 without a bump; retyping them fails; old logs without them
    (the pre-fleet golden fixtures) still validate."""
    from symbolicregression_jl_tpu.telemetry import validate_event
    from symbolicregression_jl_tpu.telemetry.events import load_schema

    schema = load_schema()
    props = schema["definitions"]["run_start"]["properties"]
    assert "run_id" in props and "attempt" in props
    base = {
        "v": 1, "t": 0.0, "run": "r", "type": "run_start",
        "config_fingerprint": "x", "backend": "cpu", "devices": ["d"],
    }
    assert validate_event(base) == []  # additive: absent is fine
    assert validate_event(
        dict(base, run_id="abc123", attempt=2)
    ) == []
    assert validate_event(dict(base, run_id=7))
    assert validate_event(dict(base, attempt="two"))


def test_schema_alert_events():
    """ISSUE 13: the fleet alert engine's `alert` events are schema-v1
    (rule/severity/message required, severity from the fixed set)."""
    from symbolicregression_jl_tpu.telemetry import validate_event
    from symbolicregression_jl_tpu.telemetry.events import load_schema

    assert "alert" in load_schema()["properties"]["type"]["enum"]
    base = {
        "v": 1, "t": 0.0, "run": "run-one", "type": "alert",
        "rule": "stalled_run", "severity": "warning",
        "message": "plateau", "value": 1.0, "threshold": None,
        "fleet": "/tmp/fleet",
    }
    assert validate_event(base) == []
    assert validate_event(
        {k: v for k, v in base.items() if k != "rule"}
    )
    assert validate_event(dict(base, severity="page-me"))


def test_analyze_run_surfaces_fleet_provenance():
    """The doctor's report["run"] carries run_id/attempt so the fleet
    scanner (and any consumer) joins on the doctor's view, not on a
    second parse of the raw log."""
    events = make_run([1.0, 0.5])
    events[0]["run_id"] = "stable-id"
    events[0]["attempt"] = 3
    report = analyze_run(events)
    assert report["run"]["run_id"] == "stable-id"
    assert report["run"]["attempt"] == 3


def test_golden_fixture_carries_fleet_provenance():
    """The regenerated golden fixture is from a post-fleet run: its
    run_start must stamp run_id + attempt (the lint gate validates the
    schema; this pins the writer actually emitting the fields)."""
    with open(GOLDEN) as f:
        start = json.loads(f.readline())
    assert start["type"] == "run_start"
    assert isinstance(start.get("run_id"), str) and start["run_id"]
    assert start.get("attempt") == 1


def test_event_log_nested_nonfinite_coercion(tmp_path):
    """ISSUE 10 satellite: non-finite -> null applies inside nested
    metric dicts (and lists/sets) at every depth, not only to top-level
    values — otherwise json.dumps(allow_nan=False) would disable the
    log on the first Inf gauge."""
    from symbolicregression_jl_tpu.telemetry import EventLog

    path = str(tmp_path / "e.jsonl")
    log = EventLog(path, run_id="r")
    ev = log.emit(
        "metrics",
        snapshot={
            "counters": {},
            "gauges": {"best_loss": float("inf"),
                       "nested": {"deep": float("nan")}},
            "histograms": {"h": {"edges": [1.0],
                                 "counts": [float("-inf"), 2]}},
        },
        per_island={"best_loss": [1.0, float("nan")]},
        odd={"set": {1.5, float("inf")}, "complex": complex(1, 2)},
    )
    assert ev is not None  # the log survived
    line = json.loads(open(path).read().splitlines()[0])
    g = line["snapshot"]["gauges"]
    assert g["best_loss"] is None
    assert g["nested"]["deep"] is None
    assert line["snapshot"]["histograms"]["h"]["counts"] == [None, 2]
    assert line["per_island"]["best_loss"] == [1.0, None]
    assert None in line["odd"]["set"] and 1.5 in line["odd"]["set"]
    assert isinstance(line["odd"]["complex"], str)
    log.close()


# ---------------------------------------------------------------------------
# srtop
# ---------------------------------------------------------------------------


def test_srtop_renders_complete_and_truncated_logs(tmp_path, capsys):
    srtop = _load_script("srtop")
    assert srtop.main([GOLDEN, "--once"]) == 0
    out = capsys.readouterr().out
    assert "srtop" in out and "stages:" in out and "diversity" in out
    # truncated mid-write copy: renders without crashing, last event is
    # simply held back — and --once now gates on the doctor verdict
    # (ISSUE 12), so the run_end-less copy reads incomplete -> exit 1
    data = open(GOLDEN).read()
    p = tmp_path / "trunc.jsonl"
    p.write_text(data[: len(data) - 37])
    assert srtop.main([str(p), "--once"]) == 1
    out = capsys.readouterr().out
    assert "srtop" in out and "doctor verdict: incomplete" in out
    # directory form resolves the newest events-*.jsonl
    d = tmp_path / "runs"
    d.mkdir()
    (d / "events-a.jsonl").write_text(data)
    assert srtop.main([str(d), "--once"]) == 0
    capsys.readouterr()
    # empty dir: waiting frame, no crash
    e = tmp_path / "empty"
    e.mkdir()
    assert srtop.main([str(e), "--once"]) == 0
    assert "waiting" in capsys.readouterr().out
    # nonexistent FILE path: waiting frame too, not an empty 'run ?'
    # dashboard that never fills
    assert srtop.main([str(tmp_path / "no-such.jsonl"), "--once"]) == 0
    assert "waiting" in capsys.readouterr().out


def test_srtop_logtail_incremental_and_partial_lines(tmp_path):
    srtop = _load_script("srtop")
    p = tmp_path / "events.jsonl"
    p.write_text('{"type": "progress", "t": 1.0}\n{"type": "prog')
    tail = srtop.LogTail(str(p))
    events = tail.poll()
    assert len(events) == 1  # the partial line is buffered, not parsed
    with open(p, "a") as f:
        f.write('ress", "t": 2.0}\n')
    events = tail.poll()
    assert len(events) == 1 and events[0]["t"] == 2.0
    assert tail.poll() == []  # nothing new
    # sparkline handles decades + non-finite entries
    s = srtop.sparkline([1000.0, 10.0, None, float("nan"), 0.1])
    assert len(s) == 3


# ---------------------------------------------------------------------------
# bench trajectory
# ---------------------------------------------------------------------------


def test_bench_trajectory_from_checked_in_rounds():
    bt = _load_script("bench_trajectory")
    traj = bt.build_trajectory(REPO)
    rounds = [p.get("round") for p in traj["rounds"]]
    assert rounds == sorted(rounds) and len(rounds) >= 5
    # acceptance: throughput, roofline_fraction and multichip
    # scaling_efficiency series exist over the checked-in artifacts
    for key in ("throughput", "roofline_fraction",
                "multichip_scaling_efficiency"):
        assert key in traj["series"]
        assert len(traj["series"][key]) >= 5
    assert any(
        p["value"] is not None for p in traj["series"]["throughput"]
    )
    assert any(
        p["value"] is not None
        for p in traj["series"]["multichip_scaling_efficiency"]
    )
    md = bt.render_markdown(traj)
    assert "| round |" in md and "Per-metric summary" in md
    summary = bt.bench_summary(traj)
    assert set(summary) >= {"rounds", "throughput", "roofline_fraction",
                            "multichip_scaling_efficiency",
                            "regressions"}
    # the checked-in TRAJECTORY.json is current-format (regenerated by
    # this PR's scripts/bench_trajectory.py run)
    with open(os.path.join(REPO, "TRAJECTORY.json")) as f:
        checked_in = json.load(f)
    assert checked_in["generated_by"] == "scripts/bench_trajectory.py"
    assert [p.get("round") for p in checked_in["rounds"]] == rounds


def test_bench_trajectory_regression_detection():
    bt = _load_script("bench_trajectory")
    points = [
        {"round": 1, "platform": "cpu", "throughput": 100.0},
        {"round": 2, "platform": "cpu", "throughput": 120.0},
        {"round": 3, "platform": "tpu", "throughput": 50.0},  # new plat
        {"round": 4, "platform": "cpu", "throughput": 90.0},  # -25%
        {"round": 5, "platform": "cpu", "throughput": None},  # null ok
    ]
    regs = bt.detect_regressions(points, metrics=("throughput",),
                                 threshold=0.10)
    assert len(regs) == 1
    r = regs[0]
    assert r["round"] == 4 and r["platform"] == "cpu"
    assert r["best_prev"] == 120.0
    assert math.isclose(r["drop_frac"], 0.25)


def test_bench_trajectory_latest_round_regression_renders():
    # a regression on the MULTICHIP_LATEST point carries round='latest'
    # — every formatter must survive the non-integer round tag
    bt = _load_script("bench_trajectory")
    points = [
        {"round": 3, "platform": "cpu",
         "multichip_scaling_efficiency": 0.5},
        {"round": "latest", "platform": "cpu",
         "multichip_scaling_efficiency": 0.2},
    ]
    regs = bt.detect_regressions(
        points, metrics=("multichip_scaling_efficiency",), threshold=0.1
    )
    assert len(regs) == 1 and regs[0]["round"] == "latest"
    assert bt.round_label("latest") == "latest"
    assert bt.round_label(4) == "r04"
    traj = {
        "threshold": 0.1, "rounds": [], "multichip": [],
        "series": {m: [] for m in bt.METRICS},
        "summary": {}, "regressions": regs,
    }
    md = bt.render_markdown(traj)  # must not raise on round='latest'
    assert "latest" in md


def test_bench_trajectory_r04_tail_recovery():
    bt = _load_script("bench_trajectory")
    # the real r04 file: parsed is empty, but the last_tpu embed's
    # trailing on-chip headline pair is recoverable
    point = bt.load_bench_round(os.path.join(REPO, "BENCH_r04.json"))
    assert point["platform"] == "tpu"
    assert point["throughput"] and point["throughput"] > 1e8


# ---------------------------------------------------------------------------
# watcher telemetry classification (ROADMAP #4 groundwork)
# ---------------------------------------------------------------------------


def _write_log(d, name, events):
    with open(os.path.join(d, name), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_watcher_reads_telemetry_instead_of_stdout(tmp_path):
    watcher = _load_script("tpu_watcher")
    d = str(tmp_path)
    # no dir / empty dir -> None (stdout-scrape fallback)
    assert watcher.read_telemetry_verdict(None) is None
    assert watcher.read_telemetry_verdict(d) is None

    _write_log(d, "events-a.jsonl", [
        {"type": "run_start", "backend": "tpu"},
        {"type": "tunnel_state", "state": "up"},
        {"type": "run_end", "num_evals": 1.0, "search_time_s": 1.0},
    ])
    tv = watcher.read_telemetry_verdict(d)
    assert tv["classification"] == "completed"
    assert tv["backends"] == ["tpu"] and tv["tunnel_state"] == "up"
    # step_on_chip prefers the telemetry verdict over stdout scraping:
    # no platform-stamped JSON rows needed
    rec = {"rc": 0, "json": [], "stdout_tail": "", "telemetry": tv}
    assert watcher.step_on_chip("bench", rec) is True
    rec_cpu = dict(rec, telemetry=dict(tv, backends=["cpu"]))
    assert watcher.step_on_chip("bench", rec_cpu) is False


def test_watcher_fault_with_saved_state_is_resumable(tmp_path):
    watcher = _load_script("tpu_watcher")
    d = str(tmp_path)
    _write_log(d, "events-dead.jsonl", [
        {"type": "run_start", "backend": "tpu"},
        {"type": "dispatch_fault", "error_type": "XlaRuntimeError"},
    ])
    assert watcher.read_telemetry_verdict(d)["classification"] == "dead"
    _write_log(d, "events-resume.jsonl", [
        {"type": "run_start", "backend": "tpu"},
        {"type": "saved_state", "outputs": 1, "iteration": 7},
        {"type": "dispatch_fault", "error_type": "XlaRuntimeError"},
    ])
    tv = watcher.read_telemetry_verdict(d)
    assert tv["classification"] == "resumable"
    assert tv["faults"] == 2 and tv["saved_states"] == 1
    # in-flight: neither fault nor run_end; truncated lines skipped
    with open(os.path.join(d, "events-live.jsonl"), "w") as f:
        f.write(json.dumps({"type": "run_start", "backend": "cpu"}))
        f.write('\n{"type": "metr')  # mid-write
    # only the new log (mtime filter keyed on 0 here -> all read)
    tv2 = watcher.read_telemetry_verdict(d, since_ts=0.0)
    assert "cpu" in tv2["backends"]
