"""Fleet observability (ISSUE 13): the multi-run scanner/index
(telemetry/fleet.py), the alert rules (telemetry/alerts.py), the
OpenMetrics exposition + validator + HTTP endpoint
(telemetry/export.py), srfleet, and the bench-trajectory gate.

File name sorts after the other telemetry tiers (test_af_*) and
everything here is fast CPU-only host-side work — synthetic event logs,
no searches, no compiles (the real-search closed loop lives in
benchmark/suite.py's `fleet` case and the slow acceptance test at the
bottom)."""

import importlib.util
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from symbolicregression_jl_tpu.telemetry.alerts import (
    AlertRule,
    evaluate_alerts,
    trajectory_best_throughput,
)
from symbolicregression_jl_tpu.telemetry.events import validate_event
from symbolicregression_jl_tpu.telemetry.export import (
    render_openmetrics,
    serve_metrics,
    validate_exposition,
    write_textfile,
)
from symbolicregression_jl_tpu.telemetry.fleet import (
    ALERTS_LOG_NAME,
    INDEX_NAME,
    FleetScanner,
    discover_logs,
    load_fleet_index,
    load_registry,
    register_run,
)
from symbolicregression_jl_tpu.telemetry.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# synthetic event-log builder
# ---------------------------------------------------------------------------

NOW = 1_700_000_000.0

STAGES7 = ("init", "cycle", "mutate", "eval", "simplify", "optimize",
           "merge_migrate")


def run_events(
    run_id,
    *,
    attempt=1,
    t0=NOW - 100.0,
    best=(1.0, 0.5, 0.2),
    diversity=0.8,
    backend="cpu",
    fault=False,
    saved=False,
    complete=True,
    resume=None,
    eval_attrs=None,
):
    """A synthetic run trail shaped like a real one (run_start with
    fleet provenance, the seven stage spans, metrics, optional
    fault/saved_state/run_end)."""
    run = f"{run_id}-a{attempt}"
    t = [t0]

    def ev(type, **f):
        t[0] += 1.0
        return {"v": 1, "t": t[0], "run": run, "type": type, **f}

    events = [ev(
        "run_start", run_id=run_id, attempt=attempt,
        config_fingerprint="x", backend=backend,
        devices=["TFRT_CPU_0"], nout=1, niterations=3,
        **({"resume_from": resume} if resume else {}),
    )]
    for s in STAGES7:
        attrs = dict(eval_attrs or {"trees": 100, "rows": 50}) \
            if s == "eval" else {}
        events.append(ev("span", name=s, t_start=t[0], duration_s=0.5,
                         attrs=attrs))
    for i, b in enumerate(best):
        events.append(ev(
            "metrics", output=0, iteration=i,
            snapshot={"counters": {}, "histograms": {},
                      "gauges": {"best_loss": b,
                                 "population_diversity": diversity}},
        ))
    if saved:
        events.append(ev("saved_state", outputs=1, path="/tmp/x.ckpt",
                         iteration=len(best)))
    if fault:
        events.append(ev(
            "dispatch_fault", where="iteration",
            error_type="XlaRuntimeError", error="UNAVAILABLE",
            iteration=len(best), fatal=True,
        ))
    if complete:
        events.append(ev("run_end", num_evals=100.0, search_time_s=9.0))
    return events


def write_log(dirpath, name, events):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, f"events-{name}.jsonl")
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


# ---------------------------------------------------------------------------
# OpenMetrics exposition + validator
# ---------------------------------------------------------------------------


def test_registry_exposition_valid_and_typed():
    reg = MetricsRegistry()
    reg.counter("iterations_total", "iters").inc(3)
    reg.gauge("best_loss", "best").set(0.25)
    reg.gauge("never_observed")  # no sample, never NaN
    h = reg.histogram("population_length", [4, 8], "lengths")
    h.add_counts([3, 2, 1])
    text = render_openmetrics(registry=reg)
    assert validate_exposition(text) == []
    assert "# TYPE srtpu_iterations_total counter" in text
    assert "srtpu_best_loss 0.25" in text
    assert "never_observed" not in text
    # cumulative buckets + +Inf + count
    assert 'srtpu_population_length_bucket{le="4"} 3' in text
    assert 'srtpu_population_length_bucket{le="8"} 5' in text
    assert 'srtpu_population_length_bucket{le="+Inf"} 6' in text
    assert "srtpu_population_length_count 6" in text
    assert text.rstrip("\n").endswith("# EOF")


def test_exposition_skips_none_and_nonfinite():
    reg = MetricsRegistry()
    reg.gauge("g").set(float("inf"))  # snapshot would null it; render skips
    text = render_openmetrics(registry=reg)
    assert validate_exposition(text) == []
    assert "srtpu_g" not in text


def test_exposition_label_escaping():
    index = {"rollup": {"runs": 1}, "runs": [{
        "run_id": 'we"ird\\id\nx', "verdict": "healthy",
        "backend": "cpu", "attempts": [], "alerts": [],
        "last_event_age_s": 1.0, "best_loss": None,
        "throughput_trees_rows_per_s": None, "faults": 0,
    }]}
    text = render_openmetrics(fleet_index=index)
    assert validate_exposition(text) == []
    assert '\\"' in text and "\\\\" in text and "\\n" in text


def test_validator_catches_malformations():
    good = "# TYPE a gauge\na 1\n# EOF\n"
    assert validate_exposition(good) == []
    assert any("EOF" in p for p in validate_exposition("# TYPE a gauge\na 1\n"))
    assert any("no TYPE" in p for p in validate_exposition("b 1\n# EOF\n"))
    assert any("duplicate sample" in p for p in validate_exposition(
        "# TYPE a gauge\na 1\na 2\n# EOF\n"
    ))
    assert any("after its samples" in p for p in validate_exposition(
        "a 1\n# TYPE a gauge\n# EOF\n"
    ))
    assert any("not a sample" in p for p in validate_exposition(
        "# TYPE a gauge\na one two three four\n# EOF\n"
    ))
    assert any("unparseable value" in p for p in validate_exposition(
        "# TYPE a gauge\na abc\n# EOF\n"
    ))
    assert any("blank line" in p for p in validate_exposition(
        "# TYPE a gauge\n\na 1\n# EOF\n"
    ))
    assert any("content after" in p for p in validate_exposition(
        "# TYPE a gauge\na 1\n# EOF\nz 2\n"
    ))
    assert any("bad label" in p or "unterminated" in p
               for p in validate_exposition(
                   '# TYPE a gauge\na{x="y} 1\n# EOF\n'
               ))


def test_write_textfile_atomic_and_self_checking(tmp_path):
    path = str(tmp_path / "metrics.prom")
    good = "# TYPE a gauge\na 1\n# EOF\n"
    write_textfile(path, good)
    with open(path) as f:
        assert f.read() == good
    assert not os.path.exists(path + ".tmp")
    with pytest.raises(ValueError):
        write_textfile(path, "garbage without eof\n")
    with open(path) as f:
        assert f.read() == good  # the bad write never landed


def test_serve_metrics_http_endpoint():
    text = "# TYPE a gauge\na 1\n# EOF\n"
    srv = serve_metrics(lambda: text)
    port = srv.server_address[1]
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert body == text
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10
            )
        assert ei.value.code == 404
    finally:
        srv.shutdown()
        srv.server_close()


def test_serve_metrics_render_failure_degrades_to_500():
    def boom():
        raise RuntimeError("nope")

    srv = serve_metrics(boom)
    port = srv.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            )
        assert ei.value.code == 500
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# fleet scanner: discovery, rows, rollups, index
# ---------------------------------------------------------------------------


def test_two_runs_two_rows_and_rollup(tmp_path):
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    write_log(os.path.join(root, "b"), "r2", run_events("run-two"))
    index = FleetScanner(root).refresh(now=NOW)
    assert len(index["runs"]) == 2
    assert {r["verdict"] for r in index["runs"]} == {"healthy"}
    roll = index["rollup"]
    assert roll["runs"] == 2
    assert roll["verdicts"] == {"healthy": 2}
    assert roll["fault_rate"] == 0.0
    # eval span (100 trees x 50 rows / 0.5 s) x 2 runs
    assert roll["throughput_trees_rows_per_s"] == pytest.approx(20000.0)
    # the exposition of a real index validates
    assert validate_exposition(
        render_openmetrics(fleet_index=index)
    ) == []
    # index file is on disk, atomic, loadable
    idx = load_fleet_index(os.path.join(root, INDEX_NAME))
    assert idx["rollup"]["runs"] == 2
    assert not os.path.exists(os.path.join(root, INDEX_NAME) + ".tmp")


def test_row_fields(tmp_path):
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    row = FleetScanner(root).refresh(now=NOW)["runs"][0]
    assert row["run_id"] == "run-one"
    assert row["backend"] == "cpu"
    assert row["attempt"] == 1 and not row["resumed"]
    assert row["complete"] and row["faults"] == 0
    assert row["best_loss"] == pytest.approx(0.2)
    assert set(row["stage_shares"]) == set(STAGES7)
    assert sum(row["stage_shares"].values()) == pytest.approx(1.0, abs=0.01)
    assert row["last_event_age_s"] is not None
    assert row["alerts"] == []


def test_truncated_mid_write_log_is_held_then_completed(tmp_path):
    """srtop's partial-line discipline: a half-written trailing line is
    buffered (not parsed, not an error) until its newline lands — the
    next refresh picks up exactly the completed events."""
    root = str(tmp_path)
    events = run_events("run-one", complete=False)
    path = write_log(os.path.join(root, "a"), "r1", events)
    end_event = json.dumps({
        "v": 1, "t": NOW, "run": "run-one-a1", "type": "run_end",
        "num_evals": 100.0, "search_time_s": 9.0,
    })
    with open(path, "a") as f:
        f.write(end_event[:20])  # mid-write: no newline, half a line
    sc = FleetScanner(root)
    index = sc.refresh(now=NOW)
    row = index["runs"][0]
    assert row["verdict"] == "incomplete" and not row["complete"]
    with open(path, "a") as f:
        f.write(end_event[20:] + "\n")
    row2 = sc.refresh(now=NOW)["runs"][0]
    assert row2["complete"] and row2["verdict"] == "healthy"


def test_corrupt_lines_counted_never_fatal(tmp_path):
    root = str(tmp_path)
    path = write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    with open(path, "a") as f:
        f.write("{not json at all\n[5]\n")
    index = FleetScanner(root).refresh(now=NOW)
    row = index["runs"][0]
    assert row["verdict"] == "healthy"
    assert row["skipped_lines"] == 2


def test_vanishing_run_dir_between_scans(tmp_path):
    """A run directory deleted between refreshes drops its row — no
    exception, no ghost — and the loss is counted in the rollup."""
    import shutil

    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    write_log(os.path.join(root, "b"), "r2", run_events("run-two"))
    sc = FleetScanner(root)
    assert len(sc.refresh(now=NOW)["runs"]) == 2
    shutil.rmtree(os.path.join(root, "b"))
    index = sc.refresh(now=NOW)
    assert [r["run_id"] for r in index["runs"]] == ["run-one"]
    assert index["rollup"]["vanished_logs"] == 1


def test_run_without_run_end_is_incomplete_and_ages(tmp_path):
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1",
              run_events("run-one", complete=False, t0=NOW - 100.0))
    index = FleetScanner(root, stale_after_s=30.0).refresh(now=NOW)
    row = index["runs"][0]
    assert row["verdict"] == "incomplete"
    assert row["last_event_age_s"] > 30.0
    assert "stale_run" in row["alerts"]
    assert index["rollup"]["stale_runs"] == 1


def test_multi_attempt_trail_collapses_into_one_row(tmp_path):
    """The supervisor threads one run_id through every attempt: the
    fleet index must show ONE row whose lineage reads
    faulted+resumable -> resumed healthy (ISSUE 13 acceptance)."""
    root = str(tmp_path)
    d = os.path.join(root, "supervised")
    write_log(d, "a1", run_events(
        "run-sup", attempt=1, fault=True, saved=True, complete=False,
        t0=NOW - 200.0,
    ))
    write_log(d, "a2", run_events(
        "run-sup", attempt=2, t0=NOW - 100.0,
        resume={"path": "/tmp/x.ckpt", "iteration": 3, "outputs": 1,
                "populations_compatible": True},
    ))
    index = FleetScanner(root).refresh(now=NOW)
    assert len(index["runs"]) == 1
    row = index["runs"][0]
    assert row["run_id"] == "run-sup"
    assert row["verdict"] == "healthy"
    assert row["resumed"] and row["attempt"] == 2
    assert [(a["attempt"], a["verdict"], a["resumable"])
            for a in row["attempts"]] == [
        (1, "faulted", True), (2, "healthy", False),
    ]
    assert row["faults"] == 1 and row["saved_states"] == 1
    kinds = [e["kind"] for e in row["timeline"]]
    assert kinds == ["saved_state", "fault", "resume", "run_end"]
    roll = index["rollup"]
    assert roll["resumable_runs"] == 1
    assert roll["resume_success_rate"] == 1.0


def test_registry_pending_rows(tmp_path):
    root = str(tmp_path)
    rec = register_run(root, source="supervisor", run_id="not-yet",
                       telemetry_dir=os.path.join(root, "x"))
    assert rec is not None
    assert load_registry(root)[0]["run_id"] == "not-yet"
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    register_run(root, source="supervisor", run_id="run-one")
    index = FleetScanner(root).refresh(now=NOW)
    assert index["rollup"]["registered"] == 2
    assert index["rollup"]["pending_runs"] == 1
    assert [p["run_id"] for p in index["pending"]] == ["not-yet"]


def test_anonymous_registration_pending_until_logs_appear(tmp_path):
    """A watcher step registers WITHOUT a run_id (it launches many
    searches and cannot pre-know their ids): it must still read as
    pending while silent, and clear once any log under its
    telemetry_dir starts after the registration."""
    root = str(tmp_path)
    step_dir = os.path.join(root, "step")
    register_run(root, source="watcher:bench", run_id=None,
                 telemetry_dir=step_dir, attempt=1)
    # register_run stamps wall-clock t; rewrite with a controlled one
    reg_path = os.path.join(root, "fleet_registry.jsonl")
    with open(reg_path) as f:
        rec = json.loads(f.readline())
    rec["t"] = NOW - 50.0
    with open(reg_path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    sc = FleetScanner(root)
    index = sc.refresh(now=NOW)
    assert index["rollup"]["pending_runs"] == 1
    # a log under the step's dir starting AFTER the registration clears it
    write_log(step_dir, "r1", run_events("run-one", t0=NOW - 40.0))
    index2 = sc.refresh(now=NOW)
    assert index2["rollup"]["pending_runs"] == 0
    # ...but a log elsewhere would not have (dir-scoped join)
    register_run(root, source="watcher:suite", run_id=None,
                 telemetry_dir=os.path.join(root, "other"))
    index3 = sc.refresh(now=NOW)
    assert index3["rollup"]["pending_runs"] == 1


def test_refresh_caches_summaries_when_no_new_bytes(tmp_path, monkeypatch):
    """An idle refresh costs only the (zero) new bytes: analyze_run is
    not re-run over logs that did not grow."""
    import symbolicregression_jl_tpu.telemetry.fleet as fleet_mod

    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    sc = FleetScanner(root)
    sc.refresh(now=NOW)
    calls = []
    real = fleet_mod.analyze_run
    monkeypatch.setattr(
        fleet_mod, "analyze_run",
        lambda events, **kw: (calls.append(1), real(events, **kw))[1],
    )
    index = sc.refresh(now=NOW)  # no new bytes anywhere
    assert calls == []
    assert index["runs"][0]["verdict"] == "healthy"  # rows still built
    # growth re-analyzes exactly the grown log
    with open(os.path.join(root, "a", "events-r1.jsonl"), "a") as f:
        f.write(json.dumps({
            "v": 1, "t": NOW, "run": "run-one-a1", "type": "progress",
            "num_evals": 200.0,
        }) + "\n")
    sc.refresh(now=NOW)
    assert len(calls) == 1


def test_fleet_files_not_discovered_as_runs(tmp_path):
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    sc = FleetScanner(root)
    sc.refresh(now=NOW)
    # registry/alerts/index live under the root but are not run logs
    register_run(root, source="test", run_id="x")
    assert all(
        os.path.basename(p).startswith("events-")
        for p in discover_logs(root)
    )
    assert len(sc.refresh(now=NOW)["runs"]) == 1


# ---------------------------------------------------------------------------
# alert rules + alert events
# ---------------------------------------------------------------------------


def test_fault_without_saved_state_alerts_critical(tmp_path):
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1",
              run_events("run-dead", fault=True, saved=False,
                         complete=False))
    index = FleetScanner(root).refresh(now=NOW)
    alerts = index["alerts"]
    assert [a["rule"] for a in alerts] == ["fault_unresumable"]
    assert alerts[0]["severity"] == "critical"
    # the resumable complement is the supervisor's normal path: no alert
    root2 = str(tmp_path / "b")
    write_log(os.path.join(root2, "a"), "r1",
              run_events("run-resumable", fault=True, saved=True,
                         complete=False))
    index2 = FleetScanner(root2).refresh(now=NOW)
    assert index2["alerts"] == []


def test_stalled_and_diverging_rules():
    rows = [
        {"run_id": "s", "verdict": "stalled", "reasons": ["plateau"],
         "complete": True, "resumable": False, "faults": 0},
        {"run_id": "d", "verdict": "diverging", "reasons": ["NaN"],
         "complete": True, "resumable": False, "faults": 0},
    ]
    alerts = evaluate_alerts(rows, {"stale_after_s": 600.0})
    assert [(a["rule"], a["severity"]) for a in alerts] == [
        ("diverging_run", "critical"), ("stalled_run", "warning"),
    ]


def test_compile_bound_rule_is_info():
    rows = [{"run_id": "c", "verdict": "healthy", "compile_bound": True,
             "compile_share": 0.9, "faults": 0}]
    alerts = evaluate_alerts(rows, {})
    assert [(a["rule"], a["severity"]) for a in alerts] == [
        ("compile_bound", "info"),
    ]


def test_throughput_regression_rule_requires_trajectory():
    row = {"run_id": "r", "verdict": "healthy", "backend": "cpu",
           "throughput_trees_rows_per_s": 1000.0, "faults": 0}
    # no trajectory in ctx: never fires
    assert evaluate_alerts([row], {}) == []
    traj = {"series": {"throughput": [
        {"round": 3, "platform": "cpu", "value": 4.7e6},
        {"round": 4, "platform": "tpu", "value": 1.0e9},
        {"round": 5, "platform": "cpu", "value": None},
    ]}}
    assert trajectory_best_throughput(traj) == {
        "cpu": 4.7e6, "tpu": 1.0e9,
    }
    alerts = evaluate_alerts(
        [row], {"trajectory": traj, "regression_threshold": 0.10}
    )
    assert [a["rule"] for a in alerts] == ["throughput_regression"]
    # same-platform only: a TPU bar must not judge a CPU run
    fast_cpu = dict(row, throughput_trees_rows_per_s=4.6e6)
    assert evaluate_alerts(
        [fast_cpu], {"trajectory": traj, "regression_threshold": 0.10}
    ) == []


def test_broken_rule_reports_itself():
    def boom(row, ctx):
        raise RuntimeError("bad rule")

    rules = (AlertRule("x", "warning", "boom", boom),)
    alerts = evaluate_alerts(
        [{"run_id": "r", "faults": 0}], {}, rules=rules
    )
    assert [a["rule"] for a in alerts] == ["rule_error"]


def test_alert_events_emitted_once_and_schema_valid(tmp_path):
    """Each (rule, run) firing appends ONE schema-v1 alert event; a
    steady-state refresh re-emits nothing; a cleared-then-recurring
    alert logs again (the log is the history, the index the state)."""
    root = str(tmp_path)
    path = write_log(os.path.join(root, "a"), "r1",
                     run_events("run-dead", fault=True, saved=False,
                                complete=False))
    sc = FleetScanner(root)
    sc.refresh(now=NOW)
    sc.refresh(now=NOW)  # steady state: no duplicate
    alog = os.path.join(root, ALERTS_LOG_NAME)
    with open(alog) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert len(lines) == 1
    assert validate_event(lines[0]) == []
    assert lines[0]["type"] == "alert"
    assert lines[0]["run"] == "run-dead"
    assert lines[0]["rule"] == "fault_unresumable"
    # the alert clears (a NEW log of the same logical run completes
    # healthy — logs are append-only, so clearing means a new trail,
    # never an in-place rewrite), then recurs: the recurrence logs
    # again — the alerts log is the history, the index the state
    os.remove(path)
    path2 = write_log(os.path.join(root, "a"), "r2",
                      run_events("run-dead"))
    assert sc.refresh(now=NOW)["alerts"] == []
    os.remove(path2)
    write_log(os.path.join(root, "a"), "r3",
              run_events("run-dead", fault=True, saved=False,
                         complete=False))
    sc.refresh(now=NOW)
    with open(alog) as f:
        assert sum(1 for ln in f if ln.strip()) == 2


# ---------------------------------------------------------------------------
# srfleet CLI
# ---------------------------------------------------------------------------


def test_srfleet_once_exit_matches_alert_state(tmp_path, capsys):
    srfleet = _load_script("srfleet")
    root = str(tmp_path)
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    assert srfleet.main([root, "--once"]) == 0
    out = capsys.readouterr().out
    assert "run-one" in out and "healthy" in out
    # inject a stalled run: the gate flips
    write_log(os.path.join(root, "b"), "r2", run_events(
        "run-stalled", best=[1.0] * 8, diversity=0.05,
    ))
    assert srfleet.main([root, "--once"]) == 1
    out = capsys.readouterr().out
    assert "stalled_run" in out


def test_srfleet_fail_on_severity(tmp_path, capsys):
    """info alerts (compile_bound on a cold smoke run) report without
    failing the default gate; --fail-on info makes them fail."""
    srfleet = _load_script("srfleet")
    root = str(tmp_path)
    events = run_events("run-one")
    # dwarf the stage spans with compile time -> compile-bound
    events.insert(2, {
        "v": 1, "t": NOW - 99.0, "run": "run-one-a1", "type": "compile",
        "name": "cycle", "duration_s": 100.0,
    })
    write_log(os.path.join(root, "a"), "r1", events)
    assert srfleet.main([root, "--once"]) == 0
    capsys.readouterr()
    assert srfleet.main([root, "--once", "--fail-on", "info"]) == 1
    out = capsys.readouterr().out
    assert "compile_bound" in out


def test_srfleet_metrics_out_writes_valid_exposition(tmp_path):
    srfleet = _load_script("srfleet")
    root = str(tmp_path / "root")
    write_log(os.path.join(root, "a"), "r1", run_events("run-one"))
    out = str(tmp_path / "metrics.prom")
    assert srfleet.main([root, "--once", "--metrics-out", out]) == 0
    with open(out) as f:
        assert validate_exposition(f.read()) == []


# ---------------------------------------------------------------------------
# bench_trajectory --gate
# ---------------------------------------------------------------------------


def _write_bench_round(repo, n, value, vs_baseline, platform="cpu"):
    with open(os.path.join(repo, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({
            "n": n,
            "parsed": {"value": value, "vs_baseline": vs_baseline,
                       "platform": platform},
        }, f)


def test_trajectory_gate_exits_nonzero_on_latest_regression(tmp_path):
    bt = _load_script("bench_trajectory")
    repo = str(tmp_path)
    _write_bench_round(repo, 1, 4.0e6, 0.2)
    _write_bench_round(repo, 2, 2.0e6, 0.1)  # latest: 50% drop
    traj = bt.build_trajectory(repo)
    assert {r["metric"] for r in traj["latest_regressions"]} == {
        "throughput", "vs_baseline",
    }
    assert bt.main(["--repo", repo, "--no-write"]) == 0  # report only
    assert bt.main(["--repo", repo, "--no-write", "--gate"]) == 2


def test_trajectory_gate_ignores_historical_regressions(tmp_path):
    """Only the LATEST round gates: an old dip that later recovered is
    a report forever, never an exit code."""
    bt = _load_script("bench_trajectory")
    repo = str(tmp_path)
    _write_bench_round(repo, 1, 4.0e6, 0.2)
    _write_bench_round(repo, 2, 2.0e6, 0.1)  # historical dip
    _write_bench_round(repo, 3, 4.1e6, 0.21)  # recovered
    traj = bt.build_trajectory(repo)
    assert traj["regressions"]  # the dip is still reported
    assert traj["latest_regressions"] == []
    assert bt.main(["--repo", repo, "--no-write", "--gate"]) == 0


def test_trajectory_gate_clean_exits_zero(tmp_path):
    bt = _load_script("bench_trajectory")
    repo = str(tmp_path)
    _write_bench_round(repo, 1, 4.0e6, 0.2)
    _write_bench_round(repo, 2, 4.2e6, 0.22)
    assert bt.main(["--repo", repo, "--no-write", "--gate"]) == 0


# ---------------------------------------------------------------------------
# checked-in fixture + lint gate plumbing
# ---------------------------------------------------------------------------


def test_golden_fleet_index_fixture_renders_valid_exposition():
    """The lint gate's contract, asserted from the tests too: the
    checked-in fleet index (captured from the real two-search +
    supervised-fault acceptance scenario) renders to a valid
    exposition, and carries the 3-row resumable->resumed story."""
    path = os.path.join(
        REPO, "tests", "data", "telemetry", "golden_fleet_index.json"
    )
    with open(path) as f:
        index = json.load(f)
    rows = index["runs"]
    assert len(rows) == 3
    assert all(r["verdict"] == "healthy" for r in rows)
    sup = [r for r in rows if r["resumed"]]
    assert len(sup) == 1
    assert [(a["attempt"], a["verdict"], a["resumable"])
            for a in sup[0]["attempts"]] == [
        (1, "faulted", True), (2, "healthy", False),
    ]
    text = render_openmetrics(fleet_index=index)
    assert validate_exposition(text) == []


def test_lint_fleet_exposition_gate():
    lint = _load_script("lint")
    out = lint.check_fleet_exposition()
    assert out["ok"], out
    assert out["samples"] > 10


# ---------------------------------------------------------------------------
# slow: the full acceptance loop with real searches
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_acceptance_end_to_end(tmp_path):
    """ISSUE 13 acceptance on CPU: two searches + one supervisor-resumed
    faulted search under one fleet root -> 3 index rows with correct
    verdicts (the faulted run shows resumable->resumed lineage via
    run_id/attempt), a valid exposition, and HoF bit-identity with
    fleet registration on vs off."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.resilience import (
        FaultPlan,
        clear_fault_plan,
        set_fault_plan,
        supervised_search,
    )

    root = str(tmp_path / "fleet")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        verbosity=0, progress=False,
    )
    frontier = lambda r: [
        (c.complexity, float(c.loss), c.equation) for c in r.frontier()
    ]
    baseline = sr.equation_search(X, y, niterations=2, seed=0, **kw)
    results = []
    for i, seed in enumerate((0, 1)):
        results.append(sr.equation_search(
            X, y, niterations=2, seed=seed, telemetry=True,
            telemetry_dir=os.path.join(root, f"run{i}"), **kw,
        ))
    # fleet registration/telemetry on vs off: bit-identical HoF
    assert frontier(results[0]) == frontier(baseline)

    snap = str(tmp_path / "snap.ckpt")
    set_fault_plan(FaultPlan(kind="raise", at=1))
    try:
        sup = supervised_search(
            X, y, niterations=2, seed=0,
            snapshot_path=snap, snapshot_every_dispatches=1,
            max_attempts=3, backoff_base_s=0.05, backoff_jitter=0.0,
            telemetry=True,
            telemetry_dir=os.path.join(root, "supervised"),
            fleet_root=root, **kw,
        )
    finally:
        clear_fault_plan()
    assert sup.attempts == 2 and sup.run_id
    assert frontier(sup.result) == frontier(baseline)
    # the supervisor registered its run_id before attempt 1
    assert any(
        rec.get("run_id") == sup.run_id for rec in load_registry(root)
    )

    index = FleetScanner(root).refresh()
    rows = index["runs"]
    assert len(rows) == 3
    assert all(r["verdict"] == "healthy" for r in rows)
    sup_row = next(r for r in rows if r["run_id"] == sup.run_id)
    assert sup_row["resumed"]
    assert [(a["attempt"], a["verdict"], a["resumable"])
            for a in sup_row["attempts"]] == [
        (1, "faulted", True), (2, "healthy", False),
    ]
    assert validate_exposition(
        render_openmetrics(fleet_index=index)
    ) == []
