"""Mixed-configuration end-to-end searches.

Analog of the reference's test/test_mixed.jl:7-146 matrix, which sweeps
{batching, weighted, multi-output, precision, crossover, frequency modes,
optimizer algorithm, warmup, progress} and asserts the target equation is
recovered (best.loss < 1e-2) with held-out prediction match (:129-141).

Each config searches for y = x0^2 + 2*cos(x2) (the reference's
2cos(x4)+x1^2-2 family) with a small-but-sufficient budget.
"""

import numpy as np
import pytest

import symbolicregression_jl_tpu as sr

BUDGET = dict(
    niterations=14,
    npop=48,
    npopulations=4,
    ncycles_per_iteration=150,
    maxsize=14,
    verbosity=0,
    progress=False,
    early_stop_condition=1e-6,
)
OPSET = dict(binary_operators=["+", "-", "*"], unary_operators=["cos"])


def make_data(rng, n=80):
    X = (rng.standard_normal((3, n)) * 2).astype(np.float32)
    y = X[0] * X[0] + 2.0 * np.cos(X[2])
    return X, y


def check(res, X_test, y_test, atol=0.15):
    best = res.best_loss()
    assert best.loss < 1e-2, f"loss {best.loss} (eq: {best.equation})"
    pred = res.predict(X_test)
    np.testing.assert_allclose(pred, y_test, atol=atol)


@pytest.mark.slow
def test_batching_annealing(rng):
    X, y = make_data(rng, n=400)
    res = sr.equation_search(
        X, y, seed=3, batching=True, batch_size=50, annealing=True,
        **OPSET, **BUDGET,
    )
    Xt, yt = make_data(np.random.default_rng(99))
    check(res, Xt, yt)


@pytest.mark.slow
def test_weighted_search_recovers(rng):
    X, y = make_data(rng)
    w = rng.uniform(0.5, 2.0, y.shape[0]).astype(np.float32)
    res = sr.equation_search(X, y, weights=w, seed=4, **OPSET, **BUDGET)
    Xt, yt = make_data(np.random.default_rng(98))
    check(res, Xt, yt)


@pytest.mark.slow
def test_crossover_heavy(rng):
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y, seed=5, crossover_probability=0.3, **OPSET, **BUDGET
    )
    Xt, yt = make_data(np.random.default_rng(97))
    check(res, Xt, yt)


@pytest.mark.slow
def test_no_frequency_with_warmup(rng):
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y, seed=6, use_frequency=False, use_frequency_in_tournament=False,
        warmup_maxsize_by=0.5, **OPSET, **BUDGET,
    )
    Xt, yt = make_data(np.random.default_rng(96))
    check(res, Xt, yt)


@pytest.mark.slow
def test_nelder_mead_search(rng):
    """Constant-bearing target forces the optimizer path: y has the
    irrational constants the mutations alone rarely hit."""
    X = (rng.standard_normal((2, 80)) * 2).astype(np.float32)
    y = 2.5382 * np.cos(X[1]) + X[0] * X[0] - 0.5
    res = sr.equation_search(
        X, y, seed=7,
        optimizer_algorithm="NelderMead",
        optimizer_probability=0.3,
        **OPSET, **BUDGET,
    )
    best = res.best_loss()
    assert best.loss < 1e-2, f"loss {best.loss} (eq: {best.equation})"


@pytest.mark.slow
def test_custom_elementwise_loss(rng):
    X, y = make_data(rng)
    res = sr.equation_search(
        X, y, seed=8, loss=lambda p, t: (p - t) ** 2, **OPSET, **BUDGET
    )
    Xt, yt = make_data(np.random.default_rng(95))
    check(res, Xt, yt)


def test_multi_output_distinct_targets(rng):
    """Per-output hall of fame, like the reference's y::Matrix dispatch
    (src/SymbolicRegression.jl:308-315)."""
    X = (rng.standard_normal((2, 60)) * 2).astype(np.float32)
    Y = np.stack([X[0] * X[0], 3.0 * np.cos(X[1])])
    # 4 islands: a 2-island archipelago can collapse to a cos-family local
    # optimum on output 0 for many seeds (diversity, not plumbing — this
    # test is about the per-output HoF); with 4 islands every nearby seed
    # recovers both outputs exactly
    res = sr.equation_search(
        X, Y, seed=9,
        niterations=8, npop=33, npopulations=4, ncycles_per_iteration=80,
        maxsize=10, verbosity=0, progress=False,
        early_stop_condition=1e-6, **OPSET,
    )
    assert res.multi_output and len(res.candidates) == 2
    for j in range(2):
        best = res.best_loss(output=j)
        assert best.loss < 1e-1, f"output {j}: {best.equation} {best.loss}"
