"""Fused loss+gradient kernel (ops/pallas_grad.py) vs two oracles:
`jax.grad` through the jnp lockstep interpreter where that is finite, and
float64 central finite differences of the numpy oracle where autodiff
produces spurious NaN (the lockstep interpreter evaluates every candidate
operator per slot, and a non-selected branch that overflows turns the
zero cotangent into inf*0=NaN — the backward kernel muxes derivative
VALUES instead, so discarded candidates cannot contaminate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.models.mutate_device import (
    gen_random_tree_fixed_size,
)
from symbolicregression_jl_tpu.models.trees import (
    CONST,
    Expr,
    encode_tree,
    stack_trees,
)
from symbolicregression_jl_tpu.ops.eval_numpy import eval_tree_numpy
from symbolicregression_jl_tpu.ops.interpreter import eval_trees
from symbolicregression_jl_tpu.ops.operators import make_operator_set
from symbolicregression_jl_tpu.ops.pallas_grad import eval_loss_grad_pallas

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp", "sqrt", "log"])
L = 24
NFEAT = 3
NROWS = 64


def _workload(n=24, seed=0):
    sizes = jax.random.randint(jax.random.PRNGKey(seed + 1), (n,), 1, 16)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, NFEAT, OPS, L)
    )(jax.random.split(jax.random.PRNGKey(seed), n), sizes)
    X = jax.random.normal(
        jax.random.PRNGKey(seed + 2), (NFEAT, NROWS), jnp.float32
    )
    y = jax.random.normal(jax.random.PRNGKey(seed + 3), (NROWS,), jnp.float32)
    return trees, X, y


def _autodiff_oracle(trees, X, y, weights=None):
    """loss + grad per tree via jax.grad through the jnp interpreter."""
    def loss_of(cval, tree):
        t2 = tree._replace(cval=cval)
        yp, _ = eval_trees(
            jax.tree_util.tree_map(lambda x: x[None], t2), X, OPS
        )
        e = (yp[0] - y) ** 2
        if weights is None:
            return jnp.mean(e)
        return jnp.sum(e * weights) / jnp.sum(weights)

    n = trees.length.shape[0]
    losses, grads = [], []
    for i in range(n):
        t = jax.tree_util.tree_map(lambda x: x[i], trees)
        losses.append(float(loss_of(t.cval, t)))
        grads.append(np.asarray(jax.grad(loss_of)(t.cval, t)))
    return np.asarray(losses), np.stack(grads)


def _fd64(trees, X, y, i, s, h=1e-5):
    """f64 central finite difference of the numpy oracle at (tree i, slot s)."""
    t = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x))[i], trees
    )
    X64 = np.asarray(X, np.float64)
    y64 = np.asarray(y, np.float64)

    def loss_at(c):
        cv = t.cval.astype(np.float64).copy()
        cv[s] = c
        yp, _ = eval_tree_numpy(t._replace(cval=cv), X64, OPS)
        return float(np.mean((yp - y64) ** 2))

    c0 = float(t.cval[s])
    d = max(abs(c0) * h, h)
    return (loss_at(c0 + d) - loss_at(c0 - d)) / (2 * d)


def _check_grads(trees, X, y, grad, ok_mask, grad_ref, kmask):
    """Per-entry comparison: autodiff oracle where finite, f64 finite
    differences where autodiff produced spurious NaN."""
    grad_expect = np.where(kmask, grad_ref, 0.0)
    for i in np.flatnonzero(ok_mask):
        for s in range(L):
            want = grad_expect[i, s]
            if np.isfinite(want):
                np.testing.assert_allclose(
                    grad[i, s], want, rtol=2e-4, atol=1e-4,
                    err_msg=f"tree {i} slot {s}",
                )
            elif kmask[i, s]:
                fd = _fd64(trees, X, y, i, s)
                np.testing.assert_allclose(
                    grad[i, s], fd, rtol=1e-3, atol=1e-4,
                    err_msg=f"tree {i} slot {s} (fd oracle)",
                )


@pytest.mark.parametrize("tree_unroll", [1, 4])
def test_grad_kernel_matches_oracles(tree_unroll):
    trees, X, y = _workload()
    loss, grad, ok = eval_loss_grad_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=8,
        tree_unroll=tree_unroll,
    )
    loss, grad, ok = (np.asarray(jax.device_get(a)) for a in (loss, grad, ok))
    _, ok_ref = jax.device_get(eval_trees(trees, X, OPS))
    np.testing.assert_array_equal(ok, np.asarray(ok_ref))

    loss_ref, grad_ref = _autodiff_oracle(trees, X, y)
    kmask = np.asarray(trees.kind) == CONST
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(loss[m], loss_ref[m], rtol=1e-5, atol=1e-6)
    _check_grads(trees, X, y, grad, m, grad_ref, kmask)


def test_grad_kernel_weighted():
    trees, X, y = _workload(n=12, seed=7)
    w = jax.random.uniform(jax.random.PRNGKey(11), (NROWS,)) + 0.5
    loss, grad, ok = eval_loss_grad_pallas(
        trees, X, y, w, OPS, interpret=True, t_block=8, tree_unroll=2
    )
    loss_ref, grad_ref = _autodiff_oracle(trees, X, y, weights=w)
    kmask = np.asarray(trees.kind) == CONST
    m = np.asarray(jax.device_get(ok))
    grad_expect = np.where(kmask, grad_ref, 0.0)
    np.testing.assert_allclose(
        np.asarray(loss)[m], loss_ref[m], rtol=1e-5, atol=1e-6
    )
    both = m[:, None] & np.isfinite(grad_expect)
    np.testing.assert_allclose(
        np.asarray(grad)[both], grad_expect[both], rtol=2e-4, atol=1e-5
    )


def test_grad_kernel_edge_shapes():
    """Bare const leaf, bare var leaf, and a unary chain."""
    chain = Expr.const(0.8)
    for _ in range(3):
        chain = Expr.unary(1, chain)  # exp^3(0.8), finite in f32
    trees = stack_trees([
        encode_tree(Expr.const(2.5), L),
        encode_tree(Expr.var(1), L),
        encode_tree(chain, L),
    ])
    X = jnp.asarray(
        np.random.default_rng(3).standard_normal((NFEAT, 40)), jnp.float32
    )
    y = jnp.asarray(
        np.random.default_rng(4).standard_normal(40), jnp.float32
    )
    loss, grad, ok = eval_loss_grad_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=8, tree_unroll=1
    )
    ok = np.asarray(jax.device_get(ok))
    assert np.all(ok)
    loss_ref, grad_ref = _autodiff_oracle(trees, X, y)
    kmask = np.asarray(trees.kind) == CONST
    np.testing.assert_allclose(
        np.asarray(loss), loss_ref, rtol=1e-5, atol=1e-6
    )
    _check_grads(trees, X, y, np.asarray(grad), ok, grad_ref, kmask)
    # var-leaf tree has no constants: all-zero grad
    assert np.all(np.asarray(grad)[1] == 0.0)


def test_grad_kernel_poison_flag():
    """sqrt of a negative constant poisons ok, like the eval kernels."""
    trees = stack_trees([
        encode_tree(Expr.unary(2, Expr.const(-4.0)), L),  # sqrt(-4)
        encode_tree(Expr.const(1.0), L),
    ])
    X = jnp.ones((NFEAT, 16), jnp.float32)
    y = jnp.zeros((16,), jnp.float32)
    _, _, ok = eval_loss_grad_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=8, tree_unroll=1
    )
    assert not bool(ok[0])
    assert bool(ok[1])


def test_loss_only_kernel_matches_grad_kernel():
    """eval_loss_pallas (line-search evaluator) returns the same fused
    loss and ok as the with-grad kernel."""
    from symbolicregression_jl_tpu.ops.pallas_grad import eval_loss_pallas

    trees, X, y = _workload(n=16, seed=5)
    l1, _, ok1 = eval_loss_grad_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=8, tree_unroll=2
    )
    l2, ok2 = eval_loss_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=8, tree_unroll=2
    )
    np.testing.assert_array_equal(np.asarray(ok1), np.asarray(ok2))
    m = np.asarray(ok1)
    np.testing.assert_allclose(
        np.asarray(l1)[m], np.asarray(l2)[m], rtol=1e-6, atol=1e-7
    )


def test_grad_kernel_zero_weight_row_still_poisons():
    """A tree that is non-finite only on a zero-weighted VALID row must
    still be flagged not-ok (parity with eval_trees_pallas, whose ok is
    weight-independent) — row validity comes from nrows, not weights."""
    # log(x0): negative only on the zero-weighted row
    trees = stack_trees([encode_tree(Expr.unary(3, Expr.var(0)), L)])
    Xh = np.ones((NFEAT, 16), np.float32)
    Xh[0, 5] = -1.0
    w = np.ones(16, np.float32)
    w[5] = 0.0
    _, _, ok = eval_loss_grad_pallas(
        trees, jnp.asarray(Xh), jnp.zeros((16,), jnp.float32),
        jnp.asarray(w), OPS, interpret=True, t_block=8, tree_unroll=1,
    )
    assert not bool(ok[0])


def test_grad_kernel_rows_beyond_one_block():
    """nrows > r_block splits the row grid; loss/grad/poison must
    accumulate across row tiles and match the autodiff oracle."""
    n = 12
    sizes = jax.random.randint(jax.random.PRNGKey(5), (n,), 1, 14)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, NFEAT, OPS, L)
    )(jax.random.split(jax.random.PRNGKey(4), n), sizes)
    n_rows = 300  # 3 row tiles at r_block=128
    X = jax.random.normal(
        jax.random.PRNGKey(6), (NFEAT, n_rows), jnp.float32
    )
    y = jax.random.normal(jax.random.PRNGKey(7), (n_rows,), jnp.float32)
    loss, grad, ok = eval_loss_grad_pallas(
        trees, X, y, None, OPS, interpret=True, t_block=4, r_block=128,
        tree_unroll=2,
    )
    loss_ref, grad_ref = _autodiff_oracle(trees, X, y)
    kmask = np.asarray(trees.kind) == CONST
    m = np.asarray(jax.device_get(ok))
    _, ok_ref = jax.device_get(eval_trees(trees, X, OPS))
    np.testing.assert_array_equal(m, np.asarray(ok_ref))
    np.testing.assert_allclose(
        np.asarray(loss)[m], loss_ref[m], rtol=1e-5, atol=1e-6
    )
    _check_grads(trees, X, y, np.asarray(grad), m, grad_ref, kmask)
