"""Hostile-data hardening (ISSUE 15): the dataset front door
(validate/sanitize + Options.data_policy), the shared numeric
containment primitive, the fixed-order pairwise row reduction, and the
new telemetry fields (docs/robustness_numeric.md).

Search-level tests share ONE Options graph (same shapes, same knobs) so
the whole file pays a single compile; the heavyweight combinations live
under `slow` per the tier-1 dot-budget policy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.dataset import (
    SCALE_HAZARD_ABS,
    DatasetDiagnostics,
    HostileDatasetError,
    sanitize_dataset,
    validate_dataset,
)
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.ops.losses import (
    aggregate_loss,
    contain_nonfinite,
    pairwise_sum,
)

KW = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=16,
    npopulations=2,
    ncycles_per_iteration=10,
    maxsize=8,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
    runtests=False,
    niterations=1,
)


def make_data(seed=0, n=64):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((3, n)).astype(np.float32)
    y = (X[0] * X[0] + np.cos(X[2])).astype(np.float32)
    return X, y


def frontier(r):
    return [
        (c.complexity, c.equation, float(c.loss), float(c.score))
        for c in r.frontier()
    ]


# ---------------------------------------------------------------------------
# contain_nonfinite — THE containment primitive
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_contain_nonfinite_semantics():
    v = jnp.asarray([1.0, np.nan, np.inf, -np.inf, 2.0])
    out = np.asarray(contain_nonfinite(v))
    np.testing.assert_array_equal(out, [1.0, np.inf, np.inf, np.inf, 2.0])
    # ok flag folds in
    ok = jnp.asarray([True, True, True, True, False])
    out = np.asarray(contain_nonfinite(v, ok))
    np.testing.assert_array_equal(
        out, [1.0, np.inf, np.inf, np.inf, np.inf]
    )
    # ref: judge another array's finiteness (score contained on loss)
    score = jnp.asarray([0.1, 0.2, 0.3])
    loss = jnp.asarray([1.0, np.nan, 2.0])
    np.testing.assert_array_equal(
        np.asarray(contain_nonfinite(score, ref=loss)),
        np.asarray([0.1, np.inf, 0.3], np.float32),
    )
    # bit-identical to the historic inline form
    ref = jnp.where(ok & jnp.isfinite(v), v, jnp.inf)
    np.testing.assert_array_equal(
        np.asarray(contain_nonfinite(v, ok)), np.asarray(ref)
    )


# ---------------------------------------------------------------------------
# pairwise_sum / deterministic aggregation
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_pairwise_sum_matches_sum():
    rng = np.random.default_rng(0)
    for n in (1, 2, 3, 7, 64, 100, 1000):
        x = rng.standard_normal(n).astype(np.float32)
        got = float(pairwise_sum(jnp.asarray(x)))
        want = float(np.sum(x.astype(np.float64)))
        assert abs(got - want) < 1e-3 * max(1.0, abs(want)), (n, got, want)
    # batched, non-last axis
    xb = rng.standard_normal((5, 33)).astype(np.float32)
    got = np.asarray(pairwise_sum(jnp.asarray(xb.T), axis=0))
    np.testing.assert_allclose(
        got, xb.astype(np.float64).sum(1), rtol=1e-5
    )
    # empty axis sums to zero
    assert float(pairwise_sum(jnp.zeros((0,), jnp.float32))) == 0.0


@pytest.mark.fast
def test_aggregate_loss_deterministic_forms():
    rng = np.random.default_rng(1)
    elem = rng.standard_normal(257).astype(np.float32)
    w = np.abs(rng.standard_normal(257)).astype(np.float32)
    for weights in (None, w):
        a = float(aggregate_loss(jnp.asarray(elem), None if weights is
                                 None else jnp.asarray(weights)))
        b = float(aggregate_loss(
            jnp.asarray(elem), None if weights is None
            else jnp.asarray(weights), deterministic=True,
        ))
        assert abs(a - b) < 1e-4 * max(1.0, abs(a))
    # NaN poison propagates through the pairwise tree like the flat sum
    elem_bad = elem.copy()
    elem_bad[13] = np.nan
    assert not np.isfinite(
        float(aggregate_loss(jnp.asarray(elem_bad), deterministic=True))
    )


@pytest.mark.fast
def test_deterministic_loss_matches_flat_closely_and_exactly_repeats():
    from symbolicregression_jl_tpu.models.fitness import eval_loss_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )

    opts = make_options(**{k: v for k, v in KW.items()
                           if k not in ("verbosity", "progress",
                                        "runtests", "niterations")})
    X, y = make_data(n=100)
    keys = jax.random.split(jax.random.PRNGKey(0), 32)
    sizes = jax.random.randint(jax.random.PRNGKey(1), (32,), 3, 8)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 3, opts.operators, opts.max_len
        )
    )(keys, sizes)
    args = (trees, jnp.asarray(X), jnp.asarray(y), None, opts.operators,
            opts.elementwise_loss)
    flat = np.asarray(eval_loss_trees(*args, backend="jnp"))
    det1 = np.asarray(
        eval_loss_trees(*args, backend="jnp", deterministic=True)
    )
    det2 = np.asarray(
        eval_loss_trees(*args, backend="jnp", deterministic=True)
    )
    np.testing.assert_array_equal(det1, det2)
    fin = np.isfinite(flat)
    np.testing.assert_array_equal(fin, np.isfinite(det1))
    np.testing.assert_allclose(det1[fin], flat[fin], rtol=1e-5)


# ---------------------------------------------------------------------------
# validate_dataset — the census
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_validate_clean_dataset():
    X, y = make_data()
    d = validate_dataset(X, y)
    assert d.ok and not d.warnings
    assert d.n_rows == 64 and d.n_features == 3 and d.n_outputs == 1
    assert d.bad_rows == 0 and d.to_dict()["bad_rows"] == 0


@pytest.mark.fast
def test_validate_nonfinite_census():
    X, y = make_data()
    X[0, 3] = np.nan
    X[1, 3] = np.inf  # same row: counted once in bad_rows
    y[10] = np.nan
    w = np.ones(64, np.float32)
    w[20] = np.inf
    d = validate_dataset(X, y, w)
    assert d.nonfinite_x_cells == 2
    assert d.nonfinite_y_cells == 1
    assert d.nonfinite_weight_cells == 1
    assert d.bad_rows == 3
    assert not d.ok and len(d.errors) == 3


@pytest.mark.fast
def test_validate_warnings_never_errors():
    X, y = make_data()
    X[2, :] = 7.0                      # degenerate (constant) feature
    X[0, 0] = SCALE_HAZARD_ABS * 10    # scale hazard
    yc = np.full_like(y, 1.5)          # constant target
    d = validate_dataset(X, yc)
    assert d.ok
    assert d.constant_y_outputs == [0]
    assert 2 in d.degenerate_features
    assert d.scale_hazard_features == [0]
    assert len(d.warnings) == 3
    # negative weights are an error (undefined weighted mean)
    w = np.ones(64, np.float32)
    w[0] = -1.0
    d = validate_dataset(X, y, w)
    assert not d.ok and d.nonpositive_weights == 1


@pytest.mark.fast
def test_validate_multi_output():
    X, y = make_data()
    ys = np.stack([y, np.full_like(y, 2.0)])
    ys[0, 5] = np.nan
    d = validate_dataset(X, ys)
    assert d.n_outputs == 2
    assert d.constant_y_outputs == [1]
    assert d.bad_rows == 1


# ---------------------------------------------------------------------------
# sanitize_dataset — the three policies
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_sanitize_clean_passthrough_identity():
    X, y = make_data()
    w = np.ones(64, np.float32)
    for pol in ("reject", "mask", "repair"):
        X2, y2, w2, d = sanitize_dataset(X, y, w, pol)
        assert X2 is X and y2 is y and w2 is w, pol
        assert d.policy == pol and d.masked_rows == 0


@pytest.mark.fast
def test_sanitize_reject_raises_structured():
    X, y = make_data()
    X[0, 0] = np.nan
    with pytest.raises(HostileDatasetError) as ei:
        sanitize_dataset(X, y, None, "reject")
    assert isinstance(ei.value, ValueError)  # stays a ValueError
    assert isinstance(ei.value.diagnostics, DatasetDiagnostics)
    assert ei.value.diagnostics.bad_rows == 1
    assert "mask" in str(ei.value)  # names the way out


@pytest.mark.fast
def test_sanitize_mask_zeroes_and_placeholders():
    X, y = make_data()
    X[0, 3] = np.nan
    y[10] = np.inf
    Xm, ym, wm, d = sanitize_dataset(X, y, None, "mask")
    assert np.isfinite(Xm).all() and np.isfinite(ym).all()
    assert wm is not None and wm[3] == 0 and wm[10] == 0
    assert wm.sum() == 62 and d.masked_rows == 2
    # untouched rows keep their exact values
    keep = np.ones(64, bool)
    keep[[3, 10]] = False
    np.testing.assert_array_equal(Xm[:, keep], X[:, keep])
    np.testing.assert_array_equal(ym[keep], y[keep])


@pytest.mark.fast
def test_sanitize_repair_imputes_cells_keeps_rows_live():
    X, y = make_data()
    X[0, 3] = np.nan
    X[0, 4] = np.inf
    y[10] = np.nan
    Xr, yr, wr, d = sanitize_dataset(X, y, None, "repair")
    assert d.repaired_cells == 2 and d.masked_rows == 1
    # imputed with the column's finite mean
    col_mean = X[0][np.isfinite(X[0])].mean()
    assert abs(Xr[0, 3] - col_mean) < 1e-6
    # repaired rows keep full weight; only the bad-target row is masked
    assert wr[3] == 1 and wr[4] == 1 and wr[10] == 0


@pytest.mark.fast
def test_sanitize_unusable_raises_under_every_policy():
    # every column all-NaN: repair has nothing to impute FROM (imputing
    # would invent data wholesale), so every policy rejects
    X = np.full((2, 6), np.nan, np.float32)
    y = np.ones(6, np.float32)
    for pol in ("reject", "mask", "repair"):
        with pytest.raises(HostileDatasetError):
            sanitize_dataset(X, y, None, pol)
    # zero rows
    for pol in ("reject", "mask", "repair"):
        with pytest.raises(HostileDatasetError):
            sanitize_dataset(
                np.zeros((2, 0), np.float32), np.zeros(0, np.float32),
                None, pol,
            )


@pytest.mark.fast
def test_repair_recovers_every_row_bad_dataset():
    """Review regression: a dataset where EVERY row has one bad cell but
    every column still has finite values to impute from is fully
    repairable — 'no usable rows' must not be structural-fatal under
    repair (it is under mask: masking every row leaves nothing)."""
    X, y = make_data(n=12)
    for j in range(12):
        X[j % 3, j] = np.nan  # one bad cell per row, spread over columns
    Xr, yr, wr, d = sanitize_dataset(X, y, None, "repair")
    assert np.isfinite(Xr).all()
    assert d.repaired_cells == 12 and d.masked_rows == 0
    assert wr is None  # no row needed masking: weights untouched
    with pytest.raises(HostileDatasetError):
        sanitize_dataset(X, y, None, "mask")  # every row masked = unusable


@pytest.mark.fast
def test_wrong_shape_weights_structured_error():
    """Review regression: a wrong-length weights vector must come back
    as a structured HostileDatasetError, not a raw numpy broadcast
    ValueError from inside the census."""
    X, y = make_data(n=16)
    w = np.ones(5, np.float32)
    d = validate_dataset(X, y, w)
    assert not d.ok and any("weights shape" in e for e in d.errors)
    for pol in ("reject", "mask", "repair"):
        with pytest.raises(HostileDatasetError):
            sanitize_dataset(X, y, w, pol)


@pytest.mark.fast
def test_loss_function_incompatible_with_row_shards():
    with pytest.raises(ValueError, match="loss_function.*row_shards"):
        make_options(
            binary_operators=["+"], row_shards=2,
            loss_function=lambda t, X, y, w, o: 0.0,
        )


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_data_policy_option_validation():
    with pytest.raises(ValueError):
        make_options(binary_operators=["+"], data_policy="explode")
    for pol in ("reject", "mask", "repair"):
        assert make_options(
            binary_operators=["+"], data_policy=pol
        ).data_policy == pol


@pytest.mark.fast
def test_row_shards_rejects_pallas_backends():
    """The Pallas kernel's row reduction is not the pairwise tree, so
    the explicit kernel backends are unconstructible with row_shards>1
    (and 'auto' routing consults deterministic — the review fix that
    keeps the bit-identity contract true on TPU, not just on CPU)."""
    from symbolicregression_jl_tpu.models.fitness import (
        resolve_eval_backend_pallas,
    )

    with pytest.raises(ValueError, match="pallas.*row_shards|row_shards"):
        make_options(
            binary_operators=["+"], eval_backend="pallas", row_shards=2
        )
    with pytest.raises(ValueError, match="row_shards"):
        make_options(
            binary_operators=["+"], optimizer_backend="pallas",
            row_shards=2,
        )
    # the routing predicate itself: deterministic never routes to the
    # kernel, whatever the shape
    assert resolve_eval_backend_pallas(
        "auto", jnp.float32, 10**6, 10**6, deterministic=True
    ) is False


@pytest.mark.fast
def test_cast_overflow_diagnosed_not_misreported():
    """float64 data with finite values beyond float32 range must be
    diagnosed as a precision-cast overflow (rescale / use float64),
    never as phantom NaN/Inf in the caller's data."""
    X, y = make_data()
    X64 = X.astype(np.float64)
    X64[0, 0] = 1e40  # finite in f64, inf in f32
    with pytest.raises(HostileDatasetError) as ei:
        sr.equation_search(X64, y.astype(np.float64), seed=0, **KW)
    d = ei.value.diagnostics
    assert d.cast_overflow_cells == 1
    assert any("overflowed" in e for e in d.errors)
    # the same data under precision='float64' is clean (validated on
    # the lossless cast) — no search needed: validate directly
    d64 = validate_dataset(X64, y.astype(np.float64))
    assert d64.ok and d64.scale_hazard_features == [0]


@pytest.mark.fast
def test_row_shards_in_graph_key_data_policy_not():
    base = make_options(binary_operators=["+"])
    sharded = make_options(binary_operators=["+"], row_shards=2)
    masked = make_options(binary_operators=["+"], data_policy="mask")
    # row_shards selects a different scoring graph -> different key
    assert base != sharded and hash(base) != hash(sharded)
    # data_policy transforms data before any trace -> same key
    assert base == masked and hash(base) == hash(masked)


# ---------------------------------------------------------------------------
# search-level: policies on clean and hostile data
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_clean_data_bit_identical_across_policies():
    """Acceptance: data_policy='reject' (the default) is seed behavior on
    clean data, and 'mask' is bit-identical to it — the front door is a
    no-op when there is nothing to sanitize. Full searches: slow tier
    (the 870s tier-1 dot budget; the pass-through identity that makes
    this hold is asserted fast in
    test_sanitize_clean_passthrough_identity)."""
    X, y = make_data()
    rs = {
        p: sr.equation_search(X, y, seed=0, data_policy=p, **KW)
        for p in ("reject", "mask", "repair")
    }
    assert frontier(rs["reject"]) == frontier(rs["mask"])
    assert frontier(rs["reject"]) == frontier(rs["repair"])
    d = rs["mask"].dataset_diagnostics
    assert d is not None and d["policy"] == "mask" and d["masked_rows"] == 0


@pytest.mark.fast
def test_preflight_probe_skips_zero_weight_rows():
    """Regression (found by the verify drive): the pipeline probe used
    to slice the FIRST 20 rows blindly — under data_policy='mask' a
    leading block of bad rows becomes 20 zero-weight placeholder rows,
    the probe's weighted loss aggregates 0/0, every score is contained
    to inf, and a perfectly healthy configuration failed preflight. The
    probe must select usable (positively weighted) rows."""
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.utils.preflight import (
        test_entire_pipeline,
    )

    X, y = make_data(n=64)
    w = np.ones(64, np.float32)
    w[:30] = 0.0  # leading block excluded from the loss
    opts = make_options(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npop=16, npopulations=2, maxsize=8,
    )
    test_entire_pipeline(opts, X, y[None, :], w)  # must not raise


def test_reject_is_default_and_raises_on_hostile():
    X, y = make_data()
    X[1, 7] = np.nan
    with pytest.raises(HostileDatasetError):
        sr.equation_search(X, y, seed=0, **KW)


@pytest.mark.slow
def test_hostile_injection_never_crashes_never_nonfinite_hof():
    """Property test (acceptance): random NaN/Inf injection over 3 seeds
    — the search completes under mask AND repair and the hall of fame
    is finite every time. One Options graph serves all runs (same
    shapes), so this is 6 searches on one compile."""
    for seed in (0, 1, 2):
        rng = np.random.default_rng(100 + seed)
        X, y = make_data(seed=seed)
        # poison ~10% of X cells and a few targets, mixing NaN/Inf
        cells = rng.integers(0, X.size, size=X.size // 10)
        flat = X.reshape(-1)
        flat[cells] = np.where(
            rng.random(cells.size) < 0.5, np.nan, np.inf
        )
        y[rng.integers(0, y.size, size=3)] = -np.inf
        for pol in ("mask", "repair"):
            r = sr.equation_search(
                X, y, seed=seed, data_policy=pol, **KW
            )
            losses = [c.loss for c in r.frontier()]
            assert losses, (seed, pol)
            assert all(np.isfinite(l) for l in losses), (seed, pol)
            d = r.dataset_diagnostics
            assert d["policy"] == pol and (
                d["masked_rows"] > 0 or d["repaired_cells"] > 0
            )


@pytest.mark.slow
def test_hostile_search_populates_run_start_diagnostics(tmp_path):
    from symbolicregression_jl_tpu.telemetry.analyze import (
        analyze_run,
        resolve_log,
    )

    X, y = make_data()
    X[0, :4] = np.inf
    r = sr.equation_search(
        X, y, seed=0, data_policy="mask", telemetry=True,
        telemetry_dir=str(tmp_path), **KW
    )
    report = analyze_run(resolve_log(str(tmp_path)))
    diags = (report.get("run") or {}).get("dataset_diagnostics")
    assert diags is not None
    assert diags["policy"] == "mask" and diags["masked_rows"] == 4
    assert diags == r.dataset_diagnostics
    # the new containment gauges rode the fused reduction into the log
    assert report.get("nonfinite_fraction") is not None


# ---------------------------------------------------------------------------
# telemetry: schema evolution for the new fields + doctor/alert logic
# ---------------------------------------------------------------------------


def _envelope(**fields):
    return {"v": 1, "t": 0.0, "run": "r", **fields}


@pytest.mark.fast
def test_schema_accepts_new_run_start_and_metrics_fields():
    from symbolicregression_jl_tpu.telemetry.events import validate_event

    rs = _envelope(
        type="run_start", config_fingerprint="f", backend="cpu",
        devices=["cpu:0"], nout=1,
        dataset_diagnostics={
            "n_rows": 10, "n_features": 2, "bad_rows": 1,
            "policy": "mask", "masked_rows": 1, "repaired_cells": 0,
            "errors": [], "warnings": ["w"],
        },
    )
    assert validate_event(rs) == []
    # null diagnostics allowed (older writers)
    rs["dataset_diagnostics"] = None
    assert validate_event(rs) == []
    # wrong type rejected
    rs["dataset_diagnostics"] = "nope"
    assert validate_event(rs) != []

    m = _envelope(
        type="metrics",
        snapshot={
            "counters": {"contained_losses_total": 3.0},
            "gauges": {"population_nonfinite_fraction": 0.25},
            "histograms": {},
        },
        per_island={"best_loss": [1.0], "nonfinite": [4]},
    )
    assert validate_event(m) == []


@pytest.mark.fast
def test_run_doctor_numerically_degenerate_reason():
    from symbolicregression_jl_tpu.telemetry.analyze import analyze_run

    def metrics_event(nonfinite_frac, best):
        return _envelope(
            type="metrics", output=0, iteration=0,
            snapshot={
                "counters": {},
                "gauges": {
                    "best_loss": best,
                    "population_finite_frac": 1.0 - nonfinite_frac,
                    "population_nonfinite_fraction": nonfinite_frac,
                },
                "histograms": {},
            },
        )

    base = [
        _envelope(type="run_start", config_fingerprint="f",
                  backend="cpu", devices=["cpu:0"], nout=1),
        metrics_event(0.8, 1.0),
        _envelope(type="run_end", num_evals=1.0, search_time_s=1.0),
    ]
    report = analyze_run(base)
    assert report["numerically_degenerate"] is True
    assert report["nonfinite_fraction"] == pytest.approx(0.8)
    assert any("numerically-degenerate" in r for r in report["reasons"])
    # below the threshold: no flag
    ok = [base[0], metrics_event(0.1, 1.0), base[2]]
    report = analyze_run(ok)
    assert report["numerically_degenerate"] is False
    assert not any("numerically-degenerate" in r
                   for r in report["reasons"])


@pytest.mark.fast
def test_fleet_alert_numerically_degenerate():
    from symbolicregression_jl_tpu.telemetry.alerts import evaluate_alerts

    row = {
        "run_id": "r1", "verdict": "healthy", "faults": 0,
        "attempts": [], "resumed": False,
        "nonfinite_fraction": 0.7, "numerically_degenerate": True,
    }
    alerts = evaluate_alerts([row], {})
    hits = [a for a in alerts if a["rule"] == "numerically_degenerate"]
    assert len(hits) == 1 and hits[0]["severity"] == "warning"
    # ctx threshold override
    assert not [
        a for a in evaluate_alerts(
            [dict(row, numerically_degenerate=False,
                  nonfinite_fraction=0.2)],
            {"nonfinite_threshold": 0.5},
        )
        if a["rule"] == "numerically_degenerate"
    ]
    hits = [
        a for a in evaluate_alerts(
            [dict(row, numerically_degenerate=False,
                  nonfinite_fraction=0.6)],
            {"nonfinite_threshold": 0.5},
        )
        if a["rule"] == "numerically_degenerate"
    ]
    assert len(hits) == 1 and hits[0]["threshold"] == 0.5
