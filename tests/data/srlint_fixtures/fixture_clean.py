"""srlint fixture: idiomatic jitted code that must produce ZERO findings
(precision guard for the linter's heuristics).

Never imported — parsed by tests/test_analysis.py only."""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("reps",))
def scan_step(x, reps: int = 4):
    idx = jnp.arange(x.shape[0], dtype=jnp.int32)

    def body(carry, i):
        carry = carry + jnp.where(i % 2 == 0, 1.0, -1.0)
        return carry, carry

    init = jnp.zeros((), jnp.float32)
    out, ys = lax.scan(body, init, idx)
    sel = lax.cond(reps > 2, lambda: ys * 2.0, lambda: ys)
    if x.ndim > 1:  # static rank check: fine
        sel = sel[:, None] * x
    return sel


def helper(y):
    # reachable from scan_step? no — host helper using host numpy is fine
    import numpy as np

    return np.asarray(y).item()
