"""srlint fixture: SR006 jit entries that rebuild and return a carry
without donating its buffers.

Never imported — parsed by tests/test_analysis.py only. Expected: 3
SR006 findings (the plain wrap, the bare decorator, and the aliased
return); the donating wrappers, the non-carry function, and the
static_argnames parameter stay clean."""

import functools

import jax


def step(state, dx):
    state = state + dx
    return state


fast_step = jax.jit(step)  # SR006: carry rebuilt+returned, no donation
donated = jax.jit(step, donate_argnums=(0,))  # not flagged
named = jax.jit(step, donate_argnames="state")  # not flagged


@jax.jit  # SR006: bare decorator cannot donate at all
def dec_step(carry, dx):
    carry = carry * dx
    return carry


@functools.partial(jax.jit, donate_argnums=(0,))
def dec_donated(carry, dx):  # not flagged
    carry = carry * dx
    return carry


def aliased(state, key, dx):
    state = state + dx
    outs = (state, key)
    return outs


packed = jax.jit(aliased)  # SR006: carry reachable through the alias


def pure(x, scale):
    y = x * scale
    return y


fn = jax.jit(pure)  # not flagged: no parameter is rebuilt


def tiled(x, block: int = 8):
    block = max(block, 1)
    return x, block


# not flagged: the rebuilt-and-returned parameter is static, not a carry
cfg = jax.jit(tiled, static_argnames=("block",))
