"""srlint fixture: SR007 broadcast materializations in jit-reachable
code.

Never imported — parsed by tests/test_analysis.py only. Expected: 3
SR007 findings (broadcast_to, outer, tile with a literal factor >= 8);
the small literal repeat, the non-literal tile, and the host-side
helper stay clean."""

import jax
import jax.numpy as jnp


@jax.jit
def hot(x, y, n):
    a = jnp.broadcast_to(x, (1024, 1024))  # SR007
    b = jnp.outer(x, y)  # SR007
    c = jnp.tile(x, 16)  # SR007 (literal factor >= 8)
    d = jnp.repeat(x, 2)  # not flagged: small literal factor
    e = jnp.tile(x, (n, 1))  # not flagged: non-literal factor
    return a.sum() + b.sum() + c.sum() + d.sum() + e.sum()


def host_only(x):
    # identical call, not jit-reachable: not flagged
    return jnp.broadcast_to(x, (1024, 1024))
