"""srlint fixture: pragma suppression.

Never imported — parsed by tests/test_analysis.py only."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def with_pragmas(x):
    # suppressed: justified static-table conversion
    table = np.asarray([1.0, 2.0])  # srlint: disable=SR001 -- static table
    buf = jnp.zeros((4,))  # srlint: disable=SR004 -- weak-type on purpose
    wrong = np.asarray(x)  # srlint: disable=SR004 -- wrong rule id: stays
    return jnp.sum(buf) + table[0] + jnp.sum(wrong)


@jax.jit
def multi_rule(d):
    out = jnp.arange(  # srlint: disable=SR004,SR003 -- multi-id spelling
        4
    )
    return out
