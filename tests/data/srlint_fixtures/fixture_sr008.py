"""srlint fixture: SR008 host round-trips fed straight back into jitted
entry points.

Never imported — parsed by tests/test_analysis.py only. Expected: 2
SR008 findings in drive() (tainted name, inline round-trip); fine()
stays clean (device value stays on device; the synced value is consumed
on the host, never fed back) and so does retainted() (reassignment from
a non-sync value kills the taint)."""

import jax
import numpy as np


@jax.jit
def step(x):
    return x * 2


def drive(x):
    h = np.asarray(x)  # pulls the device value to the host...
    y = step(h)  # SR008: ...and feeds it straight back into jit
    z = step(np.asarray(y))  # SR008: inline round-trip
    return y, z


def fine(x):
    y = step(x)  # device value straight into jit: not flagged
    total = float(np.asarray(y).sum())  # sync consumed on host: fine
    return total


def retainted(x, batch):
    v = np.asarray(x)  # taints v...
    print(v.sum())
    v = batch  # ...reassignment from a non-sync value kills the taint
    return step(v)  # not flagged: v holds a device value again
