"""srlint fixture: SR001 host-sync calls reachable from jitted code.

Never imported — parsed by tests/test_analysis.py only."""

import jax
import jax.numpy as jnp
import numpy as np


def _inner(x):
    # reachable through step() below: both must be flagged
    host = np.asarray(x)  # SR001 (np.asarray)
    return jnp.sum(host)


def step(x):
    y = _inner(x) + 1.0
    jax.block_until_ready(y)  # SR001 (module call form)
    return y.item()  # SR001 (method form)


step_jit = jax.jit(step)


def host_only(x):
    # NOT jit-reachable: identical calls must NOT be flagged
    return np.asarray(x).item()
