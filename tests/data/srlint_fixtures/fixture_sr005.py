"""srlint fixture: SR005 static_argnames naming nonexistent parameters.

Never imported — parsed by tests/test_analysis.py only."""

import functools

import jax


def kernel(x, block_size: int = 8):
    return x * block_size


bad = jax.jit(kernel, static_argnames=("block_sz",))  # SR005 (typo)
good = jax.jit(kernel, static_argnames=("block_size",))  # not flagged
multi = jax.jit(  # SR005 (one of two stale)
    kernel, static_argnames=("block_size", "tile")
)


@functools.partial(jax.jit, static_argnames=("mode",))
def dispatch(x, mode: str = "fast"):  # decorator form: not flagged
    return x


@functools.partial(jax.jit, static_argnames=("modes",))  # SR005
def dispatch2(x, mode: str = "fast"):
    return x


def flexible(x, **kwargs):
    return x


# **kwargs can absorb any name: not checked
flex = jax.jit(flexible, static_argnames=("anything",))
