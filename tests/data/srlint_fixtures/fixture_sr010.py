"""SR010 fixture: orchestration-classified options fields read inside
jit-reachable code. Parsed by the linter, never imported — the fixture
declares its own ORCHESTRATION_FIELDS vocabulary, exactly like
models/options.py declares the real one."""

import jax
import jax.numpy as jnp

ORCHESTRATION_FIELDS = (
    "seed",
    "verbosity",
    "snapshot_path",
)


@jax.jit
def bad_seed_read(x, options):
    # VIOLATION SR010: a host-side knob read inside a traced body —
    # the first caller's seed is baked into the shared compiled graph
    return x + options.seed


def _inner(x, opts):
    # VIOLATION SR010 (reachable through traced_caller below); the
    # `opts` receiver spelling is covered too
    return x * opts.verbosity


@jax.jit
def traced_caller(x, opts):
    return _inner(x, opts)


@jax.jit
def bad_attr_receiver(x, state):
    # VIOLATION SR010: receiver resolved through an attribute chain
    # ending in an options-ish name
    return x + state.run_options.seed


@jax.jit
def good_graph_read(x, options):
    # OK: maxsize is not orchestration-classified
    return x[: options.maxsize]


@jax.jit
def good_other_receiver(x, args):
    # OK: `args.seed` is some other object, not an Options
    return x + args.seed


@jax.jit
def pragma_suppressed(x, options):
    return x + options.seed  # srlint: disable=SR010 -- fixture pragma


def host_only(x, options):
    # OK: not jit-reachable — the host loop is where these belong
    if options.verbosity > 0:
        print("host", options.snapshot_path)
    return jnp.asarray(x)
