"""srlint fixture: SR004 implicit dtypes in hot-path buffer constructors.

Never imported — parsed by tests/test_analysis.py only. The fixture file
name starts with ``fixture_`` which the linter treats as a hot-path
prefix, so SR004 applies here module-wide (no jit root needed)."""

import jax.numpy as jnp


def make_buffers(n):
    a = jnp.zeros((n,))  # SR004
    b = jnp.ones((n, 2))  # SR004
    c = jnp.full((n,), 3.5)  # SR004
    d = jnp.arange(n)  # SR004
    e = jnp.zeros((n,), jnp.float32)  # positional dtype: not flagged
    f = jnp.full((n,), 3.5, dtype=jnp.float32)  # kwarg dtype: not flagged
    g = jnp.arange(n, dtype=jnp.int32)  # not flagged
    h = jnp.zeros_like(e)  # inherits dtype: not flagged
    return a, b, c, d, e, f, g, h
