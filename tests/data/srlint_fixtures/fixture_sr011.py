"""SR011 fixture: id() of a callable inside hash/key/fingerprint/memo
computations. Parsed by the linter, never imported. SR011 applies to
HOST code too (keys are computed on the host) — none of these functions
needs to be jit-reachable to be flagged."""


def graph_key(options):
    # VIOLATION SR011: id() is reused after GC — two distinct losses
    # can alias one warm-compile bucket
    return (options.maxsize, id(options.loss))


def dataset_fingerprint(loss):
    # VIOLATION SR011: fingerprint keyed on a reusable id
    return f"callable:{id(loss)}"


class Bank:
    def _memo_slot(self, fn):
        # VIOLATION SR011: method form, "memo" in the qualname
        return id(fn) % 1024


def cache_hash(fn):
    # VIOLATION SR011: "hash" in the qualname
    return hash((id(fn), 7))


def good_token_key(options, callable_token):
    # OK: the process-lifetime token registry, not id()
    return (options.maxsize, callable_token(options.loss))


def ordinary_helper(fn):
    # OK: id() outside any key/hash/fingerprint/memo computation
    # (object-graph bookkeeping like lint.py's own FuncInfo index)
    return id(fn)


def shadowed_key(values):
    # OK: `id` here is a local variable, not the builtin
    def id(v):
        return v

    return id(values)


def pragma_key(fn):
    return id(fn)  # srlint: disable=SR011 -- fixture pragma
