"""SR009 fixture: jnp.where-after-NaN-producing-op (select on the
poisoned output instead of clamping the input). Parsed by the linter,
never imported."""

import jax
import jax.numpy as jnp


@jax.jit
def bad_log_branch(x):
    # VIOLATION SR009: log evaluates over x <= 0 lanes anyway
    return jnp.where(x > 0, jnp.log(x), 0.0)


@jax.jit
def bad_sqrt_branch(x):
    # VIOLATION SR009: sqrt of unclamped negative lanes
    return jnp.where(x >= 0, jnp.sqrt(x), x)


@jax.jit
def bad_division_branch(x, y):
    # VIOLATION SR009: x / y computes over y == 0 lanes
    return jnp.where(y != 0, x / y, 0.0)


@jax.jit
def bad_fractional_power(x):
    # VIOLATION SR009: x ** 0.5 is sqrt of an unclamped base
    return jnp.where(x > 0, x ** 0.5, 0.0)


@jax.jit
def good_clamped_log(x):
    # OK: the input is clamped into the domain (the safe_* pattern)
    return jnp.where(x > 0, jnp.log(jnp.where(x > 0, x, 1.0)), 0.0)


@jax.jit
def good_clamped_sqrt(x):
    # OK: maximum clamps the input
    return jnp.sqrt(jnp.maximum(x, 0.0))


@jax.jit
def good_clamped_division(x, y):
    # OK: the denominator is clamped
    return jnp.where(y != 0, x / jnp.where(y != 0, y, 1.0), 0.0)


@jax.jit
def good_integer_power(x):
    # OK: integer powers are total on floats
    return jnp.where(x > 1, x ** 2, x)


@jax.jit
def good_plain_select(x, y):
    # OK: no NaN-producing op in either branch
    return jnp.where(x > y, x, y)


@jax.jit
def pragma_suppressed(x):
    return jnp.where(x > 1, jnp.log(x), 0.0)  # srlint: disable=SR009 -- x > 1 proven by the caller's contract


def host_only_where(x):
    # not jit-reachable: SR009 does not apply
    return jnp.where(x > 0, jnp.log(x), 0.0)
