"""SR012 fixture: with_sharding_constraint / NamedSharding inside a
vmapped/scanned body referencing an outer mesh object. Parsed by the
linter, never imported. The batched bodies below are marked by the
jax.vmap / jax.lax.scan calls in driver(); helpers taking the mesh as a
PARAMETER (the migration.py pin_replicated pattern) stay clean."""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

MESH = object()  # stands in for a module-level jax.sharding.Mesh


def batched_body(x):
    # VIOLATION SR012: constraint inside a vmapped body naming the
    # outer mesh — the batched trace cannot see MESH's dims
    return jax.lax.with_sharding_constraint(
        x * 2, NamedSharding(MESH, P("islands"))
    )


def batched_named(x):
    # VIOLATION SR012: bare NamedSharding construction against the
    # outer mesh inside a vmapped body
    sharding = NamedSharding(MESH, P())
    return jax.device_put(x, sharding)


def scan_body(carry, x):
    # VIOLATION SR012: same rule through jax.lax.scan
    pinned = jax.lax.with_sharding_constraint(
        carry + x, NamedSharding(MESH, P())
    )
    return pinned, x


def _inner_helper(x):
    # VIOLATION SR012: not itself passed to vmap, but reachable from
    # batched_caller below — it still runs under the batching transform
    return jax.lax.with_sharding_constraint(x, NamedSharding(MESH, P()))


def batched_caller(x):
    return _inner_helper(x) + 1


def good_param_mesh(x, mesh):
    # OK: mesh is a parameter — the caller threads None under vmap
    # (api.py's inner_mesh rule), so the constraint never fires batched
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


def good_local_mesh(x):
    # OK: the mesh is built locally from the body's own data
    mesh = make_local_mesh()
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


def host_constrain(x):
    # OK: never vmapped/scanned — host-side placement is SR012-clean
    return jax.lax.with_sharding_constraint(x, NamedSharding(MESH, P()))


def pragma_body(x):
    return jax.lax.with_sharding_constraint(  # srlint: disable=SR012 -- fixture pragma
        x, NamedSharding(MESH, P())
    )


def make_local_mesh():
    return object()


def driver(xs, carry):
    a = jax.vmap(batched_body)(xs)
    b = jax.vmap(batched_named)(xs)
    c, _ = jax.lax.scan(scan_body, carry, xs)
    d = jax.vmap(batched_caller)(xs)
    e = jax.vmap(lambda x: good_param_mesh(x, None))(xs)
    f = jax.vmap(good_local_mesh)(xs)
    g = jax.vmap(pragma_body)(xs)
    return jnp.stack([a, b, c, d, e, f, g]), host_constrain(xs)
