"""srlint fixture: SR003 unsorted dict iteration in jit-reachable code.

Never imported — parsed by tests/test_analysis.py only."""

import jax
import jax.numpy as jnp


@jax.jit
def build(table):
    out = {}
    for k, v in table.items():  # SR003 (statement form)
        out[k] = v * 2.0
    doubled = {k: v + 1.0 for k, v in table.items()}  # SR003 (comprehension)
    ordered = {k: v for k, v in sorted(table.items())}  # sorted: not flagged
    return out, doubled, ordered


def host_side(table):
    # NOT jit-reachable: not flagged
    return [v for _, v in table.items()]
