"""srlint fixture: SR002 Python control flow / concretization on tracers.

Never imported — parsed by tests/test_analysis.py only."""

import jax
import jax.numpy as jnp


@jax.jit
def branchy(x):
    s = jnp.sum(x)
    if s > 0:  # SR002 (if on a traced value)
        x = x + 1.0
    while jnp.max(x) > 2.0:  # SR002 (while on a traced expression)
        x = x * 0.5
    return float(jnp.mean(x))  # SR002 (float() concretizes)


@jax.jit
def fine(x, flag: bool):
    if flag:  # static Python bool: not flagged
        x = x + 1.0
    if x is None:  # identity test: not flagged
        return jnp.zeros((3,), jnp.float32)
    n = x.shape[0]
    if n > 4:  # shape math is static: not flagged
        x = x[:4]
    return jnp.where(jnp.sum(x) > 0, x, -x)  # traced select: correct form
