"""Structural tree hashing (analog of reference test/test_hash.jl:
hash(tree) is content-based, insensitive to storage identity — here,
insensitive to padded-tail garbage in the flat encoding)."""

import jax
import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.trees import (
    encode_tree,
    parse_expression,
    stack_trees,
    tree_hash,
)
from symbolicregression_jl_tpu.ops.operators import make_operator_set

OPS = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])


def _t(s, max_len=20):
    return encode_tree(parse_expression(s, OPS), max_len)


def test_equal_programs_equal_hashes():
    assert tree_hash(_t("(x0 + 1.5) * cos(x1)")) == tree_hash(
        _t("(x0 + 1.5) * cos(x1)")
    )


def test_padding_garbage_ignored():
    a = _t("x0 + 1.0", max_len=8)
    b = _t("x0 + 1.0", max_len=8)
    # poison the padded tail of b: same program, different storage bytes
    b = b._replace(
        kind=b.kind.at[5:].set(4),
        op=b.op.at[5:].set(3),
        cval=b.cval.at[5:].set(99.0),
    )
    assert tree_hash(a) == tree_hash(b)


def test_dead_fields_ignored():
    """op on leaves and feat on consts are dead fields — not program
    content."""
    a = _t("x0 + 1.0", max_len=8)
    b = a._replace(op=a.op.at[0].set(3))  # x0 is VAR: op slot is dead
    assert tree_hash(a) == tree_hash(b)


def test_different_programs_differ():
    hs = {
        int(tree_hash(_t(s)))
        for s in [
            "x0 + 1.5",
            "x0 - 1.5",
            "x0 + 1.6",
            "x1 + 1.5",
            "cos(x0) + 1.5",
            "(x0 + 1.5) * x1",
        ]
    }
    assert len(hs) == 6


def test_batched_hashing():
    batch = stack_trees([_t("x0 + 1.0", 12), _t("cos(x1)", 12)])
    hs = tree_hash(batch)
    assert hs.shape == (2,)
    assert hs[0] != hs[1]
    assert hs[0] == tree_hash(_t("x0 + 1.0", 12))
