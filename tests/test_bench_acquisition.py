"""bench.py accelerator-acquisition state machine (VERDICT r3 #6).

All tunnel contact is mocked — these tests must be safe to run while the
watcher holds the single tunnel slot. The invariants under test:

- every attempt is bounded (probe/init timeouts <= 60 s constants);
- a successful probe + TPU init records tunnel_state='up';
- an init that silently lands on CPU (sitecustomize's 'axon,cpu' fallback
  when the tunnel drops between probe and init) is NEVER recorded as
  'up' — the process re-execs to continue the schedule;
- a fresh memo-up verdict skips the throwaway probe subprocess;
- the CPU-fallback re-entry classifies half-open (hang somewhere in the
  attempts) vs down (fast errors only) by exact result constants.
"""

import importlib
import sys
import types

import pytest

bench = importlib.import_module("bench")


class _Dev:
    def __init__(self, platform):
        self.platform = platform

    def __repr__(self):
        return f"<dev {self.platform}>"


class _Reexec(Exception):
    def __init__(self, resume_at):
        self.resume_at = resume_at


@pytest.fixture()
def acq(monkeypatch, tmp_path):
    """Fresh ACQUISITION + memo isolated to tmp; os.execve trapped."""
    monkeypatch.setattr(bench, "ACQUISITION",
                        {"attempts": [], "tunnel_state": "unknown"})
    monkeypatch.setattr(bench, "_MEMO_PATH", str(tmp_path / "memo.json"))
    monkeypatch.setattr(
        bench, "_reexec",
        lambda resume_at: (_ for _ in ()).throw(_Reexec(resume_at)),
    )
    monkeypatch.delenv("_SRTPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.delenv("_SRTPU_BENCH_RESUME_AT", raising=False)
    monkeypatch.delenv("_SRTPU_BENCH_ACQ", raising=False)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    return bench.ACQUISITION


def test_timeout_constants_bounded():
    # _PROBE_TIMEOUT honors SRTPU_BENCH_PROBE_TIMEOUT at import time, so
    # assert the DEFAULT (what ships) rather than the env-dependent
    # module constant — a developer running the suite with that env set
    # above 60 must not fail here spuriously.
    import os

    assert bench._PROBE_TIMEOUT_DEFAULT <= 60.0
    if "SRTPU_BENCH_PROBE_TIMEOUT" not in os.environ:
        assert bench._PROBE_TIMEOUT <= 60.0
    assert bench._INIT_TIMEOUT <= 60.0


def test_probe_ok_init_tpu_records_up(acq, monkeypatch):
    monkeypatch.setattr(bench, "_probe_tpu_subprocess",
                        lambda t: ("tpu", "ok"))
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: ([_Dev("tpu")], None))
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "tpu"
    assert acq["tunnel_state"] == "up"
    assert bench._read_memo() == "up"
    assert acq["attempts"][0]["result"] == "tpu"
    assert "init_s" in acq["attempts"][0]


def test_probe_ok_but_init_lands_on_cpu_is_not_up(acq, monkeypatch):
    """The review-caught hazard: TPU-positive probe, tunnel drops, init
    falls back to CPU without raising — must re-exec, never return the
    CPU devices as an 'up' capture. A stale 'up' memo (e.g. from a
    sibling moments before the drop) must be CLEARED on the way out so
    other suite children re-probe instead of burning an init timeout on
    the known-poisoned tunnel."""
    bench._write_memo("up")
    monkeypatch.setattr(bench, "_probe_tpu_subprocess",
                        lambda t: ("tpu", "ok"))
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: ([_Dev("cpu")], None))
    with pytest.raises(_Reexec) as ei:
        bench._devices_or_cpu_fallback(verbose=False)
    assert ei.value.resume_at == 0
    assert acq["tunnel_state"] != "up"
    assert bench._read_memo() is None
    assert acq["attempts"][0]["result"] == "probe-ok-cpu-fallback"


def test_probe_cpu_means_absent(acq, monkeypatch):
    monkeypatch.setattr(bench, "_probe_tpu_subprocess",
                        lambda t: ("cpu", "ok"))
    fake_jax = types.SimpleNamespace(
        config=types.SimpleNamespace(update=lambda *a: None),
        devices=lambda: [_Dev("cpu")],
    )
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "cpu"
    assert acq["tunnel_state"] == "absent"


def test_probe_hang_skips_zero_sleep_slot(acq, monkeypatch):
    """After a failed fast-path probe the loop must start at slot 1 (a
    zero-sleep identical re-probe learns nothing) — and a later good
    probe+init still succeeds."""
    calls = []

    def probe(t):
        calls.append("probe")
        return (None, "hang") if len(calls) == 1 else ("tpu", "ok")

    monkeypatch.setattr(bench, "_probe_tpu_subprocess", probe)
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: ([_Dev("tpu")], None))
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "tpu"
    # fast-path probe failed; the first loop attempt used slot 1's backoff
    assert acq["attempts"][0]["result"] == "probe-hang"
    assert acq["attempts"][1]["sleep_s"] == bench._PROBE_BACKOFFS[1]


def test_probe_ok_init_error_retries_init_without_reprobe(acq, monkeypatch):
    """A retryable init error after a good probe retries the init
    DIRECTLY — the tunnel answered seconds ago; a second throwaway probe
    subprocess would waste ~20 s of a chip window."""
    inits = []
    probes = []

    def init(t):
        inits.append(1)
        if len(inits) == 1:
            return None, "init-error: transient"
        return [_Dev("tpu")], None

    def probe(t):
        probes.append(1)
        return ("tpu", "ok")

    monkeypatch.setattr(bench, "_probe_tpu_subprocess", probe)
    monkeypatch.setattr(bench, "_init_backend_with_watchdog", init)
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "tpu"
    assert len(probes) == 1 and len(inits) == 2


def test_single_slot_schedule_still_gets_one_retry(acq, monkeypatch):
    """With a 1-element probe schedule, a failed fast-path probe must not
    skip the whole loop (that would mean zero retries and an immediate
    memo='down' CPU fallback)."""
    calls = []

    def probe(t):
        calls.append(1)
        return (None, "hang") if len(calls) == 1 else ("tpu", "ok")

    monkeypatch.setattr(bench, "_PROBE_BACKOFFS", (0,))
    monkeypatch.setattr(bench, "_probe_tpu_subprocess", probe)
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: ([_Dev("tpu")], None))
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "tpu"
    assert len(calls) == 2


def test_memo_up_skips_probe(acq, monkeypatch):
    bench._write_memo("up")
    monkeypatch.setattr(
        bench, "_probe_tpu_subprocess",
        lambda t: pytest.fail("memo-up must skip the probe subprocess"),
    )
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: ([_Dev("tpu")], None))
    devices = bench._devices_or_cpu_fallback(verbose=False, use_memo=True)
    assert devices[0].platform == "tpu"
    assert acq["attempts"][0]["result"] == "memo-up-tpu"
    assert acq["attempts"][0]["probe_s"] == 0.0


def test_memo_up_stale_tunnel_reexecs(acq, monkeypatch):
    bench._write_memo("up")
    monkeypatch.setattr(bench, "_init_backend_with_watchdog",
                        lambda t: (None, "init-hung"))
    with pytest.raises(_Reexec) as ei:
        bench._devices_or_cpu_fallback(verbose=False, use_memo=True)
    assert ei.value.resume_at == 0
    assert acq["attempts"][0]["result"] == "memo-up-init-hung"


def test_memo_down_goes_straight_to_fallback(acq, monkeypatch):
    bench._write_memo("down")
    monkeypatch.setattr(
        bench, "_fallback_to_cpu",
        lambda verbose: (_ for _ in ()).throw(SystemExit(0)),
    )
    with pytest.raises(SystemExit):
        bench._devices_or_cpu_fallback(verbose=False, use_memo=True)
    assert acq["attempts"][0]["result"] == "memo-down"


@pytest.mark.parametrize(
    "attempts,want",
    [
        ([{"result": "probe-hang"}], "half-open"),
        ([{"result": "probe-ok-init-hung"}], "half-open"),
        ([{"result": "memo-up-init-hung"}], "half-open"),
        ([{"result": "probe-error: channel hung up"}], "down"),
        ([{"result": "probe-error: connection refused"},
          {"result": "probe-error: connection refused"}], "down"),
    ],
)
def test_cpu_fallback_reentry_classifies_tunnel(acq, monkeypatch, attempts,
                                                want):
    """Half-open (something hangs) vs down (fast errors) keyed on exact
    recorder constants, never on free-form error text."""
    import json
    import os

    monkeypatch.setenv("_SRTPU_BENCH_CPU_FALLBACK", "1")
    monkeypatch.setenv("_SRTPU_BENCH_ACQ", json.dumps(
        {"attempts": attempts, "tunnel_state": "unknown"}
    ))
    fake_jax = types.SimpleNamespace(
        config=types.SimpleNamespace(update=lambda *a: None),
        devices=lambda: [_Dev("cpu")],
    )
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    devices = bench._devices_or_cpu_fallback(verbose=False)
    assert devices[0].platform == "cpu"
    assert bench.ACQUISITION["tunnel_state"] == want
