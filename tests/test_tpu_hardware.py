"""Real-TPU validation (skipped unless TPU hardware is reachable).

Run manually with the default env (JAX_PLATFORMS=axon) and the conftest
CPU pin disabled:
    SRTPU_TPU_TESTS=1 python -m pytest tests/test_tpu_hardware.py -q -m tpu

These duplicate interpret-mode coverage ON HARDWARE: Mosaic compilation
can diverge from interpret mode, so the compiled kernel gets its own
oracle comparison here."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _tpu_available():
    # tests/conftest.py pins jax_platforms=cpu for the main suite; this
    # module only makes sense in a separate process with the TPU env
    import jax

    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@pytest.fixture(scope="module")
def tpu_ready():
    if os.environ.get("JAX_PLATFORMS", "") == "cpu" or not _tpu_available():
        pytest.skip("no TPU reachable")


def test_compiled_kernel_matches_interpreter(tpu_ready):
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.operators import make_operator_set
    from symbolicregression_jl_tpu.ops.pallas_eval import eval_trees_pallas

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp", "sqrt", "log"])
    n, L = 1024, 24
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n,), 1, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 4, ops, L)
    )(jax.random.split(jax.random.PRNGKey(0), n), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (4, 1000), jnp.float32) * 2

    y_ref, ok_ref = jax.device_get(eval_trees(trees, X, ops))
    y, ok = jax.device_get(eval_trees_pallas(trees, X, ops))
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-4, atol=1e-4
    )


def test_compiled_kernel_variants_match(tpu_ready):
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.operators import make_operator_set
    from symbolicregression_jl_tpu.ops.pallas_eval import eval_trees_pallas

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])
    n, L = 512, 24
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n,), 1, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 3, ops, L)
    )(jax.random.split(jax.random.PRNGKey(0), n), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (3, 500), jnp.float32)

    y0, ok0 = jax.device_get(
        eval_trees_pallas(trees, X, ops, dispatch="chain", tree_unroll=1,
                          sort_trees=False)
    )
    for kw in (
        dict(dispatch="mux", tree_unroll=4, sort_trees=True),
        dict(dispatch="mux", tree_unroll=8, sort_trees=True),
    ):
        y, ok = jax.device_get(eval_trees_pallas(trees, X, ops, **kw))
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok0))
        m = np.asarray(ok0)
        np.testing.assert_allclose(
            np.asarray(y)[m], np.asarray(y0)[m], rtol=1e-5, atol=1e-5,
            err_msg=str(kw),
        )


def test_compiled_kernel_bf16_on_chip(tpu_ready):
    """Mosaic-compiled bf16-storage variant on real hardware.

    Two claims, separately checked (measured on v5e 2026-07-31):
    1. The compiled path matches interpret mode EXACTLY — same stores,
       same rounding — so Mosaic lowering introduces no drift.
    2. Against an INDEPENDENT bf16 evaluation — the lockstep jnp
       interpreter carrying bf16 values — the kernel agrees within a few
       bf16 ulps everywhere. Comparing against the f32 interpreter
       instead is unsound: storage rounding of an exp()/cos() argument
       amplifies (exp(x(1+eps))), so chaotic trees are >10% off in ANY
       faithful bf16 evaluation, and no input-perturbation filter can
       screen that (scale-invariant subtrees like x0/x3 cancel it).
    """
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.operators import make_operator_set
    from symbolicregression_jl_tpu.ops.pallas_eval import eval_trees_pallas

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])
    n, L = 1024, 24
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n,), 1, 12)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 4, ops, L)
    )(jax.random.split(jax.random.PRNGKey(0), n), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (4, 1000), jnp.float32)

    y_ref, ok_ref = jax.device_get(eval_trees(trees, X, ops))
    y, ok = jax.device_get(
        eval_trees_pallas(trees, X, ops, compute_dtype="bfloat16")
    )
    y_i, ok_i = jax.device_get(
        eval_trees_pallas(
            trees, X, ops, compute_dtype="bfloat16", interpret=True
        )
    )
    ok, ok_i, ok_ref = map(np.asarray, (ok, ok_i, ok_ref))
    # claim 1: compiled == interpret — ok-mask exactly; values within a
    # few bf16 ulps (bit-for-bit held on v5e 2026-07-31, but Mosaic's
    # transcendental lowering is not guaranteed identical to the
    # interpret path across libtpu/jaxlib versions, so the value check
    # tolerates 4 ulps of bf16 drift rather than pinning the toolchain)
    assert (ok == ok_i).all()
    a = np.asarray(y, np.float32)[ok]
    b = np.asarray(y_i, np.float32)[ok_i]
    np.testing.assert_allclose(a, b, rtol=2.0**-6, atol=1e-6)
    # sanity vs f32: the ok mask may only drift through bf16 overflow,
    # which must stay rare on this workload
    both = ok_ref & ok
    assert both.mean() > 0.5
    # claim 2: against the lockstep interpreter carrying bf16 values
    # (an independent code path with the same round-between-ops
    # semantics; measured CPU+v5e 2026-07-31: ok agreement 1.0, zero
    # elements outside 2%)
    y_o, ok_o = jax.device_get(
        eval_trees(trees, X.astype(jnp.bfloat16), ops)
    )
    y_o = np.asarray(y_o, dtype=np.float32)
    ok_o = np.asarray(ok_o)
    assert (ok == ok_o).mean() > 0.99
    m = ok & ok_o
    d = np.abs(np.asarray(y)[m] - y_o[m])
    assert (
        (d <= 0.02 + 0.02 * np.abs(y_o[m])).mean() > 0.999
    ), "bf16 kernel drifts from the independent bf16 interpreter"


def test_compiled_instr_program_on_chip(tpu_ready):
    """The compressed instruction program, Mosaic-compiled, must match the
    jnp interpreter on hardware (its interpret-mode parity lives in
    test_pallas_eval.py; Mosaic can diverge from interpret mode)."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.operators import make_operator_set
    from symbolicregression_jl_tpu.ops.pallas_eval import eval_trees_pallas

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp", "sqrt", "log"])
    n, L = 1024, 24
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n,), 1, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 4, ops, L)
    )(jax.random.split(jax.random.PRNGKey(0), n), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (4, 1000), jnp.float32) * 2

    y_ref, ok_ref = jax.device_get(eval_trees(trees, X, ops))
    for program, unroll in (
        ("instr", 4), ("instr", 16),
        ("instr_packed", 4), ("instr_packed", 8),
    ):
        y, ok = jax.device_get(
            eval_trees_pallas(trees, X, ops, program=program,
                              tree_unroll=unroll)
        )
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
        m = np.asarray(ok_ref)
        np.testing.assert_allclose(
            np.asarray(y)[m], np.asarray(y_ref)[m], rtol=1e-4, atol=1e-4,
            err_msg=f"{program} tree_unroll={unroll}",
        )


def test_compiled_grad_kernel_on_chip(tpu_ready):
    """The fused loss+grad kernel, Mosaic-compiled, must reproduce the
    interpret-mode results that tests/test_pallas_grad.py pins against
    the autodiff and finite-difference oracles."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.operators import make_operator_set
    from symbolicregression_jl_tpu.ops.pallas_grad import (
        eval_loss_grad_pallas,
    )

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp", "sqrt"])
    n, L = 512, 24
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n,), 1, 18)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, 3, ops, L)
    )(jax.random.split(jax.random.PRNGKey(0), n), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (3, 500), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(3), (500,), jnp.float32)

    loss, grad, ok = jax.device_get(
        eval_loss_grad_pallas(trees, X, y, None, ops)
    )
    y_ref, ok_ref = jax.device_get(eval_trees(trees, X, ops))
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    # losses match direct scoring on the ok trees. Reference MSE in
    # float64: poisoned rows carry f32 values whose square overflows to
    # inf with a RuntimeWarning. Rows that are entirely NaN (dead trees)
    # are skipped rather than fed to nanmean (mean-of-empty-slice
    # warning); they are outside the ok mask anyway.
    sq = (
        np.asarray(y_ref, np.float64)
        - np.asarray(jax.device_get(y), np.float64)[None, :]
    ) ** 2
    mse = np.full(sq.shape[0], np.nan)
    rows = ~np.all(np.isnan(sq), axis=-1)
    mse[rows] = np.nanmean(sq[rows], axis=-1)
    m = np.asarray(ok_ref)
    np.testing.assert_allclose(
        np.asarray(loss)[m], mse[m], rtol=1e-4, atol=1e-5
    )
    # spot-check gradients by f32 central differences on a few trees
    h = 1e-3
    checked = 0
    kind = np.asarray(jax.device_get(trees.kind))
    for i in np.flatnonzero(m)[:8]:
        slots = np.flatnonzero(kind[i] == 1)
        if not len(slots):
            continue
        s = int(slots[0])
        cv = np.asarray(jax.device_get(trees.cval))

        def loss_at(c):
            cv2 = cv.copy()
            cv2[i, s] = c
            t2 = trees._replace(cval=jnp.asarray(cv2))
            l2, _, _ = jax.device_get(
                eval_loss_grad_pallas(t2, X, y, None, ops)
            )
            return float(np.asarray(l2)[i])

        c0 = float(cv[i, s])
        d = max(abs(c0) * h, h)
        fd = (loss_at(c0 + d) - loss_at(c0 - d)) / (2 * d)
        g = float(np.asarray(grad)[i, s])
        if abs(fd) > 1e-3 and np.isfinite(fd):
            np.testing.assert_allclose(g, fd, rtol=0.05, atol=1e-2,
                                       err_msg=f"tree {i} slot {s}")
            checked += 1
    assert checked >= 3


def test_search_step_on_chip(tpu_ready):
    """A full jitted evolution iteration — mutations, scoring, constant
    optimization, hall-of-fame merge, migration — compiles and runs ON
    the TPU backend and improves the hall of fame over two steps. The
    kernel tests above cover the scoring hot path; this covers the rest
    of the search graph (span-arithmetic tree surgery, tournament
    selection, annealing accepts) whose lowering the CPU suite only sees
    through the virtual-device mesh."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.api import (
        _make_init_fn,
        _make_iteration_fn,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos"],
        npop=16,
        npopulations=4,
        ncycles_per_iteration=20,
        maxsize=12,
    )
    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((3, 256)).astype(np.float32)
    y_h = (2.0 * np.cos(X_h[2]) + X_h[0] ** 2 - 2.0).astype(np.float32)
    X, y = jnp.asarray(X_h), jnp.asarray(y_h)
    baseline = jnp.float32(float(np.var(y_h)))

    init_fn = _make_init_fn(options, 3, False)
    scalars = options.traced_scalars()
    states = init_fn(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    it_fn = _make_iteration_fn(options, False)
    cm = jnp.int32(options.maxsize)

    states, hof1 = it_fn(
        states, jax.random.PRNGKey(1), cm, X, y, baseline, scalars
    )
    states, hof2 = it_fn(
        states, jax.random.PRNGKey(2), cm, X, y, baseline, scalars
    )

    exists1 = np.asarray(jax.device_get(hof1.exists))
    exists2 = np.asarray(jax.device_get(hof2.exists))
    losses1 = np.asarray(jax.device_get(hof1.losses))
    losses2 = np.asarray(jax.device_get(hof2.losses))
    assert exists1.any(), "hall of fame empty after first on-chip step"
    assert exists2.any(), "hall of fame empty after two on-chip steps"
    best1 = losses1[exists1].min()
    best2 = losses2[exists2].min()
    assert np.isfinite(best2)
    assert best2 <= best1 + 1e-7, (best1, best2)
