"""Precision sweeps (analog of the reference's Float16/32/64 type-parameter
tests, e.g. test/test_nan_detection.jl:5-47 and test_mixed.jl dtype axes)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import symbolicregression_jl_tpu as sr


def _tiny_search(precision):
    rng = np.random.default_rng(0)
    X = (rng.standard_normal((2, 40)) * 2).astype("f4")
    y = X[0] * X[0]
    return sr.equation_search(
        X, y, niterations=2, binary_operators=["+", "*"],
        npop=16, npopulations=2, ncycles_per_iteration=20,
        tournament_selection_n=6, precision=precision,
        verbosity=0, progress=False, maxsize=10, seed=0,
    )


@pytest.mark.parametrize("precision", ["float32", "bfloat16", "float16"])
def test_search_runs_at_precision(precision):
    res = _tiny_search(precision)
    tol = 1e-4 if precision == "float32" else 1e-2
    assert res.best_loss().loss < tol


def test_invalid_precision_rejected():
    with pytest.raises(ValueError):
        sr.make_options(binary_operators=["+"], precision="float8")


@pytest.mark.slow
def test_float64_in_subprocess():
    """x64 mode flips a global jax flag; run isolated."""
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import numpy as np\n"
        "import symbolicregression_jl_tpu as sr\n"
        "rng = np.random.default_rng(0)\n"
        "X = (rng.standard_normal((2, 40))*2).astype('f8'); y = X[0]*X[0]\n"
        "res = sr.equation_search(X, y, niterations=2,\n"
        "    binary_operators=['+','*'], npop=16, npopulations=2,\n"
        "    ncycles_per_iteration=20, tournament_selection_n=6,\n"
        "    precision='float64', verbosity=0, progress=False, maxsize=10)\n"
        "assert res.best_loss().loss < 1e-8, res.best_loss().loss\n"
        "print('OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=280, cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


def test_pallas_backend_rejects_float64():
    """eval_backend='pallas' must refuse non-f32/bf16 data instead of
    silently downcasting (the kernel computes in f32; VERDICT r2
    missing-1: the float64 trade-off must be loud)."""
    import jax.numpy as jnp
    import pytest

    from symbolicregression_jl_tpu.models.fitness import dispatch_eval
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.operators import make_operator_set

    import jax

    ops = make_operator_set(["+", "*"], [])
    trees = jax.vmap(
        lambda k: gen_random_tree_fixed_size(k, 5, 2, ops, 12)
    )(jax.random.split(jax.random.PRNGKey(0), 4))
    X = jnp.zeros((2, 8), jnp.float16)  # any non-f32/bf16 dtype
    with pytest.raises(ValueError, match="float32/bfloat16"):
        dispatch_eval(trees, X, ops, backend="pallas")
