"""Migration injects pool/HoF members into islands
(analog of reference test/test_migration.jl:17-22)."""

import jax
import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.evolve import init_island_state
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.population import update_hall_of_fame
from symbolicregression_jl_tpu.parallel.migration import (
    merge_hofs_across_islands,
    migrate,
)


def _states(options, nfeat=2, n_islands=3):
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((nfeat, 32)).astype(np.float32))
    y = X[0] * 2.0
    keys = jax.random.split(jax.random.PRNGKey(0), n_islands)
    states = jax.vmap(
        lambda k: init_island_state(k, options, nfeat, X, y, None, 1.0)
    )(keys)
    return states


def test_migrate_replaces_expected_fraction():
    options = make_options(
        binary_operators=["+", "*"], npop=64, npopulations=3,
        fraction_replaced=0.5, fraction_replaced_hof=0.0, topn=4,
    )
    states = _states(options)
    ghof = merge_hofs_across_islands(states.hof)
    before = np.asarray(states.pop.birth).copy()
    out = migrate(jax.random.PRNGKey(1), states, ghof, options)
    after = np.asarray(out.pop.birth)
    frac = float((before != after).mean())
    assert 0.3 < frac < 0.7  # ~Bernoulli(0.5)


def test_migrated_members_come_from_pool():
    options = make_options(
        binary_operators=["+", "*"], npop=16, npopulations=2,
        fraction_replaced=1.0, fraction_replaced_hof=0.0, topn=2,
    )
    states = _states(options, n_islands=2)
    ghof = merge_hofs_across_islands(states.hof)
    out = migrate(jax.random.PRNGKey(2), states, ghof, options)
    # with fraction 1.0 every member must be one of the 2*topn pool members
    pool_scores = []
    for i in range(2):
        order = np.argsort(np.asarray(states.pop.scores[i]))[:2]
        pool_scores.extend(np.asarray(states.pop.scores[i])[order].tolist())
    pool_scores = np.asarray([s for s in pool_scores if np.isfinite(s)])
    new_scores = np.asarray(out.pop.scores).ravel()
    finite = new_scores[np.isfinite(new_scores)]
    dists = np.abs(finite[:, None] - pool_scores[None, :])
    assert np.all(dists.min(axis=1) < 1e-5)


def test_hof_migration_injects_frontier_members():
    options = make_options(
        binary_operators=["+", "*"], npop=16, npopulations=2,
        fraction_replaced=0.0, fraction_replaced_hof=1.0,
    )
    states = _states(options, n_islands=2)
    hofs = jax.vmap(
        lambda h, t, s, l: update_hall_of_fame(h, t, s, l, options)
    )(states.hof, states.pop.trees, states.pop.scores, states.pop.losses)
    states = states._replace(hof=hofs)
    ghof = merge_hofs_across_islands(states.hof)
    assert bool(np.asarray(ghof.exists).any())
    out = migrate(jax.random.PRNGKey(3), states, ghof, options)
    hof_losses = np.asarray(ghof.losses)[np.asarray(ghof.exists)]
    new_losses = np.asarray(out.pop.losses).ravel()
    # every replaced slot carries a frontier loss value
    dists = np.abs(new_losses[:, None] - hof_losses[None, :])
    assert np.all(dists.min(axis=1) < 1e-5)


def test_migration_disabled_is_identity():
    options = make_options(
        binary_operators=["+", "*"], npop=8, npopulations=2, migration=False,
        tournament_selection_n=4,
    )
    states = _states(options, n_islands=2)
    ghof = merge_hofs_across_islands(states.hof)
    out = migrate(jax.random.PRNGKey(4), states, ghof, options)
    np.testing.assert_array_equal(
        np.asarray(out.pop.birth), np.asarray(states.pop.birth)
    )
