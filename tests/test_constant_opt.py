"""Constant optimization recovers exact constants
(parity: reference test/test_optimizer_mutation.jl:29-41 — recovers
sin(2.1x+0.8)-style constants)."""

import jax
import jax.numpy as jnp
import numpy as np

from symbolicregression_jl_tpu.models.constant_opt import (
    _bfgs_single,
    _member_loss_fn,
    optimize_constants_population,
)
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.population import Population
from symbolicregression_jl_tpu.models.trees import Expr, encode_tree, stack_trees


def test_bfgs_recovers_constants(rng):
    """Fit c0*cos(x0) + c1 to 2.5*cos(x0) - 1.3."""
    opt = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    e = Expr.binary(
        plus,
        Expr.binary(mult, Expr.const(1.0), Expr.unary(cos, Expr.var(0))),
        Expr.const(0.0),
    )
    tree = encode_tree(e, opt.max_len)
    X = rng.standard_normal((1, 60)).astype(np.float32)
    y = 2.5 * np.cos(X[0]) - 1.3
    f = _member_loss_fn(tree, jnp.asarray(X), jnp.asarray(y), None, opt)
    idx = jnp.arange(opt.max_len)
    cmask = ((tree.kind == 1) & (idx < tree.length)).astype(jnp.float32)
    x, loss = jax.jit(lambda: _bfgs_single(f, tree.cval, cmask, 20))()
    assert float(loss) < 1e-6
    consts = np.asarray(x)[np.asarray(cmask) > 0]
    np.testing.assert_allclose(sorted(consts), [-1.3, 2.5], atol=1e-3)


def test_population_optimize(rng):
    opt = make_options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        maxsize=10,
        optimizer_probability=1.0,
        optimizer_iterations=15,
        optimizer_nrestarts=1,
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    X = rng.standard_normal((1, 50)).astype(np.float32)
    y = 2.0 * np.cos(X[0]) + 0.5

    def member(c0, c1):
        return encode_tree(
            Expr.binary(
                plus,
                Expr.binary(mult, Expr.const(c0), Expr.unary(cos, Expr.var(0))),
                Expr.const(c1),
            ),
            opt.max_len,
        )

    trees = stack_trees([member(1.0, 0.0), member(-1.0, 2.0), member(0.3, 0.3)])
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    from symbolicregression_jl_tpu.models.fitness import score_trees

    scores, losses = score_trees(trees, Xj, yj, None, 1.0, opt)
    pop = Population(
        trees=trees, scores=scores, losses=losses,
        birth=jnp.arange(3, dtype=jnp.int32),
    )
    pop2, n_evals, _ = jax.jit(
        lambda p: optimize_constants_population(
            jax.random.PRNGKey(0), p, Xj, yj, None, 1.0, opt
        )
    )(pop)
    assert float(n_evals) > 0
    # every member should now fit nearly exactly
    assert np.asarray(pop2.losses).max() < 1e-4
    # losses never get worse
    assert bool(np.all(np.asarray(pop2.losses) <= np.asarray(pop.losses) + 1e-7))


def test_optimize_skips_constant_free_members(rng):
    opt = make_options(
        binary_operators=["+", "*"], maxsize=10, optimizer_probability=1.0
    )
    e = Expr.binary(0, Expr.var(0), Expr.var(0))
    trees = stack_trees([encode_tree(e, opt.max_len)])
    X = rng.standard_normal((1, 20)).astype(np.float32)
    y = X[0] * 2
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    from symbolicregression_jl_tpu.models.fitness import score_trees

    scores, losses = score_trees(trees, Xj, yj, None, 1.0, opt)
    pop = Population(
        trees=trees, scores=scores, losses=losses,
        birth=jnp.zeros(1, jnp.int32),
    )
    pop2, _, _ = optimize_constants_population(
        jax.random.PRNGKey(0), pop, Xj, yj, None, 1.0, opt
    )
    np.testing.assert_array_equal(
        np.asarray(pop.trees.cval), np.asarray(pop2.trees.cval)
    )


def _fit_single(optimizer_fn, n_iters, rng):
    """Fit c0*cos(x0) + c1 to 2.5*cos(x0) - 1.3 with the given optimizer."""
    opt = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    e = Expr.binary(
        plus,
        Expr.binary(mult, Expr.const(1.0), Expr.unary(cos, Expr.var(0))),
        Expr.const(0.0),
    )
    tree = encode_tree(e, opt.max_len)
    X = rng.standard_normal((1, 60)).astype(np.float32)
    y = 2.5 * np.cos(X[0]) - 1.3
    f = _member_loss_fn(tree, jnp.asarray(X), jnp.asarray(y), None, opt)
    idx = jnp.arange(opt.max_len)
    cmask = ((tree.kind == 1) & (idx < tree.length)).astype(jnp.float32)
    x, loss = jax.jit(
        lambda: optimizer_fn(f, tree.cval, cmask, n_iters)
    )()
    return np.asarray(x)[np.asarray(cmask) > 0], float(loss)


def test_nelder_mead_recovers_constants(rng):
    from symbolicregression_jl_tpu.models.constant_opt import (
        _nelder_mead_single,
    )

    consts, loss = _fit_single(_nelder_mead_single, 40, rng)
    assert loss < 1e-4
    np.testing.assert_allclose(sorted(consts), [-1.3, 2.5], atol=1e-2)


def test_newton_recovers_constants(rng):
    from symbolicregression_jl_tpu.models.constant_opt import _newton_single

    # Jacobi-preconditioned steps converge linearly on coupled constants —
    # 1e-4 in 30 iterations is the expected envelope (exact Newton only for
    # single-constant trees)
    consts, loss = _fit_single(_newton_single, 30, rng)
    assert loss < 1e-4
    np.testing.assert_allclose(sorted(consts), [-1.3, 2.5], atol=3e-2)


def test_population_optimize_nelder_mead(rng):
    opt = make_options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        maxsize=10,
        optimizer_algorithm="NelderMead",
        optimizer_probability=1.0,
        optimizer_iterations=30,
        optimizer_nrestarts=1,
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    X = rng.standard_normal((1, 50)).astype(np.float32)
    y = 2.0 * np.cos(X[0]) + 0.5
    e = Expr.binary(
        plus,
        Expr.binary(mult, Expr.const(1.5), Expr.unary(cos, Expr.var(0))),
        Expr.const(0.1),
    )
    trees = stack_trees([encode_tree(e, opt.max_len)] * 4)
    pop = Population(
        trees=jax.tree_util.tree_map(jnp.asarray, trees),
        scores=jnp.full((4,), 1e9, jnp.float32),
        losses=jnp.full((4,), 1e9, jnp.float32),
        birth=jnp.zeros((4,), jnp.int32),
    )
    pop2, n_evals, _ = optimize_constants_population(
        jax.random.PRNGKey(0), pop, jnp.asarray(X), jnp.asarray(y), None,
        1.0, opt,
    )
    assert float(jnp.min(pop2.losses)) < 1e-3
    assert float(n_evals) > 0


def test_unknown_optimizer_rejected(rng):
    opt = make_options(optimizer_algorithm="LBFGSB")
    X = jnp.ones((1, 10), jnp.float32)
    pop = Population(
        trees=jax.tree_util.tree_map(
            jnp.asarray, stack_trees([encode_tree(Expr.const(1.0), opt.max_len)] * 2)
        ),
        scores=jnp.ones((2,), jnp.float32),
        losses=jnp.ones((2,), jnp.float32),
        birth=jnp.zeros((2,), jnp.int32),
    )
    import pytest

    with pytest.raises(ValueError, match="optimizer_algorithm"):
        optimize_constants_population(
            jax.random.PRNGKey(0), pop, X, X[0], None, 1.0, opt
        )


def test_bfgs_batched_matches_vmapped(rng, monkeypatch):
    """The fused-kernel batched BFGS (optimizer_backend='pallas', interpret
    mode here) recovers the same constants as the vmapped-interpreter
    path on the same starts."""
    import symbolicregression_jl_tpu.models.constant_opt as co

    opt = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        optimizer_probability=1.0, optimizer_iterations=12,
        optimizer_nrestarts=0, optimizer_backend="pallas",
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    X = rng.standard_normal((1, 40)).astype(np.float32)
    y = 2.0 * np.cos(X[0]) + 0.5

    def member(c0, c1):
        return encode_tree(
            Expr.binary(
                plus,
                Expr.binary(
                    mult, Expr.const(c0), Expr.unary(cos, Expr.var(0))
                ),
                Expr.const(c1),
            ),
            opt.max_len,
        )

    trees = stack_trees([member(1.0, 0.0), member(-0.5, 1.5),
                         member(3.0, -1.0), member(0.2, 0.2)])
    pop = Population(
        trees=jax.tree_util.tree_map(jnp.asarray, trees),
        scores=jnp.full((4,), 1e9, jnp.float32),
        losses=jnp.full((4,), 1e9, jnp.float32),
        birth=jnp.zeros((4,), jnp.int32),
    )
    monkeypatch.setattr(co, "_FORCE_INTERPRET", True)
    pop_p, n_evals, n_att = optimize_constants_population(
        jax.random.PRNGKey(0), pop, jnp.asarray(X), jnp.asarray(y), None,
        1.0, opt,
    )
    # every member should land on c0=2.0, c1=0.5 (convex in constants)
    assert float(jnp.max(pop_p.losses)) < 1e-4
    assert int(n_att) == 4
    # and the jnp path agrees on the fit quality
    opt_j = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"],
        maxsize=10, optimizer_probability=1.0, optimizer_iterations=12,
        optimizer_nrestarts=0, optimizer_backend="jnp",
    )
    pop_j, _, _ = optimize_constants_population(
        jax.random.PRNGKey(0), pop, jnp.asarray(X), jnp.asarray(y), None,
        1.0, opt_j,
    )
    np.testing.assert_allclose(
        np.asarray(pop_p.losses), np.asarray(pop_j.losses),
        rtol=1e-3, atol=1e-5,
    )


def test_optimizer_backend_pallas_validates(rng):
    import pytest

    opt = make_options(
        optimizer_algorithm="NelderMead", optimizer_backend="pallas",
        optimizer_probability=1.0,
    )
    X = jnp.ones((1, 10), jnp.float32)
    pop = Population(
        trees=jax.tree_util.tree_map(
            jnp.asarray,
            stack_trees([encode_tree(Expr.const(1.0), opt.max_len)] * 2),
        ),
        scores=jnp.ones((2,), jnp.float32),
        losses=jnp.ones((2,), jnp.float32),
        birth=jnp.zeros((2,), jnp.int32),
    )
    with pytest.raises(ValueError, match="optimizer_backend"):
        optimize_constants_population(
            jax.random.PRNGKey(0), pop, X, X[0], None, 1.0, opt
        )


def test_use_fused_kernels_routing(monkeypatch):
    """'auto' engages the fused path only on TPU, at scale, in f32, for
    BFGS with an elementwise loss, AND when the packed layout fits;
    'jnp' always pins the interpreter path."""
    import symbolicregression_jl_tpu.models.constant_opt as co
    import symbolicregression_jl_tpu.ops.pallas_eval as pe

    # 1024 rows: one full (8, 128) row tile, so the instances x rows
    # work-volume gate (fitness._pallas_work_gate) reduces to the
    # instance count vs the old batch threshold
    X = jnp.ones((1, 1024), jnp.float32)
    opt = make_options(optimizer_backend="auto")
    # off-TPU: never
    assert not co._use_fused_kernels(opt, 10_000, X)

    monkeypatch.setattr(pe, "pallas_available", lambda: True)
    assert co._use_fused_kernels(opt, 10_000, X)
    # too small a batch
    assert not co._use_fused_kernels(opt, 8, X)
    # many instances but tiny rows: insufficient work volume — the grad
    # kernel would mostly pad the row tile
    assert not co._use_fused_kernels(
        opt, 10_000, jnp.ones((1, 10), jnp.float32)
    )
    # non-f32 data (bf16 here; f64 is unconstructable without x64 enabled)
    assert not co._use_fused_kernels(
        opt, 10_000, jnp.ones((1, 1024), jnp.bfloat16)
    )
    # layout overflow (wide feature space) falls back quietly on auto
    X_wide = jnp.ones((2040, 1024), jnp.float32)
    assert not co._use_fused_kernels(opt, 10_000, X_wide)
    # non-BFGS never routes on auto
    opt_nm = make_options(
        optimizer_algorithm="NelderMead", optimizer_backend="auto"
    )
    assert not co._use_fused_kernels(opt_nm, 10_000, X)
    # explicit jnp pin
    opt_jnp = make_options(optimizer_backend="jnp")
    assert not co._use_fused_kernels(opt_jnp, 10_000, X)


def test_optimize_constants_islands_fused_matches_vmapped(rng, monkeypatch):
    """The islands-level entry must give the same result through the
    global fused-kernel batch (interpret mode) as through the vmapped
    per-member path, and identical to vmapping the single-population
    function (the production-equivalence guarantee)."""
    import symbolicregression_jl_tpu.models.constant_opt as co
    from symbolicregression_jl_tpu.models.constant_opt import (
        optimize_constants_islands,
    )

    def opts(backend):
        return make_options(
            binary_operators=["+", "*"], unary_operators=["cos"],
            maxsize=10, optimizer_probability=1.0,
            optimizer_iterations=8, optimizer_nrestarts=1,
            optimizer_backend=backend,
        )

    opt_p, opt_j = opts("pallas"), opts("jnp")
    ops = opt_p.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    X = rng.standard_normal((1, 30)).astype(np.float32)
    y = 2.0 * np.cos(X[0]) + 0.5

    def member(c0, c1):
        return encode_tree(
            Expr.binary(
                plus,
                Expr.binary(
                    mult, Expr.const(c0), Expr.unary(cos, Expr.var(0))
                ),
                Expr.const(c1),
            ),
            opt_p.max_len,
        )

    I, npop = 3, 2
    flat = stack_trees([
        member(float(c0), float(c1))
        for c0, c1 in rng.uniform(-2, 2, (I * npop, 2))
    ])
    trees = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a).reshape((I, npop) + a.shape[1:]), flat
    )
    pops = Population(
        trees=trees,
        scores=jnp.full((I, npop), 1e9, jnp.float32),
        losses=jnp.full((I, npop), 1e9, jnp.float32),
        birth=jnp.zeros((I, npop), jnp.int32),
    )
    keys = jax.random.split(jax.random.PRNGKey(0), I)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)

    monkeypatch.setattr(co, "_FORCE_INTERPRET", True)
    pops_f, ev_f, att_f = optimize_constants_islands(
        keys, pops, Xj, yj, None, 1.0, opt_p
    )
    pops_j, ev_j, att_j = optimize_constants_islands(
        keys, pops, Xj, yj, None, 1.0, opt_j
    )
    # same members attempted, same eval accounting, same quality
    np.testing.assert_array_equal(np.asarray(att_f), np.asarray(att_j))
    np.testing.assert_allclose(
        np.asarray(pops_f.losses), np.asarray(pops_j.losses),
        rtol=1e-3, atol=1e-5,
    )
    # and the jnp islands path is bit-identical to vmapping the
    # single-population function (what api.py used to do)
    pops_v, ev_v, att_v = jax.vmap(
        lambda k, p: optimize_constants_population(
            k, p, Xj, yj, None, 1.0, opt_j
        )
    )(keys, pops)
    np.testing.assert_array_equal(
        np.asarray(pops_j.trees.cval), np.asarray(pops_v.trees.cval)
    )
    np.testing.assert_array_equal(
        np.asarray(pops_j.losses), np.asarray(pops_v.losses)
    )
    np.testing.assert_array_equal(np.asarray(ev_j), np.asarray(ev_v))


def test_chunked_portable_path_matches_unchunked(rng):
    """_run_vmapped_chunked with a tiny chunk (forcing padding + lax.map)
    must reproduce the single-vmap fast path exactly — the chunking only
    bounds XLA temp memory (the 64-island HBM OOM), never results."""
    from symbolicregression_jl_tpu.models.constant_opt import (
        _bfgs_single,
        _run_vmapped_chunked,
    )

    opt = make_options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        optimizer_iterations=6, optimizer_nrestarts=0,
    )
    ops = opt.operators
    plus, mult = ops.binary_index("+"), ops.binary_index("*")
    cos = ops.unary_index("cos")
    X = jnp.asarray(rng.standard_normal((1, 30)).astype(np.float32))
    y = 1.7 * jnp.cos(X[0]) - 0.3

    trees = stack_trees([
        encode_tree(
            Expr.binary(
                plus,
                Expr.binary(
                    mult, Expr.const(float(c)), Expr.unary(cos, Expr.var(0))
                ),
                Expr.const(0.1 * i),
            ),
            opt.max_len,
        )
        for i, c in enumerate(rng.uniform(0.5, 3.0, 10))
    ])
    L = opt.max_len
    starts = trees.cval
    idx = jnp.arange(L)
    cmask = (
        (trees.kind == 1) & (idx < trees.length[:, None])
    ).astype(jnp.float32)

    xs_fast, fs_fast = _run_vmapped_chunked(
        trees, starts, cmask, X, y, None, opt, _bfgs_single, chunk=64
    )
    xs_chunk, fs_chunk = _run_vmapped_chunked(
        trees, starts, cmask, X, y, None, opt, _bfgs_single, chunk=4
    )
    np.testing.assert_array_equal(np.asarray(fs_fast), np.asarray(fs_chunk))
    np.testing.assert_array_equal(np.asarray(xs_fast), np.asarray(xs_chunk))


# ---------------------------------------------------------------------------
# containment contract (ISSUE 15): a non-finite objective at the initial
# point must end in the restored-constants fallback, never in adopted
# line-search wreckage or a non-finite constant written into the carry
# ---------------------------------------------------------------------------


def _overflow_member(opt):
    """c0 * x0 + c1 with c0 so large the f32 objective overflows: the
    squared-error loss at the initial point is inf for every row."""
    plus = opt.operators.binary_index("+")
    mult = opt.operators.binary_index("*")
    return encode_tree(
        Expr.binary(
            plus,
            Expr.binary(mult, Expr.const(1e30), Expr.var(0)),
            Expr.const(1e30),
        ),
        opt.max_len,
    )


def test_nonfinite_initial_objective_restores_constants(rng):
    """Regression (ISSUE 15 satellite): a member whose objective is
    non-finite AT THE INITIAL POINT used to flow through the line
    search unguarded; the contract now is reject-step + restore — the
    population comes back with the ORIGINAL constants bit-for-bit and
    its stored losses untouched, for BFGS, NelderMead and Newton."""
    X = rng.standard_normal((1, 40)).astype(np.float32)
    y = (2.0 * X[0] + 0.5).astype(np.float32)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    from symbolicregression_jl_tpu.models.fitness import score_trees

    for algo in ("BFGS", "NelderMead", "Newton"):
        opt = make_options(
            binary_operators=["+", "*"], maxsize=10,
            optimizer_probability=1.0, optimizer_iterations=4,
            optimizer_nrestarts=0, optimizer_algorithm=algo,
        )
        trees = stack_trees([_overflow_member(opt)])
        scores, losses = score_trees(trees, Xj, yj, None, 1.0, opt)
        assert not np.isfinite(np.asarray(losses)).any()  # inf-contained
        pop = Population(
            trees=trees, scores=scores, losses=losses,
            birth=jnp.zeros(1, jnp.int32),
        )
        pop2, _, _ = optimize_constants_population(
            jax.random.PRNGKey(0), pop, Xj, yj, None, 1.0, opt
        )
        np.testing.assert_array_equal(
            np.asarray(pop.trees.cval), np.asarray(pop2.trees.cval),
            err_msg=f"{algo}: constants not restored",
        )
        np.testing.assert_array_equal(
            np.asarray(pop.losses), np.asarray(pop2.losses),
            err_msg=f"{algo}: losses overwritten from an inf objective",
        )
        assert np.isfinite(np.asarray(pop2.trees.cval)).all()


def test_optimizer_never_writes_nonfinite_constants(rng):
    """The write-back guard: even when an objective reaches a finite
    value through a non-finite constant (exp(c) with c -> -inf is
    finite), the population never adopts a non-finite cval."""
    opt = make_options(
        binary_operators=["+", "*"], unary_operators=["exp"],
        maxsize=10, optimizer_probability=1.0, optimizer_iterations=8,
        optimizer_nrestarts=1,
    )
    plus = opt.operators.binary_index("+")
    exp_i = opt.operators.unary_index("exp")
    # exp(c0) + c1 fit to y ~ 0.5: a huge negative c0 drive is plausible
    tree = encode_tree(
        Expr.binary(
            plus, Expr.unary(exp_i, Expr.const(-2.0)), Expr.const(0.0)
        ),
        opt.max_len,
    )
    X = rng.standard_normal((1, 30)).astype(np.float32)
    y = np.full(30, 0.5, np.float32)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    from symbolicregression_jl_tpu.models.fitness import score_trees

    trees = stack_trees([tree])
    scores, losses = score_trees(trees, Xj, yj, None, 1.0, opt)
    pop = Population(
        trees=trees, scores=scores, losses=losses,
        birth=jnp.zeros(1, jnp.int32),
    )
    pop2, _, _ = optimize_constants_population(
        jax.random.PRNGKey(0), pop, Xj, yj, None, 1.0, opt
    )
    assert np.isfinite(np.asarray(pop2.trees.cval)).all()
    assert np.isfinite(np.asarray(pop2.losses)).all()
