"""Multi-device search tests on the 8-virtual-CPU-device mesh (conftest
sets --xla_force_host_platform_device_count=8 — the analog of the
reference's in-process addprocs(2) distributed tests,
test/test_custom_operators_multiprocessing.jl:18-34).

These run the FULL public equation_search sharded over the mesh, not just
one engine step: recovery must work through sharding, and the merged hall
of fame must match the single-device run bit-for-bit (SPMD partitioning
must not change the computation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.parallel import mesh as mesh_mod

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=4,
    ncycles_per_iteration=40,
    maxsize=12,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
    runtests=False,
)


def make_data(seed=0, n=64):
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((3, n)) * 2).astype(np.float32)
    y = X[0] * X[0] + 2.0 * np.cos(X[2])
    return X, y


def test_mesh_is_active():
    """Sanity: the virtual-device harness is in effect and equation_search
    will actually build a mesh (guards against silently running all other
    tests single-device)."""
    assert len(jax.devices()) >= 8
    opts = make_options(binary_operators=["+"], npopulations=4)
    m = mesh_mod.make_mesh(opts, 4)
    assert m is not None
    assert m.devices.size >= 4


@pytest.mark.slow
def test_sharded_search_recovers_target():
    """Full sharded equation_search over the (islands, rows) mesh recovers
    the synthetic target (reference e2e bar: loss < 1e-2,
    test/test_mixed.jl:129-141) — with the rows axis active via the
    row_shards Options knob."""
    X, y = make_data()
    res = sr.equation_search(
        X, y,
        niterations=8,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npop=33, npopulations=4, ncycles_per_iteration=120, maxsize=14,
        row_shards=2,
        verbosity=0, progress=False, runtests=False,
        early_stop_condition=1e-6, seed=3,
    )
    assert min(c.loss for c in res.frontier()) < 1e-2


def test_single_vs_multi_device_hof_parity(monkeypatch):
    """The merged hall of fame from the sharded run equals the
    single-device run: SPMD placement must be semantics-preserving.
    (VERDICT r1 item 3b.)"""
    X, y = make_data()

    res_multi = sr.equation_search(X, y, niterations=2, seed=11, **TINY)

    # force the single-device path: no mesh, plain jit
    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.make_mesh", lambda *a, **k: None
    )
    res_single = sr.equation_search(X, y, niterations=2, seed=11, **TINY)

    eq_m = [(c.complexity, c.equation) for c in res_multi.frontier()]
    eq_s = [(c.complexity, c.equation) for c in res_single.frontier()]
    assert eq_m == eq_s
    np.testing.assert_allclose(
        [c.loss for c in res_multi.frontier()],
        [c.loss for c in res_single.frontier()],
        rtol=1e-5,
    )


def test_row_shards_two_matches_one():
    """Row sharding is a layout choice, not an algorithm change: the same
    search with row_shards=2 produces the same frontier as row_shards=1."""
    X, y = make_data()
    r1 = sr.equation_search(X, y, niterations=2, seed=7, row_shards=1, **TINY)
    r2 = sr.equation_search(X, y, niterations=2, seed=7, row_shards=2, **TINY)
    assert [(c.complexity, c.equation) for c in r1.frontier()] == [
        (c.complexity, c.equation) for c in r2.frontier()
    ]


def test_sharded_iteration_lowers_to_collectives():
    """The compiled sharded iteration contains real cross-device
    communication: migration's island-axis gather and the row-axis loss
    reduction must show up as collective ops in the optimized HLO (not be
    partitioned away into per-device replicas)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbolicregression_jl_tpu.api import _make_iteration_fn
    from symbolicregression_jl_tpu.models.evolve import init_island_state

    opts = make_options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        npop=16,
        npopulations=4,
        ncycles_per_iteration=2,
        maxsize=10,
        tournament_selection_n=5,
        should_optimize_constants=False,
        row_shards=2,
    )
    mesh = mesh_mod.make_mesh(opts, 4, row_shards=2)
    assert mesh is not None and mesh.devices.size == 8

    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((2, 32)).astype(np.float32)
    y_h = (X_h[0] * X_h[0]).astype(np.float32)
    X = jax.device_put(
        jnp.asarray(X_h), NamedSharding(mesh, P(None, opts.row_axis))
    )
    y = jax.device_put(
        jnp.asarray(y_h), NamedSharding(mesh, P(opts.row_axis))
    )
    baseline = jnp.float32(float(np.var(y_h)))
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    states = jax.vmap(
        lambda k: init_island_state(k, opts, 2, X, y, None, baseline)
    )(keys)
    states = jax.tree_util.tree_map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(opts.island_axis))
        ),
        states,
    )

    fn = _make_iteration_fn(opts, has_weights=False)
    compiled = fn.lower(
        states, jax.random.PRNGKey(1), jnp.int32(opts.maxsize), X, y,
        baseline, opts.traced_scalars(),
    ).compile()
    hlo = compiled.as_text()
    has_collective = any(
        marker in hlo
        for marker in (
            "all-reduce", "all-gather", "collective-permute", "all-to-all",
            "reduce-scatter",
        )
    )
    assert has_collective, "no collective ops in the sharded iteration HLO"
