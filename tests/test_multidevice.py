"""Multi-device search tests on the 8-virtual-CPU-device mesh (conftest
sets --xla_force_host_platform_device_count=8 — the analog of the
reference's in-process addprocs(2) distributed tests,
test/test_custom_operators_multiprocessing.jl:18-34).

These run the FULL public equation_search sharded over the mesh, not just
one engine step: recovery must work through sharding, and the merged hall
of fame must match the single-device run bit-for-bit (SPMD partitioning
must not change the computation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.parallel import mesh as mesh_mod

TINY = dict(
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=24,
    npopulations=4,
    ncycles_per_iteration=40,
    maxsize=12,
    should_optimize_constants=False,
    verbosity=0,
    progress=False,
    runtests=False,
)


def make_data(seed=0, n=64):
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((3, n)) * 2).astype(np.float32)
    y = X[0] * X[0] + 2.0 * np.cos(X[2])
    return X, y


def test_mesh_is_active():
    """Sanity: the virtual-device harness is in effect and equation_search
    will actually build a mesh (guards against silently running all other
    tests single-device)."""
    assert len(jax.devices()) >= 8
    opts = make_options(binary_operators=["+"], npopulations=4)
    m = mesh_mod.make_mesh(opts, 4)
    assert m is not None
    assert m.devices.size >= 4


@pytest.mark.slow
def test_sharded_search_recovers_target():
    """Full sharded equation_search over the (islands, rows) mesh recovers
    the synthetic target (reference e2e bar: loss < 1e-2,
    test/test_mixed.jl:129-141) — with the rows axis active via the
    row_shards Options knob."""
    X, y = make_data()
    res = sr.equation_search(
        X, y,
        niterations=8,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npop=33, npopulations=4, ncycles_per_iteration=120, maxsize=14,
        row_shards=2,
        verbosity=0, progress=False, runtests=False,
        early_stop_condition=1e-6, seed=3,
    )
    assert min(c.loss for c in res.frontier()) < 1e-2


def _assert_island_sharded(states, island_axis="islands"):
    """Every leaf of a carried IslandState must report island-axis
    NamedSharding — no replicated carries (ISSUE 9 acceptance: a
    replicated carry means GSPMD collapsed the islands onto one
    device and every later iteration serializes there)."""
    from jax.sharding import NamedSharding

    for path, leaf in jax.tree_util.tree_flatten_with_path(states)[0]:
        sh = getattr(leaf, "sharding", None)
        assert isinstance(sh, NamedSharding), (
            f"{jax.tree_util.keystr(path)}: {type(sh)}"
        )
        spec = tuple(sh.spec)
        assert spec and spec[0] == island_axis, (
            f"{jax.tree_util.keystr(path)}: sharding {sh} is not "
            "island-axis sharded"
        )
        assert not sh.is_fully_replicated, (
            f"{jax.tree_util.keystr(path)}: replicated carry"
        )


def test_single_vs_multi_device_hof_parity(monkeypatch):
    """The merged hall of fame from the sharded run equals the
    single-device run: SPMD placement must be semantics-preserving.
    (VERDICT r1 item 3b.) Since the sharding contract landed in the jit
    factories (ISSUE 9), also asserts the returned state's carries are
    island-sharded — same searches, no extra compile."""
    X, y = make_data()

    res_multi = sr.equation_search(
        X, y, niterations=2, seed=11, return_state=True, **TINY
    )
    _assert_island_sharded(res_multi.state[0].island_states)

    # force the single-device path: no mesh, plain jit
    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.make_mesh", lambda *a, **k: None
    )
    res_single = sr.equation_search(X, y, niterations=2, seed=11, **TINY)

    eq_m = [(c.complexity, c.equation) for c in res_multi.frontier()]
    eq_s = [(c.complexity, c.equation) for c in res_single.frontier()]
    assert eq_m == eq_s
    np.testing.assert_allclose(
        [c.loss for c in res_multi.frontier()],
        [c.loss for c in res_single.frontier()],
        rtol=1e-5,
    )


def test_row_shards_two_bit_identical_to_single_device(monkeypatch):
    """row_shards=2 is back INSIDE the bit-identity contract (ISSUE 15):
    the per-tree row-loss reduction is the fixed-order pairwise tree
    (ops/losses.py::pairwise_sum — every add its own HLO op, so
    partitioning cannot reassociate it) and row-sharded searches run
    under jax_threefry_partitionable (partition-invariant random
    streams; the legacy lowering's draws measurably changed with the
    partitioning). The row-sharded search over the (islands, rows) mesh
    must therefore equal the SINGLE-DEVICE run of the same Options, bit
    for bit — losses and scores included, not allclose. (The ISSUE 9 -
    15 interim asserted only determinism + same-regime; before ISSUE 9
    the old bit-equality test passed only because GSPMD ignored the row
    axis entirely.)"""
    X, y = make_data()
    r2 = sr.equation_search(X, y, niterations=2, seed=7, row_shards=2, **TINY)
    r2b = sr.equation_search(X, y, niterations=2, seed=7, row_shards=2, **TINY)
    frontier = lambda r: [
        (c.complexity, c.equation, float(c.loss), float(c.score))
        for c in r.frontier()
    ]
    assert frontier(r2) == frontier(r2b)  # deterministic, same mesh

    # force the single-device path: no mesh, plain jit — SAME Options
    # (row_shards=2 selects the deterministic reduction graph in both)
    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.make_mesh", lambda *a, **k: None
    )
    r1 = sr.equation_search(X, y, niterations=2, seed=7, row_shards=2, **TINY)
    assert frontier(r2) == frontier(r1)
    assert np.isfinite(min(c.loss for c in r2.frontier()))


def test_row_shards_threefry_flag_restored():
    """The row-sharded search flips jax_threefry_partitionable for its
    own duration only: a later row_shards=1 search in the same process
    must see the legacy streams every golden value was recorded under."""
    prev = jax.config.jax_threefry_partitionable
    X, y = make_data()
    sr.equation_search(X, y, niterations=1, seed=7, row_shards=2, **TINY)
    assert jax.config.jax_threefry_partitionable == prev


def test_sharded_iteration_lowers_to_collectives():
    """The compiled sharded iteration contains real cross-device
    communication: migration's island-axis gather and the row-axis loss
    reduction must show up as collective ops in the optimized HLO (not be
    partitioned away into per-device replicas)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbolicregression_jl_tpu.api import _make_iteration_fn
    from symbolicregression_jl_tpu.models.evolve import init_island_state

    opts = make_options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        npop=16,
        npopulations=4,
        ncycles_per_iteration=2,
        maxsize=10,
        tournament_selection_n=5,
        should_optimize_constants=False,
        row_shards=2,
    )
    mesh = mesh_mod.make_mesh(opts, 4, row_shards=2)
    assert mesh is not None and mesh.devices.size == 8

    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((2, 32)).astype(np.float32)
    y_h = (X_h[0] * X_h[0]).astype(np.float32)
    X = jax.device_put(
        jnp.asarray(X_h), NamedSharding(mesh, P(None, opts.row_axis))
    )
    y = jax.device_put(
        jnp.asarray(y_h), NamedSharding(mesh, P(opts.row_axis))
    )
    baseline = jnp.float32(float(np.var(y_h)))
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    states = jax.vmap(
        lambda k: init_island_state(k, opts, 2, X, y, None, baseline)
    )(keys)
    states = jax.tree_util.tree_map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(opts.island_axis))
        ),
        states,
    )

    fn = _make_iteration_fn(opts, has_weights=False)
    compiled = fn.lower(
        states, jax.random.PRNGKey(1), jnp.int32(opts.maxsize), X, y,
        baseline, opts.traced_scalars(),
    ).compile()
    hlo = compiled.as_text()
    has_collective = any(
        marker in hlo
        for marker in (
            "all-reduce", "all-gather", "collective-permute", "all-to-all",
            "reduce-scatter",
        )
    )
    assert has_collective, "no collective ops in the sharded iteration HLO"


def test_make_mesh_warns_on_idle_devices():
    """8 devices / 6 islands cannot tile: make_mesh must say so (named
    mesh + idle count), not silently run on 6 devices (ISSUE 9
    satellite), and describe_mesh must report the degradation for the
    telemetry run_start record."""
    import warnings

    opts = make_options(binary_operators=["+"], npopulations=6)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = mesh_mod.make_mesh(opts, 6)
    assert m is not None and m.devices.size == 6
    msgs = [str(x.message) for x in w if "make_mesh" in str(x.message)]
    assert msgs, "no idle-device warning"
    assert "2 idle" in msgs[0] and "(6, 1)" in msgs[0]

    info = mesh_mod.describe_mesh(m)
    assert info["mesh_shape"] == {"islands": 6, "rows": 1}
    assert info["n_devices"] == 6
    assert info["idle_devices"] == len(jax.devices()) - 6
    assert info["device_kind"] == "cpu"

    # a clean tiling warns nothing
    opts8 = make_options(binary_operators=["+"], npopulations=8)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m8 = mesh_mod.make_mesh(opts8, 8)
    assert not [x for x in w if "make_mesh" in str(x.message)]
    assert mesh_mod.describe_mesh(m8)["idle_devices"] == 0

    # single-device description (the run_start record when unsharded)
    info1 = mesh_mod.describe_mesh(None)
    assert info1["mesh_shape"] is None and info1["n_devices"] == 1


def test_tenant_mesh_warns_naming_idle_devices():
    """ISSUE 19 satellite: (tenants=3, islands=4) cannot tile 8 devices
    — the tenant branch of make_mesh must warn naming WHICH devices sit
    idle (not just how many), so a degraded serving deployment is
    attributable from the log alone."""
    import warnings

    devices = jax.devices()
    opts = make_options(binary_operators=["+"], npopulations=4, tenants=3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = mesh_mod.make_mesh(opts, 4, tenants=3)
    # 3 tenant shards x 2 island shards = 6 of 8 devices
    assert m is not None and m.devices.shape == (3, 2)
    assert m.axis_names == (opts.tenant_axis, opts.island_axis)
    msgs = [str(x.message) for x in w if "make_mesh" in str(x.message)]
    assert msgs, "no idle-device warning from the tenant mesh branch"
    assert "2 idle" in msgs[0] and "(3, 2)" in msgs[0]
    for d in devices[6:8]:
        assert str(d) in msgs[0], f"idle device {d} not named in warning"

    info = mesh_mod.describe_mesh(m)
    assert info["mesh_shape"] == {
        opts.tenant_axis: 3, opts.island_axis: 2,
    }
    assert info["n_devices"] == 6
    assert info["idle_devices"] == len(devices) - 6


@pytest.mark.slow
def test_degraded_mesh_lands_in_run_start(tmp_path):
    """Slow (compiles a fresh search on a 6x1 mesh, ~3 min). The
    degraded-mesh facts are machine-readable, not just a warning:
    a search whose island count does not tile the devices must stamp
    mesh_shape + idle_devices into the telemetry run_start event via
    describe_mesh (ISSUE 19 satellite)."""
    from symbolicregression_jl_tpu.telemetry.analyze import (
        load_events,
        resolve_log,
    )

    X, y = make_data()
    with pytest.warns(UserWarning, match="make_mesh"):
        sr.equation_search(
            X, y, niterations=1, seed=5, telemetry=True,
            telemetry_dir=str(tmp_path), **{**TINY, "npopulations": 6}
        )
    events, skipped = load_events(resolve_log(str(tmp_path)))
    assert skipped == 0
    start = next(e for e in events if e.get("type") == "run_start")
    assert start["mesh_shape"] == {"islands": 6, "rows": 1}
    assert start["n_devices"] == 6
    assert start["idle_devices"] == len(jax.devices()) - 6


def test_search_shardings_cover_island_state():
    """ISSUE 19 satellite: the search_shardings vocabulary structurally
    covers the carry — EVERY post-init IslandState leaf accepts the
    ``island`` spec (leading dim = the island count, divisible by the
    islands axis), so srshard's contract check (analysis/shard.py) and
    the api jit factories can pin the whole tree from one vocabulary
    entry with no per-leaf exceptions. Also pins the vocabulary key
    sets srshard's stage specs are written against."""
    from symbolicregression_jl_tpu.models.evolve import init_island_state

    I = 4
    opts = make_options(
        binary_operators=["+", "*"], npop=16, npopulations=I,
        maxsize=10, should_optimize_constants=False,
    )
    mesh = mesh_mod.make_mesh(opts, I)
    assert mesh is not None
    sh = mesh_mod.search_shardings(mesh, opts)
    assert set(sh) == {
        "island", "tenant", "replicated", "x", "rows", "events",
    }

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((2, 32)).astype(np.float32))
    y = X[0] * X[0]
    baseline = jnp.var(y)
    keys = jax.random.split(jax.random.PRNGKey(0), I)
    # trace-only: the structural claim is about shapes, not values
    states = jax.eval_shape(
        jax.vmap(
            lambda k: init_island_state(k, opts, 2, X, y, None, baseline)
        ),
        keys,
    )

    leaves = jax.tree_util.tree_flatten_with_path(states)[0]
    assert leaves, "empty IslandState pytree"
    n_island_shards = mesh.shape[opts.island_axis]
    for path, leaf in leaves:
        name = jax.tree_util.keystr(path)
        assert leaf.ndim >= 1, f"{name}: rank-0 leaf cannot ride P(islands)"
        assert leaf.shape[0] == I, (
            f"{name}: leading dim {leaf.shape[0]} != island count {I}"
        )
        assert leaf.shape[0] % n_island_shards == 0, (
            f"{name}: leading dim does not tile the islands axis"
        )
        # the spec is genuinely applicable: shard_shape must accept it
        shard = sh["island"].shard_shape(leaf.shape)
        assert shard[0] == leaf.shape[0] // n_island_shards, name

    # tenant-mesh vocabulary: same coverage story with a leading tenant
    # dim composed in front (and no events entry — the recorder is a
    # solo-driver feature)
    topts = make_options(
        binary_operators=["+", "*"], npop=16, npopulations=2,
        maxsize=10, should_optimize_constants=False, tenants=2,
    )
    tmesh = mesh_mod.make_mesh(topts, 2, tenants=2)
    assert tmesh is not None
    tsh = mesh_mod.search_shardings(tmesh, topts)
    assert set(tsh) == {"island", "tenant", "replicated", "x", "rows"}
    assert tuple(tsh["island"].spec) == (
        topts.tenant_axis, topts.island_axis,
    )

    # the JSON-able view (what srshard records per config) round-trips
    # the same names and axes
    table = mesh_mod.spec_table(mesh, opts)
    assert set(table) == set(sh)
    assert table["island"] == [opts.island_axis]
    assert mesh_mod.spec_table(None, opts) is None


# one island per virtual device — the ISSUE 9 acceptance configuration
TINY8 = {**TINY, "npopulations": 8}


def test_tenant_batched_state_sharded():
    """ISSUE 16: on the (tenants, islands) serving mesh the carried
    IslandState leaves are sharded over BOTH named axes —
    P('tenants', 'islands') — after init and after an iteration, so a
    4-tenant batch actually spreads over all 8 devices instead of
    GSPMD collapsing the tenants axis onto one replica."""
    from jax.sharding import NamedSharding

    from symbolicregression_jl_tpu.api import (
        _make_init_fn,
        _make_iteration_driver,
    )

    T, I = 4, 2
    tiny = {k: v for k, v in TINY.items() if k != "runtests"}
    opts = make_options(seed=0, tenants=T, **{**tiny, "npopulations": I})
    mesh = mesh_mod.make_mesh(opts, I, tenants=T)
    assert mesh is not None and mesh.devices.shape == (T, I)
    assert mesh.axis_names == (opts.tenant_axis, opts.island_axis)

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((T, 2, 32)).astype(np.float32))
    y = X[:, 0] * X[:, 0]
    bl = jnp.var(y, axis=-1)
    scalars = opts.traced_scalars()
    masters = jnp.stack([jax.random.PRNGKey(s) for s in range(T)])
    ks = jax.vmap(lambda k: jax.random.split(k))(masters)
    init_keys = jax.vmap(lambda k: jax.random.split(k, I))(ks[:, 0])

    init_fn = _make_init_fn(opts, 2, False, False, mesh)
    states = init_fn(init_keys, X, y, bl, scalars)

    def _assert_tenant_island(tree):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            sh = getattr(leaf, "sharding", None)
            assert isinstance(sh, NamedSharding), (
                f"{jax.tree_util.keystr(path)}: {type(sh)}"
            )
            spec = tuple(sh.spec)
            assert spec[:2] == (opts.tenant_axis, opts.island_axis), (
                f"{jax.tree_util.keystr(path)}: {sh} is not "
                "(tenants, islands)-sharded"
            )

    _assert_tenant_island(states)

    it_fn = _make_iteration_driver(opts, False, donate=False, mesh=mesh)
    states, ghof = it_fn(
        states, ks[:, 1], jnp.int32(opts.maxsize), X, y, bl, scalars
    )
    _assert_tenant_island(states)
    # the merged per-tenant HoF rides the tenants axis
    gsh = ghof.losses.sharding
    assert isinstance(gsh, NamedSharding)
    assert tuple(gsh.spec)[:1] == (opts.tenant_axis,)


@pytest.mark.slow
def test_sharded_search_production_contract(monkeypatch):
    """ISSUE 9 acceptance, fused driver: on the 8-device mesh with
    row_shards=1, (a) the hall of fame is BIT-identical to the
    single-device run (islands-only sharding leaves per-island math
    unchanged — strict equality including losses, not allclose), and
    (b) every leaf of the carried IslandState is island-sharded after 3
    iterations."""
    X, y = make_data()
    res_m = sr.equation_search(
        X, y, niterations=3, seed=11, return_state=True, **TINY8
    )
    _assert_island_sharded(res_m.state[0].island_states)

    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.make_mesh", lambda *a, **k: None
    )
    res_s = sr.equation_search(X, y, niterations=3, seed=11, **TINY8)
    assert [
        (c.complexity, c.equation, float(c.loss), float(c.score))
        for c in res_m.frontier()
    ] == [
        (c.complexity, c.equation, float(c.loss), float(c.score))
        for c in res_s.frontier()
    ]


@pytest.mark.slow
def test_chunked_sharded_search_matches_fused(monkeypatch):
    """ISSUE 9 acceptance, chunked driver: the phased dispatches carry
    the same sharding contract — the chunked sharded search equals the
    single-device FUSED run bit for bit (chunked==fused composes with
    sharded==single), and the carry stays island-sharded across the
    phase-boundary round trips."""
    X, y = make_data()
    res_c = sr.equation_search(
        X, y, niterations=2, seed=11, max_cycles_per_dispatch=20,
        return_state=True, **TINY8
    )
    _assert_island_sharded(res_c.state[0].island_states)

    monkeypatch.setattr(
        "symbolicregression_jl_tpu.api.make_mesh", lambda *a, **k: None
    )
    res_s = sr.equation_search(X, y, niterations=2, seed=11, **TINY8)
    assert [
        (c.complexity, c.equation, float(c.loss))
        for c in res_c.frontier()
    ] == [
        (c.complexity, c.equation, float(c.loss))
        for c in res_s.frontier()
    ]


@pytest.mark.slow
def test_donation_neutral_under_mesh(monkeypatch):
    """Donated sharded carries must stay value-identical to undonated
    ones: donation is buffer aliasing, and under the mesh each shard
    aliases shard-for-shard (ISSUE 9 test satellite (c))."""
    X, y = make_data()
    res_on = sr.equation_search(X, y, niterations=2, seed=3, **TINY8)
    monkeypatch.setenv("SRTPU_DONATE", "0")
    res_off = sr.equation_search(X, y, niterations=2, seed=3, **TINY8)
    assert [
        (c.complexity, c.equation, float(c.loss))
        for c in res_on.frontier()
    ] == [
        (c.complexity, c.equation, float(c.loss))
        for c in res_off.frontier()
    ]


@pytest.mark.slow
def test_saved_state_resume_round_trips_sharded():
    """ISSUE 9 test satellite (d): a kill/resume cycle round-trips the
    mesh — resuming from a saved state re-places the carries island-
    sharded (no silent full replication), the resumed search advances
    the iteration counter, and the caller's saved state stays usable
    after the donating resume."""
    X, y = make_data()
    res_a = sr.equation_search(
        X, y, niterations=2, seed=11, return_state=True, **TINY8
    )
    assert res_a.state[0].iteration == 2
    res_b = sr.equation_search(
        X, y, niterations=2, seed=11, saved_state=res_a.state,
        return_state=True, **TINY8
    )
    _assert_island_sharded(res_b.state[0].island_states)
    assert res_b.state[0].iteration == 4
    # the donating resume copied before consuming: resuming AGAIN from
    # the same saved state must still work (kill/retry semantics)
    res_c = sr.equation_search(
        X, y, niterations=1, seed=11, saved_state=res_a.state,
        return_state=True, **TINY8
    )
    _assert_island_sharded(res_c.state[0].island_states)
    assert res_c.state[0].iteration == 3
    # resumed frontiers can only keep or improve the saved best loss
    # (the HoF merge is monotone)
    best = lambda r: min(c.loss for c in r.frontier())
    assert best(res_b) <= best(res_a) + 1e-7
