"""Worker process for the 2-process multi-host test (the analog of the
reference's in-process addprocs(2) distributed tests — here each "host" is
a real separate process joined through jax.distributed, 4 virtual CPU
devices each, global mesh of 8).

Usage: python multihost_worker.py <process_id> <coordinator_port>
Prints MULTIHOST_OK <best_loss> on success.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["SYMBOLIC_REGRESSION_TEST"] = "true"

import jax

jax.config.update("jax_platforms", "cpu")

process_id = int(sys.argv[1])
port = int(sys.argv[2])
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}",
    num_processes=2,
    process_id=process_id,
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.local_devices()) == 4
assert len(jax.devices()) == 8

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr

rng = np.random.default_rng(0)
X = (rng.standard_normal((3, 64)) * 2).astype(np.float32)
y = X[0] * X[0] + 2.0 * np.cos(X[2])

res = sr.equation_search(
    X, y,
    niterations=2,
    binary_operators=["+", "-", "*"],
    unary_operators=["cos"],
    npop=16,
    npopulations=8,
    ncycles_per_iteration=10,
    maxsize=10,
    should_optimize_constants=False,
    row_shards=2,
    verbosity=0,
    progress=False,
    runtests=False,
    seed=0,
    return_state=True,
)
best = min(c.loss for c in res.frontier())
assert np.isfinite(best)

# disk checkpoint of multi-process sharded state: every process can
# materialize the global state (allgather); each writes its own copy
# here so the test can compare them byte-for-byte
ckpt = f"/tmp/srtpu_mh_state_{process_id}.ckpt"
sr.save_search_state(ckpt, res.state)
reloaded = sr.load_search_state(ckpt)
assert reloaded[0].iteration == res.state[0].iteration
losses = np.asarray(reloaded[0].island_states.pop.losses, np.float64)
pop_hash = float(np.sum(np.where(np.isfinite(losses), losses, 0.0)))
print(f"MULTIHOST_OK {best:.6f} ckpt={pop_hash:.6f}", flush=True)
