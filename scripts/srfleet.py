#!/usr/bin/env python
"""srfleet — live terminal dashboard over a FLEET of telemetry runs.

The multi-run sibling of srtop: point it at a fleet root (the directory
the watcher/supervisor/suite/bench write their event logs under — e.g.
whatever ``SRTPU_BENCH_TELEMETRY_DIR`` points at) and it renders,
refreshing in place, one line per live/recent run:

* the fleet header — run count, verdict histogram, fault rate,
  aggregate trees-rows/s, alerts firing;
* per run: run_id, doctor verdict, supervisor attempt, last-event age
  (the liveness signal), backend, best loss, eval throughput, the
  dominant stage of its wall-time split, and any alert rules firing
  for it;
* the firing-alert tail (rule, severity, message).

Every frame is one ``FleetScanner.refresh()``: logs are tailed
incrementally (srtop's byte-offset discipline — a frame costs only the
new bytes), ``fleet_index.json`` is atomically rewritten, and each
NEWLY-firing alert is appended to ``fleet_alerts.jsonl`` as a schema-v1
``alert`` event. The dashboard never modifies any run's own log.

Usage:
    python scripts/srfleet.py FLEET_ROOT [--interval 5] [--once]
        [--stall-after 600] [--threshold 0.1] [--trajectory PATH]
        [--metrics-out FILE]

``--once`` renders a single frame and exits — the CI gate: exit status
is 0 iff NO alert rule at ``--fail-on`` severity or above fires
(default ``warning`` — ``info`` notes like ``compile_bound`` on a
cold-start smoke run report without failing), so
``srfleet.py ROOT --once`` gates a pipeline on fleet health the same
way ``srtop.py DIR --once`` gates on one run's. ``--trajectory`` opts
the same-platform throughput-regression rule in (pass the repo's
TRAJECTORY.json); ``--metrics-out`` additionally writes the OpenMetrics
exposition of every frame atomically to FILE for a node-exporter-style
textfile collector (serving an HTTP ``/metrics`` endpoint instead is
``telemetry.export.serve_metrics`` — one call from any driver).

Curses-free like srtop: ANSI rewind-and-redraw on TTYs, plain append
when piped. The package import pins ``JAX_PLATFORMS=cpu`` first (the
fleet layer is host-side file reading, but the package import must not
route backend init at a TPU tunnel).
"""

from __future__ import annotations

import argparse
import math
import os
import sys
import time

# the fleet layer is host-side only, but importing the package pulls
# jax — pin CPU before anything backend-shaped can initialize (srtop's
# --once gate does the same)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def fmt(v, spec=".3g"):
    if isinstance(v, (int, float)) and not isinstance(v, bool) \
            and math.isfinite(v):
        return format(v, spec)
    return "-"


def _age_s(v):
    if v is None:
        return "-"
    if v < 120:
        return f"{v:.0f}s"
    if v < 7200:
        return f"{v / 60:.1f}m"
    return f"{v / 3600:.1f}h"


def render_frame(index) -> str:
    """One dashboard frame from one fleet index dict."""
    rollup = index.get("rollup", {}) or {}
    rows = index.get("runs", [])
    alerts = index.get("alerts", [])
    L = []
    verd = rollup.get("verdicts") or {}
    verd_s = " ".join(f"{k}:{v}" for k, v in sorted(verd.items()))
    L.append(
        f"srfleet — {index.get('root')}   runs: {rollup.get('runs', 0)}"
        + (f" ({verd_s})" if verd_s else "")
    )
    agg = rollup.get("throughput_trees_rows_per_s")
    bits = [
        f"alerts firing: {rollup.get('alerts_firing', 0)}",
        f"fault rate: {fmt(rollup.get('fault_rate'), '.0%')}",
    ]
    if rollup.get("resume_success_rate") is not None:
        bits.append(
            f"resume success: {fmt(rollup['resume_success_rate'], '.0%')}"
        )
    if agg is not None:
        bits.append(f"agg eval t-r/s: {fmt(agg, '.3g')}")
    if rollup.get("stale_runs"):
        bits.append(f"stale: {rollup['stale_runs']}")
    if rollup.get("pending_runs"):
        bits.append(f"pending: {rollup['pending_runs']}")
    L.append("   ".join(bits))
    if rows:
        L.append(
            f"{'run_id':<18} {'verdict':<10} {'att':>3} {'age':>6} "
            f"{'backend':<7} {'best':>9} {'t-r/s':>9} "
            f"{'top stage':<18} alerts"
        )
    for row in rows:
        shares = row.get("stage_shares") or {}
        top = max(shares.items(), key=lambda kv: kv[1])[0] if shares \
            else None
        top_s = f"{top} {shares[top]:.0%}" if top else "-"
        if row.get("compile_bound"):
            top_s += " [compile!]"
        resumed = "+r" if row.get("resumed") else ""
        L.append(
            f"{str(row.get('run_id'))[:18]:<18} "
            f"{str(row.get('verdict')):<10} "
            f"{str(row.get('attempt', 1)) + resumed:>3} "
            f"{_age_s(row.get('last_event_age_s')):>6} "
            f"{str(row.get('backend') or '-'):<7} "
            f"{fmt(row.get('best_loss')):>9} "
            f"{fmt(row.get('throughput_trees_rows_per_s')):>9} "
            f"{top_s:<18} "
            + (",".join(row.get("alerts") or []) or "-")
        )
    if alerts:
        L.append("alerts:")
        for a in alerts:
            L.append(
                f"  [{a['severity']}] {a['rule']} "
                f"run {str(a.get('run_id'))[:18]}: {a['message']}"
            )
    return "\n".join(L)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "root",
        help="fleet root: every events-*.jsonl under it (recursively) "
        "is one run",
    )
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument(
        "--once", action="store_true",
        help="render one frame and exit; exit 0 iff no alert at "
        "--fail-on severity or above fires (the CI gate)",
    )
    ap.add_argument(
        "--fail-on", choices=("info", "warning", "critical"),
        default="warning",
        help="minimum alert severity that flips the --once exit code "
        "(default warning: info notes never fail the gate)",
    )
    ap.add_argument(
        "--stall-after", type=float, default=None, metavar="SECONDS",
        help="last-event age past which an in-flight run alerts as "
        "stale (default 600)",
    )
    ap.add_argument(
        "--threshold", type=float, default=0.10,
        help="throughput-regression fraction vs the trajectory's best "
        "same-platform round (with --trajectory)",
    )
    ap.add_argument(
        "--trajectory", default=None, metavar="TRAJECTORY_JSON",
        help="opt the throughput-regression rule in against this "
        "TRAJECTORY.json",
    )
    ap.add_argument(
        "--metrics-out", default=None, metavar="FILE",
        help="also write the OpenMetrics exposition of each frame "
        "atomically to FILE (textfile-collector handoff)",
    )
    ns = ap.parse_args(argv)

    from symbolicregression_jl_tpu.telemetry.fleet import (
        STALE_AFTER_S,
        FleetScanner,
    )

    trajectory = None
    if ns.trajectory:
        import json

        with open(ns.trajectory) as f:
            trajectory = json.load(f)
    scanner = FleetScanner(
        ns.root,
        stale_after_s=(
            STALE_AFTER_S if ns.stall_after is None else ns.stall_after
        ),
        trajectory=trajectory,
        regression_threshold=ns.threshold,
    )
    last_lines = 0
    try:
        while True:
            index = scanner.refresh()
            frame = render_frame(index)
            if ns.metrics_out:
                from symbolicregression_jl_tpu.telemetry.export import (
                    render_openmetrics,
                    write_textfile,
                )

                write_textfile(
                    ns.metrics_out, render_openmetrics(fleet_index=index)
                )
            if last_lines and sys.stdout.isatty():
                sys.stdout.write(f"\x1b[{last_lines}F\x1b[0J")
            sys.stdout.write(frame + "\n")
            sys.stdout.flush()
            last_lines = frame.count("\n") + 1
            if ns.once:
                rank = {"info": 0, "warning": 1, "critical": 2}
                firing = [
                    a for a in index.get("alerts", [])
                    if rank.get(a.get("severity"), 2)
                    >= rank[ns.fail_on]
                ]
                return 1 if firing else 0
            time.sleep(ns.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
