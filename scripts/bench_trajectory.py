#!/usr/bin/env python
"""Bench-trajectory aggregator: the per-round artifacts -> one
machine-readable TRAJECTORY.json + human TRAJECTORY.md, with per-metric
regression detection.

Every round leaves ``BENCH_rNN.json`` (the headline bench capture) and
``MULTICHIP_rNN.json`` / ``MULTICHIP_LATEST.json`` (dryrun, then
real-search sharding evidence) at the repo root — but until now nothing
joined them, so "is throughput trending up? did roofline_fraction ever
move? did multichip scaling regress?" meant opening five files by hand
(ROADMAP #3 explicitly flags the untracked roofline_fraction trend; the
ROADMAP's own bench-trajectory paragraph was being maintained by hand).

This script builds, per metric, a round-indexed series and flags
regressions: a round whose value dropped more than ``--threshold``
(default 10%) below the best earlier value captured on the SAME
platform (a CPU-fallback round is not a regression against an on-chip
round — the platform column keeps the comparison apples-to-apples).
By default everything is a REPORT, not a gate: scripts/lint.py prints
it non-fatally and bench.py embeds a summary in its JSON, so a
regression is visible the moment the artifact lands without ever
blocking a capture. ``--gate`` opts the gate in: exit 2 when the
LATEST round regresses (any metric of the newest bench round — or the
'latest' multichip point — more than --threshold below the best
earlier same-platform round). Historical rounds never gate (they are
already shipped); lint.py prints the gate's would-be verdict on every
run so the flag is visible before anyone opts in.

Tolerant by design: BENCH_r04-style records whose ``parsed`` block is
empty fall back to scanning the step's stdout tail for the headline
JSON line; missing files and dryrun-era MULTICHIP records (no
scaling_efficiency yet) contribute null points, never errors.

Usage:
    python scripts/bench_trajectory.py [--repo DIR] [--threshold 0.1]
        [--no-write] [--print] [--gate]

Writes TRAJECTORY.json + TRAJECTORY.md at the repo root by default.
Exit is 0 unless the repo holds no rounds at all (1) or --gate is set
and the latest round regressed (2).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: higher-is-better metrics tracked round-over-round. value = headline
#: trees-rows/s; the rest are ratios in [0, ~]. Lower-is-better columns
#: (first_call_s) are recorded in the rounds but not regression-gated —
#: compile time is dominated by cache state, not code.
METRICS = (
    "throughput",
    "vs_baseline",
    "roofline_fraction",
    "roofline_modeled",
    "interp_bucketed_vs_flat",
    "pallas_bucketed_vs_flat",
    "multichip_scaling_efficiency",
    "multichip_speedup",
)

DEFAULT_THRESHOLD = 0.10

#: Pinned regression floors (ISSUE 17): a series whose checked-in
#: history is all-null (the column landed after the last capture round)
#: has no "best earlier round" to regress against, so its FIRST real
#: capture could land arbitrarily low without a flag. A pin seeds the
#: per-platform bar at the acceptance value the series shipped with
#: (round label "pin"); any real round that beats the pin replaces it
#: as the bar, exactly like a measured best. interp_bucketed_vs_flat's
#: 1.5 is the ISSUE 5 CPU acceptance target the ladder was merged on.
PINNED_FLOORS = {
    "interp_bucketed_vs_flat": {"cpu": 1.5},
}


def _headline_from_tail(tail: str):
    """BENCH_r04 regression-proofing: when the round record's ``parsed``
    is empty, the headline JSON line (the one carrying vs_baseline) is
    usually still in the captured stdout tail."""
    tail = tail or ""
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("{") and '"vs_baseline"' in line:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "vs_baseline" in obj:
                return obj
    # r04-style damage: the tail is one truncated mega-line with the
    # headline object EMBEDDED mid-string — raw_decode from each
    # '{"metric"' anchor still recovers it
    dec = json.JSONDecoder()
    for m in re.finditer(r'\{"metric"', tail):
        try:
            obj, _ = dec.raw_decode(tail, m.start())
        except ValueError:
            continue
        if isinstance(obj, dict) and "vs_baseline" in obj:
            return obj
    # last resort (the actual r04 file): only the `last_tpu` embed's
    # trailing on-chip headline pair survived the truncation. Those two
    # fields are, by construction (bench._last_tpu_block), the last
    # ON-CHIP bench values — platform tpu, not the fallback CPU run.
    pairs = re.findall(
        r'"value":\s*([0-9.eE+-]+),\s*"vs_baseline":\s*([0-9.eE+-]+)',
        tail,
    )
    if pairs:
        v, b = pairs[-1]
        try:
            return {
                "value": float(v), "vs_baseline": float(b),
                "platform": "tpu", "recovered_from": "last_tpu_tail",
            }
        except ValueError:
            pass
    return None


def _round_no(path: str):
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def round_label(r) -> str:
    """'r04' for integer rounds, the literal tag otherwise ('latest',
    None) — every formatter must go through this: a regression entry can
    legitimately carry round='latest' (the MULTICHIP_LATEST point)."""
    return f"r{r:02d}" if isinstance(r, int) else str(r)


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def _multichip_summary(rows):
    """The summary row of a benchmark/multichip.py capture (list of
    suite rows), or None."""
    if not isinstance(rows, list):
        return None
    return next(
        (r for r in rows
         if isinstance(r, dict) and r.get("case") == "summary"),
        None,
    )


def load_bench_round(path: str):
    """One BENCH_rNN.json -> a trajectory point (never raises)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return {"source": os.path.basename(path),
                "error": f"{type(e).__name__}: {e}"}
    parsed = data.get("parsed") or {}
    if not parsed.get("vs_baseline"):
        parsed = _headline_from_tail(data.get("tail")) or parsed
    point = {
        "round": _round_no(path) or data.get("n"),
        "source": os.path.basename(path),
        "platform": parsed.get("platform"),
        "tunnel_state": parsed.get("tunnel_state"),
        "throughput": _num(parsed.get("value")),
        "vs_baseline": _num(parsed.get("vs_baseline")),
        # PR 10 split the old roofline_fraction into measured/modeled:
        # the measured series keeps its historical column name (old
        # rounds recorded it as roofline_fraction), the modeled series
        # — non-null even on CPU-only rounds — charts alongside it
        "roofline_fraction": _num(
            parsed.get("roofline_measured",
                       parsed.get("roofline_fraction"))
        ),
        "roofline_modeled": _num(parsed.get("roofline_modeled")),
        "roofline_skip_reason": parsed.get("roofline_skip_reason"),
        "interp_bucketed_vs_flat": _num(
            parsed.get("interp_bucketed_vs_flat")
        ),
        "pallas_bucketed_vs_flat": _num(
            parsed.get("pallas_bucketed_vs_flat")
        ),
        "first_call_s": _num(parsed.get("first_call_s")),
    }
    mc = _multichip_summary(parsed.get("multichip"))
    if mc is not None:
        point["multichip_scaling_efficiency"] = _num(
            mc.get("scaling_efficiency")
        )
        point["multichip_speedup"] = _num(mc.get("speedup_vs_single"))
    return point


def load_multichip_record(path: str):
    """One MULTICHIP_*.json -> a trajectory point. Handles both the
    dryrun era ({n_devices, ok, rc, skipped, tail}) and the real-search
    capture format ({platform, rows: [...]})."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return {"source": os.path.basename(path),
                "error": f"{type(e).__name__}: {e}"}
    name = os.path.basename(path)
    point = {
        "round": _round_no(path) if _round_no(path) is not None
        else "latest",
        "source": name,
    }
    if "rows" in data:  # real-search capture (benchmark/multichip.py)
        point["platform"] = data.get("platform")
        mc = _multichip_summary(data.get("rows"))
        if mc is not None:
            point["multichip_scaling_efficiency"] = _num(
                mc.get("scaling_efficiency")
            )
            point["multichip_speedup"] = _num(mc.get("speedup_vs_single"))
            point["hof_bit_identical"] = mc.get("hof_bit_identical")
            point["n_devices"] = mc.get("n_devices")
    else:  # dryrun era
        point["dryrun_ok"] = bool(data.get("ok"))
        point["n_devices"] = data.get("n_devices")
    return point


def detect_regressions(points, metrics=METRICS,
                       threshold: float = DEFAULT_THRESHOLD,
                       pins=PINNED_FLOORS):
    """Per metric: flag every point whose value sits more than
    `threshold` below the best EARLIER value on the same platform.
    Null points neither regress nor set the bar. PINNED_FLOORS entries
    pre-seed the bar (round 'pin') for series with no measured history
    yet."""
    out = []
    for metric in metrics:
        best_by_platform = {
            plat: {"value": float(v), "round": "pin"}
            for plat, v in (pins or {}).get(metric, {}).items()
        }
        for p in points:
            v = _num(p.get(metric))
            plat = p.get("platform")
            if v is None:
                continue
            best = best_by_platform.get(plat)
            if best is not None and v < best["value"] * (1 - threshold):
                out.append({
                    "metric": metric,
                    "round": p.get("round"),
                    "platform": plat,
                    "value": v,
                    "best_prev": best["value"],
                    "best_prev_round": best["round"],
                    "drop_frac": round(1 - v / best["value"], 4),
                })
            if best is None or v > best["value"]:
                best_by_platform[plat] = {
                    "value": v, "round": p.get("round"),
                }
    return out


def latest_round_regressions(traj):
    """The regression entries the --gate verdict keys on: only flags on
    the LATEST bench round (highest integer round number) or the
    'latest'-tagged multichip point. Older rounds' flags stay a report —
    they already shipped; the gate exists to stop the NEXT one."""
    rounds = [
        p.get("round") for p in traj.get("rounds", [])
        if isinstance(p.get("round"), int)
    ]
    latest = max(rounds, default=None)
    return [
        r for r in traj.get("regressions", [])
        if r.get("round") == "latest"
        or (latest is not None and r.get("round") == latest)
    ]


def build_trajectory(repo: str = REPO,
                     threshold: float = DEFAULT_THRESHOLD):
    """Aggregate every checked-in round artifact under `repo` into the
    TRAJECTORY payload."""
    bench_paths = sorted(
        glob.glob(os.path.join(repo, "BENCH_r[0-9]*.json")),
        key=lambda p: _round_no(p) or 0,
    )
    mc_paths = sorted(
        glob.glob(os.path.join(repo, "MULTICHIP_r[0-9]*.json")),
        key=lambda p: _round_no(p) or 0,
    )
    latest = os.path.join(repo, "MULTICHIP_LATEST.json")
    rounds = [load_bench_round(p) for p in bench_paths]
    multichip = [load_multichip_record(p) for p in mc_paths]
    if os.path.exists(latest):
        multichip.append(load_multichip_record(latest))

    # merge multichip scaling onto the same-round bench point ONLY when
    # the platforms agree (regression detection groups by platform — a
    # TPU multichip capture must not inherit a CPU-fallback bench row's
    # label, or it would set/compare the wrong platform's bar);
    # unmerged carriers become their own series points, in round order,
    # with "latest" trailing
    by_round = {p.get("round"): p for p in rounds}
    series_points = list(rounds)
    for p in multichip:
        tgt = by_round.get(p.get("round"))
        plat_ok = tgt is not None and (
            p.get("platform") is None
            or tgt.get("platform") is None
            or p.get("platform") == tgt.get("platform")
        )
        if plat_ok:
            for k in ("multichip_scaling_efficiency", "multichip_speedup",
                      "hof_bit_identical"):
                if k in p and k not in tgt:
                    tgt[k] = p[k]
        elif any(k in p for k in ("multichip_scaling_efficiency",
                                  "multichip_speedup")):
            series_points.append(p)
    series_points.sort(
        key=lambda p: (0, p["round"]) if isinstance(p.get("round"), int)
        else (1, 0)
    )

    series = {
        m: [
            {"round": p.get("round"), "platform": p.get("platform"),
             "value": _num(p.get(m))}
            for p in series_points
        ]
        for m in METRICS
    }
    regressions = detect_regressions(series_points, threshold=threshold)
    summary = {}
    for m in METRICS:
        vals = [
            (p.get("round"), _num(p.get(m))) for p in series_points
            if _num(p.get(m)) is not None
        ]
        if vals:
            summary[m] = {
                "points": len(vals),
                "first": vals[0][1],
                "last": vals[-1][1],
                "best": max(v for _, v in vals),
                "best_round": max(vals, key=lambda rv: rv[1])[0],
            }
    traj = {
        "generated_by": "scripts/bench_trajectory.py",
        "threshold": threshold,
        "rounds": rounds,
        "multichip": multichip,
        "series": series,
        "summary": summary,
        "regressions": regressions,
    }
    # the subset the opt-in --gate exits nonzero on (and lint.py
    # surfaces as the gate's would-be verdict)
    traj["latest_regressions"] = latest_round_regressions(traj)
    return traj


def render_markdown(traj) -> str:
    """TRAJECTORY.md: one table over rounds, the regression list, and
    the per-metric summary."""
    lines = [
        "# Bench trajectory",
        "",
        "*Generated by `scripts/bench_trajectory.py` — do not edit; "
        "regenerate after a new BENCH/MULTICHIP capture lands "
        "(`python scripts/bench_trajectory.py`). Regression flags "
        "compare each round against the best earlier round on the same "
        f"platform (threshold {traj['threshold']:.0%}); they are a "
        "report, not a gate.*",
        "",
        "| round | platform | tunnel | trees-rows/s | vs_baseline | "
        "roofline | roofline (modeled) | bucketed/flat | "
        "pallas bucketed/flat | mc scaling | mc speedup |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]

    def cell(v, spec=".3g"):
        if isinstance(v, bool):
            return str(v).lower()
        if isinstance(v, (int, float)):
            return format(v, spec)
        return v if isinstance(v, str) else "—"

    for p in traj["rounds"]:
        roof = p.get("roofline_fraction")
        roof_cell = (
            cell(roof) if roof is not None
            else (p.get("roofline_skip_reason") or "—")
        )
        lines.append(
            f"| {round_label(p.get('round'))} | {cell(p.get('platform'))} "
            f"| {cell(p.get('tunnel_state'))} "
            f"| {cell(p.get('throughput'), '.3e')} "
            f"| {cell(p.get('vs_baseline'))} "
            f"| {roof_cell} "
            f"| {cell(p.get('roofline_modeled'))} "
            f"| {cell(p.get('interp_bucketed_vs_flat'))} "
            f"| {cell(p.get('pallas_bucketed_vs_flat'))} "
            f"| {cell(p.get('multichip_scaling_efficiency'))} "
            f"| {cell(p.get('multichip_speedup'))} |"
        )
    mc_latest = [p for p in traj["multichip"] if p.get("round") == "latest"]
    for p in mc_latest:
        lines.append(
            f"| latest | {cell(p.get('platform'))} | — | — | — | — | — "
            f"| — | — "
            f"| {cell(p.get('multichip_scaling_efficiency'))} "
            f"| {cell(p.get('multichip_speedup'))} |"
        )
    lines.append("")
    if traj["regressions"]:
        lines.append("## Regressions (vs best earlier same-platform round)")
        lines.append("")
        for r in traj["regressions"]:
            lines.append(
                f"- **{r['metric']}** {round_label(r['round'])} "
                f"[{r['platform']}]: {r['value']:.4g} is "
                f"{r['drop_frac']:.0%} below "
                f"{round_label(r['best_prev_round'])}'s "
                f"{r['best_prev']:.4g}"
            )
    else:
        lines.append("No regressions at the current threshold.")
    lines.append("")
    lines.append("## Per-metric summary")
    lines.append("")
    lines.append("| metric | points | first | last | best | best round |")
    lines.append("|---|---|---|---|---|---|")
    for m, s in traj["summary"].items():
        lines.append(
            f"| {m} | {s['points']} | {cell(s['first'])} "
            f"| {cell(s['last'])} | {cell(s['best'])} "
            f"| {s['best_round']} |"
        )
    lines.append("")
    lines.append(
        "Multichip rounds r01–r05 predate the real-search capture "
        "(dryrun only — no scaling series); `MULTICHIP_LATEST.json` "
        "carries the current sharded-vs-single measurement."
    )
    return "\n".join(lines) + "\n"


def bench_summary(traj) -> dict:
    """The compact block bench.py embeds in its one-line JSON: enough to
    see the trend and any flag without re-reading five files."""
    return {
        "rounds": len(traj["rounds"]),
        "throughput": [
            p["value"] for p in traj["series"]["throughput"]
        ],
        "roofline_fraction": [
            p["value"] for p in traj["series"]["roofline_fraction"]
        ],
        "roofline_modeled": [
            p["value"] for p in traj["series"]["roofline_modeled"]
        ],
        "multichip_scaling_efficiency": [
            p["value"]
            for p in traj["series"]["multichip_scaling_efficiency"]
        ],
        "regressions": traj["regressions"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap.add_argument(
        "--no-write", action="store_true",
        help="build and report only; do not touch TRAJECTORY.*",
    )
    ap.add_argument("--print", dest="do_print", action="store_true",
                    help="print the JSON payload to stdout")
    ap.add_argument(
        "--gate", action="store_true",
        help="exit 2 when the LATEST round regressed more than "
        "--threshold below the best earlier same-platform round "
        "(opt-in: the default run stays a report, never a gate)",
    )
    ns = ap.parse_args(argv)

    traj = build_trajectory(ns.repo, threshold=ns.threshold)
    if not traj["rounds"] and not traj["multichip"]:
        print("no BENCH_r*/MULTICHIP_* artifacts found", file=sys.stderr)
        return 1
    if not ns.no_write:
        with open(os.path.join(ns.repo, "TRAJECTORY.json"), "w") as f:
            json.dump(traj, f, indent=1, sort_keys=True)
            f.write("\n")
        with open(os.path.join(ns.repo, "TRAJECTORY.md"), "w") as f:
            f.write(render_markdown(traj))
        print(
            f"wrote TRAJECTORY.json + TRAJECTORY.md "
            f"({len(traj['rounds'])} bench rounds, "
            f"{len(traj['regressions'])} regression flags)",
            file=sys.stderr,
        )
    if ns.do_print:
        print(json.dumps(traj, indent=1, sort_keys=True))
    for r in traj["regressions"]:
        print(
            f"# regression: {r['metric']} {round_label(r['round'])} "
            f"{r['drop_frac']:.0%} below best", file=sys.stderr,
        )
    if ns.gate and traj["latest_regressions"]:
        mets = ", ".join(
            f"{r['metric']} ({r['drop_frac']:.0%})"
            for r in traj["latest_regressions"]
        )
        print(
            f"# GATE: latest round regressed — {mets}", file=sys.stderr
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
