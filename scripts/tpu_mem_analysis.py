#!/usr/bin/env python
"""AOT-compile the search iteration for the TPU target and print XLA's
memory analysis — compile only, nothing executes, so a flaky tunnel
window cannot be wedged by a faulting run.

Motivation (2026-08-02): equation_search at >=64 islands dies on chip
with an opaque UNAVAILABLE device error. XLA-CPU memory analysis of the
same program shows temp buffers of 11.7GB at 64x256 and 45GB at 64x1000
(v5e HBM is 16GB), dominated by optimize_islands_constants — but the
CPU build routes eval/optimize through the jnp interpreter, so the
TPU-target numbers (Pallas kernels, TPU layouts) must be measured to
confirm HBM OOM as the fault and to attribute it per stage.

Usage: python scripts/tpu_mem_analysis.py [--islands 64] [--npop 256]
"""

import argparse
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--islands", type=int, default=64)
    ap.add_argument("--npop", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    print(f"# device: {dev} ({dev.platform})", file=sys.stderr, flush=True)
    if dev.platform not in ("tpu", "axon"):
        sys.exit("# needs the TPU target — tunnel unavailable")

    from symbolicregression_jl_tpu.api import _make_init_fn
    from symbolicregression_jl_tpu.models.evolve import (
        optimize_islands_constants,
        s_r_cycle_islands,
        simplify_population_islands,
    )
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.parallel.migration import (
        merge_hofs_across_islands,
        migrate,
    )

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "sqrt", "square"],
        npop=args.npop, npopulations=args.islands,
        ncycles_per_iteration=100, maxsize=18, seed=0,
    )
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.uniform(1, 3, (2, 1000)).astype(np.float32))
    y = jnp.asarray(np.asarray(X[0] * X[1]))
    baseline = jnp.asarray(1.0, jnp.float32)
    scalars = options.traced_scalars()
    keys = jax.random.split(jax.random.PRNGKey(0), args.islands)
    init = _make_init_fn(options, 2, False)
    states = jax.eval_shape(
        lambda k: init(k, X, y, baseline, scalars), keys
    )
    cm = jnp.asarray(options.maxsize, jnp.int32)
    opts_b = options.bind_scalars(scalars)
    kk = jax.random.PRNGKey(1)
    okeys = jax.random.split(kk, args.islands)

    def report(name, f, *fargs):
        t0 = time.time()
        try:
            compiled = jax.jit(f).lower(*fargs).compile()
        except Exception as e:
            print(
                f"{name}: COMPILE-FAIL {type(e).__name__}: "
                f"{str(e)[:160]} ({time.time() - t0:.0f}s)",
                flush=True,
            )
            return
        ma = compiled.memory_analysis()
        if ma is None:  # runtime doesn't implement memory_analysis
            print(f"{name}: compiled OK, memory_analysis unavailable "
                  f"({time.time() - t0:.0f}s)", flush=True)
            return
        print(
            f"{name}: temp={ma.temp_size_in_bytes / 1e6:.0f}MB "
            f"args={ma.argument_size_in_bytes / 1e6:.0f}MB "
            f"({time.time() - t0:.0f}s)",
            flush=True,
        )

    report("init", lambda k: init(k, X, y, baseline, scalars), keys)
    report(
        "cycle100",
        lambda s: s_r_cycle_islands(s, cm, X, y, None, baseline, opts_b),
        states,
    )
    report(
        "simplify",
        lambda s: simplify_population_islands(
            s, cm, X, y, None, baseline, opts_b
        ),
        states,
    )
    report(
        "optimize",
        lambda k, s: optimize_islands_constants(
            k, s, X, y, None, baseline, opts_b
        ),
        okeys, states,
    )
    report(
        "merge_migrate",
        lambda k, s: migrate(
            k, s, merge_hofs_across_islands(s.hof), opts_b
        ),
        kk, states,
    )


if __name__ == "__main__":
    main()
