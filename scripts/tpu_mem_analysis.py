#!/usr/bin/env python
"""AOT-compile the search stages for the TPU target and print XLA's
memory analysis — compile only, nothing executes, so a flaky tunnel
window cannot be wedged by a faulting run.

Motivation (2026-08-02): equation_search at >=64 islands dies on chip
with an opaque UNAVAILABLE device error. XLA-CPU memory analysis of the
same program shows temp buffers of 11.7GB at 64x256 and 45GB at 64x1000
(v5e HBM is 16GB), dominated by optimize_islands_constants — but the
CPU build routes eval/optimize through the jnp interpreter, so the
TPU-target numbers (Pallas kernels, TPU layouts) must be measured to
confirm HBM OOM as the fault and to attribute it per stage.

The stage programs and the AOT plumbing live in
symbolicregression_jl_tpu.analysis.memory (the srmem engine — this
script is its on-TPU face; CI runs the same engine's modeled numbers on
CPU via `python -m symbolicregression_jl_tpu.analysis --only memory`).
Each stage also prints the srmem live-buffer model alongside XLA's
number, so the model's tracking can be eyeballed against ground truth.

Usage: python scripts/tpu_mem_analysis.py [--islands 64] [--npop 256]
           [--rows 1000]
"""

import argparse
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--islands", type=int, default=64)
    ap.add_argument("--npop", type=int, default=256)
    ap.add_argument("--rows", type=int, default=1000)
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"# device: {dev} ({dev.platform})", file=sys.stderr, flush=True)
    if dev.platform not in ("tpu", "axon"):
        sys.exit("# needs the TPU target — tunnel unavailable")

    from symbolicregression_jl_tpu.analysis.memory import (
        build_stage_programs,
        live_buffer_peak,
        xla_stage_analysis,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "sqrt", "square"],
        npop=args.npop, npopulations=args.islands,
        ncycles_per_iteration=100, maxsize=18, seed=0,
    )
    programs = build_stage_programs(
        options, nfeatures=2, nrows=args.rows
    )
    for name, (fn, fargs) in programs.items():
        t0 = time.time()
        try:
            modeled = live_buffer_peak(jax.make_jaxpr(fn)(*fargs))
        except Exception as e:  # keep reporting the remaining stages
            print(f"{name}: TRACE-FAIL {type(e).__name__}: {e} "
                  f"({time.time() - t0:.0f}s)", flush=True)
            continue
        res = xla_stage_analysis(fn, fargs)
        dt = time.time() - t0
        if "error" in res:
            print(f"{name}: COMPILE-FAIL {res['error']} ({dt:.0f}s)",
                  flush=True)
        elif res.get("unavailable"):
            print(f"{name}: compiled OK, memory_analysis unavailable "
                  f"({dt:.0f}s)", flush=True)
        else:
            print(
                f"{name}: temp={res['temp_bytes'] / 1e6:.0f}MB "
                f"args={res['argument_bytes'] / 1e6:.0f}MB "
                f"modeled={modeled['peak_bytes'] / 1e6:.0f}MB "
                f"({dt:.0f}s)",
                flush=True,
            )


if __name__ == "__main__":
    main()
