#!/usr/bin/env python
"""Bisect the at-scale (64-island) TPU device fault stage by stage.

Background (2026-08-01): `equation_search` at npopulations>=64 dies on the
real chip with `UNAVAILABLE: TPU device error — often a kernel fault`,
while <=16x256 searches, the 16384-tree eval kernel, and the identical
64x1000 program on XLA-CPU all run clean. The fault reproduces with
eval_backend="jnp" and with the constant optimizer disabled, so it lives
somewhere else in the jitted iteration. This script runs each stage of
`api._make_iteration_fn`'s pipeline in a FRESH subprocess (a faulted TPU
client wedges its process — later calls fail instantly) and reports
OK/FAIL per stage, so one tunnel window pinpoints the faulting stage.

Usage: python scripts/scale_fault_bisect.py [--islands 64] [--npop 256]
"""

import os
import signal
import subprocess
import sys
import time

STAGE_CODE = """
import numpy as np, jax, jax.numpy as jnp
import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options
from symbolicregression_jl_tpu.models.evolve import (
    s_r_cycle_islands, simplify_population_islands, optimize_islands_constants,
)
from symbolicregression_jl_tpu.parallel.migration import (
    merge_hofs_across_islands,
    migrate,
)
from symbolicregression_jl_tpu.api import _make_init_fn

ISLANDS, NPOP, NCYC = {islands}, {npop}, {ncyc}
STAGE = {stage!r}

options = make_options(
    binary_operators=["+", "-", "*", "/"],
    unary_operators=["cos", "exp", "sqrt", "square"],
    npop=NPOP, npopulations=ISLANDS, ncycles_per_iteration=NCYC,
    maxsize=18, seed=0,
)
rng = np.random.default_rng(0)
X = jnp.asarray(rng.uniform(1, 3, (2, 1000)).astype(np.float32))
y = jnp.asarray(np.asarray(X[0] * X[1]))
baseline = jnp.asarray(float(np.var(np.asarray(y))), jnp.float32)
scalars = options.traced_scalars()
keys = jax.random.split(jax.random.PRNGKey(0), ISLANDS)

init = _make_init_fn(options, 2, False)
states = init(keys, X, y, baseline, scalars)
jax.block_until_ready(states.pop.scores)
print("MARK init ok", flush=True)
if STAGE == "init":
    raise SystemExit(0)

curmaxsize = jnp.asarray(options.maxsize, jnp.int32)
opts_b = options.bind_scalars(scalars)

if STAGE in ("cycle", "cycle_long"):
    f = jax.jit(lambda s: s_r_cycle_islands(
        s, curmaxsize, X, y, None, baseline, opts_b))
    states = f(states)
    jax.block_until_ready(states.pop.scores)
elif STAGE == "simplify":
    f = jax.jit(lambda s: simplify_population_islands(
        s, curmaxsize, X, y, None, baseline, opts_b))
    states = f(states)
    jax.block_until_ready(states.pop.scores)
elif STAGE == "optimize":
    okeys = jax.random.split(jax.random.PRNGKey(1), ISLANDS)
    f = jax.jit(lambda k, s: optimize_islands_constants(
        k, s, X, y, None, baseline, opts_b))
    states = f(okeys, states)
    jax.block_until_ready(states.pop.scores)
elif STAGE == "merge_migrate":
    def mm(k, s):
        ghof = merge_hofs_across_islands(s.hof)
        return migrate(k, s, ghof, opts_b), ghof
    f = jax.jit(mm)
    states, ghof = f(jax.random.PRNGKey(2), states)
    jax.block_until_ready(ghof.losses)
elif STAGE == "full":
    from symbolicregression_jl_tpu.api import _make_iteration_fn
    it = _make_iteration_fn(options, False)
    states, ghof = it(states, jax.random.PRNGKey(3), curmaxsize,
                      X, y, baseline, scalars)
    jax.block_until_ready(ghof.losses)
print("MARK stage ok", flush=True)
"""

STAGES = [
    ("init", 2), ("cycle", 2), ("cycle_long", 100), ("simplify", 2),
    ("optimize", 2), ("merge_migrate", 2), ("full", 100),
]


def _run_stage(code, timeout=900):
    """Run one stage in its own process GROUP and kill the whole group on
    timeout — a wedged axon client must not keep holding the tunnel's one
    slot after the probe gives up (same guard as tpu_watcher's
    probe_platform)."""
    p = subprocess.Popen(
        [sys.executable, "-c", code], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except Exception:
            p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:
            pass
        return None, "", ""


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--islands", type=int, default=64)
    ap.add_argument("--npop", type=int, default=256)
    ap.add_argument("--stage", choices=[s for s, _ in STAGES], default=None)
    ns = ap.parse_args()
    for stage, ncyc in STAGES:
        if ns.stage and stage != ns.stage:
            continue
        t0 = time.time()
        code = STAGE_CODE.format(
            islands=ns.islands, npop=ns.npop, ncyc=ncyc, stage=stage
        )
        rc, out, err = _run_stage(code)
        if rc is None:
            print(f"{stage}: HANG (900s) — tunnel likely down", flush=True)
            break
        ok = rc == 0 and (
            "MARK stage ok" in out
            or (stage == "init" and "MARK init ok" in out)
        )
        tail = [ln for ln in (err or "").splitlines() if ln.strip()][-2:]
        print(
            f"{stage}: {'OK' if ok else 'FAIL'} {time.time() - t0:.0f}s"
            + ("" if ok else f"  | {' / '.join(tail)[:200]}"),
            flush=True,
        )


if __name__ == "__main__":
    main()
