#!/usr/bin/env python
"""Bisect the at-scale (64x1000) TPU device fault, stage by stage.

History: `equation_search` at npopulations>=64 died on chip with
`UNAVAILABLE: TPU device error` in rounds 3 and 4. Round 3's instance was
an HBM OOM in the portable constant-opt path (fixed: 2048-instance
chunking; confirmed gone by TPU-target compile-time memory analysis,
BASELINE.md 2026-08-02). Round 4's recurrence is execution-level and
undiagnosed: the same 64x1000x25 iteration (suite
search_iteration_northstar) faulted at 15:58 2026-08-02 with the fix in
the build, while every stage fits in HBM at compile time.

This script localizes it. Each stage runs the EXACT suite-northstar
configuration (binary +,-,*,/; unary cos,exp; npop 1000 x 64 islands x
25 cycles; maxsize 20; 1x1000 gaussian-pdf dataset — matching
benchmark/suite.py bench_search_iteration_northstar) in a FRESH
subprocess group (a faulted TPU client wedges its process; a wedged axon
client must not hold the tunnel slot), and reports one JSON line per
stage so the tpu_watcher's `json` capture keeps every verdict even if a
later stage kills the window.

The `kernel_macro_*` duration ladder tests the leading hypothesis
directly: every program that has ever completed on this tunnel runs a
few seconds per device call; the northstar iteration is the only
program shape that faults AND the only one whose single fused call runs
much longer. The ladder runs the known-good eval kernel — nothing else —
inside ONE jit call stretched to ~5 s / ~30 s / ~90 s / ~240 s of device
time. If the fault is a per-call deadline in the tunnel/runtime, the
ladder faults at some duration with zero search machinery involved; if
the ladder is clean at 240 s, the fault is in a search stage and the
stage rows below localize it.

`full` is the exact fused single-call iteration the suite runs;
`full_chunked` is the same iteration under max_cycles_per_dispatch=5
(api._make_iteration_driver) — the production mitigation if long single
calls are the trigger.

Usage: python scripts/scale_fault_bisect.py [--islands 64] [--npop 1000]
       [--stage NAME] [--skip-ladder]
"""

import json
import os
import signal
import subprocess
import sys
import time

COMMON_SETUP = """
import numpy as np, jax, jax.numpy as jnp
import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.models.options import make_options

ISLANDS, NPOP, NCYC = {islands}, {npop}, {ncyc}
STAGE = {stage!r}
print("MARK platform=" + jax.devices()[0].platform, flush=True)

def northstar_options(**kw):
    # EXACTLY benchmark/suite.py bench_search_iteration_northstar
    base = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=NPOP, npopulations=ISLANDS, ncycles_per_iteration=NCYC,
        maxsize=20,
    )
    base.update(kw)
    return make_options(**base)

def northstar_dataset():
    rng = np.random.default_rng(0)
    theta = rng.uniform(1.0, 3.0, 1000).astype(np.float32)
    X = jnp.asarray(theta[None, :])
    y = jnp.asarray(
        (np.exp(-(theta ** 2) / 2.0) / np.sqrt(2 * np.pi)).astype(np.float32)
    )
    baseline = jnp.float32(float(jnp.var(y)))
    return X, y, baseline
"""

LADDER_CODE = COMMON_SETUP + """
# Duration ladder: the production Pallas eval kernel (the program shape
# proven at 1.0e9 t-r/s in every bench run) stretched to a target
# single-call duration with a fori_loop. The tree constants depend on
# the loop index so XLA cannot hoist the kernel out of the loop.
import time
from symbolicregression_jl_tpu.models.fitness import score_trees
from symbolicregression_jl_tpu.models.mutate_device import (
    gen_random_tree_fixed_size,
)

TARGET_S = {target_s}
options = northstar_options()
n_trees, n_rows = 8192, 1000
sizes = jax.random.randint(jax.random.PRNGKey(1), (n_trees,), 3, 20)
trees = jax.vmap(
    lambda k, s: gen_random_tree_fixed_size(
        k, s, 1, options.operators, options.max_len
    )
)(jax.random.split(jax.random.PRNGKey(0), n_trees), sizes)
X, y, baseline = northstar_dataset()

def one(i, acc):
    t = trees._replace(cval=trees.cval + (acc * 0 + i).astype(jnp.float32) * 1e-9)
    s, l = score_trees(t, X, y, None, baseline, options)
    return acc + jnp.nansum(jnp.where(jnp.isfinite(l), l, 0.0))

@jax.jit
def macro(n):
    return jax.lax.fori_loop(0, n, one, jnp.float32(0.0))

# calibrate per-iter cost with a short call, then one long call
t0 = time.time(); jax.block_until_ready(macro(3)); cal3 = time.time() - t0
t0 = time.time(); jax.block_until_ready(macro(10)); cal = (time.time() - t0) / 10
n = max(10, int(TARGET_S / max(cal, 1e-4)))
print(f"MARK calibrated {{cal*1e3:.1f}} ms/iter -> n={{n}}", flush=True)
t0 = time.time()
jax.block_until_ready(macro(n))
dt = time.time() - t0
print(f"MARK ladder ok single_call_s={{dt:.1f}}", flush=True)
"""

STAGE_CODE = COMMON_SETUP + """
from symbolicregression_jl_tpu.models.evolve import (
    s_r_cycle_islands, simplify_population_islands,
    optimize_islands_constants,
)
from symbolicregression_jl_tpu.parallel.migration import (
    merge_hofs_across_islands, migrate,
)
from symbolicregression_jl_tpu.api import _make_init_fn

options = northstar_options(**({opt_kwargs!r}))
X, y, baseline = northstar_dataset()
scalars = options.traced_scalars()
keys = jax.random.split(jax.random.PRNGKey(0), ISLANDS)

init = _make_init_fn(options, 1, False)
states = init(keys, X, y, baseline, scalars)
jax.block_until_ready(states.pop.scores)
print("MARK init ok", flush=True)
if STAGE == "init":
    raise SystemExit(0)

curmaxsize = jnp.asarray(options.maxsize, jnp.int32)
opts_b = options.bind_scalars(scalars)

if STAGE.startswith("cycle"):
    f = jax.jit(lambda s: s_r_cycle_islands(
        s, curmaxsize, X, y, None, baseline, opts_b, ncycles=NCYC))
    states = f(states)
    jax.block_until_ready(states.pop.scores)
elif STAGE == "simplify":
    f = jax.jit(lambda s: simplify_population_islands(
        s, curmaxsize, X, y, None, baseline, opts_b))
    states = f(states)
    jax.block_until_ready(states.pop.scores)
elif STAGE.startswith("optimize"):
    okeys = jax.random.split(jax.random.PRNGKey(1), ISLANDS)
    f = jax.jit(lambda k, s: optimize_islands_constants(
        k, s, X, y, None, baseline, opts_b))
    states = f(okeys, states)
    jax.block_until_ready(states.pop.scores)
elif STAGE == "merge_migrate":
    def mm(k, s):
        ghof = merge_hofs_across_islands(s.hof)
        return migrate(k, s, ghof, opts_b), ghof
    f = jax.jit(mm)
    states, ghof = f(jax.random.PRNGKey(2), states)
    jax.block_until_ready(ghof.losses)
elif STAGE.startswith("full"):
    from symbolicregression_jl_tpu.api import _make_iteration_driver
    it = _make_iteration_driver(options, False)
    states, ghof = it(states, jax.random.PRNGKey(3), curmaxsize,
                      X, y, baseline, scalars)
    jax.block_until_ready(ghof.losses)
print("MARK stage ok", flush=True)
"""

# (name, ncyc override, options kwargs, timeout_s). ncyc matters only for
# the cycle/full stages; 25 is the production northstar count.
STAGES = [
    ("init", 25, {}, 600),
    ("kernel_macro_5s", 25, {"target_s": 5}, 600),
    ("kernel_macro_30s", 25, {"target_s": 30}, 600),
    ("kernel_macro_90s", 25, {"target_s": 90}, 900),
    ("kernel_macro_240s", 25, {"target_s": 240}, 1200),
    ("cycle_2", 2, {}, 900),
    ("cycle_25", 25, {}, 1800),
    ("cycle_2_jnp", 2, {"eval_backend": "jnp"}, 900),
    ("simplify", 25, {}, 900),
    ("optimize", 25, {}, 1800),
    ("optimize_jnp", 25, {"optimizer_backend": "jnp"}, 1800),
    ("merge_migrate", 25, {}, 600),
    ("full_chunked", 25, {"max_cycles_per_dispatch": 5}, 2400),
    ("full", 25, {}, 2400),
]


def _run_stage(code, timeout):
    """Own process GROUP, killed wholesale on timeout — a wedged axon
    client must not keep holding the tunnel's one slot."""
    p = subprocess.Popen(
        [sys.executable, "-c", code], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except Exception:
            p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:
            pass
        return None, "", ""


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--islands", type=int, default=64)
    ap.add_argument("--npop", type=int, default=1000)
    ap.add_argument("--stage", choices=[s[0] for s in STAGES], default=None)
    ap.add_argument("--skip-ladder", action="store_true")
    ns = ap.parse_args()
    any_fail = False
    for stage, ncyc, kwargs, timeout in STAGES:
        if ns.stage and stage != ns.stage:
            continue
        if ns.skip_ladder and stage.startswith("kernel_macro"):
            continue
        t0 = time.time()
        if stage.startswith("kernel_macro"):
            code = LADDER_CODE.format(
                islands=ns.islands, npop=ns.npop, ncyc=ncyc, stage=stage,
                target_s=kwargs["target_s"],
            )
        else:
            code = STAGE_CODE.format(
                islands=ns.islands, npop=ns.npop, ncyc=ncyc, stage=stage,
                opt_kwargs=kwargs,
            )
        rc, out, err = _run_stage(code, timeout)
        dt = round(time.time() - t0, 1)
        marks = [ln for ln in (out or "").splitlines()
                 if ln.startswith("MARK")]
        plat = next(
            (m.split("platform=", 1)[1] for m in marks if "platform=" in m),
            None,
        )
        if rc is None:
            rec = {"bisect": stage, "ok": False, "hang": True,
                   "seconds": dt, "timeout_s": timeout, "marks": marks,
                   "platform": plat}
            print(json.dumps(rec), flush=True)
            # a hang usually means the tunnel died mid-stage: stop
            # burning the window on stages that can no longer answer,
            # and exit nonzero so the watcher retries the bisect in the
            # next window (attempt-capped there)
            print(json.dumps({"bisect": "verdict", "all_ok": False,
                              "aborted_on_hang": stage}), flush=True)
            raise SystemExit(2)
        ok = rc == 0 and (
            "MARK stage ok" in out
            or "MARK ladder ok" in out
            or (stage == "init" and "MARK init ok" in out)
        )
        any_fail = any_fail or not ok
        tail = [ln for ln in (err or "").splitlines() if ln.strip()][-3:]
        rec = {
            "bisect": stage, "ok": ok, "rc": rc, "seconds": dt,
            "islands": ns.islands, "npop": ns.npop, "marks": marks,
            "platform": plat,
        }
        if not ok:
            rec["err_tail"] = " / ".join(tail)[:400]
        print(json.dumps(rec), flush=True)
    print(json.dumps({"bisect": "verdict",
                      "all_ok": not any_fail}), flush=True)


if __name__ == "__main__":
    main()
