#!/usr/bin/env python
"""srtop — live terminal dashboard over a search's telemetry event log.

`top` for a symbolic-regression run: point it at an event log (or a
telemetry directory — it follows the newest ``events-*.jsonl``) and it
renders, refreshing in place:

* run header — run id, backend, mesh/device state, last-event age (the
  liveness signal: a growing age on an ``incomplete`` run is the "dead
  vs mid-run fault" distinction ROADMAP #4 cares about);
* per-stage wall-time split (the span breakdown, summed live) with a
  utilization column once the run's srprof ``profile`` events land:
  each stage's wall share next to its modeled-cost share, flagging
  (``!``) stages whose wall share far exceeds their modeled share —
  the "this stage burns time its work doesn't justify" signal;
* best/mean loss per island + a sparkline of the global best-loss
  trajectory, population diversity, exact hypervolume;
* mutation acceptance and memo-bank hit rates;
* the fault/tunnel/saved-state tail.

Deliberately curses-free: plain ANSI rewind-and-redraw on TTYs (the
same trick utils/progress.ProgressBar uses), plain append when piped —
so it works over ssh, inside tmux, and in CI logs. Reading is
incremental (byte offset + partial-line buffer), so tailing a large log
costs only the new bytes, and a HALF-WRITTEN last line is simply held
until its newline arrives — safe against a log being written this
moment, or truncated by a kill.

Usage:
    python scripts/srtop.py RUN_DIR_OR_LOG [--interval 2] [--once]

``--once`` renders a single frame and exits (also the test hook / CI
gate): its exit status is 0 only when the tailed log's run-doctor
verdict is ``healthy`` (nonzero otherwise — so CI can gate on
``srtop.py DIR --once``). The verdict comes from the real doctor
(telemetry.analyze, imported lazily with the platform pinned to CPU);
the follow-loop dashboard itself stays stdlib-only. The dashboard
never modifies the log.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

# stdlib-only on purpose: tailing a log must never pay (or hang on)
# the package/jax import — resolve() below mirrors analyze.resolve_log

SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 40) -> str:
    """Unicode sparkline of the last `width` finite values (log-scaled
    when the spread warrants it — loss trajectories span decades)."""
    vals = [
        float(v) for v in values
        if isinstance(v, (int, float)) and math.isfinite(v)
    ][-width:]
    if not vals:
        return ""
    if min(vals) > 0 and max(vals) / min(vals) > 50:
        vals = [math.log10(v) for v in vals]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        SPARK[int((v - lo) / span * (len(SPARK) - 1))] for v in vals
    )


class LogTail:
    """Incremental reader of one JSONL event log. ``poll()`` returns the
    complete NEW events since the last call; a partial trailing line
    (mid-write) stays buffered until its newline lands; a truncated
    file (log rotated / rewritten shorter) resets the tail."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.buf = ""

    def poll(self):
        events = []
        try:
            size = os.path.getsize(self.path)
            if size < self.offset:
                self.offset, self.buf = 0, ""  # rewritten: start over
            with open(self.path) as f:
                f.seek(self.offset)
                chunk = f.read()
                self.offset = f.tell()
        except OSError:
            return events
        self.buf += chunk
        while "\n" in self.buf:
            line, self.buf = self.buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue  # corrupt line: skip, keep tailing
            if isinstance(e, dict):
                events.append(e)
        return events


class Dashboard:
    """Accumulates events and renders frames."""

    #: utilization flag threshold: a stage whose wall-time share
    #: exceeds its modeled-cost share by this factor (and is not
    #: negligible) gets the '!' marker
    SKEW_FLAG = 2.0
    SKEW_MIN_WALL = 0.10

    def __init__(self):
        self.start = {}
        self.stages = {}
        self.profile = {}        # stage -> last srprof profile event
        self.compile_s = {}      # stage -> summed compile seconds
        self.metrics_tail = []   # last N metrics events
        self.best_series = []
        self.progress_last = None
        self.faults = []
        self.tunnel = None
        self.saved = None
        self.ended = None
        self.t_last = None
        self.n_events = 0
        self.MAX_TAIL = 512

    def feed(self, events) -> None:
        for e in events:
            self.n_events += 1
            t = e.get("t")
            if isinstance(t, (int, float)):
                self.t_last = max(self.t_last or t, t)
            typ = e.get("type")
            if typ == "run_start":
                self.start = e
            elif typ == "span":
                row = self.stages.setdefault(
                    e.get("name"), {"total_s": 0.0, "count": 0}
                )
                d = e.get("duration_s")
                if isinstance(d, (int, float)) and math.isfinite(d):
                    row["total_s"] += d
                    row["count"] += 1
            elif typ == "metrics":
                self.metrics_tail.append(e)
                del self.metrics_tail[:-4]
                g = (e.get("snapshot") or {}).get("gauges") or {}
                self.best_series.append(g.get("best_loss"))
                del self.best_series[:-self.MAX_TAIL]
            elif typ == "profile":
                if isinstance(e.get("stage"), str):
                    self.profile[e["stage"]] = e
            elif typ == "compile":
                d = e.get("duration_s")
                if isinstance(e.get("name"), str) and isinstance(
                    d, (int, float)
                ) and math.isfinite(d):
                    self.compile_s[e["name"]] = (
                        self.compile_s.get(e["name"], 0.0) + d
                    )
            elif typ == "progress":
                self.progress_last = e
            elif typ == "dispatch_fault":
                self.faults.append(e)
            elif typ == "tunnel_state":
                self.tunnel = e.get("state")
            elif typ == "saved_state":
                self.saved = e
            elif typ == "run_end":
                self.ended = e

    def render(self, now=None) -> str:
        now = now or time.time()
        L = []

        def fmt(v, spec=".4g"):
            if isinstance(v, (int, float)) and math.isfinite(v):
                return format(v, spec)
            return "-"

        s = self.start
        mesh = s.get("mesh_shape")
        hdr = (
            f"srtop — run {s.get('run', '?')} [{s.get('backend', '?')}] "
            f"devices={s.get('n_devices', len(s.get('devices', []) or []) or '?')}"
        )
        if mesh:
            hdr += f" mesh={mesh}"
        L.append(hdr)
        age = (now - self.t_last) if self.t_last else None
        if self.ended is not None:
            state = (
                f"ENDED — {fmt(self.ended.get('num_evals'), '.3g')} evals "
                f"in {fmt(self.ended.get('search_time_s'), '.1f')}s"
            )
        elif self.faults:
            f = self.faults[-1]
            state = (
                f"FAULTED at iteration {f.get('iteration')} "
                f"({f.get('error_type')}) — "
                + ("resumable: saved_state on disk" if self.saved
                   else "no saved_state")
            )
        else:
            state = "RUNNING"
        L.append(
            f"state: {state}   last event {fmt(age, '.1f')}s ago   "
            f"events: {self.n_events}"
            + (f"   tunnel: {self.tunnel}" if self.tunnel else "")
        )

        m = self.metrics_tail[-1] if self.metrics_tail else None
        if m is not None:
            g = (m.get("snapshot") or {}).get("gauges") or {}
            L.append(
                f"iter {m.get('iteration')}: best {fmt(g.get('best_loss'))}"
                f"  mean {fmt(g.get('mean_loss'))}"
                f"  diversity {fmt(g.get('population_diversity'), '.3f')}"
                f"  hypervolume {fmt(g.get('hof_hypervolume'), '.4f')}"
                f"  hof {fmt(g.get('hof_size'), '.0f')}"
            )
            rates = []
            if g.get("mutation_accept_rate") is not None:
                rates.append(
                    f"mut-accept {fmt(g.get('mutation_accept_rate'), '.3f')}"
                )
            if g.get("memo_hit_rate") is not None:
                rates.append(
                    f"memo-hit {fmt(g.get('memo_hit_rate'), '.3f')}"
                )
            if g.get("cycles_per_second") is not None:
                rates.append(
                    f"cycles/s {fmt(g.get('cycles_per_second'), '.3g')}"
                )
            if g.get("num_evals_total") is not None:
                rates.append(
                    f"evals {fmt(g.get('num_evals_total'), '.3g')}"
                )
            if rates:
                L.append("  ".join(rates))
            spark = sparkline(self.best_series)
            if spark:
                L.append(f"best loss: {spark}")
            pi = m.get("per_island") or {}
            best_i = pi.get("best_loss") or []
            mean_i = pi.get("mean_loss") or []
            div_i = pi.get("diversity") or []
            if best_i:
                show = min(len(best_i), 8)
                L.append("island     " + " ".join(
                    f"{i:>8d}" for i in range(show)
                ) + (" ..." if len(best_i) > show else ""))
                L.append("  best     " + " ".join(
                    f"{fmt(v, '.3g'):>8}" for v in best_i[:show]
                ))
                if mean_i:
                    L.append("  mean     " + " ".join(
                        f"{fmt(v, '.3g'):>8}" for v in mean_i[:show]
                    ))
                if div_i:
                    L.append("  diversity" + " ".join(
                        f"{fmt(v, '.2f'):>8}" for v in div_i[:show]
                    ))

        if self.stages:
            # wall shares with compile time folded out (the doctor's
            # convention: a first dispatch's span includes its compile)
            net = {
                name: max(
                    v["total_s"] - self.compile_s.get(name, 0.0), 0.0
                )
                for name, v in self.stages.items()
            }
            total = sum(net.values()) or 1.0
            # modeled-cost shares from the srprof profile events
            # (present once a telemetry run ends); utilization = wall
            # share x modeled share, '!' when wall far exceeds model.
            # Per-dispatch modeled flops weight by the live span COUNT
            # — the wall side sums every dispatch, so an unweighted
            # share would inflate per-iteration stages' skew by
            # niterations vs the one-shot probe stages
            mf = {
                s: p["flops"] * self.stages.get(
                    s, {"count": 0}
                )["count"]
                for s, p in self.profile.items()
                if isinstance(p.get("flops"), (int, float))
            }
            mtot = sum(mf.values()) or None
            parts = []
            for name, wall in sorted(net.items(), key=lambda kv: -kv[1]):
                ws = wall / total
                cell = f"{name} {wall:.1f}s ({100 * ws:.0f}%"
                if mtot and name in mf:
                    ms = mf[name] / mtot
                    cell += f"|mod {100 * ms:.0f}%"
                    if (ws > self.SKEW_MIN_WALL
                            and ms > 0
                            and ws / ms > self.SKEW_FLAG):
                        cell += " !"
                parts.append(cell + ")")
            L.append("stages: " + "  ".join(parts))
            ctot = sum(self.compile_s.values())
            if ctot:
                L.append(f"compile: {ctot:.1f}s (excluded from shares)")
        return "\n".join(L)


def _doctor_verdict(events):
    """The --once CI gate: run the real doctor (telemetry.analyze) over
    the collected events. Imported lazily with the platform pinned to
    CPU (the analyzer itself never touches jax, but the package import
    must not route backend init at a TPU tunnel); returns None when the
    package is unavailable — the dashboard itself stays stdlib-only and
    a box without the package still renders frames."""
    try:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from symbolicregression_jl_tpu.telemetry.analyze import analyze_run
    except Exception:
        return None
    try:
        return analyze_run(events).get("verdict")
    except Exception:
        return None


def resolve(path: str):
    """The log file to tail right now, or None while nothing exists yet
    (a dir with no events-*.jsonl, or a log path that has not been
    created / was cleaned up — both render the waiting frame rather
    than an empty 'run ?' dashboard that never fills)."""
    if os.path.isdir(path):
        cands = [
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("events-") and f.endswith(".jsonl")
        ]
        return max(cands, key=os.path.getmtime) if cands else None
    return path if os.path.exists(path) else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "log", help="event log path, or a telemetry dir (follows the "
        "newest events-*.jsonl, switching when a newer run appears)",
    )
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument(
        "--once", action="store_true",
        help="render one frame and exit; exit 0 only when the log's "
        "run-doctor verdict is healthy (the CI gate)",
    )
    ns = ap.parse_args(argv)

    tail = None
    dash = Dashboard()
    last_lines = 0
    try:
        while True:
            path = resolve(ns.log)
            events = []
            if path is not None:
                if tail is None or tail.path != path:
                    tail, dash = LogTail(path), Dashboard()
                events = tail.poll()
                dash.feed(events)
                frame = dash.render()
            else:
                frame = (
                    f"srtop — waiting for "
                    f"{'events-*.jsonl in ' if os.path.isdir(ns.log) else ''}"
                    f"{ns.log} (not there yet)"
                )
            if ns.once and path is not None:
                # one frame = one complete read of the log: gate on the
                # doctor's verdict so `srtop DIR --once` is a CI check
                verdict = _doctor_verdict(events)
                if verdict is not None:
                    frame += f"\ndoctor verdict: {verdict}"
            if last_lines and sys.stdout.isatty():
                sys.stdout.write(f"\x1b[{last_lines}F\x1b[0J")
            sys.stdout.write(frame + "\n")
            sys.stdout.flush()
            last_lines = frame.count("\n") + 1
            if ns.once:
                if path is None:
                    return 0  # nothing to judge: waiting, not broken
                return (
                    0 if verdict in (None, "healthy") else 1
                )
            time.sleep(ns.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
