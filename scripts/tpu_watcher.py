#!/usr/bin/env python
"""Standing TPU-tunnel watcher: captures on-chip evidence the moment the
tunnel answers, so a later outage cannot erase it.

The axon tunnel drops for hours at a time and — worse — hangs
`jax.devices()` rather than erroring, so a benchmark launched at a fixed
time (e.g. the driver's end-of-round capture) can miss every hardware
window of a working day. This watcher inverts that: it polls the tunnel
with a killable subprocess probe and, the first time the chip answers,
runs the full hardware evidence list (round-4 order — two short
canaries, then the north-star suite, then the sweeps; see the STEPS
comment for the rationale):

  1. SRTPU_TPU_TESTS=1 pytest tests/test_tpu_hardware.py   (Mosaic tier)
  2. python bench.py                                        (headline)
  3. python benchmark/suite.py          (north-star search iteration)
  4. python benchmark/kernel_tune.py --tail 7   (scalar_pack + top_carry)
  5. python benchmark/opset_sweep.py    (per-slot overhead decomposition)
  6. python benchmark/kernel_tune.py --rows-sweep  (lane-waste diagnostic)
  7. python benchmark/feynman_scale.py  (64x1000 quality at scale)

After every completed step the accumulated results are written to
BENCH_TPU_LATEST.json at the repo root and committed, so a tunnel drop
mid-list still preserves the finished steps. bench.py embeds this file
as a `last_tpu` block whenever it is forced into its CPU fallback —
giving the round's official artifact a dated on-chip record even if the
tunnel is down at capture time.

A sentinel at /tmp/srtpu_watcher_capturing marks an active capture:
nothing else should run benchmarks or test suites on this 1-core box
while it exists (concurrent load corrupts timings — BASELINE.md's
timing discipline).

Exits after one complete capture.

A restarted watcher resumes: steps recorded CLEANLY in an incomplete,
recent (<24 h) BENCH_TPU_LATEST.json are not re-run. A complete or stale
capture file disables resume automatically (a new round must re-capture,
not silently exit on last round's file); --fresh forces that manually.

With ``--telemetry-dir DIR`` the watcher threads the directory into
every step (``SRTPU_BENCH_TELEMETRY_DIR``) and classifies each step
from the telemetry event logs written during it instead of scraping
stdout: the ``run_start`` backend replaces the platform-field scrape,
``tunnel_state`` events carry the acquisition verdict, and a
``dispatch_fault`` (or a kill) with a ``saved_state`` event in the same
trail is classified **resumable**, not dead (ROADMAP #3 — a faulted
64x1000 run with a snapshot on disk should be resumed, never
restarted). Steps without telemetry fall back to the stdout scrape.

Resumable steps take the SUPERVISED-RESUME path, not a dead restart
(docs/resilience.md): the snapshot directory
(``SRTPU_BENCH_SNAPSHOT_DIR``, ``--snapshot-dir``; defaults to
``<telemetry-dir>/snapshots``) persists across attempts, so the step's
own snapshot/supervisor machinery continues from where the fault cut it
off — and the attempt accounting distinguishes the two: a resumable
retry whose newest snapshot ADVANCED past the previous attempt's resets
the step's attempt counter (real progress must never exhaust
MAX_ATTEMPTS), while a resumable retry with no new progress keeps the
decrement (crash loops still terminate).

Fleet observability (ISSUE 13, docs/observability.md "Fleet"): with a
telemetry dir the watcher also acts as a fleet producer — the fleet
root (``--fleet-root``, default: the telemetry dir itself) is exported
to steps as ``SRTPU_FLEET_ROOT`` (so supervised searches register
themselves), each step is registered into the root's
``fleet_registry.jsonl`` before it runs (one strict-JSON line, written
inline — the watcher must never import the package: importing jax at a
flapping tunnel is exactly what its subprocess probes guard against;
the line format is the compatibility contract documented in
``telemetry/fleet.py::register_run``), and the step's attempt counter
is exported as ``SRTPU_RUN_ATTEMPT`` so every search the step launches
stamps the additive ``attempt`` field into its ``run_start`` — fleet
joins by (run_id, attempt), not filename inference. Watch the whole
root live with ``python scripts/srfleet.py <dir>``.

Usage:  python scripts/tpu_watcher.py [--poll SECONDS] [--fresh]
            [--telemetry-dir DIR] [--snapshot-dir DIR] [--fleet-root DIR]
"""

from __future__ import annotations

import datetime
import glob as _glob
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULT_PATH = os.path.join(REPO, "BENCH_TPU_LATEST.json")
SENTINEL = "/tmp/srtpu_watcher_capturing"

# set by main() from --telemetry-dir; empty = stdout-scrape behavior
TELEMETRY_DIR = None

# set by main() from --snapshot-dir (default <telemetry-dir>/snapshots):
# exported to steps as SRTPU_BENCH_SNAPSHOT_DIR so search-state
# snapshots survive attempts and a resumable retry actually resumes
SNAPSHOT_DIR = None

# set by main() from --fleet-root (default: the telemetry dir): the
# fleet-index root steps are registered into and srfleet watches;
# exported to steps as SRTPU_FLEET_ROOT
FLEET_ROOT = None

# Round-5 order (VERDICT r4 #1/#2/#3): after the ONE short canary, the
# scale-fault bisect runs FIRST — the 64x1000 northstar iteration has
# faulted the chip two rounds running, and the bisect (fresh process per
# stage, duration ladder for the long-single-call hypothesis, chunked-
# dispatch mitigation stage) is the diagnosis loop built for exactly
# this. The suite (now one fresh subprocess per case, northstar last,
# chunked-first measurement) follows; then the remaining short sweep
# (rows at 4096/8192); feynman_scale goes last because its --resume
# makes partial progress durable across tunnel windows, so it can soak
# whatever chip time remains. bench is known-good two rounds running —
# it stays a canary but after the bisect so the window's first minutes
# go to the unknown, not the known.
STEPS = [
    # (name, argv, timeout_s, extra_env)
    ("bench", [sys.executable, "bench.py"], 3000, None),
    (
        "scale_bisect",
        [sys.executable, "scripts/scale_fault_bisect.py",
         "--islands", "64", "--npop", "1000"],
        10800,
        None,
    ),
    ("suite", [sys.executable, "benchmark/suite.py", "--isolate"],
     10800, None),
    (
        "tpu_tests",
        [sys.executable, "-m", "pytest", "tests/test_tpu_hardware.py",
         "-q", "--no-header"],
        3000,
        {"SRTPU_TPU_TESTS": "1"},
    ),
    # lane-utilization: the 2026-08-02 capture showed rows=2048 at
    # 1.39e9 > the 1024-row plateau — extend to 4096/8192 to find the
    # true knee before re-shaping bench.py's headline config.
    (
        "rows_sweep",
        [sys.executable, "benchmark/kernel_tune.py", "--rows-sweep",
         "--rows-max", "8192"],
        1800,
        None,
    ),
    # --resume: skip (case, seed) pairs already captured on chip in
    # BENCH_TPU_LATEST.json (main() persists the guard-railed resume
    # state to that file BEFORE any step runs, so the script can trust
    # it) — a retry after a drop spends its window on unfinished cases
    (
        "feynman_scale",
        [sys.executable, "benchmark/feynman_scale.py", "--seed", "0",
         "--resume"],
        10800,
        None,
    ),
]


def log(msg):
    ts = datetime.datetime.now().strftime("%H:%M:%S")
    print(f"[{ts}] {msg}", flush=True)


def probe_platform(timeout=90):
    """jax.devices()[0].platform in a killable subprocess, or None."""
    code = "import jax; print('PLAT=' + jax.devices()[0].platform)"
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        start_new_session=True,
    )
    try:
        out, _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except Exception:
            p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:
            pass
        return None
    for line in (out or "").splitlines():
        if line.startswith("PLAT="):
            return line[len("PLAT="):].strip()
    return None


def parse_json_lines(text):
    out = []
    for line in (text or "").splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def read_telemetry_verdict(telemetry_dir, since_ts=0.0):
    """Aggregate the telemetry event logs (events-*.jsonl) written under
    `telemetry_dir` since `since_ts` into one machine-readable verdict —
    the event-log replacement for scraping a step's stdout:

      {"logs", "backends", "tunnel_state", "faults", "saved_states",
       "last_saved_iteration", "complete", "classification"}

    classification: 'completed' (a run_end with no fault and no
    saved_state AFTER it — a supervised step whose faulted attempt was
    resumed to completion in the same window reads completed, not
    resumable), 'resumable' (a dispatch_fault newer than any run_end
    WITH a saved_state event in the trail — or a kill/timeout that left
    saved_state events newer than any run_end: resume, don't restart,
    ROADMAP #3), 'dead' (such a fault with nothing to resume from),
    'in-flight' (no fault, no run_end, no snapshot — still running or
    killed with nothing recoverable). last_saved_iteration is the newest saved_state
    event's iteration counter: the progress signal the
    supervised-resume attempt accounting compares across attempts.
    Returns None when the dir is unset/absent or holds no new logs
    (callers fall back to the stdout scrape); never raises on content —
    truncated lines in a crashed run's log are skipped."""
    if not telemetry_dir or not os.path.isdir(telemetry_dir):
        return None
    logs = [
        p for p in _glob.glob(
            os.path.join(telemetry_dir, "events-*.jsonl")
        )
        if os.path.getmtime(p) >= since_ts
    ]
    if not logs:
        return None
    out = {
        "logs": len(logs), "backends": [], "tunnel_state": None,
        "faults": 0, "saved_states": 0, "last_saved_iteration": None,
        "complete": False,
    }
    backends = set()
    last_fault_t = last_end_t = last_saved_t = None
    for path in sorted(logs, key=os.path.getmtime):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue  # truncated mid-write line: expected, skip
            typ = e.get("type")
            if typ == "run_start" and e.get("backend"):
                backends.add(e["backend"])
            elif typ == "tunnel_state":
                out["tunnel_state"] = e.get("state")
            elif typ == "dispatch_fault":
                out["faults"] += 1
                t = e.get("t")
                if isinstance(t, (int, float)):
                    last_fault_t = max(last_fault_t or t, t)
            elif typ == "saved_state":
                out["saved_states"] += 1
                t = e.get("t")
                if isinstance(t, (int, float)):
                    last_saved_t = max(last_saved_t or t, t)
                it = e.get("iteration")
                if isinstance(it, int):
                    prev = out["last_saved_iteration"]
                    out["last_saved_iteration"] = (
                        it if prev is None else max(prev, it)
                    )
            elif typ == "run_end":
                out["complete"] = True
                t = e.get("t")
                if isinstance(t, (int, float)):
                    last_end_t = max(last_end_t or t, t)
    out["backends"] = sorted(backends)
    # a fault only drives the verdict while it is UNRESOLVED — i.e. no
    # run_end postdates it. The supervised flow makes fault-then-
    # completed the normal success trail of one step window (the
    # interrupted attempt's log + the resumed attempt's), which must
    # read completed; a fault AFTER the last run_end (a later sub-run
    # dying) still reads resumable/dead.
    unresolved_fault = out["faults"] and (
        last_end_t is None
        or (last_fault_t is not None and last_fault_t > last_end_t)
    )
    # snapshots NEWER than the last run_end mean a later sub-run was
    # killed mid-flight (a kill writes neither dispatch_fault nor
    # run_end — the line-buffered log simply stops): resumable even
    # when an earlier sub-run in the same window completed. The
    # supervised success trail stays 'completed' — its snapshots all
    # predate the resumed attempt's final run_end.
    unresolved_snapshot = out["saved_states"] and (
        last_end_t is None
        or (last_saved_t is not None and last_saved_t > last_end_t)
    )
    if unresolved_fault:
        out["classification"] = (
            "resumable" if out["saved_states"] else "dead"
        )
    elif unresolved_snapshot:
        out["classification"] = "resumable"
    elif out["complete"]:
        out["classification"] = "completed"
    else:
        out["classification"] = "in-flight"
    return out


def register_fleet_step(name, attempt):
    """Announce this step into the fleet root's registry so the fleet
    index (telemetry/fleet.py, srfleet) sees it as launched even before
    it writes any event log. Written INLINE — one strict-JSON line in
    register_run's documented key format — because the watcher must
    never import the package (jax init at a flapping tunnel). Never
    fatal: observability must not block the capture."""
    if not FLEET_ROOT:
        return
    try:
        os.makedirs(FLEET_ROOT, exist_ok=True)
        line = json.dumps({
            "t": time.time(),
            "source": f"watcher:{name}",
            "run_id": None,  # steps launch many searches; no single id
            "telemetry_dir": TELEMETRY_DIR,
            "attempt": attempt,
        })
        with open(
            os.path.join(FLEET_ROOT, "fleet_registry.jsonl"), "a"
        ) as f:
            f.write(line + "\n")
    except (OSError, ValueError):
        pass


def run_step(name, argv, timeout, extra_env, attempt=1):
    env = dict(os.environ)
    if TELEMETRY_DIR:
        # every step's telemetry lands in one place; the verdict reader
        # below picks up only the logs this step wrote (mtime >= t0)
        env["SRTPU_BENCH_TELEMETRY_DIR"] = TELEMETRY_DIR
    if FLEET_ROOT:
        # steps (and the supervised searches inside them) register into
        # and stamp provenance for the same fleet root srfleet watches
        env["SRTPU_FLEET_ROOT"] = FLEET_ROOT
    # the step's retry counter becomes every launched search's additive
    # run_start `attempt` field (fleet joins are exact, not inferred)
    env["SRTPU_RUN_ATTEMPT"] = str(max(1, int(attempt)))
    if SNAPSHOT_DIR:
        # snapshots persist ACROSS attempts in one place, so a retry of
        # a resumable step finds the previous attempt's newest snapshot
        # and resumes instead of restarting (docs/resilience.md)
        os.makedirs(SNAPSHOT_DIR, exist_ok=True)
        env["SRTPU_BENCH_SNAPSHOT_DIR"] = SNAPSHOT_DIR
    if extra_env:
        env.update(extra_env)
    t0 = time.time()
    timed_out = False
    try:
        p = subprocess.run(
            argv, cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        rc, out, err = p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as ex:
        rc, timed_out = -9, True
        out = ex.stdout if isinstance(ex.stdout, str) else (
            (ex.stdout or b"").decode("utf-8", "replace")
        )
        err = ex.stderr if isinstance(ex.stderr, str) else (
            (ex.stderr or b"").decode("utf-8", "replace")
        )
    dt = round(time.time() - t0, 1)
    jl = parse_json_lines(out)
    rec = {
        "rc": rc,
        "argv": list(argv),  # resume only honors records of the SAME command
        "seconds": dt,
        "timed_out": timed_out,
        # per-step stamp: resumed payloads must not re-date carried-over
        # steps to a window they did not run in
        "captured_at": datetime.datetime.now().isoformat(
            timespec="seconds"
        ),
        "json": jl,
        "stdout_tail": "\n".join((out or "").splitlines()[-12:]),
        "stderr_tail": "\n".join((err or "").splitlines()[-8:]),
    }
    tv = read_telemetry_verdict(TELEMETRY_DIR, since_ts=t0)
    if tv is not None:
        rec["telemetry"] = tv
    return rec


def step_on_chip(name, rec):
    """Did this step's output actually come from the TPU? Preferred
    evidence: the telemetry trail's run_start backend (present whenever
    the step ran with --telemetry-dir — the event log, not a stdout
    scrape, is the record). Fallbacks: bench/suite report a platform
    field — feynman_scale stamps it per case line, so a
    partially-finished suite still attributes its finished cases; the
    pytest tier passes only when not skipped; text-only steps count by
    exit code."""
    tv = rec.get("telemetry")
    if tv and tv.get("backends"):
        return "tpu" in tv["backends"]
    if name in ("bench", "suite", "feynman_scale", "scale_bisect",
                "rows_sweep"):
        plats = {j.get("platform") for j in rec["json"] if "platform" in j}
        return "tpu" in plats
    if name == "tpu_tests":
        tail = rec["stdout_tail"]
        return rec["rc"] == 0 and "passed" in tail and "skipped" not in tail
    if name == "kernel_tune_tail":
        # on a CPU fallback every variant FAILs and no BEST line prints
        return rec["rc"] == 0 and "BEST" in rec["stdout_tail"]
    return rec["rc"] == 0


def save_and_commit(results, done, first_captured_at=None):
    now = datetime.datetime.now().isoformat(timespec="seconds")
    payload = {
        # last write time; per-step captured_at records when each step
        # actually ran, first_captured_at when this capture began
        "captured_at": now,
        "first_captured_at": first_captured_at or now,
        "complete": done,
        "steps": results,
    }
    with open(RESULT_PATH, "w") as f:
        json.dump(payload, f, indent=1)
    steps = ",".join(results)
    msg = (
        f"TPU evidence capture ({'complete' if done else 'partial'}): "
        f"{steps}"
    )
    for attempt in range(5):
        add = subprocess.run(
            ["git", "add", "BENCH_TPU_LATEST.json"], cwd=REPO,
            capture_output=True, text=True,
        )
        commit = subprocess.run(
            ["git", "commit", "-m", msg, "--", "BENCH_TPU_LATEST.json"],
            cwd=REPO, capture_output=True, text=True,
        )
        if commit.returncode == 0 or "nothing to commit" in (
            commit.stdout + commit.stderr
        ):
            log(f"committed: {msg}")
            return
        log(f"git commit retry ({attempt}): "
            f"{(commit.stderr or add.stderr).strip()[:120]}")
        time.sleep(10)


def load_previous_results():
    """Resume support: steps already captured CLEANLY (on-chip, rc=0, not
    partial) in BENCH_TPU_LATEST.json survive a watcher restart — a
    restarted watcher (step-list edit, reboot) must not burn a tunnel
    window re-running finished work. Partial records are kept in the
    payload but their steps re-run.

    Guard rails: a COMPLETE capture or one older than 24 h disables
    resume entirely — restarting the watcher then means a fresh capture
    is wanted (a new round must not silently exit on last round's file).
    Malformed files (merge-conflict damage) also fall back to fresh.
    Returns (steps, first_captured_at)."""
    try:
        with open(RESULT_PATH) as f:
            data = json.load(f)
        if data.get("complete"):
            return {}, None
        started = data.get("first_captured_at") or data.get("captured_at")
        age_h = (
            datetime.datetime.now()
            - datetime.datetime.fromisoformat(started)
        ).total_seconds() / 3600.0
        if age_h > 24:
            return {}, None
        steps = data.get("steps")
        if not isinstance(steps, dict):
            return {}, None
        return (
            {n: rec for n, rec in steps.items() if isinstance(rec, dict)},
            started,
        )
    except Exception:
        return {}, None


MAX_ATTEMPTS = 3  # per step, across tunnel windows AND restarts


def adjust_attempts_for_resume(prev_rec, rec, attempts):
    """Supervised-resume attempt accounting (ISSUE 11): the retry of a
    RESUMABLE failure is a resume, not a dead restart, and must not
    burn MAX_ATTEMPTS the same way.

    * resume WITH progress — the failed attempt's newest snapshot
      advanced past the previous attempt's (`last_saved_iteration`
      strictly greater, or a first snapshot where none existed): the
      counter RESETS to 0. A preemptible window that kills a 3-hour run
      every 40 minutes still finishes it eventually, because each death
      banked real iterations.
    * resume WITHOUT progress — a resumable classification whose
      snapshot never advances keeps the normal decrement: a config that
      faults at the same dispatch every attempt is a crash loop and the
      cap must still terminate it.
    * anything non-resumable (dead/completed/no telemetry) — untouched.

    Pure function of (previous record, new record, attempts-so-far);
    returns the adjusted attempts count."""
    tv = (rec or {}).get("telemetry") or {}
    if tv.get("classification") != "resumable":
        return attempts
    cur = tv.get("last_saved_iteration")
    if cur is None:
        return attempts
    prev_tv = ((prev_rec or {}).get("telemetry")) or {}
    prev = prev_tv.get("last_saved_iteration")
    if prev is None or cur > prev:
        return 0
    return attempts


def merge_retry_record(prev, rec):
    """A json-less failed attempt (e.g. JAX init dying in seconds on a
    flapping tunnel) must not destroy an earlier attempt's on-chip JSON —
    hours of finished feynman cases live there. Mutates rec in place,
    carrying the prior attempt's json forward (flagged) and keeping the
    on-chip attribution that came with it. The telemetry record carries
    forward the same way: losing it to one telemetry-less crash would
    reset the supervised-resume progress memory, letting the next
    no-progress resumable fault masquerade as a first snapshot and
    re-zero the attempt cap forever (adjust_attempts_for_resume's
    'crash loops still terminate' guarantee depends on this)."""
    if prev and prev.get("json") and not rec.get("json"):
        rec["json"] = prev["json"]
        rec["json_from_earlier_attempt"] = True
        rec["on_chip"] = rec.get("on_chip", False) or prev.get(
            "on_chip", False
        )
    if prev and prev.get("telemetry") and not rec.get("telemetry"):
        rec["telemetry"] = prev["telemetry"]
        rec["telemetry_from_earlier_attempt"] = True


def compute_resume_state(results):
    """The single derivation both main() and the tests use: drop records
    that don't match the step that would run NOW (same name AND argv — a
    --tail width change between rounds must re-run the sweep, and a
    renamed step's orphan must not masquerade as current evidence; git
    history keeps dropped captures), then partition the survivors.

    "Clean" is read straight off the partial flag the save path computed
    when the step ran (ok = on-chip && rc 0 && not timed out); exhausted
    steps (attempt cap hit) stay recorded as partial and must not burn
    another window's chip time either.

    Returns (kept_results, done_names, attempts, stale_names)."""
    current = {s[0]: [str(a) for a in s[1]] for s in STEPS}
    stale = {
        n for n, rec in results.items()
        if n not in current or rec.get("argv") != current[n]
    }
    kept = {n: rec for n, rec in results.items() if n not in stale}
    attempts = {n: rec.get("attempts", 0) for n, rec in kept.items()}
    clean = {n for n, rec in kept.items() if not rec.get("partial", True)}
    exhausted = {
        n for n, rec in kept.items()
        if rec.get("partial") and attempts.get(n, 0) >= MAX_ATTEMPTS
    }
    return kept, clean | exhausted, attempts, stale


def main():
    global TELEMETRY_DIR, SNAPSHOT_DIR, FLEET_ROOT
    poll = 120
    if "--poll" in sys.argv:
        poll = int(sys.argv[sys.argv.index("--poll") + 1])
    if "--telemetry-dir" in sys.argv:
        TELEMETRY_DIR = sys.argv[sys.argv.index("--telemetry-dir") + 1]
        os.makedirs(TELEMETRY_DIR, exist_ok=True)
    if "--snapshot-dir" in sys.argv:
        SNAPSHOT_DIR = sys.argv[sys.argv.index("--snapshot-dir") + 1]
    elif TELEMETRY_DIR:
        # default: snapshots live beside the telemetry they classify,
        # persisting across attempts so resumable retries resume
        SNAPSHOT_DIR = os.path.join(TELEMETRY_DIR, "snapshots")
    if "--fleet-root" in sys.argv:
        FLEET_ROOT = sys.argv[sys.argv.index("--fleet-root") + 1]
    elif TELEMETRY_DIR:
        # default: the telemetry dir IS the fleet root — every step's
        # event logs already land under it, so the fleet index and the
        # registry live next to the trails they describe
        FLEET_ROOT = TELEMETRY_DIR

    results = {}
    first_captured_at = None
    attempts = {}
    done = set()
    if "--fresh" not in sys.argv:
        results, first_captured_at = load_previous_results()
        results, done, attempts, stale = compute_resume_state(results)
        if stale:
            log(f"dropping stale/mismatched records: {sorted(stale)}")
            # persist the cleaned payload NOW: scripts that read the
            # file under --resume (feynman_scale) must never see records
            # this guard just rejected. (Epoch: if nothing survived this
            # is a fresh capture — stamp it as such, not with the
            # dropped file's age.)
            save_and_commit(
                results, done=False,
                first_captured_at=first_captured_at if results else None,
            )
        if not results:
            # nothing usable carried over: this is a fresh capture, so
            # its epoch must not inherit the dropped file's age (a
            # 23h-old inherited stamp would spuriously trip the 24h
            # guard on the very next restart)
            first_captured_at = None
        if done:
            log(f"resuming: already have {sorted(done)}")
    if first_captured_at is None:
        # pin the capture epoch NOW: every later save reuses it, so the
        # resume staleness guard measures from the true start, not the
        # last write
        first_captured_at = datetime.datetime.now().isoformat(
            timespec="seconds"
        )
    remaining = [s for s in STEPS if s[0] not in done]
    if not remaining:
        # a step-list edit can make the previous capture fully cover the
        # current STEPS: finalize the payload (complete=True) rather
        # than exiting with the file stuck at complete=False
        save_and_commit(results, done=True,
                        first_captured_at=first_captured_at)
        log("all evidence already captured — finalizing and exiting")
        return
    while remaining:
        plat = probe_platform()
        if plat != "tpu":
            log(f"tunnel down (probe: {plat}); retry in {poll}s")
            time.sleep(poll)
            continue
        log("tunnel UP — starting capture")
        with open(SENTINEL, "w") as f:
            f.write(str(os.getpid()))
        try:
            while remaining:
                name, argv, timeout, extra_env = remaining[0]
                attempts[name] = attempts.get(name, 0) + 1
                log(f"step {name} (attempt {attempts[name]}): "
                    f"{' '.join(argv)}")
                register_fleet_step(name, attempts[name])
                rec = run_step(
                    name, argv, timeout, extra_env,
                    attempt=attempts[name],
                )
                on_chip = step_on_chip(name, rec)
                ok = on_chip and rec["rc"] == 0 and not rec["timed_out"]
                rec["on_chip"] = on_chip
                rec["partial"] = not ok
                prev_rec = results.get(name)
                if not ok:
                    # supervised-resume accounting: a resumable failure
                    # whose snapshot ADVANCED resets the cap — banked
                    # progress must never exhaust MAX_ATTEMPTS
                    adjusted = adjust_attempts_for_resume(
                        prev_rec, rec, attempts[name]
                    )
                    if adjusted != attempts[name]:
                        log(
                            f"step {name}: supervised resume with "
                            "progress — attempt counter reset"
                        )
                        attempts[name] = adjusted
                # persisted so the attempt cap survives a restart: a
                # deterministically failing step must not re-block the
                # never-run steps behind it in the next window
                rec["attempts"] = attempts[name]
                merge_retry_record(prev_rec, rec)
                log(
                    f"step {name}: rc={rec['rc']} {rec['seconds']}s "
                    f"on_chip={on_chip} ok={ok}"
                )
                tv = rec.get("telemetry")
                if tv is not None:
                    # fault-with-saved_state is RESUMABLE, not dead: the
                    # run left a snapshot to resume from (ROADMAP #4)
                    log(
                        f"step {name} telemetry: "
                        f"{tv['classification']} "
                        f"(faults={tv['faults']}, "
                        f"saved_states={tv['saved_states']}, "
                        f"tunnel={tv['tunnel_state']})"
                    )
                if ok or attempts[name] >= MAX_ATTEMPTS:
                    # done — or persistently failing: record what there
                    # is (flagged partial) and stop burning chip time
                    results[name] = rec
                    remaining.pop(0)
                    save_and_commit(results, done=not remaining,
                                    first_captured_at=first_captured_at)
                    continue
                # failed with attempts left: record the attempt (the
                # attempts cap must survive a restart even for json-less
                # crashes, and any on-chip JSON the step emitted before
                # dying — hours of finished feynman cases — must survive
                # a drop), flagged partial, then retry — immediately if
                # the tunnel is still up, else back to polling
                results[name] = rec
                save_and_commit(results, done=False,
                                first_captured_at=first_captured_at)
                if probe_platform() != "tpu":
                    log(f"tunnel dropped during {name}; back to polling")
                    break
        finally:
            try:
                os.remove(SENTINEL)
            except OSError:
                pass
        if remaining:
            time.sleep(poll)
    log("all evidence captured — exiting")


if __name__ == "__main__":
    main()
