#!/usr/bin/env python
"""Repo-wide static-analysis gate: srlint + compile-surface + srmem HBM
gate + srcost analytic-cost gate + srkey Options-contract gate + srshard
sharding-contract gate + doc drift.

The one command CI (and benchmark/suite.py's `static_analysis` case) runs:

    python scripts/lint.py [--format text|json]
        [--only lint|surface|memory|cost|keys|shard[,...]]
        [--update-baseline] [--hbm-budget-gb G] [--xla-memory] [--skip-docs]

srshard (like compile-surface's `sharded` config) is skip-aware: on a
host without 8 devices every mesh config reports `skipped`, the run
stays green against the checked-in shard_baseline.json, and a refresh
never writes skipped entries (skipped != missing).

Wraps `python -m symbolicregression_jl_tpu.analysis` and adds the
doc-drift check: docs/api_reference.md must be exactly what
scripts/gen_api_reference.py generates (the page is generated, never
hand-edited — see that script's docstring). Exit 0 only when everything
is clean.

JSON mode prints ONE object: the analysis report
(report.py schema) plus a "docs" section:
    {"...", "docs": {"api_reference_current": bool, "detail": str}}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def check_telemetry_schema() -> dict:
    """telemetry-schema gate: the golden event-log fixture must validate
    against the checked-in JSON schema
    (symbolicregression_jl_tpu/telemetry/event_schema_v1.json) and carry
    all seven stage spans — so the event writer, the schema, and the
    stage vocabulary cannot drift apart without CI noticing. The fixture
    is a real (truncated) run captured by tests/test_ab_telemetry.py's
    generator; refresh it by re-running a telemetry search and copying
    the log (docs/observability.md 'Golden fixture')."""
    import json as _json

    from symbolicregression_jl_tpu.telemetry import (
        STAGES,
        validate_events_file,
    )

    golden = os.path.join(
        REPO, "tests", "data", "telemetry", "golden_events.jsonl"
    )
    report = validate_events_file(golden)
    problems = list(report["problems"])
    doctor_verdict = None
    if report["ok"]:
        seen = set()
        dynamics = False
        with open(golden) as f:
            for line in f:
                e = _json.loads(line)
                if e.get("type") == "span":
                    seen.add(e.get("name"))
                elif e.get("type") == "metrics":
                    g = (e.get("snapshot") or {}).get("gauges") or {}
                    dynamics = dynamics or (
                        "population_diversity" in g
                        and "hof_hypervolume" in g
                        and "pareto" in e
                        and "mutations" in e
                    )
        missing = [s for s in STAGES if s not in seen]
        if missing:
            problems.append(f"golden fixture missing stage spans {missing}")
        if not dynamics:
            problems.append(
                "golden fixture has no dynamics-metrics event "
                "(diversity/hypervolume/pareto/mutations)"
            )
        # the run doctor must produce a verdict on the golden fixture
        # (`analyze --self-check` equivalent): the doctor, the writer,
        # and the schema move together or CI notices. The fixture was
        # schema-validated just above — skip the second pass.
        from symbolicregression_jl_tpu.telemetry.analyze import self_check

        doctor = self_check(golden, skip_validation=True)
        doctor_verdict = doctor["verdict"]
        if not doctor["ok"]:
            problems.append(f"run doctor self-check: {doctor['detail']}")
    return {
        "ok": not problems,
        "events": report["events"],
        "doctor_verdict": doctor_verdict,
        "detail": problems[0] if problems else "",
    }


def trajectory_report() -> dict:
    """NON-FATAL bench-trajectory report (scripts/bench_trajectory.py):
    the round-over-round series + regression flags, printed alongside
    the gates so a throughput/roofline/scaling drop is visible on every
    lint run — but never failing it (capture conditions, not code,
    usually move these numbers). `latest_regressions` is the subset the
    opt-in `bench_trajectory.py --gate` would exit nonzero on — printed
    here as the gate's would-be verdict so the flag is visible on every
    lint run before anyone opts in."""
    try:
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        from bench_trajectory import build_trajectory

        traj = build_trajectory(REPO)
        return {
            "rounds": len(traj["rounds"]),
            "regressions": traj["regressions"],
            "latest_regressions": traj["latest_regressions"],
        }
    except Exception as e:  # pragma: no cover - defensive
        return {"error": f"{type(e).__name__}: {e}"}


def check_fleet_exposition() -> dict:
    """Fleet OpenMetrics gate (ISSUE 13): the checked-in fleet-index
    fixture (tests/data/telemetry/golden_fleet_index.json — captured
    from a real two-search + supervised-fault fleet) must render to a
    text exposition that passes telemetry/export.py's self-check
    validator — so the index schema, the renderer, and the validator
    cannot drift apart without CI noticing (the scrape path has no
    Prometheus binary in this container to notice for us)."""
    from symbolicregression_jl_tpu.telemetry.export import (
        render_openmetrics,
        validate_exposition,
    )

    fixture = os.path.join(
        REPO, "tests", "data", "telemetry", "golden_fleet_index.json"
    )
    try:
        with open(fixture) as f:
            index = json.load(f)
    except (OSError, ValueError) as e:
        return {"ok": False, "samples": 0,
                "detail": f"fixture unreadable: {e}"}
    text = render_openmetrics(fleet_index=index)
    problems = validate_exposition(text)
    samples = sum(
        1 for line in text.splitlines()
        if line and not line.startswith("#")
    )
    detail = problems[0] if problems else ""
    if not problems and not index.get("runs"):
        problems = ["fixture index has no runs"]
        detail = problems[0]
    return {"ok": not problems, "samples": samples, "detail": detail}


def check_tune_cache() -> dict:
    """Kernel tune-cache gate (ISSUE 17): a checked-in
    symbolicregression_jl_tpu/tune/tune_cache.json (or one named by
    SRTPU_TUNE_CACHE) must parse and satisfy the schema
    (tune/cache.py::validate_tune_cache — schema version, config shapes,
    interpret-under-TPU quarantine). An ABSENT cache is fine: that is
    the byte-identical static-default regime. A present-but-invalid one
    fails the gate — models/fitness.py would silently ignore it at
    runtime (load warns and returns None), and a cache nobody can
    consult must not sit in the tree looking authoritative."""
    from symbolicregression_jl_tpu.tune import (
        default_cache_path,
        validate_tune_cache,
    )

    path = os.environ.get("SRTPU_TUNE_CACHE") or default_cache_path()
    if not os.path.exists(path):
        return {"ok": True, "present": False, "entries": 0, "detail": ""}
    try:
        with open(path) as f:
            cache = json.load(f)
    except (OSError, ValueError) as e:
        return {"ok": False, "present": True, "entries": 0,
                "detail": f"unreadable: {e}"}
    problems = validate_tune_cache(cache)
    entries = sum(
        len(dk.get("entries", {}))
        for dk in cache.get("device_kinds", {}).values()
        if isinstance(dk, dict)
    ) if isinstance(cache, dict) else 0
    return {
        "ok": not problems,
        "present": True,
        "entries": entries,
        "detail": problems[0] if problems else "",
    }


def check_docs() -> dict:
    """gen_api_reference.py --check in a subprocess (it imports the whole
    package and renders docstrings; isolation keeps this process's jax
    state and the analysis run independent of it)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "gen_api_reference.py"),
            "--check",
        ],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO, timeout=600,
    )
    detail = (proc.stdout + proc.stderr).strip().splitlines()
    return {
        "api_reference_current": proc.returncode == 0,
        "detail": detail[-1] if detail else "",
    }


def main(argv=None) -> int:
    import argparse

    from symbolicregression_jl_tpu.analysis import (
        add_engine_args,
        pin_platform,
        run_analysis,
    )

    ap = argparse.ArgumentParser(description=__doc__)
    add_engine_args(ap)
    ap.add_argument(
        "--skip-docs", action="store_true",
        help="skip the docs/api_reference.md drift check",
    )
    ap.add_argument(
        "--skip-telemetry-schema", action="store_true",
        help="skip the telemetry golden-fixture schema check",
    )
    ns = ap.parse_args(argv)

    pin_platform()
    report = run_analysis(
        lint=ns.only is None or "lint" in ns.only,
        surface=ns.only is None or "surface" in ns.only,
        memory=ns.only is None or "memory" in ns.only,
        cost=ns.only is None or "cost" in ns.only,
        keys=ns.only is None or "keys" in ns.only,
        shard=ns.only is None or "shard" in ns.only,
        update_baseline=ns.update_baseline,
        hbm_budget_gb=ns.hbm_budget_gb,
        xla_memory=ns.xla_memory,
    )
    docs = None if ns.skip_docs else check_docs()
    telemetry = (
        None if (ns.skip_telemetry_schema or ns.only is not None)
        else check_telemetry_schema()
    )
    fleet = (
        None if (ns.skip_telemetry_schema or ns.only is not None)
        else check_fleet_exposition()
    )
    tune_cache = None if ns.only is not None else check_tune_cache()
    # non-fatal: the bench trajectory is a report, never a gate
    trajectory = None if ns.only is not None else trajectory_report()
    ok = (
        report.ok
        and (docs is None or docs["api_reference_current"])
        and (telemetry is None or telemetry["ok"])
        and (fleet is None or fleet["ok"])
        and (tune_cache is None or tune_cache["ok"])
    )

    if ns.format == "json":
        payload = report.to_dict()
        payload["docs"] = docs
        payload["telemetry_schema"] = telemetry
        payload["fleet_exposition"] = fleet
        payload["tune_cache"] = tune_cache
        payload["trajectory"] = trajectory
        payload["ok"] = ok
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(report.to_text())
        if docs is not None:
            state = (
                "current" if docs["api_reference_current"]
                else f"OUT OF DATE ({docs['detail']})"
            )
            print(f"docs/api_reference.md: {state}")
        if telemetry is not None:
            state = (
                f"valid ({telemetry['events']} events, doctor verdict "
                f"{telemetry.get('doctor_verdict')})" if telemetry["ok"]
                else f"INVALID ({telemetry['detail']})"
            )
            print(f"telemetry golden fixture: {state}")
        if fleet is not None:
            state = (
                f"valid ({fleet['samples']} samples)" if fleet["ok"]
                else f"INVALID ({fleet['detail']})"
            )
            print(f"fleet OpenMetrics exposition: {state}")
        if tune_cache is not None:
            state = (
                ("absent (static defaults)" if not tune_cache["present"]
                 else f"valid ({tune_cache['entries']} entries)")
                if tune_cache["ok"]
                else f"INVALID ({tune_cache['detail']})"
            )
            print(f"kernel tune cache: {state}")
        if trajectory is not None and "error" not in trajectory:
            n_reg = len(trajectory["regressions"])
            print(
                f"bench trajectory (non-fatal): {trajectory['rounds']} "
                f"rounds, {n_reg} regression flag(s)"
            )
            for r in trajectory["regressions"]:
                # round may be an int or the 'latest' tag
                rnd = r["round"]
                lab = f"r{rnd:02d}" if isinstance(rnd, int) else str(rnd)
                print(
                    f"  - {r['metric']} {lab} [{r['platform']}]: "
                    f"{r['drop_frac']:.0%} below best earlier round"
                )
            latest = trajectory.get("latest_regressions") or []
            print(
                "  gate (bench_trajectory --gate, opt-in): "
                + (
                    "latest round REGRESSED — "
                    + ", ".join(r["metric"] for r in latest)
                    if latest else "latest round clean"
                )
            )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
