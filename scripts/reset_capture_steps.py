#!/usr/bin/env python
"""Drop named steps from BENCH_TPU_LATEST.json so a restarted
scripts/tpu_watcher.py re-captures them in the next tunnel window.

Needed when a step's failure was caused by a code bug that is now fixed:
the watcher's resume logic deliberately refuses to re-run a step that
exhausted its attempt cap (so a deterministically failing step cannot
burn every future window), which means a *fixed* step must have its
record cleared by hand — that is an explicit human decision, recorded in
git by the file change this script makes.

Usage: python scripts/reset_capture_steps.py step [step ...]
"""

import json
import os
import sys

PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_TPU_LATEST.json",
)


def main():
    names = sys.argv[1:]
    if not names:
        sys.exit(__doc__)
    with open(PATH) as f:
        data = json.load(f)
    steps = data.get("steps", {})
    dropped = [n for n in names if steps.pop(n, None) is not None]
    missing = [n for n in names if n not in dropped]
    # the capture is no longer complete once anything is dropped
    if dropped:
        data["complete"] = False
    with open(PATH, "w") as f:
        json.dump(data, f, indent=1)
    print(f"dropped: {dropped}; not present: {missing}; "
          f"complete={data.get('complete')}")


if __name__ == "__main__":
    main()
