#!/usr/bin/env python
"""srserve — the multi-tenant SR job server CLI (docs/serving.md).

Feeds jobs into :class:`symbolicregression_jl_tpu.serving.JobServer`:
each job is admitted through the hostile-data front door, quantized
onto the pad ladder, bucketed by (padded shape, opset, Options graph
key), batched up to ``--max-tenants`` per bucket and dispatched as ONE
tenant-batched program — so N small jobs cost one warm compile per
bucket, not N compiles.

Job sources (combine freely):

* positional ``.npz`` paths — each file holds ``X`` (nfeatures, n),
  ``y`` (n,) and optionally ``weights`` (n,); one job per file;
* ``--demo N`` — N synthetic jobs over a few ladder shapes (the smoke
  mode: exercises bucketing and the warm-compile path with no data on
  hand).

Serving knobs: ``--max-tenants`` (bucket fill that triggers dispatch),
``--flush-timeout`` (seconds a partial bucket may sit before it
flushes anyway), ``--niterations`` per job, and search Options via
``--binary-operators``/``--unary-operators``/``--npop``/
``--npopulations``/``--maxsize``/``--seed``.

Observability: ``--fleet-root DIR`` registers every job's run id in
the fleet index (srfleet reads it) and lands dispatch event logs
under DIR; ``--metrics-port P`` serves the OpenMetrics exposition
(``srtpu_serve_queue_depth``, ``srtpu_serve_bucket_fill``,
``srtpu_serve_warm_hit_rate``, ``srtpu_serve_job_latency_seconds``)
on ``http://127.0.0.1:P/metrics`` while the server drains.

Exit status: 0 iff every submitted job completed with a non-empty
frontier.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="multi-tenant SR job server (see docs/serving.md)"
    )
    p.add_argument("jobs", nargs="*", help=".npz job files (X, y[, weights])")
    p.add_argument("--demo", type=int, default=0, metavar="N",
                   help="generate N synthetic jobs")
    p.add_argument("--max-tenants", type=int, default=4)
    p.add_argument("--flush-timeout", type=float, default=2.0)
    p.add_argument("--niterations", type=int, default=10)
    p.add_argument("--fleet-root", default=None)
    p.add_argument("--metrics-port", type=int, default=None)
    p.add_argument("--binary-operators", default="+,-,*")
    p.add_argument("--unary-operators", default="cos")
    p.add_argument("--npop", type=int, default=24)
    p.add_argument("--npopulations", type=int, default=2)
    p.add_argument("--maxsize", type=int, default=12)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true",
                   help="print one JSON line per completed job")
    return p.parse_args(argv)


def _demo_jobs(n, rng):
    """Synthetic jobs over two ladder shapes: enough variety to prove
    bucketing, enough repetition to prove the warm-compile path."""
    shapes = [(2, 48), (2, 48), (3, 100)]
    for i in range(n):
        nfeat, rows = shapes[i % len(shapes)]
        X = rng.standard_normal((nfeat, rows)).astype("float32")
        y = X[0] * X[0] + (X[1] if nfeat > 1 else 0.0)
        yield f"demo-{i:03d}", X, y, None


def main(argv=None) -> int:
    args = _parse_args(argv)
    import numpy as np

    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.serving import JobServer
    from symbolicregression_jl_tpu.telemetry.export import (
        render_openmetrics,
        serve_metrics,
    )
    from symbolicregression_jl_tpu.telemetry.metrics import MetricsRegistry

    registry = MetricsRegistry()
    server = JobServer(
        niterations=args.niterations,
        max_tenants=args.max_tenants,
        flush_timeout_s=args.flush_timeout,
        fleet_root=args.fleet_root,
        registry=registry,
        binary_operators=args.binary_operators.split(","),
        unary_operators=(
            args.unary_operators.split(",") if args.unary_operators else []
        ),
        npop=args.npop,
        npopulations=args.npopulations,
        maxsize=args.maxsize,
        seed=args.seed,
        verbosity=0,
        progress=False,
    )

    httpd = None
    if args.metrics_port is not None:
        httpd = serve_metrics(
            lambda: render_openmetrics(registry=registry),
            port=args.metrics_port,
        )
        print(
            f"metrics: http://127.0.0.1:{httpd.server_address[1]}/metrics",
            file=sys.stderr,
        )

    submitted = 0
    for path in args.jobs:
        data = np.load(path)
        server.submit(
            data["X"], data["y"],
            data["weights"] if "weights" in data else None,
            job_id=os.path.splitext(os.path.basename(path))[0],
            seed=args.seed + submitted,
        )
        submitted += 1
    rng = np.random.default_rng(args.seed)
    for job_id, X, y, w in _demo_jobs(args.demo, rng):
        server.submit(X, y, w, job_id=job_id, seed=args.seed + submitted)
        submitted += 1

    if not submitted:
        print("no jobs (pass .npz files or --demo N)", file=sys.stderr)
        return 2

    done = server.drain()
    ok = True
    for jr in done:
        front = jr.result.frontier()
        ok = ok and bool(front)
        best = min((c.loss for c in front), default=float("nan"))
        if args.json:
            print(json.dumps({
                "job_id": jr.job_id,
                "tenants": jr.tenants,
                "warm": jr.warm,
                "latency_s": round(jr.latency_s, 3),
                "best_loss": float(best),
                "frontier": len(front),
            }))
        else:
            print(
                f"{jr.job_id}: best_loss={best:.4g} "
                f"frontier={len(front)} tenants={jr.tenants} "
                f"warm={'yes' if jr.warm else 'no'} "
                f"latency={jr.latency_s:.2f}s"
            )
    stats = server.stats()
    print(
        f"done: {stats['completed']} job(s), "
        f"{stats['dispatches']} dispatch(es), "
        f"warm_hit_rate={stats['warm_hit_rate']:.0%}",
        file=sys.stderr,
    )
    if httpd is not None:
        httpd.shutdown()
    return 0 if ok and len(done) == submitted else 1


if __name__ == "__main__":
    sys.exit(main())
