"""Host-side NumPy oracle evaluator — the ground truth the device kernels are
tested against (SURVEY.md §7 build order step 2).

Mirrors the semantics of the reference's `eval_tree_array`
(DynamicExpressions.jl, wrapped at reference
src/InterfaceDynamicExpressions.jl:17-52): returns (output, complete) where
complete=False as soon as any intermediate value is non-finite.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..models.trees import BIN, CONST, PAD, UNA, VAR, Expr, TreeBatch, decode_tree
from .operators import OperatorSet

# NumPy implementations of each operator, matching ops/operators.py semantics.
_UNARY_NP = {
    "cos": np.cos,
    "sin": np.sin,
    "tan": np.tan,
    "exp": np.exp,
    "log": lambda x: np.where(x > 0, np.log(np.where(x > 0, x, 1.0)), np.nan),
    "log2": lambda x: np.where(x > 0, np.log2(np.where(x > 0, x, 1.0)), np.nan),
    "log10": lambda x: np.where(x > 0, np.log10(np.where(x > 0, x, 1.0)), np.nan),
    "log1p": lambda x: np.where(x > -1, np.log1p(np.where(x > -1, x, 0.0)), np.nan),
    "sqrt": lambda x: np.where(x >= 0, np.sqrt(np.where(x >= 0, x, 0.0)), np.nan),
    "abs": np.abs,
    "square": lambda x: x * x,
    "cube": lambda x: x * x * x,
    "neg": lambda x: -x,
    "relu": lambda x: np.maximum(x, 0.0),
    "sinh": np.sinh,
    "cosh": np.cosh,
    "tanh": np.tanh,
    "asin": lambda x: np.where(np.abs(x) <= 1, np.arcsin(np.clip(x, -1, 1)), np.nan),
    "acos": lambda x: np.where(np.abs(x) <= 1, np.arccos(np.clip(x, -1, 1)), np.nan),
    "atan": np.arctan,
    "asinh": np.arcsinh,
    "acosh": lambda x: np.where(x >= 1, np.arccosh(np.where(x >= 1, x, 1.0)), np.nan),
    "atanh": lambda x: np.arctanh(((x + 1.0) % 2.0) - 1.0),
    "sigmoid": lambda x: 1.0 / (1.0 + np.exp(-x)),
    "gauss": lambda x: np.exp(-(x * x)),
    "inv": lambda x: 1.0 / x,
    "sign": np.sign,
    "identity": lambda x: x,
}
try:  # SpecialFunctions analog (reference src/Operators.jl:3-12)
    from scipy import special as _sp

    _UNARY_NP["erf"] = _sp.erf
    _UNARY_NP["erfc"] = _sp.erfc

    def _gamma_np(x):
        out = _sp.gamma(x)
        return np.where(np.isfinite(out), out, np.nan)

    _UNARY_NP["gamma"] = _gamma_np
except ImportError:  # pragma: no cover
    import math

    _UNARY_NP["erf"] = np.vectorize(math.erf)
    _UNARY_NP["erfc"] = np.vectorize(math.erfc)


def _safe_pow_np(x, y):
    bad = ((x < 0) & (y != np.round(y))) | ((x == 0) & (y < 0))
    out = np.power(np.where(bad, 1.0, x), y)
    return np.where(bad, np.nan, out)


_BINARY_NP = {
    "+": lambda x, y: x + y,
    "-": lambda x, y: x - y,
    "*": lambda x, y: x * y,
    "/": lambda x, y: x / y,
    "^": _safe_pow_np,
    "mod": np.mod,
    "max": np.maximum,
    "min": np.minimum,
    "greater": lambda x, y: np.where(x > y, 1.0, 0.0),
    "logical_or": lambda x, y: np.where((x > 0) | (y > 0), 1.0, 0.0),
    "logical_and": lambda x, y: np.where((x > 0) & (y > 0), 1.0, 0.0),
    "atan2": np.arctan2,
}


def eval_expr_numpy(
    expr: Expr, X: np.ndarray, operators: OperatorSet
) -> Tuple[np.ndarray, bool]:
    """Evaluate one Expr over X (nfeatures, nrows). Returns (y, complete)."""
    complete = True

    def rec(e: Expr) -> np.ndarray:
        nonlocal complete
        if e.kind == CONST:
            v = np.full(X.shape[1], e.cval, dtype=X.dtype)
        elif e.kind == VAR:
            v = X[e.feat].astype(X.dtype)
        elif e.kind == UNA:
            a = rec(e.children[0])
            with np.errstate(all="ignore"):
                v = _UNARY_NP[operators.unary_names[e.op]](a)
        else:
            a = rec(e.children[0])
            b = rec(e.children[1])
            with np.errstate(all="ignore"):
                v = _BINARY_NP[operators.binary_names[e.op]](a, b)
        if not np.all(np.isfinite(v)):
            complete = False
        return v

    y = rec(expr)
    return y, complete


def eval_tree_numpy(
    tree: TreeBatch, X: np.ndarray, operators: OperatorSet
) -> Tuple[np.ndarray, bool]:
    return eval_expr_numpy(decode_tree(tree), X, operators)
