"""Elementwise loss library + weighted aggregation.

TPU-native analog of the LossFunctions.jl losses the reference re-exports
(reference: src/SymbolicRegression.jl:87-113 re-exports 25 losses;
src/LossFunctions.jl:11-31 aggregates with mean / weighted mean).

Distance losses take (pred, target) and are evaluated on the residual;
margin losses take (target, pred) agreement = target*pred, as in
LossFunctions.jl. All are elementwise jnp functions fused by XLA into the
interpreter's reduction.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

# ---------------------------------------------------------------------------
# Distance-based losses: f(difference) where difference = pred - target
# ---------------------------------------------------------------------------


def l2_dist_loss(pred: Array, target: Array) -> Array:
    d = pred - target
    return d * d


def l1_dist_loss(pred: Array, target: Array) -> Array:
    return jnp.abs(pred - target)


def lp_dist_loss(p: float) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return jnp.abs(pred - target) ** p

    return loss


def logit_dist_loss(pred: Array, target: Array) -> Array:
    d = pred - target
    return -jnp.log(4.0 * jax.nn.sigmoid(d) * jax.nn.sigmoid(-d))


def huber_loss(delta: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        d = jnp.abs(pred - target)
        quad = 0.5 * d * d
        lin = delta * (d - 0.5 * delta)
        return jnp.where(d <= delta, quad, lin)

    return loss


def l1_epsilon_ins_loss(eps: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return jnp.maximum(0.0, jnp.abs(pred - target) - eps)

    return loss


def l2_epsilon_ins_loss(eps: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        e = jnp.maximum(0.0, jnp.abs(pred - target) - eps)
        return e * e

    return loss


def periodic_loss(c: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return 1.0 - jnp.cos((pred - target) * 2.0 * jnp.pi / c)

    return loss


def quantile_loss(tau: float = 0.5) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        d = target - pred
        return jnp.where(d >= 0, tau * d, (tau - 1.0) * d)

    return loss


# ---------------------------------------------------------------------------
# Margin-based losses: f(agreement) where agreement = target * pred
# ---------------------------------------------------------------------------


def zero_one_loss(pred: Array, target: Array) -> Array:
    return jnp.where(target * pred >= 0, 0.0, 1.0)


def perceptron_loss(pred: Array, target: Array) -> Array:
    return jnp.maximum(0.0, -target * pred)


def l1_hinge_loss(pred: Array, target: Array) -> Array:
    return jnp.maximum(0.0, 1.0 - target * pred)


def l2_hinge_loss(pred: Array, target: Array) -> Array:
    h = jnp.maximum(0.0, 1.0 - target * pred)
    return h * h


def smoothed_l1_hinge_loss(gamma: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        a = target * pred
        h = jnp.maximum(0.0, 1.0 - a)
        return jnp.where(a >= 1.0 - gamma, 0.5 / gamma * h * h, 1.0 - gamma / 2.0 - a)

    return loss


def modified_huber_loss(pred: Array, target: Array) -> Array:
    a = target * pred
    h = jnp.maximum(0.0, 1.0 - a)
    return jnp.where(a >= -1.0, h * h, -4.0 * a)


def l2_margin_loss(pred: Array, target: Array) -> Array:
    d = 1.0 - target * pred
    return d * d


def exp_loss(pred: Array, target: Array) -> Array:
    return jnp.exp(-target * pred)


def sigmoid_loss(pred: Array, target: Array) -> Array:
    return 1.0 - jnp.tanh(target * pred)


def dwd_margin_loss(q: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        a = target * pred
        thresh = q / (q + 1.0)
        big = (q ** q) / ((q + 1.0) ** (q + 1.0)) / jnp.maximum(a, thresh) ** q
        return jnp.where(a <= thresh, 1.0 - a, big)

    return loss


def logit_margin_loss(pred: Array, target: Array) -> Array:
    return jnp.log1p(jnp.exp(-target * pred))


def log_cosh_loss(pred: Array, target: Array) -> Array:
    # numerically-stable log(cosh(d)) = |d| + log1p(exp(-2|d|)) - log 2
    d = jnp.abs(pred - target)
    return d + jnp.log1p(jnp.exp(-2.0 * d)) - jnp.log(2.0)


# Name table mirroring the reference's re-export list
# (src/SymbolicRegression.jl:87-113). Parameterized losses are exposed as
# factories; the bare name maps to the default-parameter instance.
LOSS_REGISTRY: Dict[str, Callable[[Array, Array], Array]] = {
    "L2DistLoss": l2_dist_loss,
    "mse": l2_dist_loss,
    "L1DistLoss": l1_dist_loss,
    "mae": l1_dist_loss,
    "LogitDistLoss": logit_dist_loss,
    "HuberLoss": huber_loss(1.0),
    "L1EpsilonInsLoss": l1_epsilon_ins_loss(1.0),
    "EpsilonInsLoss": l1_epsilon_ins_loss(1.0),
    "L2EpsilonInsLoss": l2_epsilon_ins_loss(1.0),
    "PeriodicLoss": periodic_loss(1.0),
    "QuantileLoss": quantile_loss(0.5),
    "PinballLoss": quantile_loss(0.5),
    "ZeroOneLoss": zero_one_loss,
    "PerceptronLoss": perceptron_loss,
    "L1HingeLoss": l1_hinge_loss,
    "HingeLoss": l1_hinge_loss,
    "L2HingeLoss": l2_hinge_loss,
    "SmoothedL1HingeLoss": smoothed_l1_hinge_loss(1.0),
    "ModifiedHuberLoss": modified_huber_loss,
    "L2MarginLoss": l2_margin_loss,
    "ExpLoss": exp_loss,
    "SigmoidLoss": sigmoid_loss,
    "DWDMarginLoss": dwd_margin_loss(1.0),
    "LogitMarginLoss": logit_margin_loss,
    "LogCoshLoss": log_cosh_loss,
    "LPDistLoss": lp_dist_loss(2.0),
}


def resolve_loss(loss) -> Callable[[Array, Array], Array]:
    """Accept a name from LOSS_REGISTRY or a callable (pred, target) -> elem."""
    if callable(loss):
        return loss
    if loss in LOSS_REGISTRY:
        return LOSS_REGISTRY[loss]
    raise ValueError(f"Unknown loss {loss!r}")


def contain_nonfinite(value: Array, ok=None, ref: Optional[Array] = None):
    """THE numeric containment primitive (docs/robustness_numeric.md):
    clamp ``value`` to the ``+inf`` sentinel wherever the evaluation left
    the finite domain — ``ok`` is the evaluator's per-tree completeness
    flag (the reference's ``complete=false`` from ``eval_tree_array``,
    src/LossFunctions.jl:36-39) and ``ref`` is the array whose
    finiteness is judged (defaults to ``value`` itself; scores pass
    their underlying loss so a finite score built on a poisoned loss is
    still contained).

    One definition on purpose: every scoring path — the flat and fused
    interpreter compositions, the Pallas batch epilogue, the custom
    loss_function path, and the BFGS/NelderMead constant-optimizer
    objectives — routes its inf-sentinel fold through this exact
    expression, so "non-finite never escapes a scoring epilogue" is a
    structural property instead of four ad-hoc ``jnp.where`` sites kept
    in sync by review. The expression is bit-identical to the historic
    inline form ``jnp.where(ok & jnp.isfinite(loss), loss, jnp.inf)``.
    """
    ref = value if ref is None else ref
    fin = jnp.isfinite(ref)
    if ok is not None:
        fin = ok & fin
    return jnp.where(fin, value, jnp.inf)


def pairwise_sum(x: Array, axis: int = -1) -> Array:
    """Fixed-order pairwise-tree sum along ``axis``: adjacent pairs are
    added, then adjacent pair-sums, ... log2(n) levels of explicit
    elementwise adds (zero-padded to the next power of two; ``x + 0``
    is exact in IEEE arithmetic).

    The reduction ORDER is pinned by the graph structure — every add is
    its own HLO op — so the result is invariant to how XLA partitions
    the array: a row-sharded pairwise sum equals the single-device one
    bit for bit (each level's adds stay shard-local until the array is
    down to the shard count), which is what re-admits ``row_shards>1``
    into the search's bit-identity contract (docs/multichip.md). A
    ``jnp.sum`` by contrast lowers to a reassociable reduce whose
    partitioned form (per-shard partials + psum) is ULP-different.

    Accuracy: pairwise summation's error grows O(log n) vs the naive
    left fold's O(n) — deterministic mode is also (slightly) more
    accurate, never less."""
    x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    if n == 0:
        return jnp.zeros(x.shape[:-1], x.dtype)
    size = 1
    while size < n:
        size *= 2
    if size != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, size - n)]
        x = jnp.pad(x, pad)
    while size > 1:
        x = x.reshape(x.shape[:-1] + (size // 2, 2))
        x = x[..., 0] + x[..., 1]
        size //= 2
    return x[..., 0]


def _tiled_row_sum(elem: Array, tile_rows: int) -> Array:
    """Fixed-order tiled row sum along the LAST axis: zero-pad to a
    multiple of ``tile_rows``, view the padded axis as (tile, sublane,
    lane) = (n_tiles, tile_rows//128, 128) blocks, ``jnp.sum`` each block,
    and left-fold the per-tile partials sequentially.

    This is, op for op, the reduction order of the Pallas fused-loss
    epilogue (ops/pallas_eval.eval_loss_trees_pallas): the kernel sums
    each (r_sub, 128) elem tile with one ``jnp.sum`` and accumulates
    across the row-tile grid sweep with ``accum_tile``'s sequential
    adds. Zero padding is exact (x + 0), a batched block ``jnp.sum``
    produces the same bits as the kernel's per-tile unbatched one (same
    reduce extent; the batch axis cannot reassociate it), and the fold
    here is the same chain of scalar adds — so kernel and host graph
    agree bit for bit by construction, not by tolerance."""
    n = elem.shape[-1]
    padded = _round_up_rows(n, tile_rows)
    if padded != n:
        pad = [(0, 0)] * (elem.ndim - 1) + [(0, padded - n)]
        elem = jnp.pad(elem, pad)
    r_sub = tile_rows // 128
    tiles = elem.reshape(elem.shape[:-1] + (padded // tile_rows, r_sub, 128))
    partials = jnp.sum(tiles, axis=(-2, -1))  # (..., n_tiles)
    acc = partials[..., 0]
    for t in range(1, partials.shape[-1]):
        acc = acc + partials[..., t]
    return acc


def _round_up_rows(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def aggregate_loss(
    elem: Array,
    weights: Optional[Array] = None,
    axis=-1,
    deterministic: bool = False,
    tile_rows: int = 0,
) -> Array:
    """Mean / weighted-mean aggregation (reference: src/LossFunctions.jl:11-31).

    ``deterministic=True`` replaces the reassociable ``jnp.sum``/
    ``jnp.mean`` row reduction with the fixed-order :func:`pairwise_sum`
    tree, making the aggregate invariant to row-axis sharding (the
    ``row_shards>1`` bit-identity contract — see pairwise_sum). The two
    modes are numerically different reduction orders, so the flag is
    part of the compiled graph (derived from ``Options.row_shards`` in
    models/fitness.py, which is in ``_graph_key``).

    ``tile_rows > 0`` (unweighted, non-deterministic, ``axis=-1`` only)
    selects the fixed-order TILED mean ``_tiled_row_sum(elem) / n`` —
    the host-graph twin of the Pallas fused-loss epilogue's in-kernel
    reduction at ``r_block = tile_rows``. Like ``deterministic``, it is
    a pinned reduction order: the fused kernel's per-tree loss is
    bit-identical to ``aggregate_loss(elem, tile_rows=r_block)`` on the
    same elem bits (docs/eval_pipeline.md exactness table), while the
    untiled ``jnp.mean`` default differs by reassociation ULPs."""
    if tile_rows:
        if weights is not None or deterministic or axis != -1:
            raise ValueError(
                "tile_rows applies to the unweighted non-deterministic "
                "axis=-1 aggregation only (the Pallas fused epilogue's "
                "contract); weighted/deterministic paths never fuse"
            )
        if tile_rows < 128 or tile_rows % 128:
            raise ValueError(
                f"tile_rows must be a positive multiple of 128, got "
                f"{tile_rows}"
            )
        n = jnp.asarray(elem.shape[-1], elem.dtype)
        return _tiled_row_sum(elem, tile_rows) / n
    if deterministic:
        if weights is None:
            n = jnp.asarray(
                elem.shape[axis if axis >= 0 else elem.ndim + axis],
                elem.dtype,
            )
            return pairwise_sum(elem, axis=axis) / n
        return pairwise_sum(elem * weights, axis=axis) / pairwise_sum(
            weights, axis=axis
        )
    if weights is None:
        return jnp.mean(elem, axis=axis)
    return jnp.sum(elem * weights, axis=axis) / jnp.sum(weights, axis=axis)
