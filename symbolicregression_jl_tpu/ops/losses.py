"""Elementwise loss library + weighted aggregation.

TPU-native analog of the LossFunctions.jl losses the reference re-exports
(reference: src/SymbolicRegression.jl:87-113 re-exports 25 losses;
src/LossFunctions.jl:11-31 aggregates with mean / weighted mean).

Distance losses take (pred, target) and are evaluated on the residual;
margin losses take (target, pred) agreement = target*pred, as in
LossFunctions.jl. All are elementwise jnp functions fused by XLA into the
interpreter's reduction.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

# ---------------------------------------------------------------------------
# Distance-based losses: f(difference) where difference = pred - target
# ---------------------------------------------------------------------------


def l2_dist_loss(pred: Array, target: Array) -> Array:
    d = pred - target
    return d * d


def l1_dist_loss(pred: Array, target: Array) -> Array:
    return jnp.abs(pred - target)


def lp_dist_loss(p: float) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return jnp.abs(pred - target) ** p

    return loss


def logit_dist_loss(pred: Array, target: Array) -> Array:
    d = pred - target
    return -jnp.log(4.0 * jax.nn.sigmoid(d) * jax.nn.sigmoid(-d))


def huber_loss(delta: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        d = jnp.abs(pred - target)
        quad = 0.5 * d * d
        lin = delta * (d - 0.5 * delta)
        return jnp.where(d <= delta, quad, lin)

    return loss


def l1_epsilon_ins_loss(eps: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return jnp.maximum(0.0, jnp.abs(pred - target) - eps)

    return loss


def l2_epsilon_ins_loss(eps: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        e = jnp.maximum(0.0, jnp.abs(pred - target) - eps)
        return e * e

    return loss


def periodic_loss(c: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        return 1.0 - jnp.cos((pred - target) * 2.0 * jnp.pi / c)

    return loss


def quantile_loss(tau: float = 0.5) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        d = target - pred
        return jnp.where(d >= 0, tau * d, (tau - 1.0) * d)

    return loss


# ---------------------------------------------------------------------------
# Margin-based losses: f(agreement) where agreement = target * pred
# ---------------------------------------------------------------------------


def zero_one_loss(pred: Array, target: Array) -> Array:
    return jnp.where(target * pred >= 0, 0.0, 1.0)


def perceptron_loss(pred: Array, target: Array) -> Array:
    return jnp.maximum(0.0, -target * pred)


def l1_hinge_loss(pred: Array, target: Array) -> Array:
    return jnp.maximum(0.0, 1.0 - target * pred)


def l2_hinge_loss(pred: Array, target: Array) -> Array:
    h = jnp.maximum(0.0, 1.0 - target * pred)
    return h * h


def smoothed_l1_hinge_loss(gamma: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        a = target * pred
        h = jnp.maximum(0.0, 1.0 - a)
        return jnp.where(a >= 1.0 - gamma, 0.5 / gamma * h * h, 1.0 - gamma / 2.0 - a)

    return loss


def modified_huber_loss(pred: Array, target: Array) -> Array:
    a = target * pred
    h = jnp.maximum(0.0, 1.0 - a)
    return jnp.where(a >= -1.0, h * h, -4.0 * a)


def l2_margin_loss(pred: Array, target: Array) -> Array:
    d = 1.0 - target * pred
    return d * d


def exp_loss(pred: Array, target: Array) -> Array:
    return jnp.exp(-target * pred)


def sigmoid_loss(pred: Array, target: Array) -> Array:
    return 1.0 - jnp.tanh(target * pred)


def dwd_margin_loss(q: float = 1.0) -> Callable[[Array, Array], Array]:
    def loss(pred: Array, target: Array) -> Array:
        a = target * pred
        thresh = q / (q + 1.0)
        big = (q ** q) / ((q + 1.0) ** (q + 1.0)) / jnp.maximum(a, thresh) ** q
        return jnp.where(a <= thresh, 1.0 - a, big)

    return loss


def logit_margin_loss(pred: Array, target: Array) -> Array:
    return jnp.log1p(jnp.exp(-target * pred))


def log_cosh_loss(pred: Array, target: Array) -> Array:
    # numerically-stable log(cosh(d)) = |d| + log1p(exp(-2|d|)) - log 2
    d = jnp.abs(pred - target)
    return d + jnp.log1p(jnp.exp(-2.0 * d)) - jnp.log(2.0)


# Name table mirroring the reference's re-export list
# (src/SymbolicRegression.jl:87-113). Parameterized losses are exposed as
# factories; the bare name maps to the default-parameter instance.
LOSS_REGISTRY: Dict[str, Callable[[Array, Array], Array]] = {
    "L2DistLoss": l2_dist_loss,
    "mse": l2_dist_loss,
    "L1DistLoss": l1_dist_loss,
    "mae": l1_dist_loss,
    "LogitDistLoss": logit_dist_loss,
    "HuberLoss": huber_loss(1.0),
    "L1EpsilonInsLoss": l1_epsilon_ins_loss(1.0),
    "EpsilonInsLoss": l1_epsilon_ins_loss(1.0),
    "L2EpsilonInsLoss": l2_epsilon_ins_loss(1.0),
    "PeriodicLoss": periodic_loss(1.0),
    "QuantileLoss": quantile_loss(0.5),
    "PinballLoss": quantile_loss(0.5),
    "ZeroOneLoss": zero_one_loss,
    "PerceptronLoss": perceptron_loss,
    "L1HingeLoss": l1_hinge_loss,
    "HingeLoss": l1_hinge_loss,
    "L2HingeLoss": l2_hinge_loss,
    "SmoothedL1HingeLoss": smoothed_l1_hinge_loss(1.0),
    "ModifiedHuberLoss": modified_huber_loss,
    "L2MarginLoss": l2_margin_loss,
    "ExpLoss": exp_loss,
    "SigmoidLoss": sigmoid_loss,
    "DWDMarginLoss": dwd_margin_loss(1.0),
    "LogitMarginLoss": logit_margin_loss,
    "LogCoshLoss": log_cosh_loss,
    "LPDistLoss": lp_dist_loss(2.0),
}


def resolve_loss(loss) -> Callable[[Array, Array], Array]:
    """Accept a name from LOSS_REGISTRY or a callable (pred, target) -> elem."""
    if callable(loss):
        return loss
    if loss in LOSS_REGISTRY:
        return LOSS_REGISTRY[loss]
    raise ValueError(f"Unknown loss {loss!r}")


def aggregate_loss(
    elem: Array, weights: Optional[Array] = None, axis=-1
) -> Array:
    """Mean / weighted-mean aggregation (reference: src/LossFunctions.jl:11-31)."""
    if weights is None:
        return jnp.mean(elem, axis=axis)
    return jnp.sum(elem * weights, axis=axis) / jnp.sum(weights, axis=axis)
