"""Pallas TPU kernel: fused loss + gradient w.r.t. constants.

The constant-optimization objective (reference src/ConstantOptimization.jl
:11-19 — full-dataset loss as a function of the tree's constants, gradients
via Zygote-derived operator rules in DynamicExpressions) evaluated for a
whole BATCH of trees in one kernel launch: forward sweep of the compressed
instruction program (ops/pallas_eval.instruction_schedule), elementwise-loss
seed, then a backward adjoint sweep over the same program, accumulating
d loss / d cval per postfix constant slot on-chip.

Why a hand-rolled backward instead of `jax.grad` through the interpreter:
the lockstep jnp interpreter differentiates fine (models/constant_opt.py
uses that path), but XLA's autodiff materializes the full primal scan in
HBM and pays the padded-slot lockstep cost twice; here the primals live in
VMEM scratch (written by the forward sweep, still resident for the
backward), programs stop at their own instruction count, and per-step
operator derivatives come from `jax.vjp` of the SAME registered operator
implementations — so NaN-guard semantics (ops/operators.py) and their
gradients match the interpreter path exactly.

The one structural gift of expression trees: every node has exactly ONE
consumer, so each adjoint is written exactly once — the backward sweep has
no read-modify-write and needs no zero-initialization. Adjoint addresses
reuse the packed operand index (pack_instr_tables with const_base):

    [0, nfeat)                    feature operands (adjoint discarded)
    [nfeat, nfeat+L)              instruction results
    [const_base, const_base+ML)   constants, by postfix slot
    const_base + ML               trash (dummy left operand of non-binary
                                  steps; ML = postfix max_len)

Backward runs instructions in descending order, so a consumer's adjoint
write always precedes the producer's read, and DEAD padding steps (which
write zeros at the const-space base) run before every real step.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.trees import CONST, TreeBatch
from .losses import l2_dist_loss
from .operators import OperatorSet, isfinite_
from .pallas_eval import (
    _SLOT_UNROLL,
    _SRC_CONST,
    _balanced_mux,
    _check_r_block,
    _round_up,
    accum_tile,
    decode_packed_word,
    instr_dispatch,
    kernel_row_validity,
    pack_instr_tables,
    prep_instr_tables,
)

Array = jax.Array


def _make_grad_kernel(operators: OperatorSet, t_block: int, r_block: int,
                      L: int, ML: int, tree_unroll: int, nfeat: int,
                      loss_fn: Callable, with_grad: bool = True):
    """L = padded instruction-table width; ML = postfix max_len (the width
    of the cval slot axis the gradient is reported in).

    with_grad=False builds the loss-only sibling (forward sweep + fused
    weighted loss, no adjoint scratch / backward sweep / cgrad output) —
    the line-search evaluator of the batched constant optimizer, which
    needs thousands of candidate losses per step WITHOUT materializing
    (trees, rows) predictions in HBM the way eval_trees_pallas would."""
    if tree_unroll not in (1, 2, 4, 8, 16) or t_block % tree_unroll:
        raise ValueError(
            "tree_unroll must be 1/2/4/8/16 and divide t_block, "
            f"got {tree_unroll}"
        )
    unary_fns = operators.kernel_unary_fns
    binary_fns = operators.kernel_binary_fns
    r_sub = r_block // 128
    const_base = nfeat + L
    A = const_base + ML + 1  # adjoint scratch slots (incl. trash)
    V = nfeat + L            # value scratch slots

    def kernel(nrows_ref, word_ref, lcval_ref, rcval_ref, ninstr_ref,
               X_ref, y_ref, wn_ref,
               *outs_and_scratch):
        if with_grad:
            loss_ref, cgrad_ref, bad_ref = outs_and_scratch[:3]
            scratch = outs_and_scratch[3:]
            adj_refs = scratch[tree_unroll:]
        else:
            loss_ref, bad_ref = outs_and_scratch[:2]
            scratch = outs_and_scratch[2:]
        val_refs = scratch[:tree_unroll]

        # row validity comes from nrows (matching the eval kernels) — a
        # genuinely zero-weighted VALID row must still poison a tree
        # whose evaluation is non-finite there, exactly like
        # eval_trees_pallas and the jnp scoring path
        pid_j, valid_f = kernel_row_validity(nrows_ref, r_sub)
        wn = wn_ref[...]
        y_t = y_ref[...]

        for f in range(nfeat):
            xf = X_ref[f]
            for t in range(tree_unroll):
                val_refs[t][f] = xf

        def operands(si, ti, val_ref):
            code, lconst, rconst, lidx, ridx = decode_packed_word(
                word_ref[si, ti]
            )
            acv = jnp.full((r_sub, 128), rcval_ref[si, ti], jnp.float32)
            bcv = jnp.full((r_sub, 128), lcval_ref[si, ti], jnp.float32)
            # const operands carry adjoint-space indices past the value
            # scratch; clip the (muxed-away) value read back into range
            a = jnp.where(rconst == 1, acv,
                          val_ref[jnp.minimum(ridx, V - 1)])
            b = jnp.where(lconst == 1, bcv,
                          val_ref[jnp.minimum(lidx, V - 1)])
            return code, lidx, ridx, a, b

        def fwd_body(si, ti, bad, val_ref):
            code, _, _, a, b = operands(si, ti, val_ref)
            v = instr_dispatch(code, a, b, unary_fns, binary_fns)
            val_ref[nfeat + si] = v
            fin = isfinite_(v) & isfinite_(a) & isfinite_(b)
            return jnp.maximum(
                bad, jnp.where(fin | (code == 0), 0.0, valid_f)
            )

        def bwd_body(si, ti, val_ref, adj_ref):
            code, lidx, ridx, a, b = operands(si, ti, val_ref)
            w = adj_ref[nfeat + si]
            zero = jnp.zeros((r_sub, 128), jnp.float32)
            da_cands = [zero, w]   # DEAD, IDENT (pass-through)
            db_cands = [zero, zero]
            for fn in unary_fns:
                _, vf = jax.vjp(fn, a)
                da_cands.append(vf(w)[0])
                db_cands.append(zero)
            for fn in binary_fns:
                _, vf = jax.vjp(fn, b, a)
                db_j, da_j = vf(w)
                da_cands.append(da_j)
                db_cands.append(db_j)
            da = _balanced_mux(code, da_cands)
            db = _balanced_mux(code, db_cands)
            # single-writer: each operand (result or const slot) has
            # exactly one consumer, so plain stores suffice
            adj_ref[jnp.minimum(ridx, A - 1)] = da
            adj_ref[jnp.minimum(lidx, A - 1)] = db

        def tree_group_body(p, _):
            tis = [p * tree_unroll + k for k in range(tree_unroll)]
            ns = [ninstr_ref[0, ti] for ti in tis]
            n_max = ns[0]
            for n in ns[1:]:
                n_max = jnp.maximum(n_max, n)
            n_groups = (n_max + _SLOT_UNROLL - 1) // _SLOT_UNROLL

            zero = jnp.zeros((r_sub, 128), jnp.float32)

            def fwd_group(g, bads):
                bads = list(bads)
                for k in range(_SLOT_UNROLL):
                    si = g * _SLOT_UNROLL + k
                    for t in range(tree_unroll):
                        bads[t] = fwd_body(si, tis[t], bads[t], val_refs[t])
                return tuple(bads)

            bads = jax.lax.fori_loop(
                0, n_groups, fwd_group, (zero,) * tree_unroll
            )

            # seed: adjoint of the root = d(weighted elementwise loss)/dy
            for t in range(tree_unroll):
                y_pred = val_refs[t][nfeat + jnp.maximum(ns[t] - 1, 0)]
                elem, vloss = jax.vjp(
                    lambda yp: loss_fn(yp, y_t), y_pred
                )
                masked = jnp.where(wn != 0.0, elem * wn, 0.0)
                # accumulate across the row-tile sweep (accum_tile: tile 0
                # initializes, later tiles add)
                accum_tile(loss_ref, (0, tis[t]), pid_j, jnp.sum(masked))
                accum_tile(bad_ref, (0, tis[t]), pid_j, jnp.sum(bads[t]))
                if with_grad:
                    (seed,) = vloss(wn)
                    seed = jnp.where(wn != 0.0, seed, 0.0)
                    adj_refs[t][nfeat + jnp.maximum(ns[t] - 1, 0)] = seed

            if not with_grad:
                return 0

            def bwd_group(g, _):
                # descending instruction order: consumers before producers
                for k in range(_SLOT_UNROLL):
                    si = (n_groups - 1 - g) * _SLOT_UNROLL \
                        + (_SLOT_UNROLL - 1 - k)
                    for t in range(tree_unroll):
                        bwd_body(si, tis[t], val_refs[t], adj_refs[t])
                return 0

            jax.lax.fori_loop(0, n_groups, bwd_group, 0)

            # flush per-slot constant gradients (row-reduced) for this
            # group's trees; non-const slots are stale scratch — the
            # wrapper masks them by kind. PADDED lanes can carry NaN
            # (0-seed x inf local derivative on garbage rows), so mask by
            # validity before the reduction — lanes never mix (all ops
            # are elementwise), so valid lanes are exact. A NaN on a
            # zero-weight VALID lane survives, matching `jax.grad`
            # through the interpreter on the same data.
            for t in range(tree_unroll):
                for s in range(ML):
                    accum_tile(
                        cgrad_ref, (0, s, tis[t]), pid_j,
                        jnp.sum(
                            jnp.where(
                                valid_f != 0.0,
                                adj_refs[t][const_base + s], 0.0,
                            )
                        ),
                    )
            return 0

        jax.lax.fori_loop(0, t_block // tree_unroll, tree_group_body, 0)

    return kernel, A


def eval_loss_grad_pallas(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Optional[Callable] = None,
    t_block: int = 256,
    r_block: int = 1024,
    tree_unroll: int = 4,
    sort_trees: bool = True,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Batched constant-optimization objective: per-tree aggregated loss
    and its gradient w.r.t. every constant slot, in one fused kernel.

    Returns (loss (...,), grad (..., max_len), ok (...,)) where
    loss = weighted mean of `loss_fn(y_pred, y)` over rows (mean when
    weights is None), grad is d loss / d trees.cval masked to CONST
    slots, and ok mirrors eval_trees_pallas' poison flag (loss is NOT
    forced to inf for poisoned trees — callers gate on ok, matching
    models/fitness.eval_loss_trees' contract before its where()).

    TPU only (or interpret=True anywhere); float32.
    """
    return _loss_impl(
        trees, X, y, weights, operators, loss_fn, t_block, r_block,
        tree_unroll, sort_trees, interpret, with_grad=True,
    )


def eval_loss_pallas(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Optional[Callable] = None,
    t_block: int = 256,
    r_block: int = 1024,
    tree_unroll: int = 4,
    sort_trees: bool = True,
    interpret: bool = False,
) -> Tuple[Array, Array]:
    """Loss-only sibling of eval_loss_grad_pallas: (loss, ok) with the
    weighted mean fused on-chip, never materializing per-row predictions
    in HBM (unlike scoring through eval_trees_pallas). The line-search
    evaluator of the batched constant optimizer."""
    loss, _, ok = _loss_impl(
        trees, X, y, weights, operators, loss_fn, t_block, r_block,
        tree_unroll, sort_trees, interpret, with_grad=False,
    )
    return loss, ok


def make_loss_kernel(trees, X, y, weights, operators, loss_fn=None,
                     with_grad=True, t_block=256, r_block=1024,
                     tree_unroll=4, sort_trees=True, interpret=False):
    """Stage the structure-dependent work of the fused loss(+grad) kernel
    ONCE and return `fn(cval) -> (loss, grad|None, ok)` for repeated
    evaluation at different constants.

    The instruction schedule (a sequential O(max_len) scan), the sort by
    instruction count, and the word packing depend only on tree
    STRUCTURE; per call only the operand-constant tables are rebuilt —
    two (T, L) gathers from `cval` via the postfix-slot indices the
    schedule already records for const operands — plus the kernel
    launch. This is what makes the batched constant optimizer cheap: its
    BFGS loop calls fn() twice per iteration inside a fori_loop, where
    re-running the schedule each step would dominate.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if loss_fn is None:
        loss_fn = l2_dist_loss
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    nfeat, nrows = X.shape
    ML = flat.kind.shape[-1]

    tables, n_instr, flat, inv_perm, L = prep_instr_tables(
        flat, operators, sort_trees
    )
    T = tables["icode"].shape[0]
    const_base = nfeat + L
    n_codes = 2 + operators.n_unary + operators.n_binary
    if n_codes > 255 or const_base + ML + 1 > 2048:
        raise ValueError(
            "the fused loss/grad kernel needs <=255 opcodes and "
            f"nfeat + padded_len + max_len <= ~2048 (got {n_codes} "
            f"opcodes, nfeat={nfeat}, L={L}, max_len={ML})"
        )

    t_block = min(t_block, _round_up(max(T, 8), tree_unroll))
    r_block = min(r_block, _round_up(nrows, 128))
    _check_r_block(r_block, nrows, interpret)
    r_sub = r_block // 128
    T_pad = _round_up(T, t_block)
    R_pad = _round_up(nrows, r_block)
    NR = R_pad // 128

    def padT(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T), (0, 0)),
                       constant_values=fill).T

    word = padT(pack_instr_tables(tables, nfeat, const_base=const_base))
    ninstr_p = jnp.pad(n_instr, (0, T_pad - T))[None, :]
    perm = None if inv_perm is None else jnp.argsort(inv_perm)
    # operand-constant reconstruction indices: const operands carry their
    # postfix cval slot (instruction_schedule records it); the dummy left
    # operand of non-binary steps points at slot ML, which maps onto the
    # zero pad column below
    lconst_m = tables["lsrc"] == _SRC_CONST
    rconst_m = tables["rsrc"] == _SRC_CONST
    lslot = jnp.clip(tables["lidx"], 0, ML)
    rslot = jnp.clip(tables["ridx"], 0, ML)

    Xp = jnp.pad(X.astype(jnp.float32), ((0, 0), (0, R_pad - nrows)))
    Xp = Xp.reshape(nfeat, NR, 128)
    yp = jnp.pad(y.astype(jnp.float32), (0, R_pad - nrows))
    yp = yp.reshape(NR, 128)
    # normalized weights: w / sum(w) (or 1/nrows), zero on padded rows —
    # the kernel's loss partials and seeds then just sum
    if weights is None:
        wn = jnp.full((nrows,), 1.0 / nrows, jnp.float32)
    else:
        wf = weights.astype(jnp.float32)
        wn = wf / jnp.sum(wf)
    wn = jnp.pad(wn, (0, R_pad - nrows)).reshape(NR, 128)

    kernel, A = _make_grad_kernel(
        operators, t_block, r_block, L, ML, tree_unroll, nfeat, loss_fn,
        with_grad=with_grad,
    )
    # INVARIANT (accum_tile soundness): j (row tiles) must remain the
    # trailing sequential grid dimension — see the matching note at
    # pallas_eval's grid construction; a reorder or a parallel
    # dimension_semantics annotation here silently corrupts
    # loss/cgrad/poison accumulation.
    grid = (T_pad // t_block, NR // r_sub)
    smem_spec = lambda shape, imap: pl.BlockSpec(
        shape, imap, memory_space=pltpu.SMEM
    )
    tree_tbl = lambda: smem_spec((L, t_block), lambda i, j: (0, i))
    # scalar outputs are single rows accumulated across the row-tile sweep
    # inside the kernel (index maps ignore j, so the blocks stay resident;
    # row tile 0 initializes, later tiles add). A per-tile (1, t_block)
    # block over a (grid_j, T_pad) array would be an ILLEGAL Mosaic block
    # shape for grid_j > 1, and a (grid_j, ...) resident block would grow
    # SMEM linearly with the row-tile count — same design as
    # pallas_eval's poison output.
    scalar_out = lambda: smem_spec((1, t_block), lambda i, j: (0, i))
    scalar_shape = jax.ShapeDtypeStruct((1, T_pad), jnp.float32)
    if with_grad:
        out_specs = [
            scalar_out(),                                       # loss
            smem_spec((1, ML, t_block),
                      lambda i, j: (0, 0, i)),                  # cgrad
            scalar_out(),                                       # bad
        ]
        out_shape = [
            scalar_shape,
            jax.ShapeDtypeStruct((1, ML, T_pad), jnp.float32),
            scalar_shape,
        ]
        scratch = (
            [pltpu.VMEM((nfeat + L, r_sub, 128), jnp.float32)
             for _ in range(tree_unroll)]
            + [pltpu.VMEM((A, r_sub, 128), jnp.float32)
               for _ in range(tree_unroll)]
        )
    else:
        out_specs = [scalar_out(), scalar_out()]  # loss, bad
        out_shape = [scalar_shape, scalar_shape]
        scratch = [pltpu.VMEM((nfeat + L, r_sub, 128), jnp.float32)
                   for _ in range(tree_unroll)]
    launch = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # nrows scalar
            tree_tbl(),  # packed word
            tree_tbl(),  # lcval
            tree_tbl(),  # rcval
            smem_spec((1, t_block), lambda i, j: (0, i)),  # n_instr
            pl.BlockSpec((nfeat, r_sub, 128), lambda i, j: (0, j, 0)),
            pl.BlockSpec((r_sub, 128), lambda i, j: (j, 0)),  # y
            pl.BlockSpec((r_sub, 128), lambda i, j: (j, 0)),  # wn
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    nrows_arr = jnp.asarray([nrows], jnp.int32)

    def fn(cval):
        cv = cval.reshape((-1, ML))
        if perm is not None:
            cv = cv[perm]
        # extra zero column: the dummy-operand slot ML resolves to 0.0
        cv_ext = jnp.pad(cv.astype(jnp.float32), ((0, 0), (0, 1)))
        take = lambda slot: jnp.take_along_axis(cv_ext, slot, axis=1)
        lcval = padT(jnp.where(lconst_m, take(lslot), 0.0))
        rcval = padT(jnp.where(rconst_m, take(rslot), 0.0))
        outs = launch(nrows_arr, word, lcval, rcval, ninstr_p, Xp, yp, wn)
        if with_grad:
            loss_p, cgrad_p, bad = outs
        else:
            loss_p, bad = outs
            cgrad_p = None

        loss = loss_p[0, :T]
        ok = (bad[0, :T] == 0) & (flat.length > 0)
        if cgrad_p is None:
            grad = None
        else:
            grad = cgrad_p[0, :, :T].T  # (T, ML)
            # only CONST slots carry gradients; the rest is stale scratch
            grad = jnp.where(flat.kind == CONST, grad, 0.0)
        if inv_perm is not None:
            loss = loss[inv_perm]
            ok = ok[inv_perm]
            if grad is not None:
                grad = grad[inv_perm]
        return (
            loss.reshape(batch_shape),
            None if grad is None else grad.reshape(batch_shape + (ML,)),
            ok.reshape(batch_shape),
        )

    return fn


def _loss_impl(trees, X, y, weights, operators, loss_fn, t_block, r_block,
               tree_unroll, sort_trees, interpret, with_grad):
    fn = make_loss_kernel(
        trees, X, y, weights, operators, loss_fn, with_grad, t_block,
        r_block, tree_unroll, sort_trees, interpret,
    )
    return fn(trees.cval)
