"""Batched postfix-tree interpreter (jnp / XLA path).

This replaces the reference's fused eval kernels (`eval_tree_array` in
DynamicExpressions.jl, wrapped at reference
src/InterfaceDynamicExpressions.jl:17-52): one jitted XLA call evaluates a
whole population of trees against all dataset rows.

Design (SURVEY.md §7 decision 2): each tree is a postfix program; evaluation
is a stack machine driven by `lax.scan` over the L slots. All trees advance
in lockstep, so per-slot we compute every operator's result on the current
stack tops and select by opcode — XLA fuses this into one pass over the row
vectors. NaN/Inf is tracked as a per-tree `ok` flag (the analog of
`complete=false`), reduced on-chip.

Differentiable: `jax.grad` through the scan w.r.t. `cval` gives exact
gradients for constant optimization (the analog of `eval_grad_tree_array`
with variable=false, reference src/InterfaceDynamicExpressions.jl:76-107);
grads w.r.t. X give the variable=true variant.

A Pallas kernel with true scalar dispatch (one op per node instead of
all-and-select) lives in ops/pallas_eval.py; this module is the portable
path and the correctness oracle for it.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.trees import ARITY, BIN, CONST, PAD, UNA, VAR, TreeBatch
from .operators import OperatorSet

Array = jax.Array


def _slot_step(carry, node, X: Array, operators: OperatorSet, arity_table):
    """One stack-machine step over all rows: carry (stack (depth, nrows),
    sp, bad (nrows,)), node (kind, op, feat, cval) scalars.

    The ONE definition of the per-slot math: the full-L scan
    (`_eval_single`), the bounded fori_loop evaluator (`_eval_rows` — the
    bucketed/fused loss paths), and their vmapped forms all execute this
    exact op sequence, which is what makes the bucketed evaluation
    bit-identical to the flat path a structural property instead of a
    keep-two-interpreters-in-sync obligation. A PAD step is an identity
    on the whole carry — truncating the slot loop anywhere past a
    program's `length` cannot change its result."""
    stack, sp, bad = carry
    k, o, f, c = node
    nrows = X.shape[1]
    unary_fns = operators.unary_fns
    binary_fns = operators.binary_fns
    a = stack[jnp.maximum(sp - 1, 0)]  # top: unary operand / right operand
    b = stack[jnp.maximum(sp - 2, 0)]  # second: left operand
    leaf = jnp.where(k == CONST, jnp.broadcast_to(c, (nrows,)), X[f])  # srlint: disable=SR007 -- scalar-over-rows select arm, fused by XLA
    if unary_fns:
        una_all = jnp.stack([fn(a) for fn in unary_fns])
        una = una_all[jnp.clip(o, 0, len(unary_fns) - 1)]
    else:
        una = jnp.zeros_like(a)
    if binary_fns:
        bin_all = jnp.stack([fn(b, a) for fn in binary_fns])
        binv = bin_all[jnp.clip(o, 0, len(binary_fns) - 1)]
    else:
        binv = jnp.zeros_like(a)
    v = jnp.where(k <= VAR, leaf, jnp.where(k == UNA, una, binv))
    # some operator impls upcast half precisions internally (special
    # functions route through f32); pin the working dtype so the stack
    # update below type-checks for bf16/f16 inputs
    v = v.astype(stack.dtype)
    arity = arity_table[k]
    new_sp = jnp.where(k == PAD, sp, sp - arity + 1)
    write = jnp.maximum(new_sp - 1, 0)
    v_final = jnp.where(k == PAD, stack[write], v)
    new_stack = jax.lax.dynamic_update_index_in_dim(stack, v_final, write, 0)
    # elementwise NaN/Inf poison per row; reduced once at the end
    # (cheaper than a per-step all-rows reduction, same semantics as the
    # reference's early exit: any non-finite intermediate -> incomplete)
    new_bad = bad | ((k != PAD) & ~jnp.isfinite(v))
    return new_stack, new_sp, new_bad


def _stack_init(L: int, nrows: int, dtype):
    return (
        jnp.zeros((L // 2 + 2, nrows), dtype),
        jnp.int32(0),
        jnp.zeros((nrows,), jnp.bool_),
    )


def _eval_single(
    kind: Array,
    op: Array,
    feat: Array,
    cval: Array,
    length: Array,
    X: Array,
    operators: OperatorSet,
) -> Tuple[Array, Array]:
    """Evaluate one tree over X (nfeatures, nrows) -> (y (nrows,), ok bool)."""
    L = kind.shape[0]
    arity_table = jnp.asarray(ARITY)

    def step(carry, node):
        return _slot_step(carry, node, X, operators, arity_table), None

    (stack, sp, bad), _ = jax.lax.scan(
        step, _stack_init(L, X.shape[1], X.dtype), (kind, op, feat, cval)
    )
    y = stack[0]
    ok = ~jnp.any(bad) & (length > 0)
    return y, ok


def _eval_rows(
    kind: Array,
    op: Array,
    feat: Array,
    cval: Array,
    X: Array,
    operators: OperatorSet,
    n_steps,
) -> Tuple[Array, Array]:
    """One tree over X with the slot loop truncated to `n_steps` (a static
    int or a traced int32 scalar) -> (y (nrows,), bad (nrows,)).

    Exact for every tree whose `length <= n_steps`: slots past the program
    end are PAD, and a PAD `_slot_step` is an identity on the carry. A
    traced bound lowers `fori_loop` to `while_loop` (not reverse-mode
    differentiable — scoring only; constant optimization grads go through
    the `_eval_single` scan). Returns the raw per-row poison flags so
    callers that tile or mask rows can reduce them correctly."""
    arity_table = jnp.asarray(ARITY)

    def body(i, carry):
        node = (kind[i], op[i], feat[i], cval[i])
        return _slot_step(carry, node, X, operators, arity_table)

    stack, sp, bad = jax.lax.fori_loop(
        0, n_steps, body, _stack_init(kind.shape[0], X.shape[1], X.dtype)
    )
    return stack[0], bad


def filler_trees(
    batch_shape: Tuple[int, ...], max_len: int, dtype=jnp.float32
) -> TreeBatch:
    """The cheapest VALID program this layer evaluates: length-1 `CONST 0`.

    The cache subsystem's intra-batch dedup (cache/dedup.py) compacts
    unique trees to the front of a fixed-shape buffer and must fill the
    freed slots with something every backend accepts. Length-1 keeps
    `ok=True` semantics uniform (an all-PAD length-0 tree reports
    incomplete), prices at ONE step in the Pallas kernel's length-bounded
    slot loop (ops/pallas_eval.py design note 3b — the filler's padded
    tail is skipped), and costs the same as any tree in this lockstep
    interpreter (which always scans all L slots). Jittable constants."""
    shape = tuple(batch_shape) + (max_len,)
    return TreeBatch(
        kind=jnp.zeros(shape, jnp.int32).at[..., 0].set(CONST),
        op=jnp.zeros(shape, jnp.int32),
        feat=jnp.zeros(shape, jnp.int32),
        cval=jnp.zeros(shape, dtype),
        length=jnp.ones(batch_shape, jnp.int32),
    )


def eval_trees(
    trees: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Evaluate a batch of trees. trees batch shape (...,); X (nfeat, nrows).

    Returns (y (..., nrows), ok (...,) bool). Jittable with static operators.
    """
    batch_shape = trees.length.shape
    L = trees.max_len

    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    f = jax.vmap(
        lambda k, o, ft, c, n: _eval_single(k, o, ft, c, n, X, operators)
    )
    y, ok = f(flat.kind, flat.op, flat.feat, flat.cval, flat.length)
    return y.reshape(batch_shape + (X.shape[1],)), ok.reshape(batch_shape)


def eval_tree(
    tree: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Single tree (batch shape ()) -> (y (nrows,), ok). Public inference API,
    analog of `eval_tree_array(tree, X, options)` (reference README.md:67-74)."""
    return _eval_single(
        tree.kind, tree.op, tree.feat, tree.cval, tree.length, X, operators
    )


def _eval_loss_single(
    kind: Array,
    op: Array,
    feat: Array,
    cval: Array,
    length: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn,
    n_steps,
    rows_per_tile: int,
    deterministic: bool = False,
) -> Array:
    """One tree -> aggregated loss scalar (Inf on NaN/Inf evals), never
    materializing the prediction row vector past the reduction.

    rows_per_tile == 0 (exact mode): evaluate all rows at once and apply
    literally the flat scoring composition — loss_fn, aggregate_loss,
    inf-on-incomplete via the shared `contain_nonfinite` epilogue — so
    the result is bit-identical to the unfused path. rows_per_tile > 0:
    stream the rows through a lax.scan of fixed-width tiles,
    accumulating per-tree sufficient statistics (weighted loss sum,
    weight sum, poison flag); the tile-wise partial sums reduce in a
    different order than the flat row reduction, so this mode is NOT
    bit-identical to rows_per_tile=0 (documented opt-in for large
    datasets — peak memory per tree drops from O(nrows) to
    O(rows_per_tile)).

    deterministic=True swaps every row reduction for the fixed-order
    pairwise tree (ops/losses.py::pairwise_sum), making the loss
    invariant to row-axis sharding — the row_shards>1 graphs
    (docs/robustness_numeric.md). In tiled mode the within-tile sums go
    pairwise and the cross-tile fold is the scan's fixed sequential
    order, so the tiled loss is partition-invariant too (while staying
    a different order than the untiled one)."""
    from .losses import aggregate_loss, contain_nonfinite

    nrows = X.shape[1]
    if rows_per_tile <= 0 or rows_per_tile >= nrows:
        y_pred, bad = _eval_rows(kind, op, feat, cval, X, operators, n_steps)
        ok = ~jnp.any(bad) & (length > 0)
        elem = loss_fn(y_pred, y)
        loss = aggregate_loss(elem, weights, deterministic=deterministic)
        return contain_nonfinite(loss, ok)

    tile = int(rows_per_tile)
    n_tiles = -(-nrows // tile)
    pad = n_tiles * tile - nrows
    # edge-pad the rows (in-domain values keep the padded lanes from
    # manufacturing spurious non-finites; the mask below excludes them
    # from every reduction regardless)
    Xp = jnp.pad(X, ((0, 0), (0, pad)), mode="edge")
    yp = jnp.pad(y, (0, pad), mode="edge")
    mask = jnp.arange(n_tiles * tile, dtype=jnp.int32) < nrows
    wp = None if weights is None else jnp.pad(weights, (0, pad))
    xs = (
        jnp.moveaxis(Xp.reshape(X.shape[0], n_tiles, tile), 1, 0),
        yp.reshape(n_tiles, tile),
        mask.reshape(n_tiles, tile),
        (jnp.zeros((n_tiles, 0), X.dtype) if wp is None
         else wp.reshape(n_tiles, tile)),
    )

    from .losses import pairwise_sum

    _rowsum = pairwise_sum if deterministic else jnp.sum

    def tile_step(carry, xt):
        num, den, bad_any = carry
        Xt, yt, mt, wt = xt
        y_pred, bad = _eval_rows(kind, op, feat, cval, Xt, operators,
                                 n_steps)
        elem = loss_fn(y_pred, yt)
        w_eff = mt.astype(elem.dtype) if weights is None else jnp.where(
            mt, wt, jnp.zeros((), elem.dtype)
        )
        num = num + _rowsum(elem * w_eff)
        den = den + _rowsum(w_eff)
        bad_any = bad_any | jnp.any(bad & mt)
        return (num, den, bad_any), None

    init = (
        jnp.zeros((), X.dtype), jnp.zeros((), X.dtype),
        jnp.zeros((), jnp.bool_),
    )
    (num, den, bad_any), _ = jax.lax.scan(tile_step, init, xs)
    loss = num / den
    ok = ~bad_any & (length > 0)
    return contain_nonfinite(loss, ok)


def eval_loss_trees_fused(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn,
    rows_per_tile: int = 0,
    n_steps=None,
    deterministic: bool = False,
) -> Array:
    """Fused evaluate+reduce: per-tree aggregated loss (Inf on NaN/Inf
    evals) with NO (batch, nrows) prediction intermediate — the
    elementwise loss reduces to a scalar inside the vmapped evaluator.
    deterministic=True selects the fixed-order pairwise row reduction
    (sharding-invariant; see _eval_loss_single / ops/losses.py).

    trees batch shape (...,); X (nfeat, nrows); y (nrows,); returns loss
    (...,). With rows_per_tile=0 (default) the result is bit-identical to
    the unfused composition ``aggregate_loss(loss_fn(eval_trees(...)))``
    with the same inf-on-incomplete fold (asserted in tests);
    rows_per_tile>0 streams rows through fixed-width tiles and is NOT
    bit-identical (different reduction order — see _eval_loss_single).

    n_steps truncates the slot loop (static int or traced int32): exact
    whenever every tree in the batch has length <= n_steps, because
    truncated slots are PAD identities. None means all max_len slots —
    the drop-in flat replacement. The length-bucketed driver
    (models/fitness.py) passes each bucket's dynamic length bound."""
    batch_shape = trees.length.shape
    if n_steps is None:
        n_steps = trees.max_len
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    f = jax.vmap(
        lambda k, o, ft, c, n: _eval_loss_single(
            k, o, ft, c, n, X, y, weights, operators, loss_fn, n_steps,
            rows_per_tile, deterministic,
        )
    )
    loss = f(flat.kind, flat.op, flat.feat, flat.cval, flat.length)
    return loss.reshape(batch_shape)


def eval_grad_constants(
    trees: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array, Array]:
    """Forward value + gradient of each output w.r.t. each constant slot.

    Returns (y (..., nrows), ok, dy_dc (..., L, nrows)). Analog of
    eval_grad_tree_array(variable=false)."""

    def one(k, o, f, c, n):
        def val(cv):
            y, _ = _eval_single(k, o, f, cv, n, X, operators)
            return y

        y, ok = _eval_single(k, o, f, c, n, X, operators)
        dy = jax.jacfwd(val)(c)  # (nrows, L)
        return y, ok, jnp.moveaxis(dy, -1, 0)

    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    y, ok, dy = jax.vmap(one)(flat.kind, flat.op, flat.feat, flat.cval, flat.length)
    L = trees.max_len
    return (
        y.reshape(batch_shape + (X.shape[1],)),
        ok.reshape(batch_shape),
        dy.reshape(batch_shape + (L, X.shape[1])),
    )


def eval_grad_variables(
    tree: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Gradient of output w.r.t. X (analog of eval_grad_tree_array
    variable=true). Returns (y (nrows,), dy_dX (nfeat, nrows))."""

    def val(Xv):
        y, _ = eval_tree(tree, Xv, operators)
        return jnp.sum(y)

    y, _ = eval_tree(tree, X, operators)
    return y, jax.grad(val)(X)


def eval_diff_tree(
    tree: TreeBatch, X: Array, operators: OperatorSet, direction: int
) -> Tuple[Array, Array, Array]:
    """Forward-mode derivative of the output w.r.t. ONE feature — the analog
    of `eval_diff_tree_array(tree, X, options, direction)` (reference
    src/InterfaceDynamicExpressions.jl:76-87). Returns (y, dy_dx, ok)."""

    def val(Xv):
        return eval_tree(tree, Xv, operators)

    tangent = jnp.zeros_like(X).at[direction].set(1.0)
    (y, ok), (dy, _) = jax.jvp(val, (X,), (tangent,))
    return y, dy, ok
