"""Batched postfix-tree interpreter (jnp / XLA path).

This replaces the reference's fused eval kernels (`eval_tree_array` in
DynamicExpressions.jl, wrapped at reference
src/InterfaceDynamicExpressions.jl:17-52): one jitted XLA call evaluates a
whole population of trees against all dataset rows.

Design (SURVEY.md §7 decision 2): each tree is a postfix program; evaluation
is a stack machine driven by `lax.scan` over the L slots. All trees advance
in lockstep, so per-slot we compute every operator's result on the current
stack tops and select by opcode — XLA fuses this into one pass over the row
vectors. NaN/Inf is tracked as a per-tree `ok` flag (the analog of
`complete=false`), reduced on-chip.

Differentiable: `jax.grad` through the scan w.r.t. `cval` gives exact
gradients for constant optimization (the analog of `eval_grad_tree_array`
with variable=false, reference src/InterfaceDynamicExpressions.jl:76-107);
grads w.r.t. X give the variable=true variant.

A Pallas kernel with true scalar dispatch (one op per node instead of
all-and-select) lives in ops/pallas_eval.py; this module is the portable
path and the correctness oracle for it.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.trees import ARITY, BIN, CONST, PAD, UNA, VAR, TreeBatch
from .operators import OperatorSet

Array = jax.Array


def _eval_single(
    kind: Array,
    op: Array,
    feat: Array,
    cval: Array,
    length: Array,
    X: Array,
    operators: OperatorSet,
) -> Tuple[Array, Array]:
    """Evaluate one tree over X (nfeatures, nrows) -> (y (nrows,), ok bool)."""
    L = kind.shape[0]
    nrows = X.shape[1]
    depth = L // 2 + 2
    arity_table = jnp.asarray(ARITY)
    unary_fns = operators.unary_fns
    binary_fns = operators.binary_fns

    def step(carry, node):
        stack, sp, bad = carry  # stack: (depth, nrows); bad: (nrows,) bool
        k, o, f, c = node
        a = stack[jnp.maximum(sp - 1, 0)]  # top: unary operand / right operand
        b = stack[jnp.maximum(sp - 2, 0)]  # second: left operand
        leaf = jnp.where(k == CONST, jnp.broadcast_to(c, (nrows,)), X[f])  # srlint: disable=SR007 -- scalar-over-rows select arm, fused by XLA
        if unary_fns:
            una_all = jnp.stack([fn(a) for fn in unary_fns])
            una = una_all[jnp.clip(o, 0, len(unary_fns) - 1)]
        else:
            una = jnp.zeros_like(a)
        if binary_fns:
            bin_all = jnp.stack([fn(b, a) for fn in binary_fns])
            binv = bin_all[jnp.clip(o, 0, len(binary_fns) - 1)]
        else:
            binv = jnp.zeros_like(a)
        v = jnp.where(k <= VAR, leaf, jnp.where(k == UNA, una, binv))
        # some operator impls upcast half precisions internally (special
        # functions route through f32); pin the working dtype so the stack
        # update below type-checks for bf16/f16 inputs
        v = v.astype(stack.dtype)
        arity = arity_table[k]
        new_sp = jnp.where(k == PAD, sp, sp - arity + 1)
        write = jnp.maximum(new_sp - 1, 0)
        v_final = jnp.where(k == PAD, stack[write], v)
        new_stack = jax.lax.dynamic_update_index_in_dim(stack, v_final, write, 0)
        # elementwise NaN/Inf poison per row; reduced once at the end
        # (cheaper than a per-step all-rows reduction, same semantics as the
        # reference's early exit: any non-finite intermediate -> incomplete)
        new_bad = bad | ((k != PAD) & ~jnp.isfinite(v))
        return (new_stack, new_sp, new_bad), None

    init = (
        jnp.zeros((depth, nrows), X.dtype),
        jnp.int32(0),
        jnp.zeros((nrows,), jnp.bool_),
    )
    (stack, sp, bad), _ = jax.lax.scan(step, init, (kind, op, feat, cval))
    y = stack[0]
    ok = ~jnp.any(bad) & (length > 0)
    return y, ok


def filler_trees(
    batch_shape: Tuple[int, ...], max_len: int, dtype=jnp.float32
) -> TreeBatch:
    """The cheapest VALID program this layer evaluates: length-1 `CONST 0`.

    The cache subsystem's intra-batch dedup (cache/dedup.py) compacts
    unique trees to the front of a fixed-shape buffer and must fill the
    freed slots with something every backend accepts. Length-1 keeps
    `ok=True` semantics uniform (an all-PAD length-0 tree reports
    incomplete), prices at ONE step in the Pallas kernel's length-bounded
    slot loop (ops/pallas_eval.py design note 3b — the filler's padded
    tail is skipped), and costs the same as any tree in this lockstep
    interpreter (which always scans all L slots). Jittable constants."""
    shape = tuple(batch_shape) + (max_len,)
    return TreeBatch(
        kind=jnp.zeros(shape, jnp.int32).at[..., 0].set(CONST),
        op=jnp.zeros(shape, jnp.int32),
        feat=jnp.zeros(shape, jnp.int32),
        cval=jnp.zeros(shape, dtype),
        length=jnp.ones(batch_shape, jnp.int32),
    )


def eval_trees(
    trees: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Evaluate a batch of trees. trees batch shape (...,); X (nfeat, nrows).

    Returns (y (..., nrows), ok (...,) bool). Jittable with static operators.
    """
    batch_shape = trees.length.shape
    L = trees.max_len

    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    f = jax.vmap(
        lambda k, o, ft, c, n: _eval_single(k, o, ft, c, n, X, operators)
    )
    y, ok = f(flat.kind, flat.op, flat.feat, flat.cval, flat.length)
    return y.reshape(batch_shape + (X.shape[1],)), ok.reshape(batch_shape)


def eval_tree(
    tree: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Single tree (batch shape ()) -> (y (nrows,), ok). Public inference API,
    analog of `eval_tree_array(tree, X, options)` (reference README.md:67-74)."""
    return _eval_single(
        tree.kind, tree.op, tree.feat, tree.cval, tree.length, X, operators
    )


def eval_grad_constants(
    trees: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array, Array]:
    """Forward value + gradient of each output w.r.t. each constant slot.

    Returns (y (..., nrows), ok, dy_dc (..., L, nrows)). Analog of
    eval_grad_tree_array(variable=false)."""

    def one(k, o, f, c, n):
        def val(cv):
            y, _ = _eval_single(k, o, f, cv, n, X, operators)
            return y

        y, ok = _eval_single(k, o, f, c, n, X, operators)
        dy = jax.jacfwd(val)(c)  # (nrows, L)
        return y, ok, jnp.moveaxis(dy, -1, 0)

    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    y, ok, dy = jax.vmap(one)(flat.kind, flat.op, flat.feat, flat.cval, flat.length)
    L = trees.max_len
    return (
        y.reshape(batch_shape + (X.shape[1],)),
        ok.reshape(batch_shape),
        dy.reshape(batch_shape + (L, X.shape[1])),
    )


def eval_grad_variables(
    tree: TreeBatch, X: Array, operators: OperatorSet
) -> Tuple[Array, Array]:
    """Gradient of output w.r.t. X (analog of eval_grad_tree_array
    variable=true). Returns (y (nrows,), dy_dX (nfeat, nrows))."""

    def val(Xv):
        y, _ = eval_tree(tree, Xv, operators)
        return jnp.sum(y)

    y, _ = eval_tree(tree, X, operators)
    return y, jax.grad(val)(X)


def eval_diff_tree(
    tree: TreeBatch, X: Array, operators: OperatorSet, direction: int
) -> Tuple[Array, Array, Array]:
    """Forward-mode derivative of the output w.r.t. ONE feature — the analog
    of `eval_diff_tree_array(tree, X, options, direction)` (reference
    src/InterfaceDynamicExpressions.jl:76-87). Returns (y, dy_dx, ok)."""

    def val(Xv):
        return eval_tree(tree, Xv, operators)

    tangent = jnp.zeros_like(X).at[direction].set(1.0)
    (y, ok), (dy, _) = jax.jvp(val, (X,), (tangent,))
    return y, dy, ok
