"""Operator library with NaN-safe semantics.

TPU-native analog of the reference's scalar operator library
(reference: src/Operators.jl:8-111). Where the reference defines NaN-guarded
scalar Julia functions consumed by DynamicExpressions' fused eval loops, we
define jnp elementwise functions over row vectors consumed by the batched
tree interpreter (ops/interpreter.py) and the Pallas kernel.

Every operator must be total on float inputs: invalid domains return NaN
(never raise), matching the reference's "safe_*" convention
(src/Operators.jl:38-73). NaN/Inf is detected by the interpreter as a
per-tree validity flag, the analog of `eval_tree_array`'s `complete=false`.

Users can register custom operators with `register_unary` / `register_binary`
(analog of `@extend_operators`, reference
src/InterfaceDynamicExpressions.jl:206-215).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# ---------------------------------------------------------------------------
# NaN-safe scalar/elementwise definitions (reference: src/Operators.jl)
# ---------------------------------------------------------------------------


def _nan_like(x: Array) -> Array:
    return jnp.full_like(x, jnp.nan)


def isfinite_(x: Array) -> Array:
    """`jnp.isfinite` that also lowers inside bf16 Pallas TPU kernels.

    Mosaic's finiteness check (`tpu.weird`) only accepts F32 vectors, so a
    bf16 value is cast up first — lossless for finiteness (bf16 inf/nan map
    to f32 inf/nan). Other dtypes (f32, f64) pass through unchanged; f64 is
    NOT cast down, since a finite f64 above f32 max would falsely read as
    inf.
    """
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    return jnp.isfinite(x)


def safe_pow(x: Array, y: Array) -> Array:
    """x^y, NaN when x<0 with non-integer y, or x==0 with y<0.

    Reference: src/Operators.jl:38-46 (safe_pow) — negative bases are legal
    for integer exponents ((-2)^2 == 4).
    """
    bad = ((x < 0) & (y != jnp.round(y))) | ((x == 0) & (y < 0))
    base = jnp.where(bad, 1.0, x)
    out = jnp.power(base, y)
    return jnp.where(bad, jnp.nan, out)


def safe_log(x: Array) -> Array:
    """log(x), NaN for x<=0. Reference: src/Operators.jl:50-53."""
    return jnp.where(x > 0, jnp.log(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log2(x: Array) -> Array:
    return jnp.where(x > 0, jnp.log2(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log10(x: Array) -> Array:
    return jnp.where(x > 0, jnp.log10(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log1p(x: Array) -> Array:
    return jnp.where(x > -1, jnp.log1p(jnp.where(x > -1, x, 0.0)), jnp.nan)


def safe_sqrt(x: Array) -> Array:
    """sqrt(x), NaN for x<0. Reference: src/Operators.jl:70-73."""
    return jnp.where(x >= 0, jnp.sqrt(jnp.where(x >= 0, x, 0.0)), jnp.nan)


def safe_acosh(x: Array) -> Array:
    """acosh(x), NaN for x<1. Reference: src/Operators.jl:66-69."""
    return jnp.where(x >= 1, jnp.arccosh(jnp.where(x >= 1, x, 1.0)), jnp.nan)


def safe_asin(x: Array) -> Array:
    ok = jnp.abs(x) <= 1
    return jnp.where(ok, jnp.arcsin(jnp.clip(x, -1, 1)), jnp.nan)


def safe_acos(x: Array) -> Array:
    ok = jnp.abs(x) <= 1
    return jnp.where(ok, jnp.arccos(jnp.clip(x, -1, 1)), jnp.nan)


def atanh_clip(x: Array) -> Array:
    """atanh of x wrapped to (-1, 1). Reference: src/Operators.jl:14."""
    return jnp.arctanh(((x + 1.0) % 2.0) - 1.0)


def gamma_op(x: Array) -> Array:
    """gamma(x) with poles -> NaN. Reference: src/Operators.jl:8-12.

    lgamma gives log|Gamma|; for x<0 recover the signed value via the
    reflection formula. The reference maps Inf -> NaN at the poles.
    """
    pos = jnp.exp(jax.lax.lgamma(x))
    # Reflection: Gamma(x) = pi / (sin(pi x) Gamma(1-x)) for x < 0.
    neg = jnp.pi / (jnp.sin(jnp.pi * x) * jnp.exp(jax.lax.lgamma(1.0 - x)))
    out = jnp.where(x > 0, pos, neg)
    is_pole = (x <= 0) & (x == jnp.round(x))
    out = jnp.where(is_pole | ~isfinite_(out), jnp.nan, out)
    return out


def erf_op(x: Array) -> Array:
    return jax.lax.erf(x)


def erfc_op(x: Array) -> Array:
    return jax.lax.erfc(x)


def square(x: Array) -> Array:
    return x * x


def cube(x: Array) -> Array:
    return x * x * x


def neg(x: Array) -> Array:
    return -x


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0.0)


def greater(x: Array, y: Array) -> Array:
    """1.0 if x > y else 0.0. Reference: src/Operators.jl:90-96."""
    return jnp.where(x > y, 1.0, 0.0)


def logical_or(x: Array, y: Array) -> Array:
    """Reference: src/Operators.jl:99-104."""
    return jnp.where((x > 0) | (y > 0), 1.0, 0.0)


def logical_and(x: Array, y: Array) -> Array:
    return jnp.where((x > 0) & (y > 0), 1.0, 0.0)


def plus(x, y):
    return x + y


def sub(x, y):
    return x - y


def mult(x, y):
    return x * y


def div(x, y):
    return x / y


def mod_op(x, y):
    return jnp.mod(x, y)


def identity_op(x):
    return x


def sigmoid(x):
    return jax.nn.sigmoid(x)


def gauss(x):
    return jnp.exp(-(x * x))


def inv(x):
    return 1.0 / x


def safe_tan(x):
    return jnp.tan(x)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

# Canonical name -> fn. Names match the reference's spellings where they
# exist (plus Julia builtins the reference lets users pass directly).
UNARY_REGISTRY: Dict[str, Callable] = {
    "cos": jnp.cos,
    "sin": jnp.sin,
    "tan": safe_tan,
    "exp": jnp.exp,
    "log": safe_log,
    "log2": safe_log2,
    "log10": safe_log10,
    "log1p": safe_log1p,
    "sqrt": safe_sqrt,
    "abs": jnp.abs,
    "square": square,
    "cube": cube,
    "neg": neg,
    "relu": relu,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "asin": safe_asin,
    "acos": safe_acos,
    "atan": jnp.arctan,
    "asinh": jnp.arcsinh,
    "acosh": safe_acosh,
    "atanh": atanh_clip,
    "erf": erf_op,
    "erfc": erfc_op,
    "gamma": gamma_op,
    "sigmoid": sigmoid,
    "gauss": gauss,
    "inv": inv,
    "sign": jnp.sign,
    "identity": identity_op,
}

BINARY_REGISTRY: Dict[str, Callable] = {
    "+": plus,
    "-": sub,
    "*": mult,
    "/": div,
    "^": safe_pow,
    "pow": safe_pow,
    "mod": mod_op,
    "max": jnp.maximum,
    "min": jnp.minimum,
    "greater": greater,
    "logical_or": logical_or,
    "logical_and": logical_and,
    "atan2": jnp.arctan2,
}

# ---------------------------------------------------------------------------
# Mosaic-safe kernel substitutes
# ---------------------------------------------------------------------------
# The Pallas TPU (Mosaic) lowering supports only a subset of lax's
# elementwise transcendentals (exp/log/log1p/sqrt/rsqrt/sin/cos/tan/tanh/
# pow/logistic and arithmetic/compare/select — see
# jax/_src/pallas/mosaic/lowering.py's rule table). jnp.cosh, jnp.sinh,
# the inverse trig/hyperbolic family, erf/erfc, gamma (lgamma), atan2 and
# rem (jnp.mod) all hit `NotImplementedError: Unimplemented primitive in
# Pallas TPU lowering`. So the compiled-kernel path routes these names to
# compositions built ONLY from Mosaic-lowerable primitives. The jnp
# interpreter path keeps the exact lax implementations; the compositions
# below are f32-accurate to a few ulp (each is parity-tested against its
# lax counterpart over a domain grid in tests/test_operators.py), which is
# within the kernel's existing f32-vs-f64-oracle comparison tolerances.
# Two exceptions to "few ulp": mod_kernel's x - floor(x/y)*y error grows
# with |x/y| (unbounded for huge ratios; parity-tested to rtol 1e-3 on a
# +-40 grid), and erfc_kernel's relative error degrades in the positive
# tail where the true value underflows. Kernel-path fitness can therefore
# diverge from the jnp-interpreter path for mod/erfc-heavy expressions in
# those regimes, enough to flip near-tie rankings between backends.
#
# The substitutions also keep the library's NaN-domain semantics
# (reference src/Operators.jl:8-73) bit-identical: every guard is applied
# to the composition exactly as it is to the lax version.
#
# Derivatives: the |x|-based compositions have a zero subgradient at
# x == 0 under plain autodiff (the odd-sign select routes the cotangent
# into a constant branch), so every substitute whose true derivative at 0
# is nonzero carries a custom_jvp with the EXACT closed-form derivative —
# itself Mosaic-lowerable, and more accurate than differentiating the
# approximation. The Pallas grad kernel's per-step `jax.vjp` picks these
# up automatically.

_LN2 = 0.6931471805599453


def _odd_sign(x: Array, r: Array) -> Array:
    """sign(x) * r for an odd function's |x|-based magnitude r, with
    f(0) = 0 preserved (including -0.0 and NaN passthrough)."""
    return jnp.where(x < 0, -r, jnp.where(x > 0, r, x * 0.0))


def _exact_grad(dfn):
    """Attach `dfn` as the exact derivative of a unary composition."""
    def deco(fn):
        f = jax.custom_jvp(fn)

        @f.defjvp
        def _jvp(primals, tangents):
            (x,), (t,) = primals, tangents
            return fn(x), dfn(x) * t

        return f
    return deco


def _atan_poly(z: Array) -> Array:
    # minimax for (atan(t) - t)/t^3 on |t| <= tan(pi/8) (classic 4-term
    # Cephes-style coefficients, ~2 ulp f32)
    return (
        (8.05374449538e-2 * z - 1.38776856032e-1) * z + 1.99777106478e-1
    ) * z - 3.33329491539e-1


@_exact_grad(lambda x: 1.0 / (1.0 + x * x))
def atan_kernel(x: Array) -> Array:
    """arctan from +,*,/,select only: octant reduction + odd minimax poly."""
    ax = jnp.abs(x)
    big = ax > 2.414213562373095  # tan(3pi/8): atan(t) = pi/2 - atan(1/t)
    med = ax > 0.41421356237309503  # tan(pi/8): atan(t)=pi/4+atan((t-1)/(t+1))
    t = jnp.where(
        big,
        -1.0 / jnp.where(big, ax, 1.0),
        jnp.where(med, (ax - 1.0) / (ax + 1.0), ax),
    )
    y0 = jnp.where(big, jnp.pi / 2, jnp.where(med, jnp.pi / 4, 0.0))
    z = t * t
    r = y0 + t + t * z * _atan_poly(z)
    return _odd_sign(x, r)


def _dasin(x: Array) -> Array:
    # 1/sqrt(1-x^2); inf at |x|==1 and NaN outside, matching lax.asin's vjp
    return jax.lax.rsqrt((1.0 - x) * (1.0 + x))


@_exact_grad(_dasin)
def asin_kernel(x: Array) -> Array:
    """safe_asin semantics (NaN outside [-1,1]) via atan composition."""
    ok = jnp.abs(x) <= 1
    xc = jnp.clip(x, -1, 1)
    s = jnp.sqrt((1.0 - xc) * (1.0 + xc))
    edge = s == 0
    r = atan_kernel(xc / jnp.where(edge, 1.0, s))
    r = jnp.where(edge, jnp.sign(xc) * (jnp.pi / 2), r)
    return jnp.where(ok, r, jnp.nan)


def acos_kernel(x: Array) -> Array:
    # pi/2 - asin: correct exact gradient flows through asin's custom rule
    return jnp.pi / 2 - asin_kernel(x)


def cosh_kernel(x: Array) -> Array:
    # e' = exp(|x|)/2 so the largest finite cosh (|x| ~ 89.4) stays finite:
    # exp(|x|) itself overflows f32 from |x| ~ 88.7 while cosh is still
    # representable up to ~3.4e38. (Autodiff is exact here: cosh' = sinh
    # is odd with sinh(0) = 0, so the |x| subgradient-0 point is correct.)
    e = jnp.exp(jnp.abs(x) - _LN2)
    return e + 0.25 / e


def sinh_kernel(x: Array) -> Array:
    # tanh (natively lowerable) carries the near-0 accuracy and the sign;
    # cosh the range. Product-rule autodiff is exact incl. at 0.
    return jnp.tanh(x) * cosh_kernel(x)


@_exact_grad(lambda x: jax.lax.rsqrt(1.0 + x * x))
def asinh_kernel(x: Array) -> Array:
    ax = jnp.abs(x)
    big = ax > 1e8  # x*x would overflow f32; asinh ~ log(2|x|)
    axs = jnp.where(big, 1.0, ax)
    x2 = axs * axs
    small = jnp.log1p(axs + x2 / (1.0 + jnp.sqrt(x2 + 1.0)))
    large = jnp.log(jnp.where(big, ax, 1.0)) + _LN2
    return _odd_sign(x, jnp.where(big, large, small))


def acosh_kernel(x: Array) -> Array:
    """safe_acosh semantics (NaN for x<1). Reference: src/Operators.jl:66-69.

    No zero-crossing, so autodiff through the composition is correct
    (inf slope at x=1, NaN below, ~1/x above — matching lax.acosh's vjp
    under the same domain guard).
    """
    ok = x >= 1
    xs = jnp.where(ok, x, 1.0)
    big = xs > 1e8
    xb = jnp.where(big, 1.0, xs)
    small = jnp.log1p((xb - 1.0) + jnp.sqrt((xb - 1.0) * (xb + 1.0)))
    large = jnp.log(jnp.where(big, xs, 1.0)) + _LN2
    return jnp.where(ok, jnp.where(big, large, small), jnp.nan)


def mod_kernel(x: Array, y: Array) -> Array:
    """Floor-mod (jnp.mod semantics) from div/floor/mul; rem_p doesn't lower.

    Autodiff gives d/dx = 1 and d/dy = -floor(x/y) a.e., the same
    gradients as jnp.mod's.
    """
    return x - jnp.floor(x / y) * y


def atanh_clip_kernel(x: Array) -> Array:
    """atanh of x wrapped to (-1, 1). Reference: src/Operators.jl:14."""
    w = mod_kernel(x + 1.0, 2.0) - 1.0
    # atanh(w) = 0.5 log1p(2w / (1-w)); w == 1 is unreachable from the wrap
    return 0.5 * jnp.log1p(2.0 * w / jnp.where(w == 1.0, 1.0, 1.0 - w))


@_exact_grad(lambda x: 1.1283791670955126 * jnp.exp(-x * x))  # 2/sqrt(pi)
def erf_kernel(x: Array) -> Array:
    """Abramowitz-Stegun 7.1.26 rational approximation (|err| < 1.5e-7)."""
    ax = jnp.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * ax)
    poly = (
        (((1.061405429 * t - 1.453152027) * t + 1.421413741) * t
         - 0.284496736) * t + 0.254829592
    ) * t
    r = 1.0 - poly * jnp.exp(-ax * ax)
    return _odd_sign(x, r)


def erfc_kernel(x: Array) -> Array:
    # absolute error matches erf_kernel (~1.5e-7); relative error in the
    # far x>0 tail is worse than lax.erfc's — acceptable for f32 fitness
    return 1.0 - erf_kernel(x)


# Lanczos g=7, n=9 coefficients (standard published set; f32-accurate)
_LANCZOS = (
    676.5203681218851, -1259.1392167224028, 771.32342877765313,
    -176.61502916214059, 12.507343278686905, -0.13857109526572012,
    9.9843695780195716e-6, 1.5056327351493116e-7,
)


def gamma_kernel(x: Array) -> Array:
    """gamma(x) with poles/Inf -> NaN via Lanczos; lgamma doesn't lower.

    Same semantics as gamma_op (reference src/Operators.jl:8-12).
    """
    refl = x < 0.5
    xx = jnp.where(refl, 1.0 - x, x) - 1.0
    a = jnp.full_like(x, 0.99999999999980993)
    for i, c in enumerate(_LANCZOS):
        a = a + c / (xx + (i + 1.0))
    t = xx + 7.5
    # t^(xx+0.5) e^-t in log space: the factored form overflows f32 at
    # x ~ 26 while the true value (~1e25) is still representable
    y = 2.5066282746310002 * a * jnp.exp(
        (xx + 0.5) * jnp.log(t) - t
    )
    sin_pix = jnp.sin(jnp.pi * x)
    out = jnp.where(
        refl, jnp.pi / (sin_pix * jnp.where(refl, y, 1.0)), y
    )
    is_pole = (x <= 0) & (x == jnp.round(x))
    return jnp.where(is_pole | ~isfinite_(out), jnp.nan, out)


def _atan2_comp(y: Array, x: Array) -> Array:
    r = atan_kernel(y / jnp.where(x == 0, 1.0, x))
    r = jnp.where(x == 0, jnp.sign(y) * (jnp.pi / 2), r)
    ysign = jnp.where(y < 0, -1.0, 1.0)
    return jnp.where(x < 0, r + ysign * jnp.pi, r)


@jax.custom_jvp
def atan2_kernel(y: Array, x: Array) -> Array:
    """Quadrant-corrected atan composition (atan2_p doesn't lower).

    Matches lax.atan2 on finite inputs with x != 0 or y != 0 off the
    negative-real axis; the +-0 / double-inf IEEE edge cases differ.
    Exact closed-form jvp (d/dy = x/r^2, d/dx = -y/r^2) replaces the
    composition's where-masked autodiff.
    """
    return _atan2_comp(y, x)


@atan2_kernel.defjvp
def _atan2_jvp(primals, tangents):
    (y, x), (ty, tx) = primals, tangents
    r2 = x * x + y * y
    return _atan2_comp(y, x), (x * ty - y * tx) / r2


# name -> Mosaic-lowerable replacement used by the Pallas kernels only.
# Unary and binary tables are separate because the registries are separate
# namespaces: a custom binary op named like a built-in unary (or vice
# versa) must not clobber the other arity's substitute.
KERNEL_SUBSTITUTES_UNARY: Dict[str, Callable] = {
    "sinh": sinh_kernel,
    "cosh": cosh_kernel,
    "atan": atan_kernel,
    "asin": asin_kernel,
    "acos": acos_kernel,
    "asinh": asinh_kernel,
    "acosh": acosh_kernel,
    "atanh": atanh_clip_kernel,
    "erf": erf_kernel,
    "erfc": erfc_kernel,
    "gamma": gamma_kernel,
}

KERNEL_SUBSTITUTES_BINARY: Dict[str, Callable] = {
    "mod": mod_kernel,
    "atan2": atan2_kernel,
}


# Aliases accepted on input (reference maps raw -> safe ops in
# src/Options.jl:86-120 binopmap/unaopmap).
_ALIASES = {
    "plus": "+",
    "sub": "-",
    "mult": "*",
    "div": "/",
    "safe_pow": "^",
    "safe_log": "log",
    "safe_log2": "log2",
    "safe_log10": "log10",
    "safe_log1p": "log1p",
    "safe_sqrt": "sqrt",
    "safe_acosh": "acosh",
    "atanh_clip": "atanh",
}

# Infix printing set
INFIX = {"+", "-", "*", "/", "^"}


def register_unary(
    name: str, fn: Callable, kernel_fn: Callable | None = None
) -> None:
    """Register a custom unary operator (jnp elementwise fn).

    `kernel_fn` optionally supplies a Mosaic-lowerable variant for the
    compiled Pallas path (needed only if `fn` uses lax primitives outside
    Mosaic's lowering set — see KERNEL_SUBSTITUTES_UNARY). Re-registering
    a name drops any stale substitute so the kernel path never pairs an
    old substitute with a new fn.
    """
    UNARY_REGISTRY[name] = fn
    if kernel_fn is not None:
        KERNEL_SUBSTITUTES_UNARY[name] = kernel_fn
    else:
        KERNEL_SUBSTITUTES_UNARY.pop(name, None)


def register_binary(
    name: str, fn: Callable, kernel_fn: Callable | None = None
) -> None:
    """Register a custom binary operator (jnp elementwise fn)."""
    BINARY_REGISTRY[name] = fn
    if kernel_fn is not None:
        KERNEL_SUBSTITUTES_BINARY[name] = kernel_fn
    else:
        KERNEL_SUBSTITUTES_BINARY.pop(name, None)


def canonical_name(name: str) -> str:
    return _ALIASES.get(name, name)


@dataclasses.dataclass(frozen=True)
class OperatorSet:
    """The operator tables selected by an Options instance.

    Analog of the reference's `OperatorEnum` (src/Options.jl:586-591): an
    ordered list of unary and binary operators; tree nodes store indices
    into these lists.
    """

    unary_names: Tuple[str, ...]
    binary_names: Tuple[str, ...]

    @property
    def unary_fns(self) -> List[Callable]:
        return [UNARY_REGISTRY[n] for n in self.unary_names]

    @property
    def binary_fns(self) -> List[Callable]:
        return [BINARY_REGISTRY[n] for n in self.binary_names]

    @property
    def kernel_unary_fns(self) -> List[Callable]:
        """unary_fns with Mosaic-lowerable substitutes for the Pallas path."""
        return [
            KERNEL_SUBSTITUTES_UNARY.get(n, UNARY_REGISTRY[n])
            for n in self.unary_names
        ]

    @property
    def kernel_binary_fns(self) -> List[Callable]:
        return [
            KERNEL_SUBSTITUTES_BINARY.get(n, BINARY_REGISTRY[n])
            for n in self.binary_names
        ]

    @property
    def n_unary(self) -> int:
        return len(self.unary_names)

    @property
    def n_binary(self) -> int:
        return len(self.binary_names)

    def unary_index(self, name: str) -> int:
        return self.unary_names.index(canonical_name(name))

    def binary_index(self, name: str) -> int:
        return self.binary_names.index(canonical_name(name))


def make_operator_set(
    binary_operators: Sequence[str] = ("+", "-", "*", "/"),
    unary_operators: Sequence[str] = (),
) -> OperatorSet:
    bins = tuple(canonical_name(b) for b in binary_operators)
    unas = tuple(canonical_name(u) for u in unary_operators)
    for b in bins:
        if b not in BINARY_REGISTRY:
            raise ValueError(f"Unknown binary operator {b!r}")
    for u in unas:
        if u not in UNARY_REGISTRY:
            raise ValueError(f"Unknown unary operator {u!r}")
    if set(bins) & set(unas):
        # Reference rejects binop/unaop overlap (src/Configure.jl:44-50).
        raise ValueError("Operators cannot be both unary and binary")
    if len(set(bins)) != len(bins) or len(set(unas)) != len(unas):
        raise ValueError("Duplicate operators")
    return OperatorSet(unary_names=unas, binary_names=bins)
