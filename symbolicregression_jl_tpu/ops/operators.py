"""Operator library with NaN-safe semantics.

TPU-native analog of the reference's scalar operator library
(reference: src/Operators.jl:8-111). Where the reference defines NaN-guarded
scalar Julia functions consumed by DynamicExpressions' fused eval loops, we
define jnp elementwise functions over row vectors consumed by the batched
tree interpreter (ops/interpreter.py) and the Pallas kernel.

Every operator must be total on float inputs: invalid domains return NaN
(never raise), matching the reference's "safe_*" convention
(src/Operators.jl:38-73). NaN/Inf is detected by the interpreter as a
per-tree validity flag, the analog of `eval_tree_array`'s `complete=false`.

Users can register custom operators with `register_unary` / `register_binary`
(analog of `@extend_operators`, reference
src/InterfaceDynamicExpressions.jl:206-215).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# ---------------------------------------------------------------------------
# NaN-safe scalar/elementwise definitions (reference: src/Operators.jl)
# ---------------------------------------------------------------------------


def _nan_like(x: Array) -> Array:
    return jnp.full_like(x, jnp.nan)


def isfinite_(x: Array) -> Array:
    """`jnp.isfinite` that also lowers inside bf16 Pallas TPU kernels.

    Mosaic's finiteness check (`tpu.weird`) only accepts F32 vectors, so a
    bf16 value is cast up first — lossless for finiteness (bf16 inf/nan map
    to f32 inf/nan). Other dtypes (f32, f64) pass through unchanged; f64 is
    NOT cast down, since a finite f64 above f32 max would falsely read as
    inf.
    """
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    return jnp.isfinite(x)


def safe_pow(x: Array, y: Array) -> Array:
    """x^y, NaN when x<0 with non-integer y, or x==0 with y<0.

    Reference: src/Operators.jl:38-46 (safe_pow) — negative bases are legal
    for integer exponents ((-2)^2 == 4).
    """
    bad = ((x < 0) & (y != jnp.round(y))) | ((x == 0) & (y < 0))
    base = jnp.where(bad, 1.0, x)
    out = jnp.power(base, y)
    return jnp.where(bad, jnp.nan, out)


def safe_log(x: Array) -> Array:
    """log(x), NaN for x<=0. Reference: src/Operators.jl:50-53."""
    return jnp.where(x > 0, jnp.log(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log2(x: Array) -> Array:
    return jnp.where(x > 0, jnp.log2(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log10(x: Array) -> Array:
    return jnp.where(x > 0, jnp.log10(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log1p(x: Array) -> Array:
    return jnp.where(x > -1, jnp.log1p(jnp.where(x > -1, x, 0.0)), jnp.nan)


def safe_sqrt(x: Array) -> Array:
    """sqrt(x), NaN for x<0. Reference: src/Operators.jl:70-73."""
    return jnp.where(x >= 0, jnp.sqrt(jnp.where(x >= 0, x, 0.0)), jnp.nan)


def safe_acosh(x: Array) -> Array:
    """acosh(x), NaN for x<1. Reference: src/Operators.jl:66-69."""
    return jnp.where(x >= 1, jnp.arccosh(jnp.where(x >= 1, x, 1.0)), jnp.nan)


def safe_asin(x: Array) -> Array:
    ok = jnp.abs(x) <= 1
    return jnp.where(ok, jnp.arcsin(jnp.clip(x, -1, 1)), jnp.nan)


def safe_acos(x: Array) -> Array:
    ok = jnp.abs(x) <= 1
    return jnp.where(ok, jnp.arccos(jnp.clip(x, -1, 1)), jnp.nan)


def atanh_clip(x: Array) -> Array:
    """atanh of x wrapped to (-1, 1). Reference: src/Operators.jl:14."""
    return jnp.arctanh(((x + 1.0) % 2.0) - 1.0)


def gamma_op(x: Array) -> Array:
    """gamma(x) with poles -> NaN. Reference: src/Operators.jl:8-12.

    lgamma gives log|Gamma|; for x<0 recover the signed value via the
    reflection formula. The reference maps Inf -> NaN at the poles.
    """
    pos = jnp.exp(jax.lax.lgamma(x))
    # Reflection: Gamma(x) = pi / (sin(pi x) Gamma(1-x)) for x < 0.
    neg = jnp.pi / (jnp.sin(jnp.pi * x) * jnp.exp(jax.lax.lgamma(1.0 - x)))
    out = jnp.where(x > 0, pos, neg)
    is_pole = (x <= 0) & (x == jnp.round(x))
    out = jnp.where(is_pole | ~isfinite_(out), jnp.nan, out)
    return out


def erf_op(x: Array) -> Array:
    return jax.lax.erf(x)


def erfc_op(x: Array) -> Array:
    return jax.lax.erfc(x)


def square(x: Array) -> Array:
    return x * x


def cube(x: Array) -> Array:
    return x * x * x


def neg(x: Array) -> Array:
    return -x


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0.0)


def greater(x: Array, y: Array) -> Array:
    """1.0 if x > y else 0.0. Reference: src/Operators.jl:90-96."""
    return jnp.where(x > y, 1.0, 0.0)


def logical_or(x: Array, y: Array) -> Array:
    """Reference: src/Operators.jl:99-104."""
    return jnp.where((x > 0) | (y > 0), 1.0, 0.0)


def logical_and(x: Array, y: Array) -> Array:
    return jnp.where((x > 0) & (y > 0), 1.0, 0.0)


def plus(x, y):
    return x + y


def sub(x, y):
    return x - y


def mult(x, y):
    return x * y


def div(x, y):
    return x / y


def mod_op(x, y):
    return jnp.mod(x, y)


def identity_op(x):
    return x


def sigmoid(x):
    return jax.nn.sigmoid(x)


def gauss(x):
    return jnp.exp(-(x * x))


def inv(x):
    return 1.0 / x


def safe_tan(x):
    return jnp.tan(x)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

# Canonical name -> fn. Names match the reference's spellings where they
# exist (plus Julia builtins the reference lets users pass directly).
UNARY_REGISTRY: Dict[str, Callable] = {
    "cos": jnp.cos,
    "sin": jnp.sin,
    "tan": safe_tan,
    "exp": jnp.exp,
    "log": safe_log,
    "log2": safe_log2,
    "log10": safe_log10,
    "log1p": safe_log1p,
    "sqrt": safe_sqrt,
    "abs": jnp.abs,
    "square": square,
    "cube": cube,
    "neg": neg,
    "relu": relu,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "asin": safe_asin,
    "acos": safe_acos,
    "atan": jnp.arctan,
    "asinh": jnp.arcsinh,
    "acosh": safe_acosh,
    "atanh": atanh_clip,
    "erf": erf_op,
    "erfc": erfc_op,
    "gamma": gamma_op,
    "sigmoid": sigmoid,
    "gauss": gauss,
    "inv": inv,
    "sign": jnp.sign,
    "identity": identity_op,
}

BINARY_REGISTRY: Dict[str, Callable] = {
    "+": plus,
    "-": sub,
    "*": mult,
    "/": div,
    "^": safe_pow,
    "pow": safe_pow,
    "mod": mod_op,
    "max": jnp.maximum,
    "min": jnp.minimum,
    "greater": greater,
    "logical_or": logical_or,
    "logical_and": logical_and,
    "atan2": jnp.arctan2,
}

# Aliases accepted on input (reference maps raw -> safe ops in
# src/Options.jl:86-120 binopmap/unaopmap).
_ALIASES = {
    "plus": "+",
    "sub": "-",
    "mult": "*",
    "div": "/",
    "safe_pow": "^",
    "safe_log": "log",
    "safe_log2": "log2",
    "safe_log10": "log10",
    "safe_log1p": "log1p",
    "safe_sqrt": "sqrt",
    "safe_acosh": "acosh",
    "atanh_clip": "atanh",
}

# Infix printing set
INFIX = {"+", "-", "*", "/", "^"}


def register_unary(name: str, fn: Callable) -> None:
    """Register a custom unary operator (jnp elementwise fn)."""
    UNARY_REGISTRY[name] = fn


def register_binary(name: str, fn: Callable) -> None:
    """Register a custom binary operator (jnp elementwise fn)."""
    BINARY_REGISTRY[name] = fn


def canonical_name(name: str) -> str:
    return _ALIASES.get(name, name)


@dataclasses.dataclass(frozen=True)
class OperatorSet:
    """The operator tables selected by an Options instance.

    Analog of the reference's `OperatorEnum` (src/Options.jl:586-591): an
    ordered list of unary and binary operators; tree nodes store indices
    into these lists.
    """

    unary_names: Tuple[str, ...]
    binary_names: Tuple[str, ...]

    @property
    def unary_fns(self) -> List[Callable]:
        return [UNARY_REGISTRY[n] for n in self.unary_names]

    @property
    def binary_fns(self) -> List[Callable]:
        return [BINARY_REGISTRY[n] for n in self.binary_names]

    @property
    def n_unary(self) -> int:
        return len(self.unary_names)

    @property
    def n_binary(self) -> int:
        return len(self.binary_names)

    def unary_index(self, name: str) -> int:
        return self.unary_names.index(canonical_name(name))

    def binary_index(self, name: str) -> int:
        return self.binary_names.index(canonical_name(name))


def make_operator_set(
    binary_operators: Sequence[str] = ("+", "-", "*", "/"),
    unary_operators: Sequence[str] = (),
) -> OperatorSet:
    bins = tuple(canonical_name(b) for b in binary_operators)
    unas = tuple(canonical_name(u) for u in unary_operators)
    for b in bins:
        if b not in BINARY_REGISTRY:
            raise ValueError(f"Unknown binary operator {b!r}")
    for u in unas:
        if u not in UNARY_REGISTRY:
            raise ValueError(f"Unknown unary operator {u!r}")
    if set(bins) & set(unas):
        # Reference rejects binop/unaop overlap (src/Configure.jl:44-50).
        raise ValueError("Operators cannot be both unary and binary")
    if len(set(bins)) != len(bins) or len(set(unas)) != len(unas):
        raise ValueError("Duplicate operators")
    return OperatorSet(unary_names=unas, binary_names=bins)
