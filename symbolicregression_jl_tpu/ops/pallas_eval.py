"""Pallas TPU kernel: batched postfix-tree interpreter.

This is the hot kernel of the framework (SURVEY.md §7 decision 2) — the
TPU-native replacement for DynamicExpressions' fused eval loops (reference
wraps them at src/InterfaceDynamicExpressions.jl:17-52).

Design, in order of what made it fast on real hardware:

1. **Precomputed operand schedule.** A postfix stack machine carries a
   scalar stack pointer from slot to slot — a scalar dependency chain that
   Mosaic cannot pipeline (measured ~800 ns/slot with `lax.switch`
   dispatch). But the stack layout is fully determined by the opcodes, so
   the wrapper precomputes, per (tree, slot), WHERE that slot's operands
   live (`lidx`/`ridx` into a value array) with a vectorized jnp scan.
   The kernel step then has no carried scalars at all: read operands at
   SMEM-supplied indices, compute, write slot value.
2. **Branchless op dispatch.** Instead of `lax.switch` (real branches,
   pipeline flushes), every operator is computed on the operands and the
   result selected without branching — ~n_ops vector ops per slot, all
   pipelineable. (The lockstep jnp interpreter pays the same n_ops factor
   but on *padded* slots; here short trees stop at their own length.)
   Two selection shapes (`dispatch=`): "chain" = serial `where` chain
   (n_ops dependent selects on the critical path), "mux" (default) = a
   balanced log2(n_ops)-deep select tree on opcode ranges.
2b. **Tree interleaving** (`tree_unroll`, default 8). A single tree's slot
   stream is a serial write→read chain through its value scratch; two
   independent trees advanced in lockstep give the pipeline parallel work
   at every step. The wrapper sorts trees by length (`sort_trees`) so
   interleaved groups finish together (the group loop runs to the max
   length in the group).
3. **Full-vreg row tiles.** Rows live on BOTH sublanes and lanes as
   (r_sub, 128) tiles, so each op runs on full 8x128 vregs.
3b. **Length-bounded slot loop.** Each tree runs ceil(length/4) dynamic
   loop steps of a 4-slot unrolled body — short trees skip their padded
   tail (avg tree fills ~half of max_len) while compiled code stays small
   (a full static unroll, or lax.cond block specializations, multiply
   Mosaic compile time past usability).
4. **SMEM table transpose.** Per-tree tables are (L, t_block), trees on
   the minor axis: SMEM pads each major row to 1 KiB, so the transposed
   layout costs 24 KiB per table instead of 256 KiB (which OOMs the 1 MiB
   SMEM on v5e).

Layout per grid cell (i, j): trees block i (SMEM tables), rows block j
(VMEM (r_sub, 128) tiles), values scratch (L, r_sub, 128) VMEM reused
across the block's trees. Per-row NaN/Inf poison is accumulated elementwise
and reduced to a per-tree badness count (the analog of the reference's
`complete=false` early exit).

Opcodes are pre-fused into a single program code:
  0 = PAD, 1 = CONST, 2 = VAR, 3..3+U-1 = unary ops, 3+U.. = binary ops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.trees import BIN, CONST, PAD, UNA, VAR, TreeBatch
from .losses import contain_nonfinite
from .operators import OperatorSet, isfinite_

Array = jax.Array

DEFAULT_T_BLOCK = 256
DEFAULT_R_BLOCK = 1024


def fuse_opcodes(trees: TreeBatch, operators: OperatorSet) -> Array:
    """kind/op -> single program opcode (same shape as trees.kind)."""
    U = operators.n_unary
    return jnp.where(
        trees.kind == PAD,
        0,
        jnp.where(
            trees.kind == CONST,
            1,
            jnp.where(
                trees.kind == VAR,
                2,
                jnp.where(trees.kind == UNA, 3 + trees.op, 3 + U + trees.op),
            ),
        ),
    ).astype(jnp.int32)


def operand_schedule(kind: Array):
    """Per-slot operand locations for the postfix program.

    Simulates the evaluation stack over the slot axis with a vectorized
    scan (int ops only, batched over trees): returns (lidx, ridx), the
    value-array slots holding each node's left/right operand (unary ops use
    ridx; leaves ignore both). This hoists ALL stack bookkeeping out of the
    TPU kernel, whose steps then carry no scalar state.

    kind: (..., L) int32. Returns int32 arrays of the same shape."""
    from ..models.trees import ARITY

    arity = jnp.asarray(ARITY)[kind]  # (..., L)
    L = kind.shape[-1]
    depth = L // 2 + 2

    def step(stack_sp, inputs):
        stack, sp = stack_sp  # stack: (..., depth) int32, sp: (...,) int32
        si, ar = inputs
        top = jnp.clip(sp - 1, 0, depth - 1)
        sec = jnp.clip(sp - 2, 0, depth - 1)
        ridx = jnp.take_along_axis(stack, top[..., None], axis=-1)[..., 0]
        lidx = jnp.take_along_axis(stack, sec[..., None], axis=-1)[..., 0]
        is_pad = ar < 0
        new_sp = jnp.where(is_pad, sp, sp - jnp.maximum(ar, 0) + 1)
        w = jnp.clip(new_sp - 1, 0, depth - 1)
        new_stack = jnp.where(
            (jnp.arange(depth, dtype=jnp.int32) == w[..., None])
            & ~is_pad[..., None],
            si[..., None],
            stack,
        )
        return (new_stack, new_sp), (lidx, ridx)

    batch_shape = kind.shape[:-1]
    init = (
        jnp.zeros(batch_shape + (depth,), jnp.int32),
        jnp.zeros(batch_shape, jnp.int32),
    )
    sis = jnp.arange(L, dtype=jnp.int32)
    # PAD gets arity -1 so the stack is left untouched
    ar_seq = jnp.moveaxis(jnp.where(kind == PAD, -1, arity), -1, 0)
    si_seq = jnp.broadcast_to(
        sis.reshape((L,) + (1,) * len(batch_shape)), (L,) + batch_shape
    )  # srlint: disable=SR007 -- int32 scan xs input; scan requires a real array
    _, (lidx, ridx) = jax.lax.scan(step, init, (si_seq, ar_seq))
    return jnp.moveaxis(lidx, 0, -1), jnp.moveaxis(ridx, 0, -1)


_SLOT_UNROLL = 4  # slots per dynamic loop step


def _balanced_mux(code, cands):
    """log2(n)-deep select tree over candidates by opcode range — shortens
    the step's serial critical path vs a chained `where` (shared by the
    postfix and instr kernels; their candidate lists differ)."""

    def mux(lo, hi):
        if hi - lo == 1:
            return cands[lo]
        mid = (lo + hi) // 2
        return jnp.where(code < mid, mux(lo, mid), mux(mid, hi))

    return mux(0, len(cands))


# operand-source codes for the compressed instruction program
_SRC_RES = 0  # a previous instruction's result (idx = instruction index)
_SRC_VAR = 1  # a dataset feature (idx = feature index)
_SRC_CONST = 2  # an inline constant (cval)


def instruction_schedule(trees: TreeBatch, operators: OperatorSet):
    """Compress postfix programs to operator-only instruction lists.

    Roughly half the slots of a postfix program are leaves (a tree with b
    binary ops has b+1 of them), and the postfix kernel pays the full
    candidate mux on every slot. This schedule emits one instruction per
    OPERATOR node only; each operand is described by (src, idx, cval)
    where src says whether it is a previous instruction's result, a
    feature column, or a constant. The kernel then fetches operands with
    a 2-select source mux (cheap) and runs the candidate mux ~half as
    often — and, just as important for the TPU pipeline, each tree's
    serial write->read chain through its value scratch is ~half as long.

    Instruction opcodes: 0 = DEAD (padding; executes harmlessly, excluded
    from the poison flag), 1 = IDENT (passes operand `a` through — emitted
    only for bare-leaf trees so every tree has >= 1 instruction),
    2..2+U-1 = unary, 2+U.. = binary.

    trees: flat TreeBatch with (T, L) fields. Returns a dict of (T, L)
    int32/float32 tables (icode, lsrc, lidx, lcval, rsrc, ridx, rcval)
    plus n_instr (T,). Pure jnp (jittable); runs once per eval call on
    the host-side of the kernel launch, like `operand_schedule`.
    """
    from ..models.trees import ARITY

    kind, op, feat, cval = trees.kind, trees.op, trees.feat, trees.cval
    T, L = kind.shape
    U = operators.n_unary
    depth = L // 2 + 2

    arity = jnp.asarray(ARITY)[kind]  # (T, L)

    def step(state, inputs):
        ssrc, sidx, scval, sp, nins = state
        k, o, f, c, ar, si = inputs
        is_pad = k == PAD
        is_op = ar > 0
        top = jnp.clip(sp - 1, 0, depth - 1)[:, None]
        sec = jnp.clip(sp - 2, 0, depth - 1)[:, None]
        take = lambda s, i: jnp.take_along_axis(s, i, axis=-1)[:, 0]
        # right operand = stack top; left = second (binary only)
        rsrc, ridx, rcval = take(ssrc, top), take(sidx, top), take(scval, top)
        is_bin = ar == 2
        lsrc = jnp.where(is_bin, take(ssrc, sec), _SRC_CONST)
        # dummy left operand of non-binary steps points at slot L — a
        # trash address distinct from every real postfix slot, so the
        # gradient kernel's dead db write can never clobber a real
        # constant's adjoint (eval kernels ignore idx for const operands)
        lidx = jnp.where(is_bin, take(sidx, sec), L)
        lcval = jnp.where(is_bin, take(scval, sec), 0.0)
        icode = jnp.where(
            is_op, jnp.where(k == UNA, 2 + o, 2 + U + o), 0
        ).astype(jnp.int32)
        # push: the op's result, or the leaf itself. CONST leaves record
        # their postfix slot as idx (unused by eval, which reads cval, but
        # it lets the gradient kernel scatter d loss/d cval by slot).
        psrc = jnp.where(is_op, _SRC_RES,
                         jnp.where(k == VAR, _SRC_VAR, _SRC_CONST))
        pidx = jnp.where(is_op, nins, jnp.where(k == VAR, f, si))
        pcval = jnp.where(k == CONST, c, 0.0)
        new_sp = jnp.where(is_pad, sp, sp - jnp.maximum(ar, 0) + 1)
        w = jnp.clip(new_sp - 1, 0, depth - 1)
        at_w = (jnp.arange(depth, dtype=jnp.int32) == w[:, None]) \
            & ~is_pad[:, None]
        new_state = (
            jnp.where(at_w, psrc[:, None], ssrc),
            jnp.where(at_w, pidx[:, None], sidx),
            jnp.where(at_w, pcval[:, None], scval),
            new_sp,
            nins + is_op.astype(jnp.int32),
        )
        out = (is_op, icode, lsrc, lidx, lcval, rsrc, ridx, rcval)
        return new_state, out

    init = (
        jnp.zeros((T, depth), jnp.int32),
        jnp.zeros((T, depth), jnp.int32),
        jnp.zeros((T, depth), jnp.float32),
        jnp.zeros((T,), jnp.int32),
        jnp.zeros((T,), jnp.int32),
    )
    mv = lambda x: jnp.moveaxis(x, -1, 0)
    si_seq = jnp.broadcast_to(
        jnp.arange(L, dtype=jnp.int32)[:, None], (L, T)
    )  # srlint: disable=SR007 -- int32 scan xs input; scan requires a real array
    inputs = (mv(kind), mv(op), mv(feat),
              mv(cval.astype(jnp.float32)), mv(arity), si_seq)
    (ssrc, sidx, scval, sp, nins), outs = jax.lax.scan(step, init, inputs)
    is_op, icode, lsrc, lidx, lcval, rsrc, ridx, rcval = (
        jnp.moveaxis(x, 0, -1) for x in outs
    )

    # compact: drop leaf slots, placing instruction k of each tree at
    # column k (batched scatter; dropped slots land in the L overflow col)
    pos = jnp.cumsum(is_op.astype(jnp.int32), axis=-1) - 1
    col = jnp.where(is_op, pos, L)
    rows = jnp.arange(T, dtype=jnp.int32)[:, None]

    def compact(x, fill=0):
        out = jnp.full((T, L + 1), fill, x.dtype)
        return out.at[rows, col].set(x, mode="drop")[:, :L]

    tables = {
        "icode": compact(icode),
        "lsrc": compact(lsrc, _SRC_CONST), "lidx": compact(lidx),
        "lcval": compact(lcval, 0.0),
        "rsrc": compact(rsrc, _SRC_CONST), "ridx": compact(ridx),
        "rcval": compact(rcval, 0.0),
    }

    # bare-leaf trees (no operator nodes): one IDENT instruction whose
    # operand is the root leaf, sitting on the final stack top
    top = jnp.clip(sp - 1, 0, depth - 1)[:, None]
    take = lambda s: jnp.take_along_axis(s, top, axis=-1)[:, 0]
    bare = (nins == 0) & (trees.length > 0)
    first = jnp.arange(L, dtype=jnp.int32) == 0
    sel = bare[:, None] & first
    tables["icode"] = jnp.where(sel, 1, tables["icode"])
    tables["rsrc"] = jnp.where(sel, take(ssrc)[:, None], tables["rsrc"])
    tables["ridx"] = jnp.where(sel, take(sidx)[:, None], tables["ridx"])
    tables["rcval"] = jnp.where(
        sel, take(scval)[:, None], tables["rcval"]
    )
    # IDENT's dummy left operand gets the same trash slot as other
    # non-binary steps (the compact fill of 0 would alias postfix slot 0
    # in the gradient kernel's adjoint space)
    tables["lidx"] = jnp.where(sel, L, tables["lidx"])
    n_instr = jnp.where(bare, 1, nins)
    return tables, n_instr


def pack_instr_tables(tables, nfeat: int, const_base: int = 0):
    """Pack the instr program's five integer tables into ONE int32 word per
    step, and unify result/feature operand indices into a single address
    space (see _make_instr_kernel with packed=True).

    Per step the packed kernel reads 3 SMEM scalars (word, lcval, rcval)
    instead of 7 — the per-slot scalar-unit work (loads + addressing) is
    what bounds the interpreter once trees are interleaved, so shrinking
    it matters more than any vector-side tweak.

    Unified operand space: scratch slot f in [0, nfeat) holds feature f
    (preloaded once per grid cell), slot nfeat+k holds instruction k's
    result. A _SRC_VAR operand becomes idx=feat, a _SRC_RES operand
    becomes idx=nfeat+k, and only _SRC_CONST keeps a flag bit.

    const_base > 0 (gradient kernel): a _SRC_CONST operand's idx becomes
    const_base + its postfix slot, giving each constant its own adjoint
    scratch address so the backward sweep can scatter d loss/d cval by
    slot; the eval kernel passes 0 and ignores idx for const operands.

    Word layout (32 bits): icode[0:8] | lconst[8] | rconst[9] |
    lidx[10:21] | ridx[21:32]. Requires icode < 256 and indices < 2048
    (11 bits) — checked by the caller.
    """
    icode = tables["icode"]
    lconst = (tables["lsrc"] == _SRC_CONST).astype(jnp.int32)
    rconst = (tables["rsrc"] == _SRC_CONST).astype(jnp.int32)

    def unify(src, idx):
        return jnp.where(
            src == _SRC_RES, nfeat + idx,
            jnp.where(
                src == _SRC_VAR, idx,
                (const_base + idx) if const_base else 0,
            ),
        )

    lidx = unify(tables["lsrc"], tables["lidx"])
    ridx = unify(tables["rsrc"], tables["ridx"])
    word = (
        icode
        | (lconst << 8)
        | (rconst << 9)
        | (lidx << 10)
        | (ridx << 21)
    ).astype(jnp.int32)
    return word


def decode_packed_word(w):
    """Inverse of pack_instr_tables' bit layout — the single decoder
    shared by every packed-program kernel (eval and gradient), so a
    layout change cannot silently diverge them. Returns
    (code, lconst, rconst, lidx, ridx)."""
    return (w & 0xFF, (w >> 8) & 1, (w >> 9) & 1,
            (w >> 10) & 0x7FF, (w >> 21) & 0x7FF)


def instr_dispatch(code, a, b, unary_fns, binary_fns, dispatch="mux"):
    """Branchless candidate dispatch over the instruction opcodes —
    shared by both instr-kernel table layouts and the gradient kernel's
    forward sweep (opcodes: 0 DEAD, 1 IDENT, then unary, then binary)."""
    if dispatch == "chain":
        U = len(unary_fns)
        v = a
        for j, fn in enumerate(unary_fns):
            v = jnp.where(code == 2 + j, fn(a), v)
        for j, fn in enumerate(binary_fns):
            v = jnp.where(code == 2 + U + j, fn(b, a), v)
        return v
    cands = [a, a]  # DEAD (dead), IDENT
    cands += [fn(a) for fn in unary_fns]
    cands += [fn(b, a) for fn in binary_fns]
    return _balanced_mux(code, cands)


def kernel_row_validity(nrows_ref, r_sub):
    """Shared kernel-top-level preamble: the row-grid index and the
    row-validity mask for this grid step.

    pid_j is read ONCE here and threaded to the loop bodies — a fresh
    pl.program_id() call inside a fori_loop body does not survive
    interpret-mode lowering. The mask zeroes padded tail rows so they
    cannot poison a tree. Returns (pid_j, valid_f).
    """
    from jax.experimental import pallas as pl  # noqa: PLC0415

    pid_j = pl.program_id(1)
    sub = jax.lax.broadcasted_iota(jnp.int32, (r_sub, 128), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_sub, 128), 1)
    row = (pid_j * r_sub + sub) * 128 + lane
    return pid_j, jnp.where(row < nrows_ref[0], 1.0, 0.0)


def accum_tile(ref, idx, pid_j, val):
    """Init-or-accumulate a per-tree scalar across the row-tile sweep.

    The scalar output blocks' index maps ignore the row-grid index j, so
    the block stays resident while j advances sequentially (j is the
    minor grid dim): tile 0 initializes, later tiles add. Shared by the
    eval kernels' poison outputs and the grad kernel's loss/grad/poison
    outputs so the init condition lives in exactly one place.
    """
    ref[idx] = jnp.where(pid_j == 0, 0.0, ref[idx]) + val


def _make_kernel(operators: OperatorSet, t_block: int, r_block: int,
                 max_len: int, slot_loop: str, dispatch: str,
                 tree_unroll: int, compute_dtype=jnp.float32,
                 leaf_skip: "bool | str" = False,
                 scalar_pack: bool = False,
                 top_carry: bool = False,
                 fused_loss=None):
    """fused_loss (elementwise (pred, target) -> elem, or None): when set,
    the kernel fuses the loss epilogue — instead of writing each tree's
    root-value row tile to a (T_pad, NR, 128) output, it computes
    ``elem = fused_loss(root, y_tile)`` in-register, zeroes padded rows,
    reduces the tile with one ``jnp.sum``, and accumulates the per-tree
    scalar across the row-tile sweep exactly like the poison output
    (``accum_tile``; the loss-sum block's index map ignores j). The call
    then never materializes a ``(B, nrows)`` array on either side of the
    kernel boundary. The reduction order — per-tile ``jnp.sum``, then a
    sequential fold over row tiles — is the order
    ``ops.losses.aggregate_loss(tile_rows=r_block)`` pins on the host
    graph, which is what makes the fused epilogue bit-identical to that
    composition rather than merely close to ``jnp.mean``."""
    from jax.experimental import pallas as pl  # noqa: PLC0415

    if slot_loop not in ("dynamic", "unrolled"):
        raise ValueError(
            f"slot_loop must be 'dynamic' or 'unrolled', got {slot_loop!r}"
        )
    if dispatch not in ("mux", "chain"):
        raise ValueError(f"dispatch must be 'mux' or 'chain', got {dispatch!r}")
    if tree_unroll not in (1, 2, 4, 8, 16) or t_block % tree_unroll:
        raise ValueError(
            "tree_unroll must be 1/2/4/8/16 and divide t_block, "
            f"got {tree_unroll}"
        )

    unary_fns = operators.kernel_unary_fns
    binary_fns = operators.kernel_binary_fns
    U = len(unary_fns)
    r_sub = r_block // 128
    cdt = compute_dtype

    def make_kernel_with_fetch(fetch_of_refs, n_tbl_refs):
        """Shared postfix body around a per-slot scalar fetch.

        `fetch_of_refs(tbl_refs)(si, ti) -> (code, feat, lidx, ridx)` —
        scalar_pack=True reads ONE packed word per (slot, tree) instead
        of four table entries, halving the scalar-unit SMEM traffic the
        opset_sweep decomposition identified as part of the dominant
        fixed per-slot cost."""

        def kernel(nrows_ref, *rest):
            tbl_refs = rest[:n_tbl_refs]
            length_ref, cval_ref = rest[n_tbl_refs:n_tbl_refs + 2]
            if fused_loss is None:
                X_ref, out_ref, bad_ref = rest[n_tbl_refs + 2:n_tbl_refs + 5]
                ytgt_ref = None
                val_refs = rest[n_tbl_refs + 5:]
            else:
                X_ref, ytgt_ref, out_ref, bad_ref = (
                    rest[n_tbl_refs + 2:n_tbl_refs + 6]
                )
                val_refs = rest[n_tbl_refs + 6:]
            fetch = fetch_of_refs(tbl_refs)
            pid_j, valid_f = kernel_row_validity(nrows_ref, r_sub)
            run_postfix_body(
                fetch, length_ref, cval_ref, X_ref, ytgt_ref, out_ref,
                bad_ref, val_refs, pid_j, valid_f,
            )

        return kernel

    def run_postfix_body(fetch, length_ref, cval_ref, X_ref, ytgt_ref,
                         out_ref, bad_ref, val_refs, pid_j, valid_f):
        def slot_body(si, ti, bad, val_ref, v_prev):
            """One postfix slot: branchless dispatch over the operator set.

            PAD slots execute harmlessly: code 0 is masked out of the
            poison flag, writes land in dead val_ref slots, and operand
            indices are stack-clipped by construction.

            Returns (bad', stored): the slot's stored value feeds the
            next slot's `v_prev` — in postfix order the TOP of stack (an
            operator's right/unary operand) is ALWAYS the immediately
            preceding slot's result (encode-time invariant: ridx == si-1
            for every operator slot), so top_carry=True replaces the
            dynamic `val_ref[ridx]` scratch read with this loop-carried
            register, dropping one dynamic VMEM read per step AND taking
            the scratch write->read round-trip out of the tree's serial
            dependence chain (the chain tree-interleaving exists to
            hide). PAD tail slots clobber v_prev harmlessly: padding is
            only ever trailing, so no real slot consumes it."""
            code, fidx, lidx, ridx = fetch(si, ti)
            if top_carry:
                a = v_prev  # top of stack == previous slot's result
            else:
                a = val_ref[ridx]  # top of stack: right arg
            b = val_ref[lidx]  # second: left arg
            x = X_ref[fidx]
            if cdt != jnp.float32:
                # bf16 is a STORAGE dtype only: operands upcast to f32 for
                # the candidate ops (Mosaic cannot lower cos/sin/sqrt/round
                # /mod/nan-splat on bf16 vectors — probed on v5e 2026-07-31)
                # and results round back to bf16 at the scratch store, so
                # only the VMEM/X traffic pays half price.
                a, b, x = (t.astype(jnp.float32) for t in (a, b, x))
            cv = jnp.full((r_sub, 128), cval_ref[si, ti], jnp.float32)
            if leaf_skip:
                # Scalar-predicated branches: roughly half the slots of a
                # postfix program are leaves (a tree with b binary ops
                # has b+1 of them), and the branchless mux pays the FULL
                # candidate set (every transcendental) on each. The
                # opcode is a per-(slot, tree) SCALAR — uniform across
                # lanes — so a real branch skips the operator candidates
                # entirely on leaf slots without any lane divergence.
                # leaf_skip=True: 2-way (leaf | all ops).
                # leaf_skip="class": 3-way (leaf | unary | binary) — the
                # binary arm (usually cheap arithmetic, the most common
                # operator class) skips the transcendental unary
                # candidates too.
                # (The 2023-vintage lax.switch-per-op design measured
                # ~800 ns/slot, but that was ~n_ops branch targets plus a
                # carried stack pointer; these are 2-3 branches with the
                # precomputed operand schedule intact. Whether Mosaic's
                # lowering keeps the tree-interleave pipeline overlap
                # across the branches is exactly what kernel_tune
                # measures.)
                @pl.when(code < 3)
                def _():
                    val_ref[si] = jnp.where(code == 1, cv, x).astype(cdt)

                if leaf_skip == "class" and U > 0 and binary_fns:
                    @pl.when((code >= 3) & (code < 3 + U))
                    def _():
                        v = _balanced_mux(
                            code - 3, [fn(a) for fn in unary_fns]
                        )
                        val_ref[si] = v.astype(jnp.float32).astype(cdt)

                    @pl.when(code >= 3 + U)
                    def _():
                        v = _balanced_mux(
                            code - 3 - U,
                            [fn(b, a) for fn in binary_fns],
                        )
                        val_ref[si] = v.astype(jnp.float32).astype(cdt)
                else:
                    @pl.when(code >= 3)
                    def _():
                        cands = [fn(a) for fn in unary_fns]
                        cands += [fn(b, a) for fn in binary_fns]
                        v = _balanced_mux(code - 3, cands)
                        val_ref[si] = v.astype(jnp.float32).astype(cdt)

                stored = val_ref[si]
                stored_f32 = stored
                if cdt != jnp.float32:
                    stored_f32 = stored.astype(jnp.float32)
                return jnp.maximum(
                    bad,
                    jnp.where(
                        isfinite_(stored_f32) | (code == 0), 0.0, valid_f
                    ),
                ), stored
            if dispatch == "chain":
                # serial select chain: n_codes dependent `where`s
                v = jnp.where(code == 1, cv, x)
                for j, fn in enumerate(unary_fns):
                    v = jnp.where(code == 3 + j, fn(a), v)
                for j, fn in enumerate(binary_fns):
                    v = jnp.where(code == 3 + U + j, fn(b, a), v)
            else:
                # balanced mux: all candidates computed in parallel (stack
                # writes/reads already serialize consecutive slots, so the
                # select tree's depth is what the pipeline sees)
                cands = [x, cv, x]  # PAD (dead), CONST, VAR
                cands += [fn(a) for fn in unary_fns]
                cands += [fn(b, a) for fn in binary_fns]
                v = _balanced_mux(code, cands)
            # some operator impls upcast internally (special functions);
            # normalize, then round to the storage dtype at the store.
            # Poison checks the STORED value: rounding f32->bf16 can
            # overflow to inf in (bf16_max, f32_max], which downstream
            # slots will read and must count as non-finite.
            stored = v.astype(jnp.float32).astype(cdt)
            val_ref[si] = stored
            return jnp.maximum(
                bad,
                jnp.where(isfinite_(stored) | (code == 0), 0.0, valid_f),
            ), stored

        zero = jnp.zeros((r_sub, 128), jnp.float32)
        vzero = jnp.zeros((r_sub, 128), cdt)

        def tree_group_body(p, _):
            """tree_unroll independent trees advanced in lockstep: their
            slot streams have no data dependencies on each other, so the
            pipeline overlaps them (each single tree is a serial
            write-then-read chain through its val scratch). Padded slots of
            the shorter trees execute harmlessly (PAD semantics above);
            the wrapper sorts trees by length so group members match."""
            tis = [p * tree_unroll + k for k in range(tree_unroll)]
            ns = [length_ref[0, ti] for ti in tis]
            if slot_loop == "dynamic":
                n_max = ns[0]
                for n in ns[1:]:
                    n_max = jnp.maximum(n_max, n)
                n_groups = (n_max + _SLOT_UNROLL - 1) // _SLOT_UNROLL
                if top_carry:
                    def slot_group(g, carry):
                        bads, vprevs = list(carry[0]), list(carry[1])
                        for k in range(_SLOT_UNROLL):
                            si = g * _SLOT_UNROLL + k
                            for t in range(tree_unroll):
                                bads[t], vprevs[t] = slot_body(
                                    si, tis[t], bads[t], val_refs[t],
                                    vprevs[t],
                                )
                        return (tuple(bads), tuple(vprevs))

                    bads, _ = jax.lax.fori_loop(
                        0, n_groups, slot_group,
                        ((zero,) * tree_unroll, (vzero,) * tree_unroll),
                    )
                else:
                    # no carried v_prev when the variant is off: dead
                    # loop-carried vregs would shift baseline codegen
                    # (register pressure) on every previously measured
                    # variant
                    def slot_group(g, bads):
                        bads = list(bads)
                        for k in range(_SLOT_UNROLL):
                            si = g * _SLOT_UNROLL + k
                            for t in range(tree_unroll):
                                bads[t], _ = slot_body(
                                    si, tis[t], bads[t], val_refs[t],
                                    None,
                                )
                        return tuple(bads)

                    bads = jax.lax.fori_loop(
                        0, n_groups, slot_group, (zero,) * tree_unroll
                    )
            else:
                # Full static unroll: every slot executes for every tree —
                # more straight-line overlap, no loop overhead, but pays
                # for padded tails and compiles slower. (A/B alternative.)
                bads = [zero] * tree_unroll
                vprevs = [vzero] * tree_unroll
                for si in range(max_len):
                    for t in range(tree_unroll):
                        bads[t], vprevs[t] = slot_body(
                            si, tis[t], bads[t], val_refs[t], vprevs[t]
                        )
            for t in range(tree_unroll):
                if fused_loss is None:
                    # output/accumulation stays float32 regardless of cdt
                    out_ref[tis[t]] = val_refs[t][
                        jnp.maximum(ns[t] - 1, 0)
                    ].astype(jnp.float32)
                else:
                    # fused epilogue: elem on the root's row tile, padded
                    # rows zeroed (a `where`, not a multiply: 0 * inf is
                    # NaN and the pad region of y/X is garbage), one
                    # per-tile jnp.sum, accum_tile across the j sweep —
                    # the exact order aggregate_loss(tile_rows=r_block)
                    # replays on the host graph
                    root = val_refs[t][
                        jnp.maximum(ns[t] - 1, 0)
                    ].astype(jnp.float32)
                    elem = jnp.where(
                        valid_f > 0, fused_loss(root, ytgt_ref[...]), 0.0
                    )
                    accum_tile(out_ref, (0, tis[t]), pid_j, jnp.sum(elem))
                accum_tile(bad_ref, (0, tis[t]), pid_j, jnp.sum(bads[t]))
            return 0

        jax.lax.fori_loop(0, t_block // tree_unroll, tree_group_body, 0)

    if scalar_pack:
        def fetch_packed(tbls):
            (pword_ref,) = tbls

            def fetch(si, ti):
                # top_carry never consumes the decoded ridx field; XLA
                # DCEs its (pure) shift+mask
                return decode_postfix_word(pword_ref[si, ti])

            return fetch

        return make_kernel_with_fetch(fetch_packed, 1)

    def fetch_tables(tbls):
        pcode_ref, feat_ref, lidx_ref, ridx_ref = tbls

        def fetch(si, ti):
            # top_carry replaces the per-slot ridx scalar read with the
            # loop-carried register (see slot_body)
            r = 0 if top_carry else ridx_ref[si, ti]
            return (pcode_ref[si, ti], feat_ref[si, ti],
                    lidx_ref[si, ti], r)

        return fetch

    return make_kernel_with_fetch(fetch_tables, 4)


def pack_postfix_scalars(pcode, feat, lidx, ridx, n_codes, nfeat, L):
    """Pack the four per-slot scalar tables into one i32 word table
    (6+8+9+9 bits): the packed postfix kernel reads 1 SMEM scalar per
    (slot, tree) instead of 4. Raises when a field exceeds its width —
    an explicit failure, not a silent fallback (benchmark attribution).
    decode_postfix_word is the matching (and only) decoder."""
    if n_codes > 64 or nfeat > 256 or L > 512:
        raise ValueError(
            "scalar_pack needs n_codes <= 64, nfeat <= 256, max_len <= "
            f"512; got {n_codes} codes, {nfeat} features, {L} slots"
        )
    return (
        pcode.astype(jnp.int32)
        | (feat.astype(jnp.int32) << 6)
        | (lidx.astype(jnp.int32) << 14)
        | (ridx.astype(jnp.int32) << 23)
    )


def decode_postfix_word(w):
    """(pcode, feat, lidx, ridx) from one packed postfix word — the single
    decoder for pack_postfix_scalars' layout, shared so a field-width
    change cannot silently diverge encoder and kernel (same discipline as
    decode_packed_word for the instr program). The mask after the
    (arithmetic) shift also clears sign-extension when bit 31 is set."""
    return (
        w & 0x3F,
        (w >> 6) & 0xFF,
        (w >> 14) & 0x1FF,
        (w >> 23) & 0x1FF,
    )


def _make_instr_kernel(operators: OperatorSet, t_block: int, r_block: int,
                       max_len: int, dispatch: str, tree_unroll: int,
                       nfeat: int, compute_dtype=jnp.float32,
                       packed: bool = False):
    """Kernel for the compressed instruction program (instruction_schedule).

    Same layout discipline as `_make_kernel` (SMEM transposed tables, VMEM
    row tiles, tree interleaving); differs per step: operands are fetched
    as data (result / feature / constant) instead of always from the value
    scratch, and only operator nodes execute, so programs are ~half as
    long and leaves never pay the candidate mux.

    packed=False: five integer SMEM tables; each operand materializes all
    three candidate sources behind a 2-deep select.
    packed=True (see pack_instr_tables): one packed int32 word per step
    (3 SMEM reads instead of 7) and a unified operand scratch — features
    preloaded at [0, nfeat), results at nfeat+k — so each operand is one
    dynamic VMEM load plus a constant select. Both are scalar-unit
    relief: per-step scalar loads/addressing, not vector issue, bound the
    interpreter once enough trees are interleaved."""
    from jax.experimental import pallas as pl  # noqa: PLC0415

    if dispatch not in ("mux", "chain"):
        raise ValueError(f"dispatch must be 'mux' or 'chain', got {dispatch!r}")
    if tree_unroll not in (1, 2, 4, 8, 16) or t_block % tree_unroll:
        raise ValueError(
            "tree_unroll must be 1/2/4/8/16 and divide t_block, "
            f"got {tree_unroll}"
        )

    unary_fns = operators.kernel_unary_fns
    binary_fns = operators.kernel_binary_fns
    r_sub = r_block // 128
    cdt = compute_dtype
    base = nfeat if packed else 0  # scratch offset of instruction results

    def make_body(read_operands, val_refs, valid_f):
        """The per-step body around a layout-specific operand reader."""

        def instr_body(si, ti, bad, val_ref):
            code, a, b = read_operands(si, ti, val_ref)
            if cdt != jnp.float32:
                # bf16 is storage-only: ops run in f32 (Mosaic cannot
                # lower cos/sin/sqrt/round/mod on bf16 vectors), results
                # round back at the scratch store — see _make_kernel.
                a, b = a.astype(jnp.float32), b.astype(jnp.float32)
            v = instr_dispatch(
                code, a, b, unary_fns, binary_fns, dispatch
            ).astype(jnp.float32)
            # store first, poison on the STORED value (f32->bf16 rounding
            # can overflow to inf; see _make_kernel)
            v = v.astype(cdt)
            val_ref[base + si] = v
            # operand finiteness matters too: the postfix kernel checks
            # every leaf slot's value, so a tree whose op maps an Inf
            # operand back to a finite result (relu(-inf)=0) must still
            # be poisoned for parity
            fin = isfinite_(v) & isfinite_(a) & isfinite_(b)
            return jnp.maximum(
                bad, jnp.where(fin | (code == 0), 0.0, valid_f)
            )

        return instr_body

    def run_groups(instr_body, ninstr_ref, out_ref, bad_ref, val_refs,
                   pid_j):
        """Interleaved tree-group loop shared by both layouts."""
        zero = jnp.zeros((r_sub, 128), jnp.float32)

        def tree_group_body(p, _):
            tis = [p * tree_unroll + k for k in range(tree_unroll)]
            ns = [ninstr_ref[0, ti] for ti in tis]
            n_max = ns[0]
            for n in ns[1:]:
                n_max = jnp.maximum(n_max, n)

            def slot_group(g, bads):
                bads = list(bads)
                for k in range(_SLOT_UNROLL):
                    si = g * _SLOT_UNROLL + k
                    for t in range(tree_unroll):
                        bads[t] = instr_body(si, tis[t], bads[t], val_refs[t])
                return tuple(bads)

            n_groups = (n_max + _SLOT_UNROLL - 1) // _SLOT_UNROLL
            bads = jax.lax.fori_loop(
                0, n_groups, slot_group, (zero,) * tree_unroll
            )
            for t in range(tree_unroll):
                out_ref[tis[t]] = val_refs[t][
                    base + jnp.maximum(ns[t] - 1, 0)
                ].astype(jnp.float32)
                accum_tile(bad_ref, (0, tis[t]), pid_j, jnp.sum(bads[t]))
            return 0

        jax.lax.fori_loop(0, t_block // tree_unroll, tree_group_body, 0)

    if packed:
        def kernel(nrows_ref, word_ref, lcval_ref, rcval_ref, ninstr_ref,
                   X_ref, out_ref, bad_ref, *val_refs):
            pid_j, valid_f = kernel_row_validity(nrows_ref, r_sub)
            # preload features into every interleave slot's scratch once
            # per grid cell; instruction results only ever write at
            # nfeat+k so these stay valid across all tree groups
            for f in range(nfeat):
                xf = X_ref[f]
                for t in range(tree_unroll):
                    val_refs[t][f] = xf

            def read_operands(si, ti, val_ref):
                code, lconst, rconst, lidx, ridx = decode_packed_word(
                    word_ref[si, ti]
                )
                acv = jnp.full((r_sub, 128), rcval_ref[si, ti], cdt)
                bcv = jnp.full((r_sub, 128), lcval_ref[si, ti], cdt)
                a = jnp.where(rconst == 1, acv, val_ref[ridx])
                b = jnp.where(lconst == 1, bcv, val_ref[lidx])
                return code, a, b

            run_groups(
                make_body(read_operands, val_refs, valid_f),
                ninstr_ref, out_ref, bad_ref, val_refs, pid_j,
            )

        return kernel

    def kernel(nrows_ref, icode_ref,
               lsrc_ref, lidx_ref, lcval_ref,
               rsrc_ref, ridx_ref, rcval_ref,
               ninstr_ref,
               X_ref, out_ref, bad_ref,
               *val_refs):
        pid_j, valid_f = kernel_row_validity(nrows_ref, r_sub)

        def fetch(src, idx, cv, val_ref):
            """Source mux: previous result / feature column / constant.
            All three candidates are materialized (branchless); the two
            dynamic reads are clipped to their arrays' bounds so dead
            sources read harmless garbage."""
            v_res = val_ref[jnp.minimum(idx, max_len - 1)]
            v_var = X_ref[jnp.minimum(idx, nfeat - 1)]
            v_cv = jnp.full((r_sub, 128), cv, cdt)
            return jnp.where(
                src == _SRC_RES, v_res,
                jnp.where(src == _SRC_VAR, v_var, v_cv),
            )

        def read_operands(si, ti, val_ref):
            code = icode_ref[si, ti]
            a = fetch(rsrc_ref[si, ti], ridx_ref[si, ti],
                      rcval_ref[si, ti], val_ref)
            b = fetch(lsrc_ref[si, ti], lidx_ref[si, ti],
                      lcval_ref[si, ti], val_ref)
            return code, a, b

        run_groups(
            make_body(read_operands, val_refs, valid_f),
            ninstr_ref, out_ref, bad_ref, val_refs, pid_j,
        )

    return kernel


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _ladder_bounds(n: int, ladder: Tuple[float, ...]):
    """Host-static (lo, hi) bucket slices of a length-sorted batch of n
    trees under a cumulative-fraction ladder — THE positional boundary
    definition is models.fitness._bucket_bounds (shared with the jnp
    interpreter's bucketed driver so both backends split one sorted
    order at identical positions). Empty slices are dropped; an empty
    ladder is the single flat bucket."""
    if not ladder:
        return [(0, n)] if n else []
    from ..models.fitness import _bucket_bounds  # noqa: PLC0415

    bounds = _bucket_bounds(n, ladder)
    return [(lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo]


def _postfix_call(flat_b: TreeBatch, Xp: Array, ytgt, nrows_arr: Array,
                  operators: OperatorSet, L: int, t_block: int,
                  r_block: int, interpret: bool, slot_loop: str,
                  dispatch: str, tree_unroll: int, cdt, leaf_skip,
                  scalar_pack: bool, top_carry: bool, NR: int,
                  nfeat: int, fused_loss=None):
    """One postfix pallas_call over a contiguous slice of the (sorted)
    flat batch — the per-bucket unit of the length-bucket ladder. The
    tree-block size re-clamps to THIS slice, so a small tail bucket runs
    a small grid instead of inheriting the full batch's t_block padding.

    Returns (y (Tb, R_pad) float32, bad (Tb,)) in value mode, or
    (loss_sum (Tb,), bad (Tb,)) when fused_loss is set (ytgt = the
    (NR, 128)-tiled f32 target; see _make_kernel's fused_loss note)."""
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    T = flat_b.length.shape[0]
    r_sub = r_block // 128
    t_block = min(t_block, _round_up(max(T, 8), tree_unroll))
    T_pad = _round_up(T, t_block)

    # tables transposed to (L, T_pad) — see module docstring point 4
    def padT(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T), (0, 0)),
                       constant_values=fill).T

    pcode = padT(fuse_opcodes(flat_b, operators))
    feat = padT(flat_b.feat)
    lidx, ridx = operand_schedule(flat_b.kind)
    lidx, ridx = padT(lidx), padT(ridx)
    length = jnp.pad(flat_b.length, (0, T_pad - T))[None, :]
    cval = padT(flat_b.cval.astype(jnp.float32))

    kernel = _make_kernel(operators, t_block, r_block, L, slot_loop,
                          dispatch, tree_unroll, cdt, leaf_skip=leaf_skip,
                          scalar_pack=scalar_pack, top_carry=top_carry,
                          fused_loss=fused_loss)

    # INVARIANT (accum_tile soundness): the row-tile index j MUST stay the
    # trailing, sequentially-iterated grid dimension, and the scalar
    # outputs' index maps must ignore j so their blocks stay resident
    # across the j sweep (tile 0 initializes, later tiles accumulate).
    # Reordering this grid or marking j parallel via dimension_semantics
    # would silently corrupt poison/loss outputs.
    grid = (T_pad // t_block, NR // r_sub)
    smem_spec = lambda shape, imap: pl.BlockSpec(
        shape, imap, memory_space=pltpu.SMEM
    )
    tree_tbl = lambda: smem_spec((L, t_block), lambda i, j: (0, i))
    if scalar_pack:
        n_codes = 3 + operators.n_unary + operators.n_binary
        tbl_args = (
            pack_postfix_scalars(pcode, feat, lidx, ridx, n_codes,
                                 nfeat, L),
        )
    else:
        tbl_args = (pcode, feat, lidx, ridx)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # nrows scalar
        *[tree_tbl() for _ in tbl_args],  # scalar table(s)
        smem_spec((1, t_block), lambda i, j: (0, i)),  # length
        tree_tbl(),  # cval
        pl.BlockSpec((nfeat, r_sub, 128), lambda i, j: (0, j, 0)),
    ]
    args = [nrows_arr, *tbl_args, length, cval, Xp]
    # the poison row (and the fused loss-sum row) is accumulated across
    # row tiles inside the kernel (the index map ignores j, so the block
    # stays resident for the whole j sweep). A per-tile (1, t_block)
    # block over a (grid_j, T_pad) array would be an ILLEGAL Mosaic
    # block shape for grid_j > 1 (sublane dim must be a multiple of 8 or
    # equal the array's), and a (grid_j, t_block) resident block would
    # grow SMEM linearly with the row-tile count.
    if fused_loss is not None:
        in_specs.append(
            pl.BlockSpec((r_sub, 128), lambda i, j: (j, 0))  # y target
        )
        args.append(ytgt)
        out_specs = [
            smem_spec((1, t_block), lambda i, j: (0, i)),  # loss sum
            smem_spec((1, t_block), lambda i, j: (0, i)),  # poison
        ]
        out_shape = [
            jax.ShapeDtypeStruct((1, T_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, T_pad), jnp.float32),
        ]
    else:
        out_specs = [
            pl.BlockSpec((t_block, r_sub, 128), lambda i, j: (i, j, 0)),
            smem_spec((1, t_block), lambda i, j: (0, i)),  # poison
        ]
        out_shape = [
            jax.ShapeDtypeStruct((T_pad, NR, 128), jnp.float32),
            jax.ShapeDtypeStruct((1, T_pad), jnp.float32),
        ]
    out, bad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((L, r_sub, 128), cdt)
            for _ in range(tree_unroll)
        ],
        interpret=interpret,
    )(*args)
    if fused_loss is not None:
        return out[0, :T], bad[0, :T]
    return out.reshape(T_pad, NR * 128)[:T], bad[0, :T]


def _check_r_block(r_block: int, nrows: int, interpret: bool):
    """Mosaic blocks over the row-tile axis must have a sublane count that
    is a multiple of 8 or covers the whole axis, and the row padding math
    needs whole 128-lane tiles; anything else dies deep in lowering (or
    tracing, or a ZeroDivision in the padding arithmetic) with an opaque
    error, so fail here with the actual knob — called before any padding
    math, on the post-clamp value."""
    if r_block < 128 or r_block % 128:
        raise ValueError(
            f"r_block must be a positive multiple of 128, got {r_block}"
        )
    r_sub = r_block // 128
    NR = _round_up(nrows, r_block) // 128
    if not interpret and r_sub % 8 and r_sub != NR:
        raise ValueError(
            f"r_block={r_block} gives {r_sub} row tiles per block over "
            f"{NR} total; the TPU lowering needs r_block % 1024 == 0 or a "
            "single block covering all rows"
        )


@functools.partial(
    jax.jit,
    static_argnames=("operators", "t_block", "r_block", "interpret",
                     "slot_loop", "dispatch", "tree_unroll", "sort_trees",
                     "compute_dtype", "program", "leaf_skip",
                     "scalar_pack", "top_carry", "bucket_ladder"),
)
def eval_trees_pallas(
    trees: TreeBatch,
    X: Array,
    operators: OperatorSet,
    t_block: int = DEFAULT_T_BLOCK,
    r_block: int = DEFAULT_R_BLOCK,
    interpret: bool = False,
    slot_loop: str = "dynamic",
    dispatch: str = "mux",
    tree_unroll: int = 8,
    sort_trees: bool = True,
    compute_dtype: str = "float32",
    program: str = "postfix",
    leaf_skip: "bool | str" = False,
    scalar_pack: bool = False,
    top_carry: bool = False,
    bucket_ladder: Tuple[float, ...] = (),
) -> Tuple[Array, Array]:
    """Evaluate a flat batch of trees over X (nfeat, nrows).

    Returns (y (..., nrows) float32, ok (...,)) with the same semantics as
    interpreter.eval_trees. TPU only (or interpret=True anywhere).

    compute_dtype="bfloat16" stores tree values (X tiles + value scratch)
    in the TPU-native half precision — halved VMEM traffic per slot — while
    every operator computes in f32 with results rounded back at the store
    (the v5e toolchain cannot lower cos/sin/sqrt/round/mod on bf16 vectors,
    so bf16 is a storage dtype, not a compute dtype). f32 output/poison
    accumulation. The bf16 analog of the reference's type-generic eval
    (its Float16/32/64 sweeps, test/test_tree_construction.jl:96-145).

    program="instr" runs the compressed operator-only instruction program
    (see `instruction_schedule`): ~half the steps per tree, leaves fetched
    as operands instead of executed as slots. program="instr_packed" is
    the same program through one packed int32 SMEM word per step and a
    unified operand scratch (see `pack_instr_tables`) — scalar-unit
    relief; requires <=255 opcodes and nfeat+max_len <= ~2048 (raises
    otherwise). `slot_loop` applies to the postfix program only.

    leaf_skip (postfix only) replaces the slot's single branchless mux
    with scalar-predicated branches that skip unused candidate work:
    True = 2-way (leaf | operator; leaves are ~half the slots of a
    postfix program), "class" = 3-way (leaf | unary | binary; the cheap-
    arithmetic binary arm also skips the transcendental candidates) — A/B
    levers for the per-slot overhead question (BASELINE.md roofline
    section; sweep with kernel_tune.py).

    scalar_pack (postfix only) packs the four per-slot scalar tables
    (pcode/feat/lidx/ridx, 6+8+9+9 bits) into one i32 word so each
    (slot, tree) step issues 1 scalar SMEM read + shifts instead of 4
    reads — an attack on the measured fixed per-slot cost. Unlike
    program="instr_packed" (refuted on chip), the dataflow is untouched:
    only the scalar fetch changes. Requires n_codes <= 64, nfeat <= 256,
    max_len <= 512 (raises otherwise).

    Cache/dedup interplay: the intra-batch dedup (cache/dedup.py) hands
    this kernel fixed-shape buffers where duplicate slots hold length-1
    filler programs (ops/interpreter.filler_trees). The length-bounded
    slot loop (design note 3b) runs a filler in one step, and sort_trees
    clusters fillers into the same interleave groups — so the dedup
    telemetry's eval-batch shrinkage is realized as proportional kernel
    time here, without any dynamic shapes. Per-tree results do not depend
    on batch position or neighbors (per-tree scratch, per-tree row
    reductions), which is what lets a deduped batch reproduce the
    uncached batch bit-for-bit.

    top_carry (postfix only) carries each tree's previous slot value in
    a loop register instead of re-reading it from scratch: postfix
    order guarantees an operator's top-of-stack operand IS the previous
    slot's result (encode-time invariant ridx == si-1, asserted by
    operand_schedule's tests), so this removes one dynamic VMEM read +
    one scalar table read per step and takes a scratch write->read
    round-trip off the tree's serial dependence chain — the latency
    chain that tree-interleaving exists to hide. Composable with
    scalar_pack and leaf_skip.

    bucket_ladder (postfix only) is the PR-4 length-bucket ladder ported
    to the kernel: the length-sorted batch is split at host-static
    positional boundaries (models.fitness._bucket_bounds — THE same
    boundary definition the jnp interpreter's bucketed driver uses, so
    both backends share one sorted order) and each bucket runs its own
    pallas_call whose slot axis and tree-block padding are clamped to
    that bucket. Bit-identity with the flat call is structural: per-tree
    results depend only on the tree's own tables/scratch (see the
    cache/dedup note above), and slots beyond a bucket's max length are
    PAD identities that a smaller L simply never executes. () = one
    flat bucket (today's behavior)."""
    if program not in ("postfix", "instr", "instr_packed"):
        raise ValueError(
            "program must be 'postfix', 'instr' or 'instr_packed', "
            f"got {program!r}"
        )
    if leaf_skip not in (False, True, "class"):
        raise ValueError(
            f"leaf_skip must be False, True or 'class', got {leaf_skip!r}"
        )
    if leaf_skip and program != "postfix":
        raise ValueError(
            "leaf_skip applies to the postfix program only (the instr "
            "programs have no leaf slots to skip)"
        )
    if scalar_pack and program != "postfix":
        raise ValueError(
            "scalar_pack applies to the postfix program only "
            "(instr_packed is the instr program's packed layout)"
        )
    if top_carry and program != "postfix":
        raise ValueError(
            "top_carry applies to the postfix program only (the instr "
            "program's operands are not stack-adjacent)"
        )
    if bucket_ladder and program != "postfix":
        raise ValueError(
            "bucket_ladder applies to the postfix program only (the "
            "instr programs have no per-bucket slot loop to truncate)"
        )
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    if program in ("instr", "instr_packed"):
        return _eval_instr(
            flat, X, operators, t_block, r_block, interpret, dispatch,
            tree_unroll, sort_trees, compute_dtype, batch_shape,
            packed=(program == "instr_packed"),
        )
    # Sort by length so (a) tree_unroll groups advance trees of matching
    # length (the group's dynamic slot loop runs to the max of the group)
    # and (b) grid blocks are length-homogeneous. Gather here, inverse
    # gather on the (T,) outputs — O(T·L) int work, dwarfed by the kernel.
    perm = inv_perm = None
    if sort_trees and flat.length.shape[0] > 1:
        perm = jnp.argsort(flat.length)
        inv_perm = jnp.zeros_like(perm).at[perm].set(
            jnp.arange(perm.shape[0], dtype=perm.dtype)
        )
        flat = jax.tree_util.tree_map(lambda x: x[perm], flat)
    # slot axis padded to a multiple of the kernel's 4-slot loop groups —
    # the last group of a length-L tree may touch slots up to
    # round_up(L, 4)-1 (PAD slots, harmless but they must exist)
    L = _round_up(trees.max_len, _SLOT_UNROLL)
    if L != trees.max_len:
        dl = L - trees.max_len
        flat = TreeBatch(
            kind=jnp.pad(flat.kind, ((0, 0), (0, dl))),
            op=jnp.pad(flat.op, ((0, 0), (0, dl))),
            feat=jnp.pad(flat.feat, ((0, 0), (0, dl))),
            cval=jnp.pad(flat.cval, ((0, 0), (0, dl))),
            length=flat.length,
        )
    T = flat.length.shape[0]
    nfeat, nrows = X.shape

    r_block = min(r_block, _round_up(nrows, 128))
    _check_r_block(r_block, nrows, interpret)
    R_pad = _round_up(nrows, r_block)
    NR = R_pad // 128  # row tiles of 128 lanes

    cdt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[compute_dtype]
    # rows folded to (..., NR, 128) tiles — see module docstring point 3
    Xp = jnp.pad(X.astype(cdt), ((0, 0), (0, R_pad - nrows)))
    Xp = Xp.reshape(nfeat, NR, 128)
    nrows_arr = jnp.asarray([nrows], jnp.int32)

    outs = []
    bads = []
    for lo, hi in _ladder_bounds(T, bucket_ladder):
        y_b, bad_b = _postfix_call(
            flat[lo:hi], Xp, None, nrows_arr, operators, L, t_block,
            r_block, interpret, slot_loop, dispatch, tree_unroll, cdt,
            leaf_skip, scalar_pack, top_carry, NR, nfeat,
        )
        outs.append(y_b)
        bads.append(bad_b)
    if not outs:
        y = jnp.zeros((0, nrows), jnp.float32)
        ok = jnp.zeros((0,), bool)
    else:
        y = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
        bad = bads[0] if len(bads) == 1 else jnp.concatenate(bads)
        y = y[:, :nrows]
        ok = (bad == 0) & (flat.length > 0)
    if inv_perm is not None:
        y = y[inv_perm]
        ok = ok[inv_perm]
    return (
        y.reshape(batch_shape + (nrows,)),
        ok.reshape(batch_shape),
    )


@functools.partial(
    jax.jit,
    static_argnames=("operators", "loss_fn", "t_block", "r_block",
                     "interpret", "slot_loop", "dispatch", "tree_unroll",
                     "sort_trees", "presorted", "leaf_skip",
                     "scalar_pack", "top_carry", "bucket_ladder"),
)
def eval_loss_trees_pallas(
    trees: TreeBatch,
    X: Array,
    y: Array,
    operators: OperatorSet,
    loss_fn,
    t_block: int = DEFAULT_T_BLOCK,
    r_block: int = DEFAULT_R_BLOCK,
    interpret: bool = False,
    slot_loop: str = "dynamic",
    dispatch: str = "mux",
    tree_unroll: int = 8,
    sort_trees: bool = True,
    presorted: bool = False,
    leaf_skip: "bool | str" = False,
    scalar_pack: bool = False,
    top_carry: bool = False,
    bucket_ladder: Tuple[float, ...] = (),
) -> Array:
    """Fused per-tree aggregated loss through the Pallas kernel — the
    kernel-side analog of the interpreter's `eval_loss_trees_fused`.

    The loss epilogue runs inside the kernel via the `accum_tile` scalar
    accumulator (`_make_kernel(fused_loss=...)`): each grid cell reduces
    its (r_sub, 128) elementwise-loss tile with `jnp.sum` and folds the
    partial into a per-tree SMEM scalar across the sequential row-tile
    sweep, so the `(B, nrows)` prediction matrix is NEVER materialized
    in HBM. The host side only divides by nrows and applies
    `contain_nonfinite` — bit-identical BY CONSTRUCTION to the host
    composition `contain_nonfinite(aggregate_loss(loss_fn(y_pred, y),
    tile_rows=r_block), ok)`: `aggregate_loss(tile_rows=...)` performs
    the identical pad → per-(r_sub, 128)-tile `jnp.sum` → sequential
    fold → divide sequence on the host graph (see ops/losses.py). The
    untiled `jnp.mean` composition differs from this by reduction order
    only (documented ULP-level difference — docs/eval_pipeline.md
    exactness table).

    Fused-seam restrictions (callers fall back to the unfused
    composition outside them, per the PR 12 determinism rules):
    float32 X/y only, unweighted, non-deterministic reduction order
    (`row_shards > 1` never routes to Pallas), postfix program only.

    loss_fn is a static elementwise callable (y_pred, y_target) -> loss,
    traced INTO the kernel per (tree, row-tile). Padded rows contribute
    exactly 0.0 via a `where` on the row mask (multiplying by the mask
    would turn inf·0 into NaN), matching the host graph's zero-padding.

    presorted=True asserts `trees` is already length-major (the dedup
    path's contract) and skips the sort; `bucket_ladder` as in
    `eval_trees_pallas`. Returns loss with `trees`' batch shape:
    finite per-tree mean loss, or +inf where the tree is empty/PAD or
    produced any nonfinite row (same containment as the interpreter
    path).
    """
    if X.dtype != jnp.float32 or y.dtype != jnp.float32:
        raise ValueError(
            "eval_loss_trees_pallas is float32-only (the fused epilogue "
            f"accumulates f32 loss sums); got X {X.dtype}, y {y.dtype}"
        )
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    inv_perm = None
    if sort_trees and not presorted and flat.length.shape[0] > 1:
        perm = jnp.argsort(flat.length)
        inv_perm = jnp.zeros_like(perm).at[perm].set(
            jnp.arange(perm.shape[0], dtype=perm.dtype)
        )
        flat = jax.tree_util.tree_map(lambda x: x[perm], flat)
    L = _round_up(trees.max_len, _SLOT_UNROLL)
    if L != trees.max_len:
        dl = L - trees.max_len
        flat = TreeBatch(
            kind=jnp.pad(flat.kind, ((0, 0), (0, dl))),
            op=jnp.pad(flat.op, ((0, 0), (0, dl))),
            feat=jnp.pad(flat.feat, ((0, 0), (0, dl))),
            cval=jnp.pad(flat.cval, ((0, 0), (0, dl))),
            length=flat.length,
        )
    T = flat.length.shape[0]
    nfeat, nrows = X.shape

    r_block = min(r_block, _round_up(nrows, 128))
    _check_r_block(r_block, nrows, interpret)
    R_pad = _round_up(nrows, r_block)
    NR = R_pad // 128

    Xp = jnp.pad(X, ((0, 0), (0, R_pad - nrows)))
    Xp = Xp.reshape(nfeat, NR, 128)
    # target rows tiled exactly like X rows; padded targets are dead
    # lanes (the kernel's row mask zeroes their loss contribution)
    yp = jnp.pad(y, (0, R_pad - nrows)).reshape(NR, 128)
    nrows_arr = jnp.asarray([nrows], jnp.int32)

    nums = []
    bads = []
    for lo, hi in _ladder_bounds(T, bucket_ladder):
        num_b, bad_b = _postfix_call(
            flat[lo:hi], Xp, yp, nrows_arr, operators, L, t_block,
            r_block, interpret, slot_loop, dispatch, tree_unroll,
            jnp.float32, leaf_skip, scalar_pack, top_carry, NR, nfeat,
            fused_loss=loss_fn,
        )
        nums.append(num_b)
        bads.append(bad_b)
    if not nums:
        loss = jnp.zeros((0,), jnp.float32)
    else:
        num = nums[0] if len(nums) == 1 else jnp.concatenate(nums)
        bad = bads[0] if len(bads) == 1 else jnp.concatenate(bads)
        ok = (bad == 0) & (flat.length > 0)
        loss = num / jnp.asarray(nrows, jnp.float32)
        loss = contain_nonfinite(loss, ok)
    if inv_perm is not None:
        loss = loss[inv_perm]
    return loss.reshape(batch_shape)


def prep_instr_tables(flat, operators, sort_trees):
    """Shared host-side prep of the instruction-program tables (used by
    the eval kernels here and the gradient kernel in pallas_grad.py, so
    their table pipelines stay identical by construction): compile the
    schedule, sort trees by instruction count — the analog of the postfix
    path's length sort (interleave groups + grid blocks stay
    work-homogeneous) — and pad the step axis to whole _SLOT_UNROLL
    groups. Returns (tables (T, L), n_instr (T,), flat trees in sorted
    order, inv_perm or None, L)."""
    tables, n_instr = instruction_schedule(flat, operators)
    inv_perm = None
    if sort_trees and flat.length.shape[0] > 1:
        perm = jnp.argsort(n_instr)
        inv_perm = jnp.zeros_like(perm).at[perm].set(
            jnp.arange(perm.shape[0], dtype=perm.dtype)
        )
        tables = {k: v[perm] for k, v in sorted(tables.items())}
        n_instr = n_instr[perm]
        flat = jax.tree_util.tree_map(lambda x: x[perm], flat)

    L0 = tables["icode"].shape[1]
    L = _round_up(L0, _SLOT_UNROLL)
    if L != L0:
        tables = {
            k: jnp.pad(v, ((0, 0), (0, L - L0)),
                       constant_values=_SRC_CONST if k.endswith("src") else 0)
            for k, v in sorted(tables.items())
        }
    return tables, n_instr, flat, inv_perm, L


def _eval_instr(flat, X, operators, t_block, r_block, interpret, dispatch,
                tree_unroll, sort_trees, compute_dtype, batch_shape,
                packed=False):
    """instr-program body of eval_trees_pallas (already flattened trees).

    packed=True runs the packed-word kernel (pack_instr_tables +
    _make_instr_kernel(packed=True)): 3 SMEM reads per step instead of 7
    and a unified operand scratch — the scalar-unit-relief variant."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if packed:
        # the packed word has 8-bit opcodes and 11-bit operand indices;
        # an explicit program='instr_packed' request that doesn't fit must
        # fail loudly (a silent fallback would mislabel benchmark and
        # roofline results) — callers wanting resilience use 'instr'
        n_codes = 2 + operators.n_unary + operators.n_binary
        if n_codes > 255 or (
            X.shape[0] + flat.kind.shape[-1] + _SLOT_UNROLL > 2048
        ):
            raise ValueError(
                "program='instr_packed' needs <=255 opcodes and "
                "nfeat + max_len <= ~2048 (got "
                f"{n_codes} opcodes, nfeat={X.shape[0]}, "
                f"max_len={flat.kind.shape[-1]}); use program='instr'"
            )

    tables, n_instr, flat, inv_perm, L = prep_instr_tables(
        flat, operators, sort_trees
    )
    length = flat.length
    T = tables["icode"].shape[0]
    nfeat, nrows = X.shape

    t_block = min(t_block, _round_up(max(T, 8), tree_unroll))
    r_block = min(r_block, _round_up(nrows, 128))
    _check_r_block(r_block, nrows, interpret)
    r_sub = r_block // 128
    T_pad = _round_up(T, t_block)
    R_pad = _round_up(nrows, r_block)
    NR = R_pad // 128

    def padT(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T), (0, 0)),
                       constant_values=fill).T

    cdt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[compute_dtype]
    tbl = {
        k: padT(v.astype(jnp.float32) if k.endswith("cval") else v,
                _SRC_CONST if k.endswith("src") else 0)
        for k, v in sorted(tables.items())
    }
    ninstr_p = jnp.pad(n_instr, (0, T_pad - T))[None, :]
    Xp = jnp.pad(X.astype(cdt), ((0, 0), (0, R_pad - nrows)))
    Xp = Xp.reshape(nfeat, NR, 128)
    nrows_arr = jnp.asarray([nrows], jnp.int32)

    grid = (T_pad // t_block, NR // r_sub)
    smem_spec = lambda shape, imap: pl.BlockSpec(
        shape, imap, memory_space=pltpu.SMEM
    )
    tree_tbl = lambda: smem_spec((L, t_block), lambda i, j: (0, i))
    common_out = dict(
        out_specs=[
            pl.BlockSpec((t_block, r_sub, 128), lambda i, j: (i, j, 0)),
            # single row-tile-accumulated poison row — see the postfix
            # path's out_specs comment
            smem_spec((1, t_block), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, NR, 128), jnp.float32),
            jax.ShapeDtypeStruct((1, T_pad), jnp.float32),
        ],
        interpret=interpret,
    )
    if packed:
        # pack is purely elementwise, so it applies directly to the
        # already-transposed (L, T_pad) tables
        word = pack_instr_tables(tbl, nfeat)
        kernel = _make_instr_kernel(
            operators, t_block, r_block, L, dispatch, tree_unroll,
            nfeat, cdt, packed=True,
        )
        y, bad = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # nrows scalar
                tree_tbl(),  # packed word
                tree_tbl(),  # lcval
                tree_tbl(),  # rcval
                smem_spec((1, t_block), lambda i, j: (0, i)),  # n_instr
                pl.BlockSpec((nfeat, r_sub, 128), lambda i, j: (0, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((nfeat + L, r_sub, 128), cdt)
                for _ in range(tree_unroll)
            ],
            **common_out,
        )(nrows_arr, word, tbl["lcval"], tbl["rcval"], ninstr_p, Xp)
    else:
        kernel = _make_instr_kernel(operators, t_block, r_block, L,
                                    dispatch, tree_unroll, nfeat, cdt)
        y, bad = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # nrows scalar
                tree_tbl(),  # icode
                tree_tbl(),  # lsrc
                tree_tbl(),  # lidx
                tree_tbl(),  # lcval
                tree_tbl(),  # rsrc
                tree_tbl(),  # ridx
                tree_tbl(),  # rcval
                smem_spec((1, t_block), lambda i, j: (0, i)),  # n_instr
                pl.BlockSpec((nfeat, r_sub, 128), lambda i, j: (0, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((L, r_sub, 128), cdt)
                for _ in range(tree_unroll)
            ],
            **common_out,
        )(nrows_arr, tbl["icode"], tbl["lsrc"], tbl["lidx"], tbl["lcval"],
          tbl["rsrc"], tbl["ridx"], tbl["rcval"], ninstr_p, Xp)

    y = y.reshape(T_pad, R_pad)[:T, :nrows]
    ok = (bad[0, :T] == 0) & (length > 0)
    if inv_perm is not None:
        y = y[inv_perm]
        ok = ok[inv_perm]
    return (
        y.reshape(batch_shape + (nrows,)),
        ok.reshape(batch_shape),
    )


def pallas_available() -> bool:
    """Single source of truth for whether the TPU Pallas kernel can run
    (used by models.fitness.dispatch_eval's 'auto' routing).

    Honors an active `jax.default_device(...)` context: computations traced
    under it run on that device's platform, not the process default — e.g.
    a CPU-anchor benchmark on a TPU host must NOT route to the TPU kernel."""
    try:
        dd = jax.config.jax_default_device
        if dd is not None:
            return getattr(dd, "platform", None) in ("tpu", "axon")
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
