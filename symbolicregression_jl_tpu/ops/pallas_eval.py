"""Pallas TPU kernel: batched postfix-tree interpreter with scalar dispatch.

This is the hot kernel of the framework (SURVEY.md §7 decision 2) — the
TPU-native replacement for DynamicExpressions' fused eval loops. Unlike the
portable jnp path (ops/interpreter.py), which must compute EVERY operator on
every node and select (vmap lockstep), this kernel reads each node's opcode
from SMEM and executes exactly ONE operator per node via `lax.switch` on a
scalar — the same work per node as the reference's native CPU loop, but on
8x128 VPU lanes with the dataset resident in VMEM.

Layout per grid cell (i, j):
  trees block i : opcode/operand tables in SMEM (int32/f32, tiny). Tables
                  are stored transposed, (L, t_block), because SMEM pads
                  each major row to 1 KiB: with trees on the minor axis a
                  (24, 256) table costs 24 KiB instead of the 256 KiB of
                  its (256, 24) transpose (which OOMs the 1 MiB SMEM).
  rows block j  : X rows in VMEM,
  stack         : (depth, R_BLK) f32 VMEM scratch, reused across the block's
                  trees; per-row NaN/Inf poison is accumulated elementwise
                  and reduced to a per-tree badness count.

Short trees cost only `length` steps (dynamic fori_loop) — no padded work,
unlike the jnp path.

Opcodes are pre-fused into a single program code:
  0 = PAD, 1 = CONST, 2 = VAR, 3..3+U-1 = unary ops, 3+U.. = binary ops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.trees import BIN, CONST, PAD, UNA, VAR, TreeBatch
from .operators import OperatorSet

Array = jax.Array

DEFAULT_T_BLOCK = 256
DEFAULT_R_BLOCK = 1024


def fuse_opcodes(trees: TreeBatch, operators: OperatorSet) -> Array:
    """kind/op -> single program opcode (same shape as trees.kind)."""
    U = operators.n_unary
    return jnp.where(
        trees.kind == PAD,
        0,
        jnp.where(
            trees.kind == CONST,
            1,
            jnp.where(
                trees.kind == VAR,
                2,
                jnp.where(trees.kind == UNA, 3 + trees.op, 3 + U + trees.op),
            ),
        ),
    ).astype(jnp.int32)


def _make_kernel(operators: OperatorSet, t_block: int, r_block: int,
                 depth: int, max_len: int):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    unary_fns = operators.unary_fns
    binary_fns = operators.binary_fns
    U = len(unary_fns)

    def kernel(nrows_ref, pcode_ref, feat_ref, length_ref, cval_ref,  # SMEM
               X_ref, out_ref, bad_ref,  # VMEM / SMEM out
               stack_ref):  # scratch VMEM (depth, r_block)
        # SMEM tables are transposed: [slot, tree] (see module docstring).
        # row-validity mask: padded tail rows must not poison the tree
        col = jax.lax.broadcasted_iota(jnp.int32, (1, r_block), 1)
        row_valid = (pl.program_id(1) * r_block + col) < nrows_ref[0]
        valid_f = jnp.where(row_valid, 1.0, 0.0)

        def tree_body(ti, _):
            n = length_ref[0, ti]

            def slot_body(si, carry):
                sp, bad = carry  # sp: int32; bad: (1, r_block) f32
                code = pcode_ref[si, ti]

                a_idx = jnp.maximum(sp - 1, 0)
                b_idx = jnp.maximum(sp - 2, 0)

                def br_pad():
                    return stack_ref[pl.ds(a_idx, 1), :]

                def br_const():
                    return jnp.full(
                        (1, r_block), cval_ref[si, ti], dtype=jnp.float32
                    )

                def br_var():
                    f = feat_ref[si, ti]
                    return X_ref[pl.ds(f, 1), :]

                def mk_unary(fn):
                    def br():
                        a = stack_ref[pl.ds(a_idx, 1), :]
                        return fn(a)

                    return br

                def mk_binary(fn):
                    def br():
                        a = stack_ref[pl.ds(a_idx, 1), :]  # right operand
                        b = stack_ref[pl.ds(b_idx, 1), :]  # left operand
                        return fn(b, a)

                    return br

                branches = (
                    [br_pad, br_const, br_var]
                    + [mk_unary(fn) for fn in unary_fns]
                    + [mk_binary(fn) for fn in binary_fns]
                )
                v = jax.lax.switch(code, branches)

                is_leaf = (code == 1) | (code == 2)
                is_una = (code >= 3) & (code < 3 + U)
                arity = jnp.where(is_leaf, 0, jnp.where(is_una, 1, 2))
                new_sp = jnp.where(code == 0, sp, sp - arity + 1)
                w = jnp.maximum(new_sp - 1, 0)
                stack_ref[pl.ds(w, 1), :] = v
                bad = jnp.maximum(
                    bad, jnp.where(jnp.isfinite(v), 0.0, valid_f)
                )
                return new_sp, bad

            bad0 = jnp.zeros((1, r_block), jnp.float32)
            sp, bad = jax.lax.fori_loop(
                0, n, slot_body, (jnp.int32(0), bad0)
            )
            out_ref[pl.ds(ti, 1), :] = stack_ref[0:1, :]
            bad_ref[0, ti] = jnp.sum(bad)
            return 0

        jax.lax.fori_loop(0, t_block, tree_body, 0)

    return kernel, pl, pltpu


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(
    jax.jit,
    static_argnames=("operators", "t_block", "r_block", "interpret"),
)
def eval_trees_pallas(
    trees: TreeBatch,
    X: Array,
    operators: OperatorSet,
    t_block: int = DEFAULT_T_BLOCK,
    r_block: int = DEFAULT_R_BLOCK,
    interpret: bool = False,
) -> Tuple[Array, Array]:
    """Evaluate a flat batch of trees over X (nfeat, nrows).

    Returns (y (..., nrows), ok (...,)) with the same semantics as
    interpreter.eval_trees. TPU only (or interpret=True anywhere)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch_shape = trees.length.shape
    L = trees.max_len
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    T = flat.length.shape[0]
    nfeat, nrows = X.shape

    t_block = min(t_block, max(T, 8))
    r_block = min(r_block, _round_up(nrows, 128))
    T_pad = _round_up(T, t_block)
    R_pad = _round_up(nrows, r_block)

    # tables transposed to (L, T_pad): SMEM pads each major row to 1 KiB,
    # so the tree index must live on the minor axis (see module docstring)
    pcode = fuse_opcodes(flat, operators)
    pcode = jnp.pad(pcode, ((0, T_pad - T), (0, 0))).T
    feat = jnp.pad(flat.feat, ((0, T_pad - T), (0, 0))).T
    length = jnp.pad(flat.length, (0, T_pad - T))[None, :]
    cval = jnp.pad(
        flat.cval.astype(jnp.float32), ((0, T_pad - T), (0, 0))
    ).T
    Xp = jnp.pad(X.astype(jnp.float32), ((0, 0), (0, R_pad - nrows)))
    nrows_arr = jnp.asarray([nrows], jnp.int32)

    depth = L // 2 + 2
    kernel, _, _ = _make_kernel(operators, t_block, r_block, depth, L)

    grid = (T_pad // t_block, R_pad // r_block)
    y, bad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # nrows scalar
            pl.BlockSpec((L, t_block), lambda i, j: (0, i),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((L, t_block), lambda i, j: (0, i),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, t_block), lambda i, j: (0, i),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((L, t_block), lambda i, j: (0, i),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((nfeat, r_block), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((t_block, r_block), lambda i, j: (i, j)),
            pl.BlockSpec((1, t_block), lambda i, j: (j, i),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, R_pad), jnp.float32),
            jax.ShapeDtypeStruct((grid[1], T_pad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((depth, r_block), jnp.float32)],
        interpret=interpret,
    )(nrows_arr, pcode, feat, length, cval, Xp)

    y = y[:T, :nrows]
    ok = (jnp.sum(bad[:, :T], axis=0) == 0) & (flat.length > 0)
    return (
        y.reshape(batch_shape + (nrows,)),
        ok.reshape(batch_shape),
    )


def pallas_available() -> bool:
    """Single source of truth for whether the TPU Pallas kernel can run
    (used by models.fitness.dispatch_eval's 'auto' routing).

    Honors an active `jax.default_device(...)` context: computations traced
    under it run on that device's platform, not the process default — e.g.
    a CPU-anchor benchmark on a TPU host must NOT route to the TPU kernel."""
    try:
        dd = jax.config.jax_default_device
        if dd is not None:
            return getattr(dd, "platform", None) in ("tpu", "axon")
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
