"""srtune — persistent per-device-kind kernel autotuner.

The Pallas kernel's free parameters (t_block, r_block, dispatch,
tree_unroll, bucket ladder) and the `auto` router's work-volume
crossover were compile-time constants tuned by hand from kernel_tune.py
sweeps. This package makes them data: `cache.py` holds a
schema-versioned on-disk cache keyed by (device_kind, opset
fingerprint, maxsize, dtype), `tuner.py` ranks candidate configurations
with the srcost analytic model (analysis/cost.py) BEFORE measuring so a
sweep only times the top few, and `models/fitness.py` consults the
cache from the `auto` router — with every static default preserved
bit-for-bit when no cache exists. See docs/kernel_tuning.md.
"""

from .cache import (
    SCHEMA_VERSION,
    current_device_kind,
    default_cache_path,
    entry_key,
    load_tune_cache,
    lookup_kernel_config,
    opset_fingerprint,
    reset_tune_cache_memo,
    save_tune_cache,
    tuned_min_work,
    update_tune_cache,
    validate_tune_cache,
)
from .tuner import candidate_grid, model_ranked_sweep, sweep_to_cache

__all__ = [
    "SCHEMA_VERSION",
    "candidate_grid",
    "current_device_kind",
    "default_cache_path",
    "entry_key",
    "load_tune_cache",
    "lookup_kernel_config",
    "model_ranked_sweep",
    "opset_fingerprint",
    "reset_tune_cache_memo",
    "save_tune_cache",
    "sweep_to_cache",
    "tuned_min_work",
    "update_tune_cache",
    "validate_tune_cache",
]
