"""Model-ranked kernel sweep: srcost orders candidates, hardware breaks
ties.

A blind grid over (t_block, r_block, dispatch, tree_unroll, ladder) is
~70 Mosaic compiles per sweep — minutes of tunnel time each on a v5e.
The srcost analytic model (analysis/cost.py::pallas_config_cost) prices
every candidate's flops/bytes/padded-waste in microseconds on the host,
so the measured sweep only runs the top few (`top_k`). The model's
ABSOLUTE numbers drift from Mosaic reality; its ORDERING is what the
ranking uses, and measurement always has the final word within the
top-k set.

`measure_fn` is injected (config dict -> trees-rows/s, or raises) so
benchmark/kernel_tune.py plugs in its bench-methodology timer while
tests plug in deterministic fakes — the sweep logic itself never
touches a device.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .cache import (
    SCHEMA_VERSION,
    current_device_kind,
    entry_key,
    opset_fingerprint,
    update_tune_cache,
)

#: the default ladder candidate — PR 4's measured interpreter winner
#: (BASELINE.md bucket sweep); the kernel sweep re-judges it per device.
DEFAULT_LADDER = (0.25, 0.5, 0.75, 1.0)


def candidate_grid(include_bucketed: bool = True) -> List[dict]:
    """The autotuner's candidate space over the kernel's tile/dispatch
    parameters. Deliberately coarse: srcost ranks it, so breadth is
    cheap; only the measured top-k costs compile time."""
    grid: List[dict] = []
    ladders = ([], list(DEFAULT_LADDER)) if include_bucketed else ([],)
    for t_block in (128, 256, 512):
        for r_block in (512, 1024):
            for dispatch in ("mux", "chain"):
                for tree_unroll in (4, 8, 16):
                    for ladder in ladders:
                        grid.append({
                            "t_block": t_block,
                            "r_block": r_block,
                            "dispatch": dispatch,
                            "tree_unroll": tree_unroll,
                            "ladder": list(ladder),
                        })
    return grid


def model_ranked_sweep(
    operators,
    lengths: Sequence[int],
    nrows: int,
    nfeat: int,
    measure_fn: Callable[[dict], float],
    candidates: Optional[Sequence[dict]] = None,
    top_k: int = 5,
) -> dict:
    """Rank `candidates` with the srcost model, measure the top_k with
    `measure_fn`, and return the sweep record:

        {"ranked": [(config, modeled_cost), ...],   # best-modeled first
         "measured": [{"config", "trees_rows_per_s"| "error"}, ...],
         "best": {"config", "trees_rows_per_s"} | None}

    A candidate whose measurement raises is recorded with its error and
    skipped — one Mosaic lowering failure must not kill the sweep."""
    from ..analysis.cost import rank_kernel_configs

    if candidates is None:
        candidates = candidate_grid()
    ranked = rank_kernel_configs(
        list(candidates), list(lengths), nrows, nfeat, operators
    )
    measured: List[dict] = []
    best: Optional[dict] = None
    for config, _cost in ranked[:max(1, int(top_k))]:
        try:
            rate = float(measure_fn(config))
        except Exception as e:  # noqa: BLE001 - sweep must survive
            measured.append({
                "config": config,
                "error": f"{type(e).__name__}: {e}",
            })
            continue
        rec = {"config": config, "trees_rows_per_s": rate}
        measured.append(rec)
        if best is None or rate > best["trees_rows_per_s"]:
            best = rec
    return {
        "ranked": [(c, s) for c, s in ranked],
        "measured": measured,
        "best": best,
    }


def sweep_to_cache(
    sweep: dict,
    operators,
    maxsize: int,
    dtype: str = "float32",
    interpret: bool = False,
    device_kind: Optional[str] = None,
    min_work: Optional[int] = None,
    cache: Optional[dict] = None,
    source: str = "kernel_tune",
) -> Optional[dict]:
    """Fold a model_ranked_sweep result into a (new or existing) cache
    dict under THIS device kind, or None when the sweep measured
    nothing. interpret=True marks the CPU fallback sweep — update_
    tune_cache refuses to file such entries under a TPU device kind."""
    best = sweep.get("best")
    if not best:
        return cache
    return update_tune_cache(
        cache,
        device_kind or current_device_kind(),
        interpret,
        entry_key(opset_fingerprint(operators), maxsize, dtype),
        best["config"],
        trees_rows_per_s=best["trees_rows_per_s"],
        min_work=min_work,
        source=source,
    )


__all__ = [
    "DEFAULT_LADDER",
    "SCHEMA_VERSION",
    "candidate_grid",
    "model_ranked_sweep",
    "sweep_to_cache",
]
