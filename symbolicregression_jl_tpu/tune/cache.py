"""Persistent kernel-tune cache: schema, load/save, and router lookups.

One JSON file maps device kinds to tuned kernel configurations and the
`auto` router's work-volume crossover:

    {
      "schema_version": 1,
      "device_kinds": {
        "TPU v5e": {
          "interpret": false,
          "min_work": 524288,
          "entries": {
            "bin:+,-,*,/|una:cos,exp|L24|float32": {
              "config": {"t_block": 256, "r_block": 1024,
                         "dispatch": "mux", "tree_unroll": 8,
                         "ladder": [0.25, 0.5, 0.75, 1.0]},
              "trees_rows_per_s": 1.01e9,
              "source": "kernel_tune"
            }
          }
        }
      }
    }

Contracts (enforced by `validate_tune_cache`, gated by scripts/lint.py
on any checked-in cache, and unit-tested in tests/test_ah_tune.py):

- **Robust load.** A missing, corrupt, truncated, or wrong-schema file
  NEVER crashes the router: `load_tune_cache` warns once and returns
  None, and every lookup then falls back to the static defaults — so
  routing without a cache is byte-identical to routing before this
  module existed.
- **Per-device-kind isolation.** Lookups key on the CURRENT process's
  device kind (`current_device_kind`, which honors an active
  `jax.default_device(...)` context exactly like
  `ops.pallas_eval.pallas_available`). A cache written on one device
  kind never leaks configs to another.
- **Interpret-mode quarantine.** Entries measured under Pallas
  interpret mode (the CPU fallback sweep) are stored under the CPU
  device kind with ``interpret: true`` and MUST NOT appear under a TPU
  device kind — interpret timings say nothing about Mosaic schedules,
  and the validator rejects any cache that merges them.
- **Sorted-key writer.** `save_tune_cache` goes through the shared
  `analysis.report.write_baseline_json` writer, so cache refreshes
  diff like every other checked-in baseline.

`SRTPU_TUNE_CACHE` overrides the on-disk location (tests point it at
tmp paths; fleets can share one tuned cache over NFS).
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

_ENV_VAR = "SRTPU_TUNE_CACHE"

_DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tune_cache.json"
)

#: ladder fractions must ascend and end at 1.0 (the Options validation
#: rule, re-checked here because the cache bypasses Options).
_DISPATCHES = ("mux", "chain")
_TREE_UNROLLS = (1, 2, 4, 8, 16)

_CONFIG_KEYS = ("t_block", "r_block", "dispatch", "tree_unroll",
                "ladder")

# (path, mtime) -> parsed cache; reset via reset_tune_cache_memo()
_MEMO: Dict[Tuple[str, float], Optional[dict]] = {}


def default_cache_path() -> str:
    """Resolved cache location: $SRTPU_TUNE_CACHE or the in-package
    tune_cache.json (the checked-in location the lint gate watches)."""
    return os.environ.get(_ENV_VAR) or _DEFAULT_PATH


def current_device_kind() -> str:
    """The device kind lookups key on, honoring an active
    `jax.default_device(...)` context like `pallas_available` does (a
    CPU-anchor bench on a TPU host must consult CPU entries, if any,
    not the chip's)."""
    import jax

    try:
        dd = jax.config.jax_default_device
        if dd is not None:
            return str(getattr(dd, "device_kind", dd.platform))
        return str(jax.devices()[0].device_kind)
    except Exception:  # pragma: no cover - no devices at all
        return "cpu"


def opset_fingerprint(operators) -> str:
    """Order-sensitive operator-set key: opcode assignment follows
    tuple order (ops/pallas_eval.fuse_opcodes), so ("+", "-") and
    ("-", "+") are genuinely different kernels."""
    return ("bin:" + ",".join(operators.binary_names)
            + "|una:" + ",".join(operators.unary_names))


def entry_key(opset_fp: str, maxsize: int, dtype: str) -> str:
    """(opset fingerprint, maxsize, dtype) -> entry key. maxsize is the
    tree buffer's slot capacity (Options.maxsize): it fixes the kernel's
    L axis, which the tile geometry depends on."""
    return f"{opset_fp}|L{int(maxsize)}|{dtype}"


def load_tune_cache(path: Optional[str] = None) -> Optional[dict]:
    """Parse the cache file, or None when absent/unusable.

    Never raises on bad content: corrupt JSON, a truncated write, a
    non-dict payload, or a schema-version mismatch each warn once and
    return None (the router then uses the static defaults). Memoized on
    (path, mtime) so per-dispatch lookups cost a stat, not a parse."""
    path = path or default_cache_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    memo_key = (path, mtime)
    if memo_key in _MEMO:
        return _MEMO[memo_key]
    cache: Optional[dict] = None
    try:
        with open(path) as f:
            parsed = json.load(f)
        if not isinstance(parsed, dict):
            warnings.warn(
                f"kernel-tune cache {path} is not a JSON object — "
                "ignoring it (static kernel defaults stay in effect)",
                stacklevel=2,
            )
        elif parsed.get("schema_version") != SCHEMA_VERSION:
            warnings.warn(
                f"kernel-tune cache {path} has schema_version "
                f"{parsed.get('schema_version')!r}, this build reads "
                f"{SCHEMA_VERSION} — ignoring it (static kernel "
                "defaults stay in effect; re-run kernel_tune.py "
                "--autotune to regenerate)",
                stacklevel=2,
            )
        else:
            cache = parsed
    except (json.JSONDecodeError, OSError, UnicodeDecodeError) as e:
        warnings.warn(
            f"kernel-tune cache {path} is unreadable ({e.__class__.__name__}: "
            f"{e}) — ignoring it (static kernel defaults stay in effect)",
            stacklevel=2,
        )
    _MEMO.clear()  # keep exactly the newest (path, mtime) resident
    _MEMO[memo_key] = cache
    return cache


def reset_tune_cache_memo() -> None:
    """Drop the (path, mtime) memo — tests that rewrite the cache file
    within one mtime granule call this between lookups."""
    _MEMO.clear()


def save_tune_cache(cache: dict, path: Optional[str] = None) -> str:
    """Write through the shared sorted-key baseline writer; refuses an
    invalid payload (the cache is a checked-in artifact — never let a
    writer produce a file the lint gate would then fail)."""
    from ..analysis.report import write_baseline_json

    problems = validate_tune_cache(cache)
    if problems:
        raise ValueError(
            "refusing to write an invalid kernel-tune cache:\n  "
            + "\n  ".join(problems)
        )
    path = path or default_cache_path()
    write_baseline_json(path, cache)
    reset_tune_cache_memo()
    return path


def update_tune_cache(
    cache: Optional[dict],
    device_kind: str,
    interpret: bool,
    key: str,
    config: dict,
    trees_rows_per_s: Optional[float] = None,
    min_work: Optional[int] = None,
    source: str = "kernel_tune",
) -> dict:
    """Merge one tuned entry (and optionally a min_work crossover) into
    a cache dict, creating structure as needed. Refuses to mark a TPU
    device kind's entries as interpret-mode — the CPU fallback sweep
    must never masquerade as on-chip data."""
    if interpret and "tpu" in device_kind.lower():
        raise ValueError(
            f"interpret-mode timings cannot be merged into TPU device "
            f"kind {device_kind!r} (they measure the interpreter, not "
            "Mosaic schedules)"
        )
    cache = dict(cache) if cache else {"schema_version": SCHEMA_VERSION,
                                       "device_kinds": {}}
    kinds = dict(cache.get("device_kinds", {}))
    kind = dict(kinds.get(device_kind, {"entries": {}}))
    if bool(kind.get("interpret", interpret)) != interpret:
        raise ValueError(
            f"device kind {device_kind!r} already holds "
            f"interpret={kind.get('interpret')} entries — refusing to "
            "mix measurement modes under one device kind"
        )
    kind["interpret"] = bool(interpret)
    if min_work is not None:
        kind["min_work"] = int(min_work)
    entries = dict(kind.get("entries", {}))
    entry = {"config": _normalize_config(config), "source": source}
    if trees_rows_per_s is not None:
        entry["trees_rows_per_s"] = float(trees_rows_per_s)
    entries[key] = entry
    kind["entries"] = entries
    kinds[device_kind] = kind
    cache["device_kinds"] = kinds
    cache["schema_version"] = SCHEMA_VERSION
    return cache


def _normalize_config(config: dict) -> dict:
    out = {k: config[k] for k in _CONFIG_KEYS if k in config}
    if "ladder" in out:
        out["ladder"] = [float(x) for x in out["ladder"]]
    return out


def lookup_kernel_config(
    operators, maxsize: int, dtype: str,
    device_kind: Optional[str] = None,
    path: Optional[str] = None,
) -> Optional[dict]:
    """The tuned kernel configuration for (this device kind, opset,
    maxsize, dtype), or None — callers keep their static defaults on
    None, so an absent/foreign-device cache changes nothing."""
    cache = load_tune_cache(path)
    if cache is None:
        return None
    device_kind = device_kind or current_device_kind()
    kind = cache.get("device_kinds", {}).get(device_kind)
    if not isinstance(kind, dict):
        return None
    entry = kind.get("entries", {}).get(
        entry_key(opset_fingerprint(operators), maxsize, dtype)
    )
    if not isinstance(entry, dict):
        return None
    config = entry.get("config")
    return dict(config) if isinstance(config, dict) else None


def tuned_min_work(
    device_kind: Optional[str] = None, path: Optional[str] = None
) -> Optional[int]:
    """The tuned `auto`-router crossover (trees x rows) for this device
    kind, or None — the router keeps the static _PALLAS_MIN_WORK on
    None, which is what makes no-cache routing byte-identical to the
    pre-autotuner behavior."""
    cache = load_tune_cache(path)
    if cache is None:
        return None
    device_kind = device_kind or current_device_kind()
    kind = cache.get("device_kinds", {}).get(device_kind)
    if not isinstance(kind, dict):
        return None
    mw = kind.get("min_work")
    return int(mw) if isinstance(mw, (int, float)) and mw > 0 else None


def validate_tune_cache(cache) -> List[str]:
    """Schema check for the lint gate (scripts/lint.py) and the writer.
    Returns a list of problems; empty means valid."""
    problems: List[str] = []
    if not isinstance(cache, dict):
        return ["cache payload is not a JSON object"]
    if cache.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version must be {SCHEMA_VERSION}, got "
            f"{cache.get('schema_version')!r}"
        )
    kinds = cache.get("device_kinds")
    if not isinstance(kinds, dict):
        return problems + ["device_kinds must be an object"]
    for kind_name, kind in kinds.items():
        tag = f"device_kinds[{kind_name!r}]"
        if not isinstance(kind, dict):
            problems.append(f"{tag} must be an object")
            continue
        interpret = kind.get("interpret")
        if not isinstance(interpret, bool):
            problems.append(f"{tag}.interpret must be a boolean")
        elif interpret and "tpu" in kind_name.lower():
            problems.append(
                f"{tag}: interpret-mode timings under a TPU device "
                "kind — the CPU fallback sweep must never be merged "
                "into an on-chip entry"
            )
        mw = kind.get("min_work")
        if mw is not None and (
            not isinstance(mw, int) or isinstance(mw, bool) or mw <= 0
        ):
            problems.append(f"{tag}.min_work must be a positive integer")
        entries = kind.get("entries", {})
        if not isinstance(entries, dict):
            problems.append(f"{tag}.entries must be an object")
            continue
        for key, entry in entries.items():
            etag = f"{tag}.entries[{key!r}]"
            if not isinstance(entry, dict):
                problems.append(f"{etag} must be an object")
                continue
            problems += _validate_config(
                entry.get("config"), f"{etag}.config"
            )
    return problems


def _validate_config(config, tag: str) -> List[str]:
    problems: List[str] = []
    if not isinstance(config, dict):
        return [f"{tag} must be an object"]
    tb = config.get("t_block")
    ru = config.get("tree_unroll")
    if not isinstance(tb, int) or isinstance(tb, bool) or tb <= 0:
        problems.append(f"{tag}.t_block must be a positive integer")
    if ru not in _TREE_UNROLLS:
        problems.append(
            f"{tag}.tree_unroll must be one of {_TREE_UNROLLS}"
        )
    elif isinstance(tb, int) and not isinstance(tb, bool) and tb > 0 \
            and tb % ru:
        problems.append(
            f"{tag}.t_block ({tb}) must be a multiple of tree_unroll "
            f"({ru}) — the kernel's interleave-group invariant"
        )
    rb = config.get("r_block")
    if (not isinstance(rb, int) or isinstance(rb, bool) or rb <= 0
            or rb % 128):
        problems.append(
            f"{tag}.r_block must be a positive multiple of 128 "
            "(rows live on (r_sub, 128) vreg tiles)"
        )
    if config.get("dispatch") not in _DISPATCHES:
        problems.append(f"{tag}.dispatch must be one of {_DISPATCHES}")
    ladder = config.get("ladder", [])
    if not isinstance(ladder, (list, tuple)):
        problems.append(f"{tag}.ladder must be a list")
    elif ladder:
        fracs = list(ladder)
        if not all(isinstance(x, (int, float)) and not isinstance(x, bool)
                   and 0.0 < float(x) <= 1.0 for x in fracs):
            problems.append(
                f"{tag}.ladder fractions must be in (0, 1]"
            )
        elif sorted(fracs) != fracs or float(fracs[-1]) != 1.0:
            problems.append(
                f"{tag}.ladder must ascend and end at 1.0 (the "
                "Options.eval_bucket_ladder rule)"
            )
    for k in config:
        if k not in _CONFIG_KEYS:
            problems.append(f"{tag} has unknown key {k!r}")
    return problems
