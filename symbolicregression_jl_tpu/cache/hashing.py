"""64-bit structural content hashing for TreeBatch — device and host twins.

`models.trees.tree_hash` (blake2b, host-only) gives the recorder its
lineage refs; the memo bank needs the SAME digest computable both inside
a jitted graph (to key the intra-batch dedup and the device memo lookup)
and on the host (to key the LRU that absorbs scored populations). blake2b
cannot run on device, so this module defines a two-lane 32-bit FNV-1a
fold over the canonicalized program and implements it twice:

* `tree_hash_device` — jittable jnp/uint32 (vmappable over batch dims);
* `tree_hash_host`   — vectorized numpy, bit-for-bit identical digests
  (uint64 accumulators masked to 32 bits so numpy's overflow behavior
  never enters the picture).

Canonicalization matches `tree_hash` (test/test_hash.jl semantics): only
the `length` live slots plus length itself feed the digest; dead fields
(op on leaves, feat on non-VAR, cval on non-CONST) are zeroed, so two
encodings of one program digest equal regardless of padded-tail garbage.
Constant values hash by their exact storage bits (bf16/f16 widen to f32 —
exact — f64 contributes both words), so trees differing only in constants
get distinct keys: constant mutation/optimization *naturally* invalidates
memo entries by changing the key.

Collision note: the two lanes give a 64-bit digest. The intra-batch dedup
uses it only as a sort key (segments come from exact content comparison),
so collisions there are harmless. The memo tier matches on the full 64
bits — a false hit needs a 2^-64 pair collision between live keys, the
standard memoization trade documented in docs/memo_bank.md.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.trees import CONST, UNA, VAR, TreeBatch

Array = jax.Array

# lane 1: classic FNV-1a basis/prime; lane 2: independent odd constants
_BASIS1, _PRIME1 = 0x811C9DC5, 0x01000193
_BASIS2, _PRIME2 = 0x9E3779B9, 0x85EBCA6B
_MASK32 = 0xFFFFFFFF


def canonical_fields_device(trees: TreeBatch):
    """(kind, op, feat, const-words, length) with dead fields and the
    padded tail zeroed — the exact byte content of the program. Returns
    uint32 arrays: kind/op/feat (..., L), cwords (..., L, W), length (...,)
    with W = 2 for float64 constants, 1 otherwise. Jittable; also the
    equality domain for dedup's exact segment comparison."""
    kind = trees.kind
    L = kind.shape[-1]
    live = jnp.arange(L, dtype=jnp.int32) < trees.length[..., None]
    kindm = jnp.where(live, kind, 0)
    opm = jnp.where(live & (kind >= UNA), trees.op, 0)
    featm = jnp.where(live & (kind == VAR), trees.feat, 0)
    cval = jnp.where(live & (kind == CONST), trees.cval,
                     jnp.zeros((), trees.cval.dtype))
    if cval.dtype == jnp.float64:
        cwords = jax.lax.bitcast_convert_type(cval, jnp.uint32)  # (..., L, 2)
    else:
        if cval.dtype != jnp.float32:
            cval = cval.astype(jnp.float32)  # bf16/f16 -> f32 is exact
        cwords = jax.lax.bitcast_convert_type(cval, jnp.uint32)[..., None]
    return (
        kindm.astype(jnp.uint32),
        opm.astype(jnp.uint32),
        featm.astype(jnp.uint32),
        cwords,
        trees.length.astype(jnp.uint32),
    )


def tree_hash_device(trees: TreeBatch) -> Tuple[Array, Array]:
    """Two-lane 32-bit content hash, shape = batch shape. Jittable.

    The fold is unrolled over the (static, small) slot axis: ~4L wrapping
    uint32 mul/xor ops on batch-shaped arrays — noise next to one tree
    evaluation."""
    kindm, opm, featm, cwords, length = canonical_fields_device(trees)
    L = kindm.shape[-1]
    W = cwords.shape[-1]
    p1 = jnp.uint32(_PRIME1)
    p2 = jnp.uint32(_PRIME2)
    h1 = jnp.full(length.shape, _BASIS1, jnp.uint32)
    h2 = jnp.full(length.shape, _BASIS2, jnp.uint32)

    def fold(h1, h2, v):
        return (h1 ^ v) * p1, (h2 ^ v) * p2

    h1, h2 = fold(h1, h2, length)
    for i in range(L):
        h1, h2 = fold(h1, h2, kindm[..., i])
        h1, h2 = fold(h1, h2, opm[..., i])
        h1, h2 = fold(h1, h2, featm[..., i])
        for w in range(W):
            h1, h2 = fold(h1, h2, cwords[..., i, w])
    return h1, h2


def _canonical_fields_host(trees: TreeBatch):
    """numpy twin of canonical_fields_device (same shapes/dtypes)."""
    kind = np.asarray(trees.kind, np.int32)
    op = np.asarray(trees.op, np.int32)
    feat = np.asarray(trees.feat, np.int32)
    cval = np.asarray(trees.cval)
    length = np.asarray(trees.length, np.int32)
    L = kind.shape[-1]
    live = np.arange(L) < length[..., None]
    kindm = np.where(live, kind, 0)
    opm = np.where(live & (kind >= UNA), op, 0)
    featm = np.where(live & (kind == VAR), feat, 0)
    cval = np.where(live & (kind == CONST), cval, cval.dtype.type(0))
    if cval.dtype == np.float64:
        cwords = cval.view(np.uint32).reshape(cval.shape + (2,))
        if np.little_endian is False:  # pragma: no cover
            cwords = cwords[..., ::-1]
    else:
        if cval.dtype != np.float32:
            cval = cval.astype(np.float32)
        cwords = cval.view(np.uint32)[..., None]
    return (
        kindm.astype(np.uint64),
        opm.astype(np.uint64),
        featm.astype(np.uint64),
        cwords.astype(np.uint64),
        length.astype(np.uint64),
    )


def tree_hash_host(trees: TreeBatch) -> np.ndarray:
    """Combined 64-bit key (lane1 << 32 | lane2) as uint64, shape = batch
    shape — bit-identical lanes to tree_hash_device (unit-tested). This is
    the key form the FitnessMemoBank stores."""
    kindm, opm, featm, cwords, length = _canonical_fields_host(trees)
    L = kindm.shape[-1]
    W = cwords.shape[-1]
    h1 = np.full(length.shape, _BASIS1, np.uint64)
    h2 = np.full(length.shape, _BASIS2, np.uint64)
    m = np.uint64(_MASK32)
    p1 = np.uint64(_PRIME1)
    p2 = np.uint64(_PRIME2)

    def fold(h1, h2, v):
        return ((h1 ^ v) * p1) & m, ((h2 ^ v) * p2) & m

    h1, h2 = fold(h1, h2, length)
    for i in range(L):
        h1, h2 = fold(h1, h2, kindm[..., i])
        h1, h2 = fold(h1, h2, opm[..., i])
        h1, h2 = fold(h1, h2, featm[..., i])
        for w in range(W):
            h1, h2 = fold(h1, h2, cwords[..., i, w])
    return (h1 << np.uint64(32)) | h2


def split_key(key) -> Tuple[np.ndarray, np.ndarray]:
    """uint64 combined key(s) -> (lane1, lane2) uint32 — the device-table
    layout (TPU jit default has no uint64; the device memo stores lanes)."""
    key = np.asarray(key, np.uint64)
    return (
        (key >> np.uint64(32)).astype(np.uint32),
        (key & np.uint64(_MASK32)).astype(np.uint32),
    )
