"""Evaluation memo bank — fitness caching for the batched GP engine.

Two tiers (ISSUE 1; the caching answer to the reference engine tolerating
structural duplicates because Julia-side evals are cheap per tree —
src/SingleIteration.jl rescoring passim — where on TPU every redundant
tree burns a slot in the batched eval launch):

* **Intra-batch dedup** (`dedup.py`): inside the jitted cycle, content-hash
  the flat eval batch, sort-and-segment to find unique programs, evaluate
  only the unique representatives through the interpreter/Pallas path, and
  scatter each representative's loss back to all duplicates. Segment
  boundaries come from EXACT content comparison (the hash is only the sort
  key), so hash collisions can never merge distinct trees.

* **Cross-iteration memo bank** (`memo.py`): a host-side fixed-capacity
  LRU keyed by (64-bit content hash, dataset fingerprint, loss config).
  A device-resident snapshot of the most-recent entries pre-fills known
  full-data fitnesses before dispatch; the host loop absorbs each
  iteration's rescored populations afterwards. Keys include constant
  values, so constant mutation/optimization invalidates naturally (the
  re-optimized tree is a new key); explicit `invalidate()` exists for
  callers that rewrite constants in place.

Both tiers preserve bit-identical search trajectories versus the uncached
path: a memo/dedup hit substitutes a value that the deterministic
evaluator would have produced for the identical program on the identical
rows. Telemetry (scored / unique / memo-hit counters) rides in
`IslandState.cache_counts` and surfaces through progress + recorder.
"""

from .dedup import DedupStats, DeviceMemo, dedup_eval_losses, empty_device_memo
from .hashing import canonical_fields_device, tree_hash_device, tree_hash_host
from .memo import (
    FitnessMemoBank,
    clear_memo_banks,
    dataset_fingerprint,
    get_memo_bank,
)

__all__ = [
    "DedupStats",
    "DeviceMemo",
    "FitnessMemoBank",
    "canonical_fields_device",
    "clear_memo_banks",
    "dataset_fingerprint",
    "dedup_eval_losses",
    "empty_device_memo",
    "get_memo_bank",
    "tree_hash_device",
    "tree_hash_host",
]
